#!/bin/sh
# Run the FULL test suite (including the soak tier) on the virtual 8-device
# CPU platform. The bare `python -m pytest tests/` default excludes soaks
# (pytest.ini addopts) for a fast inner loop; this script clears the marker
# filter so everything runs.
#
# PYTHONPATH is stripped because the environment's axon sitecustomize dials the
# TPU relay at interpreter start; tests must not depend on (or block on) the
# tunnel. conftest.py additionally pins JAX_PLATFORMS=cpu and 8 host devices.
cd "$(dirname "$0")"

# Build the native components (parser/decoder/percentile/rebuild/ring/tail)
# up front so the suite exercises the C++ fast paths; soft-skip with a
# visible warning when no toolchain — every native consumer degrades to its
# Python fallback (the differential suite covers both).
if make -C native >/dev/null 2>&1; then
    :
else
    echo "WARNING: native build failed or no C++ toolchain;" \
         "parser/decoder fast paths unavailable — Python fallbacks in use" >&2
fi

# --lint: the static-correctness gate, ALL hard requirements (the PR-2
# pyflakes soft-skip is gone): byte-compile everything, run the in-repo
# analyzer (JAX hot-path, lock discipline, config keys, metric catalogue,
# transport headers, durability discipline, pyflakes-lite — see DESIGN.md
# §9) INCLUDING the protocol model checker at its small scopes (the
# delivery/delta-chain/sharded-epoch models verified exhaustively in
# well under 10 s, DESIGN.md §9.4 — a violated invariant prints its
# counterexample schedule and fails the gate), and run real pyflakes when
# the environment ships it (its undefined-name pass goes beyond
# pyflakes-lite; when absent, the in-repo analyzer IS the hard lint
# floor). Consumed standalone (CI lint stage) or before the suite:
# ./run_tests.sh --lint [pytest args...].
if [ "$1" = "--lint" ]; then
    shift
    echo "lint: python -m compileall apmbackend_tpu benchmarks tests"
    python -m compileall -q apmbackend_tpu benchmarks tests || exit 1
    echo "lint: python -m apmbackend_tpu.analysis (rules + small-scope protocol models)"
    env -u PYTHONPATH python -m apmbackend_tpu.analysis || exit 1
    if python -c "import pyflakes" 2>/dev/null; then
        echo "lint: python -m pyflakes apmbackend_tpu"
        python -m pyflakes apmbackend_tpu || exit 1
    fi
    # --lint alone: stop after linting; with more args fall through to pytest
    [ $# -eq 0 ] && exit 0
fi

# --model: the deep protocol-verification tier — the model checker at its
# deep scopes (larger message counts and fault budgets; minutes, not
# seconds), the full mutation catalogue (every seeded protocol bug must
# yield a counterexample), and the protocol test suite including the
# slow trace-conformance scenarios (kill−9 chaos runs replayed as model
# paths). Run before touching worker.py's epoch cycle, deltachain.py's
# recovery, or any transport's ack semantics:
# ./run_tests.sh --model [pytest args...].
if [ "$1" = "--model" ]; then
    shift
    echo "model: python -m apmbackend_tpu.analysis --models deep (deep scopes + mutants)"
    env -u PYTHONPATH python -m apmbackend_tpu.analysis -q --models deep || exit 1
    exec env -u PYTHONPATH JAX_PLATFORMS=cpu \
        XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -m pytest tests/test_protocol_models.py \
        tests/test_protocol_conformance.py \
        -m "slow or not slow" "$@"
fi

# --sanitize: rebuild every native component with ASan+UBSan (make
# sanitize -> build-sanitize/) and drive the differential fuzz suite and
# the native unit tier against the instrumented parser/percentile/rebuild/
# ring/decoder/tailer — plus the frame-spine suite, so the native APF1
# emitter (apmfrm_pack) packs every codec corpus under instrumentation. libasan/libubsan are LD_PRELOADed so the
# instrumented .so files resolve their runtime inside the stock Python;
# leak detection stays off (CPython+jax hold arenas for the process
# lifetime — interceptor noise, not parser bugs), everything else aborts
# hard so a report can never hide behind a green exit.
if [ "$1" = "--sanitize" ]; then
    shift
    echo "sanitize: make -C native sanitize"
    make -C native sanitize || exit 1
    LIBASAN=$(${CXX:-g++} -print-file-name=libasan.so)
    LIBUBSAN=$(${CXX:-g++} -print-file-name=libubsan.so)
    [ -f "$LIBASAN" ] || { echo "sanitize: libasan.so not found"; exit 1; }
    exec env -u PYTHONPATH JAX_PLATFORMS=cpu \
        XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        APM_NATIVE_SANITIZE=1 \
        LD_PRELOAD="$LIBASAN $LIBUBSAN" \
        ASAN_OPTIONS=detect_leaks=0:abort_on_error=1:handle_segv=1 \
        UBSAN_OPTIONS=print_stacktrace=1:halt_on_error=1 \
        python -m pytest tests/test_parser_native_diff.py tests/test_native.py \
        tests/test_frames.py \
        -q -m "not slow" "$@"
fi

# --fleet: the pod-scale sharded-spine tier — the slow multi-process
# scenarios (N real worker shards over a durable spool: kill −9 one shard
# mid-stream with bit-identical recovery, live-traffic quiesced rebalance
# with fleet trace conformance, and the ISSUE 18 self-managing drills:
# watermark-controller convergence on a skewed load then quiet, kill −9
# of the releasing shard mid-move, manager death mid-decision with
# recover(), and the ISSUE 20 query-plane drill: kill −9 one shard under
# live dashboard query load and require partial/stale-marked answers
# from the recorder store with zero 5xx) plus every fast in-process
# fleet test and the query-plane merge/routing suite. Tier-1 keeps only
# the in-process fast paths; run this before touching parallel/fleet.py,
# parallel/rebalancer.py, obs/queryplane.py, the worker's partition
# handoff, or shardmodel.py: ./run_tests.sh --fleet [pytest args...].
if [ "$1" = "--fleet" ]; then
    shift
    exec env -u PYTHONPATH JAX_PLATFORMS=cpu \
        XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -m pytest tests/test_fleet.py tests/test_fleet_chaos.py \
        tests/test_queryplane.py \
        tests/test_protocol_models.py \
        -m "slow or not slow" "$@"
fi

# --chaos: the crash-consistency tier explicitly — the kill−9/restart
# subprocess scenarios (marked `slow`, now also asserting crash flight
# bundles are produced and parseable after SIGKILL), the hostile-storage
# matrix (delta-chain torn tails, crash-during-compaction, ENOSPC
# degradation, stale duplicate tails), the spool durability audit, plus
# every fast chaos/at-least-once test and the trace-plane suite (trace
# headers must survive redelivery). Tier-1 runs the fast subset; this
# runs everything.
if [ "$1" = "--chaos" ]; then
    shift
    exec env -u PYTHONPATH JAX_PLATFORMS=cpu \
        XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -m pytest tests/test_chaos.py tests/test_chaos_harness.py \
        tests/test_chaos_storage.py tests/test_delta_chain.py \
        tests/test_spool_durability.py \
        tests/test_at_least_once.py tests/test_trace_plane.py \
        tests/test_protocol_conformance.py \
        -m "slow or not slow" "$@"
fi

# --broker: the broker-outage tier — kill/restart the broker mid-stream
# under at-least-once delivery (fake-redis process death, AMQP
# connection-generation churn, durable spool as the no-broker control)
# and prove bit-identical recovery vs a crash-free golden plus bounded
# producer memory throughout, plus the full redis transport suite (the
# real-server tests auto-skip when nothing answers APM_TEST_REDIS_URL)
# and the flow-control spine. Run before touching transport/ send/ack
# paths, the producer pause buffer, or the reconnect/redeliver cycle:
# ./run_tests.sh --broker [pytest args...].
if [ "$1" = "--broker" ]; then
    shift
    exec env -u PYTHONPATH JAX_PLATFORMS=cpu \
        XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -m pytest tests/test_broker_outage.py \
        tests/test_redis_transport.py tests/test_flow_control.py \
        tests/test_transport.py tests/test_amqp.py \
        -m "slow or not slow" "$@"
fi

exec env -u PYTHONPATH JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python -m pytest tests/ -m "soak or not soak" "$@"
