#!/bin/sh
# Run the test suite on the virtual 8-device CPU platform.
#
# PYTHONPATH is stripped because the environment's axon sitecustomize dials the
# TPU relay at interpreter start; tests must not depend on (or block on) the
# tunnel. conftest.py additionally pins JAX_PLATFORMS=cpu and 8 host devices.
cd "$(dirname "$0")"
exec env -u PYTHONPATH JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python -m pytest tests/ "$@"
