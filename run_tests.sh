#!/bin/sh
# Run the FULL test suite (including the soak tier) on the virtual 8-device
# CPU platform. The bare `python -m pytest tests/` default excludes soaks
# (pytest.ini addopts) for a fast inner loop; this script clears the marker
# filter so everything runs.
#
# PYTHONPATH is stripped because the environment's axon sitecustomize dials the
# TPU relay at interpreter start; tests must not depend on (or block on) the
# tunnel. conftest.py additionally pins JAX_PLATFORMS=cpu and 8 host devices.
cd "$(dirname "$0")"
exec env -u PYTHONPATH JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python -m pytest tests/ -m "soak or not soak" "$@"
