#!/bin/sh
# Run the FULL test suite (including the soak tier) on the virtual 8-device
# CPU platform. The bare `python -m pytest tests/` default excludes soaks
# (pytest.ini addopts) for a fast inner loop; this script clears the marker
# filter so everything runs.
#
# PYTHONPATH is stripped because the environment's axon sitecustomize dials the
# TPU relay at interpreter start; tests must not depend on (or block on) the
# tunnel. conftest.py additionally pins JAX_PLATFORMS=cpu and 8 host devices.
cd "$(dirname "$0")"

# Build the native components (parser/decoder/percentile/rebuild/ring/tail)
# up front so the suite exercises the C++ fast paths; soft-skip with a
# visible warning when no toolchain — every native consumer degrades to its
# Python fallback (the differential suite covers both).
if make -C native >/dev/null 2>&1; then
    :
else
    echo "WARNING: native build failed or no C++ toolchain;" \
         "parser/decoder fast paths unavailable — Python fallbacks in use" >&2
fi

# --lint: byte-compile the whole package (hard fail on any syntax error)
# and run pyflakes when the environment has it (soft-skip otherwise — the
# container image does not bake it in). Consumed standalone (CI lint stage)
# or before the suite: ./run_tests.sh --lint [pytest args...].
if [ "$1" = "--lint" ]; then
    shift
    echo "lint: python -m compileall apmbackend_tpu benchmarks tests"
    python -m compileall -q apmbackend_tpu benchmarks tests || exit 1
    if python -c "import pyflakes" 2>/dev/null; then
        echo "lint: python -m pyflakes apmbackend_tpu"
        python -m pyflakes apmbackend_tpu || exit 1
    else
        echo "lint: pyflakes unavailable, skipping (soft)"
    fi
    # --lint alone: stop after linting; with more args fall through to pytest
    [ $# -eq 0 ] && exit 0
fi

# --chaos: the crash-consistency tier explicitly — the kill−9/restart
# subprocess scenarios (marked `slow`, now also asserting crash flight
# bundles are produced and parseable after SIGKILL) plus every fast
# chaos/at-least-once test and the trace-plane suite (trace headers must
# survive redelivery). Tier-1 runs the fast subset; this runs everything.
if [ "$1" = "--chaos" ]; then
    shift
    exec env -u PYTHONPATH JAX_PLATFORMS=cpu \
        XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -m pytest tests/test_chaos.py tests/test_chaos_harness.py \
        tests/test_at_least_once.py tests/test_trace_plane.py \
        -m "slow or not slow" "$@"
fi

exec env -u PYTHONPATH JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python -m pytest tests/ -m "soak or not soak" "$@"
