"""Grafana URL/render/annotation + email MIME (stream_process_alerts.js:59-206,
apm_manager.js:224-244, util_methods.js:359-396 roles)."""

import email
import math

from apmbackend_tpu.entries import FullStatEntry
from apmbackend_tpu.integrations import EmailSender, GrafanaClient, build_mime
from apmbackend_tpu.ops.alerts import AlertsManager

GRAFANA_CFG = {
    "grafanaURL": "http://grafana.example:3000",
    "alertInspectorRelativeURL": "/d/alert-inspector",
    "grafanaNowDelayIntervalMs": 90000,
    "bearerToken": "Bearer tok",
    "renderDir": "renders",
    "renderWidth": 1800,
    "renderHeightMultiple": 750,
    "renderExtraParams": "&autofitpanels",
    "renderTimeout": 90000,
}


def fs_entry(ts=1700000000000, server="srv1", service="svc", lag=360):
    return FullStatEntry(
        ts, server, service, 2.5, lag,
        100.0, 90.0, 80.0, 110.0, 0,
        120.0, 100.0, 90.0, 130.0, 1,
        200.0, 150.0, 100.0, 220.0, 1,
    )


def buffered(entry, cause="average and per75 UB exceeded"):
    return {
        "alertTimestamp": entry.timestamp + 1000,
        "entryTimestamp": entry.timestamp,
        "server": entry.server,
        "service": entry.service,
        "cause": cause,
        "entry": entry.to_csv().replace("|", "&"),
    }


def test_alert_urls_window_and_vars():
    # now far in the future => no delay clamping
    clock = lambda: (1700000000000 + 10**9) / 1000.0
    g = GrafanaClient(GRAFANA_CFG, clock=clock)
    buf = [
        buffered(fs_entry(ts=1700000000000, server="a", service="s1", lag=360)),
        buffered(fs_entry(ts=1700000600000, server="b", service="s2", lag=8640)),
    ]
    url, render_url = g.alert_urls(buf)
    assert url.startswith("http://grafana.example:3000/d/alert-inspector?")
    assert "from=1699999700000" in url  # first - 5 min
    assert "to=1700000900000" in url  # last + 5 min
    assert "&var-server=a&var-server=b" in url
    assert "&var-service=s1&var-service=s2" in url
    assert "&var-lag=360&var-lag=8640" in url
    # height factor: 2*2*2 + 2 services = 10 -> 100 + 750*10 = 7600
    assert "&width=1800&height=7600&autofitpanels" in render_url
    assert render_url.startswith("http://grafana.example:3000/render/d/alert-inspector?")


def test_alert_urls_now_delay_clamp():
    ts = 1700000000000
    clock = lambda: (ts + 301000) / 1000.0  # "to" would be within the delay window
    g = GrafanaClient(GRAFANA_CFG, clock=clock)
    url, _ = g.alert_urls([buffered(fs_entry(ts=ts))])
    assert f"to={ts + 301000 - 90000}" in url


def test_render_writes_png(tmp_path):
    cfg = dict(GRAFANA_CFG, renderDir=str(tmp_path / "renders"))
    calls = []

    def fake_get(url, headers, timeout_s):
        calls.append((url, headers, timeout_s))
        return b"\x89PNG fake"

    g = GrafanaClient(cfg, http_get=fake_get, clock=lambda: 1700000000.0)
    path = g.render("http://grafana.example:3000/render/d/x?a=1")
    assert path and path.endswith(".png")
    assert open(path, "rb").read() == b"\x89PNG fake"
    assert calls[0][1] == {"Authorization": "Bearer tok"}
    assert calls[0][2] == 90.0


def test_render_failure_returns_none(tmp_path):
    cfg = dict(GRAFANA_CFG, renderDir=str(tmp_path))

    def boom(url, headers, timeout_s):
        raise OSError("no route")

    g = GrafanaClient(cfg, http_get=boom)
    assert g.render("http://x/render") is None


def test_post_annotation():
    posts = []

    def fake_post(url, body, headers, timeout_s):
        posts.append((url, body, headers))
        return b"{}"

    g = GrafanaClient(GRAFANA_CFG, http_post=fake_post, clock=lambda: 1700.0)
    assert g.post_annotation("restarting module", ["maintenance"])
    url, body, headers = posts[0]
    assert url == "http://grafana.example:3000/api/annotations"
    assert body == {"time": 1700000, "timeEnd": 1700000, "text": "restarting module", "tags": ["maintenance"]}


def test_build_mime_inline_image(tmp_path):
    img = tmp_path / "g.png"
    img.write_bytes(b"\x89PNG data")
    msg = build_mime("apm@x.com", "oncall@x.com", "APM Alerts Triggered!", "<p>hi</p>", str(img))
    raw = msg.as_bytes()
    parsed = email.message_from_bytes(raw)
    assert parsed["Subject"] == "APM Alerts Triggered!"
    parts = list(parsed.walk())
    types = [p.get_content_type() for p in parts]
    assert "text/html" in types and "image/png" in types
    html_part = next(p for p in parts if p.get_content_type() == "text/html")
    html = html_part.get_payload(decode=True).decode()
    img_part = next(p for p in parts if p.get_content_type() == "image/png")
    cid = img_part["Content-ID"].strip("<>")
    assert f'<img src="cid:{cid}"/>' in html


def test_build_mime_without_image():
    msg = build_mime("a@x", "b@x", "s", "<p>text</p>")
    assert "img src" not in msg.as_string()


def test_email_sender_transport_seam():
    sent = []
    sender = EmailSender("a@x", "b@x", transport=sent.append)
    assert sender.available()
    assert sender("subj", "<p>x</p>") is True
    assert sent[0]["To"] == "b@x"


def test_email_sender_missing_binary():
    sender = EmailSender("a@x", "b@x", sendmail_path="/nonexistent/sendmail")
    assert not sender.available()
    assert sender("subj", "<p>x</p>") is False


def test_alerts_manager_full_dispatch_with_grafana(tmp_path):
    """AlertsManager.flush wired to the real GrafanaClient + EmailSender seams."""
    sent_msgs = []
    cfg = {
        "emailsEnabled": True,
        "alertCollectionIntervalInSeconds": 60,
        "increaseCollectionIntervalAfterAlert": True,
        "maxCollectionIntervalInSeconds": 960,
        "perServiceAlertCooldownInMinutes": 15,
    }
    g = GrafanaClient(
        dict(GRAFANA_CFG, renderDir=str(tmp_path)),
        http_get=lambda u, h, t: b"\x89PNG!",
        clock=lambda: 1700001000.0,
    )
    sender = EmailSender("apm@x.com", "oncall@x.com", transport=sent_msgs.append)
    mgr = AlertsManager(cfg, email_sender=sender, grafana=g, clock=lambda: 1700000500.0)
    alert = mgr.process_trigger(fs_entry(), 1 << 4)
    assert alert is not None
    mgr.add_to_buffer(alert)
    count, next_interval = mgr.flush()
    assert count == 1 and next_interval == 120
    parsed = email.message_from_bytes(sent_msgs[0].as_bytes())
    assert any(p.get_content_type() == "image/png" for p in parsed.walk())
