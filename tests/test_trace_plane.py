"""Distributed trace plane (ISSUE 5): sampled per-transaction trace
propagation across the transport fabric, OpenMetrics exemplars, alert
decision provenance, crash flight-recorder bundles, and the e2e acceptance
scenario — one sampled transaction driven from a replayed log line to an
alert and recovered as a single stitched trace via ``/trace`` with its
decision record resolvable by the same trace_id."""

import json
import math
import threading
import time
import urllib.error
import urllib.request

import pytest

from apmbackend_tpu.config import default_config
from apmbackend_tpu.obs import (
    MetricsRegistry,
    TelemetryServer,
    histogram_quantile,
    parse_prom_text,
    set_registry,
)
from apmbackend_tpu.obs.decisions import DecisionRing, get_decisions, set_decisions
from apmbackend_tpu.obs.flight import FlightRecorder, list_bundles, read_bundle
from apmbackend_tpu.obs.trace import Tracer, get_tracer, set_tracer
from apmbackend_tpu.transport.base import QueueManager
from apmbackend_tpu.transport.memory import MemoryBroker, MemoryChannel

from fake_pika import FakeBroker, make_fake_pika


@pytest.fixture(autouse=True)
def fresh_obs_plane():
    """Isolate the process-global tracer/registry/decision ring per test:
    spans recorded by pipelines in OTHER tests must not leak into ours."""
    old_tr = set_tracer(Tracer())
    old_reg = set_registry(MetricsRegistry())
    old_dec = set_decisions(DecisionRing())
    yield
    set_tracer(old_tr)
    set_registry(old_reg)
    set_decisions(old_dec)


def fetch(url, timeout=10):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, resp.read().decode("utf-8"), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode("utf-8"), dict(e.headers)


def mem_qm(broker):
    return QueueManager(lambda d: MemoryChannel(broker), stat_log_interval_s=3600)


def wait_for(predicate, timeout=10.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


# -- sampling ------------------------------------------------------------------


def test_head_sampling_is_deterministic_and_off_by_rate_zero():
    t = Tracer(sample_rate=0)
    assert not any(t.should_sample(i) for i in range(1, 200))
    t4 = Tracer(sample_rate=4)
    picks = [i for i in range(1, 17) if t4.should_sample(i)]
    assert picks == [4, 8, 12, 16]
    # deterministic in the sequence: a second tracer (a replayed run)
    # samples the identical positions
    t4b = Tracer(sample_rate=4)
    assert [t4b.should_sample(i) for i in range(1, 200)] == [
        t4.should_sample(i) for i in range(1, 200)
    ]


def test_tracing_off_is_bit_identical_wire():
    """rate 0: the producer stamps exactly the pre-trace headers (ingest_ts +
    msg_id, nothing else) and records no span — OFF must be indistinguishable
    from the pre-trace backend."""
    get_tracer().configure(sample_rate=0)
    broker = MemoryBroker()
    prod = mem_qm(broker).get_queue("q", "p")
    got = []
    mem_qm(broker).get_queue("q", "c", lambda l, h: got.append((l, h))).start_consume()
    for i in range(8):
        prod.write_line(f"m{i}")
    broker.pump()
    assert len(got) == 8
    for _l, h in got:
        assert set(h) == {"ingest_ts", "msg_id"}
    assert len(get_tracer().ring) == 0


def test_memory_broker_trace_propagation_and_spans():
    get_tracer().configure(sample_rate=2, module="prodmod")
    broker = MemoryBroker()
    prod = mem_qm(broker).get_queue("transactions", "p")
    got = []
    mem_qm(broker).get_queue(
        "transactions", "c", lambda l, h: got.append((l, h))
    ).start_consume()
    for i in range(6):
        prod.write_line(f"m{i}")
    broker.pump()
    # every 2nd message carries the context; ids are distinct and tied to msg_id
    sampled = [(l, h) for l, h in got if h.get("trace_id")]
    assert [l for l, _h in sampled] == ["m1", "m3", "m5"]
    assert all(h["trace_id"] == "t-" + h["msg_id"] for _l, h in sampled)
    # ingest span at transport entry + queue span at delivery, same trace_id
    for _l, h in sampled:
        spans = get_tracer().ring.spans(trace_id=h["trace_id"])
        names = [s["name"] for s in spans]
        assert names == ["ingest", "queue"]
        assert spans[0]["attrs"]["queue"] == "transactions"
        assert spans[1]["attrs"]["redelivered"] is False
        assert spans[0]["end"] <= spans[1]["end"]
    # unsampled messages contributed nothing
    assert len(get_tracer().ring) == 2 * len(sampled)


def test_memory_redelivery_keeps_original_trace_id():
    get_tracer().configure(sample_rate=1)
    broker = MemoryBroker()
    prod = mem_qm(broker).get_queue("q", "p")
    got = []
    cons = mem_qm(broker).get_queue(
        "q", "c", lambda l, h, tok: got.append((l, h, tok)), manual_ack=True
    )
    cons.start_consume()
    for i in range(3):
        prod.write_line(f"m{i}")
    broker.pump()
    first = {l: h["trace_id"] for l, h, _t in got}
    cons.ack([got[0][2]])
    assert broker.bounce() == 2  # m1, m2 redelivered
    broker.pump()
    redelivered = got[3:]
    assert [l for l, _h, _t in redelivered] == ["m1", "m2"]
    for l, h, _t in redelivered:
        assert h["redelivered"] is True
        assert h["trace_id"] == first[l]  # ORIGINAL id: the trace extends
    # the queue span of the redelivery is marked, under the original id
    spans = get_tracer().ring.spans(trace_id=first["m1"])
    qspans = [s for s in spans if s["name"] == "queue"]
    assert [s["attrs"]["redelivered"] for s in qspans] == [False, True]


def test_amqp_fake_pika_trace_header_survives_prefetch_and_redelivery():
    from apmbackend_tpu.transport.amqp import AmqpChannel

    get_tracer().configure(sample_rate=1)
    broker = FakeBroker(block_at=1000, unblock_at=10)
    mod = make_fake_pika(broker)

    def factory(kind):
        return AmqpChannel(
            "amqp://fake", direction=kind, pika_module=mod, poll_interval_s=0.005,
            prefetch_count=100,
        )

    qm_p = QueueManager(factory, stat_log_interval_s=3600)
    qm_c = QueueManager(factory, stat_log_interval_s=3600)
    got = []
    prod = qm_p.get_queue("tx", "p")
    cons = qm_c.get_queue(
        "tx", "c", lambda l, h, tok: got.append((l, h, tok)), manual_ack=True
    )
    cons.start_consume()
    try:
        for i in range(4):
            prod.write_line(f"m{i}")
        assert wait_for(lambda: len(got) == 4), len(got)
        first_ids = [h["trace_id"] for _l, h, _t in got]
        assert all(first_ids)
        broker.kill_connections()  # unacked requeued + connections die
        assert wait_for(lambda: len(got) >= 8, timeout=20), len(got)
        redelivered = got[4:8]
        # headers rode BasicProperties through prefetch + redelivery: the
        # redelivered message keeps its ORIGINAL trace_id and gains the flag
        assert [h["trace_id"] for _l, h, _t in redelivered] == first_ids
        assert all(h["redelivered"] for _l, h, _t in redelivered)
    finally:
        qm_p.shutdown()
        qm_c.shutdown()


# -- exemplars -----------------------------------------------------------------


def test_histogram_exemplar_rendering():
    reg = MetricsRegistry()
    h = reg.histogram("apm_lat_seconds", "help", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe_exemplar(0.5, "t-abc")
    h.observe_exemplar(50.0, "t-inf")  # lands in +Inf
    plain = reg.render()
    assert "t-abc" not in plain  # prometheus 0.0.4 exposition is unchanged
    assert parse_prom_text(plain)  # and still parses
    om = reg.render(exemplars=True)
    lines = [l for l in om.splitlines() if l.startswith("apm_lat_seconds_bucket")]
    by_le = {l.split('le="')[1].split('"')[0]: l for l in lines}
    assert "# {" not in by_le["0.1"]  # no exemplar recorded for this bucket
    assert '# {trace_id="t-abc"} 0.5' in by_le["1"]
    assert 'trace_id="t-inf"' in by_le["+Inf"]
    # exemplar-carrying exposition parses if the suffix is stripped (the
    # scrape-side contract qstat/fleet rely on is the plain render)
    assert parse_prom_text(plain) == parse_prom_text(
        "\n".join(l.split(" # {")[0] for l in om.splitlines() if l != "# EOF") + "\n"
    )


def test_metrics_exemplars_query_serves_openmetrics():
    reg = MetricsRegistry()
    reg.histogram("apm_x_seconds", buckets=(1.0,)).observe_exemplar(0.5, "t-1")
    server = TelemetryServer(reg, port=0, module="m")
    server.start()
    try:
        status, text, headers = fetch(f"{server.url}/metrics?exemplars=1")
        assert status == 200
        assert "openmetrics-text" in headers["Content-Type"]
        assert text.rstrip().endswith("# EOF")
        assert 'trace_id="t-1"' in text
        status, text, headers = fetch(f"{server.url}/metrics")
        assert "openmetrics-text" not in headers["Content-Type"]
        assert "t-1" not in text
    finally:
        server.stop()


# -- /trace and /decisions endpoints -------------------------------------------


def test_trace_endpoint_filters_and_validates():
    tr = get_tracer().configure(sample_rate=1, module="worker")
    tr.span("t-1", "ingest", 1.0, 2.0, queue="tx")
    tr.span("t-1", "queue", 2.0, 3.0, queue="tx")
    tr.span("t-2", "ingest", 4.0, 5.0, queue="tx")
    server = TelemetryServer(port=0, module="worker")
    server.start()
    try:
        status, body, _ = fetch(f"{server.url}/trace")
        assert status == 200
        out = json.loads(body)
        assert out["module"] == "worker" and out["sample_rate"] == 1
        assert out["count"] == 3
        status, body, _ = fetch(f"{server.url}/trace?trace_id=t-1")
        out = json.loads(body)
        assert out["count"] == 2
        assert {s["trace_id"] for s in out["spans"]} == {"t-1"}
        assert [s["name"] for s in out["spans"]] == ["ingest", "queue"]
        assert out["spans"][0]["duration_ms"] == 1000.0
        status, body, _ = fetch(f"{server.url}/trace?n=junk")
        assert status == 400
    finally:
        server.stop()


def test_decisions_endpoint_resolves_by_trace_id():
    ring = get_decisions()
    ring.record({"trace_id": "t-9", "service": "S:a", "cause": "UB"})
    ring.record({"trace_id": None, "service": "S:b", "cause": "hard"})
    server = TelemetryServer(port=0, module="worker")
    server.start()
    try:
        status, body, _ = fetch(f"{server.url}/decisions")
        out = json.loads(body)
        assert status == 200 and out["total"] == 2 and out["count"] == 2
        status, body, _ = fetch(f"{server.url}/decisions?trace_id=t-9")
        out = json.loads(body)
        assert out["count"] == 1
        assert out["decisions"][0]["service"] == "S:a"
        status, body, _ = fetch(f"{server.url}/decisions?n=-")
        assert status == 400
    finally:
        server.stop()


def test_decision_ring_is_bounded():
    ring = DecisionRing(maxlen=4)
    for i in range(10):
        ring.record({"i": i})
    assert ring.total == 10
    assert [d["i"] for d in ring.recent()] == [6, 7, 8, 9]


# -- histogram_quantile + qstat wait percentiles -------------------------------


def test_histogram_quantile_semantics():
    assert math.isnan(histogram_quantile([], 0.5))
    assert math.isnan(histogram_quantile([(0.1, 0.0), (float("inf"), 0.0)], 0.5))
    # 10 obs uniform in the (0, 0.1] bucket: p50 interpolates to the middle
    b = [(0.1, 10.0), (1.0, 10.0), (float("inf"), 10.0)]
    assert histogram_quantile(b, 0.5) == pytest.approx(0.05)
    # mass split across buckets: p95 lands inside the second
    b = [(0.1, 50.0), (1.0, 100.0), (float("inf"), 100.0)]
    q = histogram_quantile(b, 0.95)
    assert 0.1 < q < 1.0
    # the open-ended +Inf tail clamps to the highest finite bound
    b = [(0.1, 0.0), (1.0, 0.0), (float("inf"), 10.0)]
    assert histogram_quantile(b, 0.5) == 1.0


def test_qstat_metrics_url_prints_wait_percentiles(capsys):
    from apmbackend_tpu.tools import qstat

    reg = MetricsRegistry()
    h = reg.histogram(
        "apm_queue_wait_seconds", "wait", labels={"queue": "transactions"}
    )
    for _ in range(20):
        h.observe(0.004)
    h.observe(2.0)
    reg.gauge("apm_queue_depth", labels={"queue": "transactions"}).set(3)
    # a queue with depth but no wait series yet renders "-" not a crash
    reg.gauge("apm_queue_depth", labels={"queue": "db_insert"}).set(0)
    server = TelemetryServer(reg, port=0, module="m")
    server.start()
    try:
        rows = qstat.metrics_url_stats(server.url)
        by_q = {r[0]: r for r in rows}
        _q, depth, _mb, _i, _o, p50, p95 = by_q["transactions"]
        assert depth == 3
        assert 0.0 < p50 <= 0.005  # 20/21 obs in the 5 ms bucket
        assert p95 > p50
        assert math.isnan(by_q["db_insert"][5])
        assert qstat.main(["--metrics-url", server.url]) == 0
        out = capsys.readouterr().out
        assert "wait p50 ms" in out and "wait p95 ms" in out
        # the no-wait-series queue renders a dash
        db_row = next(l for l in out.splitlines() if l.startswith("db_insert"))
        assert " - " in db_row or db_row.rstrip().endswith("-")
    finally:
        server.stop()


# -- /profile concurrency (satellite fix) --------------------------------------


def test_profile_concurrent_request_rejected_409_process_wide():
    from apmbackend_tpu.obs import exporter as exporter_mod

    a = TelemetryServer(MetricsRegistry(), port=0, module="a")
    b = TelemetryServer(MetricsRegistry(), port=0, module="b")
    a.start()
    b.start()
    try:
        assert exporter_mod._profile_capture_lock.acquire(blocking=False)
        try:
            # BOTH exporters refuse while a capture runs anywhere in the
            # process — jax.profiler is a process-global singleton
            status, body, _ = fetch(f"{a.url}/profile?ms=10", timeout=30)
            assert status == 409
            assert "already running" in json.loads(body)["error"]
            status, _body, _ = fetch(f"{b.url}/profile?ms=10", timeout=30)
            assert status == 409
        finally:
            exporter_mod._profile_capture_lock.release()
        status, _body, _ = fetch(f"{a.url}/profile?ms=10", timeout=60)
        assert status in (200, 503)  # lock released: capture proceeds again
    finally:
        a.stop()
        b.stop()


# -- flight recorder -----------------------------------------------------------


def test_flight_dump_sources_rate_limit_and_prune(tmp_path):
    fr = FlightRecorder(
        str(tmp_path), "worker", max_bundles=3, min_interval_s=30.0
    )
    fr.add_source("ok", lambda: {"n": 7})
    fr.add_source("broken", lambda: (_ for _ in ()).throw(RuntimeError("boom")))
    fr.add_source("huge", lambda: "x" * 600_000)
    path = fr.dump("unit_test")
    assert path and path.endswith(".json")
    body = read_bundle(path)
    assert body["module"] == "worker" and body["reason"] == "unit_test"
    assert body["ok"] == {"n": 7}
    assert "source error" in body["broken"]  # degraded, not a failed dump
    assert body["huge"].endswith("...[truncated]")
    # rate limit: an immediate second (non-forced) dump is suppressed
    assert fr.dump("again") is None
    # force + prune: the directory never exceeds max_bundles
    for i in range(5):
        assert fr.dump(f"forced_{i}", force=True)
    assert len(fr.bundles()) == 3
    assert all(read_bundle(p) for p in fr.bundles())


def test_flight_crash_sentinel_promotes_journal_on_next_boot(tmp_path):
    fr = FlightRecorder(str(tmp_path), "worker", min_interval_s=0.0)
    fr.add_source("engine_health", lambda: {"ticks_total": 42})
    fr.mark_alive()  # boot: sentinel + initial journal on disk
    fr.journal()
    # ... SIGKILL: no clean exit ran. The NEXT boot finds the sentinel:
    fr2 = FlightRecorder(str(tmp_path), "worker", min_interval_s=0.0)
    crash = fr2.recover_crash()
    assert crash and crash.endswith("-crash.json")
    body = read_bundle(crash)
    assert body["recovered"] is True
    assert body["journal"]["module"] == "worker"
    assert body["journal"]["engine_health"] == {"ticks_total": 42}
    # one crash, one bundle: the sentinel was consumed
    assert fr2.recover_crash() is None
    # a CLEAN shutdown leaves nothing to promote
    fr3 = FlightRecorder(str(tmp_path), "clean", min_interval_s=0.0)
    fr3.mark_alive()
    fr3.mark_clean_exit()
    assert FlightRecorder(str(tmp_path), "clean").recover_crash() is None
    assert list_bundles(str(tmp_path), module="clean") == []


def test_flight_endpoint_and_degraded_healthz_dump(tmp_path):
    server = TelemetryServer(MetricsRegistry(), port=0, module="w")
    server.start()
    try:
        status, _body, _ = fetch(f"{server.url}/flight")
        assert status == 404  # no recorder configured
        fr = FlightRecorder(str(tmp_path), "w", min_interval_s=0.0)
        fr.add_source("note", lambda: "hello")
        server.flight = fr
        status, body, _ = fetch(f"{server.url}/flight?reason=manual_pull")
        assert status == 200
        bundle = json.loads(body)["bundle"]
        assert read_bundle(bundle)["reason"] == "manual_pull"
        # healthz degradation triggers an automatic dump
        server.add_health("engine", lambda: {"ok": False, "wedged": True})
        status, body, _ = fetch(f"{server.url}/healthz")
        assert status == 503
        health = json.loads(body)
        assert health["status"] == "degraded"
        assert read_bundle(health["flight_bundle"])["reason"] == "healthz_degraded"
    finally:
        server.stop()


def test_module_runtime_wires_flight_recorder(tmp_path):
    from apmbackend_tpu.runtime.module_base import ModuleRuntime

    cfg = default_config()
    cfg["logDir"] = None
    cfg["observability"]["flightDir"] = str(tmp_path / "flight")
    cfg["observability"]["flightJournalSeconds"] = 0.05
    cfg["tpuEngine"]["metricsPort"] = 0
    runtime = ModuleRuntime(
        "tpuEngine", config=cfg, broker=MemoryBroker(),
        install_signals=False, console_log=False,
    )
    try:
        fr = runtime.flight
        assert fr is not None and runtime.telemetry.flight is fr
        # boot marked the process alive (sentinel + initial journal)
        assert wait_for(lambda: read_bundle(fr.journal_path)["reason"] == "journal")
        snap = fr.snapshot("test")
        assert "config_hash" in snap and "metrics" in snap
        assert "traces" in snap and "decisions" in snap
        assert snap["process_health"]["ok"] is True
        # the process tracer was configured from observability config
        assert get_tracer().rate == cfg["observability"]["traceSampleRate"]
    finally:
        runtime.stop_timers()
    # orderly teardown consumed the sentinel: the next boot promotes nothing
    assert FlightRecorder(str(tmp_path / "flight"), "tpuEngine").recover_crash() is None


# -- manager stitching ---------------------------------------------------------


def test_manager_trace_route_stitches_across_children(tmp_path):
    from apmbackend_tpu.manager.manager import ManagerApp
    from apmbackend_tpu.runtime.module_base import ModuleRuntime

    tr = get_tracer().configure(sample_rate=1)
    tr.span("t-e2e", "ingest", 1.0, 2.0, module="parser", queue="tx")
    tr.span("t-e2e", "feed", 3.0, 4.0, module="worker")
    tr.span("t-other", "ingest", 5.0, 6.0, module="parser")
    child = TelemetryServer(MetricsRegistry(), port=0, module="worker")
    child.start()

    cfg = default_config()
    cfg["logDir"] = str(tmp_path / "logs")
    cfg["applicationManager"]["moduleSettings"] = [
        {"module": "apmbackend_tpu.runtime.worker", "metricsPort": child.port},
    ]
    cfg["applicationManager"]["metricsPort"] = 0
    runtime = ModuleRuntime(
        "applicationManager", config=cfg, install_signals=False, console_log=False
    )
    app = ManagerApp(runtime, spawn_children=False)
    try:
        status, body, _ = fetch(f"{runtime.telemetry.url}/trace?trace_id=t-e2e")
        assert status == 200
        out = json.loads(body)
        assert out["trace_count"] == 1
        spans = out["traces"]["t-e2e"]
        # child's spans + the manager's own ring folded, sorted by start
        assert {s["name"] for s in spans} == {"ingest", "feed"}
        starts = [s["start"] for s in spans]
        assert starts == sorted(starts)
        assert "worker" in out["children"]

        # a dead child degrades to an error marker instead of failing the stitch
        child.stop()
        status, body, _ = fetch(f"{runtime.telemetry.url}/trace")
        out = json.loads(body)
        assert status == 200
        assert str(out["children"]["worker"]).startswith("error")
        assert out["trace_count"] == 2  # the process ring still serves
    finally:
        app.alerts.stop()
        app.shutdown()
        runtime.stop_timers()
        child.stop()


# -- worker feed handoff -------------------------------------------------------


def test_worker_registers_sampled_traces_on_feed(tmp_path):
    """Transport -> worker -> driver: the sampled message's feed span lands
    and the trace is claimed by the tick that closes its bucket (tick/emit
    spans under the same trace_id), via the REAL WorkerApp intake path."""
    from apmbackend_tpu.runtime.module_base import ModuleRuntime
    from apmbackend_tpu.runtime.worker import WorkerApp

    get_tracer().configure(sample_rate=1, ring_size=4096)
    broker = MemoryBroker()
    cfg = default_config()
    cfg["logDir"] = None
    cfg["observability"]["traceSampleRate"] = 1
    cfg["observability"]["traceRingSize"] = 4096
    cfg["tpuEngine"]["serviceCapacity"] = 16
    cfg["tpuEngine"]["resumeFileFullPath"] = str(tmp_path / "engine.resume.npz")
    cfg["streamProcessAlerts"]["alertsResumeFileFullPath"] = None
    runtime = ModuleRuntime(
        "tpuEngine", config=cfg, broker=broker,
        install_signals=False, console_log=False,
    )
    app = WorkerApp(runtime)
    try:
        prod = mem_qm(broker).get_queue("transactions", "p")
        base = 170_200_000
        for t in range(4):
            for j in range(5):
                ts = (base + t) * 10000 + j
                prod.write_line(f"tx|jvm1|S:a|l{t}{j}|1|{ts - 150}|{ts}|150|Y")
        broker.pump()
        assert wait_for(lambda: not app.intake_pending, timeout=20)
        app.drain_intake()
        with app._driver_lock:
            app.driver.flush()
        ring = get_tracer().ring
        feed_spans = [s for s in ring.spans() if s["name"] == "feed"]
        assert len(feed_spans) == 20  # every sampled line registered
        assert feed_spans[0]["attrs"]["service"] == "S:a"
        # ticks 1..3 closed buckets 0..2: their traces carry tick+emit spans
        closed = [
            s for s in ring.spans()
            if s["name"] in ("tick", "emit") and s["attrs"]["label"] <= base + 3
        ]
        assert closed, "claimed traces must gain tick/emit spans"
        by_trace = {}
        for s in ring.spans():
            by_trace.setdefault(s["trace_id"], set()).add(s["name"])
        stitched = [n for n in by_trace.values() if {"ingest", "queue", "feed", "tick", "emit"} <= n]
        assert stitched, by_trace
    finally:
        app.shutdown()
        runtime.stop_timers()


# -- the e2e acceptance scenario -----------------------------------------------


def test_e2e_replayed_line_to_alert_one_stitched_trace(tmp_path):
    """ISSUE 5 acceptance: a sampled transaction driven from a replayed log
    line through parser -> transport -> worker -> tick -> alert is recovered
    as ONE stitched trace (ingest/queue/feed/tick/emit/alert spans) via the
    live ``/trace`` endpoint, and the alert's decision record resolves by the
    same trace_id on ``/decisions``."""
    from apmbackend_tpu.ingest.replay import write_fixture_logs
    from apmbackend_tpu.standalone import StandalonePipeline
    from tests.test_standalone import small_config

    logs = tmp_path / "fixture_logs"
    # the injected regression guarantees at least one service pages; the
    # fixture spreads each logical service across several log-line forms
    # (soap/CT/audit), so the test asserts on whichever (server, service)
    # stream actually paged rather than hard-coding one form
    write_fixture_logs(
        str(logs), n_transactions=300, seed=7,
        anomaly={"service": "getOffers", "start_frac": 0.5, "factor": 15.0},
    )
    cfg = small_config(tmp_path, metricsPort=0)
    # sample EVERY transaction (the acceptance path must be guaranteed to
    # contain the alerting one) and hold the whole run's spans
    cfg["observability"]["traceSampleRate"] = 1
    cfg["observability"]["traceRingSize"] = 16384
    # one z channel, short window, no gates: the injected x15 regression
    # must page deterministically
    cfg["streamCalcZScore"]["defaults"] = [
        {"LAG": 4, "THRESHOLD": 2.0, "INFLUENCE": 0.1}
    ]
    al = cfg["streamProcessAlerts"]
    al["rollingAlertWindowSizeInIntervals"] = 3
    al["requiredNumberBadIntervalsInAlertWindowToTrigger"] = 2
    al["perServiceAlertCooldownInMinutes"] = 0
    al["alertOnBothOnly"] = False
    al["hardMinMsAlertThreshold"] = 1
    al["hardMinTpmAlertThreshold"] = 0
    al["emailsEnabled"] = False

    pipe = StandalonePipeline(config=cfg, tail=False, install_signals=False)
    try:
        fed = pipe.replay(str(logs))
        assert fed > 0
        decisions = get_decisions().recent()
        assert decisions, "the injected regression must raise an alert"
        traced = [d for d in decisions if d.get("trace_id")]
        assert traced, "with 1/1 sampling the alerting bucket carries a trace"
        d = traced[-1]
        svc = d["service"]
        assert d["cause"]  # human-readable cause string
        assert d["threshold"] == 2.0 and d["influence"] == pytest.approx(0.1)
        assert d["window_occupancy"] is not None and d["window_occupancy"] > 0
        if "average UB exceeded" in d["cause"]:
            # the z inputs behind the page: triggering value vs the band
            m = d["metrics"]["average"]
            assert m["value"] > m["upper"]
        tid = d["trace_id"]

        # recover the stitched trace from the LIVE exporter
        server = pipe.lead.telemetry
        status, body, _ = fetch(f"{server.url}/trace?trace_id={tid}&n=64")
        assert status == 200
        out = json.loads(body)
        spans = out["spans"]
        assert spans and all(s["trace_id"] == tid for s in spans)
        names = {s["name"] for s in spans}
        assert {"ingest", "queue", "feed", "tick", "emit", "alert"} <= names
        by_name = {s["name"]: s for s in spans}
        # causal ordering across hops of ONE transaction's journey
        assert by_name["ingest"]["end"] <= by_name["queue"]["end"]
        assert by_name["queue"]["end"] <= by_name["feed"]["end"]
        assert by_name["tick"]["end"] <= by_name["emit"]["end"] + 1e-6
        assert by_name["alert"]["attrs"]["service"] == svc

        # the decision record resolves by the SAME trace_id on /decisions
        status, body, _ = fetch(f"{server.url}/decisions?trace_id={tid}")
        out = json.loads(body)
        assert status == 200 and out["count"] >= 1
        assert out["decisions"][-1]["service"] == svc

        # histogram exemplars link the latency series back to recent traces
        status, text, _ = fetch(f"{server.url}/metrics?exemplars=1")
        assert status == 200
        assert 'trace_id="t-' in text
    finally:
        pipe.shutdown()
