"""Device z-score engine vs the float64 golden oracle (reference semantics)."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apmbackend_tpu.ops import zscore as dz

from golden import GoldenZScore

METRICS = ("avg", "p75", "p95")


def drive_both(series, lag, threshold, influence, capacity=4):
    """series: list of dict key->(avg, p75, p95) per tick; keys are row ids 0..capacity-1.
    Returns list of (tick, row, metric, golden, device) comparisons."""
    golden = GoldenZScore(lag, threshold, influence)
    cfg = dz.ZScoreConfig(capacity=capacity, lag=lag, dtype=jnp.float64)
    state = dz.init_state(cfg)
    thr = jnp.full(capacity, threshold, jnp.float64)
    infl = jnp.full(capacity, influence, jnp.float64)
    step = jax.jit(dz.step, static_argnums=1)

    comparisons = []
    for t, tick_vals in enumerate(series):
        new_vals = np.full((capacity, 3), np.nan)
        for row, vals in tick_vals.items():
            new_vals[row] = vals
        # golden: per-key step ONLY for keys present this tick (reference gets
        # one StatEntry per key per tick; absent key == absent entry)
        g_out = {}
        for row, vals in tick_vals.items():
            g_out[row] = golden.step("s", f"svc{row}", *vals)
        res, state_new = step(state, cfg, jnp.asarray(new_vals), thr, infl)
        # device steps ALL rows; only compare rows that got an entry
        for row in tick_vals:
            for m_i, m in enumerate(METRICS):
                comparisons.append(
                    (
                        t, row, m,
                        g_out[row][m],
                        {
                            "avg": float(res.window_avg[row, m_i]),
                            "lb": float(res.lower_bound[row, m_i]),
                            "ub": float(res.upper_bound[row, m_i]),
                            "signal": int(res.signal[row, m_i]),
                        },
                    )
                )
        # advance device state only for rows with entries: emulate by writing
        # back selected rows (the pipeline drives all rows every tick; partial
        # presence is exercised in test_partial_rows_via_pipeline_semantics)
        mask = np.zeros(capacity, bool)
        for row in tick_vals:
            mask[row] = True
        state = dz.ZScoreState(
            values=jnp.where(jnp.asarray(mask)[:, None, None], state_new.values, state.values),
            fill=jnp.where(jnp.asarray(mask), state_new.fill, state.fill),
            pos=jnp.where(jnp.asarray(mask), state_new.pos, state.pos),
        )
    return comparisons


def check(comparisons):
    for t, row, m, g, d in comparisons:
        for f in ("avg", "lb", "ub"):
            gv, dv = g[f], d[f]
            if math.isnan(gv):
                assert math.isnan(dv), (t, row, m, f, gv, dv)
            else:
                assert gv == pytest.approx(dv, rel=1e-9, abs=1e-12), (t, row, m, f, gv, dv)
        assert g["signal"] == d["signal"], (t, row, m, g, d)


def test_warmup_no_signals():
    lag = 5
    series = [{0: (100.0, 110.0, 120.0)} for _ in range(4)]
    comps = drive_both(series, lag, 3.0, 0.5)
    for _, _, _, g, d in comps:
        assert d["signal"] == 0 and math.isnan(d["avg"])
    check(comps)


def test_signal_and_influence_damping():
    lag = 4
    rng = np.random.RandomState(0)
    series = []
    for i in range(4):
        series.append({0: (100 + rng.rand(), 110 + rng.rand(), 120 + rng.rand())})
    # big spike: must signal +1 and damp the stored value
    series.append({0: (500.0, 600.0, 700.0)})
    # follow-ups exercise the damped history
    for i in range(6):
        series.append({0: (100 + rng.rand(), 110 + rng.rand(), 120 + rng.rand())})
    comps = drive_both(series, lag, 2.0, 0.25)
    assert any(d["signal"] == 1 for _, _, _, _, d in comps)
    check(comps)


def test_negative_signal():
    lag = 4
    series = [{0: (100.0 + i * 0.1, 100.0, 100.0 + i * 0.05)} for i in range(4)]
    series.append({0: (1.0, 100.0, 50.0)})
    comps = drive_both(series, lag, 2.0, 0.0)
    assert any(d["signal"] == -1 for _, _, _, _, d in comps)
    check(comps)


def test_zero_variance_never_signals():
    """Constant history -> std undefined -> no signal, NaN bounds (the quirk)."""
    lag = 4
    series = [{0: (100.0, 100.0, 100.0)} for _ in range(4)]
    series.append({0: (99999.0, 99999.0, 99999.0)})  # way out, but no signal
    comps = drive_both(series, lag, 2.0, 0.5)
    last = comps[-3:]
    for _, _, _, g, d in last:
        assert d["signal"] == 0
        assert not math.isnan(d["avg"])  # avg defined
        assert math.isnan(d["ub"])  # bounds undefined
    check(comps)


def test_nan_entries_skipped_in_window():
    lag = 4
    series = []
    series.append({0: (100.0, 100.5, 101.0)})
    series.append({0: (float("nan"), float("nan"), float("nan"))})  # empty window tick
    series.append({0: (102.0, 102.5, 103.0)})
    series.append({0: (101.0, 101.5, 102.0)})
    series.append({0: (300.0, 300.0, 300.0)})  # spike over NaN-holed window
    series.append({0: (101.5, 102.0, 102.5)})
    comps = drive_both(series, lag, 2.0, 0.3)
    check(comps)


def test_nan_new_value_no_signal_no_damp():
    lag = 3
    rng = np.random.RandomState(3)
    series = [{0: tuple(100 + rng.rand(3))} for _ in range(3)]
    series.append({0: (float("nan"),) * 3})
    series.append({0: tuple(100 + rng.rand(3))})
    comps = drive_both(series, lag, 2.0, 0.5)
    check(comps)


def test_all_nan_window_undefined():
    lag = 3
    series = [{0: (float("nan"),) * 3} for _ in range(3)]
    series.append({0: (100.0, 100.0, 100.0)})
    comps = drive_both(series, lag, 2.0, 0.5)
    for _, _, _, g, d in comps:
        assert d["signal"] == 0
    check(comps)


def test_multi_key_independent():
    lag = 4
    rng = np.random.RandomState(9)
    series = []
    for i in range(12):
        tick = {0: tuple(100 + rng.rand(3))}
        if i >= 3:  # key 1 appears later: shorter history
            tick[1] = tuple(200 + 10 * rng.rand(3))
        series.append(tick)
    series.append({0: (105.0, 105.0, 105.0), 1: (900.0, 900.0, 900.0)})
    comps = drive_both(series, lag, 2.0, 0.1)
    check(comps)


def test_random_fuzz_many_configs():
    rng = np.random.RandomState(1234)
    for lag, thr, infl in [(3, 1.0, 0.0), (5, 2.5, 0.9), (8, 0.5, 1.0)]:
        series = []
        for _ in range(40):
            vals = 100 + 50 * rng.rand(3)
            if rng.rand() < 0.1:
                vals = np.array([np.nan] * 3)
            if rng.rand() < 0.15:
                vals = vals * 5  # occasional spikes
            series.append({0: tuple(vals)})
        comps = drive_both(series, lag, thr, infl)
        check(comps)


def test_grow_state():
    cfg = dz.ZScoreConfig(capacity=2, lag=4, dtype=jnp.float64)
    state = dz.init_state(cfg)
    res, state = dz.step(
        state, cfg, jnp.full((2, 3), 5.0), jnp.full(2, 2.0), jnp.full(2, 0.1)
    )
    grown, gcfg = dz.grow_state(state, cfg, 8)
    assert grown.values.shape == (8, 3, 4)
    assert int(grown.fill[0]) == 1 and int(grown.fill[5]) == 0
