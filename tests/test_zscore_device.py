"""Device z-score engine vs the float64 golden oracle (reference semantics)."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apmbackend_tpu.ops import zscore as dz

from golden import GoldenZScore

METRICS = ("avg", "p75", "p95")


def drive_both(series, lag, threshold, influence, capacity=4):
    """series: list of dict key->(avg, p75, p95) per tick; keys are row ids 0..capacity-1.
    Returns list of (tick, row, metric, golden, device) comparisons."""
    golden = GoldenZScore(lag, threshold, influence)
    cfg = dz.ZScoreConfig(capacity=capacity, lag=lag, dtype=jnp.float64)
    state = dz.init_state(cfg)
    thr = jnp.full(capacity, threshold, jnp.float64)
    infl = jnp.full(capacity, influence, jnp.float64)
    step = jax.jit(dz.step, static_argnums=1)

    comparisons = []
    for t, tick_vals in enumerate(series):
        new_vals = np.full((capacity, 3), np.nan)
        for row, vals in tick_vals.items():
            new_vals[row] = vals
        # golden: per-key step ONLY for keys present this tick (reference gets
        # one StatEntry per key per tick; absent key == absent entry)
        g_out = {}
        for row, vals in tick_vals.items():
            g_out[row] = golden.step("s", f"svc{row}", *vals)
        res, state_new = step(state, cfg, jnp.asarray(new_vals), thr, infl)
        # device steps ALL rows; only compare rows that got an entry
        for row in tick_vals:
            for m_i, m in enumerate(METRICS):
                comparisons.append(
                    (
                        t, row, m,
                        g_out[row][m],
                        {
                            "avg": float(res.window_avg[row, m_i]),
                            "lb": float(res.lower_bound[row, m_i]),
                            "ub": float(res.upper_bound[row, m_i]),
                            "signal": int(res.signal[row, m_i]),
                        },
                    )
                )
        # advance device state only for rows with entries: emulate by writing
        # back selected rows (the pipeline drives all rows every tick; partial
        # presence is exercised in test_partial_rows_via_pipeline_semantics).
        # The cursor is GLOBAL, so a frozen row's ring must rotate forward by
        # one slot to keep its logical window aligned with the shared cursor
        # (rotation is content-preserving: newest stays at cursor-1, the
        # about-to-be-overwritten slot stays the oldest).
        mask = np.zeros(capacity, bool)
        for row in tick_vals:
            mask[row] = True
        rotated_old = jnp.roll(state.values, 1, axis=-1)
        state = dz.ZScoreState(
            values=jnp.where(jnp.asarray(mask)[:, None, None], state_new.values, rotated_old),
            fill=jnp.where(jnp.asarray(mask), state_new.fill, state.fill),
            pos=state_new.pos,
        )
    return comparisons


def check(comparisons):
    for t, row, m, g, d in comparisons:
        for f in ("avg", "lb", "ub"):
            gv, dv = g[f], d[f]
            if math.isnan(gv):
                assert math.isnan(dv), (t, row, m, f, gv, dv)
            else:
                assert gv == pytest.approx(dv, rel=1e-9, abs=1e-12), (t, row, m, f, gv, dv)
        assert g["signal"] == d["signal"], (t, row, m, g, d)


def test_warmup_no_signals():
    lag = 5
    series = [{0: (100.0, 110.0, 120.0)} for _ in range(4)]
    comps = drive_both(series, lag, 3.0, 0.5)
    for _, _, _, g, d in comps:
        assert d["signal"] == 0 and math.isnan(d["avg"])
    check(comps)


def test_signal_and_influence_damping():
    lag = 4
    rng = np.random.RandomState(0)
    series = []
    for i in range(4):
        series.append({0: (100 + rng.rand(), 110 + rng.rand(), 120 + rng.rand())})
    # big spike: must signal +1 and damp the stored value
    series.append({0: (500.0, 600.0, 700.0)})
    # follow-ups exercise the damped history
    for i in range(6):
        series.append({0: (100 + rng.rand(), 110 + rng.rand(), 120 + rng.rand())})
    comps = drive_both(series, lag, 2.0, 0.25)
    assert any(d["signal"] == 1 for _, _, _, _, d in comps)
    check(comps)


def test_negative_signal():
    lag = 4
    series = [{0: (100.0 + i * 0.1, 100.0, 100.0 + i * 0.05)} for i in range(4)]
    series.append({0: (1.0, 100.0, 50.0)})
    comps = drive_both(series, lag, 2.0, 0.0)
    assert any(d["signal"] == -1 for _, _, _, _, d in comps)
    check(comps)


def test_zero_variance_never_signals():
    """Constant history -> std undefined -> no signal, NaN bounds (the quirk)."""
    lag = 4
    series = [{0: (100.0, 100.0, 100.0)} for _ in range(4)]
    series.append({0: (99999.0, 99999.0, 99999.0)})  # way out, but no signal
    comps = drive_both(series, lag, 2.0, 0.5)
    last = comps[-3:]
    for _, _, _, g, d in last:
        assert d["signal"] == 0
        assert not math.isnan(d["avg"])  # avg defined
        assert math.isnan(d["ub"])  # bounds undefined
    check(comps)


def test_nan_entries_skipped_in_window():
    lag = 4
    series = []
    series.append({0: (100.0, 100.5, 101.0)})
    series.append({0: (float("nan"), float("nan"), float("nan"))})  # empty window tick
    series.append({0: (102.0, 102.5, 103.0)})
    series.append({0: (101.0, 101.5, 102.0)})
    series.append({0: (300.0, 300.0, 300.0)})  # spike over NaN-holed window
    series.append({0: (101.5, 102.0, 102.5)})
    comps = drive_both(series, lag, 2.0, 0.3)
    check(comps)


def test_nan_new_value_no_signal_no_damp():
    lag = 3
    rng = np.random.RandomState(3)
    series = [{0: tuple(100 + rng.rand(3))} for _ in range(3)]
    series.append({0: (float("nan"),) * 3})
    series.append({0: tuple(100 + rng.rand(3))})
    comps = drive_both(series, lag, 2.0, 0.5)
    check(comps)


def test_all_nan_window_undefined():
    lag = 3
    series = [{0: (float("nan"),) * 3} for _ in range(3)]
    series.append({0: (100.0, 100.0, 100.0)})
    comps = drive_both(series, lag, 2.0, 0.5)
    for _, _, _, g, d in comps:
        assert d["signal"] == 0
    check(comps)


def test_multi_key_independent():
    lag = 4
    rng = np.random.RandomState(9)
    series = []
    for i in range(12):
        tick = {0: tuple(100 + rng.rand(3))}
        if i >= 3:  # key 1 appears later: shorter history
            tick[1] = tuple(200 + 10 * rng.rand(3))
        series.append(tick)
    series.append({0: (105.0, 105.0, 105.0), 1: (900.0, 900.0, 900.0)})
    comps = drive_both(series, lag, 2.0, 0.1)
    check(comps)


def test_random_fuzz_many_configs():
    rng = np.random.RandomState(1234)
    for lag, thr, infl in [(3, 1.0, 0.0), (5, 2.5, 0.9), (8, 0.5, 1.0)]:
        series = []
        for _ in range(40):
            vals = 100 + 50 * rng.rand(3)
            if rng.rand() < 0.1:
                vals = np.array([np.nan] * 3)
            if rng.rand() < 0.15:
                vals = vals * 5  # occasional spikes
            series.append({0: tuple(vals)})
        comps = drive_both(series, lag, thr, infl)
        check(comps)


def test_grow_state():
    cfg = dz.ZScoreConfig(capacity=2, lag=4, dtype=jnp.float64)
    state = dz.init_state(cfg)
    res, state = dz.step(
        state, cfg, jnp.full((2, 3), 5.0), jnp.full(2, 2.0), jnp.full(2, 0.1)
    )
    grown, gcfg = dz.grow_state(state, cfg, 8)
    assert grown.values.shape == (8, 3, 4)
    assert int(grown.fill[0]) == 1 and int(grown.fill[5]) == 0


# ---------------------------------------------------------------- robust ----

class RobustOracle:
    """Scalar float64 median/MAD oracle mirroring the classic oracle's gating
    quirks (warm-up on raw fill, NaN skip, zero spread -> no signal,
    influence damping toward the last pushed value)."""

    def __init__(self, lag, threshold, influence):
        self.lag = lag
        self.threshold = threshold
        self.influence = influence
        self.values = []  # raw pushed (may contain NaN)

    @staticmethod
    def _median(xs):
        xs = sorted(xs)
        n = len(xs)
        if n == 0:
            return float("nan")
        return (xs[(n - 1) // 2] + xs[n // 2]) / 2

    def step(self, x):
        full = len(self.values) >= self.lag
        window = self.values[-self.lag:] if full else []
        vals = [v for v in window if not math.isnan(v)]
        has_avg = full and len(vals) > 0
        med = self._median(vals) if has_avg else float("nan")
        mad = self._median([abs(v - med) for v in vals]) if has_avg else float("nan")
        has_std = has_avg and mad > 0
        spread = dz.MAD_SIGMA * mad if has_std else float("nan")
        lb = med - self.threshold * spread if has_std else float("nan")
        ub = med + self.threshold * spread if has_std else float("nan")
        signal = 0
        if has_std and not math.isnan(x) and abs(x - med) > self.threshold * spread:
            signal = 1 if x > med else -1
        pushed = x
        if signal and self.values and not math.isnan(self.values[-1]):
            pushed = self.influence * x + (1 - self.influence) * self.values[-1]
        self.values.append(pushed)
        if len(self.values) > self.lag:
            self.values = self.values[-self.lag:]
        return {"avg": med if has_avg else float("nan"), "lb": lb, "ub": ub, "signal": signal}


def drive_robust(series, lag, threshold, influence, capacity=2):
    cfg = dz.ZScoreConfig(capacity=capacity, lag=lag, dtype=jnp.float64, robust=True)
    state = dz.init_state(cfg)
    thr = jnp.full(capacity, threshold, jnp.float64)
    infl = jnp.full(capacity, influence, jnp.float64)
    step = jax.jit(dz.step, static_argnums=1)
    out = []
    for x in series:
        nv = np.full((capacity, 3), np.nan)
        nv[0] = (x, x + 1, x + 2)
        res, state = step(state, cfg, jnp.asarray(nv), thr, infl)
        out.append(res)
    return out


@pytest.mark.parametrize("influence", [1.0, 0.2])
def test_robust_matches_oracle(influence):
    rng = np.random.RandomState(31)
    series = list(200 + 30 * rng.rand(90))
    series[40] = 5000.0
    series[41] = 4800.0
    series[60] = float("nan")
    oracle = RobustOracle(12, 3.0, influence)
    results = drive_robust(series, 12, 3.0, influence)
    for t, x in enumerate(series):
        g = oracle.step(x)
        d = results[t]
        for f, got in (("avg", float(d.window_avg[0, 0])),
                       ("lb", float(d.lower_bound[0, 0])),
                       ("ub", float(d.upper_bound[0, 0]))):
            if math.isnan(g[f]):
                assert math.isnan(got), (t, f)
            else:
                assert g[f] == pytest.approx(got, rel=1e-9, abs=1e-12), (t, f)
        assert g["signal"] == int(d.signal[0, 0]), f"t={t}"


def test_robust_zero_mad_no_signal():
    # constant window: MAD == 0 -> spread undefined -> no signal (the
    # zero-variance quirk carried over)
    series = [100.0] * 20 + [500.0]
    results = drive_robust(series, 10, 3.0, 1.0)
    assert int(results[-1].signal[0, 0]) == 0
    assert math.isnan(float(results[-1].upper_bound[0, 0]))


def test_robust_survives_outlier_contamination_classic_masked():
    """The motivating scenario: an outlier burst lands in the window. The
    classic z-score's std inflates (self-contamination) and a later genuine
    regression hides inside the widened bounds; median/MAD shrugs off the
    burst and flags the same regression."""
    rng = np.random.RandomState(7)
    lag, thr = 30, 3.0
    base = list(200 + 4 * rng.rand(60))
    burst = [4000.0, 4200.0, 3900.0]  # 3 outliers (10% of the window)
    calm = list(200 + 4 * rng.rand(20))
    probe = [260.0]  # genuine step: ~15 sigma of the clean noise, well under
    series = base + burst + calm + probe  # the burst-inflated classic bounds
    # classic path (influence=1: burst enters the window undamped)
    classic = drive_both(
        [{0: (x, x, x)} for x in series], lag, thr, influence=1.0, capacity=2
    )
    classic_last = [c for c in classic if c[0] == len(series) - 1 and c[2] == "avg"][0]
    assert classic_last[3]["signal"] == 0, "classic must be blinded by its own window"
    # robust path on the same series
    robust = drive_robust(series, lag, thr, 1.0)
    assert int(robust[-1].signal[0, 0]) == 1, "median/MAD must flag the step"


def test_robust_flows_from_config():
    from apmbackend_tpu.config import default_config
    from apmbackend_tpu.pipeline import PipelineDriver, build_engine_config

    cfg_tree = default_config()
    cfg_tree["tpuEngine"]["serviceCapacity"] = 8
    cfg_tree["tpuEngine"]["samplesPerBucket"] = 8
    cfg_tree["streamCalcZScore"]["defaults"] = [
        {"LAG": 4, "THRESHOLD": 20, "INFLUENCE": 0.1},
        {"LAG": 8, "THRESHOLD": 3, "INFLUENCE": 0.1, "ROBUST": True},
    ]
    ecfg = build_engine_config(cfg_tree, 8)
    assert [spec.robust for spec in ecfg.lags] == [False, True]
    # the engine ticks with a mixed classic/robust lag set
    from apmbackend_tpu.entries import TxEntry

    drv = PipelineDriver(cfg_tree, capacity=8)
    ts = 170_000_000_0000
    for t in range(14):
        drv.feed(TxEntry("s", "svc", f"L{t}", "A", ts - 100, float(ts), 100.0 + t, "Y"))
        ts += 10_000
    assert drv._latest_label > 0


def test_robust_window_sharding_not_supported():
    from apmbackend_tpu.parallel import make_mesh2d, make_window_sharded_step

    mesh = make_mesh2d(1, 2)
    cfg = dz.ZScoreConfig(capacity=8, lag=8, dtype=jnp.float32, robust=True)
    with pytest.raises(NotImplementedError, match="robust"):
        make_window_sharded_step(mesh, cfg)


# ----------------------------------------------------------- bf16 ring ----

def _drive_ring(series, ring_dtype, lag=12, thr=3.0, infl=0.2, capacity=2):
    cfg = dz.ZScoreConfig(capacity=capacity, lag=lag, dtype=jnp.float32,
                          ring_dtype=ring_dtype)
    state = dz.init_state(cfg)
    step = jax.jit(dz.step, static_argnums=1)
    thr_v = jnp.full(capacity, thr, jnp.float32)
    infl_v = jnp.full(capacity, infl, jnp.float32)
    out = []
    for x in series:
        nv = np.full((capacity, 3), np.nan, np.float32)
        nv[0] = (x, x + 1, x + 2)
        res, state = step(state, cfg, jnp.asarray(nv), thr_v, infl_v)
        out.append(res)
    return out, state


def test_bf16_ring_storage_and_approx_parity():
    """bfloat16 ring: stored values are bf16 (half the HBM bytes), statistics
    accumulate in f32, and results track the f32 ring within bf16's ~0.4%
    relative error — with clear-margin signals identical."""
    rng = np.random.RandomState(17)
    series = list(200 + 20 * rng.rand(40))
    series[30] = 5000.0  # far beyond any bound perturbation
    f32_res, f32_state = _drive_ring(series, None)
    bf_res, bf_state = _drive_ring(series, jnp.bfloat16)
    assert bf_state.values.dtype == jnp.bfloat16
    assert f32_state.values.dtype == jnp.float32
    for t in range(len(series)):
        a, b = f32_res[t], bf_res[t]
        np.testing.assert_allclose(
            np.nan_to_num(np.asarray(a.window_avg)),
            np.nan_to_num(np.asarray(b.window_avg)), rtol=2e-2, atol=1e-2,
        )
        np.testing.assert_array_equal(np.asarray(a.signal), np.asarray(b.signal))


def test_bf16_ring_exact_quirks():
    # constant series: every stored bf16 value is identical -> max==min ->
    # zero-variance quirk holds EXACTLY (no float luck needed)
    series = [128.0] * 20 + [500.0]
    res, _ = _drive_ring(series, jnp.bfloat16)
    assert int(res[-1].signal[0, 0]) == 0
    assert math.isnan(float(res[-1].upper_bound[0, 0]))
    # warm-up gating unchanged
    assert all(int(r.signal[0, 0]) == 0 for r in res[:12])


def test_bf16_ring_resume_roundtrip(tmp_path):
    """npz stores the bf16 ring as f32 (exact upcast); load returns the exact
    same bf16 bits."""
    from apmbackend_tpu.config import default_config
    from apmbackend_tpu.entries import TxEntry
    from apmbackend_tpu.pipeline import PipelineDriver

    cfg_tree = default_config()
    cfg_tree["tpuEngine"]["serviceCapacity"] = 8
    cfg_tree["tpuEngine"]["samplesPerBucket"] = 8
    cfg_tree["tpuEngine"]["dtype"] = "float32"
    cfg_tree["tpuEngine"]["zscoreRingDtype"] = "bfloat16"
    cfg_tree["streamCalcZScore"]["defaults"] = [{"LAG": 4, "THRESHOLD": 3, "INFLUENCE": 0.1}]
    d1 = PipelineDriver(cfg_tree, capacity=8)
    assert d1.state.zscores[0].values.dtype == jnp.bfloat16
    ts = 170_000_000_0000
    for t in range(12):
        d1.feed(TxEntry("s", "svc", f"L{t}", "A", ts - 100, float(ts), 100.0 + 7 * t, "Y"))
        ts += 10_000
    path = str(tmp_path / "resume.npz")
    d1.save_resume(path)
    d2 = PipelineDriver(cfg_tree, capacity=8)
    assert d2.load_resume(path)
    assert d2.state.zscores[0].values.dtype == jnp.bfloat16
    a = np.asarray(d1.state.zscores[0].values.astype(jnp.float32))
    b = np.asarray(d2.state.zscores[0].values.astype(jnp.float32))
    np.testing.assert_array_equal(np.nan_to_num(a), np.nan_to_num(b))


def test_ring_dtype_config_validation():
    from apmbackend_tpu.config import default_config
    from apmbackend_tpu.pipeline import build_engine_config

    cfg_tree = default_config()
    cfg_tree["tpuEngine"]["zscoreRingDtype"] = "float16"
    with pytest.raises(ValueError, match="zscoreRingDtype"):
        build_engine_config(cfg_tree, 8)
    cfg_tree["tpuEngine"]["zscoreRingDtype"] = "float32"  # == dtype -> None
    assert build_engine_config(cfg_tree, 8).zscore_ring_dtype is None
    cfg_tree["tpuEngine"]["zscoreRingDtype"] = "bfloat16"
    assert build_engine_config(cfg_tree, 8).zscore_ring_dtype == jnp.bfloat16


def test_bf16_ring_window_sharded_matches_single_chip():
    from apmbackend_tpu.parallel import make_mesh2d, make_window_sharded_step, shard_zstate

    cfg = dz.ZScoreConfig(capacity=8, lag=8, dtype=jnp.float32, ring_dtype=jnp.bfloat16)
    state_s = dz.init_state(cfg)
    state_w = shard_zstate(dz.init_state(cfg), make_mesh2d(2, 4))
    mesh = make_mesh2d(2, 4)
    wstep = make_window_sharded_step(mesh, cfg)
    step = jax.jit(dz.step, static_argnums=1)
    rng = np.random.RandomState(5)
    thr = jnp.full(8, 2.0, jnp.float32)
    infl = jnp.full(8, 0.3, jnp.float32)
    for t in range(12):
        nv = jnp.asarray((200 + 30 * rng.rand(8, 3)).astype(np.float32))
        res_s, state_s = step(state_s, cfg, nv, thr, infl)
        res_w, state_w = wstep(state_w, nv, thr, infl)
    assert state_w.values.dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.nan_to_num(np.asarray(res_s.window_avg)),
        np.nan_to_num(np.asarray(res_w.window_avg)),
    )
    np.testing.assert_array_equal(np.asarray(res_s.signal), np.asarray(res_w.signal))
    np.testing.assert_array_equal(
        np.nan_to_num(np.asarray(state_s.values.astype(jnp.float32))),
        np.nan_to_num(np.asarray(state_w.values.astype(jnp.float32))),
    )


# -------------------------------------------------------- one-pass var ----

def test_onepass_f64_guard_pins_twopass():
    """onepass_var is IGNORED in f64 parity mode: bit-identical outputs to
    the two-pass config on the same stream."""
    rng = np.random.RandomState(41)
    series = list(300 + 40 * rng.rand(60))
    series[50] = 4000.0
    outs = {}
    for onepass in (False, True):
        cfg = dz.ZScoreConfig(capacity=2, lag=12, dtype=jnp.float64, onepass_var=onepass)
        state = dz.init_state(cfg)
        step = jax.jit(dz.step, static_argnums=1)
        thr = jnp.full(2, 3.0, jnp.float64)
        infl = jnp.full(2, 0.2, jnp.float64)
        out = []
        for x in series:
            nv = np.full((2, 3), np.nan)
            nv[0] = (x, x + 1, x + 2)
            res, state = step(state, cfg, jnp.asarray(nv), thr, infl)
            out.append(np.nan_to_num(np.asarray(res.upper_bound)))
        outs[onepass] = out
    for a, b in zip(outs[False], outs[True]):
        np.testing.assert_array_equal(a, b)


def test_onepass_var_f32_matches_oracle_loose():
    """The one-pass branch itself (f32) against the float64 golden oracle:
    bounds within f32-appropriate tolerance, signals identical on
    clear-margin anomalies, including across a NaN data gap."""
    rng = np.random.RandomState(41)
    series = list(300 + 40 * rng.rand(80))
    series[40] = float("nan")  # data gap: the anchor must survive it
    series[50] = 4000.0
    golden = GoldenZScore(12, 3.0, 0.2)
    cfg = dz.ZScoreConfig(capacity=2, lag=12, dtype=jnp.float32, onepass_var=True)
    state = dz.init_state(cfg)
    step = jax.jit(dz.step, static_argnums=1)
    thr = jnp.full(2, 3.0, jnp.float32)
    infl = jnp.full(2, 0.2, jnp.float32)
    for t, x in enumerate(series):
        nv = np.full((2, 3), np.nan, np.float32)
        nv[0] = (x, x + 1, x + 2)
        g = golden.step("s", "svc", x, x + 1, x + 2)["avg"]
        res, state = step(state, cfg, jnp.asarray(nv), thr, infl)
        got = float(res.upper_bound[0, 0])
        if math.isnan(g["ub"]):
            assert math.isnan(got), t
        else:
            assert g["ub"] == pytest.approx(got, rel=5e-4), t
        assert g["signal"] == int(res.signal[0, 0]), f"t={t}"


def test_onepass_var_survives_nan_gap_at_large_magnitude():
    """Regression for the anchor=0 cancellation bug: large-magnitude values
    (~1e6) with a NaN push right before a genuine spike — the one-pass
    variance must stay sane (a zero anchor computes var as a huge negative,
    clamps to 0, and silently suppresses the signal)."""
    rng = np.random.RandomState(7)
    base = 1_000_000.0
    series = list(base + 2000 * rng.rand(30))
    series += [float("nan")]          # the gap: last pushed value becomes NaN
    series += [base + 60_000.0]       # clear spike (~30 sigma) right after
    cfg = dz.ZScoreConfig(capacity=1, lag=16, dtype=jnp.float32, onepass_var=True)
    state = dz.init_state(cfg)
    step = jax.jit(dz.step, static_argnums=1)
    thr = jnp.full(1, 3.0, jnp.float32)
    infl = jnp.full(1, 1.0, jnp.float32)
    res = None
    for x in series:
        nv = np.full((1, 3), x, np.float32)
        res, state = step(state, cfg, jnp.asarray(nv), thr, infl)
    assert int(res.signal[0, 0]) == 1, "spike after a data gap must still signal"
    assert not math.isnan(float(res.upper_bound[0, 0]))


def test_onepass_var_f32_approximates_twopass():
    """f32: one-pass bounds/avg within 1e-4 relative of two-pass; signals
    identical on clear-margin anomalies; the all-equal zero-variance quirk
    stays EXACT."""
    rng = np.random.RandomState(43)
    series = list(500 + 60 * rng.rand(60))
    series[45] = 9000.0  # unambiguous spike
    results = {}
    for onepass in (False, True):
        cfg = dz.ZScoreConfig(capacity=2, lag=16, dtype=jnp.float32, onepass_var=onepass)
        state = dz.init_state(cfg)
        step = jax.jit(dz.step, static_argnums=1)
        thr = jnp.full(2, 3.0, jnp.float32)
        infl = jnp.full(2, 0.2, jnp.float32)
        out = []
        for x in series:
            nv = np.full((2, 3), np.nan, np.float32)
            nv[0] = (x, x + 1, x + 2)
            res, state = step(state, cfg, jnp.asarray(nv), thr, infl)
            out.append(res)
        results[onepass] = out
    for t in range(len(series)):
        a, b = results[False][t], results[True][t]
        np.testing.assert_allclose(
            np.nan_to_num(np.asarray(a.window_avg)), np.nan_to_num(np.asarray(b.window_avg)),
            rtol=1e-4, atol=1e-3,
        )
        np.testing.assert_allclose(
            np.nan_to_num(np.asarray(a.upper_bound)), np.nan_to_num(np.asarray(b.upper_bound)),
            rtol=1e-3, atol=1e-2,
        )
        np.testing.assert_array_equal(np.asarray(a.signal), np.asarray(b.signal))


def test_onepass_var_all_equal_exact():
    cfg = dz.ZScoreConfig(capacity=1, lag=8, dtype=jnp.float32, onepass_var=True)
    state = dz.init_state(cfg)
    step = jax.jit(dz.step, static_argnums=1)
    thr = jnp.full(1, 1.0, jnp.float32)
    infl = jnp.full(1, 1.0, jnp.float32)
    res = None
    for x in [333.3] * 12 + [900.0]:
        nv = np.full((1, 3), x, np.float32)
        res, state = step(state, cfg, jnp.asarray(nv), thr, infl)
    assert int(res.signal[0, 0]) == 0  # zero-variance quirk held exactly
    assert math.isnan(float(res.upper_bound[0, 0]))


def test_variance_pass_config_flow():
    from apmbackend_tpu.config import default_config
    from apmbackend_tpu.pipeline import build_engine_config

    tree = default_config()
    assert build_engine_config(tree, 8).zscore_onepass  # auto
    tree["tpuEngine"]["zscoreVariancePass"] = "two"
    assert not build_engine_config(tree, 8).zscore_onepass
    tree["tpuEngine"]["zscoreVariancePass"] = "bogus"
    with pytest.raises(ValueError, match="zscoreVariancePass"):
        build_engine_config(tree, 8)


def test_onepass_window_sharding_refused():
    from apmbackend_tpu.parallel import make_mesh2d, make_window_sharded_step

    mesh = make_mesh2d(1, 2)
    cfg = dz.ZScoreConfig(capacity=8, lag=8, dtype=jnp.float32, onepass_var=True)
    with pytest.raises(NotImplementedError, match="one-pass"):
        make_window_sharded_step(mesh, cfg)


# ---------------------------------------------------------------------------
# sliding O(1) aggregates (ZScoreConfig.sliding): the production default.
# Battery strategy: drive the SAME stream through the exact two-pass mode and
# the sliding mode and demand identical signal decisions (bounds to fp
# tolerance) through every hazard the incremental path owns: NaN gaps,
# constant rows (run-length guard), outlier damping, late row activation,
# periodic rebuilds, drain-to-empty windows, large-magnitude anchoring,
# build_agg restore, and the staged three-program engine executor.
# ---------------------------------------------------------------------------


def _drive_modes(series, active_from=None, lag=6, thr=3.0, infl=0.3,
                 rebuild_every=7, capacity=None):
    """Run series through two-pass and sliding (with host-cadenced rebuilds);
    returns {mode: [ZScoreResult...]}. ``series``: list of [S, 3] float32
    (NaN allowed). ``active_from``: per-row first-active tick (None = all
    active from 0)."""
    S = series[0].shape[0] if capacity is None else capacity
    out = {}
    for mode in ("two", "sliding"):
        cfg = dz.ZScoreConfig(S, lag, jnp.float32,
                              sliding=(mode == "sliding"),
                              rebuild_every=rebuild_every)
        state = dz.init_state(cfg)
        step = jax.jit(dz.step, static_argnums=1)
        rebuild = jax.jit(dz.rebuild_agg_state, static_argnums=1)
        thr_v = jnp.full(S, thr, jnp.float32)
        infl_v = jnp.full(S, infl, jnp.float32)
        res_all = []
        since = 0
        for t, vals in enumerate(series):
            if active_from is None:
                act = jnp.ones(S, bool)
            else:
                act = jnp.asarray(np.asarray(active_from) <= t)
            r, state = step(state, cfg, jnp.asarray(vals), thr_v, infl_v, act)
            res_all.append(jax.device_get(r))
            since += 1
            if mode == "sliding" and since >= rebuild_every:
                since = 0
                state = rebuild(state, cfg)
        out[mode] = res_all
    return out


def _assert_mode_parity(out, rtol=2e-4, atol=1e-3):
    n_sig = 0
    for t, (a, b) in enumerate(zip(out["two"], out["sliding"])):
        np.testing.assert_array_equal(a.signal, b.signal, err_msg=f"tick {t}")
        n_sig += int(np.abs(a.signal).sum())
        for f in ("window_avg", "lower_bound", "upper_bound"):
            x, y = getattr(a, f), getattr(b, f)
            np.testing.assert_array_equal(np.isnan(x), np.isnan(y), err_msg=f"tick {t} {f}")
            ok = ~np.isnan(x)
            if ok.any():
                np.testing.assert_allclose(x[ok], y[ok], rtol=rtol, atol=atol,
                                           err_msg=f"tick {t} {f}")
    return n_sig


def test_sliding_matches_twopass_hazard_stream():
    """The kitchen-sink stream: noise, NaN gaps, an outlier burst (damping),
    a row that goes constant, and a late-activated row. Signals must be
    IDENTICAL to the exact two-pass mode at every tick."""
    rng = np.random.RandomState(7)
    S, T = 5, 64
    series = []
    for t in range(T):
        v = (100 + 10 * rng.randn(S, 3)).astype(np.float32)
        if t % 11 == 3:
            v[1] = np.nan  # recurring NaN gap
        if t in (30, 31):
            v[2] += 500  # outlier burst -> signals + influence damping
        if t >= 40:
            v[3] = 250.0  # goes constant: run-length guard takes over
        series.append(v)
    out = _drive_modes(series, active_from=[0, 0, 0, 0, 20])  # row 4 activates late
    n_sig = _assert_mode_parity(out)
    assert n_sig > 0, "stream must actually exercise signals"


def test_sliding_large_magnitude_anchor():
    """Fresh rows at 1e6 scale with tiny variance: the first-value re-anchor
    must keep the anchored sums tight (no E[x^2]-mean^2 blowup) AND must not
    leave a phantom (v0 - 0)^2 term behind (the re-anchor consistency bug:
    both deltas must use the post-re-anchor value)."""
    rng = np.random.RandomState(11)
    series = [(1_000_000 + 2 * rng.randn(2, 3)).astype(np.float32) for _ in range(40)]
    series[25][0] += 100  # ~50 sigma: must signal
    # semantic comparison, not per-tick signal parity: with an 8-sample
    # window the std estimate is +-30% noisy and at 1e6 magnitude the f32
    # delta quantization (ulp 0.0625 vs sigma 2) legitimately flips
    # borderline draws between modes. What the anchor bugs break is GROSS:
    # anchor 0 destroys the variance entirely (catastrophic cancellation);
    # the phantom-(v0)^2 re-anchor bug inflated std ~60% and silenced the
    # 50-sigma spike. So: spike fires in sliding mode, and the band WIDTH
    # (ub - lb = 2*thr*std) tracks two-pass within a few percent.
    out = _drive_modes(series, lag=8, thr=6.0, rebuild_every=10_000)  # no rebuild help
    spike = out["sliding"][25]
    assert int(spike.signal[0, 0]) == 1, "50-sigma spike must signal in sliding mode"
    for t in range(8, 40):
        a, b = out["two"][t], out["sliding"][t]
        wa = np.asarray(a.upper_bound) - np.asarray(a.lower_bound)
        wb = np.asarray(b.upper_bound) - np.asarray(b.lower_bound)
        ok = ~(np.isnan(wa) | np.isnan(wb))
        if ok.any():
            np.testing.assert_allclose(wb[ok], wa[ok], rtol=0.08,
                                       err_msg=f"band width diverged at tick {t}")


def test_sliding_drain_and_refill():
    """A window that drains to all-NaN and refills: cnt returns to 0, sums
    reset exactly, and the re-anchor starts clean."""
    S, lag = 1, 5
    series = []
    series += [np.full((S, 3), 77.0, np.float32) for _ in range(7)]
    series += [np.full((S, 3), np.nan, np.float32) for _ in range(lag + 2)]  # drain
    rng = np.random.RandomState(3)
    series += [(40 + rng.rand(S, 3)).astype(np.float32) for _ in range(12)]  # refill
    out = _drive_modes(series, lag=lag, rebuild_every=10_000)
    _assert_mode_parity(out)


def test_sliding_constant_then_tiny_deviation_no_signal():
    """Zero-variance quirk under sliding: after the window becomes all-equal
    (through >= lag equal pushes), a small deviation must NOT signal (std
    undefined), exactly like the reference and the two-pass guard."""
    S, lag = 1, 6
    rng = np.random.RandomState(5)
    series = [(90 + 5 * rng.randn(S, 3)).astype(np.float32) for _ in range(10)]
    series += [np.full((S, 3), 120.0, np.float32) for _ in range(lag + 3)]
    probe = np.full((S, 3), 120.4, np.float32)  # would signal if std ~ float noise
    series += [probe]
    out = _drive_modes(series, lag=lag)
    _assert_mode_parity(out)
    assert int(out["sliding"][-1].signal.sum()) == 0


def test_sliding_build_agg_restore_parity():
    """Snapshot the ring mid-stream, rebuild the aggregates via build_agg
    (the resume path), continue — emissions must match the uninterrupted
    run (restore conservatism may only delay the all-equal guard, which the
    continuation here re-establishes before it matters)."""
    rng = np.random.RandomState(13)
    S, lag = 3, 6
    series = [(50 + 6 * rng.randn(S, 3)).astype(np.float32) for _ in range(40)]
    series[33][1] += 200  # a signal after the restore point

    cfg = dz.ZScoreConfig(S, lag, jnp.float32, sliding=True, rebuild_every=10_000)
    step = jax.jit(dz.step, static_argnums=1)
    thr = jnp.full(S, 3.0, jnp.float32)
    infl = jnp.full(S, 0.3, jnp.float32)

    state = dz.init_state(cfg)
    base = []
    for vals in series:
        r, state = step(state, cfg, jnp.asarray(vals), thr, infl)
        base.append(jax.device_get(r))

    state = dz.init_state(cfg)
    for vals in series[:20]:
        r, state = step(state, cfg, jnp.asarray(vals), thr, infl)
    # restore: keep only the persisted leaves, rederive the aggregates
    state = dz.ZScoreState(
        values=state.values, fill=state.fill, pos=state.pos,
        agg=dz.build_agg(state.values, cfg, state.pos),
    )
    resumed = []
    for vals in series[20:]:
        r, state = step(state, cfg, jnp.asarray(vals), thr, infl)
        resumed.append(jax.device_get(r))
    for t, (a, b) in enumerate(zip(base[20:], resumed)):
        np.testing.assert_array_equal(a.signal, b.signal, err_msg=f"tick {20+t}")
        np.testing.assert_allclose(
            np.nan_to_num(a.upper_bound), np.nan_to_num(b.upper_bound),
            rtol=2e-4, atol=1e-3,
        )


def test_sliding_grow_state_continues():
    cfg = dz.ZScoreConfig(4, 5, jnp.float32, sliding=True)
    state = dz.init_state(cfg)
    step = jax.jit(dz.step, static_argnums=1)
    rng = np.random.RandomState(1)
    for _ in range(8):
        v = (10 + rng.rand(4, 3)).astype(np.float32)
        _, state = step(state, cfg, jnp.asarray(v), jnp.full(4, 3.0), jnp.full(4, 0.2))
    state, cfg2 = dz.grow_state(state, cfg, 8)
    assert state.agg.cnt.shape == (8, 3)
    act = jnp.asarray(np.array([True] * 4 + [False] * 4))
    r, state = step(state, cfg2, jnp.asarray((10 + rng.rand(8, 3)).astype(np.float32)),
                    jnp.full(8, 3.0), jnp.full(8, 0.2), act)
    assert int(np.asarray(state.agg.cnt)[4:].sum()) == 0  # inactive rows untouched
    assert math.isnan(float(np.asarray(state.agg.last_push)[5, 0]))


def test_sliding_f64_parity_mode_inert():
    cfg = dz.ZScoreConfig(2, 6, jnp.float64, sliding=True)
    assert not cfg.sliding_active
    state = dz.init_state(cfg)
    assert state.agg is None


def test_sliding_config_flow():
    from apmbackend_tpu.config import default_config
    from apmbackend_tpu.pipeline import build_engine_config

    tree = default_config()
    assert build_engine_config(tree, 8).zscore_sliding  # auto -> sliding
    tree["tpuEngine"]["zscoreVariancePass"] = "sliding"
    assert build_engine_config(tree, 8).zscore_sliding
    tree["tpuEngine"]["zscoreVariancePass"] = "one"
    cfg = build_engine_config(tree, 8)
    assert not cfg.zscore_sliding and cfg.zscore_onepass
    tree["tpuEngine"]["zscoreVariancePass"] = "two"
    cfg = build_engine_config(tree, 8)
    assert not cfg.zscore_sliding and not cfg.zscore_onepass


def test_sliding_window_sharding_refused():
    from apmbackend_tpu.parallel import make_mesh2d, make_window_sharded_step

    mesh = make_mesh2d(1, 2)
    cfg = dz.ZScoreConfig(capacity=8, lag=8, dtype=jnp.float32, sliding=True)
    with pytest.raises(NotImplementedError, match="sliding"):
        make_window_sharded_step(mesh, cfg)


def test_staged_engine_step_matches_single_program():
    """make_engine_step (three-dispatch staged executor) must be BITWISE
    identical to the single-program jitted engine_tick — same math, only the
    program boundaries differ."""
    from apmbackend_tpu.pipeline import (
        engine_init, engine_tick, make_demo_engine, make_engine_step,
    )

    cfg, _, params = make_demo_engine(8, 4, [(4, 3.0, 0.2), (6, 3.0, 0.2)])
    assert cfg.zscore_sliding
    state_a = engine_init(cfg)
    state_b = engine_init(cfg)
    staged = make_engine_step(cfg)
    mono = jax.jit(engine_tick, static_argnums=1)
    label = 170_000_000
    rng = np.random.RandomState(2)
    for i in range(10):
        label += 1
        em_a, state_a = staged(state_a, label, params)
        em_b, state_b = mono(state_b, cfg, label, params)
        for la, lb in zip(em_a.lags, em_b.lags):
            np.testing.assert_array_equal(np.asarray(la.signal), np.asarray(lb.signal))
            np.testing.assert_array_equal(
                np.nan_to_num(np.asarray(la.upper_bound)),
                np.nan_to_num(np.asarray(lb.upper_bound)),
            )
    for za, zb in zip(state_a.zscores, state_b.zscores):
        np.testing.assert_array_equal(
            np.nan_to_num(np.asarray(za.values)), np.nan_to_num(np.asarray(zb.values))
        )
        np.testing.assert_array_equal(np.asarray(za.pos), np.asarray(zb.pos))
