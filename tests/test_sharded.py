"""Sharded engine tests on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apmbackend_tpu.config import default_config
from apmbackend_tpu.parallel import (
    make_mesh,
    make_sharded_ingest,
    make_sharded_tick,
    padded_capacity,
    route_batch,
    shard_rows,
)
from apmbackend_tpu.pipeline import (
    EngineParams,
    build_engine_config,
    engine_init,
    engine_ingest,
    engine_tick,
)

BASE = 170_000_000


from apmbackend_tpu.pipeline import make_demo_engine

# thresholds differ per lag in the demo config but make_params historically
# used 2.0 for both; keep that via explicit settings
LAG_SETTINGS = [(4, 2.0, 0.1), (8, 2.0, 0.0)]


def small_cfg(capacity=64):
    cfg, _state, _params = make_demo_engine(capacity, 16, LAG_SETTINGS)
    return cfg


def make_params(cfg):
    _cfg, _state, params = make_demo_engine(cfg.capacity, 16, LAG_SETTINGS)
    return params


def test_mesh_and_padding():
    mesh = make_mesh(8)
    assert mesh.devices.size == 8
    assert padded_capacity(100, 8) == 104


def test_sharded_matches_single_device():
    """Sharded tick+ingest over 8 devices == unsharded reference run."""
    cfg = small_cfg(capacity=64)
    params = make_params(cfg)
    mesh = make_mesh(8)
    n = 8

    rng = np.random.RandomState(0)
    B = 128
    all_rows = rng.randint(0, 40, size=(5, B)).astype(np.int32)
    all_elaps = rng.randint(50, 2000, size=(5, B)).astype(np.float32)

    # single-device path
    state_a = engine_init(cfg)
    emissions_a = []
    for t in range(5):
        em, state_a = engine_tick(state_a, cfg, BASE + t + 1, params)
        emissions_a.append(em)
        labels = np.full(B, BASE + t + 1, np.int32)
        state_a = engine_ingest(state_a, cfg, all_rows[t], labels, all_elaps[t], np.ones(B, bool))

    # sharded path
    tick = make_sharded_tick(mesh, cfg)
    ingest = make_sharded_ingest(mesh, cfg)
    state_b = shard_rows(engine_init(cfg), mesh)
    params_b = shard_rows(params, mesh)
    emissions_b, rollups = [], []
    for t in range(5):
        em, roll, state_b = tick(state_b, jnp.int32(BASE + t + 1), params_b)
        emissions_b.append(em)
        rollups.append(roll)
        labels = np.full(B, BASE + t + 1, np.int32)
        r, l, e, v, dropped = route_batch(
            all_rows[t], labels, all_elaps[t], np.ones(B, bool),
            capacity=64, n_shards=n, batch_per_shard=B,
        )
        assert dropped == 0
        state_b = ingest(state_b, r, l, e, v)

    for em_a, em_b in zip(emissions_a, emissions_b):
        np.testing.assert_allclose(np.asarray(em_a.tpm), np.asarray(em_b.tpm), rtol=1e-6)
        np.testing.assert_array_equal(np.asarray(em_a.count), np.asarray(em_b.count))
        a = np.asarray(em_a.average)
        b = np.asarray(em_b.average)
        np.testing.assert_allclose(np.nan_to_num(a, nan=-1), np.nan_to_num(b, nan=-1), rtol=1e-5)
        for la, lb_ in zip(em_a.lags, em_b.lags):
            np.testing.assert_array_equal(np.asarray(la.signal), np.asarray(lb_.signal))
            np.testing.assert_array_equal(np.asarray(la.trigger), np.asarray(lb_.trigger))

    # rollup consistency vs the unsharded emission
    last_a, last_roll = emissions_a[-1], rollups[-1]
    assert int(last_roll.total_tx) == int(np.sum(np.asarray(last_a.count)))
    avg = np.asarray(last_a.average)[:, 0]
    defined = ~np.isnan(avg)
    if defined.any():
        assert float(last_roll.mean_elapsed) == pytest.approx(float(avg[defined].mean()), rel=1e-5)


def test_rollup_signal_counts():
    cfg = small_cfg(capacity=16)
    params = make_params(cfg)
    mesh = make_mesh(8)
    tick = make_sharded_tick(mesh, cfg)
    ingest = make_sharded_ingest(mesh, cfg)
    state = shard_rows(engine_init(cfg), mesh)
    params_s = shard_rows(params, mesh)
    rng = np.random.RandomState(1)
    roll = None
    for t in range(16):
        em, roll, state = tick(state, jnp.int32(BASE + t + 1), params_s)
        B = 64
        rows = rng.randint(0, 16, B).astype(np.int32)
        base_ms = 200 if t < 12 else 8000  # fleet-wide regression late in the run
        elaps = (base_ms + 20 * rng.rand(B)).astype(np.float32)
        r, l, e, v, _ = route_batch(
            rows, np.full(B, BASE + t + 1, np.int32), elaps, np.ones(B, bool),
            capacity=16, n_shards=8, batch_per_shard=B,
        )
        state = ingest(state, r, l, e, v)
    assert roll is not None
    assert int(roll.total_tx) > 0
    assert roll.signals_high.shape == (2,)
