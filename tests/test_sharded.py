"""Sharded engine tests on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apmbackend_tpu.config import default_config
from apmbackend_tpu.parallel import (
    make_mesh,
    make_sharded_ingest,
    make_sharded_tick,
    padded_capacity,
    route_batch,
    shard_rows,
)
from apmbackend_tpu.pipeline import (
    EngineParams,
    build_engine_config,
    engine_init,
    engine_ingest,
    engine_tick,
)

BASE = 170_000_000


from apmbackend_tpu.pipeline import make_demo_engine

# thresholds differ per lag in the demo config but make_params historically
# used 2.0 for both; keep that via explicit settings
LAG_SETTINGS = [(4, 2.0, 0.1), (8, 2.0, 0.0)]


def small_cfg(capacity=64):
    cfg, _state, _params = make_demo_engine(capacity, 16, LAG_SETTINGS)
    return cfg


def make_params(cfg):
    _cfg, _state, params = make_demo_engine(cfg.capacity, 16, LAG_SETTINGS)
    return params


def test_mesh_and_padding():
    mesh = make_mesh(8)
    assert mesh.devices.size == 8
    assert padded_capacity(100, 8) == 104


def test_sharded_matches_single_device():
    """Sharded tick+ingest over 8 devices == unsharded reference run."""
    cfg = small_cfg(capacity=64)
    params = make_params(cfg)
    mesh = make_mesh(8)
    n = 8

    rng = np.random.RandomState(0)
    B = 128
    all_rows = rng.randint(0, 40, size=(5, B)).astype(np.int32)
    all_elaps = rng.randint(50, 2000, size=(5, B)).astype(np.float32)

    # single-device path
    state_a = engine_init(cfg)
    emissions_a = []
    for t in range(5):
        em, state_a = engine_tick(state_a, cfg, BASE + t + 1, params)
        emissions_a.append(em)
        labels = np.full(B, BASE + t + 1, np.int32)
        state_a = engine_ingest(state_a, cfg, all_rows[t], labels, all_elaps[t], np.ones(B, bool))

    # sharded path
    tick = make_sharded_tick(mesh, cfg)
    ingest = make_sharded_ingest(mesh, cfg)
    state_b = shard_rows(engine_init(cfg), mesh)
    params_b = shard_rows(params, mesh)
    emissions_b, rollups = [], []
    for t in range(5):
        em, roll, state_b = tick(state_b, jnp.int32(BASE + t + 1), params_b)
        emissions_b.append(em)
        rollups.append(roll)
        labels = np.full(B, BASE + t + 1, np.int32)
        r, l, e, v, dropped = route_batch(
            all_rows[t], labels, all_elaps[t], np.ones(B, bool),
            capacity=64, n_shards=n, batch_per_shard=B,
        )
        assert dropped == 0
        state_b = ingest(state_b, r, l, e, v)

    for em_a, em_b in zip(emissions_a, emissions_b):
        np.testing.assert_allclose(np.asarray(em_a.tpm), np.asarray(em_b.tpm), rtol=1e-6)
        np.testing.assert_array_equal(np.asarray(em_a.count), np.asarray(em_b.count))
        a = np.asarray(em_a.average)
        b = np.asarray(em_b.average)
        np.testing.assert_allclose(np.nan_to_num(a, nan=-1), np.nan_to_num(b, nan=-1), rtol=1e-5)
        for la, lb_ in zip(em_a.lags, em_b.lags):
            np.testing.assert_array_equal(np.asarray(la.signal), np.asarray(lb_.signal))
            np.testing.assert_array_equal(np.asarray(la.trigger), np.asarray(lb_.trigger))

    # rollup consistency vs the unsharded emission
    last_a, last_roll = emissions_a[-1], rollups[-1]
    assert int(last_roll.total_tx) == int(np.sum(np.asarray(last_a.count)))
    avg = np.asarray(last_a.average)[:, 0]
    defined = ~np.isnan(avg)
    if defined.any():
        assert float(last_roll.mean_elapsed) == pytest.approx(float(avg[defined].mean()), rel=1e-5)


def test_rollup_signal_counts():
    cfg = small_cfg(capacity=16)
    params = make_params(cfg)
    mesh = make_mesh(8)
    tick = make_sharded_tick(mesh, cfg)
    ingest = make_sharded_ingest(mesh, cfg)
    state = shard_rows(engine_init(cfg), mesh)
    params_s = shard_rows(params, mesh)
    rng = np.random.RandomState(1)
    roll = None
    for t in range(16):
        em, roll, state = tick(state, jnp.int32(BASE + t + 1), params_s)
        B = 64
        rows = rng.randint(0, 16, B).astype(np.int32)
        base_ms = 200 if t < 12 else 8000  # fleet-wide regression late in the run
        elaps = (base_ms + 20 * rng.rand(B)).astype(np.float32)
        r, l, e, v, _ = route_batch(
            rows, np.full(B, BASE + t + 1, np.int32), elaps, np.ones(B, bool),
            capacity=16, n_shards=8, batch_per_shard=B,
        )
        state = ingest(state, r, l, e, v)
    assert roll is not None
    assert int(roll.total_tx) > 0
    assert roll.signals_high.shape == (2,)


class TestMultihostExchange:
    """The all-to-all ingest exchange: records ingested by ANY host reach
    their owning shard over the device fabric (the pod's DCN/ICI replacement
    for the reference's per-host isolation, SURVEY §5.8)."""

    def test_exchange_equals_direct_ingest(self):
        import numpy as np

        from apmbackend_tpu.parallel import (
            build_send_blocks,
            host_shard_plan,
            make_exchange_ingest,
            make_mesh,
            make_sharded_ingest,
            place_global,
            route_batch,
            shard_rows,
        )
        from apmbackend_tpu.pipeline import make_demo_engine

        n_dev = 8
        capacity = 8 * n_dev
        cfg, state0, params = make_demo_engine(capacity, 8, [(4, 20.0, 0.1)])
        mesh = make_mesh(n_dev)
        plan = host_shard_plan(mesh, capacity)
        assert plan.n_shards == n_dev and plan.n_local == n_dev  # single proc

        rng = np.random.RandomState(4)
        B = 16
        label = 170_000_001
        from apmbackend_tpu.parallel import make_sharded_tick
        tick = make_sharded_tick(mesh, cfg)

        def fresh_state():
            _, s, _ = make_demo_engine(capacity, 8, [(4, 20.0, 0.1)])
            s = shard_rows(s, mesh)
            _em, _roll, s = tick(s, label, params)
            return s

        # three virtual ingesting hosts, disjoint batches
        batches = []
        for h in range(3):
            rows = rng.randint(0, capacity, B).astype(np.int32)
            elaps = rng.randint(50, 500, B).astype(np.float32)
            batches.append((rows, np.full(B, label, np.int32), elaps, np.ones(B, bool)))

        # path A: exchange-ingest, one all_to_all per host batch, each host
        # publishing from a different source slot
        exchange = make_exchange_ingest(mesh, cfg)
        st_a = fresh_state()
        for h, (rows, labels, elaps, valid) in enumerate(batches):
            p = plan._replace(source_slot=plan.local_device_indices[h * 2])
            blocks, dropped = build_send_blocks(
                p, rows, labels, elaps, valid, capacity=capacity, batch_per_shard=B
            )
            assert dropped == 0
            st_a = exchange(st_a, *place_global(mesh, blocks))

        # path B: pre-routed direct sharded ingest of the same batches
        direct = make_sharded_ingest(mesh, cfg)
        st_b = fresh_state()
        for rows, labels, elaps, valid in batches:
            r, l, e, v, dropped = route_batch(
                rows, labels, elaps, valid,
                capacity=capacity, n_shards=n_dev, batch_per_shard=B,
            )
            assert dropped == 0
            st_b = direct(st_b, r, l, e, v)

        assert np.array_equal(np.asarray(st_a.stats.counts), np.asarray(st_b.stats.counts))
        assert np.allclose(np.asarray(st_a.stats.sums), np.asarray(st_b.stats.sums))
        assert np.array_equal(np.asarray(st_a.stats.nsamples), np.asarray(st_b.stats.nsamples))
        # sample multisets per bucket match (arrival order differs by path)
        sa = np.sort(np.nan_to_num(np.asarray(st_a.stats.samples), nan=-1), axis=-1)
        sb = np.sort(np.nan_to_num(np.asarray(st_b.stats.samples), nan=-1), axis=-1)
        assert np.allclose(sa, sb)

    def test_host_shard_plan_single_process(self):
        from apmbackend_tpu.parallel import host_shard_plan, make_mesh
        import pytest as _pytest

        mesh = make_mesh(8)
        plan = host_shard_plan(mesh, 64)
        assert plan.rows_per_shard == 8
        assert plan.source_slot == plan.local_device_indices[0]
        with _pytest.raises(ValueError):
            host_shard_plan(mesh, 63)  # not divisible


def test_sharded_tick_robust_lag_matches_single_chip():
    """A robust (median/MAD) lag through the shard_map tick must equal the
    single-chip step row-for-row (service-axis sharding: each shard owns
    whole rings, so robust stats need no collectives)."""
    import jax.numpy as jnp
    import numpy as np

    from apmbackend_tpu.parallel import make_mesh, make_sharded_tick, shard_rows
    from apmbackend_tpu.pipeline import engine_init, engine_tick, make_demo_engine

    n = 8
    cfg, _, params = make_demo_engine(8 * n, 8, [(4, 2.0, 0.1)])
    cfg = cfg._replace(lags=(cfg.lags[0]._replace(robust=True),))
    state = engine_init(cfg)

    rng = np.random.RandomState(3)
    label = 170_000_001
    # drive a few ticks with data so medians are non-trivial
    import jax

    tick1 = jax.jit(engine_tick, static_argnums=1)
    from apmbackend_tpu.pipeline import engine_ingest

    ingest1 = jax.jit(engine_ingest, static_argnums=1)
    for t in range(10):
        label += 1
        em_single, state = tick1(state, cfg, jnp.int32(label), params)
        B = 256
        rows = rng.randint(0, 8 * n, B).astype(np.int32)
        elaps = (100 + 900 * rng.rand(B)).astype(np.float32)
        state = ingest1(state, cfg, rows, np.full(B, label, np.int32), elaps, np.ones(B, bool))

    # single-chip reference FIRST: the sharded tick donates its (re-placed)
    # state buffers, and on a 1-process CPU mesh re-placement can alias
    em_single, _ = tick1(state, cfg, jnp.int32(label + 1), params)
    mesh = make_mesh(n)
    tick_sh = make_sharded_tick(mesh, cfg)
    em_sh, _rollup, _state_sh = tick_sh(
        shard_rows(state, mesh), jnp.int32(label + 1), shard_rows(params, mesh)
    )
    for field in ("window_avg", "lower_bound", "upper_bound"):
        a = np.asarray(getattr(em_single.lags[0], field))
        b = np.asarray(getattr(em_sh.lags[0], field))
        np.testing.assert_allclose(
            np.nan_to_num(a), np.nan_to_num(b), rtol=1e-6, atol=1e-6, err_msg=field
        )
    np.testing.assert_array_equal(
        np.asarray(em_single.lags[0].signal), np.asarray(em_sh.lags[0].signal)
    )


def test_staged_sharded_step_matches_mono():
    """make_sharded_step (staged pod executor) must match make_sharded_tick
    (single-program shard_map) bitwise — same math, different program
    boundaries — including the rollup collectives and the ring contents."""
    import jax.numpy as jnp

    from apmbackend_tpu.parallel import make_mesh, make_sharded_step, make_sharded_tick, shard_rows
    from apmbackend_tpu.pipeline import engine_init, make_demo_engine

    cfg, _, params = make_demo_engine(32, 8, [(4, 3.0, 0.2), (6, 3.0, 0.2)])
    mesh = make_mesh(8)
    sa = shard_rows(engine_init(cfg), mesh)
    sb = shard_rows(engine_init(cfg), mesh)
    pa = shard_rows(params, mesh)
    staged = make_sharded_step(mesh, cfg)
    mono = make_sharded_tick(mesh, cfg)
    # consecutive labels, a >buffer gap, a stale repeat — the shared host
    # advance loop must clamp identically to the in-program _advance
    labels = [170_000_001, 170_000_002, 170_000_014, 170_000_014, 170_000_015,
              170_000_016, 170_000_017, 170_000_018]
    for lbl in labels:
        ea, ra, sa = staged(sa, lbl, pa)
        eb, rb, sb = mono(sb, jnp.int32(lbl), pa)
        np.testing.assert_array_equal(np.asarray(ea.count), np.asarray(eb.count))
        for la, lb in zip(ea.lags, eb.lags):
            np.testing.assert_array_equal(np.asarray(la.signal), np.asarray(lb.signal))
            np.testing.assert_array_equal(
                np.nan_to_num(np.asarray(la.upper_bound)),
                np.nan_to_num(np.asarray(lb.upper_bound)),
            )
        assert int(ra.total_tx) == int(rb.total_tx)
        np.testing.assert_array_equal(np.asarray(ra.signals_high), np.asarray(rb.signals_high))
    for za, zb in zip(sa.zscores, sb.zscores):
        np.testing.assert_array_equal(
            np.nan_to_num(np.asarray(za.values)), np.nan_to_num(np.asarray(zb.values))
        )
        np.testing.assert_array_equal(np.asarray(za.pos), np.asarray(zb.pos))
    np.testing.assert_array_equal(
        np.nan_to_num(np.asarray(sa.stats.samples), nan=-1),
        np.nan_to_num(np.asarray(sb.stats.samples), nan=-1),
    )


def _warm_sharded(cfg, mesh, ticks=12, seed=3):
    from apmbackend_tpu.parallel import make_sharded_step

    n = mesh.devices.size
    B = 128
    step = make_sharded_step(mesh, cfg)
    ingest = make_sharded_ingest(mesh, cfg)
    state = shard_rows(engine_init(cfg), mesh)
    params = shard_rows(make_params(cfg), mesh)
    rng = np.random.RandomState(seed)
    for t in range(ticks):
        _em, _roll, state = step(state, BASE + t + 1, params)
        rows = rng.randint(0, cfg.capacity, B).astype(np.int32)
        elaps = rng.randint(50, 2000, B).astype(np.float32)
        r, l, e, v, dropped = route_batch(
            rows, np.full(B, BASE + t + 1, np.int32), elaps, np.ones(B, bool),
            capacity=cfg.capacity, n_shards=n, batch_per_shard=B,
        )
        assert dropped == 0
        state = ingest(state, r, l, e, v)
    jax.block_until_ready(state.stats.counts)
    return state, params


def _freeze(st):
    # deep copy preserving each leaf's sharding (donation-safe snapshots)
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(np.asarray(x), x.sharding), st
    )


def _assert_aggs_match(sa, sb, *, exact: bool, rtol=2e-5, atol=1e-4):
    for za, zb in zip(sa.zscores, sb.zscores):
        assert (za.agg is None) == (zb.agg is None)
        if za.agg is None:
            continue
        for name in za.agg._fields:
            x, y = np.asarray(getattr(za.agg, name)), np.asarray(getattr(zb.agg, name))
            if exact or name in ("cnt", "run_len", "last_valid", "last_push"):
                assert np.array_equal(x, y, equal_nan=True), name
            else:
                np.testing.assert_allclose(x, y, rtol=rtol, atol=atol, err_msg=name)


def test_sharded_staggered_rotation_matches_monolithic():
    """A full ShardedRebuildScheduler rotation (jitted producer) must equal
    make_sharded_rebuild's monolithic whole-ring pass bitwise — same per-row
    math, different tick amortization (VERDICT r4 item 2)."""
    from apmbackend_tpu.parallel import ShardedRebuildScheduler, make_sharded_rebuild

    cfg = small_cfg(capacity=64)
    mesh = make_mesh(8)
    state, _params = _warm_sharded(cfg, mesh)
    mono = make_sharded_rebuild(mesh, cfg)(_freeze(state))
    sched = ShardedRebuildScheduler(mesh, cfg, allow_native=False)
    # 64 rows / 8 shards = 8 local rows; chunk=ceil(8/64)=1 -> 8 chunks
    stag = _freeze(state)
    for _ in range(sched.n_chunks):
        stag = sched.step(stag)
    _assert_aggs_match(mono, stag, exact=True)


def test_sharded_staggered_native_matches_jitted():
    """The native per-addressable-shard producer must agree with the jitted
    shard_mapped producer (discrete fields bitwise, moments to tolerance)
    and must SURVIVE the rotation (a mid-step failure silently degrades)."""
    from apmbackend_tpu import native as _native
    from apmbackend_tpu.parallel import ShardedRebuildScheduler

    if not _native.have_native_rebuild():
        pytest.skip("native toolchain unavailable")
    cfg = small_cfg(capacity=64)
    mesh = make_mesh(8)
    state, _params = _warm_sharded(cfg, mesh)
    sj = ShardedRebuildScheduler(mesh, cfg, allow_native=False)
    sn = ShardedRebuildScheduler(mesh, cfg, allow_native=True)
    assert sn._native
    st_j, st_n = _freeze(state), _freeze(state)
    for _ in range(sj.n_chunks):
        st_j, st_n = sj.step(st_j), sn.step(st_n)
    assert sn._native, "native producer was disabled mid-run"
    _assert_aggs_match(st_j, st_n, exact=False)
    # sharding preserved: another sharded step must accept the merged state
    from apmbackend_tpu.parallel import make_sharded_step

    step = make_sharded_step(mesh, cfg)
    _em, _roll, st_n = step(st_n, BASE + 100, shard_rows(make_params(cfg), mesh))


def test_local_rows_contiguous_gate(monkeypatch):
    """The per-addressable-shard native stages assume each host owns one
    contiguous run of the row space. Single-process short-circuits True; the
    multi-host branch is driven here by faking process topology over the
    virtual devices (a process-interleaved mesh must fall back)."""
    from apmbackend_tpu.parallel import sharded as sh

    mesh = make_mesh(8)
    assert sh._local_rows_contiguous(mesh) is True  # single-process

    class _FakeDev:
        def __init__(self, pidx):
            self.process_index = pidx

    def fake_mesh(pidxs):
        class _M:
            devices = np.array([_FakeDev(p) for p in pidxs])

        return _M()

    monkeypatch.setattr(sh.jax, "process_count", lambda: 2)
    monkeypatch.setattr(sh.jax, "process_index", lambda: 0)
    # contiguous halves: proc 0 owns rows of devices 0-3
    assert sh._local_rows_contiguous(fake_mesh([0, 0, 0, 0, 1, 1, 1, 1])) is True
    # interleaved ownership: NOT one contiguous row run -> fused fallback
    assert sh._local_rows_contiguous(fake_mesh([0, 1, 0, 1, 0, 1, 0, 1])) is False
    # this process owns nothing on the mesh: not contiguous either
    assert sh._local_rows_contiguous(fake_mesh([1, 1, 1, 1, 1, 1, 1, 1])) is False
