"""Pallas multi-rank selection kernel vs the sort path (exactness oracle).

The kernel must return BIT-EXACT order statistics — identical to
``jnp.sort`` + ``reference_percentile_sorted`` — for any float input
(duplicates, NaN padding, ragged valid counts, negative/zero values). On CPU
it runs in interpret mode; the same program compiles for TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apmbackend_tpu.ops import stats as dstats
from apmbackend_tpu.ops.pallas_kernels import (
    _f32_to_ukey,
    _ukey_to_f32,
    percentile_rank,
    select_ranks,
    window_percentiles,
)

INTERPRET = jax.default_backend() != "tpu"


def sort_oracle(window, counts, p):
    s = jnp.sort(jnp.asarray(window, jnp.float32), axis=-1)
    return np.asarray(dstats.reference_percentile_sorted(s, jnp.asarray(counts), p))


def make_window(rng, S, N, *, dupes=False, negatives=False):
    """Rows with ragged valid prefixes, NaN tails."""
    counts = rng.randint(0, N + 1, S).astype(np.int32)
    vals = rng.rand(S, N).astype(np.float32) * 1000
    if dupes:
        vals = np.round(vals / 50) * 50  # heavy duplication
    if negatives:
        vals -= 500
    w = np.full((S, N), np.nan, np.float32)
    for i in range(S):
        w[i, : counts[i]] = vals[i, : counts[i]]
    return w, counts


class TestKeyTransform:
    def test_roundtrip_and_order(self):
        vals = np.array(
            [-np.inf, -1e30, -2.5, -1.0, -0.0, 0.0, 1e-30, 1.0, 2.5, 1e30, np.inf],
            np.float32,
        )
        keys = np.asarray(_f32_to_ukey(jnp.asarray(vals)))
        assert (np.diff(keys.astype(np.uint64)) >= 0).all()  # monotone
        back = np.asarray(_ukey_to_f32(jnp.asarray(keys)))
        np.testing.assert_array_equal(back, vals)

    def test_nan_sorts_last(self):
        keys = np.asarray(_f32_to_ukey(jnp.asarray([np.inf, np.nan], np.float32)))
        assert keys[1] > keys[0]


class TestSelectRanks:
    def test_exact_small(self):
        w = jnp.asarray([[3.0, 1.0, 2.0, np.nan], [5.0, 5.0, 5.0, 4.0]], jnp.float32)
        ranks = jnp.asarray([[1, 2], [2, 4]], jnp.int32)
        v1, v2 = select_ranks(w, ranks, block_rows=8, interpret=INTERPRET)
        # row 0: sorted [1,2,3]; rank1=1 (next 2), rank2=2 (next 3)
        assert float(v1[0, 0]) == 1.0 and float(v2[0, 0]) == 2.0
        assert float(v1[0, 1]) == 2.0 and float(v2[0, 1]) == 3.0
        # row 1: sorted [4,5,5,5]; rank2=5, its successor (dupes) is 5
        assert float(v1[1, 0]) == 5.0 and float(v2[1, 0]) == 5.0
        assert float(v1[1, 1]) == 5.0

    @pytest.mark.parametrize("dupes,negatives", [(False, False), (True, False), (True, True)])
    def test_matches_sort(self, dupes, negatives):
        rng = np.random.RandomState(hash((dupes, negatives)) % 2**31)
        S, N = 24, 100
        w, counts = make_window(rng, S, N, dupes=dupes, negatives=negatives)
        ranks = np.stack(
            [np.clip(counts, 1, None), np.maximum(counts // 2, 1)], axis=1
        ).astype(np.int32)
        v1, v2 = select_ranks(
            jnp.pad(jnp.asarray(w), ((0, 0), (0, 28)), constant_values=jnp.nan),
            jnp.asarray(ranks),
            block_rows=8,
            interpret=INTERPRET,
        )
        s = np.sort(w, axis=1)  # NaN to the end
        for i in range(S):
            n = counts[i]
            if n == 0:
                continue
            # rank column 0 = max valid element; its successor is NaN-or-self
            assert float(v1[i, 0]) == s[i, n - 1]
            k2 = ranks[i, 1]
            assert float(v1[i, 1]) == s[i, k2 - 1]
            if k2 < n:
                assert float(v2[i, 1]) == s[i, k2]


class TestWindowPercentiles:
    @pytest.mark.parametrize("S,N", [(8, 64), (24, 100), (40, 300)])
    def test_matches_sort_path(self, S, N):
        rng = np.random.RandomState(S * N)
        w, counts = make_window(rng, S, N, dupes=True)
        p75, p95 = window_percentiles(
            jnp.asarray(w), jnp.asarray(counts), (75, 95), interpret=INTERPRET
        )
        for p, got in ((75, p75), (95, p95)):
            want = sort_oracle(w, counts, p)
            np.testing.assert_array_equal(np.asarray(got), want)

    def test_empty_rows_nan(self):
        w = jnp.full((8, 32), jnp.nan, jnp.float32)
        counts = jnp.zeros(8, jnp.int32)
        p75, p95 = window_percentiles(w, counts, interpret=INTERPRET)
        assert np.all(np.isnan(np.asarray(p75)))
        assert np.all(np.isnan(np.asarray(p95)))

    def test_single_element_rows(self):
        w = jnp.full((8, 32), jnp.nan, jnp.float32)
        w = w.at[:, 0].set(jnp.arange(8, dtype=jnp.float32) + 1)
        counts = jnp.ones(8, jnp.int32)
        p75, p95 = window_percentiles(w, counts, interpret=INTERPRET)
        np.testing.assert_array_equal(np.asarray(p75), np.arange(1, 9, dtype=np.float32))
        np.testing.assert_array_equal(np.asarray(p95), np.arange(1, 9, dtype=np.float32))


class TestPercentileRankParity:
    def test_rank_formula_vs_reference_indices(self):
        # percentile_rank must produce the same element picks as
        # reference_percentile_sorted's index math for every n up to 500
        for p in (75, 95):
            n = jnp.arange(0, 501, dtype=jnp.int32)
            rank, take_pair = percentile_rank(n, p)
            n_np = np.asarray(n)
            rank = np.asarray(rank)
            tp = np.asarray(take_pair)
            for i, nn in enumerate(n_np):
                if nn == 0:
                    continue
                pn = p * nn
                if pn % 100 == 0 or nn == 1:
                    want_idx = max(pn // 100 - 1, 0)
                    assert rank[i] == want_idx + 1
                    assert not tp[i]
                else:
                    idx_ceil = (pn - 1) // 100
                    assert rank[i] == idx_ceil + 1
                    assert tp[i] == (idx_ceil != nn - 1)


class TestStatsTickPallas:
    def test_tick_pallas_matches_sort(self):
        """Full tick parity: percentile_impl='pallas' vs 'sort' on f32.

        Below samplesPerBucket only — every impl is exact there. In the
        overflow regime they differ BY DESIGN: 'sort' importance-weights
        pooled reservoirs by bucket arrival counts, while pallas/topk rank
        over the stored samples unweighted (see ops/stats.py docstring)."""
        rng = np.random.RandomState(0)
        cfg_s = dstats.StatsConfig(
            capacity=16, window_sz=4, buffer_sz=1, samples_per_bucket=32,
            dtype=jnp.float32, percentile_impl="sort",
        )
        cfg_p = cfg_s._replace(percentile_impl="pallas")
        state = dstats.init_state(cfg_s)
        label = 1000
        res_s, state = dstats.tick(state, cfg_s, label)
        B = 64  # ~4 samples per (row, bucket): far under CAP=32
        for t in range(8):
            rows = rng.randint(0, 16, B).astype(np.int32)
            labels = np.full(B, label, np.int32)
            elaps = np.round(rng.rand(B) * 100).astype(np.float32)
            state = dstats.ingest(state, cfg_s, rows, labels, elaps, np.ones(B, bool))
            label += 1
            res_s, state_s = dstats.tick(state, cfg_s, label)
            res_p, state_p = dstats.tick(state, cfg_p, label)
            assert not bool(np.asarray(res_s.overflowed).any()), "test premise: exact regime"
            np.testing.assert_array_equal(np.asarray(res_s.per75), np.asarray(res_p.per75))
            np.testing.assert_array_equal(np.asarray(res_s.per95), np.asarray(res_p.per95))
            state = state_s
