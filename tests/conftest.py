"""Test harness configuration.

Forces JAX onto a virtual 8-device CPU platform BEFORE jax is imported anywhere,
so multi-chip sharding (mesh over the service axis) is exercised without TPU
hardware. The driver's dryrun_multichip uses the same mechanism.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")

import pytest  # noqa: E402


@pytest.fixture
def tmp_logger():
    import logging

    return logging.getLogger("apm.test")
