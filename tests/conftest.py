"""Test harness configuration.

Forces JAX onto a virtual 8-device CPU platform BEFORE jax is imported anywhere,
so multi-chip sharding (mesh over the service axis) is exercised without TPU
hardware. The driver's dryrun_multichip uses the same mechanism.
"""

import os

# Force CPU regardless of the environment's JAX_PLATFORMS (the axon TPU tunnel
# must never be touched by unit tests). NOTE: if the axon sitecustomize is on
# PYTHONPATH it may already have dialed the TPU relay at interpreter start —
# use ./run_tests.sh, which strips PYTHONPATH, as the canonical entry point.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_ENABLE_X64"] = "True"

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: the suite's wall time is dominated by
# per-test compiles (~10 min cold); re-runs hit the cache and skip them
# (measured 2.3 s -> 0.3 s per compile). /tmp scope: survives across suite
# runs within a machine session, never pollutes the repo. The cpu_aot_loader
# "machine feature +prefer-no-{scatter,gather}" stderr lines it can emit are
# XLA tuning pseudo-features, not real ISA bits — same-machine reloads are
# safe.
jax.config.update("jax_compilation_cache_dir", os.environ.get(
    "APM_TEST_JAX_CACHE", "/tmp/apm_jax_test_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.4)

import pytest  # noqa: E402


@pytest.fixture
def tmp_logger():
    import logging

    return logging.getLogger("apm.test")
