"""Test harness configuration.

Forces JAX onto a virtual 8-device CPU platform BEFORE jax is imported anywhere,
so multi-chip sharding (mesh over the service axis) is exercised without TPU
hardware. The driver's dryrun_multichip uses the same mechanism.
"""

import os

# Force CPU regardless of the environment's JAX_PLATFORMS (the axon TPU tunnel
# must never be touched by unit tests). NOTE: if the axon sitecustomize is on
# PYTHONPATH it may already have dialed the TPU relay at interpreter start —
# use ./run_tests.sh, which strips PYTHONPATH, as the canonical entry point.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_ENABLE_X64"] = "True"

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)

# The persistent XLA compilation cache is DISABLED for the suite: setting
# jax_compilation_cache_dir routes XLA:CPU through the cpu_aot_loader
# compile path, which MISCOMPILED buffer donation for fused (single-program
# read+write) steps — reproduced deterministically (round 6): two
# PipelineDrivers stepping the same donated program in one process corrupt
# each other's state leaves (zeros/garbage rings, window stats from freed
# buffers), and np.savez over zero-copy views of the corrupted buffers was
# the long-flaky suite segfault. The corruption appeared on COLD runs too —
# it is the AOT codegen path, not stale cache entries.
#
# RETESTED (round 12, jax 0.4.37): NOT reproducible — the two-driver donated
# fused repro and the fused-tick parity suite are bit-identical oracle vs
# cold-cache vs warm-cache. tests/test_xla_cache_retest.py keeps that repro
# as a standing regression gate for future jax bumps. The cache stays
# opt-in (APM_TEST_JAX_CACHE) regardless: its only upside here is compile
# time, the suite runs one process, and the in-process jit cache already
# deduplicates compiles within a run.
if os.environ.get("APM_TEST_JAX_CACHE"):
    jax.config.update("jax_compilation_cache_dir", os.environ["APM_TEST_JAX_CACHE"])
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.4)

import pytest  # noqa: E402


@pytest.fixture
def tmp_logger():
    import logging

    return logging.getLogger("apm.test")
