"""EWMA/seasonal baselining channels vs a float64 numpy oracle, plus engine
integration (multi-window extension, BASELINE.json configs[4])."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apmbackend_tpu.ops import ewma as de
from apmbackend_tpu.pipeline import (
    PipelineDriver,
    engine_ingest,
    engine_tick,
    make_demo_engine,
)


class OracleEwma:
    """Scalar float64 Holt level/trend/var recursion, one (slot,) baseline.

    trend_beta == 0 is the plain EWMA recursion (trend stays 0, the baseline
    is the level itself)."""

    def __init__(self, alpha, threshold, warmup, season_slots=1, slot_intervals=1,
                 influence=1.0, trend_beta=0.0):
        self.alpha = alpha
        self.threshold = threshold
        self.warmup = warmup
        self.K = season_slots
        self.slot_intervals = slot_intervals
        self.influence = influence
        self.beta = trend_beta
        self.mean = [float("nan")] * season_slots
        self.var = [0.0] * season_slots
        self.count = [0] * season_slots
        self.trend = [0.0] * season_slots

    def step(self, x, label):
        k = (label // self.slot_intervals) % self.K
        mean, var, cnt, trend = self.mean[k], self.var[k], self.count[k], self.trend[k]
        pred = mean + trend
        warm = cnt >= self.warmup
        has_avg = warm and not math.isnan(mean)
        has_std = has_avg and var > 0
        std = math.sqrt(var) if has_std else float("nan")
        lb = pred - self.threshold * std if has_std else float("nan")
        ub = pred + self.threshold * std if has_std else float("nan")
        signal = 0
        if has_std and not math.isnan(x) and abs(x - pred) > self.threshold * std:
            signal = 1 if x > pred else -1
        if not math.isnan(x):
            pushed = self.influence * x + (1 - self.influence) * pred if signal else x
            if math.isnan(mean):
                self.mean[k] = x
                self.trend[k] = 0.0
            else:
                delta = pushed - pred
                incr = self.alpha * delta
                new_level = pred + incr
                self.mean[k] = new_level
                self.trend[k] = self.beta * (new_level - mean) + (1 - self.beta) * trend
                self.var[k] = (1 - self.alpha) * (var + delta * incr)
            self.count[k] = cnt + 1
        return {"avg": pred if has_avg else float("nan"), "lb": lb, "ub": ub, "signal": signal}


def same(a, b):
    if isinstance(a, float) and math.isnan(a):
        return isinstance(b, float) and math.isnan(b)
    return a == pytest.approx(b, rel=1e-9, abs=1e-9)


def drive(spec, series, labels):
    """series: [T] floats fed to rows 0 (the other rows get NaN)."""
    S = 4
    state = de.init_state(S, spec, jnp.float64)
    step = jax.jit(de.step, static_argnums=1)
    out = []
    for x, label in zip(series, labels):
        nv = np.full((S, 3), np.nan)
        nv[0] = (x, x + 1, x + 2)  # 3 parallel series per row
        res, state = step(state, spec, jnp.asarray(nv), jnp.int32(label))
        out.append(res)
    return out


@pytest.mark.parametrize("alpha", [1.0, 0.3, 0.05])
def test_plain_ewma_matches_oracle(alpha):
    rng = np.random.RandomState(7)
    series = list(200 + 40 * rng.rand(120))
    series[50] = 900.0  # spike
    series[80] = float("nan")  # missing tick
    labels = list(range(1000, 1000 + len(series)))
    spec = de.EwmaSpec(alpha=alpha, threshold=3.0, warmup=10)
    oracle = OracleEwma(alpha, 3.0, 10)
    results = drive(spec, series, labels)
    for t, (x, label) in enumerate(zip(series, labels)):
        g = oracle.step(x, label)
        d = results[t]
        assert same(g["avg"], float(d.window_avg[0, 0])), f"t={t} avg"
        assert same(g["lb"], float(d.lower_bound[0, 0])), f"t={t} lb"
        assert same(g["ub"], float(d.upper_bound[0, 0])), f"t={t} ub"
        assert g["signal"] == int(d.signal[0, 0]), f"t={t} signal"


def test_influence_damping_sustains_signals():
    """With influence < 1 a sustained regression keeps signalling (the anomaly
    can't inflate its own baseline); matches the oracle exactly."""
    rng = np.random.RandomState(11)
    series = list(250 + 2 * rng.rand(40)) + [3000.0] * 10
    labels = list(range(len(series)))
    spec = de.EwmaSpec(alpha=0.3, threshold=3.0, warmup=5, influence=0.1)
    oracle = OracleEwma(0.3, 3.0, 5, influence=0.1)
    results = drive(spec, series, labels)
    signals = []
    for t, (x, label) in enumerate(zip(series, labels)):
        g = oracle.step(x, label)
        d = results[t]
        assert same(g["avg"], float(d.window_avg[0, 0])), f"t={t} avg"
        assert g["signal"] == int(d.signal[0, 0]), f"t={t} signal"
        signals.append(g["signal"])
    assert all(s == 1 for s in signals[-10:])  # every regressed tick signals


def test_warmup_gates_signals():
    spec = de.EwmaSpec(alpha=0.5, threshold=1.0, warmup=50)
    series = [100.0, 200.0, 100.0, 200.0] * 10  # wild swings but cold
    results = drive(spec, series, range(len(series)))
    for d in results:
        assert int(d.signal[0, 0]) == 0
        assert math.isnan(float(d.window_avg[0, 0]))


def test_zero_variance_no_signal():
    spec = de.EwmaSpec(alpha=0.5, threshold=1.0, warmup=2)
    # constant series keeps var == 0 -> std undefined -> never signals,
    # matching the z-score channel's zero-variance quirk
    series = [100.0] * 10 + [500.0]
    results = drive(spec, series, range(len(series)))
    assert int(results[-1].signal[0, 0]) == 0
    assert math.isnan(float(results[-1].upper_bound[0, 0]))


def test_nan_input_freezes_state():
    spec = de.EwmaSpec(alpha=0.5, threshold=3.0, warmup=1)
    S = 2
    state = de.init_state(S, spec, jnp.float64)
    nv = np.full((S, 3), 100.0)
    _, state1 = de.step(state, spec, jnp.asarray(nv), jnp.int32(0))
    nan_nv = np.full((S, 3), np.nan)
    _, state2 = de.step(state1, spec, jnp.asarray(nan_nv), jnp.int32(1))
    np.testing.assert_array_equal(np.asarray(state1.mean), np.asarray(state2.mean))
    np.testing.assert_array_equal(np.asarray(state1.count), np.asarray(state2.count))


def test_seasonal_slots_are_independent():
    # 2 slots alternating: even labels see ~100, odd labels see ~500; a 500 on
    # an even label must signal against slot-0's baseline
    spec = de.EwmaSpec(alpha=0.3, threshold=3.0, warmup=3, season_slots=2, slot_intervals=1)
    oracle = OracleEwma(0.3, 3.0, 3, season_slots=2, slot_intervals=1)
    rng = np.random.RandomState(3)
    series, labels = [], []
    for t in range(60):
        base = 100.0 if t % 2 == 0 else 500.0
        series.append(base + rng.rand() * 5)
        labels.append(t)
    series.append(500.0)  # anomaly: slot-1 value arriving on slot 0
    labels.append(60)
    results = drive(spec, series, labels)
    for t, (x, label) in enumerate(zip(series, labels)):
        g = oracle.step(x, label)
        assert g["signal"] == int(results[t].signal[0, 0]), f"t={t}"
    assert int(results[-1].signal[0, 0]) == 1  # flagged vs slot-0 baseline


@pytest.mark.parametrize("beta", [0.1, 0.3])
def test_holt_trend_matches_oracle(beta):
    """trend_beta > 0: device recursion == the scalar Holt oracle, including
    signals, bounds, influence damping and NaN gaps."""
    rng = np.random.RandomState(13)
    series = list(200 + 3.0 * np.arange(100) + 10 * rng.rand(100))  # ramp
    series[60] = 1500.0  # spike far above the ramp
    series[70] = float("nan")
    labels = list(range(2000, 2000 + len(series)))
    spec = de.EwmaSpec(alpha=0.3, threshold=3.0, warmup=10, influence=0.2, trend_beta=beta)
    oracle = OracleEwma(0.3, 3.0, 10, influence=0.2, trend_beta=beta)
    results = drive(spec, series, labels)
    for t, (x, label) in enumerate(zip(series, labels)):
        g = oracle.step(x, label)
        d = results[t]
        assert same(g["avg"], float(d.window_avg[0, 0])), f"t={t} avg"
        assert same(g["lb"], float(d.lower_bound[0, 0])), f"t={t} lb"
        assert same(g["ub"], float(d.upper_bound[0, 0])), f"t={t} ub"
        assert g["signal"] == int(d.signal[0, 0]), f"t={t} signal"


def test_trend_beta_zero_is_plain_ewma():
    """trend_beta=0 must be bit-for-bit the plain EWMA channel (same jitted
    math, trend identically zero)."""
    rng = np.random.RandomState(5)
    series = list(300 + 50 * rng.rand(80))
    series[40] = 2000.0
    labels = list(range(len(series)))
    plain = drive(de.EwmaSpec(alpha=0.2, threshold=3.0, warmup=5), series, labels)
    holt0 = drive(de.EwmaSpec(alpha=0.2, threshold=3.0, warmup=5, trend_beta=0.0), series, labels)
    for t in range(len(series)):
        np.testing.assert_array_equal(
            np.asarray(plain[t].window_avg), np.asarray(holt0[t].window_avg)
        )
        np.testing.assert_array_equal(
            np.asarray(plain[t].signal), np.asarray(holt0[t].signal)
        )
        np.testing.assert_array_equal(
            np.asarray(plain[t].upper_bound), np.asarray(holt0[t].upper_bound)
        )


def test_holt_detects_step_that_ramp_inflated_ewma_masks():
    """The motivating scenario: a service whose latency is legitimately
    ramping. The flat EWMA's variance recursion absorbs the systematic
    on-ramp residual (steady-state std ~ the lag slope*(1-a)/a, far above the
    noise floor), so its bounds balloon and a real step change hides inside
    them. The Holt channel learns the slope: its residuals stay at the noise
    floor, bounds stay tight, and the same step is flagged immediately."""
    rng = np.random.RandomState(23)
    T = 150
    ramp = 200 + 8.0 * np.arange(T) + 2.0 * rng.rand(T)  # sustained clean ramp
    step_jump = 100.0  # genuine regression, small vs the inflated bounds
    series = list(ramp) + [float(200 + 8.0 * T + step_jump)]
    labels = list(range(len(series)))
    plain_res = drive(de.EwmaSpec(alpha=0.1, threshold=3.0, warmup=10), series, labels)
    holt_res = drive(
        de.EwmaSpec(alpha=0.1, threshold=3.0, warmup=10, trend_beta=0.2), series, labels
    )
    # steady ramp (past onset transient): Holt stays quiet with tight bounds;
    # the flat EWMA is quiet only because its band inflated ~50x wider
    steady = slice(80, T)
    assert all(int(r.signal[0, 0]) == 0 for r in holt_res[steady])
    holt_half_band = np.nanmedian(
        [float(r.upper_bound[0, 0] - r.window_avg[0, 0]) for r in holt_res[steady]]
    )
    plain_half_band = np.nanmedian(
        [float(r.upper_bound[0, 0] - r.window_avg[0, 0]) for r in plain_res[steady]]
    )
    assert holt_half_band < 20.0, f"Holt band should sit at the noise floor, got {holt_half_band}"
    assert plain_half_band > 100.0, f"flat EWMA band should inflate, got {plain_half_band}"
    # the step: masked by the inflated flat-EWMA band, caught by Holt
    assert int(plain_res[-1].signal[0, 0]) == 0, "flat EWMA masks the step"
    assert int(holt_res[-1].signal[0, 0]) == 1, "Holt flags the step"


def test_holt_channel_config_and_resume(tmp_path):
    """TREND_BETA flows from config; trend state survives the resume file."""
    from apmbackend_tpu.config import default_config
    from apmbackend_tpu.entries import TxEntry

    cfg_tree = default_config()
    cfg_tree["tpuEngine"]["serviceCapacity"] = 8
    cfg_tree["tpuEngine"]["samplesPerBucket"] = 8
    cfg_tree["tpuEngine"]["ewmaChannels"] = [
        {"ALPHA": 0.5, "THRESHOLD": 3.0, "WARMUP": 2, "CHANNEL_ID": -2,
         "TREND_BETA": 0.3}
    ]
    cfg_tree["streamCalcZScore"]["defaults"] = [{"LAG": 4, "THRESHOLD": 20, "INFLUENCE": 0}]
    d1 = PipelineDriver(cfg_tree, capacity=8)
    assert d1.cfg.ewma[0].trend_beta == 0.3
    ts = 170_000_000_0000
    for t in range(10):
        d1.feed(TxEntry("s1", "svcA", f"L{t}", "A", ts - 100, float(ts), 100.0 + 20 * t, "Y"))
        ts += 10_000
    path = str(tmp_path / "resume.npz")
    d1.save_resume(path)
    assert float(np.abs(np.asarray(d1.state.ewmas[0].trend)).sum()) > 0  # trend moved
    d2 = PipelineDriver(cfg_tree, capacity=8)
    assert d2.load_resume(path)
    np.testing.assert_array_equal(
        np.asarray(d1.state.ewmas[0].trend), np.asarray(d2.state.ewmas[0].trend)
    )


def test_trend_beta_validation():
    with pytest.raises(ValueError, match="TREND_BETA"):
        de.specs_from_config({"ewmaChannels": [
            {"ALPHA": 0.5, "THRESHOLD": 3.0, "CHANNEL_ID": -1, "TREND_BETA": 1.0}
        ]})


def test_engine_integration_ewma_channel_alerts():
    """End-to-end: engine with an EWMA channel raises a device-side trigger."""
    chan = {"ALPHA": 0.3, "THRESHOLD": 2.0, "WARMUP": 3, "CHANNEL_ID": -1}
    cfg, state, params = make_demo_engine(
        8, 16, [(4, 20.0, 0.1)], ewma_channels=[chan]
    )
    # loosen the alert window so a single bad interval triggers
    rule = cfg.ewma_rules[0]._replace(window_sz=1, required_bad=1)
    cfg = cfg._replace(ewma_rules=(rule,))
    tick = jax.jit(engine_tick, static_argnums=1)
    ingest = jax.jit(engine_ingest, static_argnums=1)

    label = 17_000_000
    rng = np.random.RandomState(0)
    em = None
    for t in range(40):
        label += 1
        em, state = tick(state, cfg, jnp.int32(label), params)
        B = 64
        # steady ~250 ms, then a 10x regression in the last ticks
        ms = 250.0 if t < 30 else 2500.0
        rows = np.zeros(B, np.int32)
        labels = np.full(B, label, np.int32)
        elaps = (ms + 5 * rng.rand(B)).astype(np.float64)
        state = ingest(state, cfg, rows, labels, elaps, np.ones(B, bool))
    assert len(em.ewma) == 1
    assert bool(em.ewma[0].trigger[0])
    assert int(em.ewma[0].signal[0, 0]) == 1


def test_driver_resume_roundtrip_with_ewma(tmp_path):
    from apmbackend_tpu.config import default_config

    cfg_tree = default_config()
    cfg_tree["tpuEngine"]["serviceCapacity"] = 8
    cfg_tree["tpuEngine"]["samplesPerBucket"] = 8
    cfg_tree["tpuEngine"]["ewmaChannels"] = [
        {"ALPHA": 0.5, "THRESHOLD": 3.0, "WARMUP": 2, "SEASON_SLOTS": 4,
         "SLOT_INTERVALS": 2, "CHANNEL_ID": -4}
    ]
    cfg_tree["streamCalcZScore"]["defaults"] = [{"LAG": 4, "THRESHOLD": 20, "INFLUENCE": 0}]
    from apmbackend_tpu.entries import TxEntry

    d1 = PipelineDriver(cfg_tree, capacity=8)
    ts = 170_000_000_0000
    for t in range(12):
        for k in range(3):
            tx = TxEntry("s1", f"svc{k}", f"L{t}-{k}", "A", ts - 150, float(ts), 150.0, "Y")
            d1.feed(tx)
        ts += 10_000
    path = str(tmp_path / "resume.npz")
    d1.save_resume(path)

    d2 = PipelineDriver(cfg_tree, capacity=8)
    assert d2.load_resume(path)
    np.testing.assert_array_equal(
        np.asarray(d1.state.ewmas[0].count), np.asarray(d2.state.ewmas[0].count)
    )
    np.testing.assert_allclose(
        np.asarray(d1.state.ewmas[0].mean), np.asarray(d2.state.ewmas[0].mean)
    )
    assert np.asarray(d2.state.ewmas[0].count).sum() > 0  # state actually moved


def test_sharded_tick_with_ewma_channels():
    """EWMA channels ride the shard_map step (state specs cover them)."""
    from apmbackend_tpu.parallel import make_mesh, make_sharded_tick, shard_rows

    n = 8
    chan = {"ALPHA": 0.5, "THRESHOLD": 3.0, "WARMUP": 1, "CHANNEL_ID": -1}
    cfg, state, params = make_demo_engine(8 * n, 8, [(4, 20.0, 0.1)], ewma_channels=[chan])
    mesh = make_mesh(n)
    tick = make_sharded_tick(mesh, cfg)
    state = shard_rows(state, mesh)
    params = shard_rows(params, mesh)
    em, rollup, state = tick(state, jnp.int32(17_000_001), params)
    assert len(em.ewma) == 1
    assert em.ewma[0].signal.shape == (8 * n, 3)


def test_nan_var_recovers_on_seed():
    """Rows grown past a resume snapshot (var padded NaN) must become live
    again once a value seeds them — NaN var must not poison the recursion."""
    spec = de.EwmaSpec(alpha=0.5, threshold=1.0, warmup=2)
    state = de.EwmaState(
        mean=jnp.full((1, 3, 1), jnp.nan, jnp.float64),
        var=jnp.full((1, 3, 1), jnp.nan, jnp.float64),  # poisoned pad
        count=jnp.zeros((1, 1), jnp.int32),
        trend=jnp.full((1, 3, 1), jnp.nan, jnp.float64),  # poisoned pad
    )
    vals = [100.0, 110.0, 90.0, 105.0, 500.0]
    res = None
    for t, v in enumerate(vals):
        nv = np.full((1, 3), v)
        res, state = de.step(state, spec, jnp.asarray(nv), jnp.int32(t))
    assert not math.isnan(float(state.var[0, 0, 0]))
    assert int(res.signal[0, 0]) == 1  # the spike is detected


def test_per_service_channel_overrides():
    """tpuEngine.ewmaChannelOverrides: one service gets a tighter THRESHOLD
    on one channel; the same deviation signals only for that service, and
    the override flows through hot reload (apply_config)."""
    from apmbackend_tpu.config import default_config
    from apmbackend_tpu.entries import TxEntry

    cfg_tree = default_config()
    cfg_tree["tpuEngine"]["serviceCapacity"] = 8
    cfg_tree["tpuEngine"]["samplesPerBucket"] = 16
    cfg_tree["tpuEngine"]["ewmaChannels"] = [
        {"ALPHA": 0.3, "THRESHOLD": 50.0, "WARMUP": 3, "CHANNEL_ID": -1}
    ]
    cfg_tree["tpuEngine"]["ewmaChannelOverrides"] = {
        "services": {"svcTight": {"-1": {"THRESHOLD": 2.0, "INFLUENCE": 0.5}}}
    }
    cfg_tree["streamCalcZScore"]["defaults"] = [{"LAG": 4, "THRESHOLD": 99, "INFLUENCE": 0}]

    sigs = {}
    d = PipelineDriver(
        cfg_tree, capacity=8,
        on_fullstat=lambda fs: sigs.setdefault(
            (fs.service, fs.lag), []
        ).append(fs.average_signal),
    )
    rng = np.random.RandomState(2)
    ts = 170_000_000_0000
    # identical traffic for both services: steady ~200ms, then a ~4 sigma bump
    for t in range(40):
        ms = 200.0 + rng.rand() * 4 if t < 34 else 230.0
        for svc in ("svcTight", "svcLoose"):
            d.feed(TxEntry("s1", svc, f"L{t}-{svc}", "A", ts - ms, float(ts), ms, "Y"))
        ts += 10_000
    d.flush()
    tight = sigs[("svcTight", -1)]
    loose = sigs[("svcLoose", -1)]
    assert any(s == 1 for s in tight), "tight override must flag the bump"
    assert all(s == 0 for s in loose), "default THRESHOLD=50 must stay quiet"

    # hot reload: drop the override -> svcTight goes quiet for a fresh bump
    import copy

    new_tree = copy.deepcopy(cfg_tree)
    new_tree["tpuEngine"]["ewmaChannelOverrides"] = {"services": {}}
    d.apply_config(new_tree)
    sigs.clear()
    for t in range(6):
        for svc in ("svcTight", "svcLoose"):
            d.feed(TxEntry("s1", svc, f"R{t}-{svc}", "A", ts - 230, float(ts), 235.0, "Y"))
        ts += 10_000
    d.flush()
    assert all(s == 0 for s in sigs.get(("svcTight", -1), [])), "override removed on reload"


def test_registry_ewma_params_defaults_and_overrides():
    from apmbackend_tpu.ops.registry import ServiceRegistry

    reg = ServiceRegistry(4)
    reg.lookup_or_add("s", "a")
    reg.lookup_or_add("s", "b")
    spec = de.EwmaSpec(alpha=0.1, threshold=3.0, warmup=1, channel_id=-7, influence=0.9)
    eng = {"ewmaChannelOverrides": {"services": {"b": {"-7": {"THRESHOLD": 1.5}}}}}
    out = reg.ewma_params(eng, [spec], dtype=np.float64)
    np.testing.assert_array_equal(out[-7]["threshold"], [3.0, 1.5, 3.0, 3.0])
    np.testing.assert_array_equal(out[-7]["influence"], [0.9, 0.9, 0.9, 0.9])


def test_registry_ewma_params_null_and_falsy_semantics():
    """Null-guard and truthiness parity with the z-score override helper:
    a nulled overrides key must not crash, and a 0-valued THRESHOLD is a
    no-op (stream_calc_z_score.js:106-132 semantics), never a
    signal-on-everything threshold."""
    from apmbackend_tpu.ops.registry import ServiceRegistry

    reg = ServiceRegistry(2)
    reg.lookup_or_add("s", "a")
    spec = de.EwmaSpec(alpha=0.1, threshold=3.0, warmup=1, channel_id=-1)
    # JSON config that nulls the key to disable overrides
    out = reg.ewma_params({"ewmaChannelOverrides": None}, [spec])
    np.testing.assert_array_equal(out[-1]["threshold"], [3.0, 3.0])
    out = reg.ewma_params({"ewmaChannelOverrides": {"services": None}}, [spec])
    np.testing.assert_array_equal(out[-1]["threshold"], [3.0, 3.0])
    # falsy override values are skipped, like service_zscore_settings
    eng = {"ewmaChannelOverrides": {"services": {"a": {"-1": {"THRESHOLD": 0, "INFLUENCE": 0.5}}}}}
    out = reg.ewma_params(eng, [spec])
    np.testing.assert_array_equal(out[-1]["threshold"], [3.0, 3.0])
    np.testing.assert_array_equal(out[-1]["influence"], [0.5, 1.0])
