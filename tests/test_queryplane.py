"""Fleet query plane tests (ISSUE 20): owner-map routing, scatter-gather
merge math (sum-then-quantile histograms, reset-aware cross-shard rates),
the durable degraded read path with partial/stale marking, the TTL
coalescing cache, retry-on-move rebalance consistency, format=matrix,
and the qstat rendering of per-shard freshness."""

import json
import math
import socket
import threading
import time

import pytest

from apmbackend_tpu.obs.exporter import TelemetryServer
from apmbackend_tpu.obs.queryplane import (
    QueryPlane,
    _TTLCache,
    _merge_histogram,
    _merge_series,
)
from apmbackend_tpu.obs.registry import MetricsRegistry, histogram_quantile
from apmbackend_tpu.obs.store import (
    TimeSeriesStore,
    eval_range,
    make_query_route,
    matrix_doc,
)
from apmbackend_tpu.parallel.fleet import (
    OwnerMap,
    owner_map_from_fleet_text,
    service_partition,
)

T0 = 1_000_000.0


# -- fixtures ----------------------------------------------------------------

def mem_store(tmp_path, name, rows_by_t):
    st = TimeSeriesStore(str(tmp_path / name))
    for t, rows in rows_by_t:
        st.append_samples(rows, ts=t)
    return st


def shard_server(store=None, spans=(), decisions=(), attrib=None):
    """A minimal live shard endpoint: /query over ``store`` plus static
    /trace /decisions /attrib bodies — the per-module exporter contract
    the plane scatters to."""
    srv = TelemetryServer(registry=MetricsRegistry(), port=0)
    if store is not None:
        srv.add_route("/query", make_query_route(lambda: store))
    srv.add_route("/trace", lambda q: (
        200, "application/json", json.dumps({"spans": list(spans)})))
    srv.add_route("/decisions", lambda q: (
        200, "application/json", json.dumps({"decisions": list(decisions)})))
    if attrib is not None:
        srv.add_route("/attrib", lambda q: (
            200, "application/json", json.dumps(attrib)))
    port = srv.start()
    return srv, f"http://127.0.0.1:{port}"


def dead_url():
    """A URL nothing listens on (bound then released port)."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return f"http://127.0.0.1:{port}"


def call(plane, path, **params):
    """Invoke a plane route directly (the exporter normalizes parse_qs
    lists the same way); returns (status, parsed body)."""
    status, _ctype, body = plane.make_routes()[path](
        {k: [str(v)] for k, v in params.items()})
    try:
        return status, json.loads(body)
    except json.JSONDecodeError:
        return status, body


# -- owner map ---------------------------------------------------------------

def test_owner_map_seq_bumps_only_on_change():
    om = OwnerMap({0: "shard0", 1: "shard1"})
    seq0, owners = om.read()
    assert owners == {0: "shard0", 1: "shard1"}
    assert om.update({0: "shard0", 1: "shard1"}) == seq0  # steady rescrape
    assert om.move(0, "shard0") == seq0  # no-op move
    seq1 = om.move(0, "shard1")
    assert seq1 == seq0 + 1
    seq2, owners2 = om.read()
    assert seq2 == seq1 and owners2[0] == "shard1"
    owners2[0] = "mutated"  # read returns a copy
    assert om.read()[1][0] == "shard1"


def test_owner_map_from_fleet_text():
    text = (
        "# HELP apm_fleet_partition_owner x\n"
        'apm_fleet_partition_owner{partition="3"} 1\n'
        'apm_fleet_partition_owner{module="manager",partition="7"} 0\n'
        "apm_other_metric 4\n"
    )
    assert owner_map_from_fleet_text(text) == {3: 1, 7: 0}
    assert owner_map_from_fleet_text("") == {}


# -- routing -----------------------------------------------------------------

def test_single_service_routes_to_owning_shard_only(tmp_path):
    parts = 8
    svc = "svc42"
    p = service_partition(svc, parts)
    sa = mem_store(tmp_path, "a", [
        (T0 + i, [("apm_tx_total", {"service": svc}, 3.0 * i)])
        for i in range(6)])
    sb = mem_store(tmp_path, "b", [
        (T0 + i, [("apm_tx_total", {"service": "other"}, 7.0 * i)])
        for i in range(6)])
    srv_a, url_a = shard_server(sa)
    srv_b, url_b = shard_server(sb)
    try:
        om = OwnerMap({p: "shard0"})
        plane = QueryPlane(
            lambda: [("shard0", url_a), ("shard1", url_b)],
            owners=om.read, partitions=parts)
        st, doc = call(
            plane, "/query",
            series=f'rate(apm_tx_total{{service="{svc}"}}[4s])',
            start=T0, end=T0 + 5, step=1)
        assert st == 200
        assert doc["shards_queried"] == ["shard0"]
        assert list(doc["shards"]) == ["shard0"]
        assert len(doc["series"]) == 1
        assert doc["series"][0]["labels"] == {"service": svc}
        # explicit ?service= routes the same without a selector label
        st, doc2 = call(plane, "/query", series="rate(apm_tx_total[4s])",
                        service=svc, start=T0, end=T0 + 5, step=1)
        assert st == 200 and doc2["shards_queried"] == ["shard0"]
        # unknown owner (partition not in the map) falls back to scatter
        st, doc3 = call(plane, "/query", series="rate(apm_tx_total[4s])",
                        service="unmapped-svc", start=T0, end=T0 + 5, step=1)
        assert st == 200 and set(doc3["shards_queried"]) == {"shard0", "shard1"}
    finally:
        srv_a.stop()
        srv_b.stop()


def test_scatter_merge_bit_equal_to_single_store_golden(tmp_path):
    rows = lambda svc, k: [
        (T0 + i, [("apm_tx_total", {"service": svc}, k * i)])
        for i in range(8)]
    sa = mem_store(tmp_path, "a", rows("alpha", 10.0))
    sb = mem_store(tmp_path, "b", rows("beta", 5.0))
    golden = mem_store(tmp_path, "g", [
        (T0 + i, [("apm_tx_total", {"service": "alpha"}, 10.0 * i),
                  ("apm_tx_total", {"service": "beta"}, 5.0 * i)])
        for i in range(8)])
    srv_a, url_a = shard_server(sa)
    srv_b, url_b = shard_server(sb)
    try:
        plane = QueryPlane(lambda: [("shard0", url_a), ("shard1", url_b)])
        for expr in ("apm_tx_total", "rate(apm_tx_total[4s])",
                     "increase(apm_tx_total[4s])"):
            st, doc = call(plane, "/query", series=expr,
                           start=T0, end=T0 + 7, step=1)
            gdoc = eval_range(golden, expr, T0, T0 + 7, 1)
            assert st == 200
            assert doc["series"] == gdoc["series"], expr
            assert doc["partial"] is False and doc["stale"] is False
    finally:
        srv_a.stop()
        srv_b.stop()


# -- merge math --------------------------------------------------------------

def _bucket_rows(counts_by_le, t, extra=None):
    rows = []
    for le, v in counts_by_le.items():
        rows.append(("apm_lat_seconds_bucket",
                     dict({"le": le}, **(extra or {})), v))
    return [(t, rows)]


def test_histogram_bucket_merge_beats_per_shard_quantile_average(tmp_path):
    # skewed placement: shard A holds 100 sub-0.1s observations, shard B
    # 100 observations in (1, 10]. The true fleet p50 sits in the 0.1
    # bucket; averaging the two per-shard p50s lands near 2.8 — the
    # failure mode sum-then-quantile exists to prevent.
    a0 = {"0.1": 0.0, "1": 0.0, "10": 0.0, "+Inf": 0.0}
    a1 = {"0.1": 100.0, "1": 100.0, "10": 100.0, "+Inf": 100.0}
    b1 = {"0.1": 0.0, "1": 0.0, "10": 100.0, "+Inf": 100.0}
    sa = mem_store(tmp_path, "a",
                   _bucket_rows(a0, T0) + _bucket_rows(a1, T0 + 10))
    sb = mem_store(tmp_path, "b",
                   _bucket_rows(a0, T0) + _bucket_rows(b1, T0 + 10))
    merged1 = {le: a1[le] + b1[le] for le in a1}
    golden = mem_store(tmp_path, "g",
                       _bucket_rows(a0, T0) + _bucket_rows(merged1, T0 + 10))
    srv_a, url_a = shard_server(sa)
    srv_b, url_b = shard_server(sb)
    try:
        plane = QueryPlane(lambda: [("shard0", url_a), ("shard1", url_b)])
        expr = "histogram_quantile(0.5, apm_lat_seconds[20s])"
        st, doc = call(plane, "/query", series=expr,
                       start=T0 + 10, end=T0 + 10, step=1)
        gdoc = eval_range(golden, expr, T0 + 10, T0 + 10, 1)
        assert st == 200
        assert doc["series"] == gdoc["series"]
        fleet_p50 = doc["series"][0]["points"][0][1]
        assert fleet_p50 == pytest.approx(0.1)
        # the wrong math: per-shard quantiles averaged
        pa = eval_range(sa, expr, T0 + 10, T0 + 10, 1)["series"][0]["points"][0][1]
        pb = eval_range(sb, expr, T0 + 10, T0 + 10, 1)["series"][0]["points"][0][1]
        averaged = (pa + pb) / 2.0
        assert averaged != fleet_p50
        # merged equals the single-store truth exactly; averaging misses
        # it by more than an order of magnitude on this fixture
        assert abs(averaged - fleet_p50) > 1.0
    finally:
        srv_a.stop()
        srv_b.stop()


def test_counter_reset_aware_rate_merge_across_shards(tmp_path):
    # shard A's counter resets mid-window (process restart); shard B is
    # monotone. Each shard's rate must be computed reset-aware BEFORE the
    # cross-shard sum — the PR 12 review-fix shape, now cross-shard: a
    # naive merged delta would go negative across A's reset.
    sa = mem_store(tmp_path, "a", [
        (T0 + 0, [("apm_tx_total", {"service": "s"}, 100.0)]),
        (T0 + 2, [("apm_tx_total", {"service": "s"}, 120.0)]),
        (T0 + 4, [("apm_tx_total", {"service": "s"}, 5.0)]),   # reset
        (T0 + 6, [("apm_tx_total", {"service": "s"}, 25.0)]),
    ])
    sb = mem_store(tmp_path, "b", [
        (T0 + 0, [("apm_tx_total", {"service": "s"}, 0.0)]),
        (T0 + 2, [("apm_tx_total", {"service": "s"}, 10.0)]),
        (T0 + 4, [("apm_tx_total", {"service": "s"}, 20.0)]),
        (T0 + 6, [("apm_tx_total", {"service": "s"}, 30.0)]),
    ])
    srv_a, url_a = shard_server(sa)
    srv_b, url_b = shard_server(sb)
    try:
        plane = QueryPlane(lambda: [("shard0", url_a), ("shard1", url_b)])
        expr = "rate(apm_tx_total[6s])"
        st, doc = call(plane, "/query", series=expr,
                       start=T0 + 6, end=T0 + 6, step=1)
        assert st == 200
        merged = doc["series"][0]["points"][0][1]
        ra = eval_range(sa, expr, T0 + 6, T0 + 6, 1)["series"][0]["points"][0][1]
        rb = eval_range(sb, expr, T0 + 6, T0 + 6, 1)["series"][0]["points"][0][1]
        assert merged == pytest.approx(ra + rb)
        # reset-awareness: A's window increase is 20+25 over 4s observed
        # span, never negative; a naive delta would have been 25-120 < 0
        assert ra > 0 and merged > rb
    finally:
        srv_a.stop()
        srv_b.stop()


def test_merge_series_none_is_absent_not_zero():
    docs = [
        {"series": [{"labels": {"q": "x"},
                     "points": [[0, 1.0], [1, None], [2, None]]}]},
        {"series": [{"labels": {"q": "x"},
                     "points": [[0, 2.0], [1, 4.0], [2, None]]}]},
    ]
    out = _merge_series(docs)
    assert out[0]["points"] == [[0, 3.0], [1, 4.0], [2, None]]


def test_merge_histogram_groups_minus_le():
    docs = [{"series": [
        {"labels": {"le": "0.1"}, "points": [[0, 50.0]]},
        {"labels": {"le": "+Inf"}, "points": [[0, 100.0]]},
    ]}, {"series": [
        {"labels": {"le": "0.1"}, "points": [[0, 0.0]]},
        {"labels": {"le": "+Inf"}, "points": [[0, 100.0]]},
    ]}]
    out = _merge_histogram(docs, 0.5)
    assert len(out) == 1 and out[0]["labels"] == {}
    expect = histogram_quantile([(0.1, 50.0), (math.inf, 200.0)], 0.5)
    assert out[0]["points"][0][1] == pytest.approx(expect)


# -- degraded read path ------------------------------------------------------

def test_dead_shard_served_from_store_partial_stale(tmp_path):
    sa = mem_store(tmp_path, "a", [
        (T0 + i, [("apm_tx_total", {"service": "alpha"}, 10.0 * i)])
        for i in range(8)])
    srv_a, url_a = shard_server(sa)
    # the durable recorder store holds the dead shard's slice, module-labeled
    durable = mem_store(tmp_path, "rec", [
        (T0 + i, [("apm_tx_total",
                   {"service": "beta", "module": "shard1"}, 5.0 * i)])
        for i in range(8)])
    golden = mem_store(tmp_path, "g", [
        (T0 + i, [("apm_tx_total", {"service": "alpha"}, 10.0 * i),
                  ("apm_tx_total", {"service": "beta"}, 5.0 * i)])
        for i in range(8)])
    last_ok = T0 + 7
    try:
        plane = QueryPlane(
            lambda: [("shard0", url_a), ("shard1", dead_url())],
            store=durable,
            freshness=lambda: {"shard1": last_ok},
            timeout_s=1.0)
        expr = "rate(apm_tx_total[4s])"
        st, doc = call(plane, "/query", series=expr,
                       start=T0, end=T0 + 7, step=1)
        assert st == 200  # degrade, never 5xx
        assert doc["partial"] is True and doc["stale"] is True
        assert doc["shards"]["shard0"]["status"] == "live"
        assert doc["shards"]["shard1"]["status"] == "stale"
        fresh = doc["shards"]["shard1"]["freshness_s"]
        assert fresh is not None and fresh > 0
        # the merged answer is bit-equal to the all-live golden: the
        # module label is stripped off the store slice before merging
        gdoc = eval_range(golden, expr, T0, T0 + 7, 1)
        assert doc["series"] == gdoc["series"]
    finally:
        srv_a.stop()


def test_dead_shard_without_store_marked_dead(tmp_path):
    sa = mem_store(tmp_path, "a", [
        (T0 + i, [("apm_tx_total", {"service": "alpha"}, float(i))])
        for i in range(4)])
    srv_a, url_a = shard_server(sa)
    try:
        plane = QueryPlane(
            lambda: [("shard0", url_a), ("shard1", dead_url())],
            timeout_s=1.0)
        st, doc = call(plane, "/query", series="apm_tx_total",
                       start=T0, end=T0 + 3, step=1)
        assert st == 200
        assert doc["partial"] is True and doc["stale"] is False
        assert doc["shards"]["shard1"] == {"status": "dead",
                                           "freshness_s": None}
    finally:
        srv_a.stop()


# -- cache -------------------------------------------------------------------

def test_ttl_cache_coalesces_inflight_computes():
    cache = _TTLCache(30.0)
    calls = []
    gate = threading.Event()
    results = []

    def compute():
        calls.append(1)
        gate.wait(5.0)
        return {"v": 42}

    def worker():
        results.append(cache.get_or_compute("k", compute))

    threads = [threading.Thread(target=worker) for _ in range(6)]
    for t in threads:
        t.start()
    time.sleep(0.2)  # let one leader enter compute, followers queue
    gate.set()
    for t in threads:
        t.join(10.0)
    assert len(calls) == 1  # exactly one compute
    assert len(results) == 6
    assert sum(1 for _v, hit in results if not hit) == 1  # one leader miss
    assert all(v == {"v": 42} for v, _hit in results)


def test_ttl_cache_expires_and_disabled():
    cache = _TTLCache(0.05)
    v1, hit1 = cache.get_or_compute("k", lambda: 1)
    v2, hit2 = cache.get_or_compute("k", lambda: 2)
    assert (v1, hit1, v2, hit2) == (1, False, 1, True)
    time.sleep(0.08)
    v3, hit3 = cache.get_or_compute("k", lambda: 3)
    assert (v3, hit3) == (3, False)
    off = _TTLCache(0.0)
    assert off.get_or_compute("k", lambda: 4) == (4, False)


def test_plane_cache_hit_and_bypass(tmp_path):
    sa = mem_store(tmp_path, "a", [
        (T0 + i, [("apm_tx_total", {"service": "a"}, float(i))])
        for i in range(4)])
    srv_a, url_a = shard_server(sa)
    try:
        reg = MetricsRegistry()
        plane = QueryPlane(lambda: [("shard0", url_a)], registry=reg,
                           cache_ttl_s=30.0)
        params = dict(series="apm_tx_total", start=T0, end=T0 + 3, step=1)
        _st, d1 = call(plane, "/query", **params)
        _st, d2 = call(plane, "/query", **params)
        assert d1["cached"] is False and d2["cached"] is True
        assert d1["series"] == d2["series"]
        _st, d3 = call(plane, "/query", cache=0, **params)
        assert d3["cached"] is False
        text = reg.render()
        assert "apm_queryplane_cache_hits_total 1" in text
    finally:
        srv_a.stop()


# -- rebalance consistency ---------------------------------------------------

def test_retry_on_move_is_bounded_and_counted(tmp_path):
    parts = 4
    svc = "svcmove"
    p = service_partition(svc, parts)
    sa = mem_store(tmp_path, "a", [
        (T0 + i, [("apm_tx_total", {"service": svc}, float(i))])
        for i in range(4)])
    srv_a, url_a = shard_server(sa)
    try:
        # an owner feed that bumps its seq on EVERY read: pathological
        # perpetual rebalance — the plane must still answer after
        # move_retries bounded requeries
        seqs = iter(range(1, 100))

        def storm():
            return next(seqs), {p: "shard0"}

        reg = MetricsRegistry()
        plane = QueryPlane(lambda: [("shard0", url_a)], owners=storm,
                           partitions=parts, move_retries=2, registry=reg,
                           cache_ttl_s=0.0)
        st, doc = call(plane, "/query", series="apm_tx_total", service=svc,
                       start=T0, end=T0 + 3, step=1)
        assert st == 200
        assert doc["move_retries"] == 2  # hit the bound, then served
        assert "apm_queryplane_move_retries_total 2" in reg.render()

        om = OwnerMap({p: "shard0"})
        plane2 = QueryPlane(lambda: [("shard0", url_a)], owners=om.read,
                            partitions=parts, cache_ttl_s=0.0)
        st, doc = call(plane2, "/query", series="apm_tx_total", service=svc,
                       start=T0, end=T0 + 3, step=1)
        assert st == 200 and doc["move_retries"] == 0  # stable map: no retry
        assert doc["owner_seq"] == om.read()[0]
    finally:
        srv_a.stop()


# -- format=matrix -----------------------------------------------------------

def test_matrix_doc_shape():
    doc = {"series": [
        {"labels": {"service": "a"}, "points": [[1.0, 2.5], [2.0, None]]},
    ]}
    m = matrix_doc(doc)
    assert m["status"] == "success"
    assert m["data"]["resultType"] == "matrix"
    assert m["data"]["result"] == [
        {"metric": {"service": "a"}, "values": [[1.0, "2.5"]]}]


def test_store_route_format_matrix(tmp_path):
    st = mem_store(tmp_path, "s", [
        (T0 + i, [("apm_tx_total", {"service": "a"}, float(i))])
        for i in range(4)])
    route = make_query_route(lambda: st)
    status, _ct, body = route({"series": ["apm_tx_total"],
                               "start": [str(T0)], "end": [str(T0 + 3)],
                               "step": ["1"], "format": ["matrix"]})
    assert status == 200
    doc = json.loads(body)
    assert doc["data"]["resultType"] == "matrix"
    assert doc["data"]["result"][0]["metric"] == {"service": "a"}
    # default format unchanged
    status, _ct, body = route({"series": ["apm_tx_total"],
                               "start": [str(T0)], "end": [str(T0 + 3)],
                               "step": ["1"]})
    assert "series" in json.loads(body)


def test_plane_format_matrix(tmp_path):
    sa = mem_store(tmp_path, "a", [
        (T0 + i, [("apm_tx_total", {"service": "a"}, float(i))])
        for i in range(4)])
    srv_a, url_a = shard_server(sa)
    try:
        plane = QueryPlane(lambda: [("shard0", url_a)])
        st, doc = call(plane, "/query", series="apm_tx_total", format="matrix",
                       start=T0, end=T0 + 3, step=1)
        assert st == 200
        assert doc["status"] == "success"
        assert doc["data"]["resultType"] == "matrix"
    finally:
        srv_a.stop()


def test_increase_expression_in_store(tmp_path):
    st = mem_store(tmp_path, "s", [
        (T0, [("apm_tx_total", {}, 10.0)]),
        (T0 + 5, [("apm_tx_total", {}, 40.0)]),
    ])
    doc = eval_range(st, "increase(apm_tx_total[10s])", T0 + 5, T0 + 5, 1)
    assert doc["series"][0]["points"][0][1] == pytest.approx(30.0)


# -- traces / decisions / attrib --------------------------------------------

def test_trace_scatter_dedups_by_identity(tmp_path):
    span = {"trace_id": "t1", "name": "tick", "start": T0, "dur": 1.0}
    other = {"trace_id": "t2", "name": "feed", "start": T0 + 1, "dur": 2.0}
    srv_a, url_a = shard_server(spans=[span, other])
    srv_b, url_b = shard_server(spans=[span])  # duplicate across shards
    try:
        plane = QueryPlane(lambda: [("shard0", url_a), ("shard1", url_b)])
        st, doc = call(plane, "/trace")
        assert st == 200
        assert doc["count"] == 2
        ids = {(s["trace_id"], s["name"]) for s in doc["spans"]}
        assert ids == {("t1", "tick"), ("t2", "feed")}
        assert doc["partial"] is False
    finally:
        srv_a.stop()
        srv_b.stop()


def test_decisions_fallback_from_store(tmp_path):
    dec_live = {"trace_id": "t1", "ts": T0, "service": "a", "channel": "email"}
    dec_dead = {"trace_id": "t2", "ts": T0 + 1, "service": "b",
                "channel": "email"}
    srv_a, url_a = shard_server(decisions=[dec_live])
    durable = TimeSeriesStore(str(tmp_path / "rec"))
    durable.append_decisions([dec_dead], extra={"module": "shard1"})
    try:
        plane = QueryPlane(
            lambda: [("shard0", url_a), ("shard1", dead_url())],
            store=durable, timeout_s=1.0)
        st, doc = call(plane, "/decisions")
        assert st == 200
        assert doc["partial"] is True and doc["stale"] is True
        traces = {d["trace_id"] for d in doc["decisions"]}
        assert traces == {"t1", "t2"}
    finally:
        srv_a.stop()


def test_attrib_merges_live_and_store_synthesized(tmp_path):
    live_snap = {
        "module": "shard0", "window_s": 10.0,
        "stages": {"tick": {"busy_s": 4.0, "blocked_s": 1.0, "idle_s": 5.0,
                            "events": 7}},
        "occupancy": {},
    }
    srv_a, url_a = shard_server(attrib=live_snap)
    durable = TimeSeriesStore(str(tmp_path / "rec"))
    durable.append_samples(
        [("apm_stage_busy_seconds_total", {"stage": "tick"}, 3.0),
         ("apm_stage_blocked_seconds_total", {"stage": "tick"}, 2.0),
         ("apm_stage_idle_seconds_total", {"stage": "tick"}, 5.0),
         ("apm_stage_events_total", {"stage": "tick"}, 9.0)],
        ts=T0, extra_labels={"module": "shard1"})
    try:
        plane = QueryPlane(
            lambda: [("shard0", url_a), ("shard1", dead_url())],
            store=durable, timeout_s=1.0)
        st, doc = call(plane, "/attrib")
        assert st == 200
        assert doc["partial"] is True and doc["stale"] is True
        assert set(doc["children"]) == {"shard0", "shard1"}
        # stage seconds summed across the live and the synthesized child
        assert doc["stages"]["tick"]["busy_s"] == pytest.approx(7.0)
        assert doc["stages"]["tick"]["events"] == 16
    finally:
        srv_a.stop()


def test_query_kind_names_and_stats(tmp_path):
    sa = mem_store(tmp_path, "a", [(T0, [("apm_tx_total", {}, 1.0)])])
    durable = mem_store(tmp_path, "rec",
                        [(T0, [("apm_dead_total", {"module": "x"}, 1.0)])])
    srv_a, url_a = shard_server(sa)
    try:
        plane = QueryPlane(lambda: [("shard0", url_a)], store=durable)
        st, doc = call(plane, "/query", kind="names")
        assert st == 200
        assert {"apm_tx_total", "apm_dead_total"} <= set(doc["names"])
        st, doc = call(plane, "/query", kind="stats")
        assert st == 200
        assert "plane" in doc and "store" in doc
        assert doc["plane"]["requests"] >= 1
    finally:
        srv_a.stop()


def test_bad_expression_is_400_not_error(tmp_path):
    reg = MetricsRegistry()
    plane = QueryPlane(lambda: [], registry=reg)
    st, _body = call(plane, "/query", series="sum(rate(x[1s])) by (y)")
    assert st == 400
    st, _body = call(plane, "/query")  # neither series nor kind
    assert st == 400
    assert "apm_queryplane_errors_total 0" in reg.render()


def test_serving_metrics_exported(tmp_path):
    sa = mem_store(tmp_path, "a", [(T0, [("apm_tx_total", {}, 1.0)])])
    srv_a, url_a = shard_server(sa)
    try:
        reg = MetricsRegistry()
        plane = QueryPlane(lambda: [("shard0", url_a)], registry=reg,
                           cache_ttl_s=0.0)
        call(plane, "/query", series="apm_tx_total", start=T0, end=T0, step=1)
        call(plane, "/trace")
        text = reg.render()
        assert 'apm_queryplane_requests_total{route="query"} 1' in text
        assert 'apm_queryplane_requests_total{route="trace"} 1' in text
        assert "apm_queryplane_fanout_shards_total 2" in text
        assert "apm_queryplane_latency_seconds_count 2" in text
        health = plane.health()
        assert health["ok"] is True and health["degraded"] is False
    finally:
        srv_a.stop()


# -- qstat rendering ---------------------------------------------------------

def test_qstat_renders_per_shard_freshness():
    from apmbackend_tpu.tools.qstat import format_range_result

    doc = {
        "expr": "apm_tx_total", "start": T0, "end": T0 + 9, "step": 1.0,
        "series": [{"labels": {"service": "a"},
                    "points": [[T0, 1.0], [T0 + 1, 2.0]]}],
        "shards": {"shard0": {"status": "live", "freshness_s": 0.0},
                   "shard1": {"status": "stale", "freshness_s": 4.25},
                   "shard2": {"status": "dead", "freshness_s": None}},
        "partial": True, "stale": True, "cached": False,
    }
    out = format_range_result(doc)
    assert "PARTIAL" in out and "STALE" in out
    assert "shard1" in out and "freshness=4.25s" in out
    assert "shard2" in out and "dead" in out
    # a plain per-store doc renders without the shard block
    plain = format_range_result({"expr": "x", "start": T0, "end": T0 + 1,
                                 "step": 1.0, "series": []})
    assert "shards" not in plain


def test_qstat_slo_health_includes_queryplane_section(monkeypatch):
    from apmbackend_tpu.tools import qstat

    body = {"status": "ok", "slo": {"fast": False},
            "queryplane": {"ok": True, "degraded": True}}

    class _Resp:
        def __enter__(self):
            return self

        def __exit__(self, *a):
            return False

        def read(self):
            return json.dumps(body).encode()

    monkeypatch.setattr("urllib.request.urlopen",
                        lambda *a, **k: _Resp())
    out = qstat.slo_health_url("http://x/healthz")
    assert out["queryplane"]["degraded"] is True
    # without a plane section the key stays absent (per-module healthz)
    body2 = {"status": "ok", "slo": {}}
    body.clear()
    body.update(body2)
    out2 = qstat.slo_health_url("http://x/healthz")
    assert "queryplane" not in out2


# -- QueryLoad ---------------------------------------------------------------

def test_query_load_summarizes_codes_and_latency(tmp_path):
    from apmbackend_tpu.testing.chaos import QueryLoad

    sa = mem_store(tmp_path, "a", [(T0, [("apm_tx_total", {}, 1.0)])])
    srv_a, url_a = shard_server(sa)
    try:
        load = QueryLoad(
            [f"{url_a}/query?series=apm_tx_total&start={T0}&end={T0}&step=1"],
            threads=2, seed=7).start()
        time.sleep(0.4)
        summary = load.stop()
        assert summary["requests"] > 0
        assert summary["five_xx"] == 0
        assert summary["codes"].get(200, 0) == summary["requests"]
        assert summary["p95_ms"] is not None
    finally:
        srv_a.stop()
