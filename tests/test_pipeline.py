"""End-to-end pipeline tests: fused device step vs composed golden oracles."""

import math

import numpy as np
import pytest

from apmbackend_tpu.config import default_config
from apmbackend_tpu.entries import EntryFactory, TxEntry
from apmbackend_tpu.ops import alerts as dalerts
from apmbackend_tpu.pipeline import PipelineDriver, build_engine_config

from golden import GoldenStats, GoldenZScore

BASE = 170_000_000


def small_config(lag=6, window_required=(5, 3), capacity=16):
    cfg = default_config()
    cfg["streamCalcZScore"]["defaults"] = [{"LAG": lag, "THRESHOLD": 2.0, "INFLUENCE": 0.1}]
    cfg["streamProcessAlerts"]["rollingAlertWindowSizeInIntervals"] = window_required[0]
    cfg["streamProcessAlerts"]["requiredNumberBadIntervalsInAlertWindowToTrigger"] = window_required[1]
    cfg["tpuEngine"]["serviceCapacity"] = capacity
    cfg["tpuEngine"]["dtype"] = "float64"
    return cfg


def js_round(x, digits):
    """Host-side equivalent of the wire quantization for the oracle chain."""
    if math.isnan(x):
        return x
    return math.floor(x * 10**digits + 0.5) / 10**digits


def make_stream(rng, n_ticks=30, keys=(("jvm1", "S:a"), ("jvm1", "S:b"))):
    events = []
    for i in range(n_ticks):
        label = BASE + i
        for server, service in keys:
            for j in range(int(rng.randint(1, 6))):
                elapsed = int(rng.randint(100, 1000))
                ts = label * 10000 + j * 100
                events.append(TxEntry(server, service, f"l{i}{j}", "1", ts - elapsed, ts, elapsed, "Y"))
    return events


def test_pipeline_matches_golden_chain():
    rng = np.random.RandomState(11)
    cfg = small_config()
    stats_emitted = []
    fs_emitted = []
    drv = PipelineDriver(
        cfg, on_stat=stats_emitted.append, on_fullstat=fs_emitted.append,
    )

    g_stats = GoldenStats()
    g_z = GoldenZScore(6, 2.0, 0.1)
    golden_stat_rows = []
    golden_fs = []

    events = make_stream(rng)
    for tx in events:
        rows = g_stats.add(tx.server, tx.service, int(tx.end_ts), int(tx.elapsed))
        for r in rows:
            q = {
                "ts": r["ts"], "server": r["server"], "service": r["service"],
                "tpm": js_round(r["tpm"], 2), "average": js_round(r["average"], 1),
                "per75": js_round(r["per75"], 1), "per95": js_round(r["per95"], 1),
            }
            golden_stat_rows.append(q)
            z = g_z.step(r["server"], r["service"], q["average"], q["per75"], q["per95"])
            golden_fs.append((q, z))
        drv.feed(tx)

    assert len(stats_emitted) == len(golden_stat_rows)
    for st, g in zip(stats_emitted, golden_stat_rows):
        assert (st.server, st.service) == (g["server"], g["service"])
        assert st.timestamp == g["ts"]
        for f in ("tpm", "average", "per75", "per95"):
            gv, dv = g[f], getattr(st, {"average": "average"}.get(f, f))
            if math.isnan(gv):
                assert math.isnan(dv)
            else:
                assert dv == pytest.approx(gv, rel=1e-9)

    assert len(fs_emitted) == len(golden_fs)
    for fs, (q, z) in zip(fs_emitted, golden_fs):
        assert fs.lag == 6
        for m, (a_field, s_field) in {
            "avg": ("average_avg", "average_signal"),
            "p75": ("per75_avg", "per75_signal"),
            "p95": ("per95_avg", "per95_signal"),
        }.items():
            gv = z[m]["avg"]
            dv = getattr(fs, a_field)
            if math.isnan(gv):
                assert math.isnan(dv), (fs.service, fs.timestamp, m)
            else:
                assert dv == pytest.approx(gv, rel=1e-9)
            assert int(getattr(fs, s_field)) == z[m]["signal"], (fs.service, fs.timestamp, m)


def test_ordered_tx_drain():
    cfg = small_config()
    ordered = []
    drv = PipelineDriver(cfg, on_ordered_tx=ordered.append)
    rng = np.random.RandomState(3)
    events = make_stream(rng, n_ticks=15, keys=(("s", "x"),))
    rng.shuffle(events)  # out-of-order arrival within the stream
    # ...but feed() uses end_ts tick detection; shuffle only within same tick:
    events.sort(key=lambda t: int(t.end_ts) // 10000)
    for tx in events:
        drv.feed(tx)
    # drained tx must be in end_ts order and only up to the window edge
    ts_list = [t.end_ts for t in ordered]
    assert ts_list == sorted(ts_list)
    assert len(ordered) > 0


def test_alert_trigger_through_cooldown():
    cfg = small_config(lag=4, window_required=(3, 2))
    cfg["streamProcessAlerts"]["perServiceAlertCooldownInMinutes"] = 0  # no cooldown
    cfg["streamProcessAlerts"]["emailsEnabled"] = False
    from apmbackend_tpu.ops.alerts import AlertsManager

    alerts = []
    mgr = AlertsManager(cfg["streamProcessAlerts"], clock=lambda: 1_800_000_000.0)
    drv = PipelineDriver(cfg, alerts_manager=mgr, on_alert=alerts.append)
    rng = np.random.RandomState(5)
    events = []
    for i in range(30):
        label = BASE + i
        base_ms = 300 if i < 18 else 5000  # sustained regression
        for j in range(5):
            e = int(base_ms + 10 * rng.rand())
            ts = label * 10000 + j * 100
            events.append(TxEntry("jvm1", "S:slow", "", "1", ts - e, ts, e, "Y"))
    for tx in events:
        drv.feed(tx)
    assert alerts, "sustained regression must raise alerts"
    assert alerts[0].service == "S:slow"
    assert "UB exceeded" in alerts[0].cause
    assert mgr.alert_buffer  # buffered for batch send


def test_registry_growth_mid_stream():
    cfg = small_config(capacity=2)
    stats_emitted = []
    drv = PipelineDriver(cfg, on_stat=stats_emitted.append)
    for i in range(12):
        label = BASE + i
        for k in range(min(i + 1, 5)):  # progressively more services
            ts = label * 10000 + k
            drv.feed(TxEntry("s", f"svc{k}", "", "1", ts - 100, ts, 100, "N"))
    assert drv.cfg.capacity >= 5
    services = {s.service for s in stats_emitted}
    assert {"svc0", "svc1", "svc2", "svc3", "svc4"} <= services


def test_resume_roundtrip(tmp_path):
    cfg = small_config()
    drv = PipelineDriver(cfg)
    rng = np.random.RandomState(8)
    events = make_stream(rng, n_ticks=20)
    for tx in events:
        drv.feed(tx)
    drv.flush()
    p = str(tmp_path / "engine.resume.npz")
    drv.save_resume(p)

    fs_a, fs_b = [], []
    drv.on_fullstat = fs_a.append
    drv2 = PipelineDriver(cfg, on_fullstat=fs_b.append)
    assert drv2.load_resume(p)
    assert drv2.registry.rows() == drv.registry.rows()

    tail = make_stream(np.random.RandomState(9), n_ticks=5)
    for tx in tail:
        ts_shift = (BASE + 25 - BASE) * 10000
        tx2a = TxEntry(tx.server, tx.service, "", "1", tx.start_ts + ts_shift, tx.end_ts + ts_shift, tx.elapsed, "Y")
        tx2b = TxEntry(tx.server, tx.service, "", "1", tx.start_ts + ts_shift, tx.end_ts + ts_shift, tx.elapsed, "Y")
        drv.feed(tx2a)
        drv2.feed(tx2b)
    assert len(fs_a) == len(fs_b) and len(fs_a) > 0
    for a, b in zip(fs_a, fs_b):
        assert a.to_csv() == b.to_csv()  # byte-identical continuation


def test_hot_reload_params():
    cfg = small_config()
    drv = PipelineDriver(cfg)
    row = drv.registry.lookup_or_add("s", "S:special")
    assert float(drv.params.thresholds[0][row]) == 2.0
    new_cfg = small_config()
    new_cfg["streamCalcZScore"]["overrides"]["services"] = {"S:special": {"6": {"THRESHOLD": 9.0}}}
    drv.apply_config(new_cfg)
    assert float(drv.params.thresholds[0][row]) == 9.0


def test_resume_path_without_npz_suffix(tmp_path):
    cfg = small_config()
    drv = PipelineDriver(cfg)
    drv.feed(TxEntry("s", "x", "", "1", (BASE * 10000) - 100, BASE * 10000, 100, "N"))
    drv.flush()
    p = str(tmp_path / "engine.resume")  # no .npz suffix
    drv.save_resume(p)
    drv2 = PipelineDriver(cfg)
    assert drv2.load_resume(p)
    assert drv2.registry.rows() == drv.registry.rows()


def test_resume_corrupt_file_starts_fresh(tmp_path):
    cfg = small_config()
    p = str(tmp_path / "bad.resume")
    open(p, "wb").write(b"not a zip at all")
    drv = PipelineDriver(cfg)
    assert drv.load_resume(p) is False  # no crash


def test_resume_valid_zip_wrong_contents_starts_fresh(tmp_path):
    # np.load accepts any readable zip; missing members must mean "start
    # fresh", not a lazy KeyError mid-restore.
    cfg = small_config()
    p = str(tmp_path / "wrong.resume.npz")
    np.savez_compressed(p, unrelated=np.arange(4))
    drv = PipelineDriver(cfg)
    assert drv.load_resume(p) is False
    # driver still usable after the rejected load
    drv.feed(TxEntry("s", "x", "", "1", (BASE * 10000) - 100, BASE * 10000, 100, "N"))
    drv.flush()


def test_overflow_surfaced_via_counters_and_callback():
    """Reservoir overflow must be consumed, not just computed: driver counters
    advance and the on_overflow hook fires with the affected row count."""
    cfg = small_config(capacity=4)
    cfg["tpuEngine"]["samplesPerBucket"] = 4
    cfg["tpuEngine"]["dtype"] = "float32"
    overflow_events = []
    drv = PipelineDriver(cfg, on_overflow=lambda label, n: overflow_events.append((label, n)))
    label = BASE
    # 30 tx for one service in one bucket >> CAP=4
    for j in range(30):
        ts = label * 10000 + j
        drv.feed(TxEntry("jvm1", "S:hot", f"l{j}", "1", ts - 100, ts, 100, "Y"))
    # advance far enough that `label` lands inside the stats window
    edge_label = label + drv.cfg.stats.buffer_sz + 1
    drv.feed(TxEntry("jvm1", "S:hot", "lx", "1", edge_label * 10000 - 100, edge_label * 10000, 100, "Y"))
    assert drv.overflow_ticks >= 1
    assert drv.overflow_rows_total >= 1
    assert overflow_events and overflow_events[0][1] >= 1


def _stream_lines(rng, n_ticks=12, keys=(("jvm1", "S:a"), ("jvm1", "S:b"), ("jvm2", "S:c"))):
    txs = make_stream(rng, n_ticks=n_ticks, keys=keys)
    return txs, [tx.to_csv() for tx in txs]


def test_feed_csv_batch_matches_object_path():
    """The bulk CSV fast path must reproduce the object path exactly:
    same FullStat emissions, same ordered-tx drain, same device state."""
    rng = np.random.RandomState(23)
    txs, lines = _stream_lines(rng)
    cfg = small_config()

    fs_a, ordered_a = [], []
    drv_a = PipelineDriver(
        cfg, on_fullstat=lambda fs: fs_a.append(fs.to_csv()),
        on_ordered_tx=lambda tx: ordered_a.append(tx.to_csv()),
    )
    for tx in txs:
        drv_a.feed(tx)
    drv_a.flush()

    fs_b, ordered_b = [], []
    drv_b = PipelineDriver(
        cfg, on_fullstat=lambda fs: fs_b.append(fs.to_csv()),
        on_ordered_csv=ordered_b.append,
    )
    # uneven chunks exercise tick splits at arbitrary batch boundaries
    i = 0
    for size in (7, 64, 3, 999, 11, 10_000):
        drv_b.feed_csv_batch(lines[i : i + size])
        i += size
    drv_b.feed_csv_batch(lines[i:])
    drv_b.flush()

    assert fs_b == fs_a
    # heap drain orders by end_ts; both paths must agree on the multiset per
    # tick and the timestamp ordering (heap ties are arbitrary, sort ties are
    # stable) — compare end_ts-sorted
    assert sorted(ordered_b) == sorted(ordered_a)
    assert np.array_equal(
        np.asarray(drv_a.state.stats.counts), np.asarray(drv_b.state.stats.counts)
    )
    assert np.allclose(
        np.asarray(drv_a.state.stats.sums), np.asarray(drv_b.state.stats.sums)
    )
    sa = np.nan_to_num(np.asarray(drv_a.state.stats.samples), nan=-1)
    sb = np.nan_to_num(np.asarray(drv_b.state.stats.samples), nan=-1)
    assert np.array_equal(sa, sb)  # deterministic reservoir parity too


def test_fullstat_csv_lines_byte_identical_to_objects():
    rng = np.random.RandomState(31)
    txs, lines = _stream_lines(rng, n_ticks=10)
    cfg = small_config()

    obj_lines = []
    drv_a = PipelineDriver(cfg, on_fullstat=lambda fs: obj_lines.append(fs.to_csv()))
    for tx in txs:
        drv_a.feed(tx)

    csv_lines = []
    drv_b = PipelineDriver(cfg, on_fullstat_csv=csv_lines.extend)
    drv_b.feed_csv_batch(lines)

    assert csv_lines == obj_lines


def test_feed_csv_batch_drops_malformed():
    cfg = small_config()
    drv = PipelineDriver(cfg)
    n = drv.feed_csv_batch(
        [
            "st|1700|jvm1|S:a|1|2|3|4",  # not a tx
            "tx|jvm1|S:a|l1|1",  # wrong arity
            f"tx|jvm1|S:a|l1|1|{BASE * 10000 - 100}|{BASE * 10000}|100|Y",  # good
            "tx|jvm1|S:a|l1|1|garbage|alsogarbage|100|Y",  # NaN end_ts
        ]
    )
    assert n == 1


def test_feed_csv_batch_heap_skipped_without_consumer():
    """No ordered-tx consumer => neither the heap nor the backlog grow."""
    rng = np.random.RandomState(5)
    txs, lines = _stream_lines(rng, n_ticks=6)
    cfg = small_config()
    drv = PipelineDriver(cfg)
    drv.feed_csv_batch(lines)
    assert drv.heap.size() == 0
    assert drv._tx_backlog == []
    drv2 = PipelineDriver(cfg)
    for tx in txs:
        drv2.feed(tx)
    assert drv2.heap.size() == 0
