"""Staggered sliding-aggregate rebuild (ops/zscore.py rebuild_agg_slice +
pipeline.RebuildScheduler + native/rebuild.cpp).

The sliding z-score engine owes a periodic exact re-aggregation of its
values ring (drift cancellation for the incremental moments the reference
recomputes from scratch per entry, stream_calc_z_score.js:66-104 /
util_methods.js:10-50). Round 4 paid it as one monolithic whole-ring pass
every ``rebuild_every`` ticks — a multi-second tick stall at pod shapes.
The staggered schedule rebuilds one row chunk per tick instead; these tests
pin its two contracts:

1. applying every chunk of a rotation back-to-back reproduces the
   monolithic ``rebuild_agg_state`` BITWISE (per-row math is identical);
2. the native streaming producer (double accumulators) matches the XLA
   producer within float tolerance, with the discrete fields (cnt, run_len,
   last_valid, last_push, min/max-driven repairs) bitwise.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apmbackend_tpu.ops import zscore as dz
from apmbackend_tpu.pipeline import (
    RebuildScheduler,
    engine_ingest,
    engine_rebuild_aggs,
    engine_rebuild_slice,
    make_demo_engine,
    make_engine_step,
)


def _warm_engine(capacity=96, ticks=40, seed=0, lag_settings=((6, 20.0, 0.1), (24, 15.0, 0.0))):
    cfg, state, params = make_demo_engine(capacity, 16, list(lag_settings))
    tick = make_engine_step(cfg)
    ingest = jax.jit(engine_ingest, static_argnums=1, donate_argnums=(0,))
    rng = np.random.RandomState(seed)
    label = 170_000_000
    for _ in range(ticks):
        label += 1
        _em, state = tick(state, label, params)
        B = 256
        rows = rng.randint(0, capacity, B).astype(np.int32)
        elaps = (200 + 50 * rng.rand(B)).astype(np.float32)
        # occasional quiet rows/NaN windows arise naturally from rows that
        # receive no samples in a bucket
        state = ingest(state, cfg, rows, np.full(B, label, np.int32), elaps, np.ones(B, bool))
    jax.block_until_ready(state.stats.counts)
    return cfg, state, params


def _agg_leaves_equal(a, b, *, exact_only=False, rtol=2e-5, atol=1e-4):
    for name in a._fields:
        x, y = np.asarray(getattr(a, name)), np.asarray(getattr(b, name))
        if name in ("cnt", "run_len", "last_valid", "last_push"):
            assert np.array_equal(x, y, equal_nan=True), name
        elif exact_only:
            assert np.array_equal(x, y, equal_nan=True), name
        else:
            np.testing.assert_allclose(x, y, rtol=rtol, atol=atol, err_msg=name)


def test_full_rotation_is_bitwise_monolithic():
    cfg, state, _ = _warm_engine()
    S = cfg.capacity
    mono = engine_rebuild_aggs(state, cfg)
    chunk = dz.rebuild_chunk_rows(S, cfg.zscore_rebuild_every)
    n_chunks = -(-S // chunk)
    stag = state
    for i in range(n_chunks):
        stag = engine_rebuild_slice(stag, cfg, min(i * chunk, S - chunk), chunk)
    for zm, zs in zip(mono.zscores, stag.zscores):
        assert (zm.agg is None) == (zs.agg is None)
        if zm.agg is not None:
            _agg_leaves_equal(zm.agg, zs.agg, exact_only=True)


def test_rotation_covers_every_row_within_rebuild_every():
    for S, every in [(96, 64), (8192, 64), (5, 64), (130, 64), (64, 7)]:
        chunk = dz.rebuild_chunk_rows(S, every)
        n_chunks = -(-S // chunk)
        assert n_chunks <= every
        covered = set()
        for i in range(n_chunks):
            start = min(i * chunk, S - chunk)
            covered.update(range(start, start + chunk))
        assert covered == set(range(S))


def test_scheduler_jitted_matches_scheduler_native():
    from apmbackend_tpu import native as _native

    if not _native.have_native_rebuild():
        pytest.skip("native toolchain unavailable")
    cfg, state, _ = _warm_engine()
    freeze = lambda st: jax.tree_util.tree_map(lambda x: jnp.array(np.asarray(x)), st)
    sj, sn = RebuildScheduler(cfg, allow_native=False), RebuildScheduler(cfg, allow_native=True)
    assert sn._native, "CPU backend with toolchain should select the native producer"
    st_j, st_n = freeze(state), freeze(state)
    for _ in range(sj.n_chunks):
        st_j, st_n = sj.step(st_j), sn.step(st_n)
    # the native path must have SURVIVED the loop — a mid-step failure flips
    # _native and silently degrades to jitted-vs-jitted, proving nothing
    assert sn._native, "native producer was disabled mid-run (exception in _native_step)"
    for zj, zn in zip(st_j.zscores, st_n.zscores):
        if zj.agg is not None:
            _agg_leaves_equal(zj.agg, zn.agg)


def test_ragged_capacity_rotation_is_value_exact():
    """capacity not divisible by the chunk: the clamped tail chunk re-rebuilds
    a few rows from already-refreshed aggregates — exact, though not bitwise
    (rebuild_agg_slice docstring). Verify against a from-scratch build_agg."""
    from apmbackend_tpu.pipeline import zscore_cfg

    cfg, state, _ = _warm_engine(capacity=130)  # chunk=ceil(130/64)=3, 130%3!=0
    S = cfg.capacity
    chunk = dz.rebuild_chunk_rows(S, cfg.zscore_rebuild_every)
    assert S % chunk != 0
    n_chunks = -(-S // chunk)
    stag = state
    for i in range(n_chunks):
        stag = engine_rebuild_slice(stag, cfg, min(i * chunk, S - chunk), chunk)
    for spec, z in zip(cfg.lags, stag.zscores):
        zc = zscore_cfg(cfg, spec)
        if not zc.sliding_active:
            continue
        fresh = dz.build_agg(z.values, zc, z.pos)  # exact two-pass oracle
        assert np.array_equal(np.asarray(z.agg.cnt), np.asarray(fresh.cnt))
        mean_stag = np.asarray(z.agg.anchor) + np.asarray(z.agg.vsum) / np.maximum(
            np.asarray(z.agg.cnt), 1
        )
        mean_ref = np.asarray(fresh.anchor) + np.asarray(fresh.vsum) / np.maximum(
            np.asarray(fresh.cnt), 1
        )
        has = np.asarray(z.agg.cnt) > 0
        np.testing.assert_allclose(mean_stag[has], mean_ref[has], rtol=1e-5, atol=1e-3)


def test_scheduler_native_bf16_ring():
    """bfloat16 rings (the 850 MB pod configuration the native kernel was
    written for) must reach the native producer via the uint16 bit view —
    numpy's dlpack import rejects bf16, so a naive view would silently
    disable the fast path."""
    from apmbackend_tpu import native as _native

    if not _native.have_native_rebuild():
        pytest.skip("native toolchain unavailable")
    cfg, state, params = make_demo_engine(
        64, 8, [(6, 20.0, 0.1), (24, 15.0, 0.0)], ring_dtype="bfloat16"
    )
    assert cfg.zscore_ring_dtype == jnp.bfloat16
    tick = make_engine_step(cfg)
    ingest = jax.jit(engine_ingest, static_argnums=1, donate_argnums=(0,))
    rng = np.random.RandomState(11)
    label = 170_000_000
    for _ in range(12):
        label += 1
        _em, state = tick(state, label, params)
        B = 128
        rows = rng.randint(0, cfg.capacity, B).astype(np.int32)
        elaps = (200 + 50 * rng.rand(B)).astype(np.float32)
        state = ingest(state, cfg, rows, np.full(B, label, np.int32), elaps, np.ones(B, bool))
    freeze = lambda st: jax.tree_util.tree_map(lambda x: jnp.array(np.asarray(x)), st)
    sj, sn = RebuildScheduler(cfg, allow_native=False), RebuildScheduler(cfg, allow_native=True)
    assert sn._native
    st_j, st_n = freeze(state), freeze(state)
    for _ in range(sj.n_chunks):
        st_j, st_n = sj.step(st_j), sn.step(st_n)
    assert sn._native, "bf16 ring must not knock out the native producer"
    for zj, zn in zip(st_j.zscores, st_n.zscores):
        if zj.agg is not None:
            _agg_leaves_equal(zj.agg, zn.agg)


def test_scheduler_preserves_detection_stream():
    """Interleaving the staggered rebuild with live ticks must not change
    what the detector emits: the rebuild is exact per chunk, so signals on a
    clean engine (no accumulated drift) are identical with and without it."""
    cfg, state, params = _warm_engine(ticks=10)
    tick = make_engine_step(cfg)
    ingest = jax.jit(engine_ingest, static_argnums=1, donate_argnums=(0,))
    freeze = lambda st: jax.tree_util.tree_map(lambda x: jnp.array(np.asarray(x)), st)
    sched = RebuildScheduler(cfg)
    st_plain, st_sched = freeze(state), freeze(state)
    rng = np.random.RandomState(7)
    label = 170_000_010
    for t in range(30):
        label += 1
        em_p, st_plain = tick(st_plain, label, params)
        em_s, st_sched = tick(st_sched, label, params)
        st_sched = sched.step(st_sched)
        for lp, ls in zip(em_p.lags, em_s.lags):
            assert np.array_equal(np.asarray(lp.signal), np.asarray(ls.signal))
            np.testing.assert_allclose(
                np.asarray(lp.window_avg), np.asarray(ls.window_avg),
                rtol=2e-5, atol=1e-4, equal_nan=True,
            )
        B = 256
        rows = rng.randint(0, cfg.capacity, B).astype(np.int32)
        elaps = (200 + 50 * rng.rand(B)).astype(np.float32)
        batch = (rows, np.full(B, label, np.int32), elaps, np.ones(B, bool))
        st_plain = ingest(st_plain, cfg, *batch)
        st_sched = ingest(st_sched, cfg, *batch)


def test_native_kernel_against_numpy_oracle():
    from apmbackend_tpu import native as _native

    if not _native.have_native_rebuild():
        pytest.skip("native toolchain unavailable")
    rng = np.random.RandomState(3)
    R, L = 37, 513
    ring = (1e6 + 50 * rng.rand(R, 3, L)).astype(np.float32)  # large-magnitude rows
    ring[rng.rand(R, 3, L) < 0.15] = np.nan
    ring[5] = np.nan  # all-NaN row
    ring[6] = 42.0  # all-equal row
    anchor = np.nan_to_num(np.nanmean(ring, axis=2)).astype(np.float32)
    cnt, vsum, vsumsq, vmin, vmax, lastp = _native.window_aggs_native(ring, anchor, L - 2)
    valid = ~np.isnan(ring)
    assert np.array_equal(cnt, valid.sum(2).astype(np.int32))
    d = np.where(valid, ring.astype(np.float64) - anchor[:, :, None], 0.0)
    # tolerance = the f32 accumulation bound, NOT a machine-tuned constant:
    # the kernel's reduction order depends on the build's SIMD width
    # (-march=native), so worst-case error is ~n * eps_f32 * sum|terms|
    # (~513 * 6e-8 * 9e4 ≈ 3 for vsumsq here); rtol 5e-5 covers every
    # vector width, and the merge consumers only need f32-level accuracy
    np.testing.assert_allclose(vsum, d.sum(2), rtol=5e-5, atol=5e-3)
    np.testing.assert_allclose(vsumsq, (d * d).sum(2), rtol=5e-5, atol=1e-2)
    has = cnt > 0
    assert np.array_equal(vmin[has], np.nanmin(ring, 2)[has])
    assert np.array_equal(vmax[has], np.nanmax(ring, 2)[has])
    assert np.isinf(vmin[~has]).all() and np.isinf(vmax[~has]).all()
    assert np.array_equal(lastp, ring[:, :, L - 2], equal_nan=True)
    assert (vmin[6] == 42.0).all() and (vmax[6] == 42.0).all()


def test_native_kernel_bf16_ring():
    from apmbackend_tpu import native as _native

    if not _native.have_native_rebuild():
        pytest.skip("native toolchain unavailable")
    import ml_dtypes

    rng = np.random.RandomState(4)
    R, L = 9, 129
    ring32 = (200 + 50 * rng.rand(R, 3, L)).astype(np.float32)
    ring32[rng.rand(R, 3, L) < 0.1] = np.nan
    ring = ring32.astype(ml_dtypes.bfloat16)
    rf = ring.astype(np.float32)  # the exact bits the kernel must see
    anchor = np.nan_to_num(np.nanmean(rf, axis=2)).astype(np.float32)
    cnt, vsum, vsumsq, vmin, vmax, lastp = _native.window_aggs_native(ring, anchor, 0)
    valid = ~np.isnan(rf)
    assert np.array_equal(cnt, valid.sum(2).astype(np.int32))
    d = np.where(valid, rf.astype(np.float64) - anchor[:, :, None], 0.0)
    np.testing.assert_allclose(vsum, d.sum(2), rtol=1e-6, atol=1e-3)
    has = cnt > 0
    assert np.array_equal(vmin[has], np.nanmin(rf, 2)[has])
    assert np.array_equal(lastp, rf[:, :, 0], equal_nan=True)


def test_driver_runs_staggered_rebuild_every_tick():
    """PipelineDriver retires one chunk per tick: after capacity ticks with
    chunk=ceil(S/64), the rotation index must have wrapped deterministically."""
    from apmbackend_tpu.config import default_config
    from apmbackend_tpu.pipeline import PipelineDriver

    cfg = default_config()
    cfg["tpuEngine"]["serviceCapacity"] = 32
    cfg["tpuEngine"]["samplesPerBucket"] = 8
    drv = PipelineDriver(cfg)
    if drv._step.rebuild_integrated:
        # fused executor: the chunk rides the tick program itself; the
        # executor's rotation counter is the observable contract
        rot = drv._step.rebuild_rot
        assert drv._rebuild_sched is None
        before = rot["i"]
        n_chunks = len(drv._step.rebuild_starts)
    else:
        sched = drv._rebuild_sched
        assert sched.active
        before = sched._i
        n_chunks = sched.n_chunks
    base = 170_000_000
    lines = [
        f"tx|jvm0|S:svc{r:03d}|l{i}|1|{base * 10000 - 100}|{base * 10000 + i}|{100 + i}|Y"
        for i, r in enumerate([0, 1, 2, 3] * 8)
    ]
    drv.feed_csv_batch(lines)
    drv.feed_csv_batch(
        [
            f"tx|jvm0|S:svc000|m{i}|1|{(base + 1) * 10000 - 100}|{(base + 1) * 10000 + i}|{100 + i}|Y"
            for i in range(4)
        ]
    )
    after = (
        drv._step.rebuild_rot["i"] if drv._step.rebuild_integrated else drv._rebuild_sched._i
    )
    assert after != before or n_chunks == 1


def test_scheduler_inactive_for_robust_and_f64():
    """Configs with no sliding lag (robust-only, f64 parity) must make the
    scheduler a no-op that returns the state unchanged."""
    import jax.numpy as jnp

    from apmbackend_tpu.pipeline import engine_init

    cfg = make_demo_engine(96, 16, [(6, 20.0, 0.1), (24, 15.0, 0.0)])[0]
    # sliding_active has two independent disablers; cover BOTH
    cfg_robust = cfg._replace(lags=tuple(s._replace(robust=True) for s in cfg.lags))
    cfg_f64 = cfg._replace(stats=cfg.stats._replace(dtype=jnp.float64))
    for c in (cfg_robust, cfg_f64):
        st = engine_init(c)
        sched = RebuildScheduler(c)
        assert not sched.active
        out = sched.step(st)
        assert out is st  # identity, no dispatch


def test_driver_grow_recreates_scheduler():
    """Capacity growth recompiles the engine; the rebuild scheduler must
    follow (new chunk size, fresh rotation) and keep ticking."""
    from apmbackend_tpu.config import default_config
    from apmbackend_tpu.pipeline import PipelineDriver

    cfg = default_config()
    cfg["tpuEngine"]["serviceCapacity"] = 8
    cfg["tpuEngine"]["samplesPerBucket"] = 8
    cfg["streamCalcZScore"]["defaults"] = [
        {"LAG": 4, "THRESHOLD": 3.0, "INFLUENCE": 0.1}
    ]
    drv = PipelineDriver(cfg, micro_batch_size=64)
    integrated = drv._step.rebuild_integrated

    def chunk_of(d):
        return d._step.rebuild_chunk if integrated else d._rebuild_sched.chunk

    s0 = drv._step if integrated else drv._rebuild_sched
    if not integrated:
        assert s0.active
    assert chunk_of(drv) == dz.rebuild_chunk_rows(8, drv.cfg.zscore_rebuild_every)
    base = 170_000_000
    # register more keys than capacity to force growth (8 -> 16)
    lines = [
        f"tx|jvm0|S:svc{r:03d}|l{i}|1|{base * 10000 - 100}|{base * 10000 + i}|{100 + i}|Y"
        for i, r in enumerate(range(12))
    ]
    drv.feed_csv_batch(lines)
    assert drv.cfg.capacity >= 12
    s1 = drv._step if integrated else drv._rebuild_sched
    assert s1 is not s0, "growth must rebuild the executor/scheduler for the new capacity"
    assert chunk_of(drv) == dz.rebuild_chunk_rows(drv.cfg.capacity, drv.cfg.zscore_rebuild_every)
    # and ticking advances the NEW rotation (a stale reference or a
    # post-growth stop would leave it at 0)
    before = s1.rebuild_rot["i"] if integrated else s1._i
    n_chunks = len(s1.rebuild_starts) if integrated else s1.n_chunks
    drv.feed_csv_batch([
        f"tx|jvm0|S:svc000|m{i}|1|{(base + 1) * 10000 - 100}|{(base + 1) * 10000 + i}|{100 + i}|Y"
        for i in range(4)
    ])
    assert (drv._step if integrated else drv._rebuild_sched) is s1
    after = s1.rebuild_rot["i"] if integrated else s1._i
    assert after == (before + 1) % n_chunks


def test_incremental_drift_bound_and_rebuild_margin():
    """Quantifies the float drift the rebuild cadence exists to cancel —
    the number behind 'rebuild_every=64 is conservative' (DESIGN.md §2).

    Runs the sliding step at a drift-hostile shape (large-magnitude values,
    small spread, f32) for many windows' worth of ticks, comparing the
    incremental window variance against the from-scratch build_agg oracle:
    (a) with NO rebuild at all, relative variance error stays bounded over
    20 windows' worth of pushes (the anchored-moment design keeps drift at
    spread scale, not magnitude scale); (b) with the production staggered
    rotation, the error stays at least 5x tighter."""
    S, L = 16, 32
    zc = dz.ZScoreConfig(S, L, jnp.float32, sliding=True)
    thr = jnp.full(S, 1e9, jnp.float32)  # never signal: pushes undamped
    infl = jnp.full(S, 1.0, jnp.float32)
    step = jax.jit(dz.step, static_argnums=1)

    def run(ticks, rebuild_every=None):
        rng = np.random.RandomState(5)  # IDENTICAL stream for both runs:
        # the comparison below is paired, not across two different streams
        st = dz.init_state(zc)
        i = 0
        chunk = dz.rebuild_chunk_rows(S, 64)
        n_chunks = -(-S // chunk)
        for t in range(ticks):
            nv = jnp.asarray(
                (1e6 + 3.0 * rng.rand(S, 3)).astype(np.float32)
            )  # magnitude 1e6, spread ~3: raw-sum accumulation would be fatal
            _res, st = step(st, zc, nv, thr, infl)
            if rebuild_every is not None:
                st = dz.rebuild_agg_slice(
                    st, zc, min(i * chunk, S - chunk), chunk
                )
                i = (i + 1) % n_chunks
        return st

    def max_rel_var_err(st):
        oracle = dz.build_agg(st.values, zc, st.pos)
        def var_of(a):
            cnt = np.asarray(a.cnt, np.float64)
            vs = np.asarray(a.vsum, np.float64)
            vs2 = np.asarray(a.vsumsq, np.float64)
            m = vs / np.maximum(cnt, 1)
            return np.maximum(vs2 / np.maximum(cnt, 1) - m * m, 0)
        v_inc, v_ref = var_of(st.agg), var_of(oracle)
        ok = np.asarray(oracle.cnt) > 0
        return float(np.max(np.abs(v_inc[ok] - v_ref[ok]) / np.maximum(v_ref[ok], 1e-9)))

    ticks = 20 * L  # 20 full windows of pushes with no/with rebuild
    err_none = max_rel_var_err(run(ticks))
    err_prod = max_rel_var_err(run(ticks, rebuild_every=64))
    # (a) anchored moments keep unrebuilt drift bounded even at 1e6 magnitude
    assert err_none < 5e-2, f"unrebuilt drift exploded: {err_none}"
    # (b) the production rotation keeps it at least 5x tighter than none
    assert err_prod < err_none / 5 or err_prod < 1e-4, (err_prod, err_none)
