"""Trace conformance (ISSUE 8 satellite): real worker runs replay as
valid paths of the protocol models.

The models verify the protocol; this suite pins the models to the
IMPLEMENTATION. The worker, run for real (in-process over the memory
broker in tier-1; as a kill−9'd subprocess over the durable spool in the
``slow`` tier), emits a protocol event log; the conformance checker
(analysis/protocol/conformance.py) steps a deterministic mirror of the
ALO + delta-chain model semantics through it and reports every
transition the models do not allow. Green means the chaos runs ARE model
paths; the negative tests prove the checker rejects the classic broken
orderings, so green is not vacuous.
"""

from __future__ import annotations

import pytest

from apmbackend_tpu.analysis.protocol import check_protocol_trace, read_event_log
from apmbackend_tpu.config import default_config
from apmbackend_tpu.runtime.module_base import ModuleRuntime
from apmbackend_tpu.runtime.worker import WorkerApp
from apmbackend_tpu.testing.chaos import ChaosChannel, ChaosWorkerHarness
from apmbackend_tpu.transport.base import QueueManager
from apmbackend_tpu.transport.memory import MemoryBroker, MemoryChannel

from test_chaos_harness import make_stream


def _mk_worker(tmp_path, broker, *, dup_p=0.0):
    ev = str(tmp_path / "events.jsonl")
    cfg = default_config()
    eng = cfg["tpuEngine"]
    eng["serviceCapacity"] = 32
    eng["samplesPerBucket"] = 32
    eng["deliveryMode"] = "atLeastOnce"
    eng["metricsPort"] = None
    eng["protocolEventLog"] = ev
    eng["resumeFileFullPath"] = str(tmp_path / "resume.npz")
    cfg["streamCalcZScore"]["defaults"] = [
        {"LAG": 6, "THRESHOLD": 3.0, "INFLUENCE": 0.1}]
    cfg["streamProcessAlerts"]["alertsResumeFileFullPath"] = None
    cfg["logDir"] = None

    runtime = ModuleRuntime("tpuEngine", config=cfg, broker=broker)

    def factory(direction):
        ch = MemoryChannel(broker)
        if direction == "c" and dup_p > 0:
            return ChaosChannel(ch, dup_p=dup_p, seed=11)
        return ch

    runtime.qm = QueueManager(factory, 3600, logger=runtime.logger)
    worker = WorkerApp(runtime)
    return worker, runtime, ev


# ----------------------------------------------------------- fast (tier-1)

def test_clean_run_replays_as_model_path(tmp_path):
    broker = MemoryBroker()
    worker, runtime, ev = _mk_worker(tmp_path, broker)
    prod = QueueManager(lambda d: MemoryChannel(broker), 3600).get_queue(
        "transactions", "p")
    for line in make_stream(n_labels=3, per_label=20):
        prod.write_line(line)
    broker.pump()
    worker.save_state()
    worker.shutdown()
    runtime.stop_timers()

    events = read_event_log(ev)
    kinds = {e["ev"] for e in events}
    assert {"recover", "deliver", "feed", "checkpoint", "ack"} <= kinds
    assert check_protocol_trace(events) == []


def test_bounce_redelivery_and_dups_replay_as_model_path(tmp_path):
    """Redelivery + chaos duplicates — the interleavings the ALO model
    enumerates — conform when the real worker produces them."""
    broker = MemoryBroker()
    worker, runtime, ev = _mk_worker(tmp_path, broker, dup_p=0.5)
    prod = QueueManager(lambda d: MemoryChannel(broker), 3600).get_queue(
        "transactions", "p")
    lines = make_stream(n_labels=3, per_label=15)
    half = len(lines) // 2
    for line in lines[:half]:
        prod.write_line(line)
    broker.pump()
    worker.save_state()  # epoch 1: committed + acked
    for line in lines[half:]:
        prod.write_line(line)
    broker.pump()
    broker.bounce()  # redeliver the unacked second half
    broker.pump()
    worker.save_state()  # epoch 2
    worker.shutdown()
    runtime.stop_timers()

    events = read_event_log(ev)
    deliv = [e for e in events if e["ev"] == "deliver"]
    assert any(e["dedup"] for e in deliv), "chaos produced no duplicates?"
    assert any(e.get("redelivered") for e in deliv)
    assert check_protocol_trace(events) == []


def test_conformance_rejects_broken_orderings():
    """The checker's teeth: each classic protocol violation is reported
    when spliced into an otherwise-plausible log."""
    base = [{"ev": "recover", "epoch": 0, "chain_epoch": None}]

    # ack before any checkpoint of that epoch
    v = check_protocol_trace(base + [{"ev": "ack", "n": 1, "epoch": 1}])
    assert any("ack-after-checkpoint" in x for x in v)

    # epoch jump
    v = check_protocol_trace(base + [
        {"ev": "checkpoint", "ok": True, "epoch": 2}])
    assert any("monotonic" in x for x in v)

    # commit with undrained pending feed
    v = check_protocol_trace(base + [
        {"ev": "deliver", "msg": "a", "dedup": False, "tx": True},
        {"ev": "checkpoint", "ok": True, "epoch": 1}])
    assert any("undrained" in x for x in v)

    # dedup of an unknown message
    v = check_protocol_trace(base + [
        {"ev": "deliver", "msg": "ghost", "dedup": True, "tx": True}])
    assert any("NOT in the dedup window" in x for x in v)

    # double absorb of a committed message (the double-effect shape)
    v = check_protocol_trace(base + [
        {"ev": "deliver", "msg": "a", "dedup": False, "tx": True},
        {"ev": "feed", "n": 1},
        {"ev": "checkpoint", "ok": True, "epoch": 1},
        {"ev": "crash"},
        {"ev": "recover", "epoch": 1},
        {"ev": "deliver", "msg": "a", "dedup": False, "tx": True}])
    assert any("double effect" in x or "already in the window" in x for x in v)

    # worker events from a dead process
    v = check_protocol_trace(base + [
        {"ev": "crash"},
        {"ev": "deliver", "msg": "a", "dedup": False, "tx": True}])
    assert any("after a crash marker" in x for x in v)

    # recovery past the committed boundary
    v = check_protocol_trace(base + [
        {"ev": "checkpoint", "ok": True, "epoch": 1},
        {"ev": "crash"},
        {"ev": "recover", "epoch": 3}])
    assert any("past the last committed" in x for x in v)

    # recovery losing committed epochs without an injected corruption
    v = check_protocol_trace(base + [
        {"ev": "checkpoint", "ok": True, "epoch": 1},
        {"ev": "ack", "n": 1, "epoch": 1},
        {"ev": "crash"},
        {"ev": "recover", "epoch": 0}])
    assert any("below the boundary" in x for x in v)


def test_conformance_allows_one_epoch_back_per_corruption():
    events = [
        {"ev": "recover", "epoch": 0, "chain_epoch": 0},
        {"ev": "checkpoint", "ok": True, "epoch": 1, "chain_epoch": 1},
        {"ev": "crash"},
        {"ev": "corrupt", "mode": "truncate"},
        {"ev": "recover", "epoch": 0, "chain_epoch": 0},
    ]
    assert check_protocol_trace(events) == []


def test_torn_event_log_tail_is_tolerated(tmp_path):
    p = tmp_path / "ev.jsonl"
    p.write_text('{"ev":"recover","epoch":0}\n{"ev":"deliver","ms')
    events = read_event_log(str(p))
    assert [e["ev"] for e in events] == ["recover"]


# --------------------------------------------------- slow: kill−9 subprocess

@pytest.mark.slow
def test_kill9_chaos_run_replays_as_model_path(tmp_path):
    """The acceptance scenario: the REAL worker subprocess, killed −9
    twice mid-stream under duplicate injection, restarted, run to
    completion — its protocol event log is a valid path of the models."""
    lines = make_stream(n_labels=6, per_label=80)
    h = ChaosWorkerHarness(str(tmp_path / "work"), dup_p=0.03, seed=5,
                           save_every_s=0.3, event_log=True)
    try:
        for line in lines:
            h.send_line(line)
        h.start()
        h.wait_acked(len(lines) // 3)
        h.kill9()
        h.start()
        h.wait_acked(2 * len(lines) // 3)
        h.kill9()
        h.start()
        stats = h.finish(timeout_s=240)
    finally:
        h.close()
    assert stats["acked"] == len(lines)

    events = h.events()
    kinds = {e["ev"] for e in events}
    assert "crash" in kinds and "recover" in kinds
    # three boots: the initial one + one per kill
    assert sum(1 for e in events if e["ev"] == "recover") == 3
    violations = check_protocol_trace(events)
    assert violations == [], "\n".join(violations)


@pytest.mark.slow
def test_kill9_delta_chain_with_stale_dup_replays_as_model_path(tmp_path):
    """Hostile storage on the delta chain: kill −9, plant a stale
    duplicate tail between generations, restart — recovery must REJECT
    the dup (uid/epoch linkage) and continue from the true committed
    tail, and the event log (with the harness's corrupt marker) replays
    as a model path.

    Note the scenario choice: a TORN tail is only within the storage
    contract in the commit-without-ack window (test_chaos_storage
    constructs that window explicitly) — tearing an acked epoch's
    segment is real loss, and the conformance checker rightly flags it
    (that is exactly its job). A stale dup is safe to inject at any
    boundary because recovery never replays it."""
    lines = make_stream(n_labels=6, per_label=80)
    h = ChaosWorkerHarness(str(tmp_path / "work"), seed=7, save_every_s=0.3,
                           checkpoint_mode="delta", event_log=True)
    try:
        for line in lines:
            h.send_line(line)
        h.start()
        h.wait_acked(len(lines) // 2)
        h.kill9()
        h.corrupt_chain_tail("stale-dup")
        h.start()
        stats = h.finish(timeout_s=240)
    finally:
        h.close()
    assert stats["acked"] == len(lines)
    assert stats["checkpoint_mode"] == "delta"

    events = h.events()
    assert any(e["ev"] == "corrupt" for e in events)
    violations = check_protocol_trace(events)
    assert violations == [], "\n".join(violations)
