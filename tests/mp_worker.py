"""Worker process for the two-process jax.distributed smoke test.

Each of two processes owns 2 virtual CPU devices; the 4-device service-axis
mesh spans both. The worker initializes the distributed runtime through the
PRODUCTION entry point (multihost.init_distributed, env-var driven), builds
the sharded engine with jit out_shardings (no host-side global device_put —
the multi-host-correct way), ingests a DISTINCT per-host batch through the
all-to-all exchange, ticks, and asserts the pod rollup counted both hosts'
records. Run by tests/test_multihost_procs.py; argv: <coordinator_port>
<process_id>.
"""

import os
import sys

PORT, PID = sys.argv[1], int(sys.argv[2])
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
os.environ["JAX_ENABLE_X64"] = "True"
# the production wiring init_distributed() reads:
os.environ["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{PORT}"
os.environ["JAX_NUM_PROCESSES"] = "2"
os.environ["JAX_PROCESS_ID"] = str(PID)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from apmbackend_tpu.parallel.multihost import (  # noqa: E402
    build_send_blocks,
    host_shard_plan,
    init_distributed,
    make_exchange_ingest,
    place_global,
)

assert init_distributed() is True, "two-process env must initialize distributed"

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 4 and len(jax.local_devices()) == 2

from apmbackend_tpu.parallel import make_mesh, make_sharded_tick  # noqa: E402
from apmbackend_tpu.parallel.sharded import _params_specs, _state_specs  # noqa: E402
from apmbackend_tpu.pipeline import engine_init, make_demo_engine  # noqa: E402

CAPACITY, B = 64, 48
cfg, _, _ = make_demo_engine(CAPACITY, 8, [(4, 3.0, 0.1)])
mesh = make_mesh(4)


def _shardings(spec_tree):
    return jax.tree.map(
        lambda sp: NamedSharding(mesh, sp), spec_tree,
        is_leaf=lambda x: isinstance(x, P),
    )


state = jax.jit(
    lambda: engine_init(cfg), out_shardings=_shardings(_state_specs(cfg))
)()


def _make_params():
    from apmbackend_tpu.pipeline import EngineParams

    S = CAPACITY
    return EngineParams(
        thresholds=(jnp.full(S, 3.0, jnp.float32),),
        influences=(jnp.full(S, 0.1, jnp.float32),),
        hard_max_ms=jnp.full(S, 10000.0, jnp.float32),
        suppressed=jnp.zeros(S, bool),
        active=jnp.ones(S, bool),
    )


params = jax.jit(_make_params, out_shardings=_shardings(_params_specs(cfg)))()

tick = make_sharded_tick(mesh, cfg)
exchange = make_exchange_ingest(mesh, cfg)
plan = host_shard_plan(mesh, CAPACITY)
assert plan.n_local == 2 and plan.n_shards == 4

label = 170_000_001
_em, _roll, state = tick(state, jnp.int32(label), params)

# DISTINCT per-host batches: host 0 sends rows hashed one way, host 1 another
rng = np.random.RandomState(100 + PID)
rows = rng.randint(0, CAPACITY, B).astype(np.int32)
elaps = (100 + 50 * rng.rand(B)).astype(np.float32)
blocks, dropped = build_send_blocks(
    plan, rows, np.full(B, label, np.int32), elaps, np.ones(B, bool),
    capacity=CAPACITY, batch_per_shard=B,
)
assert dropped == 0
state = exchange(state, *place_global(mesh, blocks))

# tick until `label` enters the stats window so the rollup counts the batch
emission, rollup, state = tick(
    state, jnp.int32(label + cfg.stats.buffer_sz + 1), params
)
total = int(jax.device_get(rollup.total_tx))
# BOTH hosts' batches must arrive: 2 * B records across the pod
assert total == 2 * B, f"proc {PID}: rollup {total} != {2 * B}"

# the STAGED pod executor with the per-addressable-shard NATIVE percentile
# stage, under real process boundaries: each host selects percentiles only
# for its own shards and contributes them via make_array_from_process_local
# _data (sharded.py make_sharded_step). The r4 VERDICT flagged this layout
# as written-for-multi-host but never executed that way.
from apmbackend_tpu import native as _native  # noqa: E402
from apmbackend_tpu.parallel import make_sharded_step  # noqa: E402

staged = make_sharded_step(mesh, cfg)
# gate on the EXECUTOR's decision (exposed as .native_pct), not a partial
# re-derivation of its predicate — percentile_impl/backend/contiguity all
# participate in make_sharded_step's gate
if _native.have_native_percentiles() and hasattr(staged, "native_pct"):
    em2, roll2, state = staged(state, label + cfg.stats.buffer_sz + 2, params)
    total2 = int(jax.device_get(roll2.total_tx))
    assert total2 == 2 * B, f"proc {PID}: staged rollup {total2} != {2 * B}"
    assert staged.native_pct.native_pct_ticks >= 1, (
        f"proc {PID}: native percentile stage never ran under 2 processes"
    )
    # the native-selected percentiles must agree with the in-program path:
    # re-run the SAME window through the mono tick (stale label => stats
    # unchanged) and compare this host's addressable rows
    em3, _roll3, state = tick(
        state, jnp.int32(label + cfg.stats.buffer_sz + 2), params
    )
    for a, b in zip(em2.average.addressable_shards, em3.average.addressable_shards):
        xa, xb = np.asarray(a.data), np.asarray(b.data)
        assert np.array_equal(
            np.nan_to_num(xa, nan=-1), np.nan_to_num(xb, nan=-1)
        ), f"proc {PID}: staged-native vs mono emission mismatch"
    suffix = f" native_pct_ticks={staged.native_pct.native_pct_ticks}"
else:  # pragma: no cover - no toolchain
    suffix = " native_pct=skipped"

# DIVERGENT-CAPABILITY scenario: simulate host 1's toolchain being broken.
# The executor choice must be POD-GLOBAL (sharded.py allgather) — without
# it host 0 would build the native-stage executor while host 1 builds the
# fused one, and the first tick would deadlock in mismatched collectives.
# Meaningful only when the FIRST executor actually went native (otherwise
# both hosts were already fused and the downgrade path never runs).
if hasattr(staged, "native_pct"):
    if PID == 1:
        os.environ["APM_DISABLE_NATIVE_PCT"] = "1"
    staged2 = make_sharded_step(mesh, cfg)
    assert not hasattr(staged2, "native_pct"), (
        f"proc {PID}: one host lost native capability but this host still "
        "built the native-stage executor — the pod-global agreement failed"
    )
    em4, roll4, state = staged2(state, label + cfg.stats.buffer_sz + 3, params)
    total4 = int(jax.device_get(roll4.total_tx))
    assert total4 == 2 * B, f"proc {PID}: divergent-gate rollup {total4} != {2 * B}"
    os.environ.pop("APM_DISABLE_NATIVE_PCT", None)
    suffix += " divergent_gate=agreed-fused"
else:  # pragma: no cover - no toolchain on this machine
    suffix += " divergent_gate=skipped"

# FUSED-executor agreement (round 6): the executor KIND is part of the
# dispatch sequence, so it rides the same pod-global agreement as the
# native-percentile capability. Scenario 1 — divergent request (only host 0
# asks for fused): every host must downgrade to staged and still tick.
if PID == 0:
    os.environ["APM_TICK_EXECUTOR"] = "fused"
div = make_sharded_step(mesh, cfg)
assert div.kind != "fused", (
    f"proc {PID}: one host did not request the fused executor but this host "
    "built it — the pod-global executor agreement failed"
)
em5, roll5, state = div(state, label + cfg.stats.buffer_sz + 4, params)
assert int(jax.device_get(roll5.total_tx)) == 2 * B
# Scenario 2 — unanimous request: the single-dispatch fused sharded step
# (advance_span + integrated staggered rebuild + ICI rollup) must agree
# with the staged path's rollup over the same window.
os.environ["APM_TICK_EXECUTOR"] = "fused"
fused = make_sharded_step(mesh, cfg)
os.environ.pop("APM_TICK_EXECUTOR", None)
assert fused.kind == "fused" and fused.rebuild_integrated
em6, roll6, state = fused(state, label + cfg.stats.buffer_sz + 5, params)
assert int(jax.device_get(roll6.total_tx)) == 2 * B, (
    f"proc {PID}: fused sharded rollup {int(jax.device_get(roll6.total_tx))} != {2 * B}"
)
suffix += " fused_gate=divergent-staged+unanimous-fused"

print(f"MP_SMOKE_OK proc={PID} total={total}{suffix}", flush=True)
