"""Worker process for the two-process jax.distributed smoke test.

Each of two processes owns 2 virtual CPU devices; the 4-device service-axis
mesh spans both. The worker initializes the distributed runtime through the
PRODUCTION entry point (multihost.init_distributed, env-var driven), builds
the sharded engine with jit out_shardings (no host-side global device_put —
the multi-host-correct way), ingests a DISTINCT per-host batch through the
all-to-all exchange, ticks, and asserts the pod rollup counted both hosts'
records. Run by tests/test_multihost_procs.py; argv: <coordinator_port>
<process_id>.
"""

import os
import sys

PORT, PID = sys.argv[1], int(sys.argv[2])
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
os.environ["JAX_ENABLE_X64"] = "True"
# the production wiring init_distributed() reads:
os.environ["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{PORT}"
os.environ["JAX_NUM_PROCESSES"] = "2"
os.environ["JAX_PROCESS_ID"] = str(PID)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from apmbackend_tpu.parallel.multihost import (  # noqa: E402
    build_send_blocks,
    host_shard_plan,
    init_distributed,
    make_exchange_ingest,
    place_global,
)

assert init_distributed() is True, "two-process env must initialize distributed"

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 4 and len(jax.local_devices()) == 2

from apmbackend_tpu.parallel import make_mesh, make_sharded_tick  # noqa: E402
from apmbackend_tpu.parallel.sharded import _params_specs, _state_specs  # noqa: E402
from apmbackend_tpu.pipeline import engine_init, make_demo_engine  # noqa: E402

CAPACITY, B = 64, 48
cfg, _, _ = make_demo_engine(CAPACITY, 8, [(4, 3.0, 0.1)])
mesh = make_mesh(4)


def _shardings(spec_tree):
    return jax.tree.map(
        lambda sp: NamedSharding(mesh, sp), spec_tree,
        is_leaf=lambda x: isinstance(x, P),
    )


state = jax.jit(
    lambda: engine_init(cfg), out_shardings=_shardings(_state_specs(cfg))
)()


def _make_params():
    from apmbackend_tpu.pipeline import EngineParams

    S = CAPACITY
    return EngineParams(
        thresholds=(jnp.full(S, 3.0, jnp.float32),),
        influences=(jnp.full(S, 0.1, jnp.float32),),
        hard_max_ms=jnp.full(S, 10000.0, jnp.float32),
        suppressed=jnp.zeros(S, bool),
        active=jnp.ones(S, bool),
    )


params = jax.jit(_make_params, out_shardings=_shardings(_params_specs(cfg)))()

tick = make_sharded_tick(mesh, cfg)
exchange = make_exchange_ingest(mesh, cfg)
plan = host_shard_plan(mesh, CAPACITY)
assert plan.n_local == 2 and plan.n_shards == 4

label = 170_000_001
_em, _roll, state = tick(state, jnp.int32(label), params)

# DISTINCT per-host batches: host 0 sends rows hashed one way, host 1 another
rng = np.random.RandomState(100 + PID)
rows = rng.randint(0, CAPACITY, B).astype(np.int32)
elaps = (100 + 50 * rng.rand(B)).astype(np.float32)
blocks, dropped = build_send_blocks(
    plan, rows, np.full(B, label, np.int32), elaps, np.ones(B, bool),
    capacity=CAPACITY, batch_per_shard=B,
)
assert dropped == 0
state = exchange(state, *place_global(mesh, blocks))

# tick until `label` enters the stats window so the rollup counts the batch
emission, rollup, state = tick(
    state, jnp.int32(label + cfg.stats.buffer_sz + 1), params
)
total = int(jax.device_get(rollup.total_tx))
# BOTH hosts' batches must arrive: 2 * B records across the pod
assert total == 2 * B, f"proc {PID}: rollup {total} != {2 * B}"
print(f"MP_SMOKE_OK proc={PID} total={total}", flush=True)
