"""Profiling harness (utils/profiling.py): heap snapshots, OOM hook,
module-runtime wiring. The profiler server itself is only smoke-tested (port
bind is environment-dependent)."""

import json
import os
import signal
import sys

import pytest

from apmbackend_tpu.utils.profiling import Profiling, heap_snapshot


def test_heap_snapshot_contents(tmp_path):
    path = heap_snapshot(str(tmp_path), "worker")
    assert path is not None and os.path.exists(path)
    assert os.path.basename(path).startswith("worker-")
    assert path.endswith(".heapsnapshot.json")
    with open(path) as fh:
        snap = json.load(fh)
    assert snap["gc_objects"] > 0
    assert "devices" in snap and isinstance(snap["devices"], list)
    assert snap["rss_kb"] is None or snap["rss_kb"] > 0


def test_snapshot_includes_tracemalloc_sites(tmp_path):
    p = Profiling("m", {"heapSnapshotDir": str(tmp_path), "traceAllocations": True})
    p.install(install_signal=False)
    try:
        hog = [bytearray(4096) for _ in range(100)]  # noqa: F841 - make allocations
        path = p.dump()
        with open(path) as fh:
            snap = json.load(fh)
        assert snap["traced_current_bytes"] > 0
        assert len(snap["top_sites"]) > 0
    finally:
        p.uninstall()


def test_memoryerror_hook_dumps_and_chains(tmp_path):
    seen = []
    prev = sys.excepthook
    sys.excepthook = lambda *a: seen.append(a)
    p = Profiling("oom", {"heapSnapshotDir": str(tmp_path)})
    p.install(install_signal=False)
    try:
        sys.excepthook(MemoryError, MemoryError("boom"), None)
        dumps = [f for f in os.listdir(tmp_path) if f.startswith("oom-")]
        assert len(dumps) == 1
        assert len(seen) == 1  # chained to the previous hook
        # non-OOM exceptions do not dump
        sys.excepthook(ValueError, ValueError("x"), None)
        dumps = [f for f in os.listdir(tmp_path) if f.startswith("oom-")]
        assert len(dumps) == 1
    finally:
        p.uninstall()
        sys.excepthook = prev


@pytest.mark.skipif(not hasattr(signal, "SIGUSR2"), reason="no SIGUSR2")
def test_sigusr2_dump_via_module_runtime(tmp_path):
    from apmbackend_tpu.config import default_config
    from apmbackend_tpu.runtime.module_base import ModuleRuntime

    cfg = default_config()
    cfg["logDir"] = str(tmp_path)
    rt = ModuleRuntime("streamCalcStats", config=cfg, install_signals=True)
    try:
        os.kill(os.getpid(), signal.SIGUSR2)
        import time

        deadline = time.time() + 3
        while time.time() < deadline:
            if any(".heapsnapshot.json" in f for f in os.listdir(tmp_path)):
                break
            time.sleep(0.05)
        assert any(".heapsnapshot.json" in f for f in os.listdir(tmp_path))
    finally:
        rt.profiling.uninstall()


def test_profiler_server_start(tmp_path):
    p = Profiling("srv", {"heapSnapshotDir": str(tmp_path)})
    ok = p.start_profiler_server(19377)
    # jax profiler server may be unavailable in some builds; only assert the
    # call is safe and reports a boolean
    assert ok in (True, False)
