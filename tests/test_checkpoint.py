"""Sharded checkpoint save/restore (parallel/checkpoint.py) on the virtual
8-device mesh: roundtrip parity, mesh re-placement, signature guards,
retention, and corrupt/absent handling."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apmbackend_tpu.parallel import make_mesh, shard_rows
from apmbackend_tpu.parallel.checkpoint import ShardedCheckpointer
from apmbackend_tpu.pipeline import engine_ingest, engine_tick, make_demo_engine


@pytest.fixture
def engine():
    cfg, state, params = make_demo_engine(16, 8, [(4, 20.0, 0.1), (8, 15.0, 0.0)])
    # advance a few ticks so state is non-trivial
    rng = np.random.RandomState(0)
    label = 1000
    tick = jax.jit(engine_tick, static_argnums=1)
    ingest = jax.jit(engine_ingest, static_argnums=1)
    for _ in range(6):
        label += 1
        _, state = tick(state, cfg, label, params)
        rows = rng.randint(0, 16, 64).astype(np.int32)
        state = ingest(state, cfg, rows, np.full(64, label, np.int32),
                       (100 + rng.rand(64) * 50).astype(np.float32), np.ones(64, bool))
    return cfg, state, params


REGISTRY = (("srvA", "svc1"), ("srvA", "svc2"), ("srvB", "svc1"))


def assert_state_equal(a, b):
    """Bit-equality on every PERSISTED leaf. The sliding z-score aggregates
    are derived state (checkpoint strips them; restore rebuilds from the
    ring via build_agg), so they are compared semantically: counts exact,
    sums to fp tolerance (tree-reduce vs incremental summation order), and
    the restart of the drift clock / conservative run-length are by design."""
    from apmbackend_tpu.parallel.checkpoint import _strip_agg

    fa, _ = jax.tree_util.tree_flatten(_strip_agg(a))
    fb, _ = jax.tree_util.tree_flatten(_strip_agg(b))
    assert len(fa) == len(fb)
    for x, y in zip(fa, fb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    for za, zb in zip(a.zscores, b.zscores):
        assert (za.agg is None) == (zb.agg is None)
        if za.agg is not None:
            np.testing.assert_array_equal(np.asarray(za.agg.cnt), np.asarray(zb.agg.cnt))
            np.testing.assert_allclose(
                np.asarray(za.agg.vsum), np.asarray(zb.agg.vsum), rtol=1e-5, atol=1e-5
            )
            np.testing.assert_array_equal(
                np.asarray(za.agg.last_push), np.asarray(zb.agg.last_push)
            )


def test_roundtrip_unsharded(tmp_path, engine):
    cfg, state, _ = engine
    ckpt = ShardedCheckpointer(str(tmp_path / "ck"))
    ckpt.save(7, state, cfg, REGISTRY)
    out = ckpt.restore(cfg)
    assert out is not None
    restored, registry, step = out
    assert step == 7 and registry == REGISTRY
    assert_state_equal(state, restored)
    ckpt.close()


def test_legacy_per_row_cursor_snapshot_migrates(tmp_path, engine):
    """A pre-global-cursor orbax snapshot (z-score pos saved per-row, [S])
    restores via the legacy template and the per-row rings are rotated onto
    the shared cursor bit-exactly (checkpoint._migrate_per_row_cursors)."""
    import orbax.checkpoint as ocp

    from apmbackend_tpu.parallel.checkpoint import _shape_signature, _strip_agg

    cfg, state, params = engine  # 6 ticks; lags 4 and 8
    # craft the legacy representation: per-row write slots w_r, rings rotated
    # so old[k] = new[(k - w) % L] — the inverse of the migration, which must
    # therefore reproduce `state` exactly. The current global cursor must be
    # 0 for the comparison, so advance to a lag-multiple tick count first.
    tick = jax.jit(engine_tick, static_argnums=1)
    label = 2000
    for _ in range(8 - 6 % 8):  # engine fixture ran 6 ticks; reach 8 (0 mod 4 and 8)
        label += 1
        _, state = tick(state, cfg, label, params)
    rng = np.random.RandomState(3)
    legacy_zs = []
    for z, spec in zip(state.zscores, cfg.lags):
        assert int(np.asarray(z.pos)) == 0
        L = spec.lag
        fill = np.asarray(z.fill)
        w = np.where(fill >= L, rng.randint(0, L, fill.shape[0]), np.minimum(fill, L - 1))
        new_vals = np.asarray(z.values)
        k = np.arange(L)[None, :]
        old_vals = np.empty_like(new_vals)
        idx = (k - w[:, None]) % L  # old[k] = new[(k - w) % L]
        old_vals[:] = np.take_along_axis(new_vals, idx[:, None, :], axis=2)
        # faithful legacy node: THREE keys only — the old ZScoreState had no
        # 'agg' field, and orbax treats even an agg=None key as a different
        # tree structure
        legacy_zs.append(
            {"values": jnp.asarray(old_vals), "fill": z.fill, "pos": jnp.asarray(w.astype(np.int32))}
        )
    legacy_tree = _strip_agg(state)._asdict()
    legacy_tree["zscores"] = tuple(legacy_zs)

    ckpt = ShardedCheckpointer(str(tmp_path / "ck"))
    meta = {"signature": _shape_signature(cfg), "registry": ["srvA\x00svc1"]}
    ckpt.manager.save(
        3,
        args=ocp.args.Composite(
            state=ocp.args.StandardSave(legacy_tree), meta=ocp.args.JsonSave(meta)
        ),
    )
    ckpt.wait()
    out = ckpt.restore(cfg)
    assert out is not None, "legacy per-row-cursor snapshot must be restorable"
    restored, _, step = out
    assert step == 3
    for z, rz in zip(state.zscores, restored.zscores):
        assert np.asarray(rz.pos).ndim == 0 and int(np.asarray(rz.pos)) == 0
        np.testing.assert_array_equal(np.asarray(z.values), np.asarray(rz.values))
        np.testing.assert_array_equal(np.asarray(z.fill), np.asarray(rz.fill))
    # and it steps under the current engine
    em, _ = tick(restored, cfg, label + 1, params)
    jax.block_until_ready(em.tpm)
    ckpt.close()


def test_roundtrip_sharded_placement(tmp_path, engine):
    cfg, state, params = engine
    mesh = make_mesh(8)
    sharded = shard_rows(state, mesh)
    ckpt = ShardedCheckpointer(str(tmp_path / "ck"))
    ckpt.save(1, sharded, cfg, REGISTRY)
    out = ckpt.restore(cfg, mesh=mesh)
    assert out is not None
    restored, _, _ = out
    assert_state_equal(state, restored)
    # restored arrays actually live on the mesh with row sharding
    shards = restored.stats.counts.sharding.device_set
    assert len(shards) == 8
    # and the restored state steps (shape/placement sanity)
    em, _ = jax.jit(engine_tick, static_argnums=1)(restored, cfg, 2000, params)
    jax.block_until_ready(em.tpm)
    ckpt.close()


def test_pod_snapshot_restores_on_single_device(tmp_path, engine):
    # scale-down/debug resume: saved sharded on the 8-mesh, restored with
    # mesh=None must place on one device (not re-apply the pod sharding)
    cfg, state, _ = engine
    mesh = make_mesh(8)
    ckpt = ShardedCheckpointer(str(tmp_path / "ck"))
    ckpt.save(1, shard_rows(state, mesh), cfg, REGISTRY)
    out = ckpt.restore(cfg)  # no mesh
    assert out is not None
    restored, _, _ = out
    assert_state_equal(state, restored)
    assert len(restored.stats.counts.sharding.device_set) == 1
    ckpt.close()


def test_falls_back_to_older_step_when_newest_corrupt(tmp_path, engine):
    import shutil

    cfg, state, _ = engine
    ckpt = ShardedCheckpointer(str(tmp_path / "ck"), keep=2)
    ckpt.save(1, state, cfg, REGISTRY)
    two = jax.tree_util.tree_map(lambda x: x, state)
    ckpt.save(2, two, cfg, REGISTRY)
    ckpt.wait()
    # corrupt the newest step's array data
    step_dir = tmp_path / "ck" / "2" / "state"
    assert step_dir.exists()
    shutil.rmtree(step_dir)
    out = ckpt.restore(cfg)
    assert out is not None
    _, _, step = out
    assert step == 1
    ckpt.close()


def test_signature_mismatch_returns_none(tmp_path, engine):
    cfg, state, _ = engine
    ckpt = ShardedCheckpointer(str(tmp_path / "ck"))
    ckpt.save(1, state, cfg, REGISTRY)
    other_cfg, _, _ = make_demo_engine(16, 8, [(4, 20.0, 0.1), (16, 15.0, 0.0)])
    assert ckpt.restore(other_cfg) is None  # different lag set
    other_cap, _, _ = make_demo_engine(32, 8, [(4, 20.0, 0.1), (8, 15.0, 0.0)])
    assert ckpt.restore(other_cap) is None  # different capacity
    ckpt.close()


def test_retention_keeps_latest(tmp_path, engine):
    cfg, state, _ = engine
    ckpt = ShardedCheckpointer(str(tmp_path / "ck"), keep=2)
    for step in (1, 2, 3):
        ckpt.save(step, state, cfg, REGISTRY)
    assert ckpt.latest_step() == 3
    assert sorted(ckpt.manager.all_steps()) == [2, 3]
    ckpt.close()


def test_empty_directory_returns_none(tmp_path, engine):
    cfg, _, _ = engine
    ckpt = ShardedCheckpointer(str(tmp_path / "empty"))
    assert ckpt.restore(cfg) is None
    ckpt.close()


def test_ring_dtype_mismatch_refuses_restore(tmp_path, engine):
    """A bf16-ring config must not resume an f32-ring snapshot (array dtypes
    differ), while the default config's signature stays key-compatible with
    snapshots saved before ring_dtype existed."""
    import jax.numpy as jnp

    from apmbackend_tpu.parallel.checkpoint import _shape_signature

    cfg, state, _ = engine
    assert "ring_dtype" not in _shape_signature(cfg)  # default: legacy-compatible
    ckpt = ShardedCheckpointer(str(tmp_path / "ck"))
    ckpt.save(1, state, cfg, REGISTRY)
    bf16_cfg = cfg._replace(zscore_ring_dtype=jnp.bfloat16)
    assert _shape_signature(bf16_cfg)["ring_dtype"] == "bfloat16"
    assert ckpt.restore(bf16_cfg) is None
    assert ckpt.restore(cfg) is not None
    ckpt.close()


def test_pre_holt_snapshot_restores_with_zero_trend(tmp_path):
    """Upgrade path: an orbax snapshot saved by the pre-Holt build (EwmaState
    without the ``trend`` leaf) must restore with trend zero-filled — learned
    baselines survive the upgrade, matching load_resume's npz fallback."""
    import orbax.checkpoint as ocp

    from apmbackend_tpu.parallel.checkpoint import _shape_signature

    chan = {"ALPHA": 0.3, "THRESHOLD": 3.0, "WARMUP": 2, "CHANNEL_ID": -1}
    cfg, state, params = make_demo_engine(16, 8, [(4, 20.0, 0.1)], ewma_channels=[chan])
    # move the ewma state off init values
    label = 1000
    tick = jax.jit(engine_tick, static_argnums=1)
    ingest = jax.jit(engine_ingest, static_argnums=1)
    rng = np.random.RandomState(1)
    for _ in range(12):  # > buffer_sz so ingested data enters the stats window
        label += 1
        _, state = tick(state, cfg, label, params)
        state = ingest(state, cfg, rng.randint(0, 16, 64).astype(np.int32),
                       np.full(64, label, np.int32),
                       (100 + rng.rand(64) * 50).astype(np.float32), np.ones(64, bool))
    assert int(np.asarray(state.ewmas[0].count).sum()) > 0

    # write the snapshot the way the pre-Holt build ACTUALLY serialized it:
    # 3-field ewma nodes (no 'trend') AND 3-field zscore nodes (no 'agg'
    # key, per-row [S] cursors — pre-Holt also predates sliding and the
    # global cursor)
    legacy_tree = state._asdict()
    legacy_tree["ewmas"] = tuple(
        {"mean": e.mean, "var": e.var, "count": e.count} for e in state.ewmas
    )
    legacy_tree["zscores"] = tuple(
        {
            "values": z.values,
            "fill": z.fill,
            "pos": jnp.broadcast_to(z.pos, z.fill.shape),  # per-row cursors
        }
        for z in state.zscores
    )
    ckpt = ShardedCheckpointer(str(tmp_path / "ck"))
    meta = {"signature": _shape_signature(cfg), "registry": ["srvA\x00svc1"]}
    ckpt.manager.save(
        5,
        args=ocp.args.Composite(
            state=ocp.args.StandardSave(legacy_tree),
            meta=ocp.args.JsonSave(meta),
        ),
    )
    ckpt.wait()

    out = ckpt.restore(cfg)
    assert out is not None, "legacy snapshot must be restorable"
    restored, registry, step = out
    assert step == 5 and registry == (("srvA", "svc1"),)
    np.testing.assert_array_equal(
        np.asarray(state.ewmas[0].count), np.asarray(restored.ewmas[0].count)
    )
    np.testing.assert_allclose(
        np.nan_to_num(np.asarray(state.ewmas[0].mean)),
        np.nan_to_num(np.asarray(restored.ewmas[0].mean)),
    )
    np.testing.assert_array_equal(
        np.zeros_like(np.asarray(state.ewmas[0].trend)), np.asarray(restored.ewmas[0].trend)
    )
    # and the restored state steps under the Holt-aware engine
    em, _ = jax.jit(engine_tick, static_argnums=1)(restored, cfg, label + 1, params)
    jax.block_until_ready(em.tpm)
    ckpt.close()
