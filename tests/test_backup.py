"""Backup tool (tools/backup.py — backup.sh parity): hourly-stamped copies,
re-run overwrite within the hour, retention pruning, CLI."""

import os
import time

from apmbackend_tpu.tools import backup


def make_tree(root):
    (root / "a.py").write_text("A")
    (root / "pkg").mkdir()
    (root / "pkg" / "b.py").write_text("B")
    (root / "skip.txt").write_text("no")


def test_backup_copies_matching_globs(tmp_path):
    src = tmp_path / "src"
    src.mkdir()
    make_tree(src)
    dest = tmp_path / "bk"
    copied = backup.run_backup(str(dest), ("*.py", "pkg/*.py"), root=str(src), now=0)
    assert len(copied) == 2
    stamped = dest / backup.stamp(0)
    assert (stamped / "a.py").read_text() == "A"
    assert (stamped / "pkg" / "b.py").read_text() == "B"
    assert not (stamped / "skip.txt").exists()


def test_rerun_same_hour_overwrites(tmp_path):
    src = tmp_path / "src"
    src.mkdir()
    make_tree(src)
    dest = tmp_path / "bk"
    backup.run_backup(str(dest), ("*.py",), root=str(src), now=0)
    (src / "a.py").write_text("A2")
    backup.run_backup(str(dest), ("*.py",), root=str(src), now=60)  # same hour
    assert (dest / backup.stamp(0) / "a.py").read_text() == "A2"
    assert len(os.listdir(dest)) == 1


def test_prune_removes_old_folders(tmp_path):
    dest = tmp_path / "bk"
    old = dest / "20200101_00"
    new = dest / "20990101_00"
    old.mkdir(parents=True)
    new.mkdir(parents=True)
    past = time.time() - 10 * 86400
    os.utime(old, (past, past))
    removed = backup.prune(str(dest), days=7)
    assert [os.path.basename(p) for p in removed] == ["20200101_00"]
    assert new.exists() and not old.exists()


def test_cli(tmp_path, capsys, monkeypatch):
    src = tmp_path / "src"
    src.mkdir()
    make_tree(src)
    rc = backup.main(["--dir", str(tmp_path / "bk"), "--glob", "*.py",
                      "--root", str(src), "--prune-days", "7"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Backed up 1 files" in out
