"""SpoolChannel durability audit (ISSUE 7 satellite).

The spool's cursor is the broker-side commit record the whole kill−9 story
rests on: it must be atomic under SIGKILL at any byte (tmp + rename), its
tmp must not be shareable with a zombie predecessor process (pid suffix),
and a torn leftover must never corrupt recovery.
"""

import json
import os

import pytest

from apmbackend_tpu.transport.spool import SpoolChannel, _SpoolQueue, read_spool_cursor


def _fill(tmp_path, n=5):
    ch = SpoolChannel(str(tmp_path))
    for i in range(n):
        ch.send("q", f"m{i}".encode(), {"msg_id": f"h-{i}"})
    got = []
    ch.consume("q", lambda p, h, tok: got.append(tok), "t", manual_ack=True)
    ch.deliver()
    return ch, got


def test_cursor_persist_is_atomic_against_crash_midwrite(tmp_path, monkeypatch):
    """SIGKILL between tmp write and rename == os.replace never ran: the
    cursor file must still hold the PREVIOUS committed value, and the torn
    tmp must be ignored by the next boot."""
    ch, tokens = _fill(tmp_path)
    ch.ack(tokens[:2])
    assert read_spool_cursor(str(tmp_path), "q") == 2

    real_replace = os.replace

    def crash_before_rename(src, dst):
        raise RuntimeError("SIGKILL stand-in: process died before the rename")

    monkeypatch.setattr(os, "replace", crash_before_rename)
    with pytest.raises(RuntimeError):
        ch.ack(tokens[2:4])
    monkeypatch.setattr(os, "replace", real_replace)
    # old cursor intact; the torn tmp exists but is ignored on recovery
    assert read_spool_cursor(str(tmp_path), "q") == 2
    tmps = [n for n in os.listdir(tmp_path) if ".tmp" in n]
    assert tmps, "expected the torn tmp left behind by the crash"
    q2 = _SpoolQueue(str(tmp_path), "q")
    assert q2.acked_upto == 2  # redelivery restarts at the committed cursor
    ch.close()


def test_cursor_tmp_is_pid_suffixed(tmp_path, monkeypatch):
    """Regression: the pre-audit constant ``<cursor>.tmp`` name let a
    not-quite-dead predecessor interleave writes into the SAME tmp file a
    restarted consumer was committing through."""
    seen = []
    real_replace = os.replace

    def spy(src, dst):
        seen.append(src)
        return real_replace(src, dst)

    monkeypatch.setattr(os, "replace", spy)
    ch, tokens = _fill(tmp_path)
    ch.ack(tokens)
    assert seen and all(f".{os.getpid()}.tmp" in s for s in seen)
    ch.close()


def test_torn_cursor_json_redelivers_from_zero(tmp_path):
    ch, tokens = _fill(tmp_path)
    ch.ack(tokens)
    ch.close()
    cursor = os.path.join(str(tmp_path), "q.cursor")
    open(cursor, "w").write('{"acked": ')  # torn JSON
    assert read_spool_cursor(str(tmp_path), "q") == 0
    q = _SpoolQueue(str(tmp_path), "q")
    assert q.acked_upto == 0  # safe: redeliver everything, dedup absorbs


def test_fsync_knob(tmp_path):
    """fsync=True hardens cursor + spool appends; semantics unchanged."""
    ch = SpoolChannel(str(tmp_path), fsync=True)
    for i in range(3):
        ch.send("q", f"m{i}".encode(), {"msg_id": f"h-{i}"})
    toks = []
    ch.consume("q", lambda p, h, tok: toks.append(tok), "t", manual_ack=True)
    ch.deliver()
    ch.ack(toks)
    assert read_spool_cursor(str(tmp_path), "q") == 3
    assert json.load(open(os.path.join(str(tmp_path), "q.cursor")))["acked"] == 3
    ch.close()


def test_testing_chaos_reexport():
    """Moved to transport/spool.py; the old import path keeps working."""
    from apmbackend_tpu.testing import chaos

    assert chaos.SpoolChannel is SpoolChannel
    assert chaos.read_spool_cursor is read_spool_cursor
