"""The `smoke` debug CLI (tools/smoke.py) — §2.4 manual-harness parity, run
against the fake/sqlite backends and dry-run HTTP."""

import json

import pytest

from apmbackend_tpu.tools import smoke


def _cfg(tmp_path, backend="fake"):
    from apmbackend_tpu.config import default_config

    cfg = default_config()
    cfg["streamInsertDb"]["dbBackend"] = backend
    if backend == "sqlite":
        cfg["streamInsertDb"]["dbFileFullPath"] = str(tmp_path / "smoke.db")
    cfg["grafana"]["grafanaURL"] = "http://grafana.example:3000"
    return cfg


def test_smoke_db_fake(tmp_path, capsys):
    import sys

    assert smoke.smoke_db(_cfg(tmp_path), sys.stdout) == 0
    out = capsys.readouterr().out
    assert "inserted 2 rows" in out
    assert "fake executor holds 2 rows" in out


def test_smoke_db_sqlite(tmp_path, capsys):
    import sqlite3
    import sys

    cfg = _cfg(tmp_path, backend="sqlite")
    assert smoke.smoke_db(cfg, sys.stdout) == 0
    out = capsys.readouterr().out
    assert "inserted 2 rows" in out and "sqlite" in out
    con = sqlite3.connect(cfg["streamInsertDb"]["dbFileFullPath"])
    n = con.execute("SELECT COUNT(*) FROM tx").fetchone()[0]
    con.close()
    assert n == 2


def test_smoke_annotation_dry_run(tmp_path, capsys):
    import sys

    assert smoke.smoke_annotation(
        _cfg(tmp_path), sys.stdout, dry_run=True, text="hello"
    ) == 0
    out = capsys.readouterr().out
    assert "/api/annotations" in out
    body = json.loads(out.strip().splitlines()[-1])
    assert body["text"] == "hello" and "maintenance" in body["tags"]


def test_smoke_annotation_requires_url(tmp_path, capsys):
    import sys

    cfg = _cfg(tmp_path)
    cfg["grafana"]["grafanaURL"] = ""
    assert smoke.smoke_annotation(cfg, sys.stdout, dry_run=True, text="x") == 1


def test_smoke_render_dry_run_builds_urls(tmp_path, capsys):
    import sys

    assert smoke.smoke_render(_cfg(tmp_path), sys.stdout, dry_run=True, email_to=None) == 0
    out = capsys.readouterr().out
    assert "/render" in out
    assert "var-server=smoke" in out
    assert "var-service=smoke_test" in out and "var-service=other_svc" in out
    assert "var-lag=360" in out and "var-lag=8640" in out


def test_smoke_paths_pattern(tmp_path, capsys):
    import sys

    cfg = _cfg(tmp_path)
    cfg["streamParseTransactions"]["serverFromPathPattern"] = r"_([A-Za-z0-9]+)\.log$"
    assert smoke.smoke_paths(cfg, sys.stdout, ["/x/wildfly_jvm07.log", "/x/other.txt"]) == 0
    out = capsys.readouterr().out
    assert "'jvm07'" in out and "(no match)" in out


def test_smoke_cli_dispatch(tmp_path, capsys, monkeypatch):
    # through the real argv entry point, config from file
    cfg = _cfg(tmp_path)
    path = str(tmp_path / "cfg.json")
    with open(path, "w") as fh:
        json.dump(cfg, fh)
    assert smoke.main(["db", "--config", path]) == 0
    assert "inserted 2 rows" in capsys.readouterr().out
    assert smoke.main(["paths", "--config", path, "/a/b_jvm01.log"]) == 0
    assert "jvm01" in capsys.readouterr().out


def test_smoke_registered_in_dispatcher():
    from apmbackend_tpu.__main__ import COMMANDS

    assert COMMANDS["smoke"] == ("apmbackend_tpu.tools.smoke", True)


def test_demo_detects_injected_regression(tmp_path):
    """The demo CLI end-to-end: the injected regression is detected and only
    that service alerts (exit code contract)."""
    from apmbackend_tpu.tools import demo

    rc = demo.run_demo(str(tmp_path), n_tx=900, bad_service="getOffers", factor=10.0)
    assert rc == 0


def test_fixture_anomaly_injection():
    """write_fixture_logs(anomaly=...): only the chosen service's tail
    regresses; the others' distributions are unchanged vs no-anomaly run."""
    import re
    import tempfile

    from apmbackend_tpu.ingest.replay import write_fixture_logs

    def elapsed_by_service(paths):
        out = {}
        rx = re.compile(r"(?:EJB (\S+) call: (\d+) ms|Stop (\S+) completed in time: (\d+) ms)")
        for p in paths.values():
            for line in open(p, encoding="utf-8"):
                m = rx.search(line)
                if m:
                    svc = m.group(1) or m.group(3)
                    out.setdefault(svc, []).append(int(m.group(2) or m.group(4)))
        return out

    with tempfile.TemporaryDirectory() as d1, tempfile.TemporaryDirectory() as d2:
        base = elapsed_by_service(write_fixture_logs(d1, n_transactions=400, seed=5))
        anom = elapsed_by_service(write_fixture_logs(
            d2, n_transactions=400, seed=5,
            anomaly={"service": "getOffers", "start_frac": 0.5, "factor": 10.0},
        ))
    assert base["getAccountInfo"] == anom["getAccountInfo"]  # untouched
    assert max(anom["getOffers"]) > max(base["getOffers"]) * 5  # tail regressed
    # the pre-anomaly head is intact: at least the first half of the base
    # values survive unchanged (multiset intersection — per-file collection
    # order is not chronological)
    from collections import Counter

    common = sum((Counter(anom["getOffers"]) & Counter(base["getOffers"])).values())
    assert common >= len(base["getOffers"]) // 3
