"""A faithful in-process fake of the redis-py surface RedisStreamsChannel uses.

Models the Redis Streams behaviors the at-least-once stack depends on:

- streams as append-only entry lists with monotonic ``"<seq>-0"`` ids;
  XADD MAXLEN trimming removes the OLDEST entries (the silent-loss hazard
  the channel's send-side refusal exists to stay ahead of);
- consumer groups with a ``last-delivered-id`` read cursor and a real PEL
  (pending entries list): XREADGROUP ``">"`` delivers only entries past the
  cursor and records each in the PEL; XACK removes PEL entries (idempotent
  — re-acking returns 0, never raises);
- XAUTOCLAIM as the redelivery path: PEL entries idle longer than
  ``min_idle_time`` are re-claimed (delivery counter bumped) and handed to
  the caller; PEL entries whose underlying stream entry was trimmed away
  come back in the *deleted* list, exactly like Redis >= 7.0
  (``server.redis62 = True`` emulates the 6.2 two-element reply);
- XINFO GROUPS exposing ``pending`` + ``lag`` (the backlog a group still
  owes), the channel's refusal and queue-lag input — and raising
  ``ERR no such key`` for a stream no XADD has created yet, exactly like
  a real server;
- a kill/restart seam: ``kill()`` severs every live connection (clients
  raise ConnectionError until a NEW client is built after ``restart()``),
  while streams, groups, and the PEL survive — AOF-persistence semantics,
  so recovery is a reconnect + XAUTOCLAIM cycle, never a data reload.

Idle time is virtual: ``advance_ms`` ages the PEL without sleeping, so
redelivery tests run in microseconds.

Usage: ``server = FakeRedisServer(); mod = make_fake_redis(server)`` and
pass ``redis_module=mod`` to RedisStreamsChannel.
"""

from __future__ import annotations

import threading
import time
from types import SimpleNamespace
from typing import Dict, List, Optional, Tuple


class _FakeRedisError(Exception):
    pass


class _FakeConnectionError(_FakeRedisError):
    pass


class _FakeResponseError(_FakeRedisError):
    pass


class _Group:
    """One consumer group on one stream: read cursor + pending entries list."""

    def __init__(self, last_seq: int):
        self.last_seq = last_seq  # seq of the last entry delivered via ">"
        # entry id -> [consumer, last_delivery_ms, delivery_count]
        self.pel: Dict[str, list] = {}


class FakeRedisServer:
    def __init__(self):
        self.lock = threading.RLock()
        # stream name -> ordered [(id, fields)] — trimming pops the front
        self.streams: Dict[str, List[Tuple[str, dict]]] = {}
        self._seq: Dict[str, int] = {}
        self.groups: Dict[Tuple[str, str], _Group] = {}
        self.down = False
        # bumped by kill(): clients carry the epoch they were built under and
        # a stale client keeps raising after restart() — a severed TCP
        # connection never comes back; the channel must build a new client
        self.epoch = 0
        # pre-7.0 mode: XAUTOCLAIM replies (next, claimed) with no third
        # deleted-entries element, like Redis 6.2
        self.redis62 = False
        self._skew_ms = 0.0
        self.add_count = 0
        self.ack_count = 0
        self.claim_count = 0
        self.trimmed_count = 0
        self.kill_count = 0
        self.xinfo_count = 0

    # -- virtual clock -------------------------------------------------------
    def now_ms(self) -> float:
        with self.lock:
            return time.monotonic() * 1000.0 + self._skew_ms

    def advance_ms(self, ms: float) -> None:
        """Age every PEL entry by ``ms`` without sleeping."""
        with self.lock:
            self._skew_ms += ms

    # -- chaos seam ----------------------------------------------------------
    def kill(self) -> None:
        """Broker process death: every live client starts raising and stays
        dead even after restart (its connection is gone); stream + group
        state persists (AOF semantics)."""
        with self.lock:
            self.down = True
            self.epoch += 1
            self.kill_count += 1

    def restart(self) -> None:
        with self.lock:
            self.down = False

    # -- introspection for tests --------------------------------------------
    def stream_len(self, name: str) -> int:
        with self.lock:
            return len(self.streams.get(name, ()))

    def pending_count(self, name: str, group: str = "apm") -> int:
        with self.lock:
            g = self.groups.get((name, group))
            return len(g.pel) if g else 0

    # -- ops (called by FakeRedisClient under self.lock) ---------------------
    def _check_up(self, client_epoch: int) -> None:
        if self.down:
            raise _FakeConnectionError("fake redis is down")
        if client_epoch != self.epoch:
            raise _FakeConnectionError("connection severed by broker restart")

    def _entry_seq(self, entry_id: str) -> int:
        return int(str(entry_id).split("-")[0])

    def xadd(self, name: str, fields: dict, maxlen: Optional[int]) -> str:
        seq = self._seq.get(name, 0) + 1
        self._seq[name] = seq
        entry_id = f"{seq}-0"
        self.streams.setdefault(name, []).append((entry_id, dict(fields)))
        self.add_count += 1
        if maxlen is not None:
            stream = self.streams[name]
            while len(stream) > maxlen:
                stream.pop(0)
                self.trimmed_count += 1
        return entry_id

    def xgroup_create(self, name: str, group: str, id: str, mkstream: bool) -> bool:
        if (name, group) in self.groups:
            raise _FakeResponseError(
                "BUSYGROUP Consumer Group name already exists")
        if name not in self.streams:
            if not mkstream:
                raise _FakeResponseError(
                    "NOGROUP no such key; use MKSTREAM to create it")
            self.streams[name] = []
            self._seq.setdefault(name, 0)
        last = self._seq.get(name, 0) if id in ("$",) else 0
        self.groups[(name, group)] = _Group(last)
        return True

    def xreadgroup(self, group: str, consumer: str, name: str,
                   count: Optional[int]) -> List[Tuple[str, dict]]:
        g = self.groups.get((name, group))
        if g is None:
            raise _FakeResponseError("NOGROUP no such consumer group")
        out: List[Tuple[str, dict]] = []
        now = self.now_ms()
        for entry_id, fields in self.streams.get(name, ()):
            if self._entry_seq(entry_id) <= g.last_seq:
                continue
            out.append((entry_id, dict(fields)))
            g.last_seq = self._entry_seq(entry_id)
            g.pel[entry_id] = [consumer, now, 1]
            if count is not None and len(out) >= count:
                break
        return out

    def xack(self, name: str, group: str, ids) -> int:
        g = self.groups.get((name, group))
        if g is None:
            return 0
        removed = 0
        for entry_id in ids:
            if g.pel.pop(str(entry_id), None) is not None:
                removed += 1
                self.ack_count += 1
        return removed

    def xautoclaim(self, name: str, group: str, consumer: str,
                   min_idle_ms: float, count: int):
        """(next_start_id, [(id, fields)...] claimed, [deleted ids])."""
        g = self.groups.get((name, group))
        if g is None:
            raise _FakeResponseError("NOGROUP no such consumer group")
        entries = {eid: f for eid, f in self.streams.get(name, ())}
        now = self.now_ms()
        claimed: List[Tuple[str, dict]] = []
        deleted: List[str] = []
        for entry_id in sorted(g.pel, key=self._entry_seq):
            if len(claimed) >= count:
                break
            if entry_id not in entries:
                # trimmed out from under the PEL: Redis drops the PEL entry
                # and reports the id in the deleted list — visible data loss
                deleted.append(entry_id)
                del g.pel[entry_id]
                continue
            owner, ts, n = g.pel[entry_id]
            if now - ts < min_idle_ms:
                continue
            g.pel[entry_id] = [consumer, now, n + 1]
            claimed.append((entry_id, dict(entries[entry_id])))
            self.claim_count += 1
        return "0-0", claimed, deleted

    def xinfo_groups(self, name: str) -> List[dict]:
        self.xinfo_count += 1
        if name not in self.streams:
            # real Redis errors here rather than answering [] — the channel
            # must treat a nonexistent stream as zero backlog itself
            raise _FakeResponseError("ERR no such key")
        out = []
        for (stream, group), g in self.groups.items():
            if stream != name:
                continue
            lag = sum(
                1 for eid, _f in self.streams.get(name, ())
                if self._entry_seq(eid) > g.last_seq)
            out.append({"name": group, "pending": len(g.pel), "lag": lag})
        return out


class FakeRedisClient:
    """One connection. Built via ``make_fake_redis(server).Redis.from_url``;
    carries the server epoch at creation so a broker kill permanently severs
    it (the channel's reconnect path must build a fresh client)."""

    def __init__(self, server: FakeRedisServer):
        self._server = server
        with server.lock:
            self._epoch = server.epoch

    def _srv(self) -> FakeRedisServer:
        self._server._check_up(self._epoch)
        return self._server

    def ping(self) -> bool:
        with self._server.lock:
            self._srv()
            return True

    def xadd(self, name, fields, id="*", maxlen=None, approximate=False):
        with self._server.lock:
            return self._srv().xadd(name, fields, maxlen)

    def xlen(self, name) -> int:
        with self._server.lock:
            return len(self._srv().streams.get(name, ()))

    def xgroup_create(self, name, groupname, id="$", mkstream=False):
        with self._server.lock:
            return self._srv().xgroup_create(name, groupname, id, mkstream)

    def xreadgroup(self, groupname, consumername, streams, count=None, block=None):
        with self._server.lock:
            srv = self._srv()
            out = []
            for name, cursor in streams.items():
                if cursor != ">":
                    continue  # channel only reads new entries
                entries = srv.xreadgroup(groupname, consumername, name, count)
                if entries:
                    out.append([name, entries])
            return out

    def xack(self, name, groupname, *ids) -> int:
        with self._server.lock:
            return self._srv().xack(name, groupname, ids)

    def xautoclaim(self, name, groupname, consumername, min_idle_time,
                   start_id="0-0", count=100):
        with self._server.lock:
            resp = self._srv().xautoclaim(
                name, groupname, consumername, min_idle_time, count)
            # Redis 6.2 drops trimmed PEL entries without reporting them
            return resp[:2] if self._server.redis62 else resp

    def xinfo_groups(self, name):
        with self._server.lock:
            return self._srv().xinfo_groups(name)

    def close(self) -> None:
        pass


def make_fake_redis(server: FakeRedisServer):
    """A module-like object exposing the redis-py surface the channel uses."""

    def from_url(url: str, **kw):
        with server.lock:
            if server.down:
                raise _FakeConnectionError("fake redis is down")
        return FakeRedisClient(server)

    exceptions = SimpleNamespace(
        RedisError=_FakeRedisError,
        ConnectionError=_FakeConnectionError,
        ResponseError=_FakeResponseError,
    )
    return SimpleNamespace(
        Redis=SimpleNamespace(from_url=from_url),
        exceptions=exceptions,
    )
