"""Supervisor: child lifecycle, crash-loop damping, memory watchdog plumbing,
alert batching, log retention (apm_manager.js roles)."""

import os
import time

import pytest

from apmbackend_tpu.config import default_config
from apmbackend_tpu.manager.manager import ManagerAlerts, ManagerApp, ModuleProc
from apmbackend_tpu.manager.pid_stats import pid_exists, pids_matching_cmdline, pss_swap_mb
from apmbackend_tpu.runtime.module_base import ModuleRuntime


@pytest.fixture
def sleeper_env(tmp_path):
    """A tiny importable module tree for spawning real children."""
    (tmp_path / "sleeper_mod.py").write_text("import time\nwhile True: time.sleep(0.2)\n")
    (tmp_path / "crasher_mod.py").write_text("import sys\nsys.exit(3)\n")
    return {"PYTHONPATH": str(tmp_path)}


def wait_until(cond, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return False


# -- pid_stats ---------------------------------------------------------------

def test_pss_swap_self():
    mem, swap = pss_swap_mb(os.getpid())
    assert mem is not None and mem > 1.0  # a python process uses >1 MiB
    assert swap is not None and swap >= 0.0


def test_pss_swap_missing_pid():
    assert pss_swap_mb(2 ** 22 + 12345) == (None, None)


def test_pid_exists_self_and_missing():
    assert pid_exists(os.getpid())
    assert not pid_exists(2 ** 22 + 12345)


# -- ModuleProc --------------------------------------------------------------

def test_module_proc_start_and_stop(tmp_path, sleeper_env):
    mod = ModuleProc(
        {"module": "sleeper_mod"},
        log_dir=str(tmp_path / "logs"),
        config_path=None,
        extra_env=sleeper_env,
    )
    mod.start_process()
    assert mod.pid is not None and pid_exists(mod.pid)
    assert mod.tick() is None  # healthy: no event
    # stdout redirect file exists (start.log role)
    assert os.path.exists(tmp_path / "logs" / "sleeper_mod.start.log")
    mod.stop()
    assert mod.proc is None


def test_module_proc_crash_loop_damping(tmp_path, sleeper_env):
    now = [1000.0]
    mod = ModuleProc(
        {"module": "crasher_mod"},
        log_dir=str(tmp_path / "logs"),
        config_path=None,
        clock=lambda: now[0],
        extra_env=sleeper_env,
    )
    mod.start_process()
    assert wait_until(lambda: mod.poll_exit() is not None)
    now[0] += 2.0  # "exited" 2 s after start => crash loop
    assert mod.tick() == "exited"
    assert mod.restart_pending_until == now[0] + 60.0
    # not restarted before the damping window elapses
    now[0] += 30.0
    assert mod.tick() is None and mod.pid is None
    now[0] += 31.0
    assert mod.tick() == "restarted"
    assert mod.pid is not None
    mod.stop()


def test_module_proc_fast_restart_when_not_crash_loop(tmp_path, sleeper_env):
    now = [1000.0]
    mod = ModuleProc(
        {"module": "crasher_mod"},
        log_dir=str(tmp_path / "logs"),
        config_path=None,
        clock=lambda: now[0],
        extra_env=sleeper_env,
    )
    mod.start_process()
    assert wait_until(lambda: mod.poll_exit() is not None)
    now[0] += 100.0  # ran "100 s" before exiting: normal restart in 1 s
    assert mod.tick() == "exited"
    assert mod.restart_pending_until == now[0] + 1.0
    mod.restart_pending_until = 0.0  # cancel to avoid spawning again
    assert mod.tick() is None


def test_kill_existing_pids(tmp_path, sleeper_env):
    mod = ModuleProc(
        {"module": "sleeper_mod"},
        log_dir=str(tmp_path / "logs"),
        config_path=None,
        extra_env=sleeper_env,
    )
    mod.start_process()
    pid = mod.pid
    assert wait_until(lambda: pids_matching_cmdline(mod.cmdline_pattern()) != [])
    killed = mod.kill_existing_pids()
    assert killed >= 1
    # reap: in the test the child belongs to pytest, so it would linger as a
    # zombie (which pid_exists counts as alive); production stale PIDs are
    # never our children
    mod.proc.wait(timeout=5)
    assert wait_until(lambda: not pid_exists(pid))


# -- ManagerAlerts -----------------------------------------------------------

def test_manager_alerts_interval_doubling():
    sent = []
    cfg = {
        "emailsEnabled": True,
        "alertCollectionIntervalInSeconds": 60,
        "increaseCollectionIntervalAfterAlert": True,
        "maxCollectionIntervalInSeconds": 240,
    }
    alerts = ManagerAlerts(cfg, email_sender=lambda s, h, i: sent.append((s, h)))
    alerts.add("disk low")
    alerts.add("queue deep")
    count, nxt = alerts.flush(60)
    assert count == 2 and nxt == 120
    assert "disk low" in sent[0][1] and "queue deep" in sent[0][1]
    # empty flush resets to base
    count, nxt = alerts.flush(nxt)
    assert count == 0 and nxt == 60
    # doubling caps at max
    alerts.add("x")
    _, nxt = alerts.flush(240)
    assert nxt == 240


def test_manager_alerts_no_email_retains_buffer():
    alerts = ManagerAlerts({"emailsEnabled": False}, email_sender=None)
    alerts.add("kept")
    count, _ = alerts.flush()
    assert count == 0 and alerts.buffer == ["kept"]


# -- ManagerApp --------------------------------------------------------------

def make_manager(tmp_path, **mcfg_overrides):
    cfg = default_config()
    cfg["logDir"] = str(tmp_path / "logs")
    cfg["applicationManager"]["moduleSettings"] = []
    cfg["applicationManager"].update(mcfg_overrides)
    runtime = ModuleRuntime("applicationManager", config=cfg, install_signals=False, console_log=False)
    app = ManagerApp(runtime, spawn_children=False)
    return app, runtime


def test_disk_inspection_thresholds(tmp_path):
    app, _rt = make_manager(tmp_path, diskSpaceGBAvailableThreshold=10 ** 9)
    app.inspect_disk_space()  # absurd threshold: always triggers
    assert any("disk space is low" in m.lower() for m in app.alerts.buffer)


def test_cleanup_logs(tmp_path):
    app, rt = make_manager(tmp_path, appLogRetentionDays=7)
    log_dir = rt.config["logDir"]
    os.makedirs(log_dir, exist_ok=True)
    old = os.path.join(log_dir, "ancient.log")
    new = os.path.join(log_dir, "fresh.log")
    for p in (old, new):
        open(p, "w").write("x")
    os.utime(old, (time.time() - 10 * 86400, time.time() - 10 * 86400))
    removed = app.cleanup_logs()
    assert removed == 1
    assert not os.path.exists(old) and os.path.exists(new)


def test_module_setting_override(tmp_path):
    app, _rt = make_manager(tmp_path)
    mod = ModuleProc({"module": "x", "moduleMemoryAlertThreshold": 700},
                     log_dir=str(tmp_path), config_path=None)
    assert app.module_setting(mod, "moduleMemoryAlertThreshold") == 700
    mod2 = ModuleProc({"module": "y"}, log_dir=str(tmp_path), config_path=None)
    assert app.module_setting(mod2, "moduleMemoryAlertThreshold") == 350


def test_manager_alerts_interval_never_overshoots_cap():
    """Doubling from a base that doesn't power-of-two into the cap must clamp
    at the cap, not sail past it (60 -> 120 -> 240 -> 300, never 480)."""
    cfg = {
        "emailsEnabled": True,
        "alertCollectionIntervalInSeconds": 60,
        "increaseCollectionIntervalAfterAlert": True,
        "maxCollectionIntervalInSeconds": 300,
    }
    alerts = ManagerAlerts(cfg, email_sender=lambda s, h, i: None)
    interval = 60.0
    seen = []
    for _ in range(6):
        alerts.add("x")
        _, interval = alerts.flush(interval)
        seen.append(interval)
    assert seen == [120, 240, 300, 300, 300, 300]


def test_cmdline_pattern_matches_both_launch_forms():
    import re

    from apmbackend_tpu.manager.manager import cmdline_pattern_for

    pat = cmdline_pattern_for("apmbackend_tpu.manager.manager")
    assert re.search(pat, "python -m apmbackend_tpu.manager.manager")
    assert re.search(pat, "python -m apmbackend_tpu manager")
    assert not re.search(pat, "python -m apmbackend_tpu worker")
    assert not re.search(pat, "python -m apmbackend_tpuXmanager")
    wpat = cmdline_pattern_for("apmbackend_tpu.runtime.worker")
    assert re.search(wpat, "python -m apmbackend_tpu worker --foo")
    assert not re.search(wpat, "python -m apmbackend_tpu manager")


# -- hung-tick watchdog (healthz streak -> damped restart) -------------------

class _FakeProc:
    """Stands in for a wedged-but-alive child: subprocess surface only."""

    def __init__(self, pid=4242):
        self.pid = pid
        self.returncode = None
        self.terminated = False

    def poll(self):
        return None  # alive forever (that's the point: a wedge never exits)

    def terminate(self):
        self.terminated = True
        self.returncode = -15

    def kill(self):
        self.returncode = -9

    def wait(self, timeout=None):
        return self.returncode


def make_watchdog_manager(tmp_path, monkeypatch, *, threshold=3, healthy=False):
    app, rt = make_manager(
        tmp_path,
        healthzFailureThreshold=threshold,
        moduleSettings=[{"module": "wedge_mod", "metricsPort": 19999}],
    )
    mod = app.modules[0]
    mod.proc = _FakeProc()
    now = [1000.0]
    mod.clock = lambda: now[0]
    mod.last_start_time = 0.0
    # the watchdog only probes ALIVE children
    monkeypatch.setattr("apmbackend_tpu.manager.pid_stats.pid_exists", lambda pid: True)
    app._probe_child_health = lambda url, timeout_s: healthy
    return app, mod, now


def test_watchdog_restarts_after_sustained_streak(tmp_path, monkeypatch):
    app, mod, now = make_watchdog_manager(tmp_path, monkeypatch, threshold=3)
    now[0] = 1000.0
    app.inspect_module_health()
    app.inspect_module_health()
    assert mod.proc is not None  # streak 2 < 3: still watching
    assert not mod.proc.terminated
    proc = mod.proc
    app.inspect_module_health()  # streak 3: force-restart through damped path
    assert proc.terminated
    assert mod.proc is None  # handle_exit reaped it
    assert mod.restart_pending_until > 0  # restart scheduled, damping applied
    assert any("wedged" in m for m in app.alerts.buffer)
    # counted on the watchdog counter
    assert app._m_watchdog[mod.module].value == 1


def test_watchdog_streak_resets_on_healthy_probe(tmp_path, monkeypatch):
    app, mod, _now = make_watchdog_manager(tmp_path, monkeypatch, threshold=2)
    app.inspect_module_health()  # fail: streak 1
    app._probe_child_health = lambda url, timeout_s: True
    app.inspect_module_health()  # healthy: streak resets
    app._probe_child_health = lambda url, timeout_s: False
    app.inspect_module_health()  # fail: streak 1 again — no restart
    assert mod.proc is not None and not mod.proc.terminated


def test_watchdog_respects_crash_loop_damping(tmp_path, monkeypatch):
    """A child that wedges right after starting gets the 60 s damped
    restart, exactly like a crash-looping self-exit (the existing path)."""
    app, mod, now = make_watchdog_manager(tmp_path, monkeypatch, threshold=1)
    now[0] = 1000.0
    mod.last_start_time = 998.0  # "started" 2 s ago => crash loop
    app.inspect_module_health()
    assert mod.restart_pending_until == pytest.approx(1060.0)
    # a long-lived child that wedges restarts in 1 s
    mod.proc = _FakeProc()
    mod.last_start_time = 500.0
    app.inspect_module_health()
    assert mod.restart_pending_until == pytest.approx(1001.0)


def test_watchdog_disabled_by_zero_threshold(tmp_path, monkeypatch):
    app, mod, _now = make_watchdog_manager(tmp_path, monkeypatch, threshold=0)
    for _ in range(5):
        app.inspect_module_health()
    assert mod.proc is not None and not mod.proc.terminated


def test_watchdog_skips_children_without_metrics_port(tmp_path, monkeypatch):
    app, rt = make_manager(
        tmp_path, healthzFailureThreshold=1,
        moduleSettings=[{"module": "blind_mod"}],  # no metricsPort: unwatchable
    )
    mod = app.modules[0]
    mod.proc = _FakeProc()
    monkeypatch.setattr("apmbackend_tpu.manager.pid_stats.pid_exists", lambda pid: True)
    app._probe_child_health = lambda url, timeout_s: False
    app.inspect_module_health()
    assert not mod.proc.terminated
