"""Pure-Python float64 oracles reproducing the reference modules' semantics.

These re-implement StatParser (stream_calc_stats.js:28-204) and ZScoreParser
(stream_calc_z_score.js:26-312) behavior exactly — dicts, lists, per-message —
so the batched device engine can be property-tested against them.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

from apmbackend_tpu.utils.jsmath import (
    binary_concat,
    js_average,
    js_percentile,
    js_standard_deviation,
)

NAN = float("nan")


def _nan(x: Optional[float]) -> float:
    return NAN if x is None else x


class GoldenStats:
    """Per-message bucket dicts + tick stats, reference semantics."""

    def __init__(self, window_sz=30, buffer_sz=6, interval_len=10):
        self.window_sz = window_sz
        self.buffer_sz = buffer_sz
        self.interval_len = interval_len
        self.num_keep = window_sz + buffer_sz
        self.latest_bucket = 0
        self.servers: Dict[str, Dict[str, Dict[int, List[int]]]] = {}

    def add(self, server: str, service: str, end_ts_ms: int, elapsed: int):
        """Returns list of stat rows emitted if this entry opened a new bucket."""
        label = end_ts_ms // 10000
        out = []
        if label > self.latest_bucket:
            self.latest_bucket = label
            self._remove_old()
            edge_ts = (self.latest_bucket - self.buffer_sz - 1) * 10000
            out = self.generate_all(edge_ts)
        key = self.servers.setdefault(server, {}).setdefault(service, {})
        key.setdefault(label, []).append(int(elapsed))
        return out

    def _remove_old(self):
        for services in self.servers.values():
            for buckets in services.values():
                for label in [l for l in buckets if l < self.latest_bucket - self.num_keep]:
                    del buckets[label]

    def generate_all(self, edge_ts: int):
        rows = []
        for server, services in self.servers.items():
            for service, buckets in services.items():
                cnt = 0
                total = 0.0
                sorted_elaps: List[int] = []
                for label, arr in buckets.items():
                    if (
                        label >= self.latest_bucket - self.num_keep
                        and label <= self.latest_bucket - self.buffer_sz
                    ):
                        cnt += len(arr)
                        total += sum(arr)
                        binary_concat(sorted_elaps, arr, True)
                avg = p75 = p95 = None
                if cnt != 0:
                    avg = total / cnt
                    p75 = js_percentile(sorted_elaps, 75)
                    p95 = js_percentile(sorted_elaps, 95)
                tpm = cnt / (self.window_sz * self.interval_len / 60.0)
                rows.append(
                    {
                        "ts": edge_ts, "server": server, "service": service,
                        "tpm": tpm, "average": _nan(avg), "per75": _nan(p75), "per95": _nan(p95),
                        "count": cnt,
                    }
                )
        return rows


class GoldenZScore:
    """Per-message rolling lists, reference semantics incl. influence damping."""

    def __init__(self, lag: int, threshold: float, influence: float):
        self.lag = lag
        self.threshold = threshold
        self.influence = influence
        self.lists: Dict[Tuple[str, str], Dict[str, List[float]]] = {}

    def _process_metric(self, new_value: float, lst: List[float]):
        infl_new = new_value
        avg = std = lb = ub = None
        signal = 0
        if len(lst) >= self.lag:
            avg = js_average(lst)
            std = js_standard_deviation(lst)
            # degenerate all-equal windows: zero variance exactly (the
            # reference's documented intent, util_methods.js:44-48) — the raw
            # float path makes this value-dependent luck (linear summation
            # can leave std ~ 1e-13 and signal on any deviation); the device
            # resolves it exactly via max==min, and so does the oracle
            vals = [v for v in lst if v is not None and not math.isnan(v)]
            if vals and min(vals) == max(vals):
                avg = vals[0]
                std = None
            if (avg is not None) and (std is not None):
                lb = avg - self.threshold * std
                ub = avg + self.threshold * std
            if avg is None or std is None:
                signal = 0
            elif math.isnan(new_value):
                signal = 0
            elif abs(new_value - avg) > self.threshold * std:
                signal = 1 if new_value > avg else -1
                last = lst[-1] if lst else None
                if last is not None and not math.isnan(last):
                    infl_new = self.influence * new_value + (1 - self.influence) * last
        return infl_new, _nan(avg), _nan(lb), _nan(ub), signal

    def step(self, server: str, service: str, average: float, per75: float, per95: float):
        key = (server, service)
        lists = self.lists.setdefault(key, {"avg": [], "p75": [], "p95": []})
        out = {}
        for metric, val in (("avg", average), ("p75", per75), ("p95", per95)):
            lst = lists[metric]
            infl, avg, lb, ub, sig = self._process_metric(val, lst)
            if len(lst) >= self.lag:
                lst.pop(0)
            lst.append(infl)
            out[metric] = {"avg": avg, "lb": lb, "ub": ub, "signal": sig}
        return out
