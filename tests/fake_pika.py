"""A faithful in-process fake of the pika API surface AmqpChannel uses.

Models the RabbitMQ behaviors the backpressure stack depends on:

- named durable queues holding message bodies FIFO;
- ``connection.blocked`` / ``connection.unblocked`` frames driven by a
  broker-wide depth alarm (RabbitMQ's memory/disk alarm analog): when total
  queued bodies exceed ``block_at`` every connection's blocked callback
  fires; when depth falls to ``unblock_at`` the unblocked callback fires;
- ``basic_consume`` delivery with per-connection pumping: messages are
  delivered inside ``process_data_events`` of the connection that registered
  the consumer — exactly where BlockingConnection invokes callbacks;
- ``basic_ack`` with a real per-connection UNACKED ledger: without a
  ``basic_qos`` prefetch the ledger is unbounded; with one, delivery halts at
  ``prefetch_count`` in-flight (RabbitMQ consumer-prefetch semantics). A
  connection dying (kill switch or close) requeues its unacked messages at
  the queue FRONT with the AMQP ``redelivered`` flag set — the behavior the
  at-least-once stack's dedup window exists to absorb;
- connection kill switch (``FakeBroker.kill_connections``) to exercise the
  reconnect path.

Usage: ``broker = FakeBroker(...); mod = make_fake_pika(broker)`` and pass
``pika_module=mod`` to AmqpChannel.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict, deque
from types import SimpleNamespace
from typing import Callable, Dict, List, Optional, Tuple


class _FakeAMQPError(Exception):
    pass


class _FakeConnectionError(_FakeAMQPError):
    pass


class FakeBroker:
    def __init__(self, block_at: int = 50, unblock_at: int = 10):
        self.block_at = block_at
        self.unblock_at = unblock_at
        self.lock = threading.RLock()
        self.queues: Dict[str, deque] = defaultdict(deque)
        self.declared: set = set()
        self.blocked = False
        self.connections: List["FakeBlockingConnection"] = []
        self.publish_count = 0
        self.ack_count = 0
        self.block_events = 0
        self.unblock_events = 0

    # -- depth alarm ---------------------------------------------------------
    def _total_depth_locked(self) -> int:
        return sum(len(q) for q in self.queues.values())

    def _update_alarm_locked(self) -> None:
        depth = self._total_depth_locked()
        if not self.blocked and depth >= self.block_at:
            self.blocked = True
            self.block_events += 1
            for conn in list(self.connections):
                conn._notify_blocked()
        elif self.blocked and depth <= self.unblock_at:
            self.blocked = False
            self.unblock_events += 1
            for conn in list(self.connections):
                conn._notify_unblocked()

    # -- broker ops ----------------------------------------------------------
    def publish(self, routing_key: str, body: bytes, properties=None) -> None:
        with self.lock:
            self.queues[routing_key].append((body, properties, False))
            self.publish_count += 1
            self._update_alarm_locked()

    def pop(self, queue_name: str) -> Optional[tuple]:
        """(body, properties, redelivered) of the oldest message, or None."""
        with self.lock:
            q = self.queues.get(queue_name)
            if not q:
                return None
            item = q.popleft()
            self._update_alarm_locked()
            return item

    def depth(self, queue_name: str) -> int:
        with self.lock:
            return len(self.queues.get(queue_name, ()))

    def requeue(self, queue_name: str, items) -> None:
        """Return unacked messages to the FRONT of their queue, marked
        redelivered (connection-death semantics)."""
        with self.lock:
            for body, properties, _r in reversed(list(items)):
                self.queues[queue_name].appendleft((body, properties, True))
            self._update_alarm_locked()

    def kill_connections(self) -> None:
        """Simulate a broker restart: every live connection starts raising,
        and every connection's unacked deliveries are requeued."""
        with self.lock:
            conns = list(self.connections)
            for conn in conns:
                conn._killed = True
            self.connections.clear()
        for conn in conns:
            conn._requeue_unacked()


class FakeChannel:
    def __init__(self, conn: "FakeBlockingConnection"):
        self._conn = conn
        self.is_open = True
        self._confirms = False

    def _check(self) -> None:
        if self._conn._killed or not self.is_open:
            raise _FakeConnectionError("channel/connection closed")

    def queue_declare(self, queue: str, durable: bool = False, passive: bool = False):
        self._check()
        broker = self._conn._broker
        with broker.lock:
            if passive:
                # real-broker semantics: a passive declare on a missing queue
                # closes the channel (qstat's lag observer relies on this)
                if queue not in broker.declared:
                    self.is_open = False
                    raise _FakeConnectionError(f"passive declare: no queue '{queue}'")
            else:
                broker.declared.add(queue)
            count = len(broker.queues.get(queue, ()))
        return SimpleNamespace(method=SimpleNamespace(queue=queue, message_count=count))

    def confirm_delivery(self) -> None:
        self._check()
        self._confirms = True

    def basic_qos(self, prefetch_count: int = 0) -> None:
        self._check()
        self._conn._prefetch = int(prefetch_count)

    def basic_publish(self, exchange: str, routing_key: str, body: bytes, properties=None) -> None:
        self._check()
        self._conn._broker.publish(routing_key, body, properties)

    def basic_consume(self, queue: str, on_message_callback: Callable, consumer_tag: str) -> str:
        self._check()
        self._conn._consumers[consumer_tag] = (queue, on_message_callback, self)
        return consumer_tag

    def basic_cancel(self, consumer_tag: str) -> None:
        self._check()
        self._conn._consumers.pop(consumer_tag, None)

    def basic_ack(self, delivery_tag=None) -> None:
        with self._conn._broker.lock:
            self._conn._broker.ack_count += 1
            self._conn._unacked.pop(delivery_tag, None)

    def close(self) -> None:
        self.is_open = False


class FakeBlockingConnection:
    def __init__(self, params, _broker: FakeBroker = None):
        broker = params.broker if hasattr(params, "broker") else _broker
        self._broker = broker
        self._killed = False
        self.is_open = True
        self._consumers: Dict[str, Tuple[str, Callable, FakeChannel]] = {}
        self._blocked_cbs: List[Callable] = []
        self._unblocked_cbs: List[Callable] = []
        self._threadsafe_cbs: List[Callable] = []
        self._delivery_tag = 0
        # delivery_tag -> (queue, body, properties, redelivered): the unacked
        # ledger; bounded by basic_qos prefetch, requeued on connection death
        self._unacked: Dict[int, tuple] = {}
        self._prefetch: int = 0  # 0 = unbounded (no basic_qos issued)
        with broker.lock:
            broker.connections.append(self)
            # late join while the alarm is up must still learn about it
            if broker.blocked:
                self._notify_blocked()

    def channel(self) -> FakeChannel:
        if self._killed:
            raise _FakeConnectionError("connection killed")
        return FakeChannel(self)

    def add_on_connection_blocked_callback(self, cb: Callable) -> None:
        self._blocked_cbs.append(cb)

    def add_on_connection_unblocked_callback(self, cb: Callable) -> None:
        self._unblocked_cbs.append(cb)

    def add_callback_threadsafe(self, cb: Callable) -> None:
        self._threadsafe_cbs.append(cb)

    def _notify_blocked(self) -> None:
        for cb in list(self._blocked_cbs):
            cb(self, SimpleNamespace(method="connection.blocked"))

    def _notify_unblocked(self) -> None:
        for cb in list(self._unblocked_cbs):
            cb(self, SimpleNamespace(method="connection.unblocked"))

    def process_data_events(self, time_limit: float = 0) -> None:
        if self._killed:
            raise _FakeConnectionError("connection killed")
        cbs, self._threadsafe_cbs = self._threadsafe_cbs, []
        for cb in cbs:
            cb()
        delivered = 0
        for tag, (queue_name, on_message, ch) in list(self._consumers.items()):
            while True:
                # consumer prefetch: delivery halts while the unacked ledger
                # is at the basic_qos bound (auto-ack callbacks ack inline,
                # so only manual-ack consumers ever hit it)
                if self._prefetch and len(self._unacked) >= self._prefetch:
                    break
                item = self._broker.pop(queue_name)
                if item is None:
                    break
                body, properties, redelivered = item
                self._delivery_tag += 1
                self._unacked[self._delivery_tag] = (queue_name, body, properties, redelivered)
                method = SimpleNamespace(
                    delivery_tag=self._delivery_tag, consumer_tag=tag,
                    redelivered=redelivered,
                )
                on_message(ch, method, properties or SimpleNamespace(), body)
                delivered += 1
        if delivered == 0 and time_limit:
            time.sleep(min(time_limit, 0.005))

    def _requeue_unacked(self) -> None:
        unacked, self._unacked = self._unacked, {}
        per_queue: Dict[str, list] = {}
        for tag in sorted(unacked):
            queue_name, body, properties, _r = unacked[tag]
            per_queue.setdefault(queue_name, []).append((body, properties, True))
        for queue_name, items in per_queue.items():
            self._broker.requeue(queue_name, items)

    def close(self) -> None:
        self.is_open = False
        with self._broker.lock:
            if self in self._broker.connections:
                self._broker.connections.remove(self)
        self._requeue_unacked()


def make_fake_pika(broker: FakeBroker):
    """A module-like object exposing the pika surface AmqpChannel touches."""

    def URLParameters(url: str):
        return SimpleNamespace(url=url, broker=broker)

    def BasicProperties(delivery_mode=None, **kw):
        return SimpleNamespace(delivery_mode=delivery_mode, **kw)

    exceptions = SimpleNamespace(
        AMQPError=_FakeAMQPError,
        AMQPConnectionError=_FakeConnectionError,
        UnroutableError=_FakeAMQPError,
        NackError=_FakeAMQPError,
    )
    return SimpleNamespace(
        URLParameters=URLParameters,
        BlockingConnection=FakeBlockingConnection,
        BasicProperties=BasicProperties,
        exceptions=exceptions,
    )
