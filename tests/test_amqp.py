"""AMQP backend: the full backpressure stack against a faithful pika fake.

The reference's inter-process fabric is RabbitMQ with buffered backpressure:
producer pause on full (queue.js:245-263) and drain->retry->resume
(queue.js:88-106). These tests drive that exact cycle through QueueManager +
AmqpChannel with the broker alarm, delivery, and reconnect behaviors modeled
in tests/fake_pika.py.
"""

import time

import pytest

from apmbackend_tpu.transport.amqp import AmqpChannel
from apmbackend_tpu.transport.base import QueueManager

from fake_pika import FakeBroker, make_fake_pika


def wait_for(predicate, timeout=10.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


@pytest.fixture
def broker():
    return FakeBroker(block_at=50, unblock_at=10)


def make_qm(broker, **channel_kw):
    mod = make_fake_pika(broker)
    channels = []

    def factory(kind: str):
        ch = AmqpChannel(
            "amqp://fake", direction=kind, pika_module=mod,
            poll_interval_s=0.005, **channel_kw,
        )
        channels.append(ch)
        return ch

    qm = QueueManager(factory, stat_log_interval_s=3600)
    return qm, channels


class TestPauseBufferDrainResume:
    def test_full_cycle_in_order_exactly_once(self, broker):
        # two QueueManagers = two processes (producer module, consumer module)
        # sharing one broker, like the reference's per-process queue.js
        qm_p, _ = make_qm(broker, publish_queue_max=20)
        qm_c, _ = make_qm(broker)
        events = []
        qm_p.on("pause", lambda: events.append("pause"))
        qm_p.on("resume", lambda: events.append("resume"))
        received = []

        producer = qm_p.get_queue("tx", "p")
        try:
            lines = [f"line-{i:04d}" for i in range(200)]
            for line in lines:
                producer.write_line(line)

            # the broker alarm must engage and the producer must buffer:
            # 200 lines >> block_at=50 + publish_queue_max=20
            assert wait_for(lambda: "pause" in events), events
            assert wait_for(lambda: broker.blocked)
            assert producer.buffer_count() > 0

            # now attach the consumer: draining the broker lifts the alarm,
            # the publisher drains, on_drain retries the buffers, resume fires
            consumer = qm_c.get_queue("tx", "c", lambda line: received.append(line))
            consumer.start_consume()

            assert wait_for(lambda: len(received) == len(lines), timeout=20), (
                len(received), producer.buffer_count(), broker.blocked,
            )
            assert received == lines  # FIFO preserved across pause/buffer/drain
            assert wait_for(lambda: "resume" in events), events
            assert producer.buffer_count() == 0
            assert broker.unblock_events >= 1
        finally:
            qm_p.shutdown()
            qm_c.shutdown()

    def test_send_refuses_while_broker_blocked(self, broker):
        qm, channels = make_qm(broker, publish_queue_max=500)
        producer = qm.get_queue("tx", "p")
        try:
            for i in range(80):  # > block_at with no consumer
                producer.write_line(f"l{i}")
            assert wait_for(lambda: broker.blocked)
            pchan = channels[0]
            assert wait_for(lambda: pchan.blocked)
            # a raw channel send during the alarm refuses immediately, even
            # though the outbound queue has plenty of room
            assert pchan.outbound_depth < 400
            assert pchan.send("tx", b"x") is False
        finally:
            qm.shutdown()

    def test_multiple_pressure_episodes(self, broker):
        qm, _ = make_qm(broker, publish_queue_max=10)
        qm_c, _ = make_qm(broker)
        received = []
        resumes = []
        qm.on("resume", lambda: resumes.append(1))
        producer = qm.get_queue("tx", "p")
        consumer = qm_c.get_queue("tx", "c", lambda line: received.append(line))
        try:
            total = 0
            for episode in range(2):
                for i in range(120):
                    producer.write_line(f"e{episode}-{i:03d}")
                total += 120
                consumer.start_consume()
                assert wait_for(lambda: len(received) == total, timeout=20), len(received)
                consumer.stop_consume()
                assert wait_for(lambda: producer.buffer_count() == 0)
            assert received == [f"e{e}-{i:03d}" for e in range(2) for i in range(120)]
            assert len(resumes) >= 1
        finally:
            qm.shutdown()
            qm_c.shutdown()


class TestReconnect:
    def test_publisher_and_consumer_survive_broker_restart(self, broker):
        qm, _ = make_qm(broker, publish_queue_max=100)
        qm_c, _ = make_qm(broker)
        received = []
        producer = qm.get_queue("tx", "p")
        consumer = qm_c.get_queue("tx", "c", lambda line: received.append(line))
        consumer.start_consume()
        try:
            for i in range(30):
                producer.write_line(f"a{i}")
            assert wait_for(lambda: len(received) >= 30, timeout=10), len(received)

            broker.kill_connections()  # both directions must reconnect
            for i in range(30):
                producer.write_line(f"b{i}")
            assert wait_for(
                lambda: {f"b{i}" for i in range(30)} <= set(received), timeout=20
            ), sorted(set(f"b{i}" for i in range(30)) - set(received))
            # no loss across the restart (at-least-once; dups tolerated)
            assert {f"a{i}" for i in range(30)} <= set(received)
        finally:
            qm.shutdown()
            qm_c.shutdown()


class TestChannelContract:
    def test_direction_enforcement(self, broker):
        mod = make_fake_pika(broker)
        p = AmqpChannel("amqp://fake", direction="p", pika_module=mod, poll_interval_s=0.005)
        c = AmqpChannel("amqp://fake", direction="c", pika_module=mod, poll_interval_s=0.005)
        try:
            with pytest.raises(RuntimeError):
                p.consume("q", lambda b: None, "tag")
            with pytest.raises(RuntimeError):
                c.send("q", b"x")
            with pytest.raises(ValueError):
                AmqpChannel("amqp://fake", direction="x", pika_module=mod)
        finally:
            p.close()
            c.close()

    def test_ack_on_receipt(self, broker):
        mod = make_fake_pika(broker)
        p = AmqpChannel("amqp://fake", direction="p", pika_module=mod, poll_interval_s=0.005)
        c = AmqpChannel("amqp://fake", direction="c", pika_module=mod, poll_interval_s=0.005)
        got = []
        try:
            p.assert_queue("q")
            c.consume("q", lambda b: got.append(b), "t1")
            assert p.send("q", b"m1")
            assert wait_for(lambda: got == [b"m1"])
            assert broker.ack_count == 1  # acked before the callback ran
            c.cancel("t1")
            assert p.send("q", b"m2")
            time.sleep(0.1)
            assert got == [b"m1"]  # cancelled: no further delivery
            assert broker.depth("q") == 1
        finally:
            p.close()
            c.close()

    def test_no_pika_raises_clear_error(self):
        from apmbackend_tpu.transport.amqp import HAVE_PIKA

        if HAVE_PIKA:  # pragma: no cover - this image ships without pika
            pytest.skip("pika installed: constructor would dial a real broker")
        with pytest.raises(RuntimeError, match="pika"):
            AmqpChannel("amqp://fake", direction="p")


class TestAtLeastOnce:
    def test_manual_ack_defers_until_commit(self, broker):
        qm_p, _ = make_qm(broker)
        qm_c, _ = make_qm(broker)
        got = []
        prod = qm_p.get_queue("tx", "p")
        cons = qm_c.get_queue(
            "tx", "c", lambda line, h, tok: got.append((line, h, tok)), manual_ack=True
        )
        cons.start_consume()
        try:
            for i in range(10):
                prod.write_line(f"m{i}")
            assert wait_for(lambda: len(got) == 10), len(got)
            assert broker.ack_count == 0  # nothing acked before the commit
            cons.ack([t for _l, _h, t in got])
            assert wait_for(lambda: broker.ack_count == 10), broker.ack_count
            # every delivery carried the producer's msg_id (the dedup key)
            assert all(h and h.get("msg_id") for _l, h, _t in got)
        finally:
            qm_p.shutdown()
            qm_c.shutdown()

    def test_prefetch_bounds_inflight_unacked(self, broker):
        qm_p, _ = make_qm(broker)
        qm_c, _ = make_qm(broker, prefetch_count=5)
        got = []
        prod = qm_p.get_queue("tx", "p")
        cons = qm_c.get_queue("tx", "c", lambda l, h, t: got.append(t), manual_ack=True)
        cons.start_consume()
        try:
            for i in range(20):
                prod.write_line(f"m{i}")
            assert wait_for(lambda: len(got) == 5)
            time.sleep(0.1)
            assert len(got) == 5  # delivery halted at the prefetch bound
            cons.ack(got[:5])
            assert wait_for(lambda: len(got) == 10), len(got)
        finally:
            qm_p.shutdown()
            qm_c.shutdown()

    def test_broker_bounce_redelivers_unacked_with_flag_and_stale_acks_dropped(self, broker):
        qm_p, _ = make_qm(broker)
        qm_c, _ = make_qm(broker)
        got = []
        prod = qm_p.get_queue("tx", "p")
        cons = qm_c.get_queue(
            "tx", "c", lambda line, h, tok: got.append((line, h, tok)), manual_ack=True
        )
        cons.start_consume()
        try:
            for i in range(6):
                prod.write_line(f"m{i}")
            assert wait_for(lambda: len(got) == 6)
            first = list(got)
            first_ids = [h["msg_id"] for _l, h, _t in first]
            broker.kill_connections()  # unacked requeued, connections die
            assert wait_for(lambda: len(got) >= 12, timeout=20), len(got)
            redelivered = got[6:12]
            # FIFO preserved, redelivered flag set, ORIGINAL msg ids carried
            assert [l for l, _h, _t in redelivered] == [f"m{i}" for i in range(6)]
            assert all(h.get("redelivered") for _l, h, _t in redelivered)
            assert [h["msg_id"] for _l, h, _t in redelivered] == first_ids
            # stale tokens (dead generation) are silently dropped...
            pre = broker.ack_count
            cons.ack([t for _l, _h, t in first])
            time.sleep(0.2)
            assert broker.ack_count == pre
            # ...while current-generation tokens commit
            cons.ack([t for _l, _h, t in redelivered])
            assert wait_for(lambda: broker.ack_count == pre + 6), broker.ack_count
        finally:
            qm_p.shutdown()
            qm_c.shutdown()


class TestReconnectJitter:
    def test_decorrelated_jitter_bounds_and_spread(self, broker):
        import random

        mod = make_fake_pika(broker)
        ch = AmqpChannel(
            "amqp://fake", direction="p", pika_module=mod, poll_interval_s=0.005,
            reconnect_max_backoff_s=10.0, jitter_rng=random.Random(42),
        )
        try:
            prev, draws = 0.5, []
            for _ in range(200):
                prev = ch._next_backoff(prev)
                draws.append(prev)
                assert 0.5 <= prev <= 10.0  # [base, cap] envelope
            # decorrelated: not a deterministic doubling ladder (many draws
            # saturate at the cap, which is fine — the climb must be jittered)
            assert len({round(d, 6) for d in draws}) > 50
            # two channels with different rngs do NOT march in lockstep
            ch2_rng = random.Random(43)
            ch._jitter = ch2_rng
            other = [ch._next_backoff(0.5) for _ in range(5)]
            assert draws[:5] != other
        finally:
            ch.close()
