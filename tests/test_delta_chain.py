"""Incremental delta-checkpoint chain: segment format, torn/corrupt-tail
recovery, and the bit-identical equivalence contract.

The core claim (ISSUE 7): for any feed/tick/commit interleave, restoring
``base + ordered deltas`` is bit-identical to (a) a full snapshot of the
same driver and (b) an independent driver that replayed the same stream —
including capacity growth mid-epoch, label jumps past the bucket ring,
EWMA seasonal channels, bf16 rings, and compaction with concurrent
appends. Corruption of the chain tail (torn header, truncated payload,
bit rot, stale duplicate segments from a dead incarnation) must recover
to the last committed epoch boundary — never crash, never replay garbage.
"""

import json
import os

import numpy as np
import pytest

from apmbackend_tpu.config import default_config
from apmbackend_tpu.deltachain import (
    CheckpointWriteError,
    DeltaChain,
    InvalidSegment,
    StorageFaultPlan,
    _decode_segment,
    _encode_segment,
    install_fault_plan,
)
from apmbackend_tpu.pipeline import PipelineDriver


def base_cfg(capacity=32, lag=6, ewma=False, ring_dtype=""):
    cfg = default_config()
    cfg["tpuEngine"]["serviceCapacity"] = capacity
    cfg["tpuEngine"]["samplesPerBucket"] = 16
    cfg["tpuEngine"]["zscoreRingDtype"] = ring_dtype
    cfg["streamCalcZScore"]["defaults"] = [
        {"LAG": lag, "THRESHOLD": 3.0, "INFLUENCE": 0.1}
    ]
    if ewma:
        cfg["tpuEngine"]["ewmaChannels"] = [
            {"CHANNEL_ID": -1, "ALPHA": 0.3, "THRESHOLD": 3.0, "WARMUP": 2,
             "SEASON_SLOTS": 3, "SLOT_INTERVALS": 2}
        ]
    return cfg


BASE = 170_000_000


def make_lines(seed=0, steps=12, jump_at=(), big_jump_at=(), max_per=20):
    rng = np.random.RandomState(seed)
    lines, t = [], 0
    for step in range(steps):
        t += int(rng.choice([0, 1, 1, 2]))
        if step in jump_at:
            t += 7
        if step in big_jump_at:
            t += 45  # past NB=37: a full ring clear
        for i in range(rng.randint(3, max_per)):
            e = int(rng.randint(50, 900))
            lines.append(
                f"tx|jvm{i % 3}|svc{i % 19:03d}|s{step}-{i}|1|"
                f"{(BASE + t) * 10000 - e}|{(BASE + t) * 10000 + i}|{e}|Y"
            )
    return lines


def snap(driver, path):
    driver.flush()
    driver.save_resume(str(path))
    with np.load(str(path), allow_pickle=True) as z:
        return {k: z[k] for k in z.files}


def assert_same(a, b, ignore=("delivery_state",)):
    ka, kb = set(a) - set(ignore), set(b) - set(ignore)
    assert ka == kb, ka ^ kb
    for k in sorted(ka):
        x, y = a[k], b[k]
        if x.dtype == object:
            ok = list(x.tolist()) == list(y.tolist())
        elif x.dtype.kind == "f":
            ok = np.array_equal(x, y, equal_nan=True)
        else:
            ok = np.array_equal(x, y)
        assert ok, f"array {k!r} diverged"


def run_chain(tmp_path, cfg, lines, chunk=37, capacity=32, compact_at=None,
              delivery=False):
    """Drive a delta-capturing driver over ``lines`` committing every
    ``chunk`` lines; returns (driver, chain, chain_dir)."""
    chain_dir = str(tmp_path / "chain")
    drv = PipelineDriver(cfg, capacity=capacity)
    drv.enable_delta_capture()
    chain = DeltaChain(chain_dir)
    chain.initialize(drv._capture_resume_arrays(None), epoch=0)
    n_commit = 0
    for lo in range(0, len(lines), chunk):
        drv.feed_csv_batch(lines[lo : lo + chunk])
        dd = None
        if delivery:
            dd = {"transactions": {"epoch": n_commit + 1,
                                   "added": [f"m-{n_commit}-{j}" for j in range(3)],
                                   "evicted": 1 if n_commit else 0,
                                   "deduped_total": n_commit}}
        ep = drv.save_resume_delta(chain, delivery_delta=dd)
        n_commit += 1
        if compact_at is not None and n_commit == compact_at:
            chain.compact(ep, drv._capture_resume_arrays(None))
    return drv, chain, chain_dir


# -- equivalence ------------------------------------------------------------


@pytest.mark.parametrize(
    "scenario",
    ["plain", "growth", "bigjump", "ewma", "bf16", "compacted"],
)
def test_chain_restore_bit_identical(tmp_path, scenario):
    """base + deltas == full snapshot == independent replay, per scenario."""
    kw = dict(capacity=32)
    cfg = base_cfg()
    lines = make_lines(seed=3, jump_at=(5,))
    compact_at = None
    if scenario == "growth":
        cfg = base_cfg(capacity=8)
        kw = dict(capacity=8)  # 19 services force two capacity doublings
    elif scenario == "bigjump":
        lines = make_lines(seed=4, jump_at=(3,), big_jump_at=(7,))
    elif scenario == "ewma":
        cfg = base_cfg(ewma=True)
    elif scenario == "bf16":
        cfg = base_cfg(ring_dtype="bfloat16")
    elif scenario == "compacted":
        compact_at = 3
    drv, chain, chain_dir = run_chain(tmp_path, cfg, lines, compact_at=compact_at, **kw)
    a = snap(drv, tmp_path / "a.npz")

    ref = PipelineDriver(cfg, **kw)
    ref.feed_csv_batch(lines)
    b = snap(ref, tmp_path / "b.npz")
    assert_same(a, b)  # delta tracking never perturbs the live engine

    rec = PipelineDriver(cfg, **kw)
    assert rec.load_resume_chain(chain_dir)
    c = snap(rec, tmp_path / "c.npz")
    assert_same(a, c)


def test_empty_epochs_and_delivery_replay(tmp_path):
    """Commits with no feeds/ticks are tiny but still advance the chain and
    carry the delivery record; the incremental dedup window replays to
    (old + added)[evicted:]."""
    cfg = base_cfg()
    lines = make_lines(seed=9, steps=4)
    drv, chain, chain_dir = run_chain(tmp_path, cfg, lines, delivery=True)
    tail = chain.tail_epoch
    for _ in range(3):  # idle epochs: nothing dirty
        drv.save_resume_delta(chain)
    assert chain.tail_epoch == tail + 3
    rec = PipelineDriver(cfg, capacity=32)
    assert rec.load_resume_chain(chain_dir)
    dstate = rec.delivery_state["transactions"]
    n_commits = tail  # one delivery record per line-feeding commit
    expect = []
    for c in range(n_commits):
        expect.extend(f"m-{c}-{j}" for j in range(3))
    evicted = n_commits - 1  # every commit after the first evicted one id
    assert dstate["dedup"] == expect[evicted:]
    assert dstate["epoch"] == n_commits
    assert dstate["deduped_total"] == n_commits - 1


def test_delta_segments_are_rate_proportional(tmp_path):
    """The reason this exists: a quiet epoch's segment must be orders of
    magnitude smaller than the full state snapshot."""
    cfg = base_cfg(capacity=64, lag=360)
    cfg["tpuEngine"]["samplesPerBucket"] = 128
    lines = make_lines(seed=2, steps=6, max_per=8)
    drv, chain, chain_dir = run_chain(tmp_path, cfg, lines, capacity=64)
    drv.save_resume_delta(chain)  # idle epoch
    idle_seg = os.path.getsize(
        os.path.join(chain_dir, f"delta-{chain.tail_epoch:012d}.seg")
    )
    assert idle_seg < 4096  # header + latest_bucket only
    # the claim that matters: epoch cost ∝ ingest, not state size — the
    # state this epoch would have re-serialized is ~3 orders larger
    state_bytes = sum(
        np.asarray(a).nbytes
        for a in drv._capture_resume_arrays(None).values()
        if getattr(a, "dtype", np.dtype(object)) != object
    )
    assert state_bytes > 1_000_000
    assert idle_seg < state_bytes / 1000


# -- corruption matrix ------------------------------------------------------


def _seg_blob(epoch=3, chain="c" * 16, uid="u" * 16, prev="p" * 16):
    return _encode_segment(
        epoch, chain, uid, prev,
        {"cell_rows": np.arange(4, dtype=np.int32),
         "latest_bucket": np.asarray(np.int32(7))},
        {"capacity": 8, "nb": 37, "ticks": []},
    )


def test_segment_roundtrip():
    blob = _seg_blob()
    header, arrays = _decode_segment(blob)
    assert header["epoch"] == 3 and header["uid"] == "u" * 16
    assert arrays["latest_bucket"].shape == ()  # 0-d survives (cursor regression)
    assert np.array_equal(arrays["cell_rows"], np.arange(4, dtype=np.int32))


@pytest.mark.parametrize(
    "mutate,msg",
    [
        (lambda b: b[: len(b) // 2], "footer|CRC|truncat|header length"),
        (lambda b: b[:10], "truncated"),
        (lambda b: b"XXXXXXXX" + b[8:], "magic"),
        (lambda b: b[:8] + b"\xff\xff\xff\x7f" + b[12:], "header length"),
        (lambda b: b[:-12] + bytes(4) + b[-8:], "CRC"),
        (lambda b: b[:40] + bytes(8) + b[48:], "CRC|JSON"),
        (lambda b: b"", "truncated"),
    ],
)
def test_segment_corruption_detected(mutate, msg):
    import re

    blob = mutate(_seg_blob())
    with pytest.raises(InvalidSegment) as ei:
        _decode_segment(blob)
    assert re.search(msg, str(ei.value))


@pytest.mark.parametrize("mode", ["truncate", "garbage", "header", "missing"])
def test_torn_tail_recovers_to_previous_epoch(tmp_path, mode):
    """Fixture-generated corrupt tails: recovery must land on the last
    committed epoch before the damage and keep the driver loadable."""
    cfg = base_cfg()
    lines = make_lines(seed=6, steps=8)
    drv, chain, chain_dir = run_chain(tmp_path, cfg, lines, chunk=29)
    tail = chain.tail_epoch
    seg = os.path.join(chain_dir, f"delta-{tail:012d}.seg")
    blob = open(seg, "rb").read()
    if mode == "truncate":
        open(seg, "wb").write(blob[: len(blob) // 2])
    elif mode == "garbage":
        mid = len(blob) // 2  # 0xA5 pattern: cannot coincide with real bytes' CRC
        open(seg, "wb").write(blob[:mid] + b"\xa5" * 16 + blob[mid + 16 :])
    elif mode == "header":
        open(seg, "wb").write(blob[:13])
    elif mode == "missing":
        os.unlink(seg)

    fresh = DeltaChain(chain_dir)
    rec = fresh.load()
    assert rec is not None and rec.epoch == tail - 1
    if mode != "missing":
        assert rec.dropped  # diagnostics name the damaged file
    drv2 = PipelineDriver(cfg, capacity=32)
    assert drv2.load_resume_chain(chain_dir)  # never a crash-loop

    # the next writer RE-COMMITS over the damaged name and the chain heals
    drv2.enable_delta_capture()
    drv2.feed_csv_batch(make_lines(seed=7, steps=2))
    chain2 = DeltaChain(chain_dir)
    chain2.load()
    new_epoch = drv2.save_resume_delta(chain2)
    assert new_epoch == tail
    assert DeltaChain(chain_dir).load().epoch == tail


def test_stale_duplicate_tail_rejected(tmp_path):
    """A leftover same-epoch segment from a dead incarnation (right epoch,
    right chain id, WRONG predecessor uid) must never be replayed — the
    duplicate-chain-tail-after-kill−9 scenario."""
    cfg = base_cfg()
    drv, chain, chain_dir = run_chain(tmp_path, cfg, make_lines(seed=8, steps=6))
    tail = chain.tail_epoch
    with open(os.path.join(chain_dir, f"delta-{tail:012d}.seg"), "rb") as fh:
        header, _ = _decode_segment(fh.read())
    stale = _encode_segment(
        tail + 1, header["chain"], os.urandom(8).hex(), "feedfacefeedface",
        {"latest_bucket": np.asarray(np.int32(999))},
        {"capacity": 32, "nb": 37, "ticks": []},
    )
    open(os.path.join(chain_dir, f"delta-{tail + 1:012d}.seg"), "wb").write(stale)
    rec = DeltaChain(chain_dir).load()
    assert rec.epoch == tail  # the stale segment did NOT extend the chain
    assert any("duplicate tail" in d or "linkage" in d for d in rec.dropped)
    # foreign chain id is equally rejected
    foreign = _encode_segment(
        tail + 1, "f" * 16, os.urandom(8).hex(), header["uid"],
        {"latest_bucket": np.asarray(np.int32(999))},
        {"capacity": 32, "nb": 37, "ticks": []},
    )
    open(os.path.join(chain_dir, f"delta-{tail + 1:012d}.seg"), "wb").write(foreign)
    assert DeltaChain(chain_dir).load().epoch == tail


def test_manifest_loss_and_base_fallback(tmp_path):
    """MANIFEST gone → scan recovers the newest base; newest base unreadable
    → fall back one compaction generation (the orbax keep=2 analog)."""
    cfg = base_cfg()
    drv, chain, chain_dir = run_chain(
        tmp_path, cfg, make_lines(seed=10, steps=10), compact_at=3
    )
    tail = chain.tail_epoch
    os.unlink(os.path.join(chain_dir, "MANIFEST.json"))
    assert DeltaChain(chain_dir).load().epoch == tail

    # newest base corrupted: the previous generation (base-0 + all deltas)
    # still recovers the full chain
    bases = sorted(n for n in os.listdir(chain_dir) if n.startswith("base-"))
    assert len(bases) == 2
    open(os.path.join(chain_dir, bases[-1]), "wb").write(b"not an npz")
    rec = DeltaChain(chain_dir).load()
    assert rec.epoch == tail
    drv2 = PipelineDriver(cfg, capacity=32)
    assert drv2.load_resume_chain(chain_dir)
    a = snap(drv, tmp_path / "a.npz")
    b = snap(drv2, tmp_path / "b.npz")
    assert_same(a, b)


def test_compaction_gc_keeps_one_generation(tmp_path):
    cfg = base_cfg()
    drv, chain, chain_dir = run_chain(
        tmp_path, cfg, make_lines(seed=11, steps=12), chunk=23
    )
    ep1 = chain.tail_epoch
    chain.compact(ep1, drv._capture_resume_arrays(None))
    drv.feed_csv_batch(make_lines(seed=12, steps=3))
    drv.save_resume_delta(chain)
    ep2 = chain.tail_epoch
    chain.compact(ep2, drv._capture_resume_arrays(None))
    names = sorted(os.listdir(chain_dir))
    bases = [n for n in names if n.startswith("base-")]
    segs = [int(n[6:-4]) for n in names if n.startswith("delta-")]
    assert bases == [f"base-{ep1:012d}.npz", f"base-{ep2:012d}.npz"]
    assert all(e > ep1 for e in segs)  # deltas under the previous base GC'd
    assert DeltaChain(chain_dir).load().epoch == ep2


# -- hostile storage: injected write failures --------------------------------


def test_enospc_append_fails_cleanly_then_retries(tmp_path):
    """An injected ENOSPC mid-segment-write leaves a torn tmp (never a torn
    committed segment), raises CheckpointWriteError, keeps tracking armed,
    and the retry commits a superset delta. The recovered chain equals an
    uninterrupted run."""
    cfg = base_cfg()
    lines = make_lines(seed=13, steps=8)
    half = len(lines) // 2
    chain_dir = str(tmp_path / "chain")
    drv = PipelineDriver(cfg, capacity=32)
    drv.enable_delta_capture()
    chain = DeltaChain(chain_dir)
    chain.initialize(drv._capture_resume_arrays(None), epoch=0)
    drv.feed_csv_batch(lines[:half])
    drv.save_resume_delta(chain)
    try:
        install_fault_plan(StorageFaultPlan("enospc:after=0,count=2"))
        drv.feed_csv_batch(lines[half:])
        for _ in range(2):
            with pytest.raises(CheckpointWriteError):
                drv.save_resume_delta(chain)
        assert chain.tail_epoch == 1  # tail unchanged by the failures
        assert DeltaChain(chain_dir).load().epoch == 1  # committed boundary intact
        epoch = drv.save_resume_delta(chain)  # third attempt clears
        assert epoch == 2
    finally:
        install_fault_plan(None)
    ref = PipelineDriver(cfg, capacity=32)
    ref.feed_csv_batch(lines)
    rec = PipelineDriver(cfg, capacity=32)
    assert rec.load_resume_chain(chain_dir)
    assert_same(snap(ref, tmp_path / "r.npz"), snap(rec, tmp_path / "c.npz"))
    assert not [n for n in os.listdir(chain_dir) if n.endswith(".tmp")]


def test_fault_plan_grammar():
    p = StorageFaultPlan("enospc:after=3,count=2")
    assert (p.fail_after, p.fail_count, p.fail_errno) == (3, 2, 28)
    p = StorageFaultPlan("eio:after=0")
    assert (p.fail_count, p.fail_errno) == (1, 5)
    p = StorageFaultPlan("kill:compact=pre_manifest")
    assert p.kill_at == "pre_manifest"
    with pytest.raises(ValueError):
        StorageFaultPlan("frobnicate:x=1")


def test_delivery_state_survives_compaction(tmp_path):
    """The base written by compaction carries the FULL delivery tree, so a
    chain whose deltas were all GC'd still seeds the dedup window."""
    cfg = base_cfg()
    drv, chain, chain_dir = run_chain(
        tmp_path, cfg, make_lines(seed=14, steps=6), delivery=True
    )
    ep = chain.tail_epoch
    full_delivery = {"transactions": {"epoch": 99, "dedup": ["a", "b"],
                                      "deduped_total": 7}}
    chain.compact(ep, drv._capture_resume_arrays(full_delivery))
    # wipe every delta: only the new base remains on the recovery path
    for n in os.listdir(chain_dir):
        if n.startswith("delta-"):
            os.unlink(os.path.join(chain_dir, n))
    rec = PipelineDriver(cfg, capacity=32)
    assert rec.load_resume_chain(chain_dir)
    assert rec.delivery_state == full_delivery
    assert json.loads(
        open(os.path.join(chain_dir, "MANIFEST.json")).read()
    )["base_epoch"] == ep
