"""Fault injection (testing/chaos.py): the deterministic broker-misbehavior
seams — forced-full windows driving the real pause/buffer/drain/resume stack,
drop/duplicate delivery accounting, and a pipeline surviving a lossy fabric."""

import numpy as np

from apmbackend_tpu.testing import ChaosChannel
from apmbackend_tpu.transport.base import QueueManager
from apmbackend_tpu.transport.memory import MemoryBroker, MemoryChannel


def _qm(broker, chaos_on: str, **chaos_kw):
    """QueueManager whose producer or consumer channel is chaos-wrapped."""
    chaos_holder = {}

    def factory(direction: str):
        ch = MemoryChannel(broker)
        if direction == chaos_on:
            chaos_holder["chaos"] = ChaosChannel(ch, **chaos_kw)
            return chaos_holder["chaos"]
        return ch

    qm = QueueManager(factory, stat_log_interval_s=3600)
    return qm, chaos_holder


def test_forced_full_drives_pause_buffer_drain_resume():
    broker = MemoryBroker(capacity=10_000)
    qm, holder = _qm(broker, chaos_on="p")
    events = []
    qm.on("pause", lambda: events.append("pause"))
    qm.on("resume", lambda: events.append("resume"))
    prod = qm.get_queue("q", "p")
    chaos = holder["chaos"]

    for i in range(5):
        prod.write_line(f"line{i}")
    chaos.force_full()
    for i in range(5, 12):
        prod.write_line(f"line{i}")  # refused -> buffered, pause fires
    assert prod.buffer_count() == 7
    assert "pause" in events
    assert chaos.stats.refused_sends >= 1
    chaos.release()  # broker alarm clears -> drain -> retry -> resume
    assert prod.buffer_count() == 0
    assert events[-1] == "resume"
    # every line arrives exactly once, in order (separate consumer process
    # analog: its own QueueManager over the same broker)
    lines = []
    consumer_qm = QueueManager(lambda d: MemoryChannel(broker), stat_log_interval_s=3600)
    consumer_qm.get_queue("q", "c", lines.append).start_consume()
    broker.pump()
    assert lines == [f"line{i}" for i in range(12)]


def test_drop_injection_accounts_every_message():
    broker = MemoryBroker()
    qm, holder = _qm(broker, chaos_on="c", drop_p=0.3, seed=11)
    received = []
    prod_qm = QueueManager(lambda d: MemoryChannel(broker), stat_log_interval_s=3600)
    prod = prod_qm.get_queue("q", "p")
    cons = qm.get_queue("q", "c", received.append)
    cons.start_consume()
    N = 500
    for i in range(N):
        prod.write_line(f"m{i}")
    broker.pump()
    chaos = holder["chaos"]
    assert chaos.stats.dropped > 0
    assert chaos.stats.dropped + chaos.stats.delivered == N
    assert len(received) == chaos.stats.delivered
    # order of surviving messages preserved
    assert received == [m for m in (f"m{i}" for i in range(N)) if m in set(received)]


def test_duplicate_delivery_double_processes():
    broker = MemoryBroker()
    qm, holder = _qm(broker, chaos_on="c", dup_p=1.0, seed=3)
    received = []
    prod_qm = QueueManager(lambda d: MemoryChannel(broker), stat_log_interval_s=3600)
    prod = prod_qm.get_queue("q", "p")
    qm.get_queue("q", "c", received.append).start_consume()
    for i in range(20):
        prod.write_line(f"m{i}")
    broker.pump()
    chaos = holder["chaos"]
    assert chaos.stats.duplicated == 20
    assert len(received) == 40  # ack-on-receipt consumers double-process
    assert received[0] == received[1] == "m0"


def test_same_seed_replays_identically():
    outcomes = []
    for _ in range(2):
        broker = MemoryBroker()
        qm, holder = _qm(broker, chaos_on="c", drop_p=0.5, seed=42)
        received = []
        prod_qm = QueueManager(lambda d: MemoryChannel(broker), stat_log_interval_s=3600)
        prod = prod_qm.get_queue("q", "p")
        qm.get_queue("q", "c", received.append).start_consume()
        for i in range(100):
            prod.write_line(f"m{i}")
        broker.pump()
        outcomes.append(tuple(received))
    assert outcomes[0] == outcomes[1]


def test_pipeline_survives_lossy_fabric():
    """End-to-end-lite: tx lines cross a chaotic (20% loss) queue into the
    device pipeline; every delivered line is ingested, nothing crashes, and
    the tick emission reflects exactly the delivered count."""
    from apmbackend_tpu.config import default_config
    from apmbackend_tpu.pipeline import PipelineDriver

    cfg = default_config()
    cfg["tpuEngine"]["serviceCapacity"] = 32
    cfg["tpuEngine"]["samplesPerBucket"] = 32
    cfg["streamCalcZScore"]["defaults"] = [{"LAG": 4, "THRESHOLD": 20, "INFLUENCE": 0.1}]
    drv = PipelineDriver(cfg, capacity=32)

    broker = MemoryBroker()
    qm, holder = _qm(broker, chaos_on="c", drop_p=0.2, seed=9)
    batch: list = []
    qm.get_queue("transactions", "c", batch.append).start_consume()
    prod_qm = QueueManager(lambda d: MemoryChannel(broker), stat_log_interval_s=3600)
    prod = prod_qm.get_queue("transactions", "p")

    base = 170_000_000
    rng = np.random.RandomState(0)
    sent = 0
    for t in range(6):
        for i in range(300):
            e = int(rng.randint(50, 900))
            prod.write_line(
                f"tx|jvm{i % 4}|svc{i % 24:03d}|l{t}-{i}|1|{(base + t) * 10000 - e}|"
                f"{(base + t) * 10000 + i}|{e}|Y"
            )
            sent += 1
        broker.pump()
        fed = drv.feed_csv_batch(list(batch))
        assert fed == len(batch)
        batch.clear()
    chaos = holder["chaos"]
    assert chaos.stats.dropped > 0
    assert chaos.stats.delivered + chaos.stats.dropped == sent
    # window tx count on device == delivered lines still inside the window
    total_count = int(np.asarray(drv.state.stats.counts).sum())
    assert total_count == chaos.stats.delivered
