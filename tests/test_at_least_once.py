"""At-least-once delivery: manual acks, redelivery, dedup, epoch commits.

The crash-consistency contract (DESIGN.md §7 delivery matrix): a message is
acked only after the checkpoint that absorbed it; unacked messages are
redelivered; redeliveries are skipped via the persisted msg_id dedup window.
These are the fast in-process proofs — the process-level kill−9 tier lives in
tests/test_chaos_harness.py.
"""

import os

import numpy as np
import pytest

from apmbackend_tpu.config import default_config
from apmbackend_tpu.testing.chaos import ChaosChannel, SpoolChannel
from apmbackend_tpu.transport.base import QueueManager
from apmbackend_tpu.transport.memory import MemoryBroker, MemoryChannel


def _mk_qm(broker):
    return QueueManager(lambda d: MemoryChannel(broker), stat_log_interval_s=3600)


# -- transport layer ----------------------------------------------------------


def test_manual_ack_holds_until_commit():
    broker = MemoryBroker()
    prod = _mk_qm(broker).get_queue("q", "p")
    got = []
    cons = _mk_qm(broker).get_queue(
        "q", "c", lambda line, h, tok: got.append((line, h, tok)), manual_ack=True
    )
    cons.start_consume()
    for i in range(5):
        prod.write_line(f"m{i}")
    broker.pump()
    assert [l for l, _h, _t in got] == [f"m{i}" for i in range(5)]
    assert broker.unacked_count("q") == 5  # delivered, not gone
    cons.ack([t for _l, _h, t in got[:3]])
    assert broker.unacked_count("q") == 2
    cons.ack([t for _l, _h, t in got])  # re-ack is idempotent
    assert broker.unacked_count("q") == 0


def test_unacked_redelivered_on_bounce_with_flag_and_same_msg_id():
    broker = MemoryBroker()
    prod = _mk_qm(broker).get_queue("q", "p")
    got = []
    cons = _mk_qm(broker).get_queue(
        "q", "c", lambda line, h, tok: got.append((line, h, tok)), manual_ack=True
    )
    cons.start_consume()
    for i in range(4):
        prod.write_line(f"m{i}")
    broker.pump()
    first_ids = [h["msg_id"] for _l, h, _t in got]
    cons.ack([got[0][2]])
    assert broker.bounce() == 3  # m1..m3 redelivered, m0 committed
    broker.pump()
    redelivered = got[4:]
    assert [l for l, _h, _t in redelivered] == ["m1", "m2", "m3"]  # FIFO kept
    assert all(h.get("redelivered") for _l, h, _t in redelivered)
    # redelivery carries the ORIGINAL msg_id — the dedup key
    assert [h["msg_id"] for _l, h, _t in redelivered] == first_ids[1:]


def test_consumer_channel_close_requeues_unacked():
    broker = MemoryBroker()
    prod = _mk_qm(broker).get_queue("q", "p")
    qm_c = _mk_qm(broker)
    got = []
    qm_c.get_queue("q", "c", lambda l, h, t: got.append(t), manual_ack=True).start_consume()
    prod.write_line("a")
    broker.pump()
    assert broker.unacked_count() == 1
    qm_c.shutdown()  # close -> redelivery-on-close
    assert broker.unacked_count() == 0
    assert broker.queue_depth("q") == 1


def test_cancel_keeps_unacked_ackable():
    """stop_consume (pause/resume) must NOT forfeit the open epoch's tokens."""
    broker = MemoryBroker()
    prod = _mk_qm(broker).get_queue("q", "p")
    got = []
    cons = _mk_qm(broker).get_queue("q", "c", lambda l, h, t: got.append(t), manual_ack=True)
    cons.start_consume()
    prod.write_line("a")
    broker.pump()
    cons.stop_consume()
    assert broker.unacked_count() == 1
    cons.ack(got)  # ack after cancel still commits
    assert broker.unacked_count() == 0


def test_chaos_dup_and_drop_compose_with_manual_ack():
    broker = MemoryBroker()
    prod = _mk_qm(broker).get_queue("q", "p")
    holder = {}

    def factory(direction):
        ch = MemoryChannel(broker)
        if direction == "c":
            holder["chaos"] = ChaosChannel(ch, dup_p=1.0, seed=3)
            return holder["chaos"]
        return ch

    got = []
    qm = QueueManager(factory, stat_log_interval_s=3600)
    qm.get_queue("q", "c", lambda l, h, t: got.append((l, h["msg_id"], t)), manual_ack=True).start_consume()
    for i in range(10):
        prod.write_line(f"m{i}")
    broker.pump()
    assert holder["chaos"].stats.duplicated == 10
    assert len(got) == 20
    # a dup replays the same msg_id AND token: dedup key + idempotent ack
    assert got[0][1] == got[1][1] and got[0][2] == got[1][2]
    qm.queue_map["q"].ack([t for _l, _m, t in got])
    assert broker.unacked_count() == 0


# -- the worker epoch cycle ---------------------------------------------------


def _worker_cfg(tmp_path, *, save_s=3600):
    cfg = default_config()
    eng = cfg["tpuEngine"]
    eng["serviceCapacity"] = 32
    eng["samplesPerBucket"] = 32
    eng["deliveryMode"] = "atLeastOnce"
    eng["resumeFileFullPath"] = str(tmp_path / "engine.resume.npz")
    cfg["streamCalcZScore"]["defaults"] = [{"LAG": 4, "THRESHOLD": 20, "INFLUENCE": 0.1}]
    cfg["streamCalcStats"]["resumeFileSaveFrequencyInSeconds"] = save_s
    cfg["streamProcessAlerts"]["alertsResumeFileFullPath"] = str(tmp_path / "alerts.resume")
    cfg["logDir"] = None
    return cfg


def _mk_worker(cfg, broker):
    from apmbackend_tpu.runtime.module_base import ModuleRuntime
    from apmbackend_tpu.runtime.worker import WorkerApp

    rt = ModuleRuntime(
        "tpuEngine", config=cfg, broker=broker, install_signals=False, console_log=False
    )
    return WorkerApp(rt), rt


def _tx(t, i, base=170_000_000, server="jvm0", svc=None, elapsed=None):
    e = 100 + i if elapsed is None else elapsed
    svc = svc or f"svc{i % 8:02d}"
    return (
        f"tx|{server}|{svc}|l{t}-{i}|1|{(base + t) * 10000 - e}|"
        f"{(base + t) * 10000 + i}|{e}|Y"
    )


def test_worker_epoch_cycle_ack_after_checkpoint(tmp_path):
    cfg = _worker_cfg(tmp_path)
    broker = MemoryBroker()
    worker, rt = _mk_worker(cfg, broker)
    try:
        prod = _mk_qm(broker).get_queue("transactions", "p")
        for t in range(3):
            for i in range(40):
                prod.write_line(_tx(t, i))
        broker.pump()
        # absorbed into device state but NOT acked: the epoch is open
        assert broker.unacked_count() == 120
        assert len(worker._epoch_tokens) == 120
        worker.save_state()  # feed -> tick -> checkpoint -> ack
        assert broker.unacked_count() == 0
        assert worker._delivery_epoch >= 1
        assert os.path.exists(cfg["tpuEngine"]["resumeFileFullPath"])
        # the snapshot carries the delivery tree
        with np.load(cfg["tpuEngine"]["resumeFileFullPath"], allow_pickle=True) as z:
            assert "delivery_state" in z.files
    finally:
        rt.stop_timers()


def test_worker_dedups_bounce_redelivery_and_counts_it(tmp_path):
    cfg = _worker_cfg(tmp_path)
    broker = MemoryBroker()
    worker, rt = _mk_worker(cfg, broker)
    try:
        prod = _mk_qm(broker).get_queue("transactions", "p")
        for i in range(25):
            prod.write_line(_tx(0, i))
        broker.pump()
        tx_before = int(np.asarray(worker.driver.state.stats.counts).sum())
        # broker bounce mid-epoch: everything unacked comes back
        assert broker.bounce() == 25
        broker.pump()
        assert worker._deduped_total == 25  # skipped, not double-counted
        assert int(np.asarray(worker.driver.state.stats.counts).sum()) == tx_before
        worker.save_state()
        assert broker.unacked_count() == 0
    finally:
        rt.stop_timers()


def test_worker_restart_resumes_epoch_and_dedup_window(tmp_path):
    cfg = _worker_cfg(tmp_path)
    broker = MemoryBroker()
    worker, rt = _mk_worker(cfg, broker)
    prod_qm = _mk_qm(broker)
    prod = prod_qm.get_queue("transactions", "p")
    for i in range(30):
        prod.write_line(_tx(0, i))
    broker.pump()
    worker.save_state()
    epoch1 = worker._delivery_epoch
    rt.stop_timers()

    # crash (no shutdown): a fresh worker must resume the window, and a
    # redelivery of already-committed messages must dedup, not double-count
    broker2 = MemoryBroker()
    worker2, rt2 = _mk_worker(cfg, broker2)
    try:
        assert worker2._delivery_epoch == epoch1
        assert len(worker2._dedup_fifo) == 30
        tx_before = int(np.asarray(worker2.driver.state.stats.counts).sum())
        prod2 = _mk_qm(broker2).get_queue("transactions", "p")
        # replay the exact committed stream (same msg ids via raw headers)
        for _l, mid in zip(range(30), list(worker2._dedup_fifo)):
            broker2.send("transactions", _tx(0, _l).encode(), {"msg_id": mid})
        broker2.pump()
        assert worker2._deduped_total == 30
        assert int(np.asarray(worker2.driver.state.stats.counts).sum()) == tx_before
        assert prod2 is not None
    finally:
        rt2.stop_timers()


def test_dedup_window_is_bounded(tmp_path):
    cfg = _worker_cfg(tmp_path)
    cfg["tpuEngine"]["dedupWindowSize"] = 16
    broker = MemoryBroker()
    worker, rt = _mk_worker(cfg, broker)
    try:
        prod = _mk_qm(broker).get_queue("transactions", "p")
        for i in range(50):
            prod.write_line(_tx(0, i))
        broker.pump()
        assert len(worker._dedup_fifo) == 16
        assert len(worker._dedup_set) == 16
    finally:
        rt.stop_timers()


def test_at_most_once_default_unchanged(tmp_path):
    """The default mode keeps reference semantics: ack-on-receipt, ring
    intake allowed, no delivery state in snapshots."""
    cfg = _worker_cfg(tmp_path)
    cfg["tpuEngine"]["deliveryMode"] = "atMostOnce"
    broker = MemoryBroker()
    worker, rt = _mk_worker(cfg, broker)
    try:
        assert not worker._at_least_once
        prod = _mk_qm(broker).get_queue("transactions", "p")
        for i in range(10):
            prod.write_line(_tx(0, i))
        broker.pump()
        assert broker.unacked_count() == 0  # acked on receipt
        worker.drain_intake()
        worker.save_state()
        with np.load(cfg["tpuEngine"]["resumeFileFullPath"], allow_pickle=True) as z:
            assert "delivery_state" not in z.files
    finally:
        rt.stop_timers()


def test_bad_delivery_mode_rejected(tmp_path):
    cfg = _worker_cfg(tmp_path)
    cfg["tpuEngine"]["deliveryMode"] = "exactlyOnce"
    with pytest.raises(ValueError, match="deliveryMode"):
        _mk_worker(cfg, MemoryBroker())


# -- snapshot plumbing --------------------------------------------------------


def test_save_load_resume_delivery_round_trip(tmp_path):
    from apmbackend_tpu.pipeline import PipelineDriver

    cfg = default_config()
    cfg["tpuEngine"]["serviceCapacity"] = 8
    cfg["streamCalcZScore"]["defaults"] = [{"LAG": 4, "THRESHOLD": 20, "INFLUENCE": 0.1}]
    drv = PipelineDriver(cfg, capacity=8)
    path = str(tmp_path / "r.npz")
    delivery = {"transactions": {"epoch": 7, "dedup": ["a-1", "a-2"], "deduped_total": 3}}
    drv.save_resume(path, delivery=delivery)

    drv2 = PipelineDriver(cfg, capacity=8)
    assert drv2.load_resume(path)
    assert drv2.delivery_state == delivery
    # re-saving without an explicit tree carries the loaded one forward
    drv2.save_resume(path)
    drv3 = PipelineDriver(cfg, capacity=8)
    assert drv3.load_resume(path)
    assert drv3.delivery_state == delivery


def test_sharded_checkpoint_carries_delivery(tmp_path):
    from apmbackend_tpu.parallel.checkpoint import ShardedCheckpointer
    from apmbackend_tpu.pipeline import make_demo_engine

    cfg, state, _params = make_demo_engine(8, 16, [(4, 3.0, 0.1)])
    ckpt = ShardedCheckpointer(str(tmp_path / "ckpt"))
    delivery = {"transactions": {"epoch": 2, "dedup": ["x-1"], "deduped_total": 0}}
    ckpt.save(1, state, cfg, (("s", "svc"),), delivery=delivery)
    ckpt.wait()
    out = ckpt.restore(cfg)
    assert out is not None
    assert ckpt.last_delivery == delivery
    ckpt.close()


# -- spool broker (the kill−9 fabric), in-process semantics -------------------


def test_spool_cursor_only_advances_on_ack(tmp_path):
    spool = SpoolChannel(str(tmp_path / "sp"))
    for i in range(6):
        spool.send("q", f"m{i}".encode(), {"msg_id": f"s-{i}"})
    got = []
    spool.consume("q", lambda p, h, t: got.append((p.decode(), t)), "tag", manual_ack=True)
    assert spool.deliver() == 6
    assert spool.acked_count("q") == 0
    # out-of-order acks only advance the contiguous prefix
    spool.ack([got[0][1], got[2][1]])
    assert spool.acked_count("q") == 1
    spool.ack([got[1][1]])
    assert spool.acked_count("q") == 3
    spool.close()


def test_spool_simulated_crash_redelivers_past_cursor(tmp_path):
    """The fabric the kill−9 tier rests on: a fresh channel (= restarted
    process) resumes delivery exactly at the committed cursor."""
    d = str(tmp_path / "sp")
    spool = SpoolChannel(d)
    for i in range(10):
        spool.send("q", f"m{i}".encode(), {"msg_id": f"s-{i}"})
    got = []
    spool.consume("q", lambda p, h, t: got.append((p.decode(), t)), "tag", manual_ack=True)
    spool.deliver()
    spool.ack([t for _p, t in got[:4]])  # commit m0..m3; m4..m9 in flight
    spool.close()  # SIGKILL stand-in: no further acks

    spool2 = SpoolChannel(d)
    got2 = []
    spool2.consume("q", lambda p, h, t: got2.append(p.decode()), "tag", manual_ack=True)
    spool2.deliver()
    assert got2 == [f"m{i}" for i in range(4, 10)]  # redelivered, FIFO
    spool2.close()
