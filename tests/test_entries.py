"""Wire-format parity tests for entries.py against reference entries.js semantics."""

import math

from apmbackend_tpu.entries import (
    AlertEntry,
    EntryFactory,
    FullStatEntry,
    JmxEntry,
    StatEntry,
    TxEntry,
    js_parse_float,
    js_parse_int,
    js_to_fixed,
    nf,
)


def test_js_parse_int():
    assert js_parse_int("123") == 123
    assert js_parse_int("123abc") == 123
    assert js_parse_int("12.9") == 12
    assert math.isnan(js_parse_int(""))
    assert math.isnan(js_parse_int("abc"))
    assert math.isnan(js_parse_int(None))
    assert math.isnan(js_parse_int("undefined"))
    assert math.isnan(js_parse_int("NaN"))
    assert js_parse_int("-5") == -5


def test_js_parse_float():
    assert js_parse_float("1.5") == 1.5
    assert math.isnan(js_parse_float("undefined"))
    assert js_parse_float("2.5e2") == 250.0
    assert js_parse_float("7") == 7.0


def test_js_to_fixed_matches_js_tofixed():
    # Values cross-checked against Node: (x).toFixed(d)
    assert js_to_fixed(0.15, 1) == "0.1"  # 0.15 is < .15 in binary
    assert js_to_fixed(0.25, 1) == "0.3"  # exact tie -> larger n
    assert js_to_fixed(-0.25, 1) == "-0.2"  # exact tie -> larger n (toward +inf)
    assert js_to_fixed(2.5, 0) == "3"
    assert js_to_fixed(1234.999, 1) == "1235.0"
    assert js_to_fixed(0.0, 1) == "0.0"
    assert js_to_fixed(123.456, 2) == "123.46"


def test_nf():
    assert nf(float("nan")) == "undefined"
    assert nf(None) == "undefined"
    assert nf(0) == "0.0"
    assert nf(12.34) == "12.3"
    assert nf(12.34, 2) == "12.34"


def test_tx_roundtrip():
    tx = TxEntry("srv1", "S:getFoo", "abc123", "999", 1000, 2500, 1500, "Y")
    line = tx.to_csv()
    assert line == "tx|srv1|S:getFoo|abc123|999|1000|2500|1500|Y"
    back = EntryFactory().from_csv(line)
    assert isinstance(back, TxEntry)
    assert back.server == "srv1" and back.elapsed == 1500 and back.acct_num == 999


def test_tx_missing_fields():
    tx = TxEntry("srv1", "svc", "", "", 900, 1000, 100, "N")
    line = tx.to_csv()
    assert "|NaN|" in line  # acctNum interpolates as NaN like JS template strings
    back = EntryFactory().from_csv(line)
    assert math.isnan(back.acct_num)
    pg = back.to_postgres()
    assert pg["acctnum"] is None


def test_stat_roundtrip_undefined():
    st = StatEntry(1700000000000, "s1", "svc", 1.234, float("nan"), float("nan"), float("nan"))
    line = st.to_csv()
    assert line == "st|1700000000000|s1|svc|1.23|undefined|undefined|undefined"
    back = EntryFactory().from_csv(line)
    assert math.isnan(back.average) and back.tpm == 1.23


def test_fullstat_csv_signal_formats():
    fs = FullStatEntry(
        1700000000000, "s1", "svc", 2.0, 360,
        100.0, 90.0, 80.0, 110.0, 1,
        120.0, 95.0, 85.0, 115.0, 0,
        150.0, 99.0, 89.0, 119.0, -1,
    )
    line = fs.to_csv()
    # average signal bare int; per75/95 signals via nf()
    assert "|100.0:90.0:80.0:110.0:1|" in line
    assert ":0.0|" in line  # per75 signal
    assert line.endswith(":-1.0")  # per95 signal
    back = EntryFactory().from_csv(line)
    assert back.average_signal == 1 and back.per75_signal == 0 and back.per95_signal == -1
    assert back.lag == "360"
    assert back.tpm == 2.0


def test_fullstat_undefined_roundtrip():
    nan = float("nan")
    fs = FullStatEntry(
        1700000000000, "s1", "svc", 0.0, 8640,
        nan, nan, nan, nan, 0,
        nan, nan, nan, nan, 0,
        nan, nan, nan, nan, 0,
    )
    line = fs.to_csv()
    assert "undefined:undefined:undefined:undefined:0|" in line
    back = EntryFactory().from_csv(line)
    assert math.isnan(back.average) and back.average_signal == 0


def test_alert_entry_pipe_redelimit():
    fs_line = "fs|1|s1|svc|360|1.00|2.0:3.0:1.0:4.0:0|2.0:3.0:1.0:4.0:0.0|2.0:3.0:1.0:4.0:0.0"
    al = AlertEntry(1700000000123, 1700000000000, "s1", "svc", "average exceeded hard ms threshold", fs_line)
    line = al.to_csv()
    assert "|" not in line.split("|")[6]  # nested entry uses & only
    back = EntryFactory().from_csv(line)
    assert isinstance(back, AlertEntry)
    pg = back.to_postgres()
    assert pg["entry"]["server"] == "s1"
    assert pg["entry"]["stats"]["average"] == 2.0


def test_jmx_roundtrip():
    jx = JmxEntry(1700000000000, "jvm1", 1, 2, 3, 4, 5, 6, 7, 8, 9, 0.25, 11, 12, 13, 14, 15, 16)
    line = jx.to_csv()
    assert line.startswith("jx|1700000000000|jvm1|1|2|3|")
    back = EntryFactory().from_csv(line)
    assert back.sys_load == 0.25 and back.bean_pool_max_size == 16
    pg = back.to_postgres()
    assert pg["sysload"] == 0.25 and pg["dsinusenodes"] == 1


def test_jmx_from_stats_blob():
    stats = {
        "ds": {"result": {"InUseCount": 1, "ActiveCount": 2, "AvailableCount": 3}},
        "heap": {"result": {"used": 10, "committed": 20, "max": 30}},
        "meta": {"result": {"used": 1, "committed": 2, "max": 3}},
        "sysload": {"result": 0.5},
        "classcnt": {"result": 1000},
        "threading": {"result": {"thread-count": 50, "daemon-thread-count": 40}},
        "bean": {"result": [{"result": {"pool-available-count": 5, "pool-current-size": 6, "pool-max-size": 7}}]},
    }
    jx = JmxEntry.from_jmx_stats(1700000000000, "jvm1", stats)
    assert jx.heap_used == 10 and jx.thread_cnt == 50 and jx.bean_pool_max_size == 7


def test_factory_unknown_type():
    assert EntryFactory().from_csv("zz|1|2") is None


def test_infinity_handling():
    assert js_parse_float("Infinity") == float("inf")
    assert js_parse_float("-Infinity") == float("-inf")
    assert js_to_fixed(float("inf"), 1) == "Infinity"
    assert nf(float("inf")) == "Infinity"


def test_negative_zero_tofixed():
    # (-0.04).toFixed(1) === "-0.0" in JS; (0).toFixed(1) === "0.0"
    assert js_to_fixed(-0.04, 1) == "-0.0"
    assert js_to_fixed(0.0, 1) == "0.0"
    assert js_to_fixed(-0.0, 1) == "0.0"


def test_fullstat_postgres_signal_ints():
    fs = FullStatEntry(
        1, "s", "svc", 1.0, 360,
        1.0, 1.0, 1.0, 1.0, 1,
        1.0, 1.0, 1.0, 1.0, 0,
        1.0, 1.0, 1.0, 1.0, -1,
    )
    stats = fs.to_postgres()["stats"]
    assert stats["averagesignal"] == 1 and isinstance(stats["averagesignal"], int)
    assert stats["per95signal"] == -1 and isinstance(stats["per95signal"], int)
