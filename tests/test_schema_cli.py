"""tools/schema.py: sink DDL + dashboard provisioning generated from the
same column sets the writer uses — applied DDL must accept the writer's
real row shapes end-to-end."""

import json
import sqlite3

from apmbackend_tpu.config import default_config
from apmbackend_tpu.tools import schema


def _cfg(tmp_path=None, backend="fake"):
    cfg = default_config()
    cfg["streamInsertDb"]["dbBackend"] = backend
    if backend == "sqlite":
        cfg["streamInsertDb"]["dbFileFullPath"] = str(tmp_path / "apm.db")
    return cfg


def test_ddl_covers_all_tables_with_configured_names():
    cfg = _cfg()
    cfg["streamInsertDb"]["dbTxTable"] = "my_tx"
    cfg["streamInsertDb"]["dbJmxTable"] = "my_jmx"
    ddl = schema.build_ddl(cfg)
    for table in ("my_tx", "stats", "alerts", "my_jmx"):
        assert f"CREATE TABLE IF NOT EXISTS {table}" in ddl
    assert "endts timestamptz" in ddl
    assert "stats jsonb" in ddl
    assert "tpm double precision" in ddl
    assert "heapused bigint" in ddl
    assert "CREATE INDEX IF NOT EXISTS ix_stats_lag ON stats (lag);" in ddl


def test_applied_sqlite_ddl_accepts_writer_rows(tmp_path):
    """Provision via --apply, then run the REAL sink writer against the
    provisioned tables: every entry type's to_postgres() row must insert."""
    import math

    from apmbackend_tpu.entries import (
        AlertEntry, EntryFactory, FullStatEntry, JmxEntry, TxEntry,
    )
    from apmbackend_tpu.sinks.db import column_sets_from_config, make_executor

    cfg = _cfg(tmp_path, backend="sqlite")
    assert schema.main(["ddl", "--apply", "--config", _write(tmp_path, cfg)]) == 0

    db_cfg = cfg["streamInsertDb"]
    ex = make_executor(db_cfg)
    sets = column_sets_from_config(db_cfg)
    ts = 1_700_000_000_000.0
    tx = TxEntry("s1", "svcA", "L1", "123", ts - 50, ts, 50.0, "Y")
    fs = FullStatEntry(ts, "s1", "svcA", 12.0, 360,
                       *(float(v) for v in range(15)))
    al = AlertEntry(ts, ts, "s1", "svcA", "avg", fs.to_csv().replace("|", "&"))
    jx = JmxEntry(ts, "s1", *(float(i) for i in range(16)))
    ex.insert_many(sets["tx"], [tx.to_postgres()])
    ex.insert_many(sets["fs"], [fs.to_postgres()])
    ex.insert_many(sets["al"], [al.to_postgres()])
    ex.insert_many(sets["jx"], [jx.to_postgres()])
    ex.close()

    con = sqlite3.connect(db_cfg["dbFileFullPath"])
    for table in ("tx", "stats", "alerts", "jmx"):
        assert con.execute(f"SELECT COUNT(*) FROM {table}").fetchone()[0] == 1
    # provisioned index exists
    names = {r[0] for r in con.execute(
        "SELECT name FROM sqlite_master WHERE type='index'"
    )}
    con.close()
    assert "ix_stats_timestamp" in names


def test_dashboard_variables_match_render_url_contract(tmp_path):
    """The dashboard's template variables must be exactly the var-* names
    generateGrafanaURL embeds in alert-email links."""
    from apmbackend_tpu.integrations.grafana import GrafanaClient

    cfg = _cfg()
    cfg["grafana"]["grafanaURL"] = "http://g:3000"
    dash = schema.build_dashboard(cfg)
    var_names = {v["name"] for v in dash["templating"]["list"]}
    assert var_names == {"server", "service", "lag"}

    client = GrafanaClient(cfg["grafana"])
    fs_line = "&".join([
        "fs", "1700000000000", "srv", "svc", "360", "1.00",
        "1:1:1:1:0", "1:1:1:1:0", "1:1:1:1:0",
    ])
    _view, render = client.alert_urls([{"entry": fs_line}])
    for name in var_names:
        assert f"var-{name}=" in render
    # dashboard uid matches the configured inspector URL tail
    assert dash["uid"] == cfg["grafana"].get(
        "alertInspectorRelativeURL", "/d/alert-inspector"
    ).rstrip("/").split("/")[-1]


def test_fake_backend_records_script(tmp_path, monkeypatch):
    from apmbackend_tpu.sinks.db import FakeExecutor
    from apmbackend_tpu.tools import schema as schema_mod

    captured = FakeExecutor()
    import apmbackend_tpu.sinks.db as db_mod

    monkeypatch.setattr(db_mod, "make_executor", lambda _cfg_d: captured)
    cfg = _cfg()
    assert schema_mod.main(["ddl", "--apply", "--config", _write(tmp_path, cfg)]) == 0
    assert len(captured.scripts) == 1
    assert "CREATE TABLE IF NOT EXISTS tx" in captured.scripts[0]


def test_adapt_rejects_non_datetime_objects_in_jsonb():
    """Corrupt nested objects must fail the flush loudly (re-queue path),
    not persist as reprs."""
    import pytest as _pytest

    from apmbackend_tpu.sinks.db import _adapt

    class Junk:
        pass

    with _pytest.raises(TypeError):
        _adapt({"bad": Junk()})


def test_registered_in_dispatcher():
    from apmbackend_tpu.__main__ import COMMANDS

    assert COMMANDS["schema"] == ("apmbackend_tpu.tools.schema", True)


def _write(tmp_path, cfg) -> str:
    path = str(tmp_path / "cfg.json")
    with open(path, "w") as fh:
        json.dump(cfg, fh)
    return path
