"""Flow-control spine tests: the bounded producer pause buffer.

An unbounded pause buffer turns a stalled broker into a producer OOM — the
cap (``transport.producerBufferMaxLines``) bounds it, and these tests pin
what happens at the boundary: oldest-first eviction under both overflow
policies (counted drop / spill-to-spool), the loud degradation path
(decision record + ``overflow`` event + flight bundle + /healthz 503
*before* eviction starts), the exported depth gauge, and the FIFO /
front-requeue invariants of ``retry_buffer`` racing concurrent
``write_line`` — the ordering contract the whole pause/drain cycle rests
on (queue.js:230-263)."""

import json
import random
import threading
import urllib.error
import urllib.request

import pytest

from apmbackend_tpu.config import default_config
from apmbackend_tpu.obs import MetricsRegistry, set_registry
from apmbackend_tpu.obs.decisions import get_decisions
from apmbackend_tpu.transport import Channel, QueueManager


@pytest.fixture(autouse=True)
def fresh_registry():
    old = set_registry(MetricsRegistry())
    yield
    set_registry(old)


class RefusingChannel(Channel):
    """Accepts sends until ``refuse`` is set — the stalled-broker stand-in."""

    def __init__(self):
        self.sent = []
        self.refuse = True
        self._drain_cbs = []

    def assert_queue(self, name):
        pass

    def send(self, name, payload, headers=None):
        if self.refuse:
            return False
        self.sent.append(payload.decode("utf-8"))
        return True

    def on_drain(self, cb):
        self._drain_cbs.append(cb)

    def fire_drain(self):
        for cb in list(self._drain_cbs):
            cb()


def make_producer(transport_cfg, channel=None):
    ch = channel or RefusingChannel()
    qm = QueueManager(lambda d: ch, 3600, transport_config=transport_cfg)
    return qm, qm.get_queue("q", "p"), ch


# -- cap enforcement -----------------------------------------------------------


def test_cap_evicts_oldest_and_counts():
    qm, prod, ch = make_producer({"producerBufferMaxLines": 3})
    overflows = []
    qm.on("overflow", lambda name, n: overflows.append((name, n)))
    for i in range(7):
        prod.write_line(f"line{i}")
    # buffer keeps the most RECENT window; the 4 oldest were evicted
    assert prod.buffer_count() == 3
    assert [l for l, _h in prod.buffer] == ["line4", "line5", "line6"]
    assert overflows == [("q", 1)] * 4  # one event per overflowing write
    # the episode is recorded for post-hoc triage
    kinds = [d for d in get_decisions().recent(16)
             if d.get("kind") == "producer_buffer_overflow"]
    assert kinds and kinds[-1]["queue"] == "q" and kinds[-1]["cap"] == 3


def test_zero_cap_keeps_legacy_unbounded_buffer():
    qm, prod, ch = make_producer({"producerBufferMaxLines": 0})
    for i in range(500):
        prod.write_line(f"line{i}")
    assert prod.buffer_count() == 500


def test_drained_buffer_preserves_survivor_order():
    qm, prod, ch = make_producer({"producerBufferMaxLines": 2})
    for i in range(5):
        prod.write_line(f"line{i}")
    ch.refuse = False
    prod.retry_buffer()
    assert ch.sent == ["line3", "line4"]  # survivors, still FIFO


def test_spill_spool_policy_preserves_evicted_lines(tmp_path):
    spill_dir = str(tmp_path / "overflow")
    qm, prod, ch = make_producer({
        "producerBufferMaxLines": 2,
        "producerOverflowPolicy": "spill-spool",
        "spillDirectory": spill_dir,
    })
    for i in range(5):
        prod.write_line(f"line{i}")
    assert prod.buffer_count() == 2
    # the 3 evicted lines are not gone — they landed in the durable spool,
    # headers intact, replayable after the incident
    from apmbackend_tpu.transport.spool import SpoolChannel

    reader = SpoolChannel(spill_dir)
    got = []
    reader.consume("q", lambda p, h: got.append((p.decode("utf-8"), h)), "t1")
    reader.deliver()
    assert [l for l, _h in got] == ["line0", "line1", "line2"]
    assert all("msg_id" in h for _l, h in got)


def test_overflow_counter_and_gauge_exported():
    from apmbackend_tpu.obs import get_registry

    qm, prod, ch = make_producer({"producerBufferMaxLines": 2})
    for i in range(5):
        prod.write_line(f"line{i}")
    text = get_registry().render()
    assert 'apm_producer_buffer_lines{queue="q"} 2' in text
    assert 'apm_producer_buffer_overflow_total{queue="q"} 3' in text


# -- runtime integration: healthz degradation + flight bundle ------------------


def _fetch(url, timeout=10):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, resp.read().decode("utf-8")
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode("utf-8")


def test_healthz_degrades_before_eviction_and_overflow_dumps_flight(tmp_path):
    from apmbackend_tpu.runtime.module_base import ModuleRuntime

    cfg = default_config()
    cfg["logDir"] = str(tmp_path / "logs")
    cfg["brokerBackend"] = "memory"
    cfg["transport"] = {
        "producerBufferMaxLines": 10,
        "producerBufferDegradedRatio": 0.8,
    }
    cfg["tpuEngine"]["metricsPort"] = 0
    cfg["observability"] = dict(cfg.get("observability", {}))
    cfg["observability"]["flightDir"] = str(tmp_path / "flight")
    rt = ModuleRuntime("tpuEngine", config=cfg, install_signals=False,
                       console_log=False)
    try:
        # stall the broker: every send refuses, the buffer fills
        rt.qm.producer_channel = RefusingChannel()
        prod = rt.qm.get_queue("q", "p")
        for i in range(7):
            prod.write_line(f"line{i}")
        status, body = _fetch(f"{rt.telemetry.url}/healthz")
        assert status == 200  # 7 < degraded_at=8: still healthy
        for i in range(2):
            prod.write_line(f"more{i}")
        status, body = _fetch(f"{rt.telemetry.url}/healthz")
        health = json.loads(body)
        assert status == 503  # 9 >= 8: degraded BEFORE any eviction
        assert health["flow_control"]["ok"] is False
        assert health["flow_control"]["producer_buffer_lines"]["q"] == 9
        assert health["flow_control"]["degraded_at"] == 8
        # push past the cap: eviction starts and a flight bundle lands
        for i in range(3):
            prod.write_line(f"past{i}")
        assert prod.buffer_count() == 10
        bundles = list((tmp_path / "flight").glob("*producer-overflow-q*"))
        assert bundles, "overflow must capture a flight bundle"
    finally:
        rt.stop_timers()
        if rt.telemetry is not None:
            rt.telemetry.stop()


# -- ordering under concurrency ------------------------------------------------


class FlakyChannel(Channel):
    """Deterministic-random refusals: the worst-case interleaving generator
    for the buffer's FIFO contract."""

    def __init__(self, seed=7, refuse_p=0.5):
        self.sent = []
        self.rng = random.Random(seed)
        self.refuse_p = refuse_p
        self.always_accept = False
        self._drain_cbs = []

    def assert_queue(self, name):
        pass

    def send(self, name, payload, headers=None):
        if not self.always_accept and self.rng.random() < self.refuse_p:
            return False
        self.sent.append(payload.decode("utf-8"))
        return True

    def on_drain(self, cb):
        self._drain_cbs.append(cb)


def test_retry_buffer_vs_concurrent_write_line_keeps_fifo():
    """A drain-driven retry_buffer racing a writer thread must never reorder
    the stream: a refused front-of-buffer line goes BACK to the front
    (requeue_front), and write_line appends behind it — so the channel
    accepts lines in exactly write order, every interleaving."""
    ch = FlakyChannel()
    qm = QueueManager(lambda d: ch, 3600,
                      transport_config={"producerBufferMaxLines": 0})
    prod = qm.get_queue("q", "p")
    n = 400
    done = threading.Event()

    def writer():
        for i in range(n):
            prod.write_line(f"line{i}")
        done.set()

    def drainer():
        while not done.is_set() or prod.buffer_count():
            prod.retry_buffer()
            if done.is_set() and prod.buffer_count() and ch.always_accept:
                break

    t_w = threading.Thread(target=writer)
    t_d = threading.Thread(target=drainer)
    t_w.start()
    t_d.start()
    t_w.join(timeout=10)
    ch.always_accept = True  # broker recovers: let the tail drain
    t_d.join(timeout=10)
    prod.retry_buffer()
    assert prod.buffer_count() == 0
    assert ch.sent == [f"line{i}" for i in range(n)]


def test_retry_buffer_concurrent_with_cap_never_exceeds_cap():
    """Same race with the cap active: the bound holds at every instant the
    writer can observe, and the survivors stay in FIFO order."""
    ch = FlakyChannel(seed=11, refuse_p=0.9)
    cap = 16
    qm = QueueManager(lambda d: ch, 3600,
                      transport_config={"producerBufferMaxLines": cap})
    prod = qm.get_queue("q", "p")
    n = 300
    maxima = []
    done = threading.Event()

    def writer():
        for i in range(n):
            prod.write_line(f"line{i}")
            maxima.append(prod.buffer_count())
        done.set()

    def drainer():
        while not done.is_set():
            prod.retry_buffer()

    t_w = threading.Thread(target=writer)
    t_d = threading.Thread(target=drainer)
    t_w.start()
    t_d.start()
    t_w.join(timeout=10)
    t_d.join(timeout=10)
    ch.always_accept = True
    prod.retry_buffer()
    assert max(maxima) <= cap
    assert prod.buffer_count() == 0
    # dropped lines are allowed (that is the policy) — reordering is not
    sent_idx = [int(l[4:]) for l in ch.sent]
    assert sent_idx == sorted(sent_idx)
    assert len(set(sent_idx)) == len(sent_idx)  # and never duplicated
