"""Differential fuzz: the native ingest fast path vs the Python reference.

ISSUE 4 acceptance: for the same input bytes — dense and sparse fixture
corpora, torn/truncated/interleaved lines, unicode garbage (valid and
invalid UTF-8), TTL-expiry and salvage timings — a native-backed
TransactionParser and an APM_PARSE_NO_NATIVE one must emit bit-identical
(to_csv, insert_to_db) sequences and identical cache hit/miss/keys
counters. The clock is stepped only BETWEEN chunks (both paths see the
same clock value for every line of a chunk — the documented granularity
of the batch API's TTL parity).
"""

import random

import pytest

from apmbackend_tpu.ingest.parser import TransactionParser
from apmbackend_tpu.ingest.replay import FixtureGenerator, write_fixture_logs

try:
    from apmbackend_tpu.native import have_native_parser

    HAVE_NATIVE = have_native_parser()
except Exception:
    HAVE_NATIVE = False

needs_native = pytest.mark.skipif(
    not HAVE_NATIVE, reason="no C++ toolchain: native parser unavailable"
)

SERVER = "jvmhost1"


def _mk_parser(use_native, clock):
    records = []
    parser = TransactionParser(
        lambda tx, db: records.append((tx.to_csv(), db)),
        server_from_path=lambda fp: SERVER,
        clock=clock,
        use_native=use_native,
    )
    return parser, records


def run_both(feed_plan, *, sweeps=()):
    """Feed the identical (file, chunk-bytes | ('advance', dt) | 'sweep' |
    'drain') plan through a native and a reference parser; returns both
    (records, stats, counters) result sets."""
    out = []
    for use_native in (True, False):
        now = [1000.0]
        parser, records = _mk_parser(use_native, lambda: now[0])
        assert (parser._native is not None) == use_native
        for step in feed_plan:
            if step[0] == "advance":
                now[0] += step[1]
            elif step[0] == "sweep":
                parser.sweep()
            elif step[0] == "drain":
                parser.drain()
            elif step[0] == "line":
                parser.read_line(step[1], step[2])
            else:
                fp, blob = step
                parser.read_lines(fp, blob)
        for dt in sweeps:
            now[0] += dt
            parser.sweep()
        parser.drain()
        out.append((records, parser.cache_stats(), dict(parser.counters)))
    return out


def assert_equal(native, ref):
    n_rec, n_stats, n_cnt = native
    r_rec, r_stats, r_cnt = ref
    if n_rec != r_rec:
        for i, (a, b) in enumerate(zip(n_rec, r_rec)):
            assert a == b, f"record {i} diverged:\n  native: {a}\n  ref:    {b}"
        assert len(n_rec) == len(r_rec), (
            f"record count diverged: {len(n_rec)} vs {len(r_rec)}"
        )
    assert n_stats == r_stats, f"cache stats diverged: {n_stats} vs {r_stats}"
    assert n_cnt["lines_in"] == r_cnt["lines_in"]
    assert n_cnt["tx_out"] == r_cnt["tx_out"]
    assert n_cnt["db_direct_out"] == r_cnt["db_direct_out"]


def chunked_plan(paths, *, chunk, seed=0, advance=0.01):
    """Interleave byte chunks across files, carving at line boundaries with
    a pseudo-random chunk size so torn reads land everywhere."""
    rng = random.Random(seed)
    blobs = {fp: open(fp, "rb").read() for fp in sorted(paths)}
    offs = {fp: 0 for fp in blobs}
    tails = {fp: b"" for fp in blobs}
    plan = []
    live = list(blobs)
    while live:
        nxt = []
        for fp in live:
            b, o = blobs[fp], offs[fp]
            if o >= len(b):
                if tails[fp]:
                    plan.append((fp, tails[fp]))
                    tails[fp] = b""
                continue
            step = rng.randrange(1, chunk)
            blob = tails[fp] + b[o: o + step]
            offs[fp] = o + step
            cut = blob.rfind(b"\n")
            if cut >= 0:
                plan.append((fp, blob[: cut + 1]))
                tails[fp] = blob[cut + 1:]
            else:
                tails[fp] = blob
            nxt.append(fp)
        live = nxt
        plan.append(("advance", advance))
    return plan


@needs_native
@pytest.mark.parametrize("density", [1000.0, None], ids=["dense", "sparse"])
def test_fixture_corpora_identical(tmp_path, density):
    paths = write_fixture_logs(
        str(tmp_path), n_transactions=400, seed=13, tx_per_bucket=density
    )
    plan = chunked_plan(paths.values(), chunk=2048, seed=3)
    native, ref = run_both(plan, sweeps=(31.0, 121.0))
    assert_equal(native, ref)
    # the corpus must actually exercise the fast path + the pre-filter
    assert native[2]["native_lines"] == native[2]["lines_in"] > 1000
    assert native[2]["prefilter_rejected"] > 0
    assert len(native[0]) >= 400


@needs_native
def test_ttl_expiry_and_salvage_paths_identical(tmp_path):
    """Entries without exits (record-TTL discard), exits parked numberless
    (need-TTL emit-anyway), BAF salvage, backfill release — with the clock
    stepped across every TTL boundary between chunks."""
    gen = FixtureGenerator(server=SERVER, seed=5)
    pairs = []
    # exit-less entry -> parked partial, discarded at record TTL
    pairs.append(("server.log",
                  "[jbX1] 2024-01-10 09:00:00,000 INFO [CommonTiming] The EJB "
                  "timing entry has begun for method lostCall x y z"))
    # numberless pair -> need cache -> emit-anyway at need TTL
    pairs += gen.soap_transaction("getBar", 250)
    # salvage: BAF metadata carries the number
    pairs += gen.standard_ct_transaction("getOffers", 300, acct=555000111, baf_meta=True)
    # backfill: timing first, SOAP account later
    late = gen.soap_transaction("getFoo", 400, acct=111222333)
    soap_lines = [p for p in late if p[0].startswith("soap")]
    server_lines = [p for p in late if p[0] == "server.log"]
    pairs += soap_lines[:1] + server_lines
    by_file = {}
    for fp, line in pairs:
        by_file.setdefault(fp, []).append(line)
    plan = [(fp, ("\n".join(ls) + "\n").encode()) for fp, ls in by_file.items()]
    plan.append(("advance", 31.0))   # past need TTL
    plan.append(("sweep",))
    # late SOAP account arrives after the need-cache flush
    plan.append((soap_lines[0][0], (soap_lines[1][1] + "\n").encode()))
    plan.append(("advance", 121.0))  # past record TTL
    plan.append(("sweep",))
    native, ref = run_both(plan)
    assert_equal(native, ref)
    assert len(native[0]) >= 3


def _garbage_lines(seed):
    rng = random.Random(seed)
    unicode_junk = ["café", " nbsp tok", "　wide", "znel",
                    " ogham", "\x1cfs\x1d", "résumé"]
    lines = []
    # exotic bytes INSIDE marker lines: RAW fallback joins through the shims
    lines.append("[jbé1] 2024-01-10 09:00:00,000 INFO [CommonTiming] The EJB "
                 "timing entry has begun for method accént".encode())
    lines.append("[jbé1] 2024-01-10 09:00:00,500 INFO [CommonTiming] Total "
                 "time for EJB accént call: 500 ms".encode())
    # NBSP is str-whitespace but not bytes-whitespace: tokenization parity
    lines.append("[jb2] 2024-01-10 09:00:01,000 INFO CommonTiming::Start "
                 "svc A begin".encode())
    lines.append("[jb2] 2024-01-10 09:00:01,200 INFO CommonTiming::Stop svcA "
                 "completed in time: 200 ms".encode())
    # invalid UTF-8 (truncated multibyte + stray continuation)
    lines.append(b"[jb3] 2024-01-10 09:00:02,000 INFO [CommonTiming] Total time "
                 b"for EJB sv\xff call: 10 ms")
    lines.append(b"\xc3 lone lead byte \x80 stray continuation")
    lines.append(b"[jb4] 2024-01-10 09:00:03,000 \xe2\x82 truncated INFO "
                 b"CommonTiming::Stop svcB completed in time: 30 ms")
    # torn/truncated marker lines (IndexError paths)
    lines.append(b"[jb5] 2024-01-10 09:00:04,000 INFO [CommonTiming] The EJB")
    lines.append(b"INFO CommonTiming::Start")
    lines.append(b"=== jbossId IO=I no equals token")
    lines.append("=== jbossId=jbß ts=x IO=I ===".encode())
    lines.append(b"  <accountNumber>987654321</accountNumber>")
    lines.append(b"<accountNumber no closing bracket")
    # audit machinery with unicode + garbage
    lines.append("[jb6] 2024-01-10 09:00:05,000 [ch:9:42] INFO  "
                 "auditTrailId=AUTRÄ04 begin".encode())
    lines.append("Audit Trail id : AUTRÄ04".encode())
    lines.append(b"summary: RequestTrace [stopWatchList=")
    lines.append("svçunicode :[77 millis] step".encode())
    lines.append(b"no colon data line inside elapsed section")
    lines.append(b"]")
    lines.append(b"<stopWatchList>")
    lines.append("  <name>svçunicode</name>".encode())
    lines.append(b"  <startTime>2024-01-10T09:00:05.000-00:00</startTime>")
    lines.append(b"  <stopTime>2024-01-10T09:00:05.077-00:00</stopTime>")
    lines.append(b"</stopWatchList>")
    for _ in range(60):
        junk = rng.choice(unicode_junk)
        lines.append(f"{junk} noise {rng.randrange(10**6)}".encode())
        raw = bytes(rng.randrange(256) for _ in range(rng.randrange(3, 30)))
        lines.append(raw.replace(b"\n", b"x"))
    rng.shuffle(lines)
    return lines


@needs_native
@pytest.mark.parametrize("kind_file", ["server.log", "app_x.log", "soap_io_x.log"])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_unicode_garbage_identical(kind_file, seed):
    lines = _garbage_lines(seed)
    blob = b"\n".join(lines) + b"\n"
    rng = random.Random(seed + 99)
    plan = []
    off = 0
    tail = b""
    while off < len(blob):
        step = rng.randrange(8, 400)
        piece = tail + blob[off: off + step]
        off += step
        cut = piece.rfind(b"\n")
        if cut >= 0:
            plan.append((kind_file, piece[: cut + 1]))
            tail = piece[cut + 1:]
        else:
            tail = piece
        plan.append(("advance", 0.5))
    if tail:
        plan.append((kind_file, tail))
    native, ref = run_both(plan, sweeps=(31.0, 121.0))
    assert_equal(native, ref)


@needs_native
def test_mixed_read_line_and_read_lines_identical(tmp_path):
    """The per-line API and the batch API share one native state: a stream
    fed half through read_line and half through read_lines must match the
    reference fed identically."""
    paths = write_fixture_logs(str(tmp_path), n_transactions=120, seed=21)
    plan = []
    for fp in sorted(paths.values()):
        raw = open(fp, "rb").read().decode("utf-8", "replace").split("\n")
        for i, line in enumerate(raw):
            if i % 3 == 0:
                plan.append(("line", fp, line))
            else:
                plan.append((fp, (line + "\n").encode()))
        plan.append(("advance", 0.2))
    native, ref = run_both(plan, sweeps=(31.0, 121.0))
    assert_equal(native, ref)


def test_native_absent_graceful_fallback(tmp_path, monkeypatch):
    """APM_PARSE_NO_NATIVE=1 (and native-unavailable construction) must
    yield a working pure-Python parser with the same batch API."""
    monkeypatch.setenv("APM_PARSE_NO_NATIVE", "1")
    records = []
    parser = TransactionParser(
        lambda tx, db: records.append(tx), server_from_path=lambda fp: SERVER
    )
    assert parser._native is None
    gen = FixtureGenerator(server=SERVER)
    pairs = gen.soap_transaction("getAccountInfo", 500, acct=123456789)
    by_file = {}
    for fp, line in pairs:
        by_file.setdefault(fp, []).append(line)
    fed = 0
    for fp, ls in by_file.items():
        fed += parser.read_lines(fp, "\n".join(ls) + "\n")
    assert fed == len(pairs)
    assert len(records) == 1 and records[0].acct_num == 123456789
    # str and bytes chunks are both accepted; trailing-newline rule holds
    assert parser.read_lines("app_x.log", b"") == 0
    assert parser.read_lines("app_x.log", "noise\n\nmore\n") == 3


@needs_native
def test_kill_switch_env_disables_native(monkeypatch):
    monkeypatch.setenv("APM_PARSE_NO_NATIVE", "1")
    parser = TransactionParser(lambda tx, db: None)
    assert parser._native is None
    monkeypatch.delenv("APM_PARSE_NO_NATIVE")
    parser2 = TransactionParser(lambda tx, db: None)
    assert parser2._native is not None


@needs_native
@pytest.mark.parametrize("seed", [7, 31])
def test_frame_mode_emission_identical(tmp_path, seed):
    """Frame tier (ISSUE 16): the same corpus through a native-backed and a
    reference parser, both in frame mode, must emit bit-identical APF1
    batches — and the decoded record stream must equal what the per-record
    object path would have handed to on_record for the queue."""
    from apmbackend_tpu.transport import frames

    paths = write_fixture_logs(str(tmp_path), n_transactions=200, seed=seed)

    def run(use_native, frame_mode):
        blobs, queue_csv, db_csv = [], [], []
        kw = {}
        if frame_mode:
            kw = dict(frame_sink=lambda b, n: blobs.append(bytes(b)),
                      frame_max_records=64)
        now = [1000.0]
        parser = TransactionParser(
            lambda tx, db: (db_csv if db else queue_csv).append(tx.to_csv()),
            server_from_path=lambda fp: SERVER, use_native=use_native,
            clock=lambda: now[0], **kw)
        assert (parser._native is not None) == use_native
        plan = chunked_plan(paths.values(), chunk=1536, seed=seed)
        for step in plan:
            if step[0] == "advance":
                now[0] += step[1]
            else:
                parser.read_lines(step[0], step[1])
        parser.drain()
        return blobs, queue_csv, db_csv, dict(parser.counters)

    n_blobs, _n_q, n_db, n_cnt = run(True, True)
    r_blobs, _r_q, r_db, r_cnt = run(False, True)
    # The APC1 carriage trailer embeds wall-clock ingest stamps, so two
    # separate runs differ only there: the framed payload itself must stay
    # bit-identical across parser paths.
    assert all(frames.has_carriage(b) for b in n_blobs + r_blobs)
    assert ([frames.strip_carriage(b) for b in n_blobs]
            == [frames.strip_carriage(b) for b in r_blobs])
    assert n_db == r_db
    _b, ref_queue, ref_db, _c = run(True, False)
    decoded = [l for b in n_blobs for l in frames.decode_lines(b)]
    assert decoded == ref_queue  # frame stream == object-path queue stream
    assert n_db == ref_db
    assert n_cnt["frame_records_out"] == r_cnt["frame_records_out"] == len(decoded) > 0
    assert n_cnt["frames_emitted"] == len(n_blobs) > 1


@needs_native
def test_counters_and_exporter_fields(tmp_path):
    """The new fast-path counters feed the exporter (satellite 5): present,
    monotonic, and consistent with the line totals."""
    paths = write_fixture_logs(str(tmp_path), n_transactions=50, seed=2)
    parser, _ = _mk_parser(True, __import__("time").monotonic)
    for fp in sorted(paths.values()):
        parser.read_lines(fp, open(fp, "rb").read())
    c = parser.counters
    assert c["native_lines"] == c["lines_in"] > 0
    assert 0 < c["prefilter_rejected"] < c["lines_in"]
    from apmbackend_tpu.obs import MetricsRegistry
    from apmbackend_tpu.obs.views import register_parser

    reg = MetricsRegistry()
    register_parser(parser, "testmod", registry=reg)
    text = reg.render()
    assert "apm_parser_native_lines_total" in text
    assert "apm_parser_prefilter_rejected_total" in text
