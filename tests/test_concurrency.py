"""Race-surface stress: intake, resume-save, hot reload, and alert flush all
hammering one worker concurrently (SURVEY §5.2).

The worker serializes device access behind its driver lock; these tests drive
every writer that can touch the driver from a different thread at once —
broker deliveries (ring + device loop), the resume-save timer path, config
hot-reload, and the alert sender — and assert nothing deadlocks, drops, or
corrupts state.
"""

import threading
import time

import numpy as np

from apmbackend_tpu.config import default_config
from apmbackend_tpu.standalone import StandalonePipeline


def stress_config(tmp_path):
    cfg = default_config()
    cfg["streamCalcZScore"]["defaults"] = [{"LAG": 4, "THRESHOLD": 2.0, "INFLUENCE": 0.1}]
    eng = cfg["tpuEngine"]
    eng["serviceCapacity"] = 32
    eng["samplesPerBucket"] = 16
    eng["microBatchSize"] = 512
    eng["resumeFileFullPath"] = str(tmp_path / "engine.resume")
    alerts = cfg["streamProcessAlerts"]
    alerts["alertsResumeFileFullPath"] = str(tmp_path / "alerts.resume")
    # make the alert path HOT: every tick trips the hard-max ladder with no
    # windowing or cooldown, so the device loop's process_trigger/add_to_buffer
    # genuinely races the flush + resume-save threads
    alerts["hardMaxMsAlertThreshold"] = 50
    alerts["rollingAlertWindowSizeInIntervals"] = 1
    alerts["requiredNumberBadIntervalsInAlertWindowToTrigger"] = 1
    alerts["perServiceAlertCooldownInMinutes"] = 0
    alerts["emailsEnabled"] = True
    cfg["streamInsertDb"]["dbBackend"] = "fake"
    cfg["streamInsertDb"]["bufferResumeFileFullPath"] = str(tmp_path / "db.resume")
    cfg["streamParseTransactions"]["tailPauseFileFullPath"] = str(tmp_path / "PAUSE")
    return cfg


def test_concurrent_feed_save_reload_flush(tmp_path):
    cfg = stress_config(tmp_path)
    pipe = StandalonePipeline(config=cfg, tail=False, install_signals=False)
    worker = pipe.worker
    emails = []
    # EmailSender would shell out to sendmail; capture instead (thread-safe
    # append) so flush() exercises its full snapshot/send/remove cycle
    worker.alerts_manager.email_sender = lambda subj, html, img: emails.append(subj)
    errors = []
    stop = threading.Event()

    def run(name, fn, pause):
        while not stop.is_set():
            try:
                fn()
            except Exception as e:  # pragma: no cover - the assertion target
                errors.append((name, repr(e)))
                return
            time.sleep(pause)

    def feed():
        # raw tx lines straight onto the transactions queue, like a parser;
        # elapsed >> hardMax so every tick raises alerts
        label = feed.label = getattr(feed, "label", 170_000_000) + 1
        for i in range(50):
            ts = label * 10000 + i
            elapsed = 100 + (label + i) % 900
            line = f"tx|jvm1|S:svc{i % 8}|l{label}{i}|1|{ts - elapsed}|{ts}|{elapsed}|Y"
            worker._consume(line)

    def save():
        worker.save_state()

    def reload_cfg():
        new_cfg = dict(cfg)
        worker._apply_config(new_cfg)

    def flush_alerts():
        worker.alerts_manager.flush()

    threads = [
        threading.Thread(target=run, args=("feed", feed, 0.001)),
        threading.Thread(target=run, args=("save", save, 0.01)),
        threading.Thread(target=run, args=("reload", reload_cfg, 0.005)),
        threading.Thread(target=run, args=("flush", flush_alerts, 0.005)),
    ]
    for t in threads:
        t.start()
    time.sleep(3.0)
    stop.set()
    for t in threads:
        t.join(timeout=10.0)
        assert not t.is_alive(), "stress thread wedged (deadlock?)"
    assert errors == [], errors

    worker.drain_intake()
    with worker._driver_lock:
        counts = np.asarray(worker.driver.state.stats.counts)
    assert counts.sum() > 0, "nothing reached the device under contention"
    assert worker.intake_dropped == 0
    # the alert surface must have actually been exercised under contention
    amgr = worker.alerts_manager
    assert emails or amgr.alert_buffer, "no alerts fired: the race surface was idle"
    # the resume file written mid-contention must load cleanly
    pipe.shutdown()
    pipe2 = StandalonePipeline(config=cfg, tail=False, install_signals=False)
    assert len(pipe2.worker.driver.registry.rows()) == len(worker.driver.registry.rows())
    pipe2.shutdown()
