"""DB sink: buffering, flush triggers, failure requeue, resume (stream_insert_db.js role)."""

import math

from apmbackend_tpu.entries import AlertEntry, FullStatEntry, JmxEntry, StatEntry, TxEntry
from apmbackend_tpu.sinks import (
    DBWriter,
    FakeExecutor,
    SQLiteExecutor,
    column_sets_from_config,
)
from apmbackend_tpu.utils.counters import DBStats


def make_writer(limit=3, max_ms=5000, executor=None, **kw):
    executor = executor or FakeExecutor()
    cfg = {"dbInsertBufferLimit": limit, "dbMaxTimeBetweenInsertsMs": max_ms}
    clock = FakeClock()
    w = DBWriter(executor, cfg, clock=clock, start_timer=False, **kw)
    return w, executor, clock


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def tx(i=0):
    return TxEntry("srv1", "svc", f"log{i}", 42, 1700000000000 + i, 1700000005000 + i, 5000, "Y")


def test_column_sets_table_names():
    cs = column_sets_from_config({"dbTxTable": "mytx", "dbStatTable": "st8"})
    assert cs["tx"].table == "mytx"
    assert cs["fs"].table == "st8"
    assert cs["al"].table == "alerts"
    assert "acctnum" in cs["tx"].columns
    assert len(cs["jx"].columns) == 18


def test_flush_at_buffer_limit_reference_order():
    # The flush fires when a new row finds the buffer already AT the limit:
    # the full batch is inserted first, then the new row starts a fresh buffer
    # (stream_insert_db.js:345-352).
    w, ex, _ = make_writer(limit=3)
    for i in range(3):
        w.add_entry(tx(i))
    assert ex.batches == []  # at limit but not over: no flush yet
    w.add_entry(tx(3))
    assert ex.batches == [("tx", 3)]
    assert w.buffered_counts()["tx"] == 1


def test_timeout_flush_via_deadline():
    w, ex, clock = make_writer(limit=100, max_ms=5000)
    w.add_entry(tx())
    assert w.process_due() == []  # not due yet
    clock.t += 5.1
    assert w.process_due() == ["tx"]
    assert ex.batches == [("tx", 1)]
    # deadline disarmed after flush
    clock.t += 10
    assert w.process_due() == []


def test_failure_requeues_in_front_and_rearms():
    w, ex, clock = make_writer(limit=2)
    w.add_entry(tx(1))
    w.add_entry(tx(2))
    ex.fail = True
    w.add_entry(tx(3))  # triggers flush of [1,2], which fails
    assert w.buffered_counts()["tx"] == 3
    ex.fail = False
    clock.t += 6
    w.process_due()
    assert ex.batches == [("tx", 3)]
    # order preserved: 1, 2, 3
    logids = [row[4] for row in ex.tables["tx"]]
    assert logids == ["log1", "log2", "log3"]


def test_consume_line_types():
    w, ex, _ = make_writer(limit=100)
    w.consume_line(tx().to_csv())
    st = StatEntry(1700000000000, "s", "svc", 2.5, 100.0, 120.0, 200.0)
    w.consume_line(st.to_csv())  # plain stats are rejected (consumeMsg :364-376)
    w.consume_line("garbage line")
    fs = FullStatEntry(
        1700000000000, "s", "svc", 2.5, 360,
        100.0, 90.0, 80.0, 110.0, 0,
        120.0, 100.0, 90.0, 130.0, 0,
        200.0, 150.0, 100.0, 220.0, 1,
    )
    w.consume_line(fs.to_csv())
    al = AlertEntry(1700000001000, 1700000000000, "s", "svc", "cause", fs.to_csv())
    w.consume_line(al.to_csv())
    jx = JmxEntry(1700000000000, "host1", *range(16))
    w.consume_line(jx.to_csv())
    counts = w.buffered_counts()
    assert counts == {"tx": 1, "fs": 1, "al": 1, "jx": 1}


def test_resume_roundtrip(tmp_path):
    path = str(tmp_path / "db_buffer.resume")
    w, ex, _ = make_writer(limit=100)
    w.add_entry(tx(7))
    w.add_entry(JmxEntry(1700000000000, "host1", *range(16)))
    w.save_resume(path)

    w2, ex2, clock2 = make_writer(limit=100)
    assert w2.load_resume(path)
    counts = w2.buffered_counts()
    assert counts["tx"] == 1 and counts["jx"] == 1
    clock2.t += 6
    w2.process_due()
    assert ("tx", 1) in ex2.batches and ("jmx", 1) in ex2.batches
    # datetimes survived as ISO-8601 Z strings (JS Date.toJSON shape)
    endts = ex2.tables["tx"][0][0]
    assert isinstance(endts, str) and endts.endswith("Z")


def test_deadline_rearms_after_limit_flush():
    # The row appended right after a limit-triggered flush must still get a
    # timeout flush (trickling traffic after a burst).
    w, ex, clock = make_writer(limit=2, max_ms=5000)
    for i in range(3):
        w.add_entry(tx(i))  # third add flushes [0,1], buffers [2]
    assert ex.batches == [("tx", 2)]
    clock.t += 5.1
    assert w.process_due() == ["tx"]
    assert ex.batches == [("tx", 2), ("tx", 1)]


def test_alert_row_resume_roundtrip(tmp_path):
    # 'al' rows nest an entry dict with datetimes: resume must serialize them
    fs = FullStatEntry(
        1700000000000, "s", "svc", 2.5, 360,
        100.0, 90.0, 80.0, 110.0, 0,
        120.0, 100.0, 90.0, 130.0, 0,
        200.0, 150.0, 100.0, 220.0, 1,
    )
    al = AlertEntry(1700000001000, 1700000000000, "s", "svc", "cause", fs.to_csv())
    path = str(tmp_path / "al.resume")
    w, _, _ = make_writer(limit=100)
    w.add_entry(al)
    w.save_resume(path)
    w2, ex2, _ = make_writer(limit=100)
    assert w2.load_resume(path)
    w2.process_all()
    assert ("alerts", 1) in ex2.batches


def test_load_resume_missing(tmp_path):
    w, _, _ = make_writer()
    assert not w.load_resume(str(tmp_path / "nope.resume"))


def test_sqlite_executor_end_to_end():
    ex = SQLiteExecutor(":memory:")
    stats = DBStats()
    w, _, _ = make_writer(limit=2, executor=ex, db_stats=stats)
    for i in range(5):
        w.add_entry(tx(i))
    w.process_all()
    rows = ex._conn.execute("SELECT COUNT(*), MIN(acctnum) FROM tx").fetchone()
    assert rows == (5, 42)
    assert stats.rec_ins_counter == 5
    snap = stats.snapshot_and_reset()
    assert "inserted: 5" in snap
    w.close()


def test_nan_becomes_null_in_sqlite():
    ex = SQLiteExecutor(":memory:")
    w, _, _ = make_writer(limit=100, executor=ex)
    t = tx()
    t.acct_num = math.nan
    t.elapsed = math.nan
    w.add_entry(t)
    w.process_all()
    row = ex._conn.execute("SELECT acctnum, elapsed FROM tx").fetchone()
    assert row == (None, None)
    w.close()


def test_background_timer_thread_flushes():
    ex = FakeExecutor()
    w = DBWriter(ex, {"dbInsertBufferLimit": 100, "dbMaxTimeBetweenInsertsMs": 50}, start_timer=True)
    w.add_entry(tx())
    import time

    deadline = time.monotonic() + 2.0
    while not ex.batches and time.monotonic() < deadline:
        time.sleep(0.02)
    assert ex.batches == [("tx", 1)]
    w.close()
