"""Alert rules: device eval vs a per-entry golden oracle + host manager tests."""

import math

import jax.numpy as jnp
import numpy as np

from apmbackend_tpu.entries import FullStatEntry
from apmbackend_tpu.ops import alerts as da


class GoldenAlertCounter:
    """processFSEntry's counter/trigger ladder for ONE (server,service,lag)

    (stream_process_alerts.js:348-434), minus cooldown (host-side)."""

    def __init__(self, cfg: da.AlertRuleConfig):
        self.cfg = cfg
        self.count = 0

    def step(self, average, per75, tpm, avg_sig, p75_sig, hard_max, svc_suppressed):
        causes = []
        incremented = False
        triggered = []

        def alert(s):
            nonlocal incremented
            if not incremented:
                if self.count <= self.cfg.window_sz:
                    self.count += 1
                incremented = True
            windowed = self.cfg.window_sz > 1 and self.cfg.required_bad > 1
            if windowed:
                if self.count >= self.cfg.required_bad:
                    triggered.append(s)
            else:
                triggered.append(s)

        if not self.cfg.lag_suppressed and not svc_suppressed:
            if not math.isnan(average) and average > hard_max:
                alert("average exceeded hard ms threshold")
            if not math.isnan(per75) and per75 > hard_max:
                alert("per75 exceeded hard ms threshold")
            both = 0
            if avg_sig > 0 and average > self.cfg.hard_min_ms and tpm > self.cfg.hard_min_tpm:
                if not self.cfg.alert_on_both_only:
                    alert("average UB exceeded")
                else:
                    both += 1
            if p75_sig > 0 and per75 > self.cfg.hard_min_ms and tpm > self.cfg.hard_min_tpm:
                if not self.cfg.alert_on_both_only:
                    alert("per75 UB exceeded")
                else:
                    both += 1
            if self.cfg.alert_on_both_only and both >= 2:
                alert("average and per75 UB exceeded")

        if not incremented and self.count > 0:
            self.count -= 1
        self.count = max(self.count, 0)
        return triggered


def run_pair(cfg, entries, hard_max=10000.0, suppressed=False):
    golden = GoldenAlertCounter(cfg)
    counters = jnp.zeros(1, jnp.int32)
    mism = []
    for e in entries:
        avg, p75, tpm, a_sig, p_sig = e
        g_causes = golden.step(avg, p75, tpm, a_sig, p_sig, hard_max, suppressed)
        res = da.eval_rules(
            counters, cfg,
            jnp.array([avg]), jnp.array([p75]), jnp.array([tpm]),
            jnp.array([a_sig]), jnp.array([p_sig]),
            jnp.array([hard_max]), jnp.array([suppressed]),
        )
        counters = res.counters
        d_causes = da.cause_string(int(res.cause_bits[0]))
        g_str = ",".join(g_causes)
        if g_str != d_causes or (bool(res.trigger[0]) != bool(g_causes)):
            mism.append((e, g_str, d_causes))
        assert golden.count == int(counters[0]), (e, golden.count, int(counters[0]))
    assert not mism, mism


def cfg_windowed(**kw):
    d = dict(hard_min_ms=200.0, hard_min_tpm=1.0, alert_on_both_only=True,
             window_sz=5, required_bad=3, lag_suppressed=False)
    d.update(kw)
    return da.AlertRuleConfig(**d)


def test_hard_threshold_with_window():
    cfg = cfg_windowed()
    entries = [(20000.0, 100.0, 5.0, 0, 0)] * 6  # avg over hard max repeatedly
    run_pair(cfg, entries)


def test_both_only_gate():
    cfg = cfg_windowed(window_sz=1, required_bad=1)
    # only avg signal: no alert in both-only mode
    run_pair(cfg, [(300.0, 300.0, 5.0, 1, 0)] * 3)
    # both signals: alert
    run_pair(cfg, [(300.0, 300.0, 5.0, 1, 1)] * 3)


def test_min_gates_block():
    cfg = cfg_windowed(window_sz=1, required_bad=1)
    run_pair(cfg, [(100.0, 100.0, 5.0, 1, 1)])  # below hardMin ms
    run_pair(cfg, [(300.0, 300.0, 0.5, 1, 1)])  # below min tpm


def test_counter_decay_and_cap():
    cfg = cfg_windowed(window_sz=3, required_bad=2)
    entries = (
        [(20000.0, 100.0, 5.0, 0, 0)] * 6  # bad x6 (cap at window+1)
        + [(100.0, 100.0, 5.0, 0, 0)] * 10  # quiet: decay to 0
        + [(20000.0, 100.0, 5.0, 0, 0)] * 2  # needs 2 bad again
    )
    run_pair(cfg, entries)


def test_suppressed_service_decays():
    cfg = cfg_windowed(window_sz=1, required_bad=1)
    run_pair(cfg, [(20000.0, 100.0, 5.0, 1, 1)] * 3, suppressed=True)


def test_lag_suppressed():
    cfg = cfg_windowed(window_sz=1, required_bad=1, lag_suppressed=True)
    run_pair(cfg, [(20000.0, 100.0, 5.0, 1, 1)] * 3)


def test_nan_stats_never_alert():
    cfg = cfg_windowed(window_sz=1, required_bad=1)
    nan = float("nan")
    run_pair(cfg, [(nan, nan, 0.0, 0, 0)] * 3)


def test_not_both_only_individual_causes():
    cfg = cfg_windowed(alert_on_both_only=False, window_sz=1, required_bad=1)
    run_pair(cfg, [(300.0, 100.0, 5.0, 1, 0)])
    run_pair(cfg, [(100.0, 300.0, 5.0, 0, 1)])


def test_fuzz_rules():
    rng = np.random.RandomState(5)
    for both in (True, False):
        for wsz, req in ((1, 1), (5, 3), (60, 45)):
            cfg = cfg_windowed(alert_on_both_only=both, window_sz=wsz, required_bad=req)
            entries = []
            for _ in range(200):
                avg = float(rng.choice([50, 250, 15000, float("nan")]))
                p75 = float(rng.choice([50, 250, 15000, float("nan")]))
                tpm = float(rng.choice([0.0, 0.5, 5.0]))
                entries.append((avg, p75, tpm, int(rng.randint(-1, 2)), int(rng.randint(-1, 2))))
            run_pair(cfg, entries)


# -- host-side AlertsManager ------------------------------------------------


def make_fs(service="svcA", ts=1_700_000_000_000):
    return FullStatEntry(
        ts, "srv1", service, 5.0, 360,
        300.0, 100.0, 50.0, 150.0, 1,
        300.0, 100.0, 50.0, 150.0, 1,
        300.0, 100.0, 50.0, 150.0, 0,
    )


def manager(clock, emails):
    cfg = {
        "perServiceAlertCooldownInMinutes": 15,
        "alertCollectionIntervalInSeconds": 60,
        "increaseCollectionIntervalAfterAlert": True,
        "maxCollectionIntervalInSeconds": 960,
        "emailsEnabled": True,
    }
    return da.AlertsManager(
        cfg, email_sender=lambda subj, html, img: emails.append((subj, html, img)), clock=clock
    )


def test_cooldown_per_service():
    now = [1_700_000_000.0]
    emails = []
    mgr = manager(lambda: now[0], emails)
    a1 = mgr.process_trigger(make_fs("svcA"), da.CAUSE_BOTH_UB)
    assert a1 is not None and a1.cause == "average and per75 UB exceeded"
    # within cooldown: suppressed
    now[0] += 60
    assert mgr.process_trigger(make_fs("svcA"), da.CAUSE_BOTH_UB) is None
    # different service: not suppressed (cooldown keyed by service only)
    assert mgr.process_trigger(make_fs("svcB"), da.CAUSE_AVG_HARD) is not None
    # past cooldown: fires again
    now[0] += 15 * 60 + 1
    assert mgr.process_trigger(make_fs("svcA"), da.CAUSE_BOTH_UB) is not None


def test_flush_interval_doubling_and_reset():
    now = [1_700_000_000.0]
    emails = []
    mgr = manager(lambda: now[0], emails)
    alert = mgr.process_trigger(make_fs(), da.CAUSE_BOTH_UB)
    mgr.add_to_buffer(alert)
    sent, interval = mgr.flush(60)
    assert sent == 1 and interval == 120
    assert len(emails) == 1
    assert "svcA" in emails[0][1] and "<table>" in emails[0][1]
    # quiet flush resets to base
    sent, interval = mgr.flush(interval)
    assert sent == 0 and interval == 60


def test_resume_roundtrip(tmp_path):
    now = [1_700_000_000.0]
    emails = []
    mgr = manager(lambda: now[0], emails)
    alert = mgr.process_trigger(make_fs(), da.CAUSE_AVG_HARD)
    mgr.add_to_buffer(alert)
    p = str(tmp_path / "alerts.resume")
    mgr.save_resume(p)

    mgr2 = manager(lambda: now[0], emails)
    mgr2.load_resume(p)
    assert len(mgr2.alert_buffer) == 1
    # cooldown state restored: immediate re-trigger suppressed
    assert mgr2.process_trigger(make_fs(), da.CAUSE_AVG_HARD) is None


def test_flush_retains_buffer_when_emails_disabled():
    now = [1_700_000_000.0]
    emails = []
    mgr = manager(lambda: now[0], emails)
    mgr.config["emailsEnabled"] = False
    alert = mgr.process_trigger(make_fs(), da.CAUSE_BOTH_UB)
    mgr.add_to_buffer(alert)
    sent, interval = mgr.flush(60)
    assert sent == 0 and interval == 60
    assert len(mgr.alert_buffer) == 1  # NOT lost
    assert not emails
    mgr.config["emailsEnabled"] = True
    sent, _ = mgr.flush(60)
    assert sent == 1 and len(emails) == 1


def test_flush_skips_corrupted_buffer_entry():
    now = [1_700_000_000.0]
    emails = []
    mgr = manager(lambda: now[0], emails)
    mgr.alert_buffer.append({"entry": "zz&broken", "cause": "x"})
    alert = mgr.process_trigger(make_fs(), da.CAUSE_AVG_HARD)
    mgr.add_to_buffer(alert)
    sent, _ = mgr.flush(60)
    assert sent == 2 and len(emails) == 1  # no crash; good row still in the email
    assert "svcA" in emails[0][1]


def test_buffer_drop_oldest_cap_when_emails_disabled():
    """With dispatch unavailable (the shipped default) the buffer must not
    grow without bound: drop-oldest at MAX_BUFFERED, counting evictions."""
    now = [1_700_000_000.0]
    emails = []
    mgr = manager(lambda: now[0], emails)
    mgr.config["emailsEnabled"] = False
    mgr.config["perServiceAlertCooldownInMinutes"] = 0
    cap = da.AlertsManager.MAX_BUFFERED
    for i in range(cap + 25):
        now[0] += 1
        alert = mgr.process_trigger(make_fs(f"svc{i}"), da.CAUSE_BOTH_UB)
        assert alert is not None
        mgr.add_to_buffer(alert)
        mgr.flush(60)  # emails off: retains (capped), never sends
    assert len(mgr.alert_buffer) == cap
    assert mgr.dropped_alerts == 25
    assert not emails
    # the oldest 25 were evicted; the newest survive
    assert mgr.alert_buffer[0]["service"] == "svc25"
    assert mgr.alert_buffer[-1]["service"] == f"svc{cap + 24}"
