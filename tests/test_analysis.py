"""Fixture-driven tests for the static-correctness plane (ISSUE 6).

Every rule gets at least one positive fixture (must flag) and one clean
fixture (must pass), plus the pragma grammar round-trips: allow suppresses,
bare allow is itself a finding, stale allow is itself a finding. The final
tier-1 gate runs the analyzer over the real repo and asserts a clean run —
the same invariant ``run_tests.sh --lint`` enforces in CI.
"""

from __future__ import annotations

import subprocess
import sys
import textwrap

import pytest

from apmbackend_tpu.analysis import Project, run_analysis

REPO_ROOT = __file__.rsplit("/tests/", 1)[0]


def make_project(tmp_path, files, design="", package="pkg"):
    pkg = tmp_path / package
    pkg.mkdir(exist_ok=True)
    (pkg / "__init__.py").write_text("")
    for rel, text in files.items():
        p = pkg / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text))
    (tmp_path / "DESIGN.md").write_text(textwrap.dedent(design))
    return Project(root=str(tmp_path), package=package)


def run_rules(tmp_path, files, rules, design=""):
    return run_analysis(make_project(tmp_path, files, design), rules=rules)


def rule_set(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------- jax-sync

_SYNC_BAD = """
    import jax
    import jax.numpy as jnp

    def hot(x):
        y = jnp.cumsum(x)
        return float(y)
"""

_SYNC_CLEAN = """
    import jax
    import jax.numpy as jnp

    # apm: sync-boundary: the emit readback fixture
    def emit(x):
        y = jnp.cumsum(x)
        return float(y)

    def also_fine(n):
        return float(n) + int("4")
"""


def test_jax_sync_flags_device_conversion(tmp_path):
    f = run_rules(tmp_path, {"hot.py": _SYNC_BAD}, ["jax-sync"])
    assert [x.rule for x in f] == ["jax-sync"]
    assert "float()" in f[0].message


def test_jax_sync_clean_inside_sync_boundary(tmp_path):
    assert run_rules(tmp_path, {"hot.py": _SYNC_CLEAN}, ["jax-sync"]) == []


def test_jax_sync_item_and_asarray_and_param_annotation(tmp_path):
    src = """
    import jax
    import jax.numpy as jnp
    import numpy as np

    class EngineState:
        rings: jnp.ndarray

    def f(state: EngineState):
        a = state.rings[0].item()
        b = np.asarray(state.rings)
        return a, b
    """
    f = run_rules(tmp_path, {"hot.py": src}, ["jax-sync"])
    assert len(f) == 2 and rule_set(f) == {"jax-sync"}


def test_jax_sync_skips_files_without_jax(tmp_path):
    src = "def f(x):\n    y = x.compute()\n    return float(y.item())\n"
    assert run_rules(tmp_path, {"plain.py": src}, ["jax-sync"]) == []


# -------------------------------------------------------- jax-donated-reuse

_DONATE_BAD = """
    import jax
    import jax.numpy as jnp

    step = jax.jit(lambda s: s + 1, donate_argnums=(0,))

    def loop(state):
        out = step(state)
        return state.sum()
"""

_DONATE_CLEAN = """
    import jax
    import jax.numpy as jnp

    step = jax.jit(lambda s: s + 1, donate_argnums=(0,))

    def loop(state):
        state = step(state)
        return state.sum()
"""

_DONATE_BRANCH_CLEAN = """
    import jax
    import jax.numpy as jnp

    step = jax.jit(lambda s: s + 1, donate_argnums=(0,))

    def loop(state, fast):
        if fast:
            return step(state)
        return state.sum()
"""


def test_donated_reuse_flagged(tmp_path):
    f = run_rules(tmp_path, {"d.py": _DONATE_BAD}, ["jax-donated-reuse"])
    assert [x.rule for x in f] == ["jax-donated-reuse"]


def test_donated_rebind_idiom_clean(tmp_path):
    assert run_rules(tmp_path, {"d.py": _DONATE_CLEAN}, ["jax-donated-reuse"]) == []


def test_donated_if_return_branch_clean(tmp_path):
    # the donating branch returns; the fall-through still owns the buffer
    assert run_rules(tmp_path, {"d.py": _DONATE_BRANCH_CLEAN}, ["jax-donated-reuse"]) == []


# ------------------------------------------------------------ jax-recompile

def test_recompile_literal_scalar_flagged(tmp_path):
    src = """
    import jax

    step = jax.jit(lambda s, k: s + k)

    def tick(state):
        return step(state, 3)
    """
    f = run_rules(tmp_path, {"r.py": src}, ["jax-recompile"])
    assert [x.rule for x in f] == ["jax-recompile"]


def test_recompile_static_argnums_clean(tmp_path):
    src = """
    import jax

    step = jax.jit(lambda s, k: s + k, static_argnums=(1,))

    def tick(state):
        return step(state, 3)
    """
    assert run_rules(tmp_path, {"r.py": src}, ["jax-recompile"]) == []


def test_recompile_jit_in_loop_flagged(tmp_path):
    src = """
    import jax

    def rebuild(fns, xs):
        for fn in fns:
            g = jax.jit(fn)
            xs = g(xs)
        return xs
    """
    f = run_rules(tmp_path, {"r.py": src}, ["jax-recompile"])
    assert any("inside a loop" in x.message for x in f)


# -------------------------------------------------------------- lock-guard

_LOCK_BAD = """
    import threading

    class Ledger:
        def __init__(self):
            self._lock = threading.Lock()
            self._unacked = {}  # guarded-by: _lock

        def size(self):
            return len(self._unacked)
"""

_LOCK_CLEAN = """
    import threading

    class Ledger:
        def __init__(self):
            self._lock = threading.Lock()
            self._unacked = {}  # guarded-by: _lock

        def size(self):
            with self._lock:
                return len(self._unacked)

        # apm: holds(_lock): callers in this fixture acquire it
        def _size_locked(self):
            return len(self._unacked)
"""

_LOCK_CLOSURE_BAD = """
    import threading

    class Ledger:
        def __init__(self, register):
            self._lock = threading.Lock()
            self._unacked = {}  # guarded-by: _lock
            with self._lock:
                register(lambda: len(self._unacked))
"""


def test_lock_guard_flags_unlocked_access(tmp_path):
    f = run_rules(tmp_path, {"l.py": _LOCK_BAD}, ["lock-guard"])
    assert [x.rule for x in f] == ["lock-guard"]
    assert "_unacked" in f[0].message


def test_lock_guard_with_block_and_holds_clean(tmp_path):
    assert run_rules(tmp_path, {"l.py": _LOCK_CLEAN}, ["lock-guard"]) == []


def test_lock_guard_closure_does_not_inherit_lock(tmp_path):
    # a callback registered under the lock RUNS later without it — the
    # PR-5 concurrent-profiler race shape
    f = run_rules(tmp_path, {"l.py": _LOCK_CLOSURE_BAD}, ["lock-guard"])
    assert [x.rule for x in f] == ["lock-guard"]


# ------------------------------------------------------------- config keys

_CONFIG_FIXTURE = """
    _DEFAULT_CONFIG = {
        "tpuEngine": {
            "deliveryBatchSize": 256,
            "deliveryMode": "atMostOnce",
        },
        "logDir": "logs",
    }
"""


def test_config_key_typo_flagged(tmp_path):
    reader = """
    def wire(config):
        return config["tpuEngine"]["deliveryBatchSze"]
    """
    f = run_rules(tmp_path, {"config.py": _CONFIG_FIXTURE, "w.py": reader},
                  ["config-key-unknown"])
    assert [x.rule for x in f] == ["config-key-unknown"]
    assert "deliveryBatchSze" in f[0].message


def test_config_key_valid_chains_clean(tmp_path):
    reader = """
    def resolve_path(o, p):
        return o

    def wire(config):
        a = config.get("tpuEngine", {}).get("deliveryBatchSize", 256)
        b = config["logDir"]
        c = resolve_path(config, "tpuEngine.deliveryMode")
        return a, b, c
    """
    f = run_rules(tmp_path, {"config.py": _CONFIG_FIXTURE, "w.py": reader},
                  ["config-key-unknown"])
    assert f == []


def test_config_section_param_auto_anchors(tmp_path):
    reader = """
    def wire(eng_cfg):
        return eng_cfg.get("deliveryBatchSize", 256)
    """
    f = run_rules(tmp_path, {"config.py": _CONFIG_FIXTURE, "w.py": reader},
                  ["config-key-unknown"])
    assert f == []


def test_config_resolve_path_typo_flagged(tmp_path):
    reader = """
    def resolve_path(o, p):
        return o

    def wire(config):
        return resolve_path(config, "tpuEngine.deliveryMoed")
    """
    f = run_rules(tmp_path, {"config.py": _CONFIG_FIXTURE, "w.py": reader},
                  ["config-key-unknown"])
    assert [x.rule for x in f] == ["config-key-unknown"]


def test_config_key_unread_flagged_and_satisfied(tmp_path):
    reader = """
    def wire(config):
        return config["tpuEngine"]["deliveryBatchSize"], config["logDir"]
    """
    f = run_rules(tmp_path, {"config.py": _CONFIG_FIXTURE, "w.py": reader},
                  ["config-key-unread"])
    # deliveryMode is never read anywhere in the fixture package
    assert [x.rule for x in f] == ["config-key-unread"]
    assert "deliveryMode" in f[0].message


# --------------------------------------------------------- metric catalogue

_METRIC_SRC = """
    from .registry import get_registry, Sample

    def wire():
        get_registry().counter("apm_ticks_total", "ticks")
        get_registry().histogram("apm_tick_seconds", "tick wall")

    def collect():
        yield Sample("apm_queue_depth", {}, 1.0)
"""

_METRIC_DESIGN_OK = """
    # design

    Metric catalogue: `apm_ticks_total`, `apm_tick_seconds`,
    `apm_queue_depth`.

    ## next section
"""

_METRIC_DESIGN_DRIFT = """
    # design

    Metric catalogue: `apm_ticks_total`, `apm_gone_total`.

    ## next section
"""


def test_metric_catalogue_in_sync(tmp_path):
    f = run_rules(tmp_path, {"m.py": _METRIC_SRC},
                  ["metric-uncatalogued", "metric-unregistered"],
                  design=_METRIC_DESIGN_OK)
    assert f == []


def test_metric_catalogue_drift_both_directions(tmp_path):
    f = run_rules(tmp_path, {"m.py": _METRIC_SRC},
                  ["metric-uncatalogued", "metric-unregistered"],
                  design=_METRIC_DESIGN_DRIFT)
    rules = sorted(x.rule for x in f)
    assert rules == ["metric-uncatalogued", "metric-uncatalogued",
                     "metric-unregistered"]
    assert any("apm_gone_total" in x.message for x in f)


def test_metric_catalogue_expansion_and_labels(tmp_path):
    src = """
    def wire(reg):
        reg.counter("apm_engine_capacity")
        reg.counter("apm_engine_services")
        reg.histogram("apm_queue_wait_seconds")
    """
    design = """
    Metric catalogue: `apm_engine_{capacity,services}`,
    `apm_queue_wait_seconds{queue}`.

    ## next
    """
    f = run_rules(tmp_path, {"m.py": src},
                  ["metric-uncatalogued", "metric-unregistered"], design=design)
    assert f == []


# ------------------------------------------------------------ pyflakes-lite

def test_unused_import_flagged_and_init_exempt(tmp_path):
    files = {
        "a.py": "import os\nimport sys\n\nprint(sys.argv)\n",
        "sub/__init__.py": "from . import thing\n",
        "sub/thing.py": "x = 1\n",
    }
    f = run_rules(tmp_path, files, ["unused-import"])
    assert [x.rule for x in f] == ["unused-import"]
    assert "'os'" in f[0].message


def test_redefinition_flagged_property_stack_clean(tmp_path):
    src = """
    class C:
        @property
        def x(self):
            return self._x

        @x.setter
        def x(self, v):
            self._x = v

        def go(self):
            return 1

        def go(self):
            return 2
    """
    f = run_rules(tmp_path, {"c.py": src}, ["redefinition"])
    assert [x.rule for x in f] == ["redefinition"]
    assert "'go'" in f[0].message


# ------------------------------------------------- transport-header-drift

_XPORT_BASE = """
    class ProducerQueue:
        def write_line(self, line):
            headers = {"ingest_ts": 1.0, "msg_id": "x"}
            headers["trace_id"] = "t"
            self.channel.send(self.queue_name, line, headers)
"""

_XPORT_OK = """
    class Chan:
        def send(self, name, payload, headers=None):
            self.items.append((payload, headers))

        def requeue(self):
            for payload, headers in self.items:
                headers["redelivered"] = True
"""


def test_header_drift_clean_when_all_transports_synthesize(tmp_path):
    files = {
        "transport/base.py": _XPORT_BASE,
        "transport/memory.py": _XPORT_OK,
        "transport/spool.py": _XPORT_OK,
        "consumer.py": "def on(headers):\n    return headers.get('msg_id')\n",
    }
    assert run_rules(tmp_path, files, ["transport-header-drift"]) == []


def test_header_drift_flags_missing_synthesis_and_unknown_read(tmp_path):
    files = {
        "transport/base.py": _XPORT_BASE,
        "transport/memory.py": _XPORT_OK,
        # spool never sets redelivered AND its send ignores headers
        "transport/spool.py": """
            class Chan:
                def send(self, name, payload, headers=None):
                    self.items.append(payload)
        """,
        "consumer.py": "def on(headers):\n    return headers.get('not_a_header')\n",
    }
    f = run_rules(tmp_path, files, ["transport-header-drift"])
    msgs = "\n".join(x.message for x in f)
    assert "ignores its headers parameter" in msgs
    assert "'redelivered' is synthesized by" in msgs
    assert "'not_a_header' is read here" in msgs
    assert {x.path for x in f} == {"pkg/transport/spool.py", "pkg/consumer.py"}


# ------------------------------------------------- durability-discipline

def test_durability_raw_write_flagged_atomic_helper_clean(tmp_path):
    files = {
        "store.py": """
            import os

            def bad(path):
                with open(path + ".cursor", "w") as fh:
                    fh.write("1")

            def good(path):
                tmp = path + ".cursor.tmp"
                with open(tmp, "w") as fh:
                    fh.write("1")
                os.replace(tmp, path + ".cursor")
        """,
    }
    f = run_rules(tmp_path, files, ["durability-discipline"])
    assert [x.rule for x in f] == ["durability-discipline"]
    assert f[0].line == 5  # the raw open in bad(); good() is sanctioned


def test_durability_owner_module_scope_and_pragma(tmp_path):
    files = {
        "deltachain.py": """
            import os

            def sideways(a, b):
                os.rename(a, b)  # apm: allow(durability-discipline): test fixture reason
        """,
        "other.py": "import os\n\ndef mv(a, b):\n    os.rename(a, b)\n",
    }
    # owner module: flagged (then suppressed by the pragma); non-owner
    # module with no durable token in the path: not flagged at all
    assert run_rules(tmp_path, files, ["durability-discipline"]) == []


def test_durability_append_mode_not_flagged(tmp_path):
    files = {
        "journal.py": "def log(p):\n    open(p + '.spool', 'ab').write(b'x')\n",
    }
    assert run_rules(tmp_path, files, ["durability-discipline"]) == []


# ---------------------------------------------------------- pragma grammar

def test_allow_pragma_suppresses_with_reason(tmp_path):
    src = """
    import jax
    import jax.numpy as jnp

    def hot(x):
        y = jnp.cumsum(x)
        return float(y)  # apm: allow(jax-sync): fixture-sanctioned readback
    """
    assert run_rules(tmp_path, {"h.py": src}, ["jax-sync"]) == []


def test_bare_allow_is_a_finding(tmp_path):
    src = """
    import jax
    import jax.numpy as jnp

    def hot(x):
        y = jnp.cumsum(x)
        return float(y)  # apm: allow(jax-sync)
    """
    f = run_rules(tmp_path, {"h.py": src}, ["jax-sync"])
    assert [x.rule for x in f] == ["pragma-bare"]


def test_unused_allow_is_a_finding(tmp_path):
    src = """
    def cold(x):
        return x + 1  # apm: allow(jax-sync): nothing here needs this
    """
    f = run_rules(tmp_path, {"h.py": src}, ["jax-sync"])
    assert [x.rule for x in f] == ["pragma-unused"]


def test_malformed_pragma_is_a_finding(tmp_path):
    src = "x = 1  # apm: alow(jax-sync): typo'd verb\n"
    f = run_rules(tmp_path, {"h.py": src}, ["jax-sync"])
    assert [x.rule for x in f] == ["pragma-malformed"]


def test_disabled_rules_do_not_audit_their_pragmas(tmp_path):
    src = """
    def cold(x):
        return x + 1  # apm: allow(lock-guard): other rule's pragma
    """
    assert run_rules(tmp_path, {"h.py": src}, ["jax-sync"]) == []


# ------------------------------------------------------------- repo + CLI

def test_repo_is_clean():
    """The gate itself: the whole package passes every rule. Any new
    finding must be fixed or carry a reasoned pragma before it lands."""
    findings = run_analysis(Project(root=REPO_ROOT))
    assert findings == [], "\n" + "\n".join(f.format() for f in findings)


def test_cli_exit_codes(tmp_path):
    out = subprocess.run(
        [sys.executable, "-m", "apmbackend_tpu.analysis", "--list-rules"],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert out.returncode == 0
    assert "jax-sync" in out.stdout and "lock-guard" in out.stdout

    clean = subprocess.run(
        [sys.executable, "-m", "apmbackend_tpu.analysis", "-q"],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert clean.returncode == 0, clean.stdout + clean.stderr

    bad = subprocess.run(
        [sys.executable, "-m", "apmbackend_tpu.analysis", "--rules", "nope"],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert bad.returncode == 2


def test_cli_reports_findings_nonzero(tmp_path):
    pkg = tmp_path / "apmbackend_tpu"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "bad.py").write_text("import os\n\nx = 1\n")
    out = subprocess.run(
        [sys.executable, "-m", "apmbackend_tpu.analysis",
         "--root", str(tmp_path), "--rules", "unused-import"],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert out.returncode == 1
    assert "unused-import" in out.stdout


@pytest.mark.parametrize("direction", ["registered", "catalogued"])
def test_real_metric_catalogue_is_two_way_checked(direction):
    """Belt-and-braces on the real repo: the §8 catalogue and the live
    registration set describe each other (the repo-clean test would catch
    drift too, but this pins the failure to the metric rules)."""
    from apmbackend_tpu.analysis import metriccat
    project = Project(root=REPO_ROOT)
    registered = set(metriccat._registered(project))
    catalogued = set()
    for _tok, _ln, names, _exp in metriccat._catalogue(project):
        catalogued |= names
    assert registered, "no metric registrations found in the repo?"
    if direction == "registered":
        assert registered <= catalogued
    else:
        missing = catalogued - registered - metriccat._mentioned(project)
        assert missing == set()
