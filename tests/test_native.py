"""Native C++ runtime components: apm_tail binary + SPSC LineRing.

Builds native/ via make (skipped when no toolchain). apm_tail must mirror
PyTailer/perl_tail semantics: follow appends, hold position under the pause
file, survive truncation, drain on SIGTERM. LineRing must round-trip records
across threads with wrap-around and signal backpressure when full.
"""

import os
import shutil
import subprocess
import threading
import time

import pytest

from apmbackend_tpu.native import LineRing, ensure_built, tail_binary_path

HAVE_TOOLCHAIN = shutil.which("make") is not None and (
    shutil.which("g++") is not None or shutil.which("c++") is not None
)

pytestmark = pytest.mark.skipif(not HAVE_TOOLCHAIN, reason="no C++ toolchain")


@pytest.fixture(scope="module")
def built():
    path = ensure_built(quiet=False)
    assert path is not None
    return path


def _apm_tail_children():
    """PIDs of live apm_tail processes whose parent is this test process."""
    me = os.getpid()
    found = []
    for pid in os.listdir("/proc"):
        if not pid.isdigit():
            continue
        try:
            with open(f"/proc/{pid}/stat") as fh:
                stat = fh.read()
            comm = stat[stat.index("(") + 1 : stat.rindex(")")]
            ppid = int(stat[stat.rindex(")") + 2 :].split()[1])
        except (OSError, ValueError, IndexError):
            continue
        if ppid == me and "apm_tail" in comm:
            found.append(int(pid))
    return found


@pytest.fixture(autouse=True)
def no_leaked_tail_children():
    """Every test must reap every apm_tail it spawned (round-1 leak regression)."""
    yield
    assert wait_for(lambda: not _apm_tail_children(), timeout=5.0), (
        f"leaked apm_tail children: {_apm_tail_children()}"
    )


def wait_for(predicate, timeout=8.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


class TailProc:
    def __init__(self, binary, file_path, pause_path, *args):
        self.lines = []
        self.proc = subprocess.Popen(
            [binary, file_path, pause_path, "--poll-ms", "20", *args],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, bufsize=1,
        )
        self.thread = threading.Thread(target=self._pump, daemon=True)
        self.thread.start()

    def _pump(self):
        for line in self.proc.stdout:
            self.lines.append(line.rstrip("\n"))

    def stop(self):
        if self.proc.poll() is None:
            self.proc.terminate()
        self.proc.wait(timeout=5)
        self.thread.join(timeout=5)


class TestApmTail:
    def test_follows_appends_from_eof(self, built, tmp_path):
        log = tmp_path / "a.log"
        log.write_text("old1\nold2\n")
        pause = tmp_path / "pause"
        t = TailProc(tail_binary_path(), str(log), str(pause))
        try:
            time.sleep(0.3)  # give it time to seek EOF
            with open(log, "a") as fh:
                fh.write("new1\nnew2\n")
            assert wait_for(lambda: t.lines == ["new1", "new2"]), t.lines
            assert "old1" not in t.lines  # started at EOF
        finally:
            t.stop()

    def test_from_start_flag(self, built, tmp_path):
        log = tmp_path / "b.log"
        log.write_text("x1\nx2\n")
        t = TailProc(tail_binary_path(), str(log), str(tmp_path / "pause"), "--from-start")
        try:
            assert wait_for(lambda: t.lines == ["x1", "x2"]), t.lines
        finally:
            t.stop()

    def test_pause_file_holds_position(self, built, tmp_path):
        log = tmp_path / "c.log"
        log.write_text("")
        pause = tmp_path / "pause"
        pause.write_text("")  # paused from the start
        t = TailProc(tail_binary_path(), str(log), str(pause))
        try:
            time.sleep(0.3)  # let the tailer open + anchor EOF first
            with open(log, "a") as fh:
                fh.write("p1\n")
            time.sleep(0.5)
            assert t.lines == []  # held while pause file exists
            os.unlink(pause)
            assert wait_for(lambda: t.lines == ["p1"]), t.lines
        finally:
            t.stop()

    def test_truncation_reopens_from_start(self, built, tmp_path):
        log = tmp_path / "d.log"
        log.write_text("")
        t = TailProc(tail_binary_path(), str(log), str(tmp_path / "pause"))
        try:
            time.sleep(0.3)  # let the tailer open + anchor EOF first
            with open(log, "a") as fh:
                fh.write("t1-a-long-enough-first-line\n")
            assert wait_for(lambda: t.lines == ["t1-a-long-enough-first-line"]), t.lines
            # replacement strictly shorter than the consumed offset: the
            # size-shrink truncation signal (net-mount-safe detection rule)
            with open(log, "w") as fh:
                fh.write("after\n")
            assert wait_for(
                lambda: t.lines == ["t1-a-long-enough-first-line", "after"]
            ), t.lines
        finally:
            t.stop()

    def test_waits_for_missing_file(self, built, tmp_path):
        log = tmp_path / "late.log"
        t = TailProc(tail_binary_path(), str(log), str(tmp_path / "pause"))
        try:
            time.sleep(0.3)
            assert t.proc.poll() is None  # still waiting, not dead
            log.write_text("l1\n")
            # file appeared after start: tailer reads it from the start
            assert wait_for(lambda: t.lines == ["l1"]), t.lines
        finally:
            t.stop()

    def test_child_dies_with_parent(self, built, tmp_path):
        """apm_tail must not outlive the worker that spawned it (PDEATHSIG):
        the round-1 leak was an orphan surviving a dead parent on a quiet
        file, where SIGPIPE never fires because nothing is ever written."""
        import sys

        log = tmp_path / "orphan.log"
        log.write_text("")
        script = (
            "import os, subprocess, sys\n"
            f"p = subprocess.Popen([{tail_binary_path()!r}, {str(log)!r}, "
            f"{str(tmp_path / 'pause')!r}], stdout=subprocess.DEVNULL)\n"
            "print(p.pid, flush=True)\n"
            "os._exit(0)\n"  # die without stopping the tail
        )
        out = subprocess.run(
            [sys.executable, "-c", script], capture_output=True, text=True, timeout=10
        )
        pid = int(out.stdout.strip())

        def gone():
            try:
                os.kill(pid, 0)
                return False
            except ProcessLookupError:
                return True
            except PermissionError:
                return False

        assert wait_for(gone, timeout=5.0), f"orphan apm_tail {pid} survived its parent"

    def test_stop_reaps_child(self, built, tmp_path):
        from apmbackend_tpu.ingest.tailer import NativeTailer

        log = tmp_path / "reap.log"
        log.write_text("")
        t = NativeTailer(
            tail_binary_path(), str(log), str(tmp_path / "pause"), lambda f, line: None
        )
        t.start()
        assert wait_for(lambda: t._proc is not None and t._proc.poll() is None, timeout=5.0)
        child = t._proc.pid
        t.stop()
        assert t._proc.returncode is not None  # reaped, not abandoned
        assert child not in _apm_tail_children()

    def test_native_tailer_class_integration(self, built, tmp_path):
        from apmbackend_tpu.ingest.tailer import NativeTailer

        log = tmp_path / "e.log"
        log.write_text("")
        got = []
        t = NativeTailer(
            tail_binary_path(), str(log), str(tmp_path / "pause"),
            lambda f, line: got.append(line),
        )
        t.start()
        try:
            time.sleep(0.3)
            with open(log, "a") as fh:
                fh.write("via-class\n")
            assert wait_for(lambda: got == ["via-class"]), got
        finally:
            t.stop()


class TestLineRing:
    def test_roundtrip_fifo(self, built):
        ring = LineRing(1 << 12)
        records = [f"rec-{i}".encode() for i in range(100)]
        for r in records:
            assert ring.push(r)
        out = []
        while (r := ring.pop()) is not None:
            out.append(r)
        assert out == records
        ring.close()

    def test_wraparound_many_cycles(self, built):
        ring = LineRing(256)  # tiny: forces constant wrapping
        for i in range(5000):
            data = f"payload-{i:06d}".encode()
            assert ring.push(data)
            got = ring.pop()
            assert got == data
        ring.close()

    def test_full_ring_backpressure(self, built):
        ring = LineRing(256)
        pushed = 0
        while ring.push(b"x" * 32):
            pushed += 1
            assert pushed < 100  # must eventually report full
        assert ring.dropped >= 1
        ring.pop()  # drain one record
        assert ring.push(b"x" * 16)  # resumes after drain
        ring.close()

    def test_oversized_pop_buffer_grows(self, built):
        ring = LineRing(1 << 14, max_record=8)
        big = b"y" * 1000
        assert ring.push(big)
        assert ring.pop() == big
        ring.close()

    def test_threaded_spsc(self, built):
        ring = LineRing(1 << 12)
        N = 20000
        out = []

        def producer():
            for i in range(N):
                data = f"{i}".encode()
                while not ring.push(data):
                    time.sleep(0)  # full: yield to the consumer

        def consumer():
            while len(out) < N:
                r = ring.pop()
                if r is None:
                    time.sleep(0)
                    continue
                out.append(r)

        tp, tc = threading.Thread(target=producer), threading.Thread(target=consumer)
        tp.start(), tc.start()
        tp.join(timeout=30), tc.join(timeout=30)
        assert len(out) == N
        assert out == [f"{i}".encode() for i in range(N)]
        ring.close()


class TestTxDecoder:
    """native/decoder.cpp: numeric parity with entries.js_parse_int, key
    interning, and end-to-end emission parity with the numpy path."""

    @pytest.fixture
    def dec(self):
        from apmbackend_tpu.native import TxDecoder

        if ensure_built() is None:
            pytest.skip("no native toolchain")
        d = TxDecoder()
        yield d
        d.close()

    def _line(self, ets, ela, server="jvm1", service="svcA", i=0):
        return f"tx|{server}|{service}|l{i}|1|{ets}|{ets}|{ela}|Y"

    def test_numeric_parity_with_js_parse_int(self, dec):
        import math

        from apmbackend_tpu.entries import js_parse_int

        cases = [
            "1700000010000", "-123", "+45", " 77", "\t8", "12.9", "-0.5",
            "1e5", "0x1A", "12.34.56", "abc", "", "  ", "9" * 25, "5xyz",
            "٥٤",  # unicode digits: flagged exotic, re-parsed in Python
        ]
        lines = [self._line(c, c, i=i) for i, c in enumerate(cases)]
        blob = "\n".join(lines).encode("utf-8")
        end_ts, elapsed, keyid, offs, lens, flags, n_bad = dec.decode(blob)
        assert n_bad == 0 and len(end_ts) == len(cases)
        for i, c in enumerate(cases):
            expect = js_parse_int(c)
            got = float(end_ts[i])
            if flags[i] & 1:
                # exotic: the decoder defers to Python; pipeline re-parses
                assert math.isnan(got)
            elif math.isnan(expect):
                assert math.isnan(got), f"case {c!r}"
            else:
                assert got == expect, f"case {c!r}: {got} != {expect}"

    def test_line_classification(self, dec):
        blob = b"\n".join([
            b"tx|s|v|l|1|100000|100010|10|Y",   # good
            b"",                                 # empty: skipped silently
            b"st|1|2|3",                         # non-tx
            b"tx|too|few",                       # short
            b"tx|s|v|l|1|100000|100010|10|Y|extra",  # 10 fields
            b"txx|s|v|l|1|100000|100010|10|Y",   # wrong tag
            b"tx|s|v|l|1|100000|100020|20|N",    # good (no trailing \n)
        ])
        end_ts, elapsed, keyid, offs, lens, flags, n_bad = dec.decode(blob)
        assert len(end_ts) == 2
        assert n_bad == 4
        assert [float(x) for x in elapsed] == [10.0, 20.0]

    def test_key_interning_first_appearance_order(self, dec):
        lines = [
            self._line(100000, 1, "b", "z"),
            self._line(100000, 2, "a", "y"),
            self._line(100000, 3, "b", "z"),  # repeat
            self._line(100000, 4, "c", "x"),
        ]
        _, _, keyid, *_rest = dec.decode("\n".join(lines).encode())
        assert keyid.tolist() == [0, 1, 0, 2]
        assert dec.key_count == 3
        assert dec.keys_from(0) == [("b", "z"), ("a", "y"), ("c", "x")]
        assert dec.keys_from(2) == [("c", "x")]
        # interning persists across decode calls
        _, _, keyid2, *_ = dec.decode(self._line(100000, 5, "a", "y").encode())
        assert keyid2.tolist() == [1]

    def test_line_spans_recover_lines(self, dec):
        lines = [self._line(100000 + i, i, i=i) for i in range(5)]
        blob = "\n".join(lines).encode()
        _, _, _, offs, lens, _, _ = dec.decode(blob)
        for i in range(5):
            assert blob[offs[i] : offs[i] + lens[i]].decode() == lines[i]


class TestFeedCsvBytesParity:
    """feed_csv_bytes (native) must be emission-identical to the numpy
    feed_csv_batch across ticks, registration order, backlog, and resume."""

    def _mkcfg(self, native, capacity=64):
        from apmbackend_tpu.config import default_config

        cfg = default_config()
        cfg["tpuEngine"]["serviceCapacity"] = capacity
        cfg["tpuEngine"]["samplesPerBucket"] = 8
        cfg["tpuEngine"]["nativeDecode"] = native
        cfg["streamCalcZScore"]["defaults"] = [{"LAG": 4, "THRESHOLD": 20, "INFLUENCE": 0.1}]
        return cfg

    def _mklines(self, label, n, seed):
        import numpy as np

        r = np.random.RandomState(seed)
        rows = r.randint(0, 40, n)
        elaps = r.randint(50, 900, n)
        return [
            f"tx|jvm{x % 4}|svc{x:03d}|l{i}|1|{label * 10000 - e}|{label * 10000 + i % 9999}|{e}|Y"
            for i, (x, e) in enumerate(zip(rows, elaps))
        ]

    def test_emissions_identical(self):
        from apmbackend_tpu.pipeline import PipelineDriver

        if ensure_built() is None:
            pytest.skip("no native toolchain")
        base = 170_000_000
        outs = {}
        for native in (False, True):
            got = []
            drv = PipelineDriver(
                self._mkcfg(native), micro_batch_size=512,
                on_fullstat_csv=lambda ls: got.extend(ls),
                on_ordered_csv=lambda line: got.append(line),
            )
            for t in range(5):
                lines = self._mklines(base + t, 700, seed=t) + ["junk", "tx|bad"]
                if native:
                    drv.feed_csv_bytes("\n".join(lines).encode())
                else:
                    drv.feed_csv_batch(lines)
            outs[native] = got
            if native:
                assert drv._native_dec is not None  # actually took the native path
        assert outs[False] == outs[True]

    def test_mixed_feed_and_bytes_with_resume(self, tmp_path):
        """feed() object path interleaved with blob batches; resume resets the
        decoder and the restored driver keeps emitting correctly."""
        import numpy as np

        from apmbackend_tpu.entries import TxEntry
        from apmbackend_tpu.pipeline import PipelineDriver

        if ensure_built() is None:
            pytest.skip("no native toolchain")
        cfg = self._mkcfg(True)
        drv = PipelineDriver(cfg, micro_batch_size=256)
        base = 170_000_000
        drv.feed_csv_bytes("\n".join(self._mklines(base, 300, 1)).encode())
        ts = (base + 1) * 10000.0
        drv.feed(TxEntry("jvmX", "svcNew", "L1", "A", ts - 100, ts, 100.0, "Y"))
        drv.feed_csv_bytes("\n".join(self._mklines(base + 2, 300, 2)).encode())
        rows_before = list(drv.registry.rows())
        path = str(tmp_path / "resume.npz")
        drv.save_resume(path)

        drv2 = PipelineDriver(cfg, micro_batch_size=256)
        assert drv2.load_resume(path)
        assert drv2._native_dec is None  # decoder reset with the registry
        drv2.feed_csv_bytes("\n".join(self._mklines(base + 3, 300, 3)).encode())
        assert drv2._native_dec is not None
        # pre-kill keys keep their exact rows (row order is the prefix), and
        # post-restore feeding only appends
        assert list(drv2.registry.rows())[: len(rows_before)] == rows_before
        assert len(drv2.registry.rows()) >= len(rows_before)

    def test_phantom_keys_do_not_register(self):
        """A tx-shaped line whose numerics are unparseable is interned by the
        decoder but NaN-dropped by the intake filter — it must NOT register a
        registry row (the numpy path never would). The key registers later
        if a valid record arrives."""
        from apmbackend_tpu.pipeline import PipelineDriver

        if ensure_built() is None:
            pytest.skip("no native toolchain")
        base = 170_000_000
        phantom = "tx|phantomSrv|phantomSvc|l0|1|abc|abc|abc|Y"
        good = f"tx|goodSrv|goodSvc|l1|1|{base * 10000 - 5}|{base * 10000}|55|Y"
        outs = {}
        for native in (False, True):
            drv = PipelineDriver(self._mkcfg(native), micro_batch_size=64)
            if native:
                drv.feed_csv_bytes(f"{phantom}\n{good}".encode())
                assert drv._native_dec is not None
            else:
                drv.feed_csv_batch([phantom, good])
            outs[native] = list(drv.registry.rows())
            if native:
                # the phantom key registers once a VALID record shows up
                ok_line = f"tx|phantomSrv|phantomSvc|l2|1|{base * 10000 - 3}|{base * 10000 + 1}|33|Y"
                drv.feed_csv_bytes(ok_line.encode())
                assert ("phantomSrv", "phantomSvc") in drv.registry.rows()
        assert outs[False] == outs[True] == [("goodSrv", "goodSvc")]

    def test_phantom_then_valid_interleaved_registration_order(self):
        """A phantom-interned key that later turns valid must register AFTER
        keys whose valid records appeared before it — first-appearance order
        of SURVIVING records, matching the numpy path exactly."""
        from apmbackend_tpu.pipeline import PipelineDriver

        if ensure_built() is None:
            pytest.skip("no native toolchain")
        base = 170_000_000
        lines = [
            "tx|A|A|l0|1|abc|abc|abc|Y",  # key A: interned, NaN-dropped
            f"tx|B|B|l1|1|{base * 10000 - 5}|{base * 10000}|55|Y",  # key B valid
            f"tx|A|A|l2|1|{base * 10000 - 3}|{base * 10000 + 1}|33|Y",  # A valid now
        ]
        outs = {}
        for native in (False, True):
            drv = PipelineDriver(self._mkcfg(native), micro_batch_size=64)
            if native:
                drv.feed_csv_bytes("\n".join(lines).encode())
                assert drv._native_dec is not None
            else:
                drv.feed_csv_batch(lines)
            outs[native] = list(drv.registry.rows())
        assert outs[True] == outs[False] == [("B", "B"), ("A", "A")]

    def test_growth_through_native_path(self):
        """Capacity growth (recompile) triggered by decoder-fed keys."""
        from apmbackend_tpu.pipeline import PipelineDriver

        if ensure_built() is None:
            pytest.skip("no native toolchain")
        cfg = self._mkcfg(True, capacity=8)
        drv = PipelineDriver(cfg, micro_batch_size=64)
        base = 170_000_000
        lines = [
            f"tx|j|svc{i}|l{i}|1|{base * 10000 - 5}|{base * 10000 + i}|{50 + i}|Y"
            for i in range(20)  # 20 services > capacity 8 -> two growths
        ]
        n = drv.feed_csv_bytes("\n".join(lines).encode())
        assert n == 20
        assert drv.cfg.capacity >= 20
        assert len(drv.registry.rows()) == 20

    def test_non_ascii_blob_backlog_and_keys(self):
        """UTF-8 service names: the ordered-CSV backlog takes the per-line
        decode fallback (blob.isascii() False) and decoder key interning is
        byte-faithful — emissions still match the numpy path."""
        from apmbackend_tpu.pipeline import PipelineDriver

        if ensure_built() is None:
            pytest.skip("no native toolchain")
        base = 170_000_000
        svc = "svcĀéè"  # multi-byte UTF-8
        lines = [
            f"tx|jvmÜ|{svc}|l{i}|1|{(base + i // 50) * 10000 - 5}|"
            f"{(base + i // 50) * 10000 + i}|{50 + i}|Y"
            for i in range(150)
        ]
        outs = {}
        for native in (False, True):
            got = []
            drv = PipelineDriver(
                self._mkcfg(native), micro_batch_size=64,
                on_fullstat_csv=lambda ls: got.extend(ls),
                on_ordered_csv=lambda line: got.append(line),
            )
            if native:
                drv.feed_csv_bytes("\n".join(lines).encode("utf-8"))
                assert drv._native_dec is not None
            else:
                drv.feed_csv_batch(lines)
            outs[native] = got
            assert ("jvmÜ", svc) in drv.registry.rows()
        assert outs[False] == outs[True]
