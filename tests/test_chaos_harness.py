"""Crash-consistency chaos tier: kill−9 a live worker mid-stream, restart,
and prove the recovered run EQUALS a crash-free golden run.

Two tiers:

- fast (tier-1): the full worker epoch cycle over the durable spool broker
  with an in-process "crash" (abandon the worker object without shutdown —
  no flush, no final save, exactly the state a SIGKILL leaves on disk);
- ``slow``: the real thing — ``ChaosWorkerHarness`` spawns the production
  worker as a subprocess, SIGKILLs it twice at cursor-chosen points under
  duplicate-injection chaos, restarts it, and compares the final resume
  snapshot array-for-array against the golden run. Run explicitly via
  ``./run_tests.sh --chaos``.

Equivalence claim proved here (ISSUE 3 acceptance): for every fully-acked
epoch the recovered windowed stats (TPM/avg/p75/p95 reservoir + z-state)
are bit-identical to the crash-free run, zero messages are lost, and every
redelivery is accounted for in the dedup counter.
"""

import os
import time

import numpy as np
import pytest

from apmbackend_tpu.config import default_config
from apmbackend_tpu.testing.chaos import ChaosWorkerHarness, SpoolChannel
from apmbackend_tpu.transport.base import QueueManager


def make_stream(n_labels=8, per_label=100, seed=0):
    base = 170_000_000
    rng = np.random.RandomState(seed)
    lines = []
    for t in range(n_labels):
        for i in range(per_label):
            e = int(rng.randint(50, 900))
            lines.append(
                f"tx|jvm{i % 3}|svc{i % 12:03d}|l{t}-{i}|1|{(base + t) * 10000 - e}|"
                f"{(base + t) * 10000 + i}|{e}|Y"
            )
    return lines


ENGINE_KEYS_IGNORED = {"delivery_state"}  # epoch/window counts legitimately differ


def assert_snapshots_equal(path_a, path_b):
    with np.load(path_a, allow_pickle=True) as za:
        a = {k: za[k] for k in za.files}
    with np.load(path_b, allow_pickle=True) as zb:
        b = {k: zb[k] for k in zb.files}
    keys_a = set(a) - ENGINE_KEYS_IGNORED
    keys_b = set(b) - ENGINE_KEYS_IGNORED
    assert keys_a == keys_b, (keys_a ^ keys_b)
    for k in sorted(keys_a):
        x, y = a[k], b[k]
        if x.dtype.kind == "f":
            ok = np.array_equal(x, y, equal_nan=True)
        else:
            ok = np.array_equal(x, y)
        assert ok, f"snapshot array {k!r} diverged after crash recovery"


# -- fast tier: in-process crash over the durable spool -----------------------


def _spool_worker(spool_dir, resume_path, *, dup_p=0.0, seed=0):
    """The chaos child's wiring, in-process: real WorkerApp, atLeastOnce,
    spool transport. Returns (worker, runtime, consumer_spool)."""
    from apmbackend_tpu.runtime.module_base import ModuleRuntime
    from apmbackend_tpu.runtime.worker import WorkerApp
    from apmbackend_tpu.testing.chaos import ChaosChannel

    cfg = default_config()
    eng = cfg["tpuEngine"]
    eng["serviceCapacity"] = 32
    eng["samplesPerBucket"] = 64
    eng["deliveryMode"] = "atLeastOnce"
    eng["resumeFileFullPath"] = resume_path
    cfg["streamCalcZScore"]["defaults"] = [{"LAG": 6, "THRESHOLD": 3.0, "INFLUENCE": 0.1}]
    cfg["streamCalcStats"]["resumeFileSaveFrequencyInSeconds"] = 3600  # manual commits
    cfg["streamProcessAlerts"]["alertsResumeFileFullPath"] = None
    cfg["logDir"] = None
    rt = ModuleRuntime("tpuEngine", config=cfg, install_signals=False, console_log=False)
    spools = {}

    def factory(direction):
        ch = SpoolChannel(spool_dir)
        spools[direction] = ch
        if direction == "c" and dup_p:
            return ChaosChannel(ch, dup_p=dup_p, seed=seed)
        return ch

    rt.qm = QueueManager(factory, 3600, logger=rt.logger)
    worker = WorkerApp(rt)
    return worker, rt, spools["c"]


def _feed_spool(spool_dir, lines, start_seq=0):
    import time

    prod = SpoolChannel(spool_dir)
    for n, line in enumerate(lines, start=start_seq + 1):
        prod.send(
            "transactions", line.encode("utf-8"),
            {"ingest_ts": time.time(), "msg_id": f"h-{n}"},
        )
    prod.close()


def test_in_process_crash_equivalence_over_spool(tmp_path):
    lines = make_stream(n_labels=5, per_label=60)

    # golden: absorb everything, one final commit
    gdir = str(tmp_path / "golden")
    gres = str(tmp_path / "golden.npz")
    _feed_spool(gdir, lines)
    w, rt, spool = _spool_worker(gdir, gres)
    n = 0
    while n < len(lines):
        n += spool.deliver(50)
    w.save_state()
    assert spool.acked_count("transactions") == len(lines)
    rt.stop_timers()
    spool.stop()

    # chaos: dup injection, commit mid-stream, CRASH (no shutdown), recover
    cdir = str(tmp_path / "chaos")
    cres = str(tmp_path / "chaos.npz")
    _feed_spool(cdir, lines)
    w1, rt1, spool1 = _spool_worker(cdir, cres, dup_p=0.15, seed=11)
    delivered = 0
    while delivered < 120:
        delivered += spool1.deliver(30)
        if delivered == 60:
            w1.save_state()  # one committed epoch
    committed = spool1.acked_count("transactions")
    assert committed > 0
    # SIGKILL stand-in: walk away — no flush, no save, no acks
    rt1.stop_timers()
    spool1.stop()

    w2, rt2, spool2 = _spool_worker(cdir, cres, dup_p=0.15, seed=12)
    assert w2._delivery_epoch >= 1  # resumed the committed epoch watermark
    n = spool2.delivered_count("transactions")
    assert n == committed  # redelivery starts AT the cursor: zero loss
    while n < len(lines):
        n += spool2.deliver(50)
    w2.save_state()
    assert spool2.acked_count("transactions") == len(lines)
    # messages absorbed by w1 after its commit were redelivered to w2 and
    # re-absorbed (not deduped: the crash discarded their uncommitted
    # absorption); in-flight duplicates WERE deduped
    assert w2._deduped_total >= 0
    rt2.stop_timers()
    spool2.stop()

    assert_snapshots_equal(gres, cres)


def test_in_process_redelivery_of_committed_epoch_dedups(tmp_path):
    """Crash BETWEEN checkpoint and ack: the delivered-but-committed slice
    is redelivered and must be skipped, every skip counted."""
    lines = make_stream(n_labels=3, per_label=40)
    d = str(tmp_path / "sp")
    res = str(tmp_path / "r.npz")
    _feed_spool(d, lines)

    w1, rt1, spool1 = _spool_worker(d, res)
    n = 0
    while n < len(lines):
        n += spool1.deliver(50)
    # checkpoint WITHOUT ack = the crash window between save and ack:
    # hijack by saving the resume directly through the driver
    with w1._driver_lock:
        w1.driver.flush()
        w1.driver.save_resume(
            res,
            delivery={
                "transactions": {
                    "epoch": 1,
                    "dedup": list(w1._dedup_fifo),
                    "deduped_total": 0,
                }
            },
        )
    rt1.stop_timers()
    spool1.stop()  # crash: acks never happened, cursor still 0

    w2, rt2, spool2 = _spool_worker(d, res)
    tx_before = int(np.asarray(w2.driver.state.stats.counts).sum())
    n = 0
    while n < len(lines):
        n += spool2.deliver(50)
    assert w2._deduped_total == len(lines)  # every redelivery accounted for
    assert int(np.asarray(w2.driver.state.stats.counts).sum()) == tx_before
    w2.save_state()
    assert spool2.acked_count("transactions") == len(lines)  # deduped acks advance the cursor
    rt2.stop_timers()
    spool2.stop()


# -- slow tier: real SIGKILL subprocesses -------------------------------------


@pytest.mark.slow
def test_kill9_crash_equivalence_subprocess(tmp_path):
    """THE acceptance scenario: SIGKILL a live worker subprocess twice
    mid-stream under duplicate-injection chaos, restart from checkpoint, and
    the final windowed stats equal the crash-free golden run exactly."""
    lines = make_stream(n_labels=10, per_label=120)

    golden = ChaosWorkerHarness(str(tmp_path / "golden"), dup_p=0.0, seed=1)
    for line in lines:
        golden.send_line(line)
    golden.start()
    stats_g = golden.finish(timeout_s=240)
    golden.close()
    assert stats_g["acked"] == len(lines)
    assert stats_g["deduped_total"] == 0

    chaos = ChaosWorkerHarness(str(tmp_path / "chaos"), dup_p=0.08, seed=7)
    for line in lines:
        chaos.send_line(line)
    chaos.start()
    chaos.wait_acked(len(lines) // 3)
    chaos.kill9()
    first_kill_cursor = chaos.acked()
    chaos.start()
    # wait_rearmed matches the live journal's pid stamp against the new
    # child, so a stale pre-kill journal (recover_crash consumes only the
    # sentinel) can't satisfy the re-arm check early.
    chaos.wait_rearmed(1)
    chaos.wait_acked(2 * len(lines) // 3)
    chaos.kill9()
    assert chaos.acked() >= first_kill_cursor  # the cursor never regresses
    chaos.start()
    stats_c = chaos.finish(timeout_s=240)
    chaos.close()

    assert stats_c["acked"] == len(lines)  # zero message loss
    assert stats_c["deduped_total"] > 0  # redeliveries happened AND were caught
    assert stats_c["services"] == stats_g["services"]
    assert stats_c["latest_label"] == stats_g["latest_label"]
    assert_snapshots_equal(golden.resume_path, chaos.resume_path)

    # flight recorder (ISSUE 5): each kill−9 left a journal+sentinel shadow
    # that the NEXT boot promoted into a parseable ...-crash.json bundle —
    # while the run above stayed bit-identical to the golden snapshot. The
    # golden (never-killed) run exits cleanly and must promote nothing.
    crash_bundles = [
        (p, b) for p, b in chaos.flight_bundles()
        if b.get("recovered") and p.endswith("-crash.json")
    ]
    assert len(crash_bundles) >= 2  # two SIGKILLs, two promoted journals
    for _path, body in crash_bundles:
        journal = body.get("journal")
        assert journal, "crash bundle must carry the promoted journal"
        assert journal["module"]  # parseable, source-populated shadow
        assert "engine_health" in journal and "config_hash" in journal
    assert not [
        (p, b) for p, b in golden.flight_bundles() if b.get("recovered")
    ]


@pytest.mark.slow
def test_kill9_immediately_after_start(tmp_path):
    """Degenerate kill point: before any epoch commits. Restart must begin
    from scratch with zero committed cursor and still converge."""
    lines = make_stream(n_labels=4, per_label=60)
    h = ChaosWorkerHarness(str(tmp_path / "h"), dup_p=0.0, seed=3)
    for line in lines:
        h.send_line(line)
    h.start()
    h.kill9()  # likely before the first commit — cursor 0 is a valid state
    h.start()
    stats = h.finish(timeout_s=240)
    h.close()
    assert stats["acked"] == len(lines)
