"""Device multivariate JMX detector (ops/multivariate.py).

The reference has no JMX detector (pull_jvm_stats.js only persists samples);
these tests pin the new capability's contract: EW mean/cov recursion,
normalized Mahalanobis scoring, warm-up gating, NaN masking, influence
damping, growth, and the JmxEntry feature map.
"""

import math

import jax.numpy as jnp
import numpy as np
import pytest

from apmbackend_tpu.entries import JmxEntry
from apmbackend_tpu.ops import multivariate as mv


def make_spec(**kw):
    defaults = dict(n_features=3, alpha=0.2, threshold=3.0, warmup=5, influence=1.0)
    defaults.update(kw)
    return mv.MvSpec(**defaults)


def run_steps(spec, xs, capacity=2):
    state = mv.init_state(capacity, spec, jnp.float64)
    results = []
    for x in xs:
        x = np.asarray(x, np.float64)
        if x.ndim == 1:
            x = np.tile(x[None, :], (capacity, 1))
        res, state = mv.step(state, spec, x, np.ones(capacity, bool))
        results.append(res)
    return results, state


class TestStep:
    def test_warmup_gates_score(self):
        spec = make_spec(warmup=5)
        rng = np.random.RandomState(0)
        xs = [rng.randn(3) for _ in range(7)]
        results, _ = run_steps(spec, xs)
        for res in results[:5]:
            assert math.isnan(float(res.score[0]))
            assert int(res.signal[0]) == 0
        assert not math.isnan(float(results[5].score[0]))

    def test_inlier_scores_low_outlier_scores_high(self):
        spec = make_spec(warmup=10, threshold=3.0, alpha=0.1)
        rng = np.random.RandomState(1)
        xs = [100 + rng.randn(3) for _ in range(60)]
        results, state = run_steps(spec, xs)
        warm_scores = [float(r.score[0]) for r in results[15:]]
        assert max(warm_scores) < 3.0  # in-distribution stays quiet
        res, state = mv.step(
            state, spec, np.tile(np.array([200.0, 200.0, 200.0]), (2, 1)), np.ones(2, bool)
        )
        assert float(res.score[0]) > 3.0
        assert int(res.signal[0]) == 1

    def test_correlation_aware(self):
        # two strongly correlated dims; a sample that breaks the correlation
        # but stays within marginal ranges must outscore one that follows it
        spec = make_spec(n_features=2, warmup=10, alpha=0.05, threshold=3.0)
        rng = np.random.RandomState(2)
        xs = []
        for _ in range(200):
            a = rng.randn()
            xs.append(np.array([a, a + 0.01 * rng.randn()]))
        _, state = run_steps(spec, xs, capacity=1)
        aligned, s1 = mv.step(state, spec, np.array([[1.5, 1.5]]), np.ones(1, bool))
        broken, s2 = mv.step(state, spec, np.array([[1.5, -1.5]]), np.ones(1, bool))
        assert float(broken.score[0]) > float(aligned.score[0]) * 5

    def test_nan_dims_masked(self):
        spec = make_spec(warmup=3, alpha=0.2)
        rng = np.random.RandomState(3)
        xs = [10 + rng.randn(3) for _ in range(10)]
        _, state = run_steps(spec, xs, capacity=1)
        mean_before = np.asarray(state.mean).copy()
        x = np.array([[10.0, np.nan, np.nan]])
        res, state = mv.step(state, spec, x, np.ones(1, bool))
        assert int(res.observed[0]) == 1
        assert not math.isnan(float(res.score[0]))
        # unobserved dims untouched
        np.testing.assert_allclose(np.asarray(state.mean)[0, 1:], mean_before[0, 1:])

    def test_invalid_row_untouched(self):
        spec = make_spec(warmup=1)
        state = mv.init_state(2, spec, jnp.float64)
        x = np.tile(np.arange(3.0)[None, :], (2, 1))
        res, state = mv.step(state, spec, x, np.array([True, False]))
        assert int(state.count[0]) == 1
        assert int(state.count[1]) == 0
        assert np.all(np.isnan(np.asarray(state.mean)[1]))

    def test_first_sample_seeds_mean(self):
        spec = make_spec(warmup=1)
        state = mv.init_state(1, spec, jnp.float64)
        x = np.array([[5.0, 6.0, 7.0]])
        _, state = mv.step(state, spec, x, np.ones(1, bool))
        np.testing.assert_allclose(np.asarray(state.mean)[0], [5.0, 6.0, 7.0])

    def test_influence_damps_anomaly_update(self):
        rng = np.random.RandomState(4)
        xs = [50 + rng.randn(3) for _ in range(40)]
        outlier = np.array([500.0, 500.0, 500.0])
        spec_full = make_spec(warmup=5, influence=1.0, alpha=0.2)
        spec_damped = spec_full._replace(influence=0.0)
        _, s_full = run_steps(spec_full, xs + [outlier], capacity=1)
        _, s_damped = run_steps(spec_damped, xs + [outlier], capacity=1)
        drift_full = abs(float(s_full.mean[0, 0]) - 50.0)
        drift_damped = abs(float(s_damped.mean[0, 0]) - 50.0)
        assert drift_damped < drift_full / 10

    def test_bias_corrected_early_scores(self):
        # right after a short warmup the EW covariance is far below the true
        # variance; bias correction must keep iid-noise scores below the
        # threshold instead of mass false-signaling
        spec = make_spec(n_features=8, warmup=16, alpha=0.05, threshold=3.0)
        rng = np.random.RandomState(7)
        state = mv.init_state(16, spec, jnp.float64)
        signals = 0
        scored = 0
        for _ in range(24):
            x = 100 + rng.randn(16, 8)
            res, state = mv.step(state, spec, x, np.ones(16, bool))
            sig = np.asarray(res.signal)
            score = np.asarray(res.score)
            signals += int(sig.sum())
            scored += int(np.sum(~np.isnan(score)))
        assert scored > 0
        assert signals <= scored * 0.05  # ~zero false positives on iid noise

    def test_constant_dim_does_not_false_alarm(self):
        # a metric constant for 100 polls collapses its EW variance; the next
        # +-1 blip must NOT divide by the eps floor and signal (std-floor gate,
        # zero-variance parity with ops/ewma.py has_std)
        spec = make_spec(warmup=5, alpha=0.05)
        rng = np.random.RandomState(6)
        xs = [np.array([30000.0, 200 + rng.randn(), 1.5 + 0.1 * rng.randn()]) for _ in range(100)]
        _, state = run_steps(spec, xs, capacity=1)
        res, state = mv.step(
            state, spec, np.array([[30001.0, 200.0, 1.5]]), np.ones(1, bool)
        )
        assert int(res.signal[0]) == 0
        assert float(res.score[0]) < 3.0
        # the collapsed dim is excluded from scoring but still tracks: its
        # mean moves toward the new value and variance re-inflates
        assert float(state.mean[0, 0]) > 30000.0
        assert float(state.cov[0, 0, 0]) > 0.0

    def test_grow_state(self):
        spec = make_spec(warmup=1)
        _, state = run_steps(spec, [np.ones(3)], capacity=2)
        grown = mv.grow_state(state, 4)
        assert grown.mean.shape == (4, 3)
        assert grown.cov.shape == (4, 3, 3)
        assert np.all(np.isnan(np.asarray(grown.mean)[2:]))
        with pytest.raises(ValueError):
            mv.grow_state(state, 1)


def make_entry(**kw):
    base = dict(
        timestamp=1.7e12, server="jvm1",
        ds_in_use_nodes=5, ds_active_nodes=10, ds_available_nodes=20,
        heap_used=4e9, heap_committed=6e9, heap_max=8e9,
        meta_used=2e8, meta_committed=3e8, meta_max=4e8,
        sys_load=1.5, class_cnt=30000, thread_cnt=200, daemon_thread_cnt=150,
        bean_pool_available_count=90, bean_pool_current_size=100, bean_pool_max_size=128,
    )
    base.update(kw)
    return JmxEntry(**base)


class TestJmxFeatures:
    def test_shape_and_ratios(self):
        f = mv.jmx_features(make_entry())
        assert f.shape == (mv.JMX_FEATURE_COUNT,)
        assert f[2] == pytest.approx(5 / 20)  # ds utilization
        assert f[3] == pytest.approx(0.5)  # heap fraction
        assert f[10] == pytest.approx(10 / 128)  # bean pool in-use fraction

    def test_missing_capacity_is_nan(self):
        f = mv.jmx_features(make_entry(heap_max=float("nan")))
        assert math.isnan(f[3]) and math.isnan(f[4])
        f2 = mv.jmx_features(make_entry(heap_max=0))
        assert math.isnan(f2[3])


class TestMvDriver:
    def test_feed_registry_and_growth(self):
        d = mv.MvDriver(make_spec(n_features=mv.JMX_FEATURE_COUNT, warmup=2), capacity=2)
        servers = [f"jvm{i}" for i in range(5)]  # forces growth past 2 -> 8
        for tick in range(4):
            out = d.feed([make_entry(server=s, sys_load=1.0 + 0.01 * tick) for s in servers])
            assert [o["server"] for o in out] == servers
        assert d.capacity == 8
        assert len(d.rows) == 5
        assert all(not math.isnan(o["score"]) for o in out)
        assert all(o["signal"] == 0 for o in out)

    def test_detects_fleet_outlier(self):
        d = mv.MvDriver(
            make_spec(n_features=mv.JMX_FEATURE_COUNT, warmup=5, alpha=0.1, threshold=3.0),
            capacity=2,
        )
        rng = np.random.RandomState(5)
        for _ in range(30):
            d.feed([make_entry(sys_load=1.5 + 0.05 * rng.randn(),
                               thread_cnt=200 + rng.randint(-3, 4))])
        out = d.feed([make_entry(sys_load=30.0, thread_cnt=900)])
        assert out[0]["signal"] == 1

    def test_empty_feed(self):
        d = mv.MvDriver(make_spec(n_features=mv.JMX_FEATURE_COUNT))
        assert d.feed([]) == []

    def test_resume_roundtrip(self, tmp_path):
        spec = make_spec(n_features=mv.JMX_FEATURE_COUNT, warmup=3, alpha=0.1)
        d = mv.MvDriver(spec, capacity=2)
        rng = np.random.RandomState(8)
        for _ in range(6):
            d.feed([make_entry(server=s, sys_load=1.5 + 0.1 * rng.randn())
                    for s in ("jvm1", "jvm2", "jvm3")])
        path = str(tmp_path / "mv.npz")
        d.save_resume(path)

        d2 = mv.MvDriver(spec, capacity=2)
        assert d2.load_resume(path)
        assert d2.rows == d.rows
        np.testing.assert_allclose(np.asarray(d2.state.mean), np.asarray(d.state.mean))
        np.testing.assert_allclose(np.asarray(d2.state.cov), np.asarray(d.state.cov))
        # resumed driver keeps scoring without re-warmup
        out = d2.feed([make_entry(server="jvm1")])
        assert not math.isnan(out[0]["score"])

    def test_resume_spec_mismatch_starts_fresh(self, tmp_path):
        spec = make_spec(n_features=mv.JMX_FEATURE_COUNT, warmup=2)
        d = mv.MvDriver(spec, capacity=2)
        d.feed([make_entry()])
        path = str(tmp_path / "mv.npz")
        d.save_resume(path)
        other = mv.MvDriver(spec._replace(alpha=0.5), capacity=2)
        assert not other.load_resume(path)
        assert other.rows == {}

    def test_resume_corrupt_file_starts_fresh(self, tmp_path):
        path = tmp_path / "mv.npz"
        path.write_bytes(b"not a zip")
        d = mv.MvDriver(make_spec(n_features=mv.JMX_FEATURE_COUNT))
        assert not d.load_resume(str(path))
        assert not d.load_resume(str(tmp_path / "missing.npz"))
