"""APF1 frame spine (ISSUE 16): codec identity, parser frame emission,
opaque carry across all four broker fabrics, the shared-memory ring, fleet
frame routing, and worker intake parity.

The invariant under test everywhere: turning frames ON changes the number
of Python objects and transport messages, never the records the engine
sees — frames vs per-line must be record-identical through every layer,
and every kill switch (APM_NO_FRAMES, APM_FRAMES_NO_NATIVE,
transport.frameMode, tpuEngine.feedFrames) must degrade to the exact
pre-frame behaviour.
"""

import os
import time

import pytest

from apmbackend_tpu.parallel.fleet import (
    FleetPartitioner,
    partition_queue,
    service_partition,
    tx_partition_key,
)
from apmbackend_tpu.transport import MemoryBroker, frames, make_queue_manager
from apmbackend_tpu.transport.base import QueueManager
from apmbackend_tpu.transport.memory import MemoryChannel
from apmbackend_tpu.transport.spool import SpoolChannel

try:
    from apmbackend_tpu.native import have_native_parser

    HAVE_NATIVE = have_native_parser()
except Exception:
    HAVE_NATIVE = False

needs_native = pytest.mark.skipif(
    not HAVE_NATIVE, reason="no C++ toolchain: native frame packer unavailable"
)


def _mk_qm(broker):
    return QueueManager(lambda d: MemoryChannel(broker), stat_log_interval_s=3600)


CORPUS = (
    [f"tx|jvm{i % 5}|svc{i % 17:02d}|log{i}|1|{1700000000 + i}|"
     f"{1700000100 + i}|{50 + i}|{'Y' if i % 3 else 'N'}" for i in range(64)]
    + [
        "tx|srv|svç|unïcode|1|1700000000|1700000100|100|Y",      # unicode svc
        "tx|srv|svc| résumé café |1|1700000000|1700000100|7|N",  # unicode id
        "tx|srv|svc|exotic|1| 123 |1e3|0x10|Y",                  # exotic f8s
        "tx|srv|svc|neg|1|-5|+7|1_0|N",                          # signs/junk
        "tx|short",                                              # tx| but <4 fields
        "log|not|a|transaction",
        "",                                                      # empty line
        "noise with spaces and | pipes | everywhere",
    ]
)


# -- codec --------------------------------------------------------------------


def test_roundtrip_identity_and_counts():
    blob = frames.encode_lines(CORPUS)
    assert frames.is_frames(blob)
    assert frames.frame_count(blob) == len(CORPUS)
    assert frames.decode_lines(blob) == CORPUS
    # tx classification matches the worker's is_tx rule (startswith "tx|")
    assert frames.tx_count(blob) == sum(
        1 for l in CORPUS if l.startswith("tx|")
    )
    s = frames.summarize(blob)
    assert s["records"] == len(CORPUS) and s["tx"] == frames.tx_count(blob)


def test_oversized_line_roundtrips_as_nontx():
    big = "tx|srv|svc|" + "x" * 70000 + "|1|1|2|3|Y"  # spans overflow u16
    blob = frames.encode_lines(["tx|a|b|c|1|1|2|3|Y", big])
    assert frames.decode_lines(blob) == ["tx|a|b|c|1|1|2|3|Y", big]
    assert frames.tx_count(blob) == 1  # oversized record flagged non-tx


def test_corrupt_blobs_rejected():
    blob = bytearray(frames.encode_lines(CORPUS[:4]))
    assert not frames.is_frames(b"tx|plain|line")
    assert not frames.is_frames("APF1 but str payloads are never frames")
    # is_frames is a cheap magic sniff; the envelope check is what rejects
    for bad in (bytes(blob[:12]), b"NOPE" + bytes(blob[4:]),
                bytes(blob[:40])):  # records region torn off
        with pytest.raises(frames.FrameError):
            frames.decode_lines(bad)


@needs_native
def test_native_and_python_encoders_bit_identical(monkeypatch):
    native = frames.encode_lines(CORPUS)
    monkeypatch.setenv("APM_FRAMES_NO_NATIVE", "1")
    assert bytes(frames.encode_lines(CORPUS)) == bytes(native)


# -- partition routing off the frame spans ------------------------------------


@pytest.mark.parametrize("key", ["service", "server"])
def test_partition_ids_match_per_line_hash(key):
    blob = frames.encode_lines(CORPUS)
    want = []
    for line in CORPUS:
        k = tx_partition_key(line, key)
        want.append(service_partition(k, 7) if k is not None else 0)
    assert frames.partition_ids(blob, 7, key=key) == want


def test_split_by_partition_preserves_records():
    blob = frames.encode_lines(CORPUS)
    parts = frames.split_by_partition(blob, 5)
    ids = frames.partition_ids(blob, 5)
    regrouped = {}
    for line, p in zip(CORPUS, ids):
        regrouped.setdefault(p, []).append(line)
    assert {p: frames.decode_lines(b) for p, b in parts.items()} == regrouped
    for p, sub in parts.items():
        assert frames.count_partition_mismatches(sub, 5, p) == 0
        wrong = (p + 1) % 5
        if any(tx_partition_key(l) is not None for l in regrouped[p]):
            assert frames.count_partition_mismatches(sub, 5, wrong) > 0


# -- parser frame emission ----------------------------------------------------


def _feed_fixture(parser, tmp_path, n=120, seed=9):
    from apmbackend_tpu.ingest.replay import write_fixture_logs

    paths = write_fixture_logs(str(tmp_path), n_transactions=n, seed=seed)
    for fp in sorted(paths.values()):
        parser.read_lines(fp, open(fp, "rb").read())
    parser.drain()


def test_parser_frame_emission_matches_per_record(tmp_path):
    from apmbackend_tpu.ingest.parser import TransactionParser

    ref_lines, db_ref = [], []
    ref = TransactionParser(
        lambda tx, db: (db_ref if db else ref_lines).append(tx.to_csv()),
        server_from_path=lambda fp: "jvm1",
    )
    _feed_fixture(ref, tmp_path / "ref")

    got_frames, db_frames = [], []
    fp_parser = TransactionParser(
        lambda tx, db: db_frames.append(tx.to_csv()),
        server_from_path=lambda fp: "jvm1",
        frame_sink=lambda blob, n: got_frames.append((bytes(blob), n)),
        frame_max_records=32,
    )
    _feed_fixture(fp_parser, tmp_path / "fr")

    emitted = [l for blob, _n in got_frames for l in frames.decode_lines(blob)]
    assert emitted == ref_lines  # queue-bound stream identical, order kept
    assert db_frames == db_ref   # db-direct records still object-path
    c = fp_parser.counters
    assert c["frames_emitted"] == len(got_frames) > 1  # max_records flushed
    assert c["frame_records_out"] == len(emitted)
    assert all(n == frames.frame_count(b) <= 32 for b, n in got_frames)


def test_apm_no_frames_kill_switch(monkeypatch):
    from apmbackend_tpu.ingest.parser import TransactionParser

    monkeypatch.setenv("APM_NO_FRAMES", "1")
    p = TransactionParser(lambda tx, db: None, frame_sink=lambda b, n: None)
    assert p.frame_sink is None  # falls back to the per-record object path


# -- opaque carry across the four fabrics -------------------------------------


def _assert_carry(send, drive, got):
    """Producer-agnostic carry contract: bit-identical payload, batch
    headers stamped once, frames_aware consumer sees the raw blob."""
    blob = frames.encode_lines(CORPUS)
    send(blob, len(CORPUS))
    drive(lambda: len(got) >= 1)
    assert len(got) == 1
    payload, headers = got[0]
    assert isinstance(payload, (bytes, bytearray, memoryview))
    assert bytes(payload) == bytes(blob)
    assert headers["frames"] == len(CORPUS)
    assert "msg_id" in headers and "ingest_ts" in headers
    return headers


def test_memory_fabric_carries_frames():
    broker = MemoryBroker()
    prod = _mk_qm(broker).get_queue("q", "p")
    got = []
    cons = _mk_qm(broker).get_queue("q", "c", lambda p, h: got.append((p, h)))
    cons.frames_aware = True
    cons.start_consume()
    _assert_carry(prod.write_frames, lambda done: broker.pump(), got)


def test_spool_fabric_carries_frames(tmp_path):
    ch = SpoolChannel(str(tmp_path))
    prod = QueueManager(lambda d: ch, stat_log_interval_s=3600).get_queue("q", "p")
    got = []
    cons = QueueManager(lambda d: ch, stat_log_interval_s=3600).get_queue(
        "q", "c", lambda p, h, t: got.append((p, h)), manual_ack=True
    )
    cons.frames_aware = True
    cons.start_consume()
    _assert_carry(prod.write_frames, lambda done: ch.deliver(), got)
    # one spool record per batch: the ack cursor advances batch-wise
    assert ch.delivered_count("q") == 1
    ch.close()


def test_redis_fabric_carries_frames():
    from fake_redis import FakeRedisServer, make_fake_redis

    from apmbackend_tpu.transport.redis_streams import RedisStreamsChannel

    server = FakeRedisServer()

    def mk():
        return RedisStreamsChannel(
            "redis://fake", redis_module=make_fake_redis(server))

    pch, cch = mk(), mk()
    prod = QueueManager(lambda d: pch, stat_log_interval_s=3600).get_queue("q", "p")
    got = []
    cons = QueueManager(lambda d: cch, stat_log_interval_s=3600).get_queue(
        "q", "c", lambda p, h: got.append((p, h)))
    cons.frames_aware = True
    cons.start_consume()
    _assert_carry(prod.write_frames, lambda done: cch.deliver(), got)
    pch.close(), cch.close()


def test_amqp_fabric_carries_frames():
    from fake_pika import FakeBroker, make_fake_pika

    from apmbackend_tpu.transport.amqp import AmqpChannel

    mod = make_fake_pika(FakeBroker())

    def mk(kind):
        return AmqpChannel("amqp://fake", direction=kind, pika_module=mod,
                           poll_interval_s=0.005)

    pch, cch = mk("p"), mk("c")
    try:
        prod = QueueManager(lambda d: pch, stat_log_interval_s=3600).get_queue("q", "p")
        got = []
        cons = QueueManager(lambda d: cch, stat_log_interval_s=3600).get_queue(
            "q", "c", lambda p, h: got.append((p, h)))
        cons.frames_aware = True
        cons.start_consume()

        def drive(done):
            deadline = time.time() + 5.0
            while not done() and time.time() < deadline:
                time.sleep(0.01)

        _assert_carry(prod.write_frames, drive, got)
    finally:
        pch.close(), cch.close()


def test_unaware_consumer_unfolds_frames():
    broker = MemoryBroker()
    prod = _mk_qm(broker).get_queue("q", "p")
    got = []
    _mk_qm(broker).get_queue("q", "c", got.append).start_consume()
    prod.write_frames(frames.encode_lines(CORPUS), len(CORPUS))
    broker.pump()
    assert got == CORPUS


def test_decode_error_drops_and_counts():
    broker = MemoryBroker()
    # a raw channel send bypassing write_frames: corrupt blob on the wire
    pch = MemoryChannel(broker)
    pch.assert_queue("q")
    bad = bytes(frames.encode_lines(CORPUS))[:-3]  # truncated lines region
    got = []
    _mk_qm(broker).get_queue("q", "c", got.append).start_consume()
    before = _metric_value("apm_frame_decode_errors_total")
    pch.send("q", bad, {"frames": len(CORPUS)})
    broker.pump()
    assert got == []  # dropped, not delivered as garbage
    assert _metric_value("apm_frame_decode_errors_total") == before + 1


def _metric_value(name):
    from apmbackend_tpu.obs import get_registry

    total = 0.0
    for line in get_registry().render().splitlines():
        if line.startswith(name):
            total += float(line.rsplit(" ", 1)[1])
    return total


# -- shared-memory ring -------------------------------------------------------


def _shm_pair(tmp_path, ring_bytes=1 << 16):
    from apmbackend_tpu.transport.shmring import ShmRingChannel

    prod_ch = ShmRingChannel(str(tmp_path), ring_bytes=ring_bytes)
    cons_ch = ShmRingChannel(str(tmp_path), ring_bytes=ring_bytes)
    return prod_ch, cons_ch


def test_shmring_lines_and_frames_roundtrip(tmp_path):
    prod_ch, cons_ch = _shm_pair(tmp_path)
    prod = QueueManager(lambda d: prod_ch, stat_log_interval_s=3600).get_queue("q", "p")
    got = []
    cons = QueueManager(lambda d: cons_ch, stat_log_interval_s=3600).get_queue(
        "q", "c", lambda p, h: got.append((p, h)))
    cons.frames_aware = True
    cons.start_consume()
    prod.write_line("tx|a|b|c|1|2|3|4|Y")
    blob = frames.encode_lines(CORPUS)
    prod.write_frames(blob, len(CORPUS))
    cons_ch.deliver()
    assert got[0][0] == "tx|a|b|c|1|2|3|4|Y"
    payload, h = got[1]
    assert bytes(payload) == bytes(blob) and h["frames"] == len(CORPUS)
    assert cons_ch.queue_lag("q") == 0
    assert "apm_shmring_occupancy_bytes" in __import__(
        "apmbackend_tpu.obs", fromlist=["get_registry"]).get_registry().render()
    prod_ch.close(), cons_ch.close()


def test_shmring_backpressure_pause_and_polled_drain(tmp_path):
    prod_ch, cons_ch = _shm_pair(tmp_path)
    qm_p = QueueManager(lambda d: prod_ch, stat_log_interval_s=3600)
    prod = qm_p.get_queue("q", "p")
    got = []
    cons = QueueManager(lambda d: cons_ch, stat_log_interval_s=3600).get_queue(
        "q", "c", lambda p, h: got.append(p))
    cons.start_consume()
    drained = []
    prod_ch.on_drain(lambda: drained.append(1))
    big = "x" * 1000
    sent = 0
    while not prod.paused:
        prod.write_line(f"tx|s|s|{sent}|1|1|1|1|{big}")
        sent += 1
        assert sent < 200  # ring must fill well before this
    assert prod.buffer_count() > 0
    assert cons_ch.queue_lag("q") > 0
    while cons_ch.deliver():
        pass
    prod_ch.pump_once()  # drain is polled off the mmap, not pushed
    assert drained
    qm_p.retry_all_queue_buffers()
    assert prod.buffer_count() == 0
    while cons_ch.deliver():
        pass
    assert len(got) == sent  # nothing lost across pause/flush
    prod_ch.close(), cons_ch.close()


def test_shmring_refuses_manual_ack_and_oversize(tmp_path):
    prod_ch, cons_ch = _shm_pair(tmp_path)
    with pytest.raises(NotImplementedError):
        QueueManager(lambda d: cons_ch, stat_log_interval_s=3600).get_queue(
            "alo", "c", lambda l, h, t: None, manual_ack=True).start_consume()
    with pytest.raises(ValueError):
        prod_ch.send("q", b"y" * (1 << 17), {})
    prod_ch.close(), cons_ch.close()


def test_shmring_wraparound_fifo(tmp_path):
    prod_ch, cons_ch = _shm_pair(tmp_path)
    prod = QueueManager(lambda d: prod_ch, stat_log_interval_s=3600).get_queue("q", "p")
    recv = []
    cons = QueueManager(lambda d: cons_ch, stat_log_interval_s=3600).get_queue(
        "q", "c", lambda p, h: recv.append(bytes(p)))
    cons.frames_aware = True
    cons.start_consume()
    sent = []
    for k in range(80):  # > 2x around a 64 KiB ring
        blob = bytes(frames.encode_lines(
            [f"tx|s|svc{k % 7}|c{k}-{j}|1|100|200|5|Y" for j in range(20)]))
        sent.append(blob)
        prod.write_frames(blob, 20)
        if prod.paused:
            while prod.buffer_count():
                cons_ch.deliver()
                prod_ch.pump_once()
    while cons_ch.deliver():
        pass
    assert recv == sent  # FIFO through every wrap
    prod_ch.close(), cons_ch.close()


def test_shmring_backend_selectable():
    qm = make_queue_manager(
        {"brokerBackend": "shmring",
         "transport": {"shmRingDirectory": "spool/shmring-test-sel",
                       "shmRingBytes": 1 << 16}},
        start_pumps=False)
    try:
        prod = qm.get_queue("q", "p")
        prod.write_line("tx|a|b|c|1|2|3|4|Y")
    finally:
        qm.shutdown()
        import shutil

        shutil.rmtree("spool/shmring-test-sel", ignore_errors=True)


# -- fleet frame routing ------------------------------------------------------


def test_fleet_write_frames_routes_like_write_line():
    broker = MemoryBroker()
    qm = make_queue_manager({"brokerBackend": "memory"}, broker=broker,
                            start_pumps=False)
    qmc = make_queue_manager({"brokerBackend": "memory"}, broker=broker,
                             start_pumps=False)
    N = 4
    truth = [FleetPartitioner(qm, "gt", N).write_line(l) for l in CORPUS]
    per_part = {}
    for l, p in zip(CORPUS, truth):
        per_part.setdefault(p, []).append(l)

    pt = FleetPartitioner(qm, "fr", N)
    got = {}

    def mk(p):
        def cb(payload, h):
            assert h["partition"] == p
            got.setdefault(p, []).extend(frames.decode_lines(payload))
        return cb

    for p in range(N):
        c = qmc.get_queue(partition_queue("fr", p), "c", mk(p))
        c.frames_aware = True
        c.start_consume()
    routed = pt.write_frames(frames.encode_lines(CORPUS))
    broker.pump()
    assert got == per_part
    assert routed == {p: len(ls) for p, ls in sorted(per_part.items())}
    # the grouping writer lands identically
    got.clear()
    pt2 = FleetPartitioner(qm, "gl", N)
    for p in range(N):
        c = qmc.get_queue(partition_queue("gl", p), "c", mk(p))
        c.frames_aware = True
        c.start_consume()
    assert pt2.write_lines_frames(CORPUS) == routed
    broker.pump()
    assert got == per_part


def test_fleet_harness_send_lines_counts_spool_records(tmp_path):
    from apmbackend_tpu.parallel.fleet import FleetHarness

    h = FleetHarness(str(tmp_path), shards=3, capacity=64, lags="6")
    try:
        assert h.partitions == 12  # ISSUE 18 default: 4 partitions/shard
        lines = [f"tx|jvm{i % 4}|svc{i % 11}|x{i}|1|100|200|{i}|Y"
                 for i in range(90)]
        routed = h.send_lines(lines)
        assert sum(routed.values()) == 90
        # one spool RECORD per (partition, batch): the unit finish()/acked()
        # compare against the spool cursor
        assert sum(h.sent_per_queue.values()) == len(routed)
        for p, n in routed.items():
            q = partition_queue(h.base_queue, p)
            assert h.sent_per_queue[q] == 1
            assert n == len([
                l for l in lines
                if service_partition(tx_partition_key(l), h.partitions) == p])
    finally:
        h.close()


# -- worker intake parity -----------------------------------------------------


def _worker_cfg(tmp, mode, feed_frames):
    from apmbackend_tpu.config import default_config

    cfg = default_config()
    eng = cfg["tpuEngine"]
    eng["serviceCapacity"] = 32
    eng["samplesPerBucket"] = 32
    eng["deliveryMode"] = mode
    eng["feedFrames"] = feed_frames
    eng["resumeFileFullPath"] = os.path.join(tmp, "engine.resume.npz")
    cfg["streamCalcZScore"]["defaults"] = [
        {"LAG": 4, "THRESHOLD": 20, "INFLUENCE": 0.1}]
    cfg["streamCalcStats"]["resumeFileSaveFrequencyInSeconds"] = 3600
    cfg["streamProcessAlerts"]["alertsResumeFileFullPath"] = os.path.join(
        tmp, "alerts.resume")
    cfg["logDir"] = None
    return cfg


def _worker_run(tmp, mode, use_frames, feed_frames=True, bounce=False):
    from apmbackend_tpu.runtime.module_base import ModuleRuntime
    from apmbackend_tpu.runtime.worker import WorkerApp

    broker = MemoryBroker()
    rt = ModuleRuntime("tpuEngine", config=_worker_cfg(tmp, mode, feed_frames),
                       broker=broker, install_signals=False, console_log=False)
    worker = WorkerApp(rt)
    prod = _mk_qm(broker).get_queue("transactions", "p")
    lines = [f"tx|jvm0|svc{i % 8:02d}|l{t}-{i}|1|{(170000000 + t) * 10000 - 100 - i}|"
             f"{(170000000 + t) * 10000 + i}|{100 + i}|Y"
             for t in range(3) for i in range(40)]
    if use_frames:
        for k in range(0, len(lines), 32):
            chunk = lines[k:k + 32]
            prod.write_frames(frames.encode_lines(chunk), len(chunk))
    else:
        for ln in lines:
            prod.write_line(ln)
    broker.pump()
    if mode == "atLeastOnce":
        worker.drain_delivery_pending()
        if bounce:
            # crash-redelivery BEFORE the checkpoint ack: same msg_ids come
            # back and the dedup window must drop every frame batch whole
            assert broker.bounce() > 0
            broker.pump()
            worker.drain_delivery_pending()
        worker.save_state()
        assert broker.unacked_count() == 0
    else:
        worker.drain_intake(10)
        worker.save_state()
    got = []
    _mk_qm(broker).get_queue("db_insert", "c",
                             lambda l, h=None, t=None: got.append(l)
                             ).start_consume()
    broker.pump()
    worker.shutdown()
    return got


@pytest.mark.parametrize("mode", ["atLeastOnce", "atMostOnce"])
def test_worker_frames_record_identical(tmp_path, mode):
    base = _worker_run(str(tmp_path / "a"), mode, use_frames=False)
    fr = _worker_run(str(tmp_path / "b"), mode, use_frames=True)
    nf = _worker_run(str(tmp_path / "c"), mode, use_frames=True,
                     feed_frames=False)
    assert base == fr  # frame intake == per-line intake, record for record
    assert base == nf  # feedFrames=False decodes at feed time, same records


def test_worker_frame_redelivery_deduped(tmp_path):
    base = _worker_run(str(tmp_path / "a"), "atLeastOnce", use_frames=True)
    red = _worker_run(str(tmp_path / "b"), "atLeastOnce", use_frames=True,
                      bounce=True)
    assert base == red  # redelivered batches absorbed exactly once
