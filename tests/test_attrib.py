"""Wall-clock attribution plane (ISSUE 17): stage clocks, time-weighted
occupancy, the bottleneck estimator, the APC1 frame carriage, /attrib on
exporter + manager, qstat --lag over the shmring fabric, flight-recorder
attribution/shmring sources, and the frames-on e2e regressions (stitched
trace + populated e2e latency histograms, ALO redelivery keeping the
original carriage trace_id)."""

import json
import os
import time
import urllib.error
import urllib.request

import pytest

from apmbackend_tpu.config import default_config
from apmbackend_tpu.obs import MetricsRegistry, TelemetryServer, parse_prom_text, set_registry
from apmbackend_tpu.obs.attrib import (
    CADENCE,
    STAGE_PARSER_SCAN,
    AttributionPlane,
    Occupancy,
    StageClock,
    estimate,
    get_attrib,
    merge_snapshots,
    set_attrib,
)
from apmbackend_tpu.obs.trace import Tracer, get_tracer, set_tracer
from apmbackend_tpu.transport import frames
from apmbackend_tpu.transport.memory import MemoryBroker, MemoryChannel


@pytest.fixture(autouse=True)
def fresh_attrib_plane():
    """Isolate the process-global plane + registry + tracer per test:
    clocks accumulated by pipelines in OTHER tests must not leak into
    snapshot/estimator assertions."""
    old_plane = set_attrib(AttributionPlane())
    old_reg = set_registry(MetricsRegistry())
    old_tr = set_tracer(Tracer())
    yield
    set_attrib(old_plane)
    set_registry(old_reg)
    set_tracer(old_tr)


def fetch(url, timeout=10):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, resp.read().decode("utf-8")
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode("utf-8")


def samples_by_name(text):
    out = {}
    for name, labels, value in parse_prom_text(text):
        out.setdefault(name, []).append((labels, value))
    return out


# -- accumulators --------------------------------------------------------------


def test_stage_clock_accumulates_and_ignores_nonpositive():
    c = StageClock("x")
    c.add_busy(0.5)
    c.add_busy(0.25)
    c.add_blocked(0.1)
    c.add_idle(0.2)
    c.add_busy(-1.0)  # clock skew / re-entrant timer: never subtract
    c.add_blocked(0.0)
    snap = c.snapshot()
    assert snap["busy_s"] == pytest.approx(0.75)
    assert snap["blocked_s"] == pytest.approx(0.1)
    assert snap["idle_s"] == pytest.approx(0.2)
    assert snap["events"] == 2  # one per positive busy interval


def test_occupancy_time_weighted_average_and_peak():
    occ = Occupancy("fifo", capacity=100)
    occ.sample(80)
    time.sleep(0.03)
    occ.sample(0)
    time.sleep(0.01)
    snap = occ.snapshot()
    assert snap["peak"] == 80
    assert snap["level"] == 0
    # the 80-level was held ~3/4 of the window: the time-weighted average
    # must land well above zero and below the peak
    assert 10 < snap["avg"] < 80
    assert snap["capacity"] == 100
    assert snap["utilization"] == pytest.approx(snap["avg"] / 100)


# -- the estimator -------------------------------------------------------------


def test_estimate_names_busy_blocked_and_cadence():
    # busy-dominated: a sequential replay where the parser owns the wall
    est = estimate({"parser_scan": {"busy_s": 0.9, "blocked_s": 0.0}}, 1.0)
    assert est["bottleneck"] == "parser_scan" and est["mode"] == "busy"
    assert est["share"] == pytest.approx(0.9)
    assert est["verdict"].startswith("bottleneck: parser_scan")

    # blocked-dominated: upstream starved BY downstream backpressure
    est = estimate(
        {"intake_push": {"busy_s": 0.05, "blocked_s": 0.7},
         "worker_feed": {"busy_s": 0.1, "blocked_s": 0.0}}, 1.0)
    assert est["bottleneck"] == "intake_push" and est["mode"] == "blocked"
    assert "intake_push_wait" in est["reason"]

    # mostly-unaccounted wall: the pipeline is waiting for the next tick
    # boundary to arrive in the stream
    est = estimate({"tick_dispatch": {"busy_s": 0.1, "blocked_s": 0.0}}, 1.0)
    assert est["bottleneck"] == CADENCE and est["mode"] == "drain_wait"
    assert est["share"] == pytest.approx(0.9)

    # parallel threads can account past the window; cadence clamps at zero
    est = estimate({"a": {"busy_s": 0.9}, "b": {"busy_s": 0.8}}, 1.0)
    assert est["bottleneck"] == "a"


def test_plane_snapshot_collect_and_install_idempotent():
    plane = get_attrib().configure(module="worker")
    plane.clock(STAGE_PARSER_SCAN).add_busy(0.4)
    plane.clock("tick_dispatch").add_blocked(0.1)
    plane.occupancy("frame_fifo", capacity=10).sample(5)

    snap = plane.snapshot()
    assert snap["module"] == "worker" and snap["enabled"] is True
    assert snap["stages"]["parser_scan"]["busy_s"] == pytest.approx(0.4)
    # share = busy / window; the window here is milliseconds old, so the
    # share can exceed 1.0 — only its presence and sign are contractual
    assert snap["stages"]["parser_scan"]["busy_share"] > 0
    assert "frame_fifo" in snap["occupancy"]
    assert snap["estimate"]["bottleneck"]

    reg = MetricsRegistry()
    plane.install(reg)
    plane.install(reg)  # idempotent per registry
    s = samples_by_name(reg.render())
    busy = {lb["stage"]: v for lb, v in s["apm_stage_busy_seconds_total"]}
    assert busy["parser_scan"] == pytest.approx(0.4)
    assert len([v for lb, v in s["apm_stage_busy_seconds_total"]
                if lb["stage"] == "parser_scan"]) == 1
    blocked = {lb["stage"]: v for lb, v in s["apm_stage_blocked_seconds_total"]}
    assert blocked["tick_dispatch"] == pytest.approx(0.1)
    events = {lb["stage"]: v for lb, v in s["apm_stage_events_total"]}
    assert events["parser_scan"] == 1
    occ = {lb["resource"]: v for lb, v in s["apm_occupancy_peak"]}
    assert occ["frame_fifo"] == 5
    assert "apm_occupancy_avg" in s and "apm_occupancy_level" in s
    assert all(lb["module"] == "worker"
               for lb, _v in s["apm_stage_busy_seconds_total"])


def test_kill_switch_hands_out_shared_noop_clock(monkeypatch):
    monkeypatch.setenv("APM_NO_ATTRIB", "1")
    plane = AttributionPlane()
    assert plane.enabled is False
    c = plane.clock("anything")
    assert c.enabled is False
    c.add_busy(5.0)
    c.add_blocked(5.0)
    assert c.snapshot()["busy_s"] == 0.0
    o = plane.occupancy("ring")
    o.sample(99)
    assert o.snapshot()["peak"] == 0.0
    assert plane.snapshot()["stages"] == {}


def test_set_attrib_swap_binds_components_built_after():
    mine = AttributionPlane(module="bench")
    prev = set_attrib(mine)
    try:
        assert get_attrib() is mine
        get_attrib().clock("s").add_busy(1.0)
        assert mine.stage_table()["s"]["busy_s"] == 1.0
        assert "s" not in prev.stage_table()
    finally:
        assert set_attrib(prev) is mine


def test_merge_snapshots_sums_stages_and_namespaces_occupancy():
    a = AttributionPlane(module="worker0")
    a.clock("tick_dispatch").add_busy(0.2)
    a.occupancy("ring").sample(3)
    b = AttributionPlane(module="worker1")
    b.clock("tick_dispatch").add_busy(0.3)
    b.clock("sink_absorb").add_busy(0.1)
    sa, sb = a.snapshot(), b.snapshot()
    sa["window_s"], sb["window_s"] = 2.0, 5.0

    merged = merge_snapshots([sa, sb])
    assert merged["children"] == ["worker0", "worker1"]
    assert merged["window_s"] == 5.0
    assert merged["stages"]["tick_dispatch"]["busy_s"] == pytest.approx(0.5)
    assert merged["stages"]["sink_absorb"]["busy_s"] == pytest.approx(0.1)
    assert "worker0:ring" in merged["occupancy"]
    # 0.6 s accounted over a 5 s window: the fleet verdict is cadence wait
    assert merged["estimate"]["bottleneck"] == CADENCE


# -- /attrib routes ------------------------------------------------------------


def test_exporter_attrib_route_serves_snapshot():
    get_attrib().configure(module="w")
    get_attrib().clock(STAGE_PARSER_SCAN).add_busy(0.2)
    server = TelemetryServer(MetricsRegistry(), port=0, module="w")
    server.start()
    try:
        status, body = fetch(f"{server.url}/attrib")
        assert status == 200
        out = json.loads(body)
        assert out["module"] == "w"
        assert out["stages"]["parser_scan"]["busy_s"] == pytest.approx(0.2)
        assert "verdict" in out["estimate"]
    finally:
        server.stop()


def test_manager_attrib_route_merges_children(tmp_path):
    from apmbackend_tpu.manager.manager import ManagerApp
    from apmbackend_tpu.runtime.module_base import ModuleRuntime

    # the process plane doubles as every same-process "child": the route
    # must fold child bodies + its own snapshot without error
    get_attrib().clock("tick_dispatch").add_busy(0.25)
    child = TelemetryServer(MetricsRegistry(), port=0, module="worker")
    child.start()

    cfg = default_config()
    cfg["logDir"] = str(tmp_path / "logs")
    cfg["applicationManager"]["moduleSettings"] = [
        {"module": "apmbackend_tpu.runtime.worker", "metricsPort": child.port},
    ]
    cfg["applicationManager"]["metricsPort"] = 0
    runtime = ModuleRuntime(
        "applicationManager", config=cfg, install_signals=False, console_log=False
    )
    app = ManagerApp(runtime, spawn_children=False)
    try:
        status, body = fetch(f"{runtime.telemetry.url}/attrib")
        assert status == 200
        out = json.loads(body)
        assert len(out["children"]) == 2  # manager's own plane + the child
        assert out["child_status"]["worker"] == "ok"
        # one process, one plane: both bodies carry the same clock; the
        # merge sums them and recomputes the verdict over the fleet table
        assert out["stages"]["tick_dispatch"]["busy_s"] == pytest.approx(0.5)
        assert out["estimate"]["bottleneck"]

        # a dead child degrades to a recorded error, not a failed route
        child.stop()
        status, body = fetch(f"{runtime.telemetry.url}/attrib")
        assert status == 200
        out = json.loads(body)
        assert out["child_status"]["worker"].startswith("error:")
    finally:
        app.alerts.stop()
        app.shutdown()
        runtime.stop_timers()
        child.stop()


# -- APC1 carriage -------------------------------------------------------------

LINES = [
    f"tx|jvm{i % 2}|svc{i % 5:02d}|c{i}|1|{17000000000 + i}|{17000000100 + i}|"
    f"{100 + i}|Y"
    for i in range(12)
]


def test_carriage_roundtrip_strip_and_record_ts():
    bare = frames.encode_lines(LINES)
    assert not frames.has_carriage(bare)
    assert frames.read_carriage(bare) is None
    assert frames.carriage_trace_id(bare) == ""
    assert frames.record_ingest_ts(bare) is None

    base = 1700000000.25
    deltas = [i * 3 for i in range(len(LINES))]
    blob = frames.append_carriage(bare, base, deltas, "t-abc123")
    assert frames.has_carriage(blob)
    got_base, got_deltas, tid = frames.read_carriage(blob)
    assert got_base == pytest.approx(base)
    assert list(got_deltas) == deltas
    assert tid == "t-abc123"
    assert frames.carriage_trace_id(blob) == "t-abc123"
    ts = frames.record_ingest_ts(blob)
    assert ts is not None and len(ts) == len(LINES)
    assert ts[3] == pytest.approx(base + 0.009)

    # the decode surface is carriage-blind: same records, same lines
    assert frames.decode_lines(blob) == frames.decode_lines(bare)
    assert frames.frame_count(blob) == len(LINES)
    # strip returns the EXACT pre-carriage wire (the PR 16 bit-identity)
    assert frames.strip_carriage(blob) == bare

    # double-append must refuse: one trailer per batch
    with pytest.raises(frames.FrameError):
        frames.append_carriage(blob, base, deltas)
    # delta count must match the record count
    with pytest.raises(frames.FrameError):
        frames.append_carriage(bare, base, deltas[:-1])


def test_carriage_delta_saturates_at_u16():
    bare = frames.encode_lines(LINES[:2])
    blob = frames.append_carriage(bare, 0.0, [70_000, -5])
    _b, deltas, _t = frames.read_carriage(blob)
    assert list(deltas) == [65535, 0]  # clamp, never wrap


def test_split_by_partition_reappends_carriage_per_subbatch():
    bare = frames.encode_lines(LINES)
    blob = frames.append_carriage(
        bare, 2.0, list(range(len(LINES))), "t-split")
    parts = frames.split_by_partition(blob, 3)
    assert sum(frames.frame_count(b) for b in parts.values()) == len(LINES)
    for sub in parts.values():
        base, deltas, tid = frames.read_carriage(sub)
        assert base == pytest.approx(2.0) and tid == "t-split"
        # each record kept ITS stamp: sub-batch deltas are a subset
        assert set(int(d) for d in deltas) <= set(range(len(LINES)))


def test_parser_carriage_kill_switch_is_bit_identical(tmp_path, monkeypatch):
    from apmbackend_tpu.ingest.parser import TransactionParser

    log = tmp_path / "app.log"
    fixture = None

    def run():
        blobs = []
        p = TransactionParser(lambda tx, db: None,
                              frame_sink=lambda b, n: blobs.append(bytes(b)),
                              frame_max_records=8)
        p.read_lines(str(log), fixture)
        p.flush_frames()
        return blobs

    from apmbackend_tpu.ingest.replay import write_fixture_logs
    write_fixture_logs(str(tmp_path / "fx"), n_transactions=40, seed=3)
    fx = sorted(os.listdir(tmp_path / "fx"))[0]
    with open(tmp_path / "fx" / fx, "rb") as fh:
        fixture = fh.read()

    on_blobs = run()
    assert on_blobs and all(frames.has_carriage(b) for b in on_blobs)

    monkeypatch.setenv("APM_NO_FRAME_CARRIAGE", "1")
    off_blobs = run()
    assert all(not frames.has_carriage(b) for b in off_blobs)
    # kill switch OFF wire == carriage wire minus the trailer, bit for bit
    assert off_blobs == [frames.strip_carriage(b) for b in on_blobs]


# -- ALO redelivery keeps the carriage trace_id --------------------------------


def _alo_worker(tmp_path):
    from apmbackend_tpu.runtime.module_base import ModuleRuntime
    from apmbackend_tpu.runtime.worker import WorkerApp

    broker = MemoryBroker()
    cfg = default_config()
    eng = cfg["tpuEngine"]
    eng["serviceCapacity"] = 16
    eng["samplesPerBucket"] = 16
    eng["deliveryMode"] = "atLeastOnce"
    eng["resumeFileFullPath"] = str(tmp_path / "engine.resume.npz")
    cfg["streamCalcZScore"]["defaults"] = [
        {"LAG": 4, "THRESHOLD": 20, "INFLUENCE": 0.1}]
    cfg["streamProcessAlerts"]["alertsResumeFileFullPath"] = None
    cfg["logDir"] = None
    rt = ModuleRuntime("tpuEngine", config=cfg, broker=broker,
                       install_signals=False, console_log=False)
    return broker, rt, WorkerApp(rt)


def test_alo_redelivery_keeps_original_carriage_trace_id(tmp_path):
    """A frame batch delivered WITHOUT a trace header (the header-less
    shm-ring posture) anchors its trace on the APC1 carriage tid; a
    broker redelivery of the same batch is deduped whole, so the trace
    never splits into a second id."""
    get_tracer().configure(sample_rate=1, ring_size=4096)
    broker, rt, worker = _alo_worker(tmp_path)
    try:
        base = 170_300_000
        lines = [f"tx|jvm0|svc{i % 4:02d}|a{i}|1|{base * 10000 - 100}|"
                 f"{base * 10000 + i}|{100 + i}|Y" for i in range(8)]
        blob = frames.append_carriage(
            frames.encode_lines(lines), time.time(),
            [i for i in range(len(lines))], "t-carried-1")
        ch = MemoryChannel(broker)
        assert ch.send("transactions", blob, headers={"msg_id": "m-frame-1"})
        broker.pump()
        worker.drain_delivery_pending()
        worker.save_state()  # epoch commit acks the delivery

        feed = [s for s in get_tracer().ring.spans() if s["name"] == "feed"]
        assert feed and all(s["trace_id"] == "t-carried-1" for s in feed)
        n_feed = len(feed)

        # redeliver the SAME batch (crash-before-ack shape): the dedup
        # window drops it whole — no second feed span, no new trace_id
        assert ch.send("transactions", blob,
                       headers={"msg_id": "m-frame-1", "redelivered": True})
        broker.pump()
        worker.drain_delivery_pending()
        feed2 = [s for s in get_tracer().ring.spans() if s["name"] == "feed"]
        assert len(feed2) == n_feed
        assert {s["trace_id"] for s in feed2} == {"t-carried-1"}
        assert worker._deduped_total == 1
    finally:
        worker.shutdown()
        rt.stop_timers()


# -- qstat --lag over the shmring fabric ---------------------------------------


def test_ring_stats_reads_header_without_creating(tmp_path):
    from apmbackend_tpu.transport.shmring import ShmRingChannel, ring_stats

    path = str(tmp_path / "transactions.ring")
    assert ring_stats(path) is None  # absent: no file created
    assert not os.path.exists(path)

    ch = ShmRingChannel(str(tmp_path), ring_bytes=65536)
    ch.assert_queue("transactions")
    for i in range(5):
        assert ch.send("transactions", f"l{i}".encode())
    st = ring_stats(path)
    assert st is not None
    assert st["lag"] == 5 and st["msgs_in"] == 5 and st["msgs_out"] == 0
    assert st["capacity"] > 0 and st["used_bytes"] > 0
    ch.close()

    # torn/garbage file: None, not an exception
    with open(str(tmp_path / "bad.ring"), "wb") as fh:
        fh.write(b"notaring")
    assert ring_stats(str(tmp_path / "bad.ring")) is None


def test_qstat_lag_shmring_backend(tmp_path, capsys, monkeypatch):
    from apmbackend_tpu.tools import qstat
    from apmbackend_tpu.transport.shmring import ShmRingChannel

    ring_dir = str(tmp_path / "shmring")
    ch = ShmRingChannel(ring_dir, ring_bytes=65536)
    ch.assert_queue("transactions")
    for i in range(7):
        assert ch.send("transactions", f"l{i}".encode())
    ch.close()

    cfg = default_config()
    cfg["brokerBackend"] = "shmring"
    cfg["transport"] = {"shmRingDirectory": ring_dir}
    observer, warning = qstat.make_lag_observer(cfg)
    assert warning is None
    rows = dict(qstat.lag_rows(observer, ["transactions", "db_insert"]))
    # 7 pushed, none popped: header-counter lag; untouched queues read 0
    # (the observer NEVER materializes a ring file for them)
    assert rows["transactions"] == 7
    assert rows["db_insert"] == 0
    assert not os.path.exists(os.path.join(ring_dir, "db_insert.ring"))
    observer.close()

    # the CLI path renders the same table
    monkeypatch.setattr("apmbackend_tpu.config.default_config", lambda: cfg)
    assert qstat.main(["--lag"]) == 0
    out = capsys.readouterr().out
    assert "transactions" in out and "7" in out


# -- flight recorder sources ---------------------------------------------------


def test_flight_bundle_embeds_attribution_and_shmring(tmp_path):
    from apmbackend_tpu.runtime.module_base import ModuleRuntime
    from apmbackend_tpu.transport.shmring import ShmRingChannel

    ring_dir = str(tmp_path / "shmring")
    ch = ShmRingChannel(ring_dir, ring_bytes=65536)
    ch.assert_queue("transactions")
    assert ch.send("transactions", b"x")
    ch.close()

    get_attrib().clock("worker_feed").add_busy(0.05)
    cfg = default_config()
    cfg["logDir"] = None
    cfg["brokerBackend"] = "shmring"
    cfg["transport"] = {"shmRingDirectory": ring_dir}
    cfg["observability"]["flightDir"] = str(tmp_path / "flight")
    cfg["tpuEngine"]["metricsPort"] = 0
    runtime = ModuleRuntime(
        "tpuEngine", config=cfg, broker=MemoryBroker(),
        install_signals=False, console_log=False,
    )
    try:
        snap = runtime.flight.snapshot("test")
        att = snap["attribution"]
        assert att["stages"]["worker_feed"]["busy_s"] == pytest.approx(0.05)
        assert "estimate" in att
        assert snap["shmring"]["transactions"]["lag"] == 1
    finally:
        runtime.stop_timers()


def test_flight_shmring_source_empty_for_other_backends(tmp_path):
    from apmbackend_tpu.runtime.module_base import ModuleRuntime

    cfg = default_config()  # memory backend
    cfg["logDir"] = None
    cfg["observability"]["flightDir"] = str(tmp_path / "flight")
    cfg["tpuEngine"]["metricsPort"] = 0
    runtime = ModuleRuntime(
        "tpuEngine", config=cfg, broker=MemoryBroker(),
        install_signals=False, console_log=False,
    )
    try:
        snap = runtime.flight.snapshot("test")
        assert snap["shmring"] == {}
        assert "attribution" in snap
    finally:
        runtime.stop_timers()


# -- frames-on e2e regressions -------------------------------------------------


def test_frames_on_replay_stitches_trace_and_fills_e2e_histograms(tmp_path):
    """ISSUE 17 regression: with transport.frameMode ON, a replayed stream
    still produces (a) a stitched ingest->...->tick->emit trace (the tid
    rides the APC1 carriage + headers) and (b) a POPULATED
    apm_e2e_ingest_to_emit_seconds histogram — before the carriage, frame
    batches carried no per-record stamps and both signals went dark."""
    from apmbackend_tpu.ingest.replay import write_fixture_logs
    from apmbackend_tpu.standalone import StandalonePipeline
    from tests.test_standalone import small_config

    logs = tmp_path / "fixture_logs"
    write_fixture_logs(str(logs), n_transactions=200, seed=13)
    cfg = small_config(tmp_path, metricsPort=0)
    cfg["transport"]["frameMode"] = True
    cfg["observability"]["traceSampleRate"] = 1
    cfg["observability"]["traceRingSize"] = 16384

    pipe = StandalonePipeline(config=cfg, tail=False, install_signals=False)
    try:
        fed = pipe.replay(str(logs))
        assert fed > 0
        status, text = fetch(f"{pipe.lead.telemetry.url}/metrics")
        assert status == 200
        s = samples_by_name(text)
        assert s["apm_frames_emitted_total"][0][1] > 0  # frame mode was live
        assert s["apm_e2e_ingest_to_emit_seconds_count"][0][1] > 0

        by_trace = {}
        for span in get_tracer().ring.spans():
            by_trace.setdefault(span["trace_id"], set()).add(span["name"])
        stitched = [names for names in by_trace.values()
                    if {"ingest", "feed", "tick", "emit"} <= names]
        assert stitched, by_trace

        # the attribution plane saw the replay: parser + tick stages have
        # busy seconds on the process table
        stages = get_attrib().stage_table()
        assert stages.get("parser_scan", {}).get("busy_s", 0) > 0
        assert stages.get("tick_dispatch", {}).get("busy_s", 0) > 0
    finally:
        pipe.shutdown()
