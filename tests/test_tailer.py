"""Tailer tests: follow, pause-file hold, truncation recovery, discovery."""

import os
import time

from apmbackend_tpu.ingest.tailer import PauseFile, PyTailer, discover_log_files


def wait_until(pred, timeout=3.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return pred()


def test_follow_appends(tmp_path):
    p = tmp_path / "server.log"
    p.write_text("old line\n")
    lines = []
    t = PyTailer(str(p), lambda f, l: lines.append(l), poll_interval_s=0.02)
    t.start()
    time.sleep(0.1)
    with open(p, "a") as fh:
        fh.write("new1\nnew2\n")
    assert wait_until(lambda: len(lines) == 2)
    assert lines == ["new1", "new2"]  # started at EOF: 'old line' skipped
    t.stop()


def test_from_start(tmp_path):
    p = tmp_path / "app.log"
    p.write_text("a\nb\n")
    lines = []
    t = PyTailer(str(p), lambda f, l: lines.append(l), poll_interval_s=0.02, from_start=True)
    t.start()
    assert wait_until(lambda: len(lines) == 2)
    t.stop()


def test_pause_file_holds_position(tmp_path):
    p = tmp_path / "x.log"
    p.write_text("")
    pause = PauseFile(str(tmp_path / "PAUSE"))
    lines = []
    t = PyTailer(str(p), lambda f, l: lines.append(l), pause, poll_interval_s=0.02)
    t.start()
    time.sleep(0.1)
    pause.create()
    time.sleep(0.05)
    with open(p, "a") as fh:
        fh.write("while-paused\n")
    time.sleep(0.2)
    assert lines == []  # held
    pause.delete()
    assert wait_until(lambda: lines == ["while-paused"])  # resumed from held position
    t.stop()


def test_truncation_reopens(tmp_path):
    p = tmp_path / "t.log"
    p.write_text("aaaaaaaaaa\n")
    lines = []
    t = PyTailer(str(p), lambda f, l: lines.append(l), poll_interval_s=0.02)
    t.start()
    time.sleep(0.1)
    p.write_text("")  # truncate
    time.sleep(0.1)
    with open(p, "a") as fh:
        fh.write("fresh\n")
    assert wait_until(lambda: "fresh" in lines)
    t.stop()


def test_discover_masks(tmp_path):
    for name in ("app1.log", "app2.log", "server.log", "soap_io_x.log", "hibernate.log"):
        (tmp_path / name).write_text("")
    files = discover_log_files(str(tmp_path), ["app*log", "server.log", "soap_io*log"])
    names = {os.path.basename(f) for f in files}
    assert names == {"app1.log", "app2.log", "server.log", "soap_io_x.log"}


def test_rename_rotation_reopens(tmp_path):
    """logrotate-style rename + recreate: new inode detected even when the new
    file grows past the old read position; pre-rotation tail is drained."""
    p = tmp_path / "r.log"
    p.write_text("")
    lines = []
    t = PyTailer(str(p), lambda f, l: lines.append(l), poll_interval_s=0.02)
    t.start()
    time.sleep(0.1)
    with open(p, "a") as fh:
        fh.write("before-rotate\n")
    assert wait_until(lambda: "before-rotate" in lines)
    os.rename(str(p), str(tmp_path / "r.log.1"))
    with open(p, "w") as fh:  # new file immediately larger than old pos
        fh.write("x" * 200 + "\n")
    assert wait_until(lambda: any(l.startswith("xxx") for l in lines))
    with open(p, "a") as fh:
        fh.write("after-rotate\n")
    assert wait_until(lambda: "after-rotate" in lines)
    t.stop()


def test_paused_at_start_still_anchors_eof(tmp_path):
    # pause exists before the tailer starts: the file must still be opened
    # (EOF anchor established) so lines written during the pause are
    # delivered on resume, not skipped
    p = tmp_path / "pre.log"
    p.write_text("pre-existing\n")
    pause = PauseFile(str(tmp_path / "PAUSE"))
    pause.create()
    lines = []
    t = PyTailer(str(p), lambda f, l: lines.append(l), pause, poll_interval_s=0.02)
    t.start()
    time.sleep(0.15)
    with open(p, "a") as fh:
        fh.write("during-pause\n")
    time.sleep(0.15)
    assert lines == []
    pause.delete()
    assert wait_until(lambda: lines == ["during-pause"]), lines
    t.stop()


def test_late_appearing_file_read_from_start(tmp_path):
    # the file does not exist when the tail starts; when it appears it is all
    # new content and must be read from the beginning
    p = tmp_path / "late.log"
    lines = []
    t = PyTailer(str(p), lambda f, l: lines.append(l), poll_interval_s=0.02)
    t.start()
    time.sleep(0.15)
    p.write_text("l1\nl2\n")
    assert wait_until(lambda: lines == ["l1", "l2"]), lines
    t.stop()
