"""Persistent XLA compile-cache retest (ROADMAP housekeeping, ISSUE 7).

Round 6 root-caused the suite's flaky segfault to the persistent compile
cache's cpu_aot_loader path miscompiling buffer donation for fused
(single-program read+write) steps, and disabled the cache suite-wide
(tests/conftest.py). This is the standing retest: run the exact hazardous
shape — two PipelineDrivers stepping the same donated fused program in one
process — in a subprocess with the cache ENABLED, cold and then warm, and
compare the final state against a cache-disabled oracle.

Retested 2026-08 on jax 0.4.37: NOT reproducible — oracle, cold-cache and
warm-cache runs are bit-identical, and the fused-tick parity suite passes
cold+warm with the cache on. The cache stays opt-in (APM_TEST_JAX_CACHE /
APM_BENCH_JAX_CACHE) because its only upside is compile time, but this test
keeps the question answered on every jax bump: if it starts failing, the
miscompile is back — re-quarantine before trusting any cached run.
"""

import json
import os
import subprocess
import sys

import pytest

_REPRO = r"""
import os, sys, json
sys.path.insert(0, os.getcwd())  # the repo root (subprocess cwd)
import numpy as np
os.environ["JAX_PLATFORMS"] = "cpu"
cache_dir = sys.argv[1]
import jax
if cache_dir:
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
from apmbackend_tpu.config import default_config
from apmbackend_tpu.pipeline import PipelineDriver

cfg = default_config()
cfg["tpuEngine"]["serviceCapacity"] = 64
cfg["tpuEngine"]["samplesPerBucket"] = 32
cfg["tpuEngine"]["tickExecutor"] = "fused"  # the donated read+write program
cfg["streamCalcZScore"]["defaults"] = [{"LAG": 6, "THRESHOLD": 3.0, "INFLUENCE": 0.1}]
base = 170_000_000
lines = [
    f"tx|j|s{i%9}|c{t}-{i}|1|{(base+t)*10000-7}|{(base+t)*10000+i}|{40+i%200}|Y"
    for t in range(12) for i in range(30)
]
# TWO drivers: the round-6 corruption needed a second driver re-loading the
# same cached executable in-process (shared cpu_aot_loader artifacts)
d1 = PipelineDriver(cfg, capacity=64)
d2 = PipelineDriver(cfg, capacity=64)
out = {}
for name, d in (("d1", d1), ("d2", d2)):
    d.feed_csv_batch(lines)
    d.flush()
    out[name] = {
        "counts": np.asarray(d.state.stats.counts).tolist(),
        "sums": np.nansum(np.asarray(d.state.stats.sums, dtype=np.float64)),
        "ring": np.nansum(np.asarray(d.state.zscores[0].values, dtype=np.float64)),
        "fill": np.asarray(d.state.zscores[0].fill).tolist(),
        "pos": int(np.asarray(d.state.zscores[0].pos)),
    }
print(json.dumps(out))
"""


def _run(cache_dir, tmp_path):
    script = tmp_path / "repro.py"
    script.write_text(_REPRO)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PYTHONPATH", None)
    out = subprocess.run(
        [sys.executable, str(script), cache_dir],
        capture_output=True, text=True, timeout=300,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


@pytest.mark.slow
def test_persistent_cache_donation_parity(tmp_path):
    cache = str(tmp_path / "xla-cache")
    os.makedirs(cache)
    oracle = _run("", tmp_path)
    cold = _run(cache, tmp_path)
    assert os.listdir(cache), "cache dir empty: the repro never hit the cache path"
    warm = _run(cache, tmp_path)
    assert oracle["d1"] == oracle["d2"]  # in-process agreement first
    assert cold == oracle, "cache COLD run diverged: cpu_aot_loader miscompile is back"
    assert warm == oracle, "cache WARM run diverged: cpu_aot_loader miscompile is back"
