"""Config system tests: // stripping, hot reload, override hierarchy."""

import json
import os

import pytest

from apmbackend_tpu.config import (
    ConfigError,
    ConfigWatcher,
    default_config,
    load_config,
    resolve_path,
    service_alert_overrides,
    service_zscore_settings,
    strip_json_comments,
)


def test_strip_comments_keeps_urls():
    txt = '{\n  // full line comment\n  "url": "amqp://localhost:5672", // trailing\n  "x": 1\n}'
    parsed = json.loads(strip_json_comments(txt))
    assert parsed["url"] == "amqp://localhost:5672"
    assert parsed["x"] == 1


def test_load_config(tmp_path):
    p = tmp_path / "apm_config.json"
    p.write_text('{\n// comment\n"a": {"b": 2}\n}')
    cfg = load_config(str(p))
    assert cfg["a"]["b"] == 2
    assert cfg["apmConfigFilePath"] == str(p)


def test_load_config_missing(tmp_path):
    with pytest.raises(ConfigError):
        load_config(str(tmp_path / "nope.json"))


def test_load_config_bad_json(tmp_path):
    p = tmp_path / "bad.json"
    p.write_text("{nope")
    with pytest.raises(ConfigError):
        load_config(str(p))


def test_resolve_path():
    obj = {"a": {"b": {"c": 3}}}
    assert resolve_path(obj, "a.b.c") == 3
    assert resolve_path(obj, "a.x.c") is None


def test_watcher_applies_only_valid_changes(tmp_path):
    p = tmp_path / "apm_config.json"
    p.write_text('{"v": 1}')
    seen = []
    w = ConfigWatcher(str(p), seen.append, ["v2"], poll_interval=0.05)
    assert w.current["v"] == 1

    p.write_text("{broken")
    assert w.check_once() is None
    assert w.current["v"] == 1  # old config retained

    p.write_text('{"v": 2}')
    new = w.check_once()
    assert new["v"] == 2
    assert seen and seen[-1]["v"] == 2


def test_watcher_no_change_no_callback(tmp_path):
    p = tmp_path / "apm_config.json"
    p.write_text('{"v": 1}')
    seen = []
    w = ConfigWatcher(str(p), seen.append, poll_interval=0.05)
    assert w.check_once() is None
    assert not seen


def test_zscore_settings_overrides():
    zcfg = {
        "defaults": [
            {"LAG": 360, "THRESHOLD": 20.0, "INFLUENCE": 0.1},
            {"LAG": 8640, "THRESHOLD": 15.0, "INFLUENCE": 0.0},
        ],
        "overrides": {"services": {"S:special": {"360": {"THRESHOLD": 25.0}}}},
    }
    default = service_zscore_settings(zcfg, "S:normal")
    assert default[0]["THRESHOLD"] == 20.0
    special = service_zscore_settings(zcfg, "S:special")
    assert special[0]["THRESHOLD"] == 25.0
    assert special[0]["INFLUENCE"] == 0.1  # untouched
    assert special[1]["THRESHOLD"] == 15.0  # other lag untouched
    # settings are deep-copied: defaults must not be mutated by override reads
    assert zcfg["defaults"][0]["THRESHOLD"] == 20.0


def test_alert_overrides():
    acfg = {"overrides": {"services": {"svcA": {"hardMaxMsAlertThreshold": 9000}}}}
    assert service_alert_overrides(acfg, "svcA")["hardMaxMsAlertThreshold"] == 9000
    assert service_alert_overrides(acfg, "svcB") is None


def test_default_config_shape():
    cfg = default_config()
    assert cfg["streamCalcStats"]["intervalLengthInSeconds"] == 10
    assert cfg["streamCalcZScore"]["defaults"][0]["LAG"] == 360
    assert cfg["tpuEngine"]["serviceCapacity"] >= 1
    # mutation of one copy must not leak into the next
    cfg["streamCalcStats"]["intervalLengthInSeconds"] = 99
    assert default_config()["streamCalcStats"]["intervalLengthInSeconds"] == 10


def test_config_dump_cli_roundtrips(tmp_path):
    """`python -m apmbackend_tpu config <path>` writes commented JSON that
    load_config parses back to the exact default tree."""
    from apmbackend_tpu.config import default_config, load_config, main

    out = tmp_path / "apm_config.json"
    assert main([str(out)]) == 0
    loaded = load_config(str(out))
    loaded.pop("apmConfigFilePath", None)  # injected by load_config
    assert loaded == default_config()
