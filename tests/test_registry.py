"""ServiceRegistry: row assignment, growth, parameter vector materialization."""

import numpy as np
import pytest

from apmbackend_tpu.ops.registry import CapacityExceeded, ServiceRegistry


def test_assign_and_lookup():
    reg = ServiceRegistry(4)
    r0 = reg.lookup_or_add("s1", "a")
    r1 = reg.lookup_or_add("s1", "b")
    assert (r0, r1) == (0, 1)
    assert reg.lookup_or_add("s1", "a") == 0  # stable
    assert reg.lookup("s2", "x") is None
    assert reg.key_of(1) == ("s1", "b")
    assert reg.count == 2


def test_capacity_and_growth():
    reg = ServiceRegistry(2)
    reg.lookup_or_add("s", "a")
    reg.lookup_or_add("s", "b")
    with pytest.raises(CapacityExceeded):
        reg.lookup_or_add("s", "c")
    big = reg.grown()
    assert big.capacity == 4
    assert big.lookup("s", "a") == 0  # rows preserved
    assert big.lookup_or_add("s", "c") == 2


def test_batch_lookup():
    reg = ServiceRegistry(8)
    rows = reg.lookup_or_add_batch([("s", "a"), ("s", "b"), ("s", "a")])
    assert rows.tolist() == [0, 1, 0]
    assert rows.dtype == np.int32


def test_zscore_param_vectors():
    zcfg = {
        "defaults": [
            {"LAG": 360, "THRESHOLD": 20.0, "INFLUENCE": 0.1},
            {"LAG": 8640, "THRESHOLD": 15.0, "INFLUENCE": 0.0},
        ],
        "overrides": {"services": {"hot": {"360": {"THRESHOLD": 25.0}}}},
    }
    reg = ServiceRegistry(4)
    reg.lookup_or_add("s", "cold")
    reg.lookup_or_add("s", "hot")
    params = reg.zscore_params(zcfg, [360, 8640])
    assert params[360]["threshold"][0] == 20.0
    assert params[360]["threshold"][1] == 25.0
    assert params[360]["threshold"][2] == 20.0  # unregistered rows: defaults
    assert params[8640]["threshold"][1] == 15.0  # other lag untouched
    assert params[360]["influence"][1] == np.float32(0.1)


def test_alert_param_vectors():
    acfg = {
        "hardMaxMsAlertThreshold": 10000,
        "overrides": {"services": {"slow": {"hardMaxMsAlertThreshold": 90000}}},
        "suppressedServices": ["noisy"],
    }
    reg = ServiceRegistry(4)
    reg.lookup_or_add("s", "normal")
    reg.lookup_or_add("s", "slow")
    reg.lookup_or_add("s", "noisy")
    p = reg.alert_params(acfg)
    assert p["hard_max_ms"][0] == 10000
    assert p["hard_max_ms"][1] == 90000
    assert not p["suppressed"][0] and p["suppressed"][2]
