"""Module runtime scaffolding + DB sink module wiring (single-process, memory broker)."""

import pytest

from apmbackend_tpu.config import default_config
from apmbackend_tpu.entries import TxEntry
from apmbackend_tpu.runtime.module_base import ModuleRuntime, make_queue_manager
from apmbackend_tpu.sinks import insert_db_main
from apmbackend_tpu.transport.memory import MemoryBroker


def make_runtime(section, cfg=None, broker=None):
    cfg = cfg or default_config()
    return ModuleRuntime(section, config=cfg, broker=broker, install_signals=False, console_log=False)


def test_make_queue_manager_memory_backend():
    qm = make_queue_manager({"brokerBackend": "memory", "statLogIntervalInSeconds": 60})
    q = qm.get_queue("t1", "p")
    q.write_line("tx|a|b|c|1|2|3|4|Y")
    qm.shutdown()


def test_make_queue_manager_unknown_backend():
    with pytest.raises(ValueError):
        make_queue_manager({"brokerBackend": "zeromq"})


def test_insert_db_module_end_to_end(tmp_path):
    broker = MemoryBroker()
    cfg = default_config()
    cfg["streamInsertDb"]["bufferResumeFileFullPath"] = str(tmp_path / "db.resume")
    cfg["streamInsertDb"]["dbMaxTimeBetweenInsertsMs"] = 100000  # no timer flush
    runtime = make_runtime("streamInsertDb", cfg, broker)
    # try/finally: the interval/queue-stats timer threads must be joined
    # even when an assertion fails, or the leaked timer fires into the root
    # logger at the next minute boundary (stray INFO lines after the suite
    # summary — exactly when a failing run is being read)
    try:
        writer = insert_db_main.build(runtime)

        # a producer in "another process": separate manager, same broker
        producer_qm = make_queue_manager({"brokerBackend": "memory"}, broker=broker)
        producer = producer_qm.get_queue("db_insert", "p")
        tx = TxEntry("srv1", "svc", "log1", 42, 1700000000000, 1700000005000, 5000, "Y")
        for _ in range(5):
            producer.write_line(tx.to_csv())
        broker.pump()
        assert writer.buffered_counts()["tx"] == 5
        writer.process_all()
        assert writer.executor.batches == [("tx", 5)]

        # exit handler flushes + saves resume (empty buffers here)
        for handler in reversed(runtime._exit_handlers):
            handler()
        assert (tmp_path / "db.resume").exists()
    finally:
        runtime.stop_timers()


def test_module_runtime_reload_handlers():
    runtime = make_runtime("streamInsertDb")
    try:
        seen = []
        runtime.on_reload(seen.append)
        new_cfg = default_config()
        new_cfg["statLogIntervalInSeconds"] = 5
        runtime._on_config_change(new_cfg)
        assert seen == [new_cfg]
        assert runtime.qm.queue_stats.interval == 5
    finally:
        runtime.stop_timers()
