"""JMX poller: CLI blob parsing, entry emission, scheduling (pull_jvm_stats.js role)."""

import json

from apmbackend_tpu.entries import EntryFactory
from apmbackend_tpu.ingest.jmx import JmxPoller, cli_to_json

# Shaped like real jboss-cli --output-json output: one bare JSON blob per
# command, free-text warnings interleaved, no separators between blobs.
CLI_OUTPUT = """Picked up JAVA_TOOL_OPTIONS: -Dfile.encoding=UTF8
{
    "outcome" : "success",
    "result" : {
        "ActiveCount" : 10,
        "AvailableCount" : 8,
        "InUseCount" : 2
    }
}
{
    "outcome" : "success",
    "result" : {
        "used" : 1000,
        "committed" : 2000,
        "max" : 4000
    }
}
{
    "outcome" : "success",
    "result" : {
        "used" : 100,
        "committed" : 200,
        "max" : 400
    }
}
{
    "outcome" : "success",
    "result" : 1.5
}
{
    "outcome" : "success",
    "result" : 12345
}
{
    "outcome" : "success",
    "result" : {
        "thread-count" : 77,
        "daemon-thread-count" : 33
    }
}
{
    "outcome" : "success",
    "result" : [{
        "result" : {
            "pool-available-count" : 5,
            "pool-current-size" : 3,
            "pool-max-size" : 10
        }
    }]
}"""

NAMES = ["ds", "heap", "meta", "sysload", "classcnt", "threading", "bean"]


def poller_config(**kw):
    cfg = {
        "clientJarFullPath": "/opt/jboss-cli-client.jar",
        "jvmHosts": ["jvm1.example.com", "jvm2.example.com"],
        "shortenHostname": True,
        "adminUser": "admin",
        "adminPass": "pw",
        "jmxPort": 8390,
        "clientTimeoutMs": 2000,
        "pollingIntervalSeconds": 60,
        "statCmdMap": {n: f"/cmd/{n}" for n in NAMES},
    }
    cfg.update(kw)
    return cfg


def test_cli_to_json_labels_blobs_in_order():
    stats = cli_to_json(NAMES, CLI_OUTPUT)
    assert stats["ds"]["result"]["InUseCount"] == 2
    assert stats["heap"]["result"]["max"] == 4000
    assert stats["sysload"]["result"] == 1.5
    assert stats["threading"]["result"]["thread-count"] == 77
    assert stats["bean"]["result"][0]["result"]["pool-max-size"] == 10


def test_cli_to_json_discards_warning_lines():
    out = "WARNING: something\n" + json.dumps({"result": 1}, indent=1)
    assert cli_to_json(["x"], out) == {"x": {"result": 1}}


def test_pull_all_emits_entries_and_shortens_hostnames():
    lines = []
    commands = []

    def runner(cmd, timeout_s):
        commands.append(cmd)
        return CLI_OUTPUT

    p = JmxPoller(poller_config(), lines.append, runner=runner, clock=lambda: 1700000000.0)
    entries = p.pull_all()
    assert len(entries) == 2
    assert entries[0].server == "jvm1"  # shortened
    assert entries[0].thread_cnt == 77
    assert entries[0].sys_load == 1.5
    # wire roundtrip through the shared factory
    rt = EntryFactory().from_csv(lines[0])
    assert rt.type == "jx" and rt.bean_pool_max_size == 10
    # command construction parity
    assert "--controller=jvm1.example.com:8390" in commands[0]
    assert '--connect commands="/cmd/ds,/cmd/heap' in commands[0]
    assert "--user=admin --password=pw" in commands[0]


def test_pull_all_skips_down_hosts():
    def runner(cmd, timeout_s):
        if "jvm1" in cmd:
            raise RuntimeError("connection refused")
        return CLI_OUTPUT

    p = JmxPoller(poller_config(), lambda l: None, runner=runner, clock=lambda: 1700000000.0)
    entries = p.pull_all()
    assert [e.server for e in entries] == ["jvm2"]


def test_no_hostname_shortening_when_disabled():
    p = JmxPoller(
        poller_config(shortenHostname=False, jvmHosts=["jvm1.example.com"]),
        lambda l: None,
        runner=lambda c, t: CLI_OUTPUT,
        clock=lambda: 1700000000.0,
    )
    assert p.pull_all()[0].server == "jvm1.example.com"


def test_second_aligned_schedule():
    at_13s = 1699999980.0 + 13  # :13 of the minute
    p = JmxPoller(poller_config(pollingIntervalSeconds=60), lambda l: None, clock=lambda: at_13s)
    assert p.seconds_until_next_poll() == 47
    p2 = JmxPoller(poller_config(pollingIntervalSeconds=15), lambda l: None, clock=lambda: at_13s)
    assert p2.seconds_until_next_poll() == 2  # 13 % 15 = 13 -> 2s to :15
