"""Soak proof: 72-file fan-in, >=10 min of log time, mid-run kill/restore.

The whole-system endurance test the reference never had (SURVEY §4 seams,
config scale apm_config.json:104-118): 24 JVMs x 3 log files each are fed
interleaved through the parser -> broker -> native intake ring -> fused
device pipeline, killed mid-stream (resume files saved), restored into a
fresh process object, and finished. Assertions:

1. **Detection parity across the restart**: every FullStat wire line the two
   runs emitted matches the float64 host oracle (tests/golden.py) run over
   the exact tx stream the device ingested — the resume snapshot must carry
   stats windows, z-score rings, counters and registry with no drift.
2. **Durability**: registry, latest label, and pending ordered-tx records
   survive the kill (pending_tx re-drains in run 2, no tx lost between the
   runs' window edges).
3. **Bounded memory**: the alert buffer honors its drop-oldest cap and the
   ordered-tx backlog never exceeds the 6-bucket buffer zone's worth of
   records (the leak surfaces of VERDICT round-1 Weak #5).
"""

import math
import os

import numpy as np
import pytest

from apmbackend_tpu.config import default_config
from apmbackend_tpu.entries import EntryFactory
from apmbackend_tpu.ingest.replay import write_fixture_logs
from apmbackend_tpu.standalone import StandalonePipeline

from golden import GoldenStats, GoldenZScore

# endurance tier: excluded from the default fast run (pytest.ini addopts
# -m "not soak"); run_tests.sh runs the FULL suite including these
pytestmark = pytest.mark.soak

N_JVMS = 24
TX_PER_JVM = 700  # ~1s of log time per tx => ~11-12 min => ~70 bucket labels
LAGS = [(6, 2.0, 0.1), (360, 20.0, 0.0)]


def soak_config(tmp_path):
    cfg = default_config()
    cfg["streamCalcZScore"]["defaults"] = [
        {"LAG": lag, "THRESHOLD": thr, "INFLUENCE": infl} for lag, thr, infl in LAGS
    ]
    eng = cfg["tpuEngine"]
    eng["serviceCapacity"] = 128
    eng["samplesPerBucket"] = 64  # stays exact: ~2 tx per (service, bucket)
    eng["microBatchSize"] = 4096
    eng["dtype"] = "float64"  # oracle bit-parity mode
    eng["resumeFileFullPath"] = str(tmp_path / "engine.resume")
    cfg["streamProcessAlerts"]["alertsResumeFileFullPath"] = str(tmp_path / "alerts.resume")
    cfg["streamInsertDb"]["dbBackend"] = "fake"
    cfg["streamInsertDb"]["bufferResumeFileFullPath"] = str(tmp_path / "db.resume")
    cfg["streamParseTransactions"]["serverFromPathPattern"] = r"_([A-Za-z0-9]+)\.log$"
    cfg["streamParseTransactions"]["tailPauseFileFullPath"] = str(tmp_path / "PAUSE")
    return cfg


def write_fleet(tmp_path):
    per_file = {}
    for i in range(N_JVMS):
        d = tmp_path / "fleet" / f"jvm{i:02d}"
        paths = write_fixture_logs(
            str(d), n_transactions=TX_PER_JVM, seed=500 + i, server=f"jvm{i:02d}",
            services=("getAccountInfo", "getOffers", "Provider[risk]"),
        )
        for p in paths.values():
            with open(p) as fh:
                per_file[p] = fh.read().splitlines()
    return per_file


def feed_interleaved(pipe, per_file, segment):
    """Round-robin the files 8 lines at a time; segment 0/1 = first/second half."""
    handles = []
    for p, lines in per_file.items():
        cut = len(lines) // 2
        chunk = lines[:cut] if segment == 0 else lines[cut:]
        handles.append((p, iter(chunk)))
    live = list(handles)
    while live:
        nxt = []
        for p, it in live:
            alive = False
            for _ in range(8):
                line = next(it, None)
                if line is None:
                    break
                pipe.parser.read_line(p, line)
                alive = True
            if alive:
                nxt.append((p, it))
        live = nxt
    pipe.drain()


def attach_taps(pipe, fed_lines, fullstat_lines):
    drv = pipe.worker.driver
    # feed_csv_batch and feed_csv_bytes delegate to EACH OTHER through the
    # (tapped) instance attributes — batch->bytes with a native decoder,
    # bytes->batch without one — so a depth guard keeps each line counted
    # exactly once, at the outermost entry point only.
    depth = {"n": 0}
    orig_feed = drv.feed_csv_batch

    def tee_feed(lines):
        if depth["n"] == 0:
            fed_lines.extend(lines)
        depth["n"] += 1
        try:
            return orig_feed(lines)
        finally:
            depth["n"] -= 1

    drv.feed_csv_batch = tee_feed
    orig_bytes = drv.feed_csv_bytes

    def tee_bytes(blob):
        if depth["n"] == 0:
            fed_lines.extend(blob.decode("utf-8", "replace").split("\n"))
        depth["n"] += 1
        try:
            return orig_bytes(blob)
        finally:
            depth["n"] -= 1

    drv.feed_csv_bytes = tee_bytes
    orig_fs = drv.on_fullstat_csv

    def tee_fs(lines):
        fullstat_lines.extend(lines)
        orig_fs(lines)

    drv.on_fullstat_csv = tee_fs
    return drv


def test_soak_72_file_fan_in_with_mid_run_kill(tmp_path):
    per_file = write_fleet(tmp_path)
    assert len(per_file) >= 70, f"fan-in needs >=70 files, got {len(per_file)}"
    cfg = soak_config(tmp_path)

    fed, emitted = [], []

    pipe1 = StandalonePipeline(config=cfg, tail=False, install_signals=False)
    drv1 = attach_taps(pipe1, fed, emitted)
    assert pipe1.worker._ring is not None, "soak must exercise the native ring"
    feed_interleaved(pipe1, per_file, 0)
    pipe1.shutdown()  # the kill: saves engine + alerts + pending_tx
    # snapshot AFTER shutdown: the parser's exit handler flushes TTL-expired
    # correlations as final tx, which can advance the label one more step
    rows1 = len(drv1.registry.rows())
    label1 = drv1._latest_label
    pending1 = len(drv1._tx_backlog)
    assert label1 > 0 and rows1 > 0
    # backlog bounded by the buffer zone (emitted rows drain every tick)
    assert pending1 < N_JVMS * 3 * 10 * (cfg["streamCalcStats"].get("bufferSizeInIntervals", 6) + 1)

    pipe2 = StandalonePipeline(config=cfg, tail=False, install_signals=False)
    drv2 = attach_taps(pipe2, fed, emitted)
    assert len(drv2.registry.rows()) == rows1, "registry must survive the kill"
    assert drv2._latest_label == label1, "window position must survive the kill"
    assert len(drv2._tx_backlog) == pending1, "pending ordered-tx must survive the kill"
    feed_interleaved(pipe2, per_file, 1)
    amgr = pipe2.worker.alerts_manager
    assert len(amgr.alert_buffer) <= amgr.MAX_BUFFERED
    assert drv2.overflow_rows_total == 0, "soak sized to stay in exact mode"
    pipe2.shutdown()

    # ---- the oracle: float64 host chain over the exact ingested stream ----
    fac = EntryFactory()
    golden_stats = GoldenStats()
    golden_z = {lag: GoldenZScore(lag, thr, infl) for lag, thr, infl in LAGS}

    def js_round(x, digits):
        if math.isnan(x):
            return x
        return math.floor(x * 10**digits + 0.5) / 10**digits

    expected = []  # (server, service, lag, field values)
    n_tx = 0
    key_order: dict = {}  # flat first-appearance order == registry row order
    for line in fed:
        entry = fac.from_csv(line)
        if entry is None or entry.type != "tx":
            continue
        n_tx += 1
        rows = golden_stats.add(entry.server, entry.service, int(entry.end_ts), int(entry.elapsed))
        key_order.setdefault((entry.server, entry.service), len(key_order))
        if rows:
            # golden walks its nested server->service dicts; the device emits
            # in flat registry (first-appearance) order — same SET, reorder
            rows = sorted(rows, key=lambda r: key_order[(r["server"], r["service"])])
            # device emission order: per channel block (all rows for lag A,
            # then all rows for lag B), rows in registry order
            qrows = [
                (r, js_round(r["tpm"], 2), js_round(r["average"], 1),
                 js_round(r["per75"], 1), js_round(r["per95"], 1))
                for r in rows
            ]
            for lag, _thr, _infl in LAGS:
                for r, tpm, avg, p75, p95 in qrows:
                    z = golden_z[lag].step(r["server"], r["service"], avg, p75, p95)
                    expected.append(
                        (r["ts"], r["server"], r["service"], lag, tpm, avg, p75, p95, z)
                    )
    assert n_tx > 5000, f"soak stream too small: {n_tx} tx"
    # >=10 min of log time: >=60 bucket labels emitted
    labels_seen = {e[0] for e in expected}
    assert len(labels_seen) >= 60, f"only {len(labels_seen)} tick edges"

    # ---- parity: every emitted FullStat line vs the oracle ----
    assert len(emitted) == len(expected), (
        f"emission count mismatch: device {len(emitted)} vs oracle {len(expected)}"
    )
    n_signals = 0
    for line, exp in zip(emitted, expected):
        fs = fac.from_csv(line)
        ts, server, service, lag, tpm, avg, p75, p95, z = exp
        assert (fs.timestamp, fs.server, fs.service, int(fs.lag)) == (ts, server, service, lag), (
            line, exp[:4],
        )
        for got, want in ((fs.tpm, tpm), (fs.average, avg), (fs.per75, p75), (fs.per95, p95)):
            if math.isnan(want):
                assert math.isnan(got), (line, exp)
            else:
                assert got == pytest.approx(want, rel=1e-9, abs=1e-9), (line, exp)
        for metric, (avg_f, sig_f) in {
            "avg": ("average_avg", "average_signal"),
            "p75": ("per75_avg", "per75_signal"),
            "p95": ("per95_avg", "per95_signal"),
        }.items():
            want_avg = z[metric]["avg"]
            got_avg = getattr(fs, avg_f)
            # the CSV wire carries 1 decimal; summation-order ulps can land a
            # .x5 mean on either side of the rounding boundary, so compare
            # numerically within half a wire step
            if math.isnan(want_avg):
                assert math.isnan(got_avg), (line, metric)
            else:
                assert abs(got_avg - want_avg) <= 0.0501 + 1e-9 * abs(want_avg), (
                    line, metric, got_avg, want_avg,
                )
            assert int(getattr(fs, sig_f)) == z[metric]["signal"], (line, metric)
            n_signals += abs(z[metric]["signal"])
    # the soak must actually exercise the detector, not just warm-up NaNs
    assert n_signals > 0, "no z-score signals fired over the whole soak"


def test_soak_lite_with_ewma_channels_and_resume(tmp_path):
    """Reduced fan-in soak with EWMA/seasonal channels live: the channel wire
    path (negative channel-id FullStat lines), its alert ladder, and its
    resume state must all survive a mid-run kill alongside the lag windows."""
    global N_JVMS, TX_PER_JVM
    saved = (N_JVMS, TX_PER_JVM)
    N_JVMS, TX_PER_JVM = 6, 250
    try:
        per_file = write_fleet(tmp_path)
        cfg = soak_config(tmp_path)
        cfg["tpuEngine"]["ewmaChannels"] = [
            {"ALPHA": 0.2, "THRESHOLD": 3.0, "WARMUP": 3, "CHANNEL_ID": -1},
            {"ALPHA": 0.3, "THRESHOLD": 2.5, "WARMUP": 2,
             "SEASON_SLOTS": 4, "SLOT_INTERVALS": 2, "CHANNEL_ID": -4},
        ]

        fed, emitted = [], []
        pipe1 = StandalonePipeline(config=cfg, tail=False, install_signals=False)
        drv1 = attach_taps(pipe1, fed, emitted)
        feed_interleaved(pipe1, per_file, 0)
        pipe1.shutdown()
        e1 = np.asarray(drv1.state.ewmas[0].mean)
        c1 = np.asarray(drv1.state.ewmas[1].count)
        assert np.isfinite(e1).any(), "EWMA channel never seeded in run 1"
        assert c1.sum() > 0

        fac = EntryFactory()
        chan_ids = {int(fac.from_csv(line).lag) for line in emitted}
        assert {-1, -4} <= chan_ids, f"EWMA channels missing from the wire: {chan_ids}"

        pipe2 = StandalonePipeline(config=cfg, tail=False, install_signals=False)
        drv2 = attach_taps(pipe2, fed, emitted)
        # EWMA state must resume bit-for-bit
        assert np.array_equal(
            e1, np.asarray(drv2.state.ewmas[0].mean), equal_nan=True
        ), "EWMA mean did not survive the kill"
        assert np.array_equal(c1, np.asarray(drv2.state.ewmas[1].count))
        feed_interleaved(pipe2, per_file, 1)
        pipe2.shutdown()
        # the seasonal channel's count advanced in run 2
        assert np.asarray(drv2.state.ewmas[1].count).sum() > c1.sum()
    finally:
        N_JVMS, TX_PER_JVM = saved


def test_soak_all_detector_families_with_restore(tmp_path):
    """Every detector family live at once — classic z-score lag, robust
    median/MAD lag, plain EWMA, hour-of-day seasonal, Holt level+trend —
    through the full standalone stack with a mid-run kill/restore. Each
    channel must emit FullStat wire lines in BOTH halves, and every family's
    device state must survive the restart byte-for-byte (snapshot vs
    restored)."""
    n_jvms = 8
    per_file = {}
    for i in range(n_jvms):
        d = tmp_path / "fleet" / f"jvm{i:02d}"
        paths = write_fixture_logs(
            str(d), n_transactions=400, seed=900 + i, server=f"jvm{i:02d}",
            services=("getAccountInfo", "getOffers"),
        )
        for p in paths.values():
            with open(p) as fh:
                per_file[p] = fh.read().splitlines()

    cfg = soak_config(tmp_path)
    cfg["streamCalcZScore"]["defaults"] = [
        {"LAG": 6, "THRESHOLD": 2.0, "INFLUENCE": 0.1},
        {"LAG": 12, "THRESHOLD": 3.0, "INFLUENCE": 0.0, "ROBUST": True},
    ]
    cfg["tpuEngine"]["ewmaChannels"] = [
        {"ALPHA": 0.3, "THRESHOLD": 3.0, "WARMUP": 3, "CHANNEL_ID": -1},
        {"ALPHA": 0.3, "THRESHOLD": 3.0, "WARMUP": 2, "SEASON_SLOTS": 24,
         "SLOT_INTERVALS": 360, "CHANNEL_ID": -24},
        {"ALPHA": 0.2, "THRESHOLD": 3.0, "WARMUP": 3, "CHANNEL_ID": -2,
         "TREND_BETA": 0.25},
    ]
    channel_ids = {"6", "12", "-1", "-24", "-2"}

    emitted_1, emitted_2 = [], []

    pipe1 = StandalonePipeline(config=cfg, tail=False, install_signals=False)
    drv1 = attach_taps(pipe1, [], emitted_1)
    feed_interleaved(pipe1, per_file, 0)
    # snapshot family states BEFORE shutdown mutates them further
    pipe1.shutdown()
    state1 = drv1.state
    classic_ring = np.asarray(state1.zscores[0].values)
    robust_ring = np.asarray(state1.zscores[1].values)
    holt_trend = np.asarray(state1.ewmas[2].trend)
    seasonal_count = np.asarray(state1.ewmas[1].count)

    chans_1 = {line.split("|")[4] for line in emitted_1 if line.startswith("fs|")}
    assert chans_1 == channel_ids, f"first half emitted {chans_1}"

    pipe2 = StandalonePipeline(config=cfg, tail=False, install_signals=False)
    drv2 = attach_taps(pipe2, [], emitted_2)
    # restored state == saved state for every family
    np.testing.assert_array_equal(
        classic_ring, np.asarray(drv2.state.zscores[0].values), err_msg="classic ring"
    )
    np.testing.assert_array_equal(
        robust_ring, np.asarray(drv2.state.zscores[1].values), err_msg="robust ring"
    )
    np.testing.assert_array_equal(
        holt_trend, np.asarray(drv2.state.ewmas[2].trend), err_msg="holt trend"
    )
    np.testing.assert_array_equal(
        seasonal_count, np.asarray(drv2.state.ewmas[1].count), err_msg="seasonal counts"
    )
    feed_interleaved(pipe2, per_file, 1)
    pipe2.shutdown()
    chans_2 = {line.split("|")[4] for line in emitted_2 if line.startswith("fs|")}
    assert chans_2 == channel_ids, f"second half emitted {chans_2}"
    # the Holt channel's trend state actually moved (a zero trend would mean
    # the TREND_BETA config never reached the device recursion)
    assert float(np.abs(np.nan_to_num(np.asarray(drv2.state.ewmas[2].trend))).sum()) > 0
