"""Whole-system test: replayed logs -> parser -> TPU worker -> DB sink rows,
all in one process over the memory broker (the reference's full 6-process
pipeline collapsed; SURVEY.md §7.2 minimum end-to-end slice)."""

import sqlite3

from apmbackend_tpu.config import default_config
from apmbackend_tpu.ingest.replay import write_fixture_logs
from apmbackend_tpu.standalone import StandalonePipeline


def small_config(tmp_path, **engine_overrides):
    cfg = default_config()
    cfg["logDir"] = str(tmp_path / "logs")
    cfg["streamCalcZScore"]["defaults"] = [
        {"LAG": 4, "THRESHOLD": 2.0, "INFLUENCE": 0.1},
        {"LAG": 8, "THRESHOLD": 3.0, "INFLUENCE": 0.0},
    ]
    eng = cfg["tpuEngine"]
    eng["serviceCapacity"] = 64
    eng["samplesPerBucket"] = 32
    eng["microBatchSize"] = 1024
    eng["resumeFileFullPath"] = str(tmp_path / "engine.resume.npz")
    eng.update(engine_overrides)
    cfg["streamProcessAlerts"]["alertsResumeFileFullPath"] = str(tmp_path / "alerts.resume")
    cfg["streamInsertDb"]["bufferResumeFileFullPath"] = str(tmp_path / "db.resume")
    cfg["streamInsertDb"]["dbBackend"] = "sqlite"
    cfg["streamInsertDb"]["dbFileFullPath"] = str(tmp_path / "apm.db")
    cfg["streamInsertDb"]["dbMaxTimeBetweenInsertsMs"] = 100000
    cfg["streamParseTransactions"]["tailPauseFileFullPath"] = str(tmp_path / "PAUSE")
    # flat fixture dir: server rides in the filename, default for server.log
    cfg["streamParseTransactions"]["serverFromPathPattern"] = r"_([A-Za-z0-9]+)\.log$"
    cfg["streamParseTransactions"]["serverPathComponentIndex"] = None
    cfg["streamParseTransactions"]["defaultServerName"] = "jvmhost1"
    return cfg


def test_replay_to_database(tmp_path):
    logs = tmp_path / "fixture_logs"
    write_fixture_logs(str(logs), n_transactions=150, seed=11)
    cfg = small_config(tmp_path)
    pipe = StandalonePipeline(config=cfg, tail=False, install_signals=False)
    fed = pipe.replay(str(logs))
    assert fed > 0

    conn = sqlite3.connect(cfg["streamInsertDb"]["dbFileFullPath"])
    n_tx = conn.execute("SELECT COUNT(*) FROM tx").fetchone()[0]
    # transactions land in the tx table via the ordered heap drain; records
    # newer than the last 10 s tick edge stay pending (and persist via the
    # stats resume snapshot, like the reference's heap-in-resume-file)
    assert n_tx >= 80
    drv = pipe.worker.driver
    pending = drv.heap.size() + len(drv._tx_backlog)
    assert pending > 0
    # z-score passthrough rows (2 lags x services x ticks) land in stats
    n_fs = conn.execute("SELECT COUNT(*) FROM stats").fetchone()[0]
    assert n_fs > 0
    servers = {r[0] for r in conn.execute("SELECT DISTINCT server FROM tx")}
    assert servers == {"jvmhost1"}
    pipe.shutdown()


def test_replay_resume_continuity(tmp_path):
    """Kill and restart the pipeline mid-stream: state resumes, no crash."""
    logs1 = tmp_path / "logs1"
    logs2 = tmp_path / "logs2"
    write_fixture_logs(str(logs1), n_transactions=60, seed=1)
    write_fixture_logs(str(logs2), n_transactions=60, seed=2)
    cfg = small_config(tmp_path)

    pipe1 = StandalonePipeline(config=cfg, tail=False, install_signals=False)
    pipe1.replay(str(logs1))
    rows1 = len(pipe1.worker.driver.registry.rows())
    pipe1.shutdown()
    assert rows1 > 0

    pipe2 = StandalonePipeline(config=cfg, tail=False, install_signals=False)
    # engine registry restored from the resume file
    assert len(pipe2.worker.driver.registry.rows()) == rows1
    pipe2.replay(str(logs2))
    pipe2.shutdown()


def test_stats_queue_mirroring(tmp_path):
    """emitStatsQueue mirrors StatEntry lines for per-stage inspection."""
    logs = tmp_path / "fixture_logs"
    write_fixture_logs(str(logs), n_transactions=80, seed=5)
    cfg = small_config(tmp_path, emitStatsQueue=True)
    pipe = StandalonePipeline(config=cfg, tail=False, install_signals=False)
    pipe.replay(str(logs))

    from apmbackend_tpu.tools.dequeue import drain
    from apmbackend_tpu.runtime.module_base import make_queue_manager
    import io

    out = io.StringIO()
    qm = make_queue_manager({"brokerBackend": "memory"}, broker=pipe.broker)
    seen = drain(qm, "stats", idle_s=0.3, out=out)
    assert seen > 0
    assert out.getvalue().startswith("st|")
    pipe.shutdown()


def test_tiny_ring_overflow_path_no_loss(tmp_path):
    """A ring far too small for the stream forces the bounded-spin overflow
    path (the AMQP-heartbeat protection): every line must still reach the
    driver, in order, with zero drops while under the overflow cap."""
    logs = tmp_path / "fixture_logs"
    write_fixture_logs(str(logs), n_transactions=120, seed=17)
    cfg = small_config(tmp_path, ringBytes=1 << 12, ringFullMaxBlockSeconds=0.0)
    pipe = StandalonePipeline(config=cfg, tail=False, install_signals=False)
    assert pipe.worker._ring is not None
    fed = pipe.replay(str(logs))
    assert fed > 0
    assert pipe.worker.intake_dropped == 0
    assert pipe.worker._ring_fed == pipe.worker._ring_pushed
    assert pipe.worker.driver.registry.count > 0
    pipe.shutdown()


def test_hbm_watchdog_telemetry_and_alarm(tmp_path):
    """The device-memory watchdog (worker _check_device_memory): telemetry
    fields update, the manager alert fires once past the alarm fraction,
    stays silent while latched, and re-arms after recovery hysteresis."""
    cfg = small_config(tmp_path)
    pipe = StandalonePipeline(config=cfg, tail=False, install_signals=False)
    w = pipe.worker
    try:
        GiB = 2**30
        fake = {"bytes_in_use": 1 * GiB, "bytes_limit": 16 * GiB}
        w._device_memory_stats = lambda: fake
        w._check_device_memory()
        assert w.hbm_bytes_in_use == 1 * GiB and w.hbm_bytes_limit == 16 * GiB
        assert not w._hbm_alerted
        before = len(w.ops_alerts.buffer)

        fake = {"bytes_in_use": 15 * GiB, "bytes_limit": 16 * GiB}  # 94% > 90%
        w._check_device_memory()
        assert w._hbm_alerted
        assert len(w.ops_alerts.buffer) == before + 1
        w._check_device_memory()  # latched: no repeat alert
        assert len(w.ops_alerts.buffer) == before + 1

        fake = {"bytes_in_use": 14.6 * GiB, "bytes_limit": 16 * GiB}  # 91%: still latched
        w._check_device_memory()
        assert w._hbm_alerted
        fake = {"bytes_in_use": 8 * GiB, "bytes_limit": 16 * GiB}  # < 72%: re-arm
        w._check_device_memory()
        assert not w._hbm_alerted
        fake = {"bytes_in_use": 15 * GiB, "bytes_limit": 16 * GiB}
        w._check_device_memory()
        assert len(w.ops_alerts.buffer) == before + 2

        # no memory stats (CPU backend): a clean no-op
        fake = {}
        w._check_device_memory()
    finally:
        pipe.shutdown()
