"""Fused-executor parity: the single/two-dispatch fused tick, the megatick
scan and the staged executor must be the SAME engine.

The fused paths (pipeline.make_fused_step, make_megatick — the r5
dispatch-floor fix) re-arrange WHERE each stage runs (one donated program vs
five, rebuild-before-tick vs tick-then-rebuild, host percentile kernel vs
in-program), never WHAT is computed: every test here asserts bit-identical
TickEmission leaves against the staged executor over >= 64 ticks including
label jumps, ring evictions (lag << ticks) and multiple staggered-rebuild
rotations. The rebuild phase note: fused integrates the rebuild chunk at the
START of its tick program (ring-read-only constraint), so the staged
reference runs its RebuildScheduler immediately BEFORE each tick — the same
schedule, just expressed by the host loop; both arrangements re-aggregate
every row once per zscore_rebuild_every ticks.
"""

import os

import numpy as np
import pytest

import jax

jnp = pytest.importorskip("jax.numpy")

from apmbackend_tpu.pipeline import (  # noqa: E402
    RebuildScheduler,
    engine_ingest,
    fused_copy_bytes,
    make_demo_engine,
    make_engine_step,
    make_fused_step,
    make_megatick,
    resolve_tick_executor,
)

CAP = 24
LAGS = [(6, 3.0, 0.1), (12, 2.5, 0.0)]
BASE = 170_000_000


def _engine(rebuild_every=16):
    cfg, state, params = make_demo_engine(CAP, 8, LAGS)
    return cfg._replace(zscore_rebuild_every=rebuild_every), state, params


def _batch(rng, lbl, n=64):
    return (
        rng.randint(0, CAP, n).astype(np.int32),
        np.full(n, lbl, np.int32),
        (200 + 50 * rng.rand(n)).astype(np.float32),
        np.ones(n, bool),
    )


def _labels(n):
    # +1 ticks with a jump every 9th — evictions (lag 6/12 << n) and
    # advance_span's multi-slot clear both exercised
    label, out = BASE, []
    for k in range(n):
        label += 1 if k % 9 else 3
        out.append(label)
    return out


def _run_staged_prerebuild(n_ticks):
    """Reference stream: staged executor with the scheduler stepped BEFORE
    each tick (matches the fused integrated rebuild's phase), XLA slice
    rebuild (allow_native=False => bitwise-identical math to the fused
    in-program slice)."""
    cfg, state, params = _engine()
    os.environ["APM_TICK_EXECUTOR"] = "staged"
    try:
        step = make_engine_step(cfg)
    finally:
        os.environ.pop("APM_TICK_EXECUTOR", None)
    assert step.kind == "staged"
    sched = RebuildScheduler(cfg, allow_native=False)
    ingest = jax.jit(engine_ingest, static_argnums=1, donate_argnums=(0,))
    rng = np.random.RandomState(7)
    ems = []
    for lbl in _labels(n_ticks):
        state = sched.step(state)
        em, state = step(state, lbl, params)
        ems.append(jax.tree.map(np.asarray, em))
        state = ingest(state, cfg, *_batch(rng, lbl))
    return ems


def _assert_emissions_equal(a_list, b_list, *, exact=True):
    """exact=True: bit-identical. exact=False: int/bool leaves (signals,
    triggers, counts, cause bits) still bit-identical, float leaves within
    2e-6 relative — the DOCUMENTED tolerance for pairings whose f32 reduces
    live at different XLA program boundaries (e.g. the rebuild-slice pass
    standalone vs fused into the tick program: XLA:CPU may reassociate a
    fused reduce, shifting window means by ulps; detection decisions are the
    integer leaves, and those must never differ)."""
    assert len(a_list) == len(b_list) and len(a_list) > 0
    for t, (a, b) in enumerate(zip(a_list, b_list)):
        for x, y in zip(jax.tree.flatten(a)[0], jax.tree.flatten(b)[0]):
            x, y = np.asarray(x), np.asarray(y)
            if exact or x.dtype.kind != "f":
                assert np.array_equal(
                    np.nan_to_num(x, nan=-123.0), np.nan_to_num(y, nan=-123.0)
                ), f"tick {t}: {x.dtype}{x.shape} emission leaf diverged"
            else:
                np.testing.assert_allclose(
                    np.nan_to_num(x, nan=-123.0), np.nan_to_num(y, nan=-123.0),
                    rtol=2e-6, atol=1e-4,
                    err_msg=f"tick {t}: {x.dtype}{x.shape} beyond ulp tolerance",
                )


@pytest.mark.parametrize("force_all", [False, True])
def test_fused_matches_staged_bitwise(force_all, monkeypatch):
    """Both fused forms — the two-program native-percentile split and the
    everything-in-one-program fused-all — match the staged engine over 72
    ticks with jumps, evictions and 4+ full rebuild rotations. The
    production pairing (native percentiles both sides) is BITWISE; the
    forced fused-all pairing allows the documented ulp tolerance on float
    leaves (_assert_emissions_equal) because its in-program rebuild reduce
    sits at a different fusion boundary than the reference scheduler's
    standalone program."""
    if force_all:
        # force the fused-all form even where the native kernel exists
        import apmbackend_tpu.pipeline as P

        monkeypatch.setattr(P, "_use_native_percentiles", lambda cfg: False)
    ref = _run_staged_prerebuild(72)

    cfg, state, params = _engine()
    step = make_fused_step(cfg)
    assert step.rebuild_integrated
    ingest = jax.jit(engine_ingest, static_argnums=1, donate_argnums=(0,))
    rng = np.random.RandomState(7)
    ems = []
    for lbl in _labels(72):
        em, state = step(state, lbl, params)
        ems.append(jax.tree.map(np.asarray, em))
        state = ingest(state, cfg, *_batch(rng, lbl))
    _assert_emissions_equal(ref, ems, exact=not force_all)


def test_megatick_matches_per_tick(monkeypatch):
    """The K-slot lax.scan megatick replays the same (tick, ingest) stream
    bit-identically to the per-tick fused path, across 3 megatick dispatches
    including ingest-only slots."""
    import apmbackend_tpu.pipeline as P

    # both sides in-program percentiles (the scan cannot host the kernel)
    monkeypatch.setattr(P, "_use_native_percentiles", lambda cfg: False)
    K, B = 12, 32
    cfg, state, params = _engine(rebuild_every=8)
    mega = make_megatick(cfg, K, B)
    rng = np.random.RandomState(3)

    def slots(off):
        nls = np.zeros(K, np.int32)
        do = np.zeros(K, bool)
        rows = np.zeros((K, B), np.int32)
        labels = np.zeros((K, B), np.int32)
        elaps = np.zeros((K, B), np.float32)
        valid = np.zeros((K, B), bool)
        recs = []
        for k in range(K):
            lbl = BASE + off + k
            do[k] = k > 0 or off > 0  # first-ever slot: ingest only
            nls[k] = lbl
            n = int(rng.randint(4, B))
            r = rng.randint(0, CAP, n)
            e = (200 + 50 * rng.rand(n)).astype(np.float32)
            rows[k, :n] = r
            labels[k, :n] = lbl
            elaps[k, :n] = e
            valid[k, :n] = True
            recs.append((lbl, r, e, n, bool(do[k])))
        return (nls, do, rows, labels, elaps, valid), recs

    all_recs, ems_mega = [], []
    for off in (0, K, 2 * K):
        xs, recs = slots(off)
        all_recs.extend(recs)
        em, state = mega(state, params, *xs)
        ems_mega.append(jax.tree.map(np.asarray, em))

    # reference: the per-tick fused-all executor over the identical stream
    cfg2, st2, params2 = _engine(rebuild_every=8)
    step = make_fused_step(cfg2)
    ingest = jax.jit(engine_ingest, static_argnums=1, donate_argnums=(0,))
    ems_ref = []
    for lbl, r, e, n, do in all_recs:
        if do:
            em, st2 = step(st2, lbl, params2)
            ems_ref.append(jax.tree.map(np.asarray, em))
        rows = np.zeros(B, np.int32)
        labels = np.zeros(B, np.int32)
        elaps = np.zeros(B, np.float32)
        valid = np.zeros(B, bool)
        rows[:n], labels[:n], elaps[:n], valid[:n] = r, lbl, e, True
        st2 = ingest(st2, cfg2, rows, labels, elaps, valid)

    flat_mega = []
    for g, em in enumerate(ems_mega):
        leaves = jax.tree.flatten(em)[0]
        for k in range(K):
            if all_recs[g * K + k][4]:
                flat_mega.append([lf[k] for lf in leaves])
    assert len(flat_mega) == len(ems_ref)
    # same tolerance contract as _assert_emissions_equal(exact=False): the
    # scan body is yet another fusion boundary for the f32 reduces; integer
    # decision leaves must still be bit-identical
    for t, (a, b) in enumerate(zip(flat_mega, ems_ref)):
        for x, y in zip(a, jax.tree.flatten(b)[0]):
            x, y = np.asarray(x), np.asarray(y)
            if x.dtype.kind != "f":
                assert np.array_equal(x, y), (
                    f"megatick slot {t}: integer emission leaf diverged"
                )
            else:
                np.testing.assert_allclose(
                    np.nan_to_num(x, nan=-9.0), np.nan_to_num(y, nan=-9.0),
                    rtol=2e-6, atol=1e-4,
                    err_msg=f"megatick slot {t} beyond ulp tolerance",
                )


def test_executor_resolution_and_gate(monkeypatch):
    """auto = fused under the byte budget, staged above it; explicit config
    and the env override pin either; the driver follows the resolution."""
    cfg, _, _ = _engine()
    assert resolve_tick_executor(cfg) == "fused"  # ~200 KB of state
    assert resolve_tick_executor(cfg._replace(tick_executor="staged")) == "staged"
    monkeypatch.setenv("APM_FUSED_MAX_BYTES", "1")
    assert resolve_tick_executor(cfg) == "staged"  # budget forces staged
    monkeypatch.setenv("APM_TICK_EXECUTOR", "fused")
    assert resolve_tick_executor(cfg) == "fused"  # env overrides everything
    monkeypatch.delenv("APM_TICK_EXECUTOR")
    monkeypatch.delenv("APM_FUSED_MAX_BYTES")
    assert fused_copy_bytes(cfg) > 0
    with pytest.raises(ValueError):
        resolve_tick_executor(cfg._replace(tick_executor="warp"))


def test_driver_async_emission_same_outputs(monkeypatch):
    """asyncEmission=true delivers the identical StatEntry/FullStatEntry
    stream (one tick late internally, flushed at the end) — catch-up mode
    must change latency, never content."""
    from apmbackend_tpu.config import default_config
    from apmbackend_tpu.pipeline import PipelineDriver

    def cfgd():
        c = default_config()
        c["tpuEngine"]["serviceCapacity"] = 16
        c["tpuEngine"]["samplesPerBucket"] = 8
        c["streamCalcZScore"]["defaults"] = [
            {"LAG": 4, "THRESHOLD": 3.0, "INFLUENCE": 0.1}
        ]
        return c

    def run(async_emission):
        stats, fs = [], []
        drv = PipelineDriver(
            cfgd(),
            on_stat=lambda s: stats.append(s.to_csv()),
            on_fullstat=lambda f: fs.append(f.to_csv()),
            async_emission=async_emission,
        )
        base = BASE
        lines = []
        rng = np.random.RandomState(5)
        for i in range(10):
            lbl = base + i
            for j in range(int(rng.randint(2, 6))):
                e = int(rng.randint(100, 900))
                lines.append(
                    f"tx|jvm0|S:svc{j % 3}|l{i}{j}|1|{lbl * 10000 - e}|{lbl * 10000 + j}|{e}|Y"
                )
        drv.feed_csv_batch(lines)
        drv.flush()
        return stats, fs

    s_sync, f_sync = run(False)
    s_async, f_async = run(True)
    assert s_sync == s_async and f_sync == f_async and len(f_sync) > 0


def test_advance_span_matches_advance_one_loop():
    """advance_span (the fused in-program label advance) == the staged host
    loop of advance_one, for +1 ticks, multi-label jumps, jumps past NB, and
    the stale-label clamp."""
    from apmbackend_tpu.ops import stats as dstats

    cfg = dstats.StatsConfig(capacity=5, window_sz=6, buffer_sz=2,
                             samples_per_bucket=4)
    NB = cfg.num_buckets
    rng = np.random.RandomState(0)
    st_a = dstats.init_state(cfg)
    st_b = dstats.init_state(cfg)
    span = jax.jit(dstats.advance_span, static_argnums=1)
    one = jax.jit(dstats.advance_one, static_argnums=1)
    label = 100
    # seed a first tick + some data, then exercise jump shapes
    for jump in [1, 1, 2, NB - 1, NB, NB + 3, 1, 0, -2, 1]:
        label = label + jump
        st_a = span(st_a, cfg, jnp.int32(label))
        latest = int(st_b.latest_bucket)
        nl = max(latest, label)
        for lbl in range(max(latest + 1, nl - NB + 1), nl + 1):
            st_b = one(st_b, cfg, lbl)
        if int(st_b.latest_bucket) != nl:  # stale tick: clamp like tick()
            st_b = st_b._replace(latest_bucket=jnp.int32(nl))
        label = nl
        for x, y in zip(jax.tree.flatten(st_a)[0], jax.tree.flatten(st_b)[0]):
            assert np.array_equal(
                np.nan_to_num(np.asarray(x), nan=-1.0),
                np.nan_to_num(np.asarray(y), nan=-1.0),
            )
        # scatter some data so cleared-slot content matters
        n = 8
        rows = rng.randint(0, 5, n).astype(np.int32)
        labels = np.full(n, label, np.int32)
        elaps = rng.rand(n).astype(np.float32) * 100
        valid = np.ones(n, bool)
        st_a = dstats.ingest(st_a, cfg, rows, labels, elaps, valid)
        st_b = dstats.ingest(st_b, cfg, rows, labels, elaps, valid)


def test_radix_selection_exactness():
    """The dense-window radix path of the native percentile kernel returns
    the exact reference order statistics — cross-checked against the jitted
    sorted-path oracle on adversarial rows (ties, NaN holes, near-boundary
    ranks) straddling the RADIX_MIN=256 regime switch."""
    from apmbackend_tpu import native as _native

    if not _native.have_native_percentiles():
        pytest.skip("native toolchain unavailable")
    from apmbackend_tpu.ops import stats as dstats

    rng = np.random.RandomState(11)
    S, NB, CAPS = 12, 9, 64
    samples = np.full((S, NB, CAPS), np.nan, np.float32)
    counts = np.zeros((S, NB), np.int32)
    per_row = [0, 40, 200, 255, 256, 300, 420, 576, 576, 576, 576, 130]
    for s in range(S):
        n = per_row[s]
        per_bucket = -(-n // NB) if n else 0
        left = n
        for b in range(NB):
            m = min(per_bucket, left, CAPS)
            if m <= 0:
                break
            if s == 7:
                vals = np.full(m, 42.0, np.float32)  # massive ties
            elif s == 8:
                vals = rng.choice([1.0, 2.0, 3.0], m).astype(np.float32)
            elif s == 9:
                vals = (rng.rand(m) * 1e6).astype(np.float32)
            elif s == 10:
                vals = -rng.rand(m).astype(np.float32) * 50  # negatives
            else:
                vals = (50 + 900 * rng.rand(m)).astype(np.float32)
            samples[s, b, :m] = vals
            counts[s, b] = m
            left -= m
    mask = np.ones(NB, bool)
    mask[3] = False  # one excluded bucket
    counts_masked = counts.copy()
    got = _native.window_percentiles_native(samples, mask, (75, 95), counts_masked)

    # oracle: exact reference math over the gathered window samples
    for s in range(S):
        window = samples[s, mask, :].ravel()
        window = window[~np.isnan(window)]
        n = len(window)
        if n == 0:
            assert np.isnan(got[s]).all()
            continue
        sorted_vals = jnp.asarray(np.sort(window))[None, :]
        for pi, p in enumerate((75, 95)):
            want = float(
                dstats.reference_percentile_sorted(
                    sorted_vals, jnp.asarray([n], jnp.int32), p
                )[0]
            )
            assert got[s, pi] == np.float32(want), (
                f"row {s} (n={n}) p{p}: native {got[s, pi]} != oracle {want}"
            )
