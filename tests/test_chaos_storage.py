"""Hostile-storage chaos tier: the delta-checkpoint chain under kill−9,
torn tails, crash-during-compaction and ENOSPC (ISSUE 7).

Two tiers, like tests/test_chaos_harness.py:

- fast (tier-1): the production WorkerApp epoch cycle in delta mode over
  the durable spool, with in-process "crashes" (abandon without shutdown),
  post-crash tail corruption, and injected write failures driving the
  graceful-degradation machinery end to end;
- ``slow``: real subprocesses — SIGKILL mid-stream under duplicate
  injection in delta mode compared bit-identically against a FULL-mode
  golden run (cross-representation equivalence is the strongest form of
  the chain's correctness claim), deterministic SIGKILL inside the
  compaction window via ``APM_CHAOS_FS=kill:compact=...``, and ENOSPC
  retry/recovery under the real epoch timer. Run via
  ``./run_tests.sh --chaos``.
"""

import os
import time

import numpy as np
import pytest

from apmbackend_tpu.config import default_config
from apmbackend_tpu.deltachain import (
    DeltaChain,
    StorageFaultPlan,
    install_fault_plan,
)
from apmbackend_tpu.testing.chaos import ChaosWorkerHarness, SpoolChannel
from apmbackend_tpu.transport.base import QueueManager

from test_chaos_harness import assert_snapshots_equal, make_stream


def _delta_worker(spool_dir, workdir, *, dup_p=0.0, seed=0, compact_every=0,
                  max_retries=2, flight=False):
    """The chaos child's wiring in-process: real WorkerApp, atLeastOnce,
    delta-chain checkpoints over a spool transport."""
    from apmbackend_tpu.runtime.module_base import ModuleRuntime
    from apmbackend_tpu.runtime.worker import WorkerApp
    from apmbackend_tpu.testing.chaos import ChaosChannel

    cfg = default_config()
    eng = cfg["tpuEngine"]
    eng["serviceCapacity"] = 32
    eng["samplesPerBucket"] = 64
    eng["deliveryMode"] = "atLeastOnce"
    eng["checkpointMode"] = "delta"
    eng["checkpointChainDir"] = os.path.join(workdir, "chain")
    eng["checkpointCompactEveryEpochs"] = compact_every
    eng["checkpointWriteMaxRetries"] = max_retries
    eng["checkpointWriteRetryBaseSeconds"] = 0.01
    eng["checkpointWriteRetryMaxSeconds"] = 0.05
    eng["resumeFileFullPath"] = None
    cfg["streamCalcZScore"]["defaults"] = [{"LAG": 6, "THRESHOLD": 3.0, "INFLUENCE": 0.1}]
    cfg["streamCalcStats"]["resumeFileSaveFrequencyInSeconds"] = 3600  # manual commits
    cfg["streamProcessAlerts"]["alertsResumeFileFullPath"] = None
    cfg["logDir"] = None
    if flight:
        cfg["observability"]["flightDir"] = os.path.join(workdir, "flight")
    rt = ModuleRuntime("tpuEngine", config=cfg, install_signals=False, console_log=False)
    spools = {}

    def factory(direction):
        ch = SpoolChannel(spool_dir)
        spools[direction] = ch
        if direction == "c" and dup_p:
            return ChaosChannel(ch, dup_p=dup_p, seed=seed)
        return ch

    rt.qm = QueueManager(factory, 3600, logger=rt.logger)
    worker = WorkerApp(rt)
    return worker, rt, spools["c"]


def _feed_spool(spool_dir, lines, start_seq=0):
    prod = SpoolChannel(spool_dir)
    for n, line in enumerate(lines, start=start_seq + 1):
        prod.send(
            "transactions", line.encode("utf-8"),
            {"ingest_ts": time.time(), "msg_id": f"h-{n}"},
        )
    prod.close()


def _golden_full_snapshot(tmp_path, lines):
    """A crash-free FULL-mode worker run: the cross-representation oracle."""
    from test_chaos_harness import _spool_worker

    gdir = str(tmp_path / "golden")
    gres = str(tmp_path / "golden.npz")
    _feed_spool(gdir, lines)
    w, rt, spool = _spool_worker(gdir, gres)
    n = 0
    while n < len(lines):
        n += spool.deliver(50)
    w.save_state()
    assert spool.acked_count("transactions") == len(lines)
    rt.stop_timers()
    spool.stop()
    return gres


def _export_snapshot(worker, path):
    with worker._driver_lock:
        worker.driver.save_resume(path)
    return path


# -- fast tier ---------------------------------------------------------------


def test_in_process_delta_crash_equivalence(tmp_path):
    """Delta-mode epoch cycle, crash (no shutdown), restart from the chain:
    final state equals a crash-free FULL-mode run bit-for-bit."""
    lines = make_stream(n_labels=5, per_label=60)
    gres = _golden_full_snapshot(tmp_path, lines)

    cdir = str(tmp_path / "chaos")
    wdir = str(tmp_path / "chaoswork")
    os.makedirs(wdir, exist_ok=True)
    _feed_spool(cdir, lines)
    w1, rt1, spool1 = _delta_worker(cdir, wdir, dup_p=0.15, seed=11)
    delivered = 0
    while delivered < 120:
        delivered += spool1.deliver(30)
        if delivered == 60:
            w1.save_state()  # one committed epoch
    committed = spool1.acked_count("transactions")
    assert committed > 0
    rt1.stop_timers()
    spool1.stop()  # SIGKILL stand-in: no flush, no save, no acks

    w2, rt2, spool2 = _delta_worker(cdir, wdir, dup_p=0.15, seed=12)
    assert w2._delivery_epoch >= 1  # chain seeded the watermark
    n = spool2.delivered_count("transactions")
    assert n == committed  # redelivery starts AT the cursor: zero loss
    while n < len(lines):
        n += spool2.deliver(50)
    w2.save_state()
    assert spool2.acked_count("transactions") == len(lines)
    cres = _export_snapshot(w2, str(tmp_path / "chaos.npz"))
    rt2.stop_timers()
    spool2.stop()
    assert_snapshots_equal(gres, cres)


@pytest.mark.parametrize("mode", ["truncate", "garbage", "header"])
def test_torn_tail_before_ack_recovers_and_redelivers(tmp_path, mode):
    """Crash tears the final segment AFTER the rename but BEFORE the ack
    (the non-atomic-storage window): recovery falls back one epoch, the
    broker redelivers the whole torn epoch, dedup absorbs what the
    surviving window knows, and the final state still equals golden."""
    lines = make_stream(n_labels=4, per_label=50)
    gres = _golden_full_snapshot(tmp_path, lines)

    cdir = str(tmp_path / "spool")
    wdir = str(tmp_path / "work")
    os.makedirs(wdir, exist_ok=True)
    chain_dir = os.path.join(wdir, "chain")
    _feed_spool(cdir, lines)
    w1, rt1, spool1 = _delta_worker(cdir, wdir)
    n = 0
    while n < 100:
        n += spool1.deliver(25)
    w1.save_state()  # committed + acked epoch
    while n < len(lines):
        n += spool1.deliver(50)
    # commit WITHOUT ack: the crash window between segment rename and ack
    with w1._driver_lock:
        w1._drain_alo_pending_locked()
        w1.driver.flush()
        w1.driver.save_resume_delta(
            w1._ckpt_chain,
            delivery_delta=w1._delivery_records_locked(
                w1._delivery_epoch + 1, True
            ),
        )
    torn_epoch = w1._ckpt_chain.tail_epoch
    rt1.stop_timers()
    spool1.stop()  # crash: the ack never happened

    # hostile storage tears the just-renamed tail
    seg = os.path.join(chain_dir, f"delta-{torn_epoch:012d}.seg")
    blob = open(seg, "rb").read()
    if mode == "truncate":
        open(seg, "wb").write(blob[: len(blob) // 2])
    elif mode == "garbage":
        mid = len(blob) // 2  # 0xA5: never a no-op over real segment bytes
        open(seg, "wb").write(blob[:mid] + b"\xa5" * 16 + blob[mid + 16 :])
    else:
        open(seg, "wb").write(blob[:13])

    w2, rt2, spool2 = _delta_worker(cdir, wdir)
    assert w2._ckpt_chain.tail_epoch == torn_epoch - 1  # fell back cleanly
    n = spool2.delivered_count("transactions")
    while n < len(lines):
        n += spool2.deliver(50)
    w2.save_state()
    assert spool2.acked_count("transactions") == len(lines)
    cres = _export_snapshot(w2, str(tmp_path / "chaos.npz"))
    rt2.stop_timers()
    spool2.stop()
    assert_snapshots_equal(gres, cres)


def test_enospc_degradation_pauses_intake_then_recovers(tmp_path):
    """Persistent write failure → bounded jittered retries → DEGRADED:
    flight bundle, operator alert, intake paused (healthz 503, counter up)
    — and a later successful write resumes intake and converges to golden.
    Never a crash loop."""
    lines = make_stream(n_labels=4, per_label=40)
    gres = _golden_full_snapshot(tmp_path, lines)

    cdir = str(tmp_path / "spool")
    wdir = str(tmp_path / "work")
    os.makedirs(wdir, exist_ok=True)
    _feed_spool(cdir, lines)
    w, rt, spool = _delta_worker(cdir, wdir, max_retries=2, flight=True)
    n = 0
    while n < 80:
        n += spool.deliver(20)
    try:
        install_fault_plan(StorageFaultPlan("enospc:after=0,count=99999"))
        w.save_state(force=True)  # failure 1
        assert w._ckpt_fail_streak == 1 and not w._ckpt_degraded
        w.save_state(force=True)  # failure 2 == checkpointWriteMaxRetries
        assert w._ckpt_degraded
        assert w._ckpt_failures_total == 2
        assert spool.acked_count("transactions") == 0  # nothing acked un-durably
        health = w._health()
        assert health["ok"] is False
        assert health["checkpoint"]["degraded"] is True
        # intake paused: the consumer is cancelled until a write lands
        assert not spool._consumers
        # the flight recorder captured the wreckage before the fallback
        bundles = [p for p, b in _bundles(w) if "checkpoint_write_failure" in p]
        assert bundles
        # ... and the retry loop keeps going instead of crash-looping
        w.save_state(force=True)
        assert w._ckpt_failures_total == 3 and w._ckpt_degraded
    finally:
        install_fault_plan(None)

    w.save_state(force=True)  # storage recovered: commit + un-degrade
    assert not w._ckpt_degraded and w._ckpt_fail_streak == 0
    assert w._health()["ok"] is True
    assert spool._consumers  # intake resumed
    assert spool.acked_count("transactions") > 0
    n = spool.delivered_count("transactions")
    while n < len(lines):
        n += spool.deliver(50)
    w.save_state()
    assert spool.acked_count("transactions") == len(lines)
    cres = _export_snapshot(w, str(tmp_path / "chaos.npz"))
    rt.stop_timers()
    spool.stop()
    assert_snapshots_equal(gres, cres)


def _bundles(worker):
    from apmbackend_tpu.obs.flight import list_bundles

    return list_bundles(worker.runtime.flight.directory)


def test_degraded_worker_counts_failures_in_metrics(tmp_path):
    """apm_checkpoint_* series reflect the failure/degradation state."""
    lines = make_stream(n_labels=2, per_label=20)
    cdir = str(tmp_path / "spool")
    wdir = str(tmp_path / "work")
    os.makedirs(wdir, exist_ok=True)
    _feed_spool(cdir, lines)
    w, rt, spool = _delta_worker(cdir, wdir, max_retries=1)
    spool.deliver()
    try:
        install_fault_plan(StorageFaultPlan("enospc:after=0,count=99999"))
        w.save_state(force=True)
        samples = {s.name: s.value for s in w._collect_metrics()}
        assert samples["apm_checkpoint_write_failures_total"] == 1
        assert samples["apm_checkpoint_degraded"] == 1
    finally:
        install_fault_plan(None)
    w.save_state(force=True)
    samples = {s.name: s.value for s in w._collect_metrics()}
    assert samples["apm_checkpoint_degraded"] == 0
    assert samples["apm_checkpoint_chain_epoch"] == w._ckpt_chain.tail_epoch
    rt.stop_timers()
    spool.stop()


# -- slow tier: real subprocesses --------------------------------------------


@pytest.mark.slow
def test_kill9_delta_vs_full_golden_subprocess(tmp_path):
    """THE delta acceptance scenario: SIGKILL a delta-mode worker twice
    mid-stream under duplicate injection (with live compaction every 4
    epochs), and the final state equals a crash-free FULL-mode golden run
    bit-identically — cross-representation equivalence."""
    lines = make_stream(n_labels=10, per_label=120)

    golden = ChaosWorkerHarness(str(tmp_path / "golden"), dup_p=0.0, seed=1)
    for line in lines:
        golden.send_line(line)
    golden.start()
    stats_g = golden.finish(timeout_s=240)
    golden.close()
    assert stats_g["acked"] == len(lines)

    chaos = ChaosWorkerHarness(
        str(tmp_path / "chaos"), dup_p=0.08, seed=7,
        checkpoint_mode="delta", compact_every=4,
    )
    for line in lines:
        chaos.send_line(line)
    chaos.start()
    chaos.wait_acked(len(lines) // 3)
    chaos.kill9()
    first_cursor = chaos.acked()
    chaos.start()
    chaos.wait_acked(min(len(lines), first_cursor + len(lines) // 3))
    chaos.kill9()
    assert chaos.acked() >= first_cursor  # the cursor never regresses
    chaos.start()
    stats_c = chaos.finish(timeout_s=240)
    chaos.close()

    assert stats_c["acked"] == len(lines)  # zero message loss
    assert stats_c["chain_epoch"] >= stats_c["epoch"]
    assert stats_c["latest_label"] == stats_g["latest_label"]
    assert_snapshots_equal(golden.resume_path, chaos.resume_path)


@pytest.mark.slow
@pytest.mark.parametrize("point", ["pre_base", "pre_manifest"])
def test_crash_during_compaction_subprocess(tmp_path, point):
    """Deterministic SIGKILL inside the compaction window (before the new
    base lands / after it lands but before the MANIFEST swap): the restart
    recovers through the surviving generation and converges bit-identically
    to the FULL-mode golden run.

    The chaos child runs a FAST epoch cadence so the chain crosses
    compact_every while the stream is still feeding: since the idle-skip
    (PR 9) an untouched engine commits no empty delta segments, so a
    drained stream no longer walks the chain epoch toward the compaction
    boundary by itself."""
    lines = make_stream(n_labels=8, per_label=100)

    golden = ChaosWorkerHarness(str(tmp_path / "golden"), dup_p=0.0, seed=2)
    for line in lines:
        golden.send_line(line)
    golden.start()
    stats_g = golden.finish(timeout_s=240)
    golden.close()

    chaos = ChaosWorkerHarness(
        str(tmp_path / "chaos"), dup_p=0.0, seed=3,
        checkpoint_mode="delta", compact_every=3, save_every_s=0.05,
        fault_env={1: f"kill:compact={point}"},
    )
    chaos.start()
    # PACED feed: each chunk waits for acks, so every chunk spans at least
    # one epoch commit and the chain crosses compact_every under live load
    # — the point where the fault plan kills gen 1 (a pre-fed spool would
    # drain inside the post-compile first commits and never compact)
    for lo in range(0, len(lines), 40):
        for line in lines[lo:lo + 40]:
            chaos.send_line(line)
        deadline = time.monotonic() + 120
        while (chaos.proc.poll() is None
               and chaos.acked() < chaos.sent - 80):
            assert time.monotonic() < deadline, "chaos child stalled"
            time.sleep(0.01)
        if chaos.proc.poll() is not None:
            break
    rc = chaos.wait_child_death(timeout_s=120)  # the fault plan kills gen 1
    assert rc != 0
    for line in lines[chaos.sent:]:  # the rest of the stream post-crash
        chaos.send_line(line)
    chaos.start()  # gen 2: no faults, finishes the stream (and compacts)
    stats_c = chaos.finish(timeout_s=240)
    chaos.close()
    assert stats_c["acked"] == len(lines)
    assert stats_c["latest_label"] == stats_g["latest_label"]
    assert_snapshots_equal(golden.resume_path, chaos.resume_path)


@pytest.mark.slow
def test_enospc_under_epoch_timer_subprocess(tmp_path):
    """ENOSPC injected under the REAL epoch timer: the child retries with
    jittered backoff, commits once the 'disk' clears, and the run converges
    with the failure counted — no kill, no crash loop, no loss."""
    lines = make_stream(n_labels=6, per_label=80)

    golden = ChaosWorkerHarness(str(tmp_path / "golden"), dup_p=0.0, seed=4)
    for line in lines:
        golden.send_line(line)
    golden.start()
    golden.finish(timeout_s=240)
    golden.close()

    chaos = ChaosWorkerHarness(
        str(tmp_path / "chaos"), dup_p=0.0, seed=5,
        checkpoint_mode="delta",
        fault_env="enospc:after=2,count=3",
    )
    for line in lines:
        chaos.send_line(line)
    chaos.start()
    stats_c = chaos.finish(timeout_s=240)
    chaos.close()
    assert stats_c["acked"] == len(lines)
    assert stats_c["checkpoint_write_failures"] >= 1
    assert_snapshots_equal(golden.resume_path, chaos.resume_path)


@pytest.mark.slow
def test_stale_dup_tail_subprocess(tmp_path):
    """Duplicate chain tail after kill−9: a leftover future-named segment
    from the dead generation must be ignored by the restarted child, which
    then overwrites it with its own commits and converges."""
    lines = make_stream(n_labels=6, per_label=80)
    golden = ChaosWorkerHarness(str(tmp_path / "golden"), dup_p=0.0, seed=6)
    for line in lines:
        golden.send_line(line)
    golden.start()
    golden.finish(timeout_s=240)
    golden.close()

    chaos = ChaosWorkerHarness(
        str(tmp_path / "chaos"), dup_p=0.05, seed=8, checkpoint_mode="delta",
    )
    for line in lines:
        chaos.send_line(line)
    chaos.start()
    chaos.wait_acked(len(lines) // 3)
    chaos.kill9()
    chaos.corrupt_chain_tail("stale-dup")  # dead incarnation's leftover
    chaos.start()
    stats_c = chaos.finish(timeout_s=240)
    chaos.close()
    assert stats_c["acked"] == len(lines)
    assert_snapshots_equal(golden.resume_path, chaos.resume_path)
