"""Redis Streams transport: Channel-contract conformance over the in-process
fake (tests/fake_redis.py), plus the broker-loss behaviors the backpressure
spine depends on — send-side refusal instead of MAXLEN loss, XAUTOCLAIM
redelivery with the ``redelivered`` flag, parked-ack retry after reconnect,
and loud accounting of PEL entries trimmed out from under a consumer.

Real-server tests live at the bottom: ``@pytest.mark.slow`` and skipped
unless something answers on ``APM_TEST_REDIS_URL`` (default
``redis://localhost:6379/0``).
"""

import threading
import time

import pytest

from fake_redis import FakeRedisServer, make_fake_redis

from apmbackend_tpu.transport import make_queue_manager
from apmbackend_tpu.transport.redis_streams import HAVE_REDIS, RedisStreamsChannel


def make_channel(server, **kw):
    kw.setdefault("redis_module", make_fake_redis(server))
    return RedisStreamsChannel("redis://fake", **kw)


def make_qm(server, *, maxlen=100000, transport=None, start_pumps=False):
    cfg = {
        "brokerBackend": "redis",
        "statLogIntervalInSeconds": 3600,
        "redis": {"streamMaxlen": maxlen, "claimIdleMs": 5000},
    }
    if transport is not None:
        cfg["transport"] = transport
    # start_pumps=False: these tests drive pump_once() deterministically
    return make_queue_manager(cfg, redis_module=make_fake_redis(server),
                              start_pumps=start_pumps)


# -- channel contract ----------------------------------------------------------


def test_requires_redis_module_or_library():
    if not HAVE_REDIS:
        with pytest.raises(RuntimeError):
            RedisStreamsChannel("redis://nowhere")


def test_basic_send_consume_roundtrip():
    server = FakeRedisServer()
    ch = make_channel(server)
    got = []
    ch.assert_queue("q")
    ch.consume("q", lambda payload, headers: got.append((payload, headers)), "t1")
    assert ch.send("q", b"hello", {"msg_id": "m1", "ingest_ts": 1.5})
    assert ch.deliver() == 1
    assert got == [(b"hello", {"msg_id": "m1", "ingest_ts": 1.5})]
    # auto-ack mode commits on delivery: nothing left pending
    assert server.pending_count("q") == 0


def test_first_send_on_fresh_stream_succeeds():
    # XINFO GROUPS on a stream no XADD has created raises "ERR no such key"
    # on a real server (and now on the fake): the very first send — before
    # any consumer exists anywhere — must treat that as zero backlog, not
    # die in the producer's write path
    server = FakeRedisServer()
    ch = make_channel(server)
    assert ch.send("fresh", b"first", {"msg_id": "m1"})
    assert server.stream_len("fresh") == 1
    assert ch.queue_lag("never-written") == 0  # same path from the lag gauge


def test_fresh_stream_after_wiping_restart():
    # a non-persistent broker restart loses the stream entirely; the first
    # send after reconnect recreates it instead of erroring out
    server = FakeRedisServer()
    ch = make_channel(server, reconnect_base_backoff_s=0.0,
                      reconnect_max_backoff_s=0.0)
    assert ch.send("q", b"before", {})
    server.kill()
    with server.lock:
        server.streams.clear()
        server.groups.clear()
        server._seq.clear()
    server.restart()
    deadline = time.time() + 2.0
    while not ch.send("q", b"after", {}) and time.time() < deadline:
        time.sleep(0.005)
    assert server.stream_len("q") == 1


def test_one_arg_callback_wrapped_like_spool():
    server = FakeRedisServer()
    ch = make_channel(server)
    got = []
    ch.consume("q", got.append, "t1")
    ch.send("q", b"payload", {})
    ch.deliver()
    assert got == [b"payload"]


def test_group_created_at_zero_sees_producer_backlog():
    # a consumer that attaches AFTER the producer streamed entries must
    # still see them — the group is created at id="0", not "$"
    server = FakeRedisServer()
    prod = make_channel(server)
    for i in range(3):
        assert prod.send("q", f"m{i}".encode(), {})
    cons = make_channel(server)
    got = []
    cons.consume("q", lambda p, h: got.append(p), "t1")
    assert cons.deliver() == 3
    assert got == [b"m0", b"m1", b"m2"]


def test_manual_ack_and_idempotent_reack():
    server = FakeRedisServer()
    ch = make_channel(server)
    got = []
    ch.consume("q", lambda p, h, token: got.append(token), "t1", manual_ack=True)
    ch.send("q", b"one", {})
    ch.deliver()
    assert len(got) == 1 and server.pending_count("q") == 1
    ch.ack(got)
    assert server.pending_count("q") == 0
    ch.ack(got)  # stale re-ack: ignored, never raises
    assert server.ack_count == 1


def test_prefetch_gates_unacked_deliveries():
    server = FakeRedisServer()
    ch = make_channel(server, prefetch=2)
    tokens = []
    ch.consume("q", lambda p, h, t: tokens.append(t), "t1", manual_ack=True)
    for i in range(5):
        ch.send("q", f"m{i}".encode(), {})
    assert ch.deliver() == 2  # prefetch window full
    assert ch.deliver() == 0
    ch.ack(tokens[:2])
    assert ch.deliver() == 2
    ch.ack(tokens[2:])
    assert ch.deliver() == 1


def test_cancel_stops_delivery():
    server = FakeRedisServer()
    ch = make_channel(server)
    got = []
    ch.consume("q", lambda p, h: got.append(p), "tag-a")
    ch.cancel("tag-a")
    ch.send("q", b"m", {})
    assert ch.deliver() == 0
    assert got == []


def test_autoclaim_redelivers_idle_pending_with_flag():
    server = FakeRedisServer()
    ch = make_channel(server, claim_idle_ms=5000)
    got = []
    ch.consume("q", lambda p, h, t: got.append((p, h, t)), "t1", manual_ack=True)
    ch.send("q", b"m", {"msg_id": "orig-1"})
    ch.deliver()
    assert len(got) == 1 and not got[0][1].get("redelivered")
    # not yet idle: nothing to claim
    assert ch.deliver() == 0
    server.advance_ms(6000)
    assert ch.deliver() == 1
    payload, headers, token = got[1]
    assert payload == b"m"
    assert headers["redelivered"] is True
    assert headers["msg_id"] == "orig-1"  # original identity survives the hop
    ch.ack([token])
    server.advance_ms(6000)
    assert ch.deliver() == 0  # acked: gone from the PEL for good


def test_redis62_two_element_xautoclaim_still_redelivers():
    # pre-7.0 XAUTOCLAIM replies (next, claimed) with no deleted list —
    # delivery must tolerate it rather than ValueError on every pump pass
    server = FakeRedisServer()
    server.redis62 = True
    ch = make_channel(server, claim_idle_ms=5000)
    got = []
    ch.consume("q", lambda p, h, t: got.append((p, h, t)), "t1", manual_ack=True)
    ch.send("q", b"m", {"msg_id": "m1"})
    assert ch.deliver() == 1
    server.advance_ms(6000)
    assert ch.deliver() == 1  # redelivery via the 2-element reply
    assert got[1][1]["redelivered"] is True
    ch.ack([got[1][2]])
    assert server.pending_count("q") == 0


def test_backlog_check_amortized_far_from_cap():
    # well below stream_maxlen the XINFO round trip is paid once per
    # backlog_check_every sends, not per send — the hot producer path is
    # one XADD, not XINFO (+XLEN) then XADD
    server = FakeRedisServer()
    ch = make_channel(server, stream_maxlen=100000)
    for i in range(200):
        assert ch.send("q", f"m{i}".encode(), {})
    checks_per_send = server.xinfo_count / 200
    assert checks_per_send <= 1 / ch.backlog_check_every + 0.01
    # ...while refusal at the cap stays exact: near the cap every send
    # re-measures (test_send_refuses_at_stream_maxlen covers exactness)


def test_send_refuses_at_stream_maxlen_and_drains_at_half():
    server = FakeRedisServer()
    ch = make_channel(server, stream_maxlen=4)
    drains = []
    ch.on_drain(lambda: drains.append(1))
    for i in range(4):
        assert ch.send("q", f"m{i}".encode(), {})
    assert not ch.send("q", b"overflow", {})  # backlog at cap: refused
    assert server.stream_len("q") == 4  # ...and NOT trimmed-in silently
    got = []
    cons = make_channel(server, stream_maxlen=4)
    cons.consume("q", lambda p, h, t: got.append(t), "t1", manual_ack=True)
    cons.deliver()
    assert ch.pump_once() == 0 and not drains  # delivered-but-unacked still owed
    cons.ack(got)
    ch.pump_once()  # producer pump polls the backlog: 0 <= cap//2 -> drain
    assert drains == [1]
    assert ch.send("q", b"next", {})


def test_trim_only_eats_acked_prefix():
    # retention rides at 2x the refusal cap, so with sends refused at
    # stream_maxlen the trim can only remove already-acked entries
    server = FakeRedisServer()
    ch = make_channel(server, stream_maxlen=3)
    got = []
    ch.consume("q", lambda p, h, t: got.append(t), "t1", manual_ack=True)
    for round_no in range(4):
        for i in range(3):
            assert ch.send("q", f"r{round_no}m{i}".encode(), {})
        ch.deliver()
        ch.ack(got)
        got.clear()
    assert server.trimmed_count > 0
    assert ch.deleted_count == 0  # nothing unacked was ever trimmed


def test_trimmed_pel_entries_counted_loudly():
    class Log:
        def __init__(self):
            self.errors = []

        def error(self, msg):
            self.errors.append(msg)

        def info(self, msg):
            pass

    server = FakeRedisServer()
    log = Log()
    ch = make_channel(server, stream_maxlen=100, logger=log)
    got = []
    ch.consume("q", lambda p, h, t: got.append(t), "t1", manual_ack=True)
    for i in range(3):
        ch.send("q", f"m{i}".encode(), {})
    ch.deliver()
    assert server.pending_count("q") == 3
    # a second producer with a much smaller retention trims the unacked
    # entries out from under the PEL (the misconfiguration the deleted-list
    # accounting exists to surface)
    rogue = make_channel(server, stream_maxlen=1)
    for i in range(4):
        rogue.send("q2", b"x", {})  # separate stream keeps rogue sends flowing
    with server.lock:
        server.streams["q"] = server.streams["q"][3:]  # trim below the PEL
    server.advance_ms(6000)
    ch.deliver()
    assert ch.deleted_count == 3
    assert any("trimmed 3 unacked" in e for e in log.errors)


def test_queue_lag_counts_pending_plus_undelivered():
    server = FakeRedisServer()
    ch = make_channel(server)
    tokens = []
    ch.consume("q", lambda p, h, t: tokens.append(t), "t1", manual_ack=True)
    for i in range(4):
        ch.send("q", f"m{i}".encode(), {})
    assert ch.queue_lag("q") == 4  # all undelivered (stream backlog pre-group counts)
    ch.deliver(max_messages=2)
    assert ch.queue_lag("q") == 4  # 2 pending + 2 undelivered
    ch.ack(tokens)
    assert ch.queue_lag("q") == 2
    server.kill()
    assert ch.queue_lag("q") == 0  # unknowable while down: never raises


# -- broker loss ---------------------------------------------------------------


def test_send_fails_soft_while_down_and_recovers():
    server = FakeRedisServer()
    ch = make_channel(server, reconnect_base_backoff_s=0.0,
                      reconnect_max_backoff_s=0.0)
    assert ch.send("q", b"before", {})
    server.kill()
    assert not ch.send("q", b"during", {})  # refusal, not an exception
    server.restart()
    deadline = time.time() + 2.0
    while not ch.send("q", b"after", {}) and time.time() < deadline:
        time.sleep(0.005)
    assert server.stream_len("q") == 2  # "before" + "after"; "during" was refused


def test_stale_client_is_severed_until_reconnect():
    server = FakeRedisServer()
    ch = make_channel(server, reconnect_base_backoff_s=0.0,
                      reconnect_max_backoff_s=0.0)
    ch.send("q", b"m", {})
    server.kill()
    server.restart()
    # the pre-kill client is dead even though the server is back: the first
    # op drops it and the next reconnect builds a fresh client
    assert not ch.send("q", b"x", {})
    assert ch.send("q", b"y", {})


def test_acks_park_during_outage_and_retry_after_reconnect():
    server = FakeRedisServer()
    ch = make_channel(server, reconnect_base_backoff_s=0.0,
                      reconnect_max_backoff_s=0.0)
    tokens = []
    ch.consume("q", lambda p, h, t: tokens.append(t), "t1", manual_ack=True)
    ch.send("q", b"m", {})
    ch.deliver()
    assert server.pending_count("q") == 1
    server.kill()
    ch.ack(tokens)  # parks: connection is gone
    assert server.pending_count("q") == 1
    server.restart()
    deadline = time.time() + 2.0
    while server.pending_count("q") and time.time() < deadline:
        ch.pump_once()
        time.sleep(0.005)
    assert server.pending_count("q") == 0  # parked ack landed after reconnect
    server.advance_ms(60000)
    assert ch.deliver() == 0  # ...so nothing is redelivered


def test_state_survives_restart_and_pel_redelivers():
    server = FakeRedisServer()
    ch = make_channel(server, reconnect_base_backoff_s=0.0,
                      reconnect_max_backoff_s=0.0)
    got = []
    ch.consume("q", lambda p, h, t: got.append((p, h)), "t1", manual_ack=True)
    ch.send("q", b"m", {"msg_id": "k1"})
    ch.deliver()
    server.kill()
    server.restart()
    server.advance_ms(6000)
    deadline = time.time() + 2.0
    while len(got) < 2 and time.time() < deadline:
        ch.pump_once()
        time.sleep(0.005)
    assert got[1][0] == b"m"
    assert got[1][1]["redelivered"] is True
    assert got[1][1]["msg_id"] == "k1"


def test_reconnect_backoff_gates_connection_attempts():
    server = FakeRedisServer()
    calls = []
    mod = make_fake_redis(server)
    real_from_url = mod.Redis.from_url

    def counting_from_url(url, **kw):
        calls.append(url)
        return real_from_url(url, **kw)

    mod.Redis.from_url = counting_from_url
    ch = make_channel(server, redis_module=mod,
                      reconnect_base_backoff_s=30.0,
                      reconnect_max_backoff_s=60.0)
    server.kill()
    for _ in range(20):
        ch.send("q", b"m", {})
    # one real attempt; the rest were swallowed by the backoff window
    assert len(calls) == 1


# -- QueueManager integration --------------------------------------------------


def test_queue_manager_pause_buffer_drain_resume():
    server = FakeRedisServer()
    qm_p = make_qm(server, maxlen=3)
    qm_c = make_qm(server, maxlen=3)
    events = []
    qm_p.on("pause", lambda: events.append("pause"))
    qm_p.on("resume", lambda: events.append("resume"))
    prod = qm_p.get_queue("q", "p")
    for i in range(5):
        prod.write_line(f"line{i}")
    assert events == ["pause"]
    assert prod.buffer_count() == 2
    got = []
    cons = qm_c.get_queue("q", "c",
                          lambda line, headers=None, token=None: got.append((line, token)),
                          manual_ack=True)
    cons.start_consume()
    qm_c.consumer_channel.pump_once()
    cons.ack([t for _l, t in got])
    qm_p.producer_channel.pump_once()  # drain poll -> retry buffers -> resume
    assert "resume" in events
    assert prod.buffer_count() == 0
    qm_c.consumer_channel.pump_once()
    cons.ack([t for _l, t in got[3:]])
    assert [l for l, _t in got] == [f"line{i}" for i in range(5)]  # FIFO through the buffer


def test_transport_broker_key_selects_redis():
    server = FakeRedisServer()
    qm = make_queue_manager(
        {"brokerBackend": "memory", "transport": {"broker": "redis"},
         "redis": {"streamMaxlen": 10}},
        redis_module=make_fake_redis(server), start_pumps=False)
    qm.get_queue("q", "p").write_line("via-redis")
    assert server.stream_len("q") == 1


def test_headers_roundtrip_msg_id_ingest_ts():
    server = FakeRedisServer()
    qm_p = make_qm(server)
    qm_c = make_qm(server)
    got = []
    qm_p.get_queue("q", "p").write_line("payload")
    qm_c.get_queue("q", "c",
                   lambda line, headers=None: got.append(headers)).start_consume()
    qm_c.consumer_channel.pump_once()
    assert len(got) == 1
    assert "msg_id" in got[0] and "ingest_ts" in got[0]


def test_default_factory_pumps_itself_producer_resumes():
    # make_queue_manager's default starts the pump thread on every redis
    # channel — including the producer side, where drain is polled rather
    # than pushed — so a paused producer resumes with no manual pump_once()
    server = FakeRedisServer()
    qm_p = make_qm(server, maxlen=3, start_pumps=True)
    qm_c = make_qm(server, maxlen=3, start_pumps=True)
    resumed = threading.Event()
    qm_p.on("resume", resumed.set)
    prod = qm_p.get_queue("q", "p")
    try:
        for i in range(6):
            prod.write_line(f"line{i}")
        assert prod.buffer_count() > 0  # over the cap: paused, buffering
        got = []
        qm_c.get_queue(
            "q", "c", lambda line, headers=None: got.append(line)).start_consume()
        assert resumed.wait(5.0)
        deadline = time.time() + 5.0
        while (prod.buffer_count() or len(got) < 6) and time.time() < deadline:
            time.sleep(0.01)
        assert prod.buffer_count() == 0
        assert got == [f"line{i}" for i in range(6)]
    finally:
        qm_p.producer_channel.stop()
        qm_c.consumer_channel.stop()


def test_pump_thread_end_to_end():
    server = FakeRedisServer()
    ch = make_channel(server)
    got = []
    done = threading.Event()

    def cb(payload, headers):
        got.append(payload)
        if len(got) == 20:
            done.set()

    ch.consume("q", cb, "t1")
    ch.start_pump_thread(poll_s=0.001)
    try:
        for i in range(20):
            ch.send("q", f"m{i}".encode(), {})
        assert done.wait(2.0)
    finally:
        ch.stop()
    assert got == [f"m{i}".encode() for i in range(20)]


# -- real server (auto-skip) ---------------------------------------------------


def _real_redis_or_skip():
    import os

    if not HAVE_REDIS:
        pytest.skip("redis-py not installed")
    import redis

    url = os.environ.get("APM_TEST_REDIS_URL", "redis://localhost:6379/0")
    try:
        cli = redis.Redis.from_url(url)
        cli.ping()
    except Exception:
        pytest.skip(f"no redis server answering at {url}")
    return url, cli


@pytest.mark.slow
def test_real_redis_roundtrip_and_redelivery():
    url, cli = _real_redis_or_skip()
    stream = f"apm-test-{time.time_ns()}"
    ch = RedisStreamsChannel(url, claim_idle_ms=100)
    try:
        got = []
        ch.consume(stream, lambda p, h, t: got.append((p, h, t)), "t1",
                   manual_ack=True)
        assert ch.send(stream, b"real", {"msg_id": "r1"})
        deadline = time.time() + 5.0
        while not got and time.time() < deadline:
            ch.pump_once()
            time.sleep(0.01)
        assert got and got[0][0] == b"real" and got[0][1]["msg_id"] == "r1"
        time.sleep(0.15)  # exceed claim_idle_ms: unacked -> XAUTOCLAIM
        while len(got) < 2 and time.time() < deadline:
            ch.pump_once()
            time.sleep(0.01)
        assert len(got) >= 2 and got[1][1]["redelivered"] is True
        ch.ack([t for _p, _h, t in got])
    finally:
        ch.close()
        try:
            cli.delete(stream)
        except Exception:
            pass


@pytest.mark.slow
def test_real_redis_first_send_fresh_stream():
    # the first XADD ever, before any group or consumer exists: the
    # backlog probe's XINFO GROUPS raises "ERR no such key" on a real
    # server and send() must absorb it, not kill the writer
    url, cli = _real_redis_or_skip()
    stream = f"apm-test-{time.time_ns()}"
    ch = RedisStreamsChannel(url)
    try:
        assert ch.send(stream, b"first", {"msg_id": "f1"})
        assert ch.queue_lag(stream) == 1
    finally:
        ch.close()
        try:
            cli.delete(stream)
        except Exception:
            pass


@pytest.mark.slow
def test_real_redis_backlog_refusal():
    url, cli = _real_redis_or_skip()
    stream = f"apm-test-{time.time_ns()}"
    ch = RedisStreamsChannel(url, stream_maxlen=4)
    try:
        for i in range(4):
            assert ch.send(stream, f"m{i}".encode(), {})
        assert not ch.send(stream, b"overflow", {})
    finally:
        ch.close()
        try:
            cli.delete(stream)
        except Exception:
            pass
