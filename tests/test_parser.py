"""Parser correlation tests: SOAP join, entry/exit, TTL backfill, audit trail."""

import math
import os

from apmbackend_tpu.ingest.parser import TransactionParser, convert_log_date_to_ms
from apmbackend_tpu.ingest.replay import FixtureGenerator, ReplayDriver, write_fixture_logs
from apmbackend_tpu.ingest.ttlcache import TTLCache

SERVER = "jvmhost1"


def make_parser(records, clock=None):
    kw = {"server_from_path": lambda fp: SERVER}
    if clock is not None:
        kw["clock"] = clock
    return TransactionParser(lambda tx, db: records.append((tx, db)), **kw)


def feed(parser, pairs):
    for fname, line in pairs:
        parser.read_line(fname, line)


def test_ttl_cache_expiry_callback():
    now = [0.0]
    expired = []
    c = TTLCache(10, on_expired=lambda k, v: expired.append(k), clock=lambda: now[0])
    c.set("a", 1)
    assert c.get("a") == 1
    now[0] = 11
    assert c.get("a") is None
    assert expired == ["a"]
    c.set("b", 2)
    now[0] = 30
    assert c.sweep() == 1
    assert expired == ["a", "b"]


def test_soap_ejb_join_with_account():
    records = []
    parser = make_parser(records)
    gen = FixtureGenerator(server=SERVER)
    feed(parser, gen.soap_transaction("getAccountInfo", 500, acct=123456789))
    assert len(records) == 1
    tx, db = records[0]
    assert not db
    assert tx.service == "S:getAccountInfo"
    assert tx.acct_num == 123456789
    assert tx.elapsed == 500
    assert tx.top_level == "Y"
    assert tx.end_ts - tx.start_ts == 500


def test_riskid_two_line_account():
    records = []
    parser = make_parser(records)
    gen = FixtureGenerator(server=SERVER)
    feed(parser, gen.soap_transaction("getRisk", 200, acct=987654321, riskid=True))
    assert len(records) == 1
    assert records[0][0].acct_num == 987654321


def test_standard_ct_with_baf_salvage():
    """No SOAP account: the exit line's BAF metadata is the salvage source.

    Reference semantics: the record parks in the needNum cache with the
    salvaged altAcctNum and is emitted at TTL expiry (the salvage primes the
    acct cache for later exits of the same logId, not the current one —
    stream_parse_transactions.js:542-560, :226-239)."""
    now = [0.0]
    records = []
    parser = make_parser(records, clock=lambda: now[0])
    gen = FixtureGenerator(server=SERVER)
    feed(parser, gen.standard_ct_transaction("getOffers", 300, acct=555000111, baf_meta=True))
    assert records == []  # parked
    now[0] = 31
    parser.sweep()
    assert len(records) == 1
    tx, db = records[0]
    assert tx.acct_num == 555000111
    assert tx.service == "getOffers"
    assert tx.top_level == "N"


def test_baf_salvage_primes_acct_for_second_exit():
    """A second exit on the same logId finds the salvaged number immediately."""
    records = []
    parser = make_parser(records)
    log_id = "jbX"
    meta = "[ch:7:444555666]"
    parser.read_line(
        "app_x.log",
        f"[{log_id}] 2024-01-10 09:00:00,000 {meta} INFO CommonTiming::Start svcA begin",
    )
    parser.read_line(
        "app_x.log",
        f"[{log_id}] 2024-01-10 09:00:00,300 {meta} INFO CommonTiming::Stop svcA completed in time: 300 ms",
    )
    parser.read_line(
        "app_x.log",
        f"[{log_id}] 2024-01-10 09:00:00,400 {meta} INFO CommonTiming::Start svcB begin",
    )
    parser.read_line(
        "app_x.log",
        f"[{log_id}] 2024-01-10 09:00:00,900 {meta} INFO CommonTiming::Stop svcB completed in time: 500 ms",
    )
    # svcB exits after svcA's salvage primed the acct cache -> immediate emit;
    # svcA itself stays parked (the salvage's backfill check ran before svcA
    # was parked) and surfaces on expiry — reference ordering quirk
    assert len(records) == 1
    assert records[0][0].service == "svcB"
    assert records[0][0].acct_num == 444555666


def test_missing_account_parks_then_backfills():
    """Exit before SOAP account: record parks in needNum cache, then the SOAP
    account line releases it (saveAcctNum backfill path)."""
    records = []
    parser = make_parser(records)
    gen = FixtureGenerator(server=SERVER)
    pairs = gen.soap_transaction("getFoo", 400, acct=111222333)
    soap_lines = [p for p in pairs if p[0].startswith("soap")]
    server_lines = [p for p in pairs if p[0] == "server.log"]
    # deliver timing lines FIRST (account unknown), but keep the SOAP IO=I
    # header first so the context exists
    feed(parser, soap_lines[:1])
    feed(parser, server_lines)
    assert records == []  # parked, waiting for the number
    feed(parser, soap_lines[1:])
    assert len(records) == 1
    assert records[0][0].acct_num == 111222333


def test_missing_account_expires_and_emits_numberless():
    now = [0.0]
    records = []
    parser = make_parser(records, clock=lambda: now[0])
    gen = FixtureGenerator(server=SERVER)
    pairs = gen.soap_transaction("getBar", 250)  # no account anywhere
    feed(parser, pairs)
    assert records == []
    now[0] = 31  # past needNum TTL (30 s)
    parser.sweep()
    assert len(records) == 1
    tx, _ = records[0]
    assert math.isnan(tx.acct_num)
    assert tx.elapsed == 250


def test_partial_without_exit_discarded():
    now = [0.0]
    records = []
    parser = make_parser(records, clock=lambda: now[0])
    parser.read_line(
        "server.log",
        "[jb1] 2024-01-10 09:00:00,000 INFO [CommonTiming] The EJB timing entry has begun for method getLost",
    )
    now[0] = 121
    parser.sweep()
    assert records == []  # discarded, not emitted


def test_exit_without_entry_emits_incomplete():
    records = []
    parser = make_parser(records)
    parser.read_line(
        "server.log",
        "[jb9] 2024-01-10 09:00:01,000 INFO [CommonTiming] Total time for EJB getOrphan call: 123 ms",
    )
    assert len(records) == 1
    tx, _ = records[0]
    assert tx.service == "S:getOrphan"
    assert tx.log_id == ""
    assert tx.elapsed == 123
    assert tx.start_ts == tx.end_ts - 123  # start backfilled from elapsed


def test_audit_trail_multi_subservice():
    records = []
    parser = make_parser(records)
    gen = FixtureGenerator(server=SERVER)
    feed(parser, gen.audit_trail(
        [("Provider[credit-check]", 120), ("bcottag", 10), ("bcottag", 20)], acct=999888777
    ))
    assert len(records) == 3
    services = [r[0].service for r in records]
    assert services == ["Provider:credit-check", "bcottag", "bcottag"]
    # Provider goes to the stats pipeline; others straight to DB
    assert [r[1] for r in records] == [False, True, True]
    # repeated subservice consumed FIFO: elapsed 10 then 20
    assert records[1][0].elapsed == 10 and records[2][0].elapsed == 20
    assert all(r[0].acct_num == 999888777 for r in records)


def test_provider_normalization_case_insensitive():
    now = [0.0]
    records = []
    parser = make_parser(records, clock=lambda: now[0])
    gen = FixtureGenerator(server=SERVER)
    feed(parser, gen.standard_ct_transaction("provider[x-y]", 100, acct=1, baf_meta=True))
    now[0] = 31
    parser.sweep()
    assert records[0][0].service == "Provider:x-y"


def test_fixture_replay_end_to_end(tmp_path):
    paths = write_fixture_logs(str(tmp_path), n_transactions=100, seed=3)
    records = []
    parser = TransactionParser(
        lambda tx, db: records.append(tx), server_from_path=lambda fp: SERVER
    )
    drv = ReplayDriver(parser)
    drv.feed_dir(str(tmp_path))
    drv.finish()
    assert drv.lines_fed > 300
    # every generated transaction produced at least one record
    assert len(records) >= 100
    with_acct = [r for r in records if not math.isnan(r.acct_num)]
    assert len(with_acct) / len(records) > 0.9  # correlation succeeded broadly
    # timestamps sane: elapsed == end - start whenever both present
    for r in records:
        if not math.isnan(r.start_ts):
            assert r.end_ts - r.start_ts == r.elapsed


def test_log_date_conversion():
    assert convert_log_date_to_ms("") == ""
    iso = convert_log_date_to_ms("2020-01-07T10:00:01.959-06:00")
    assert iso == str(int(1578412801959))
    std = convert_log_date_to_ms("2020-01-07 10:00:02,669")
    assert std.isdigit() and len(std) == 13


def test_malformed_lines_never_fatal():
    """Truncated/binary/garbage lines are skipped, parser keeps working."""
    records = []
    parser = make_parser(records)
    for line in [
        "complete garbage %$#@!",
        "[jb1] 2024-01-10 09:00:00,000 INFO [CommonTiming] The EJB",  # truncated
        "\x00\x01\x02 binary junk",
        "[jb2] not-a-date INFO [CommonTiming] Total time for EJB x call: abc ms",
        "Audit Trail id :",  # empty autr id
    ]:
        parser.read_line("server.log", line)
        parser.read_line("app_x.log", line)
    parser.read_line(
        "server.log",
        "[jb9] 2024-01-10 09:00:01,000 INFO [CommonTiming] Total time for EJB alive call: 10 ms",
    )
    assert records and records[-1][0].service == "S:alive"


def test_consumer_error_distinguished(caplog):
    import logging

    def bad_consumer(tx, db):
        raise RuntimeError("sink exploded")

    parser = TransactionParser(bad_consumer, server_from_path=lambda fp: SERVER)
    parser.logger = logging.getLogger("t")
    with caplog.at_level(logging.ERROR):
        parser.read_line(
            "server.log",
            "[jb9] 2024-01-10 09:00:01,000 INFO [CommonTiming] Total time for EJB x call: 10 ms",
        )
    assert any("Record consumer failed" in r.message for r in caplog.records)
    assert not any("Unparseable" in r.message for r in caplog.records)


def test_marker_cooccurrence_keeps_ladder_priority():
    """A line where two timing markers CO-OCCUR must dispatch by the
    reference's sequential ladder priority (EJB entry > EJB exit > CT
    start > CT stop), not by leftmost occurrence — the alternation scan is
    only a pre-filter (parser.py _SERVER_DISPATCH_RE note).

    Construct a line whose LOWER-priority marker appears FIRST: leftmost
    dispatch would pick the exit handler; the ladder must pick entry."""
    records = []
    parser = make_parser(records)
    # 'Total time' (exit marker) textually precedes 'The EJB' (entry
    # marker); ladder priority says EJB ENTRY wins. Token layout satisfies
    # _parse_ejb_entry (service at arr[13]).
    line = ("[jbX] 2024-01-10 09:00:00,000 pre INFO [CommonTiming] Total time "
            "noise INFO [CommonTiming] The EJB svcY call")
    parser.read_line("server.log", line)
    # entry parks a partial (no emission); a ladder regression dispatching
    # the exit handler would emit an unmatched-exit record immediately
    assert records == []
    # a later exit for the same logId but a DIFFERENT service token: the
    # join deliberately misses (the parked 'S:svcY' partial stays cached)
    # and the unmatched-exit path emits — pinning that the co-occurrence
    # line produced no emission of its own
    parser.read_line(
        "server.log",
        "[jbX] 2024-01-10 09:00:02,000 INFO [CommonTiming] Total time for "
        "EJB INFO call: 17 ms",
    )
    assert len(records) == 1
    assert records[0][0].service == "S:INFO"  # the unmatched-exit record
    assert parser.record_cache.get("jbX") and "S:svcY" in parser.record_cache.get("jbX")


def test_app_log_ejb_marker_falls_through_to_app_state():
    """APP logs only dispatch CT handlers; a line carrying an EJB marker
    (leftmost) plus no CT marker must fall through to the audit-trail state
    machine, exactly like the reference's APP branch."""
    records = []
    parser = make_parser(records)
    # 14+ tokens so a wrongly-dispatched _parse_ejb_entry would SUCCEED and
    # park a partial (an 8-token line would just raise-and-swallow, which
    # records==[] cannot distinguish from correct fall-through)
    line = ("[jb1] 2024-01-10 09:00:00,000 a b c INFO [CommonTiming] "
            "The EJB is named svcZ here")
    parser.read_line("app_1.log", line)
    assert records == []
    # DISCRIMINATING check: correct fall-through parks nothing; the EJB
    # handler regression would have cached a partial under logId jb1
    assert parser.record_cache.get("jb1") is None
    assert parser.cache_stats()["record"]["keys"] == 0
