"""TWO-PROCESS jax.distributed smoke: the production init_distributed wiring
(parallel/multihost.py) exercised across real process boundaries.

Every other "multi-host" test runs as one process on the virtual mesh; this
one launches two OS processes that rendezvous through a coordinator, form a
4-device mesh (2 local devices each, Gloo collectives on the CPU backend),
and push distinct per-host batches through the all-to-all exchange — the
closest this environment can get to the reference's multi-process topology
(apm_manager.js:333-342 role) without pod hardware.
"""

import os
import socket
import subprocess
import sys

import pytest

_HERE = os.path.dirname(os.path.abspath(__file__))
_WORKER = os.path.join(_HERE, "mp_worker.py")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_distributed_exchange():
    port = _free_port()
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)  # the axon sitecustomize must not dial the TPU
    # share the suite's persistent compile cache (conftest sets it via
    # jax.config, which does not propagate into Popen'd workers)
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   os.environ.get("APM_TEST_JAX_CACHE", "/tmp/apm_jax_test_cache"))
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.4")
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(port), str(pid)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
            cwd=_HERE,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=180)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        # re-communicate after kill to retrieve the HUNG worker's buffered
        # output — it is the diagnostic that matters
        for p in procs:
            try:
                out, _ = p.communicate(timeout=10)
                outs.append(out)
            except Exception:
                pass
        pytest.fail("two-process smoke timed out:\n" + "\n".join(o[-3000:] for o in outs))
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} rc={p.returncode}\n{out[-3000:]}"
        assert f"MP_SMOKE_OK proc={pid}" in out, out[-3000:]
