"""The protocol model checker (ISSUE 8): exhaustive small-scope proofs.

Three gates, mirroring DESIGN.md §9.4:

- every protocol model verifies CLEAN at its small scope (the same check
  ``run_tests.sh --lint`` runs), inside the documented 10 s budget;
- every seeded mutant — including the replayed PR 3 dup-loss bug —
  yields a human-readable counterexample schedule (the checker can fail);
- the checker itself behaves: BFS finds shortest schedules, canonical
  hashing dedups states, the CLI emits machine-readable verdicts.

Deep scopes are the ``slow``-marked tier (``run_tests.sh --model``).
"""

from __future__ import annotations

import json
import subprocess
import sys
import time

import pytest

from apmbackend_tpu.analysis.protocol import (
    BOUNDARY_MUTANTS,
    MUTANTS,
    SCOPES,
    AloModel,
    DeltaChainModel,
    ShardedEpochModel,
    check,
    run_model_checks,
    verify_mutants,
)

REPO_ROOT = __file__.rsplit("/tests/", 1)[0]


# ------------------------------------------------------------- the checker

class _Counter:
    """Trivial model: count to 3, invariant forbids 3 — shortest schedule
    is exactly three increments."""

    name = "counter"
    scope = {"limit": 3}

    def initial(self):
        return 0

    def actions(self, s):
        out = [("inc", s + 1)] if s < 5 else []
        out.append(("noop", s))  # self-loop: canonical hashing must dedup
        return out

    def invariant(self, s):
        return "reached 3" if s == 3 else None

    def describe(self, s):
        return f"n={s}"


def test_checker_finds_shortest_counterexample():
    r = check(_Counter())
    assert not r.ok
    assert [lbl for lbl, _ in r.schedule] == ["", "inc", "inc", "inc"]
    text = r.format_schedule()
    assert "INVARIANT VIOLATED: reached 3" in text
    assert "counter" in text and "limit=3" in text


def test_checker_exhausts_clean_models():
    class Clean(_Counter):
        def invariant(self, s):
            return None

    r = check(Clean())
    assert r.ok and r.states == 6 and not r.truncated
    assert r.schedule == [] and r.format_schedule() == ""


def test_checker_max_states_truncates():
    class Clean(_Counter):
        def invariant(self, s):
            return None

    r = check(Clean(), max_states=3)
    assert r.ok and r.truncated and r.states == 3


# ---------------------------------------------- small scopes: the hard gate

def test_small_scopes_verify_clean_within_budget():
    """The --lint gate: every protocol model exhaustively clean at its
    small scope, in well under the documented 15 s (~2 s standalone;
    the budget absorbs full-suite contention)."""
    t0 = time.monotonic()
    results = run_model_checks("small")
    elapsed = time.monotonic() - t0
    assert len(results) == len(SCOPES["small"])
    for r in results:
        assert r.ok, f"{r.model_name} violated:\n{r.format_schedule()}"
        assert not r.truncated and r.states > 100
    assert elapsed < 15.0, f"small tier took {elapsed:.1f}s (budget 15s)"


@pytest.mark.parametrize("kind", ["memory", "amqp", "spool"])
def test_alo_small_scope_per_broker(kind):
    r = check(AloModel(kind=kind))
    assert r.ok, r.format_schedule()


def test_delta_chain_small_scope():
    r = check(DeltaChainModel())
    assert r.ok, r.format_schedule()


def test_sharded_small_scope():
    r = check(ShardedEpochModel())
    assert r.ok, r.format_schedule()


# ------------------------------------------------- mutants: teeth required

def test_mutation_catalogue_is_big_enough():
    assert len(MUTANTS) >= 10
    assert "alo-dup-ack-early" in MUTANTS  # the replayed PR 3 bug


@pytest.mark.parametrize("name", sorted(MUTANTS))
def test_every_mutant_yields_a_counterexample(name):
    desc, factory = MUTANTS[name]
    r = check(factory())
    assert not r.ok, (
        f"mutant {name} produced NO counterexample in {r.states} states — "
        f"the checker cannot detect this bug class: {desc}")
    # the counterexample is a readable schedule: numbered steps, an
    # invariant statement, and at least one protocol action label
    text = r.format_schedule()
    assert "INVARIANT VIOLATED" in text
    assert len(r.schedule) >= 2
    labels = [lbl for lbl, _ in r.schedule[1:]]
    assert all(labels), f"unlabeled steps in {name}: {labels}"


def test_pr3_dup_loss_mutant_counterexample_shape():
    """The historical bug, now a 3-step certainty instead of a lucky
    chaos catch: publish, deliver, duplicate — the dup's early ack settles
    the broker while the effect is volatile."""
    _desc, factory = MUTANTS["alo-dup-ack-early"]
    r = check(factory())
    assert not r.ok
    labels = [lbl for lbl, _ in r.schedule[1:]]
    assert any(lbl.startswith("dup(") for lbl in labels)
    assert "ack-implies-durable" in r.violation


def test_boundary_mutants_stay_indistinguishable():
    """The documented negative result: recovery-order variants of the
    delta chain are UNOBSERVABLE within the single-fault storage contract
    (DESIGN.md §9.4). If one of these starts producing a counterexample,
    the fault model widened — update the docs and the deltachain.py
    hardening rationale."""
    for name, (_desc, factory) in BOUNDARY_MUTANTS.items():
        r = check(factory())
        assert r.ok, f"{name} became observable:\n{r.format_schedule()}"


# -------------------------------------------------------------- CLI plane

def test_cli_json_includes_model_verdicts():
    out = subprocess.run(
        [sys.executable, "-m", "apmbackend_tpu.analysis", "--json",
         "--models", "small"],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert out.returncode == 0, out.stdout + out.stderr
    doc = json.loads(out.stdout)
    assert doc["findings"] == []
    names = {m["model"] for m in doc["model_checks"]}
    assert {"alo-memory", "alo-amqp", "alo-spool", "delta-chain",
            "sharded-epochs"} <= names
    for m in doc["model_checks"]:
        assert m["ok"] and m["states"] > 0 and "scope" in m


def test_cli_mutants_tier_reports_counterexamples():
    out = subprocess.run(
        [sys.executable, "-m", "apmbackend_tpu.analysis", "--json",
         "--models", "mutants"],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert out.returncode == 0, out.stdout + out.stderr
    doc = json.loads(out.stdout)
    assert len(doc["mutants"]) >= 10
    assert all(m["counterexample_found"] for m in doc["mutants"])


def test_cli_rules_subset_skips_models():
    out = subprocess.run(
        [sys.executable, "-m", "apmbackend_tpu.analysis", "--json",
         "--rules", "unused-import"],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert out.returncode == 0, out.stdout + out.stderr
    doc = json.loads(out.stdout)
    assert doc["model_checks"] == [] and doc["mutants"] == []


# ------------------------------------------------------- deep scopes (slow)

@pytest.mark.slow
@pytest.mark.parametrize("idx", range(len(SCOPES["deep"])))
def test_deep_scope_verifies_clean(idx):
    model = SCOPES["deep"][idx]()
    r = check(model)
    assert r.ok, f"{r.model_name} violated at deep scope:\n{r.format_schedule()}"
    assert not r.truncated
