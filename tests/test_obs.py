"""Telemetry plane tests: registry, exporter scrape from a live standalone
pipeline, tick-span histograms, end-to-end latency series, /profile capture,
QueueStats/DBStats registry views, qstat --metrics-url, fleet aggregation,
and the handler-stream colorization fix."""

import io
import json
import logging
import os
import urllib.error
import urllib.request

import pytest

from apmbackend_tpu.config import default_config
from apmbackend_tpu.obs import (
    MetricsRegistry,
    Sample,
    TelemetryServer,
    parse_prom_text,
    relabel_metrics,
    set_registry,
)
from apmbackend_tpu.utils.counters import DBStats, QueueStats


@pytest.fixture(autouse=True)
def fresh_registry():
    """Isolate the process-global registry per test: collectors registered
    by pipelines in OTHER tests must not leak into scrape assertions."""
    old = set_registry(MetricsRegistry())
    yield
    set_registry(old)


def fetch(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read().decode("utf-8")


def samples_by_name(text):
    out = {}
    for name, labels, value in parse_prom_text(text):
        out.setdefault(name, []).append((labels, value))
    return out


# -- registry ----------------------------------------------------------------

def test_registry_instruments_render_and_parse():
    reg = MetricsRegistry()
    c = reg.counter("apm_test_total", "help text")
    c.inc()
    c.inc(2)
    g = reg.gauge("apm_test_gauge", labels={"kind": "x"})
    g.set(4.5)
    h = reg.histogram("apm_test_seconds", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = reg.render()
    s = samples_by_name(text)
    assert s["apm_test_total"] == [({}, 3.0)]
    assert s["apm_test_gauge"] == [({"kind": "x"}, 4.5)]
    # cumulative buckets + sum/count
    buckets = {lb["le"]: v for lb, v in s["apm_test_seconds_bucket"]}
    assert buckets["0.1"] == 1 and buckets["1"] == 2 and buckets["+Inf"] == 3
    assert s["apm_test_seconds_count"] == [({}, 3.0)]
    assert abs(s["apm_test_seconds_sum"][0][1] - 5.55) < 1e-9
    assert "# TYPE apm_test_total counter" in text
    # get-or-create: same (name, labels) returns the same instrument
    assert reg.counter("apm_test_total") is c


def test_registry_collector_views_and_gauge_fn():
    reg = MetricsRegistry()
    state = {"v": 7}
    reg.gauge("apm_live").set_fn(lambda: state["v"])
    reg.add_collector(lambda: [Sample("apm_coll_total", {"q": "a"}, 11, "counter", "h")])
    reg.add_collector(lambda: (_ for _ in ()).throw(RuntimeError("broken view")))
    s = samples_by_name(reg.render())  # the broken collector must not 500
    assert s["apm_live"] == [({}, 7.0)]
    assert s["apm_coll_total"] == [({"q": "a"}, 11.0)]
    state["v"] = 9
    assert samples_by_name(reg.render())["apm_live"] == [({}, 9.0)]


def test_relabel_metrics_injects_module_label():
    text = (
        "# TYPE apm_x counter\n"
        "apm_x 3\n"
        'apm_y{queue="tx"} 4\n'
    )
    out = relabel_metrics(text, {"module": "worker"})
    s = samples_by_name(out)
    assert s["apm_x"] == [({"module": "worker"}, 3.0)]
    assert s["apm_y"] == [({"queue": "tx", "module": "worker"}, 4.0)]


def test_queue_stats_and_db_stats_views_survive_reset():
    from apmbackend_tpu.obs.views import register_db_stats, register_queue_stats

    reg = MetricsRegistry()
    qs = QueueStats(interval_seconds=3600)
    qs.add_counter("transactions", "c")
    qs.add_counter("db_insert", "p")
    qs.incr("transactions", 5)
    qs.incr("db_insert", 2)
    register_queue_stats(qs, "worker", reg)
    register_queue_stats(qs, "worker", reg)  # idempotent per object
    qs.snapshot_and_reset()  # the legacy log line resets interval counts...
    qs.incr("transactions", 1)
    s = samples_by_name(reg.render())
    vals = {
        (lb["queue"], lb["direction"]): v for lb, v in s["apm_queue_messages_total"]
    }
    # ...but the registry view stays cumulative/monotonic
    assert vals[("transactions", "in")] == 6.0
    assert vals[("db_insert", "out")] == 2.0
    qs.stop()

    db = DBStats()
    db.add_inserted(10)
    db.add_elapsed_ms(500.0)
    register_db_stats(db, "sink", reg)
    db.snapshot_and_reset()
    db.add_inserted(1)
    s = samples_by_name(reg.render())
    assert s["apm_db_rows_inserted_total"][0][1] == 11.0
    assert abs(s["apm_db_insert_seconds_total"][0][1] - 0.5) < 1e-9


# -- live standalone pipeline scrape -----------------------------------------

@pytest.fixture
def obs_pipeline(tmp_path):
    from apmbackend_tpu.ingest.replay import write_fixture_logs
    from apmbackend_tpu.standalone import StandalonePipeline
    from tests.test_standalone import small_config

    logs = tmp_path / "fixture_logs"
    write_fixture_logs(str(logs), n_transactions=150, seed=11)
    cfg = small_config(tmp_path, metricsPort=0)  # ephemeral exporter port
    pipe = StandalonePipeline(config=cfg, tail=False, install_signals=False)
    try:
        yield pipe, str(logs)
    finally:
        pipe.shutdown()


def test_standalone_metrics_scrape_and_healthz(obs_pipeline):
    pipe, logs = obs_pipeline
    server = pipe.lead.telemetry
    assert server is not None and server.port

    pipe.replay(logs)
    status, text = fetch(f"{server.url}/metrics")
    assert status == 200
    s = samples_by_name(text)

    # per-stage tick histograms populated for every stage
    stage_counts = {
        lb["stage"]: v for lb, v in s["apm_tick_stage_seconds_count"]
    }
    assert stage_counts["dispatch"] > 0
    assert set(stage_counts) >= {"dispatch", "rebuild", "tx_drain", "emit"}
    ticks1 = s["apm_ticks_total"][0][1]
    assert ticks1 > 0

    # queue depth/throughput series (broker + QueueStats views)
    assert "apm_queue_depth" in s
    qtot = {
        (lb["queue"], lb["direction"]): v
        for lb, v in s["apm_queue_messages_total"]
    }
    assert qtot[("transactions", "out")] > 0  # parser produced
    assert qtot[("transactions", "in")] > 0  # worker consumed

    # end-to-end latency: transport ingest stamp -> emission readback, and
    # the transport queue-wait series the stamp also feeds
    assert s["apm_e2e_ingest_to_emit_seconds_count"][0][1] > 0
    assert s["apm_queue_wait_seconds_count"][0][1] > 0

    # engine gauges + intake counters (worker collector)
    assert s["apm_engine_services"][0][1] > 0
    assert s["apm_engine_tx_ingested_total"][0][1] > 0
    assert "apm_intake_pushed_total" in s

    # monotonicity across scrapes: replay more, counts must not decrease
    pipe.replay(logs)
    _, text2 = fetch(f"{server.url}/metrics")
    s2 = samples_by_name(text2)
    assert s2["apm_ticks_total"][0][1] >= ticks1
    stage_counts2 = {
        lb["stage"]: v for lb, v in s2["apm_tick_stage_seconds_count"]
    }
    for stage, count in stage_counts.items():
        assert stage_counts2[stage] >= count

    # healthz: engine section present and healthy
    status, body = fetch(f"{server.url}/healthz")
    assert status == 200
    health = json.loads(body)
    assert health["status"] == "ok"
    assert health["engine"]["ticks_total"] >= 1
    assert health["engine"]["executor"] in ("fused", "fused-native", "staged")
    assert health["engine"]["device_loop_alive"] is True
    assert "stage_mean_ms" in health["engine"]
    assert health["process"]["ok"] is True

    # parser stage counters rode along (registered via telemetry_active)
    assert s2["apm_parser_lines_total"][0][1] > 0
    assert s2["apm_parser_tx_total"][0][1] > 0
    assert "apm_parser_cache_hits_total" in s2


def test_profile_endpoint_captures(obs_pipeline, tmp_path):
    pipe, logs = obs_pipeline
    server = pipe.lead.telemetry
    pipe.replay(logs)
    status, body = fetch(f"{server.url}/profile?ms=20", timeout=60)
    assert status == 200
    result = json.loads(body)
    # heap snapshot always lands; the jax trace lands when the profiler is
    # available on this backend (CPU included) — accept either but require
    # at least one artifact, written under the module's log dir
    paths = [p for p in (result.get("trace_dir"), result.get("heap_snapshot")) if p]
    assert paths
    assert any(os.path.exists(p) for p in paths)

    status, _ = fetch(f"{server.url}/metrics")
    assert status == 200  # exporter still alive after the capture


def test_qstat_metrics_url_mode(obs_pipeline, capsys):
    from apmbackend_tpu.tools import qstat

    pipe, logs = obs_pipeline
    pipe.replay(logs)
    rc = qstat.main(["--metrics-url", pipe.lead.telemetry.url])
    assert rc == 0
    out = capsys.readouterr().out
    assert "transactions" in out and "db_insert" in out
    # depth + in/out totals rendered
    assert "in total" in out and "out total" in out


def test_qstat_metrics_url_unreachable(capsys):
    from apmbackend_tpu.tools import qstat

    rc = qstat.main(["--metrics-url", "http://127.0.0.1:9/metrics"])
    assert rc == 1


# -- qstat --lag: the transport-generic lag view ------------------------------


def _lag_table(out):
    rows = {}
    for line in out.strip().splitlines()[1:]:
        name, lag = line.split()
        rows[name] = int(lag)
    return rows


def test_qstat_lag_spool_backend(tmp_path, capsys, monkeypatch):
    from apmbackend_tpu.tools import qstat
    from apmbackend_tpu.transport.spool import SpoolChannel

    spool_dir = str(tmp_path / "spool")
    prod = SpoolChannel(spool_dir)
    for i in range(5):
        assert prod.send("transactions", f"l{i}".encode())
    prod.close()
    cfg = default_config()
    cfg["brokerBackend"] = "spool"
    cfg["transport"] = {"spoolDirectory": spool_dir}
    monkeypatch.setattr("apmbackend_tpu.config.default_config", lambda: cfg)
    rc = qstat.main(["--lag"])
    assert rc == 0
    rows = _lag_table(capsys.readouterr().out)
    # 5 written, none acked: the observer reads the durable backlog from
    # disk; queues nothing ever touched read 0, not an error
    assert rows["transactions"] == 5
    assert rows["db_insert"] == 0


def test_qstat_lag_redis_backend():
    from fake_redis import FakeRedisServer, make_fake_redis
    from apmbackend_tpu.tools import qstat
    from apmbackend_tpu.transport.redis_streams import RedisStreamsChannel

    server = FakeRedisServer()
    mod = make_fake_redis(server)
    cfg = default_config()
    cfg["brokerBackend"] = "redis"
    prod = RedisStreamsChannel("redis://fake", redis_module=mod)
    for i in range(4):
        assert prod.send("transactions", f"l{i}".encode())
    observer, warning = qstat.make_lag_observer(cfg, redis_module=mod)
    assert warning is None
    try:
        rows = dict(qstat.lag_rows(observer, ["transactions", "db_insert"]))
        assert rows["transactions"] == 4  # undelivered backlog, no group yet
        assert rows["db_insert"] == 0
    finally:
        observer.close()
        prod.close()


def test_qstat_lag_amqp_passive_declare():
    from fake_pika import FakeBroker, make_fake_pika
    from apmbackend_tpu.tools import qstat

    broker = FakeBroker()
    mod = make_fake_pika(broker)
    cfg = default_config()
    cfg["brokerBackend"] = "amqp"
    cfg["amqpConnectionString"] = "amqp://fake"
    observer, warning = qstat.make_lag_observer(cfg, pika_module=mod)
    assert warning is None
    try:
        conn = mod.BlockingConnection(mod.URLParameters("amqp://fake"))
        ch = conn.channel()
        ch.queue_declare(queue="transactions", durable=True)
        ch.basic_publish("", "transactions", b"x")
        ch.basic_publish("", "transactions", b"y")
        rows = dict(qstat.lag_rows(observer, ["transactions", "db_insert"]))
        assert rows["transactions"] == 2  # passive-declare message_count
        assert rows["db_insert"] == 0  # missing queue: fail-soft zero
        # the failed passive declare must not poison later reads of queues
        # that DO exist (the observer link is rebuilt)
        observer._lag_cache.clear()
        assert observer.queue_lag("transactions") == 2
    finally:
        observer.close()


def test_qstat_lag_memory_points_at_metrics_url(capsys, monkeypatch):
    from apmbackend_tpu.tools import qstat

    cfg = default_config()  # memory backend
    monkeypatch.setattr("apmbackend_tpu.config.default_config", lambda: cfg)
    rc = qstat.main(["--lag"])
    assert rc == 0
    captured = capsys.readouterr()
    assert "process-local" in captured.err and "--metrics-url" in captured.err
    assert "transactions" in captured.out  # zeros rendered, clearly labeled


# -- fleet aggregation --------------------------------------------------------

def test_manager_fleet_scrape_aggregates_children(tmp_path):
    from apmbackend_tpu.manager.manager import ManagerApp
    from apmbackend_tpu.runtime.module_base import ModuleRuntime

    # a fake child exporter with its own registry
    child_reg = MetricsRegistry()
    child_reg.counter("apm_child_thing_total").inc(5)
    child = TelemetryServer(child_reg, port=0, module="worker")
    child.start()

    cfg = default_config()
    cfg["logDir"] = str(tmp_path / "logs")
    cfg["applicationManager"]["moduleSettings"] = [
        {"module": "apmbackend_tpu.runtime.worker", "metricsPort": child.port},
        {"module": "apmbackend_tpu.ingest.jmx_main"},  # no port: not scraped
    ]
    cfg["applicationManager"]["metricsPort"] = 0
    runtime = ModuleRuntime(
        "applicationManager", config=cfg, install_signals=False, console_log=False
    )
    app = ManagerApp(runtime, spawn_children=False)
    try:
        status, text = fetch(f"{runtime.telemetry.url}/fleet")
        assert status == 200
        s = samples_by_name(text)
        # child series re-labeled with module=<name>
        assert s["apm_child_thing_total"] == [({"module": "worker"}, 5.0)]
        assert ({"module": "worker"}, 1.0) in s["apm_fleet_child_up"]

        # a dead child degrades to up=0 instead of failing the scrape
        child.stop()
        _, text = fetch(f"{runtime.telemetry.url}/fleet")
        s = samples_by_name(text)
        assert ({"module": "worker"}, 0.0) in s["apm_fleet_child_up"]

        # manager /healthz carries the fleet section (no children running)
        try:
            status, body = fetch(f"{runtime.telemetry.url}/healthz")
        except urllib.error.HTTPError as e:
            status, body = e.code, e.read().decode("utf-8")
        health = json.loads(body)
        assert "children" in health["fleet"]
        assert health["fleet"]["children"]["worker"]["up"] is False
        assert status == 503  # down children => degraded

        # restart/exit counters registered per child (keyed by name since
        # fleet shards share one module path)
        app._m_restarts["worker"].inc()
        _, mtext = fetch(f"{runtime.telemetry.url}/metrics")
        ms = samples_by_name(mtext)
        assert ({"module": "worker"}, 1.0) in ms["apm_manager_child_restarts_total"]
    finally:
        app.alerts.stop()
        app.shutdown()
        runtime.stop_timers()
        child.stop()


# -- logging colorization fix -------------------------------------------------

def test_color_formatter_follows_handler_stream(monkeypatch):
    from apmbackend_tpu.logging_util import _ColorFormatter

    record = logging.LogRecord("t", logging.ERROR, "f", 1, "boom", (), None)

    class TtyStream(io.StringIO):
        def isatty(self):
            return True

    # handler on a NON-tty stream must not colorize, even when stderr IS a tty
    import sys

    monkeypatch.setattr(sys, "stderr", TtyStream())
    plain_handler = logging.StreamHandler(io.StringIO())
    fmt = _ColorFormatter("%(message)s", handler=plain_handler)
    assert "\x1b[" not in fmt.format(record)

    # handler on a tty stream colorizes even when stderr is not a tty
    monkeypatch.setattr(sys, "stderr", io.StringIO())
    tty_handler = logging.StreamHandler(TtyStream())
    fmt = _ColorFormatter("%(message)s", handler=tty_handler)
    assert fmt.format(record).startswith("\x1b[31m")

    # a handler whose stream was rebound after construction is read live
    tty_handler.stream = io.StringIO()
    assert "\x1b[" not in fmt.format(record)
