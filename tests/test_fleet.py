"""Pod-scale sharded serving spine (parallel/fleet.py, DESIGN.md §10).

Tier-1 (fast, in-process) coverage of ISSUE 9:

- stable service-hash partitioner: pinned values (cross-process/restart
  determinism), key→partition coverage of the fixture service set at
  every N ≤ 8, routing by service vs server key;
- partition-id header round-trip on ALL three transports (memory, AMQP
  via fake_pika, durable spool);
- the driver row-handoff primitives (export / remove / import) and their
  bit-equality through the resume install path;
- the quiesced rebalance protocol in-process: release → adopt under the
  memory broker, merged fleet state bit-identical to a no-rebalance
  golden run, ownership persistence, partition-header mismatch defense;
- per-shard observability: apm_shard_id labels, dedup-window occupancy,
  epoch-stall healthz 503, manager /fleet degrade + shard expansion;
- fleet trace conformance: handoff events accepted clean, broken
  orderings rejected.

The multi-process kill−9 / live-traffic rebalance scenarios live in
tests/test_fleet_chaos.py (slow tier, ``run_tests.sh --fleet``).
"""

import subprocess
import sys

import numpy as np
import pytest

from apmbackend_tpu.config import default_config
from apmbackend_tpu.parallel.fleet import (
    FleetPartitioner,
    parse_partition,
    partition_queue,
    read_handoff,
    service_partition,
    tx_partition_key,
    write_handoff,
)
from apmbackend_tpu.transport.base import QueueManager
from apmbackend_tpu.transport.memory import MemoryBroker, MemoryChannel

FIXTURE_SERVICES = [f"svc{i:03d}" for i in range(12)]  # make_stream's set


def _tx(t, i, *, svc=None, srv=None, base=170_000_000, e=None):
    e = 100 + (i * 7 + t) % 50 if e is None else e
    svc = svc or f"svc{i % 10:03d}"
    srv = srv or f"jvm{i % 3}"
    return (
        f"tx|{srv}|{svc}|x{t}-{i}|1|{(base + t) * 10000 - e}|"
        f"{(base + t) * 10000 + i}|{e}|Y"
    )


# -- partitioner --------------------------------------------------------------


def test_service_partition_pinned_values():
    """The routing hash is part of the persistence contract: these values
    may NEVER drift (a re-hash re-routes the fleet and orphans every
    dedup window / chain). Pinned against FNV-1a/32."""
    assert [service_partition(s, 8) for s in FIXTURE_SERVICES] == [
        7, 4, 5, 2, 3, 0, 1, 6, 7, 4, 6, 1]
    assert service_partition("getOffers", 4) == 0
    assert service_partition("svc00042", 4) == 1


def test_service_partition_stable_across_processes():
    """PYTHONHASHSEED must not matter (it would if this were hash())."""
    code = (
        "from apmbackend_tpu.parallel.fleet import service_partition;"
        "print([service_partition(f'svc{i:03d}', 8) for i in range(12)])"
    )
    out = subprocess.run(
        [sys.executable, "-c", code], stdout=subprocess.PIPE, check=True,
        env={"PYTHONHASHSEED": "12345", "PATH": "/usr/bin:/bin",
             "JAX_PLATFORMS": "cpu"},
        cwd=str(__import__("pathlib").Path(__file__).resolve().parent.parent),
    ).stdout.decode()
    assert eval(out.strip()) == [7, 4, 5, 2, 3, 0, 1, 6, 7, 4, 6, 1]


@pytest.mark.parametrize("n", range(2, 9))
def test_partition_coverage_no_empty_shard(n):
    """The fixture service set reaches every partition for N <= 8 — a
    fleet sized from these fixtures never boots a shard with zero
    traffic."""
    got = {service_partition(s, n) for s in FIXTURE_SERVICES}
    assert got == set(range(n))


def test_partition_queue_roundtrip():
    assert partition_queue("transactions", 3) == "transactions.p3"
    assert parse_partition("transactions.p3", "transactions") == 3
    assert parse_partition("transactions", "transactions") is None
    assert parse_partition("transactions.px", "transactions") is None
    assert parse_partition("other.p1", "transactions") is None


def test_tx_partition_key_modes():
    line = _tx(0, 1, svc="getOffers", srv="jvmA")
    assert tx_partition_key(line, "service") == "getOffers"
    assert tx_partition_key(line, "server") == "jvmA"
    assert tx_partition_key("jmx|host|x", "service") is None
    assert tx_partition_key("garbage", "service") is None


def test_partitioner_routes_and_stamps():
    broker = MemoryBroker()
    qm = QueueManager(lambda d: MemoryChannel(broker), 3600)
    part = FleetPartitioner(qm, "transactions", 4)
    seen = {}

    def consume_for(p):
        def cb(line, headers=None, token=None):
            seen.setdefault(p, []).append((line, headers))
        return cb

    qm_c = QueueManager(lambda d: MemoryChannel(broker), 3600)
    for p in range(4):
        qm_c.get_queue(partition_queue("transactions", p), "c",
                       consume_for(p)).start_consume()
    lines = [_tx(0, i, svc=s) for i, s in enumerate(FIXTURE_SERVICES)]
    routed = [part.write_line(ln) for ln in lines]
    broker.pump()
    for ln, p in zip(lines, routed):
        assert p == service_partition(tx_partition_key(ln, "service"), 4)
        got = [h for (l2, h) in seen[p] if l2 == ln]
        assert got and got[0]["partition"] == p  # stamped header
        assert "msg_id" in got[0] and "ingest_ts" in got[0]
    # non-tx lines route deterministically to partition 0
    assert part.write_line("jmx|host|blob") == 0


# -- partition header round-trip on all transports ----------------------------


def _roundtrip_partition_header(make_channel, pump):
    qm_p = QueueManager(lambda d: make_channel("p"), 3600)
    q = qm_p.get_queue("transactions.p2", "p")
    q.partition = 2
    got = []
    qm_c = QueueManager(lambda d: make_channel("c"), 3600)
    qm_c.get_queue(
        "transactions.p2", "c",
        lambda line, headers=None, token=None: got.append(headers),
        manual_ack=True,
    ).start_consume()
    q.write_line(_tx(0, 5))
    pump()
    assert len(got) == 1
    assert got[0]["partition"] == 2
    assert "msg_id" in got[0]


def test_partition_header_roundtrip_memory():
    broker = MemoryBroker()
    _roundtrip_partition_header(lambda d: MemoryChannel(broker), broker.pump)


def test_partition_header_roundtrip_spool(tmp_path):
    from apmbackend_tpu.transport.spool import SpoolChannel

    chans = []

    def make(d):
        ch = SpoolChannel(str(tmp_path / "spool"))
        chans.append(ch)
        return ch

    _roundtrip_partition_header(make, lambda: [c.deliver() for c in chans])


def test_partition_header_roundtrip_amqp():
    import time as _time

    from fake_pika import FakeBroker, make_fake_pika

    from apmbackend_tpu.transport.amqp import AmqpChannel

    broker = FakeBroker()
    mod = make_fake_pika(broker)
    chans = []

    def make(d):
        ch = AmqpChannel("amqp://fake", direction=d, pika_module=mod,
                         poll_interval_s=0.005)
        chans.append(ch)
        return ch

    try:
        _roundtrip_partition_header(make, lambda: _time.sleep(0.3))
    finally:
        for c in chans:
            c.close()


def test_partition_header_roundtrip_redis():
    from fake_redis import FakeRedisServer, make_fake_redis

    from apmbackend_tpu.transport.redis_streams import RedisStreamsChannel

    server = FakeRedisServer()
    mod = make_fake_redis(server)
    chans = []

    def make(d):
        ch = RedisStreamsChannel("redis://fake", redis_module=mod)
        chans.append(ch)
        return ch

    _roundtrip_partition_header(make, lambda: [c.pump_once() for c in chans])


# -- driver row handoff primitives --------------------------------------------


def _driver(capacity=64):
    from apmbackend_tpu.pipeline import PipelineDriver

    cfg = default_config()
    cfg["tpuEngine"]["serviceCapacity"] = capacity
    cfg["tpuEngine"]["samplesPerBucket"] = 32
    cfg["streamCalcZScore"]["defaults"] = [
        {"LAG": 6, "THRESHOLD": 3.0, "INFLUENCE": 0.1}
    ]
    return PipelineDriver(cfg, capacity=capacity)


def test_export_remove_import_roundtrip():
    """Rows exported from one engine and imported into another must carry
    bit-identical per-row state through the resume install path."""
    a = _driver()
    lines = [_tx(t, i) for t in range(4) for i in range(30)]
    a.feed_csv_batch(lines)
    a.flush()
    pred = lambda srv, svc: service_partition(svc, 2) == 1  # noqa: E731
    keys_a = a.registry.rows()
    moved_keys = [k for k in keys_a if pred(*k)]
    data = a.export_service_rows(pred)
    assert data["registry"].shape[0] == len(moved_keys)
    before = {
        k: np.asarray(a.state.stats.counts)[i].copy()
        for i, k in enumerate(keys_a)
    }
    removed = a.remove_service_rows(pred)
    assert removed == len(moved_keys)
    assert all(not pred(*k) for k in a.registry.rows())

    b = _driver()
    rest = [ln for ln in lines if not pred(ln.split("|")[1], ln.split("|")[2])]
    assert b.import_service_rows(data) == len(moved_keys)
    del rest
    keys_b = b.registry.rows()
    counts_b = np.asarray(b.state.stats.counts)
    for i, k in enumerate(keys_b):
        assert k in before
        assert np.array_equal(counts_b[i], before[k]), k
    # re-import of the same keys is a routing violation
    with pytest.raises(ValueError):
        b.import_service_rows(data)


def test_import_rotates_ring_to_cursor(tmp_path):
    """An importer whose shared ring cursor differs from the exporter's
    must land each incoming column on the slot of the SAME label."""
    a, b = _driver(), _driver()
    # a sees labels 0..3 for svcA; b independently ticks 0..3 on svcB
    a.feed_csv_batch([_tx(t, 0, svc="svcA") for t in range(4)])
    a.flush()
    b.feed_csv_batch([_tx(t, 0, svc="svcB") for t in range(4)])
    b.flush()
    z_a = np.asarray(a.state.zscores[0].values)[0].copy()  # svcA's row
    data = a.export_service_rows(lambda srv, svc: svc == "svcA")
    b.import_service_rows(data)
    row = b.registry.rows().index(("jvm0", "svcA"))
    z_b = np.asarray(b.state.zscores[0].values)[row]
    assert np.array_equal(z_a, z_b, equal_nan=True)


def test_handoff_file_roundtrip(tmp_path):
    a = _driver()
    a.feed_csv_batch([_tx(t, i) for t in range(2) for i in range(20)])
    a.flush()
    data = a.export_service_rows(lambda srv, svc: True)
    meta = {"partition": 1, "queue": "transactions.p1",
            "base": "transactions", "window": ["m1", "m2"], "epoch": 3}
    path = str(tmp_path / "h.npz")
    write_handoff(path, data, meta)
    data2, meta2 = read_handoff(path)
    assert meta2 == meta
    assert set(data2) == set(data)
    for k in data:
        a1, a2 = np.asarray(data[k]), np.asarray(data2[k])
        eq = (np.array_equal(a1, a2, equal_nan=True)
              if a1.dtype.kind == "f" else np.array_equal(a1, a2))
        assert eq, k


# -- in-process fleet: rebalance golden equivalence ---------------------------


def _mk_fleet_worker(broker, k, shards, tmp_path=None, partitions=None,
                     **eng_overrides):
    from apmbackend_tpu.runtime.module_base import ModuleRuntime
    from apmbackend_tpu.runtime.worker import WorkerApp

    cfg = default_config()
    cfg["tpuEngine"].update(dict(
        serviceCapacity=64, samplesPerBucket=32, deliveryMode="atLeastOnce",
        metricsPort=None, resumeFileFullPath=None,
        deliveryFeedMaxDelaySeconds=0.05,
    ))
    cfg["tpuEngine"].update(eng_overrides)
    # legacy P == N identity unless the test asks for a finer keyspace
    cfg["fleet"] = {"shards": shards, "partitionKey": "service",
                    "shardId": k, "epochStallSeconds": 300.0,
                    "partitions": shards if partitions is None
                    else partitions}
    cfg["streamCalcZScore"]["defaults"] = [
        {"LAG": 6, "THRESHOLD": 3.0, "INFLUENCE": 0.1}
    ]
    cfg["streamCalcStats"]["resumeFileSaveFrequencyInSeconds"] = 3600
    cfg["streamProcessAlerts"]["alertsResumeFileFullPath"] = None
    cfg["logDir"] = None
    rt = ModuleRuntime("tpuEngine", config=cfg, install_signals=False,
                       console_log=False)
    rt.qm = QueueManager(lambda d: MemoryChannel(broker), 3600,
                         logger=rt.logger)
    return WorkerApp(rt), rt


def _fleet_run(tmp_path, rebalance):
    broker = MemoryBroker()
    workers, rts = [], []
    for k in range(2):
        w, rt = _mk_fleet_worker(broker, k, 2)
        workers.append(w)
        rts.append(rt)
    try:
        qm_p = QueueManager(lambda d: MemoryChannel(broker), 3600)
        part = FleetPartitioner(qm_p, "transactions", 2)
        for t in range(4):
            for i in range(40):
                part.write_line(_tx(t, i))
        broker.pump()
        for w in workers:
            w.drain_delivery_pending()
            w.save_state()
        if rebalance:
            hf = str(tmp_path / "handoff.npz")
            meta = workers[1].release_partition(1, hf)
            assert meta["rows"] > 0 and len(meta["window"]) > 0
            res = workers[0].adopt_partition(1, hf)
            assert res["rows"] == meta["rows"]
            assert workers[0].owned_partitions() == [0, 1]
            assert workers[1].owned_partitions() == []
            # re-adopt is a no-op (controller retry safety)
            again = workers[0].adopt_partition(1, hf)
            assert again.get("already_owned")
        # live traffic continues: partition-1 lines reach the new owner
        for t in range(4, 8):
            for i in range(40):
                part.write_line(_tx(t, i))
        broker.pump()
        for w in workers:
            w.drain_delivery_pending()
            w.save_state()
        assert broker.unacked_count() == 0
        merged = {}
        for w in workers:
            counts = np.asarray(w.driver.state.stats.counts)
            sums = np.asarray(w.driver.state.stats.sums)
            for row, key in enumerate(w.driver.registry.rows()):
                assert key not in merged, f"{key} lives on two shards"
                merged[key] = (counts[row].copy(), sums[row].copy())
        deduped = sum(w._deduped_total for w in workers)
        return merged, deduped
    finally:
        for rt in rts:
            rt.stop_timers()


def test_inprocess_rebalance_bit_identical_to_golden(tmp_path):
    """The quiesced handoff under continuing traffic: merged fleet stats
    equal a crash-free no-rebalance golden run key for key — zero loss,
    zero double-effect, owner-locality (no key on two shards)."""
    golden, _ = _fleet_run(tmp_path / "golden", rebalance=False)
    moved, _ = _fleet_run(tmp_path / "moved", rebalance=True)
    assert set(golden) == set(moved)
    for key in golden:
        gc, gs = golden[key]
        mc, ms = moved[key]
        assert np.array_equal(gc, mc), key
        assert np.array_equal(gs, ms, equal_nan=True), key


def test_partition_mismatch_rejected(tmp_path):
    """A delivery whose stamped partition contradicts its queue is counted
    and rejected, never absorbed (the shardmodel mismatch mutant's
    double-effect/stranding cannot happen)."""
    broker = MemoryBroker()
    w, rt = _mk_fleet_worker(broker, 0, 2)
    try:
        # craft a partition-1-stamped message onto partition 0's queue
        broker.send(
            "transactions.p0", _tx(0, 0, svc="svc005").encode(),
            {"msg_id": "bad-1", "partition": 1},
        )
        broker.pump()
        w.drain_delivery_pending()
        w.save_state()
        assert w._partition_mismatch_total == 1
        assert w.driver.registry.count == 0  # never absorbed
        assert broker.unacked_count() == 0  # but acked: cannot loop
        # correctly-stamped delivery on the same queue absorbs normally
        broker.send(
            "transactions.p0", _tx(0, 1, svc="svc005").encode(),
            {"msg_id": "good-1", "partition": 0},
        )
        broker.pump()
        w.drain_delivery_pending()
        assert w.driver.registry.count == 1
    finally:
        rt.stop_timers()


def test_ownership_persists_across_restart(tmp_path):
    """A shard that adopted (or released) partitions must re-own exactly
    the committed set after a restart — ownership rides the delivery
    tree in the checkpoint."""
    broker = MemoryBroker()
    res = str(tmp_path / "s0.resume.npz")
    w, rt = _mk_fleet_worker(broker, 0, 2, resumeFileFullPath=res)
    w2 = rt2 = None
    try:
        qm_p = QueueManager(lambda d: MemoryChannel(broker), 3600)
        part = FleetPartitioner(qm_p, "transactions", 2)
        for i in range(20):
            part.write_line(_tx(0, i))
        broker.pump()
        w.drain_delivery_pending()
        w.save_state()
        # release our ONLY partition, then "crash" (no shutdown)
        hf = str(tmp_path / "handoff.npz")
        w.release_partition(0, hf)
        assert w.owned_partitions() == []
        rt.stop_timers()
        broker2 = MemoryBroker()
        w2, rt2 = _mk_fleet_worker(broker2, 0, 2, resumeFileFullPath=res)
        assert w2.owned_partitions() == []  # the release COMMIT held
        assert w2.driver.registry.count == 0
    finally:
        rt.stop_timers()
        if rt2 is not None:
            rt2.stop_timers()


def test_shard_path_templating(tmp_path):
    broker = MemoryBroker()
    chain_t = str(tmp_path / "chain-shard{shard}")
    w, rt = _mk_fleet_worker(
        broker, 1, 2, checkpointMode="delta", checkpointChainDir=chain_t,
    )
    try:
        assert w._ckpt_chain.directory == str(tmp_path / "chain-shard1")
        import os

        assert os.path.isdir(str(tmp_path / "chain-shard1"))
    finally:
        rt.stop_timers()


# -- observability ------------------------------------------------------------


def test_shard_labels_and_window_occupancy(tmp_path):
    broker = MemoryBroker()
    w, rt = _mk_fleet_worker(broker, 1, 2)
    try:
        qm_p = QueueManager(lambda d: MemoryChannel(broker), 3600)
        part = FleetPartitioner(qm_p, "transactions", 2)
        for i in range(40):
            part.write_line(_tx(0, i))
        broker.pump()
        w.drain_delivery_pending()
        samples = list(w._collect_metrics())
        by_name = {}
        for s in samples:
            by_name.setdefault(s.name, []).append((s.labels, s.value))
        for name in ("apm_delivery_epoch", "apm_delivery_unacked",
                     "apm_redelivered_deduped_total",
                     "apm_delivery_epoch_age_seconds",
                     "apm_fleet_partition_mismatch_total",
                     "apm_shard_rebalances_total",
                     "apm_shard_owned_partitions"):
            labels, _v = by_name[name][0]
            assert labels.get("apm_shard_id") == "1", name
        win = by_name["apm_delivery_dedup_window"]
        assert win[0][0]["queue"] == "transactions.p1"
        assert win[0][1] > 0  # occupancy reflects absorbed ids
        assert by_name["apm_shard_owned_partitions"][0][1] == 1.0
    finally:
        rt.stop_timers()


def test_metrics_targets_feed_never_stalls_or_raises(tmp_path):
    """The FleetRecorder targets feed runs every couple of seconds: a
    shard that never published a port is skipped for the pass (not a
    TimeoutError that drops EVERY target), and a shard whose port file is
    gone (kill −9 / mid-restart unlink) keeps its last known port so the
    recorder can count the failed scrape instead of blocking 15 s."""
    import os as _os
    import time as _time

    from apmbackend_tpu.parallel.fleet import FleetHarness

    h = FleetHarness(str(tmp_path), shards=2, metrics=True)
    try:
        # nobody published yet: empty feed, no exception, no 15 s stall
        t0 = _time.monotonic()
        assert h.metrics_targets(timeout_s=0.0) == []
        assert _time.monotonic() - t0 < 1.0
        with open(h.procs[0].port_path, "w", encoding="utf-8") as fh:
            fh.write("12345\n")
        assert h.metrics_targets(timeout_s=0.0) == [
            ("shard0", "http://127.0.0.1:12345")]
        # port file unlinked (what start() does before the shard rebinds):
        # the last known port survives, the unpublished shard stays skipped
        _os.unlink(h.procs[0].port_path)
        t0 = _time.monotonic()
        assert h.metrics_targets(timeout_s=5.0) == [
            ("shard0", "http://127.0.0.1:12345")]
        assert _time.monotonic() - t0 < 1.0  # cached: no per-shard re-wait
        # a republished (new ephemeral) port replaces the cached one
        with open(h.procs[1].port_path, "w", encoding="utf-8") as fh:
            fh.write("23456\n")
        assert h.metrics_targets(timeout_s=0.0) == [
            ("shard0", "http://127.0.0.1:12345"),
            ("shard1", "http://127.0.0.1:23456")]
        # the blocking single-shard accessor still raises for callers that
        # want the hard wait (startup assertions)
        with pytest.raises(TimeoutError):
            h.metrics_port(0, timeout_s=0.0)
    finally:
        h.close()


def test_epoch_stall_degrades_healthz(tmp_path):
    import time as _time

    broker = MemoryBroker()
    w, rt = _mk_fleet_worker(broker, 0, 2)
    try:
        h = w._health()
        assert "epoch_stalled" not in h["delivery"]
        # wedge simulation: unacked deliveries + an old last-commit stamp
        with w._driver_lock:
            w._epoch_tokens.append(("transactions.p0", 1))
            w._epoch_stall_s = 0.01
            w._last_epoch_commit = _time.monotonic() - 1.0
        h = w._health()
        assert h["ok"] is False
        assert h["delivery"]["epoch_stalled"] is True
        assert h["delivery"]["shard"] == 0
    finally:
        rt.stop_timers()


def test_expand_module_settings_shards():
    from apmbackend_tpu.manager.manager import expand_module_settings

    plain = {"module": "apmbackend_tpu.ingest.parser_main"}
    sharded = {"module": "apmbackend_tpu.runtime.worker", "shards": 3,
               "metricsPort": 9300}
    out = expand_module_settings([plain, sharded])
    assert out[0] == (plain, {}, True)
    names = [ms["name"] for ms, _env, _sweep in out[1:]]
    assert names == ["worker0", "worker1", "worker2"]
    envs = [env for _ms, env, _sweep in out[1:]]
    assert [e["APM_SHARD_ID"] for e in envs] == ["0", "1", "2"]
    assert [e["APM_METRICS_PORT"] for e in envs] == ["9300", "9301", "9302"]
    ports = [ms["metricsPort"] for ms, _env, _sweep in out[1:]]
    assert ports == [9300, 9301, 9302]
    sweeps = [sweep for _ms, _env, sweep in out[1:]]
    assert sweeps == [True, False, False]  # only shard 0 sweeps stale pids


def test_manager_healthz_degrades_on_degraded_shard(tmp_path):
    """A shard answering /healthz degraded (e.g. epoch stall) must turn
    the manager's own /healthz into a 503 — the /fleet plane's contract."""
    from apmbackend_tpu.manager.manager import ManagerApp
    from apmbackend_tpu.obs.exporter import TelemetryServer
    from apmbackend_tpu.runtime.module_base import ModuleRuntime

    child = TelemetryServer(port=0, module="worker0")
    child.add_health("engine", lambda: {"ok": False, "epoch_stalled": True})
    child.start()
    cfg = default_config()
    cfg["logDir"] = str(tmp_path)
    cfg["applicationManager"]["moduleSettings"] = [
        {"module": "apmbackend_tpu.runtime.worker", "name": "worker0",
         "metricsPort": child.port},
    ]
    cfg["applicationManager"]["metricsPort"] = 0
    runtime = ModuleRuntime("applicationManager", config=cfg,
                            install_signals=False, console_log=False)
    app = ManagerApp(runtime, spawn_children=False)
    try:
        import os
        import types

        # make the child look alive so the probe path runs (no real fork)
        app.modules[0].proc = types.SimpleNamespace(
            pid=os.getpid(), poll=lambda: None, returncode=None
        )
        health = app._fleet_health()
        app.modules[0].proc = None
        assert health["ok"] is False
        assert health["children"]["worker0"]["healthz"] == "degraded"
    finally:
        app.alerts.stop()
        app.shutdown()
        runtime.stop_timers()
        child.stop()


# -- fleet trace conformance --------------------------------------------------


def _ev(ev, shard, **kw):
    kw.update(ev=ev, shard=shard)
    return kw


def test_fleet_conformance_accepts_clean_handoff():
    from apmbackend_tpu.analysis.protocol import check_fleet_trace

    events = [
        _ev("deliver", 1, queue="transactions.p1", msg="m1", dedup=False, tx=True),
        _ev("checkpoint", 1, ok=True, epoch=1),
        _ev("handoff_export", 1, partition=1, ids=["m1"], unacked=0),
        _ev("checkpoint", 1, ok=True, epoch=2, handoff=True),
        _ev("handoff_import", 0, partition=1, ids=["m1"]),
        _ev("checkpoint", 0, ok=True, epoch=1, handoff=True),
        _ev("deliver", 0, queue="transactions.p1", msg="m2", dedup=False, tx=True),
        _ev("deliver", 0, queue="transactions.p1", msg="m1", dedup=True, tx=True),
        _ev("checkpoint", 0, ok=True, epoch=2),
    ]
    assert check_fleet_trace(events) == []


def test_fleet_conformance_rejects_violations():
    from apmbackend_tpu.analysis.protocol import check_fleet_trace

    # export while unacked
    v = check_fleet_trace([
        _ev("handoff_export", 1, partition=1, ids=[], unacked=3),
    ])
    assert any("unacked" in x for x in v)
    # import without export
    v = check_fleet_trace([
        _ev("handoff_import", 0, partition=1, ids=["m1"]),
    ])
    assert any("without a pending export" in x for x in v)
    # window dropped in transit
    v = check_fleet_trace([
        _ev("handoff_export", 1, partition=1, ids=["m1"], unacked=0),
        _ev("handoff_import", 0, partition=1, ids=[]),
    ])
    assert any("window" in x for x in v)
    # fleet double effect: two shards commit the same message
    v = check_fleet_trace([
        _ev("deliver", 0, queue="transactions.p0", msg="m1", dedup=False, tx=True),
        _ev("checkpoint", 0, ok=True, epoch=1),
        _ev("deliver", 1, queue="transactions.p1", msg="m1", dedup=False, tx=True),
        _ev("checkpoint", 1, ok=True, epoch=1),
    ])
    assert any("exactly-once" in x for x in v)
    # consuming a queue owned by another shard
    v = check_fleet_trace([
        _ev("deliver", 0, queue="transactions.p1", msg="m1", dedup=False, tx=True),
    ])
    assert any("owned by s1" in x for x in v)
    # delivery inside the handoff window (released, not yet adopted)
    v = check_fleet_trace([
        _ev("handoff_export", 1, partition=1, ids=[], unacked=0),
        _ev("deliver", 1, queue="transactions.p1", msg="m1", dedup=False, tx=True),
    ])
    assert any("handoff window" in x for x in v)
    # a crash discards provisional absorbs: NOT a double effect
    v = check_fleet_trace([
        _ev("deliver", 0, queue="transactions.p0", msg="m1", dedup=False, tx=True),
        _ev("crash", 0),
        _ev("recover", 0, epoch=0),
        _ev("deliver", 0, queue="transactions.p0", msg="m1", dedup=False, tx=True),
        _ev("checkpoint", 0, ok=True, epoch=1),
    ])
    assert v == []


def test_shard_conformance_handoff_mirror():
    """The per-shard mirror follows window ids through export/import and
    treats a handoff commit's unchanged chain epoch as legal."""
    from apmbackend_tpu.analysis.protocol import check_protocol_trace

    exporter = [
        {"ev": "recover", "epoch": 0, "chain_epoch": 0},
        {"ev": "deliver", "msg": "m1", "dedup": False, "tx": True,
         "queue": "transactions.p1"},
        {"ev": "feed", "n": 1},
        {"ev": "checkpoint", "ok": True, "epoch": 1, "chain_epoch": 1},
        {"ev": "ack", "n": 1, "epoch": 1},
        {"ev": "handoff_export", "partition": 1, "ids": ["m1"], "unacked": 0},
        {"ev": "checkpoint", "ok": True, "epoch": 2, "chain_epoch": 1,
         "handoff": True},
    ]
    assert check_protocol_trace(exporter) == []
    importer = [
        {"ev": "recover", "epoch": 0, "chain_epoch": 0},
        {"ev": "handoff_import", "partition": 1, "ids": ["m1"]},
        {"ev": "checkpoint", "ok": True, "epoch": 1, "chain_epoch": 0,
         "handoff": True},
        # redelivery of the moved id must dedup against the imported window
        {"ev": "deliver", "msg": "m1", "dedup": True,
         "queue": "transactions.p1"},
        # a mismatch delivery absorbs nothing
        {"ev": "deliver", "msg": "m9", "dedup": False, "tx": False,
         "mismatch": True, "queue": "transactions.p1"},
        {"ev": "checkpoint", "ok": True, "epoch": 2, "chain_epoch": 1},
    ]
    assert check_protocol_trace(importer) == []
    # an export with a non-empty ledger is a quiesce violation
    broken = exporter[:5] + [
        {"ev": "handoff_export", "partition": 1, "ids": ["m1"], "unacked": 2},
    ]
    assert any("quiesce" in v for v in check_protocol_trace(broken))


# -- P > N fine-grained keyspace (ISSUE 18) -----------------------------------


def test_service_partition_pinned_values_p16():
    """The P > N keyspace pins a SECOND modulus: fleet.partitions is part
    of the persistence contract exactly like the hash itself (rows and
    dedup windows route by service_partition(key, P), not N)."""
    assert [service_partition(s, 16) for s in FIXTURE_SERVICES] == [
        15, 12, 5, 2, 3, 0, 9, 6, 7, 4, 6, 9]
    assert service_partition("getOffers", 16) == 0
    assert service_partition("svc00042", 16) == 9


def test_resolve_partitions_defaults_and_floor():
    from apmbackend_tpu.parallel.fleet import resolve_partitions

    assert resolve_partitions(2, 0) == 8     # default: 4x shards
    assert resolve_partitions(3, 0) == 12
    assert resolve_partitions(1, 0) == 4
    assert resolve_partitions(2, 8) == 8     # explicit wins
    assert resolve_partitions(2, 2) == 2     # P == N still legal
    with pytest.raises(ValueError):
        resolve_partitions(4, 2)             # P < N: a shard owns nothing


@pytest.mark.parametrize("transport", ["memory", "spool", "redis"])
def test_partition_header_roundtrip_high_partition_id(transport, tmp_path):
    """Partition ids above n_shards (the P > N grain) survive the header
    round-trip on every fabric — a partition id is a keyspace coordinate,
    not a shard id, and must never be clamped to the fleet size."""
    P, PID = 8, 6  # 2-shard fleet, partition id 6 > 2

    if transport == "memory":
        broker = MemoryBroker()
        make = lambda d: MemoryChannel(broker)  # noqa: E731
        pump = broker.pump
    elif transport == "spool":
        from apmbackend_tpu.transport.spool import SpoolChannel

        chans = []

        def make(d):
            ch = SpoolChannel(str(tmp_path / "spool"))
            chans.append(ch)
            return ch

        pump = lambda: [c.deliver() for c in chans]  # noqa: E731
    else:
        from fake_redis import FakeRedisServer, make_fake_redis

        from apmbackend_tpu.transport.redis_streams import RedisStreamsChannel

        server = FakeRedisServer()
        mod = make_fake_redis(server)
        chans = []

        def make(d):
            ch = RedisStreamsChannel("redis://fake", redis_module=mod)
            chans.append(ch)
            return ch

        pump = lambda: [c.pump_once() for c in chans]  # noqa: E731

    qname = partition_queue("transactions", PID)
    qm_p = QueueManager(lambda d: make("p"), 3600)
    q = qm_p.get_queue(qname, "p")
    q.partition = PID
    got = []
    qm_c = QueueManager(lambda d: make("c"), 3600)
    qm_c.get_queue(
        qname, "c",
        lambda line, headers=None, token=None: got.append(headers),
        manual_ack=True,
    ).start_consume()
    q.write_line(_tx(0, 5))
    pump()
    assert len(got) == 1
    assert got[0]["partition"] == PID
    assert parse_partition(qname, "transactions") == PID


def test_frame_path_partition_p_gt_n():
    """Frame-mode routing at P > N: split_by_partition over an 8-way
    keyspace matches the per-line hash, every sub-batch is mismatch-free
    for ITS partition, and the partitioner stamps the fine-grained id."""
    from apmbackend_tpu.transport import frames

    lines = [_tx(0, i, svc=s) for i, s in enumerate(FIXTURE_SERVICES)]
    blob = frames.encode_lines(lines)
    ids = frames.partition_ids(blob, 8)
    assert ids == [service_partition(s, 8) for s in FIXTURE_SERVICES]
    parts = frames.split_by_partition(blob, 8)
    assert set(parts) == set(ids)
    for p, sub in parts.items():
        assert frames.count_partition_mismatches(sub, 8, p) == 0

    broker = MemoryBroker()
    qm = QueueManager(lambda d: MemoryChannel(broker), 3600)
    part = FleetPartitioner(qm, "transactions", 8)
    seen = {}
    qm_c = QueueManager(lambda d: MemoryChannel(broker), 3600)
    for p in range(8):
        qm_c.get_queue(
            partition_queue("transactions", p), "c",
            (lambda p_: lambda line, headers=None, token=None:
             seen.setdefault(p_, []).append(headers))(p),
        ).start_consume()
    sent = part.write_frames(blob)
    broker.pump()
    assert sum(sent.values()) == len(lines)
    for p, hs in seen.items():
        assert all(h["partition"] == p for h in hs)


def test_worker_striped_boot_and_high_partition_handoff(tmp_path):
    """Two shards over an 8-partition keyspace: fresh boot stripes the
    ownership (p % N), per-partition lag is exported under the partition
    label, and a partition id above n_shards moves through release/adopt
    exactly like the P == N case."""
    broker = MemoryBroker()
    w0, rt0 = _mk_fleet_worker(broker, 0, 2, partitions=8)
    w1, rt1 = _mk_fleet_worker(broker, 1, 2, partitions=8)
    try:
        assert w0.owned_partitions() == [0, 2, 4, 6]
        assert w1.owned_partitions() == [1, 3, 5, 7]
        qm_p = QueueManager(lambda d: MemoryChannel(broker), 3600)
        part = FleetPartitioner(qm_p, "transactions", 8)
        for t in range(4):
            for i, s in enumerate(FIXTURE_SERVICES):
                part.write_line(_tx(t, i, svc=s))
        broker.pump()
        for w in (w0, w1):
            w.drain_delivery_pending()
            w.save_state()
        # apm_partition_lag carries the PARTITION id, one series per
        # owned partition, attributed to the owning shard
        for w, want in ((w0, {0, 2, 4, 6}), (w1, {1, 3, 5, 7})):
            lag = [s for s in w._collect_metrics()
                   if s.name == "apm_partition_lag"]
            assert {int(s.labels["partition"]) for s in lag} == want
            assert all(s.labels["apm_shard_id"] == str(w.shard_id)
                       for s in lag)
        # move p5 (> n_shards): the handoff carries the P=8 routing grain
        hf = str(tmp_path / "handoff-p5-s1-s0.npz")
        meta = w1.release_partition(5, hf)
        assert meta["partition"] == 5 and meta["partitions"] == 8
        res = w0.adopt_partition(5, hf)
        assert res["rows"] == meta["rows"] > 0
        assert w0.owned_partitions() == [0, 2, 4, 5, 6]
        assert w1.owned_partitions() == [1, 3, 7]
        # live traffic for p5 services reaches the new owner
        n_before = w0.driver.registry.count
        for i, s in enumerate(FIXTURE_SERVICES):
            if service_partition(s, 8) == 5:
                part.write_line(_tx(9, i, svc=s))
        broker.pump()
        w0.drain_delivery_pending()
        w0.save_state()
        assert broker.unacked_count() == 0
        assert w0.driver.registry.count == n_before  # same keys, absorbed
    finally:
        rt0.stop_timers()
        rt1.stop_timers()


def test_handoff_grain_mismatch_rejected(tmp_path):
    """A handoff exported under a different fleet.partitions grain must
    be refused: its rows were routed by a different modulus."""
    broker = MemoryBroker()
    w8, rt8 = _mk_fleet_worker(broker, 1, 2, partitions=8)
    w2, rt2 = _mk_fleet_worker(MemoryBroker(), 0, 2, partitions=2)
    try:
        qm_p = QueueManager(lambda d: MemoryChannel(broker), 3600)
        part = FleetPartitioner(qm_p, "transactions", 8)
        for i, s in enumerate(FIXTURE_SERVICES):
            part.write_line(_tx(0, i, svc=s))
        broker.pump()
        w8.drain_delivery_pending()
        w8.save_state()
        hf = str(tmp_path / "h.npz")
        w8.release_partition(1, hf)
        with pytest.raises(ValueError, match="partitions=8"):
            w2.adopt_partition(1, hf)
    finally:
        rt8.stop_timers()
        rt2.stop_timers()


def test_torn_handoff_read_fails_loudly(tmp_path):
    """A torn handoff file (partial write, external truncation) must
    raise out of read_handoff — never parse as an empty record — so the
    controller lands in the abort path instead of absorbing a void."""
    a = _driver()
    a.feed_csv_batch([_tx(0, i) for i in range(20)])
    a.flush()
    data = a.export_service_rows(lambda srv, svc: True)
    meta = {"partition": 1, "queue": "transactions.p1",
            "base": "transactions", "window": ["m1"], "epoch": 1}
    path = str(tmp_path / "h.npz")
    write_handoff(path, data, meta)
    blob = open(path, "rb").read()
    for cut in (0, 10, len(blob) // 2, len(blob) - 1):
        with open(path, "wb") as fh:
            fh.write(blob[:cut])
        with pytest.raises(Exception):
            read_handoff(path)


# -- rebalance policy (pure) --------------------------------------------------


def _obs(lags, owners=None, burning=None):
    from apmbackend_tpu.parallel.rebalancer import Observation

    owners = owners or {p: p % 2 for p in lags}
    return Observation(lags, owners, burning)


_POLICY_CFG = {"highWatermark": 64, "lowWatermark": 16,
               "cooldownSeconds": 30.0, "movesPerPartition": 1}


def test_policy_watermark_move_and_determinism():
    from apmbackend_tpu.parallel.rebalancer import PolicyState, decide

    lags = {0: 100.0, 1: 5.0, 2: 10.0, 3: 0.0}
    d1 = decide(_obs(lags), PolicyState(), _POLICY_CFG, 0.0)
    d2 = decide(_obs(lags), PolicyState(), _POLICY_CFG, 0.0)
    assert d1 == d2  # pure: same observation, same decision
    assert d1["move"] == [0, 0, 1] and d1["reason"] == "watermark"
    # balanced fleet: no move, explained
    d3 = decide(_obs({0: 5.0, 1: 5.0}), PolicyState(), _POLICY_CFG, 0.0)
    assert d3["move"] is None and d3["reason"] == "balanced"


def test_policy_cooldown_one_move_per_window():
    """The storm clause: after an executed move the window closes — the
    SAME stale observation cannot trigger a second move until the
    cooldown expires (shard-rebalance-storm shows the counterexample)."""
    from apmbackend_tpu.parallel.rebalancer import (
        PolicyState, apply_move, decide)

    lags = {0: 100.0, 1: 5.0, 2: 50.0, 3: 0.0}
    st = PolicyState()
    d = decide(_obs(lags), st, _POLICY_CFG, 0.0)
    assert d["move"] == [0, 0, 1]
    apply_move(st, d, _POLICY_CFG, 0.0)
    d2 = decide(_obs(lags), st, _POLICY_CFG, 10.0)
    assert d2["move"] is None and d2["reason"] == "cooldown"
    d3 = decide(_obs(lags), st, _POLICY_CFG, 31.0)  # window reopened
    assert d3["move"] is not None


def test_policy_budget_blocks_same_partition_until_lag_changes():
    """The oscillation clause: a moved partition whose observed lag has
    NOT changed is not re-armed — the stale view that justified the move
    cannot justify the reverse move (shard-rebalance-oscillation)."""
    from apmbackend_tpu.parallel.rebalancer import (
        PolicyState, apply_move, decide)

    lags = {0: 100.0, 1: 0.0, 2: 10.0, 3: 0.0}
    st = PolicyState()
    d = decide(_obs(lags), st, _POLICY_CFG, 0.0)
    assert d["move"] == [0, 0, 1]
    apply_move(st, d, _POLICY_CFG, 0.0)
    # cooldown expired, attribution refreshed (p0 now on s1), p0 lag
    # unchanged: s1 is hot but p0 may not bounce back
    owners = {0: 1, 1: 1, 2: 0, 3: 1}
    d2 = decide(_obs(lags, owners), st, _POLICY_CFG, 40.0)
    assert d2["move"] is None or d2["move"][0] != 0
    # new lag = new information: p0 re-arms (and the band still clears)
    lags2 = {0: 80.0, 1: 25.0, 2: 10.0, 3: 0.0}
    d3 = decide(_obs(lags2, owners), st, _POLICY_CFG, 80.0)
    assert d3["move"] == [0, 1, 0]


def test_policy_hysteresis_band_strict():
    """Moving a partition whose lag EQUALS the donor/recipient gap only
    swaps the imbalance — the band must be strictly wider than the moved
    lag or nothing moves."""
    from apmbackend_tpu.parallel.rebalancer import PolicyState, decide

    # gap = 70 - 0 = 70, biggest partition lag = 70: equality, no move
    d = decide(_obs({0: 70.0, 1: 0.0}), PolicyState(), _POLICY_CFG, 0.0)
    assert d["move"] is None and d["reason"] == "no-qualifying-move"
    # split load: moving p2 (30 < gap 80) strictly improves
    d2 = decide(_obs({0: 50.0, 2: 30.0, 1: 0.0, 3: 0.0}),
                PolicyState(), _POLICY_CFG, 0.0)
    assert d2["move"] == [0, 0, 1]  # hottest qualifying first


def test_policy_slo_burn_qualifies_donor_below_watermark():
    from apmbackend_tpu.parallel.rebalancer import PolicyState, decide

    lags = {0: 30.0, 1: 1.0, 2: 5.0, 3: 0.0}
    d = decide(_obs(lags), PolicyState(), _POLICY_CFG, 0.0)
    assert d["move"] is None  # 35 < high: watermark alone says no
    d2 = decide(_obs(lags, burning={0}), PolicyState(), _POLICY_CFG, 0.0)
    assert d2["move"] == [0, 0, 1] and d2["reason"] == "slo-burn"


def test_policy_recipient_must_be_cool():
    """No move lands on a shard above the LOW watermark — a recipient
    near the high mark would immediately re-donate (ping-pong)."""
    from apmbackend_tpu.parallel.rebalancer import PolicyState, decide

    d = decide(_obs({0: 100.0, 1: 20.0, 2: 0.0, 3: 0.0}),
               PolicyState(), _POLICY_CFG, 0.0)
    assert d["move"] is None and d["reason"] == "no-qualifying-move"


# -- rebalance controller (execution, abort, recovery) ------------------------


class _DirectPeer:
    """In-process peer: drives a WorkerApp's _exec_control directly (the
    durable channel collapses to a dict — CtlPeer's file protocol is
    exercised by the multiprocess tests in test_fleet_chaos.py)."""

    def __init__(self, worker):
        self.worker = worker
        self.seq = 0
        self.done = {}
        self.fail_cmds = set()  # cmds to fail once (injected fault)

    def alive(self):
        return True

    def request(self, cmd, **fields):
        self.seq += 1
        if cmd in self.fail_cmds:
            self.fail_cmds.discard(cmd)
            self.done[self.seq] = {"seq": self.seq, "ok": False,
                                   "error": "Injected: peer fault"}
        else:
            req = dict(fields, cmd=cmd, seq=self.seq)
            self.done[self.seq] = self.worker._exec_control(req)
        return self.seq

    def wait_done(self, seq, timeout_s=120.0, *, cmd="?",
                  die_on_death=True):
        done = self.done[seq]
        if not done.get("ok"):
            raise RuntimeError(f"{cmd} failed: {done.get('error')}")
        return done.get("result") or {}


def _ctl_fixture(tmp_path, broker=None):
    from apmbackend_tpu.parallel.rebalancer import (
        Observation, RebalanceController)

    broker = broker or MemoryBroker()
    w0, rt0 = _mk_fleet_worker(broker, 0, 2, partitions=8)
    w1, rt1 = _mk_fleet_worker(broker, 1, 2, partitions=8)
    qm_p = QueueManager(lambda d: MemoryChannel(broker), 3600)
    part = FleetPartitioner(qm_p, "transactions", 8)
    for t in range(4):
        for i, s in enumerate(FIXTURE_SERVICES):
            part.write_line(_tx(t, i, svc=s))
    broker.pump()
    for w in (w0, w1):
        w.drain_delivery_pending()
        w.save_state()
    owners = {p: p % 2 for p in range(8)}
    lags = {p: 0.0 for p in range(8)}
    # skew: p0 (on shard 0) is hot; p2's extra load keeps the band
    # strictly wider than p0's own lag (the hysteresis clause)
    lags[0] = 100.0
    lags[2] = 10.0

    def observe():
        return Observation(lags, owners)

    observe.owners = owners
    cfg = dict(_POLICY_CFG, moveTimeoutSeconds=10.0, intervalSeconds=0.1)
    ctl = RebalanceController(
        str(tmp_path), {0: _DirectPeer(w0), 1: _DirectPeer(w1)},
        observe, cfg)
    return ctl, (w0, w1), (rt0, rt1), lags, owners


def test_controller_executes_policy_move(tmp_path):
    from apmbackend_tpu.parallel.rebalancer import handoff_path

    ctl, (w0, w1), rts, lags, owners = _ctl_fixture(tmp_path)
    try:
        d = ctl.tick()
        assert d["move"] == [0, 0, 1] and d["executed"] is True
        assert w0.owned_partitions() == [2, 4, 6]
        assert w1.owned_partitions() == [0, 1, 3, 5, 7]
        assert owners[0] == 1  # observer view followed the move
        assert ctl.moves_total == 1 and ctl.aborts_total == 0
        # the handoff file is GC'd after the adopt commit
        assert not __import__("os").path.exists(
            handoff_path(str(tmp_path), 0, 0, 1))
        assert ctl.stale_handoffs_gc_total == 1
        # cooldown: the very next tick is suppressed and counted
        d2 = ctl.tick()
        assert d2["reason"] == "cooldown"
        assert ctl.skipped_cooldown_total == 1
        names = {s.name: s.value for s in ctl.collect_metrics()}
        assert names["apm_rebalance_moves_total"] == 1
        assert names["apm_rebalance_skipped_cooldown_total"] == 1
    finally:
        for rt in rts:
            rt.stop_timers()


def test_controller_frozen_only_observes(tmp_path):
    ctl, workers, rts, lags, owners = _ctl_fixture(tmp_path)
    try:
        ctl.cfg["enabled"] = False
        assert ctl.tick() == {"move": None, "reason": "frozen"}
        assert ctl.moves_total == 0
        assert workers[0].owned_partitions() == [0, 2, 4, 6]
    finally:
        for rt in rts:
            rt.stop_timers()


def test_controller_abort_releaser_readopts(tmp_path):
    """Adopter fault mid-move: the releaser re-adopts its OWN export —
    ownership returns to the donor, nothing is lost, the move counts as
    an abort and the cooldown is NOT burned (the decision failed to
    execute)."""
    ctl, (w0, w1), rts, lags, owners = _ctl_fixture(tmp_path)
    try:
        ctl.peers[1].fail_cmds.add("adopt")
        d = ctl.tick()
        assert d["move"] == [0, 0, 1] and d["executed"] is False
        assert w0.owned_partitions() == [0, 2, 4, 6]  # back home
        assert w1.owned_partitions() == [1, 3, 5, 7]
        assert owners[0] == 0
        assert ctl.aborts_total == 1 and ctl.moves_total == 0
        # no cooldown burned: the next tick retries (and succeeds)
        d2 = ctl.tick()
        assert d2["executed"] is True
        assert w1.owned_partitions() == [0, 1, 3, 5, 7]
    finally:
        for rt in rts:
            rt.stop_timers()


def test_controller_recover_completes_mid_move(tmp_path):
    """Manager died between release-commit and adopt: the handoff file
    holds the only copy of the rows. recover() probes live ownership,
    finishes the move on the intended recipient, and GCs the file."""
    import os as _os

    from apmbackend_tpu.parallel.rebalancer import handoff_path

    ctl, (w0, w1), rts, lags, owners = _ctl_fixture(tmp_path)
    try:
        path = handoff_path(str(tmp_path), 0, 0, 1)
        w0.release_partition(0, path)  # the dead manager got this far
        assert _os.path.exists(path)
        res = ctl.recover()
        assert res == [{"file": _os.path.basename(path),
                        "resolution": "completed"}]
        assert w1.owned_partitions() == [0, 1, 3, 5, 7]
        assert not _os.path.exists(path)
        assert ctl.moves_total == 1
        assert ctl.stale_handoffs_gc_total == 1
    finally:
        for rt in rts:
            rt.stop_timers()


def test_controller_recover_stale_and_torn_files(tmp_path):
    """Stale files are resolved by the OWNERSHIP probe, not file content
    (a torn file whose partition is still owned somewhere is just
    garbage — GC'd, counted); a torn file for a partition NOBODY owns is
    the data-loss alarm: the abort path fails loudly and the file is
    KEPT as evidence, never silently GC'd."""
    import os as _os

    from apmbackend_tpu.parallel.rebalancer import handoff_path

    ctl, (w0, w1), rts, lags, owners = _ctl_fixture(tmp_path)
    try:
        # stale-completed: p1 is owned by shard 1 == `to` of this file
        stale = handoff_path(str(tmp_path), 1, 0, 1)
        with open(stale, "wb") as fh:
            fh.write(b"leftover")
        # stale-aborted: torn file, but shard 0 (frm) still owns p2 —
        # ownership says the release never committed, content irrelevant
        stale2 = handoff_path(str(tmp_path), 2, 0, 1)
        with open(stale2, "wb") as fh:
            fh.write(b"PK\x03\x04 torn npz prefix")
        # torn + nobody owns: release p4 COMMITTED (rows dropped from
        # w0), then the only copy got corrupted
        torn = handoff_path(str(tmp_path), 4, 0, 1)
        w0.release_partition(4, torn)
        blob = open(torn, "rb").read()
        with open(torn, "wb") as fh:
            fh.write(blob[: len(blob) // 2])
        res = {r["file"]: r["resolution"] for r in ctl.recover()}
        assert res[_os.path.basename(stale)] == "stale-completed"
        assert res[_os.path.basename(stale2)] == "stale-aborted"
        assert res[_os.path.basename(torn)] == "abort-failed"
        assert not _os.path.exists(stale) and not _os.path.exists(stale2)
        assert _os.path.exists(torn)  # evidence kept
        assert ctl.stale_handoffs_gc_total == 2
        assert ctl.aborts_total == 0  # the abort did NOT succeed
        assert w0.owned_partitions() == [0, 2, 6]  # p4 genuinely lost
    finally:
        for rt in rts:
            rt.stop_timers()


def test_manager_rebalance_wiring_and_fleet_owner_map(tmp_path):
    """fleet.rebalance.enabled + controlDir turn the supervisor into the
    controller: one CtlPeer per shard child (APM_SHARD_ID), the scraped
    observation carries lag + ownership from the SAME bodies, and /fleet
    grows the partition -> shard map derived from that attribution."""
    from apmbackend_tpu.manager.manager import ManagerApp
    from apmbackend_tpu.obs import MetricsRegistry, TelemetryServer
    from apmbackend_tpu.runtime.module_base import ModuleRuntime

    srvs = []
    for k, parts in ((0, (0, 2)), (1, (1, 3))):
        reg = MetricsRegistry()
        for p in parts:
            reg.gauge(
                "apm_partition_lag", "per-partition backlog",
                labels={"partition": str(p), "queue": f"transactions.p{p}"},
            ).set(10.0 * (p + 1))
        srv = TelemetryServer(reg, port=0, module=f"worker{k}")
        srv.start()
        srvs.append(srv)
    cfg = default_config()
    cfg["logDir"] = str(tmp_path)
    cfg["fleet"]["controlDir"] = str(tmp_path / "ctl")
    cfg["fleet"]["rebalance"].update(
        enabled=True, intervalSeconds=3600.0, moveTimeoutSeconds=0.2)
    cfg["applicationManager"]["moduleSettings"] = [
        {"module": "apmbackend_tpu.runtime.worker", "shards": 2,
         "metricsPort": 9999},
    ]
    cfg["applicationManager"]["metricsPort"] = 0
    runtime = ModuleRuntime("applicationManager", config=cfg,
                            install_signals=False, console_log=False)
    app = ManagerApp(runtime, spawn_children=False)
    try:
        assert app.rebalancer is not None
        assert sorted(app.rebalancer.peers) == [0, 1]
        # aim the scrape inventory at the fake shard exporters
        for k, srv in enumerate(srvs):
            app.modules[k].setting["metricsPort"] = srv.port
        obs = app._rebalance_observation()
        assert obs.owners == {0: 0, 2: 0, 1: 1, 3: 1}
        assert obs.lags == {0: 10.0, 2: 30.0, 1: 20.0, 3: 40.0}
        text = app.scrape_fleet()
        assert 'apm_fleet_partition_owner{partition="0"} 0' in text
        assert 'apm_fleet_partition_owner{partition="2"} 0' in text
        assert 'apm_fleet_partition_owner{partition="3"} 1' in text
        # the freeze switch: a frozen controller only observes
        app.rebalancer.cfg["enabled"] = False
        assert app.rebalancer.tick() == {"move": None, "reason": "frozen"}
    finally:
        app.alerts.stop()
        app.shutdown()
        runtime.stop_timers()
        for s in srvs:
            s.stop()


def test_slo_burning_partitions_extraction():
    """The SLO -> policy bridge: fast burns of the partition_lag
    objective surface as partition ids; everything else is ignored."""
    from apmbackend_tpu.obs.slo import DEFAULT_OBJECTIVES, burning_partitions

    assert any(o["name"] == "partition_lag" and o["per"] == "partition"
               and o["series"] == "apm_partition_lag"
               for o in DEFAULT_OBJECTIVES)
    res = [
        {"objective": "partition_lag", "key": "3", "severity": "fast"},
        {"objective": "partition_lag", "key": "5", "severity": "slow"},
        {"objective": "queue_lag", "key": "transactions.p1",
         "severity": "fast"},
        {"objective": "partition_lag", "key": "7", "severity": "fast"},
    ]
    assert burning_partitions(res) == {3, 7}
    assert burning_partitions([]) == set()
    assert burning_partitions(None) == set()
