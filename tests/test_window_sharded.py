"""Window-axis (sequence-parallel) z-score sharding vs the single-device op.

A (services x window) mesh over the virtual 8-CPU platform must reproduce
ops.zscore.step: means/bounds to reduction-order rounding (a psum over shard
partials sums in a different order than one flat sum — last-ulp differences
are inherent to floating point), and signals, ring contents, and counters
exactly — across enough steps to cover fill-up, full-ring rotation, and
signalling regimes. Ring contents are compared to the same tight tolerance:
XLA may contract the damping expression to an FMA in one program and not the
other, so even bit-identical inputs can round differently in the last ulp.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apmbackend_tpu.ops import zscore as z
from apmbackend_tpu.parallel.window_sharded import (
    WINDOW_AXIS,
    make_mesh2d,
    make_window_sharded_step,
    shard_zstate,
)

S, LAG = 8, 16
DTYPE = jnp.float64


def series(rng, t):
    """Mostly-steady series with occasional NaN and occasional spikes."""
    x = 100 + rng.randn(S, 3)
    if t % 7 == 3:
        x[rng.randint(0, S)] = np.nan
    if t > LAG and t % 11 == 5:
        x[rng.randint(0, S)] *= 3  # spike -> signal + influence damping
    return x.astype(np.float64)


@pytest.mark.parametrize("mesh_shape", [(2, 4), (1, 8), (4, 2)])
def test_parity_with_single_device(mesh_shape):
    n_s, n_w = mesh_shape
    cfg = z.ZScoreConfig(S, LAG, DTYPE)
    mesh = make_mesh2d(n_s, n_w)
    step_sharded = make_window_sharded_step(mesh, cfg)

    ref_state = z.init_state(cfg)
    sh_state = shard_zstate(z.init_state(cfg), mesh)

    thr = jnp.asarray(np.linspace(2.0, 4.0, S), DTYPE)
    infl = jnp.asarray(np.linspace(0.0, 1.0, S), DTYPE)
    rng = np.random.RandomState(42)

    for t in range(2 * LAG + 9):
        x = jnp.asarray(series(rng, t))
        ref_res, ref_state = z.step(ref_state, cfg, x, thr, infl)
        sh_res, sh_state = step_sharded(sh_state, x, thr, infl)
        for field in ("window_avg", "lower_bound", "upper_bound"):
            np.testing.assert_allclose(
                np.asarray(getattr(ref_res, field)),
                np.asarray(getattr(sh_res, field)),
                rtol=1e-12, atol=0,
                err_msg=f"{field} diverged at step {t}",
            )
        np.testing.assert_array_equal(
            np.asarray(ref_res.signal), np.asarray(sh_res.signal), err_msg=f"signal @ {t}"
        )
        np.testing.assert_allclose(
            np.asarray(ref_state.values), np.asarray(sh_state.values),
            rtol=1e-12, atol=0, err_msg=f"ring @ {t}",
        )
        np.testing.assert_array_equal(np.asarray(ref_state.fill), np.asarray(sh_state.fill))
        np.testing.assert_array_equal(np.asarray(ref_state.pos), np.asarray(sh_state.pos))


def test_signals_fire_through_sharded_path():
    cfg = z.ZScoreConfig(S, LAG, DTYPE)
    mesh = make_mesh2d(2, 4)
    step_sharded = make_window_sharded_step(mesh, cfg)
    state = shard_zstate(z.init_state(cfg), mesh)
    # threshold 6: plain randn never exceeds in this window, the x2 spike always does
    thr = jnp.full(S, 6.0, DTYPE)
    infl = jnp.full(S, 0.1, DTYPE)
    rng = np.random.RandomState(0)
    for _ in range(LAG + 2):
        x = jnp.asarray(200 + rng.randn(S, 3))
        res, state = step_sharded(state, x, thr, infl)
    assert not np.any(np.asarray(res.signal))
    res, state = step_sharded(state, jnp.asarray(np.full((S, 3), 400.0)), thr, infl)
    assert np.all(np.asarray(res.signal) == 1)


def test_lag_not_divisible_raises():
    cfg = z.ZScoreConfig(S, 10, DTYPE)  # 10 % 4 != 0
    mesh = make_mesh2d(2, 4)
    with pytest.raises(ValueError, match="divisible"):
        make_window_sharded_step(mesh, cfg)


def test_capacity_not_divisible_raises():
    cfg = z.ZScoreConfig(9, LAG, DTYPE)
    mesh = make_mesh2d(2, 4)
    with pytest.raises(ValueError, match="capacity"):
        make_window_sharded_step(mesh, cfg)


def test_degenerate_all_equal_window_parity():
    """All-equal windows must resolve exactly on the sharded path too: no std,
    no signal, mean == the value — same as ops.zscore.step (pmin/pmax)."""
    cfg = z.ZScoreConfig(S, LAG, DTYPE)
    mesh = make_mesh2d(2, 4)
    step_sharded = make_window_sharded_step(mesh, cfg)
    st_a = z.init_state(cfg)
    st_b = shard_zstate(z.init_state(cfg), mesh)
    thr = jnp.full(S, 2.0, DTYPE)
    infl = jnp.full(S, 0.1, DTYPE)
    const = jnp.full((S, 3), 515.3, DTYPE)  # a value whose k-sum does NOT
    # reproduce itself under linear summation (the FP-luck case)
    for t in range(LAG):
        _ra, st_a = z.step(st_a, cfg, const, thr, infl)
        _rb, st_b = step_sharded(st_b, const, thr, infl)
    probe = const.at[:, 0].add(200.0)  # big deviation: would signal iff std defined
    ra, _ = z.step(st_a, cfg, probe, thr, infl)
    rb, _ = step_sharded(st_b, probe, thr, infl)
    assert np.array_equal(np.asarray(ra.signal), np.asarray(rb.signal))
    assert int(np.asarray(rb.signal).sum()) == 0  # all-equal -> no std -> no signal
    assert np.allclose(np.asarray(rb.window_avg), 515.3)
    assert np.all(np.isnan(np.asarray(rb.lower_bound)))
