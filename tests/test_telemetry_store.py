"""Durable telemetry spine (DESIGN.md §8.4): the on-disk time-series
store's framing/recovery/degradation, range-query evaluation, the fleet
recorder against a live exporter, the SLO burn-rate engine (provenance +
healthz 503), the transport queue-lag gauge, and the qstat --range/--slo
modes. Hostile storage reuses the deltachain ``APM_CHAOS_FS`` seam."""

import json
import math
import os
import time
import urllib.error
import urllib.request
from urllib.parse import urlencode

import pytest

from apmbackend_tpu.config import default_config
from apmbackend_tpu.deltachain import StorageFaultPlan, install_fault_plan
from apmbackend_tpu.obs import (
    FleetRecorder,
    MetricsRegistry,
    SLOEngine,
    TelemetryServer,
    TimeSeriesStore,
    eval_range,
    make_query_route,
    set_registry,
)
from apmbackend_tpu.obs.decisions import DecisionRing
from apmbackend_tpu.obs.store import SEGMENT_GLOB_RE


@pytest.fixture(autouse=True)
def fresh_registry():
    old = set_registry(MetricsRegistry())
    yield
    set_registry(old)


@pytest.fixture(autouse=True)
def no_fault_plan():
    install_fault_plan(None)
    yield
    install_fault_plan(None)


def _fill(store, n=10, t0=1000.0, dt=10.0, name="apm_x_total", q="db"):
    for i in range(n):
        store.append_samples(
            [[name, {"queue": q}, float(i)]], ts=t0 + i * dt
        )


def _segs(d):
    return sorted(f for f in os.listdir(d) if SEGMENT_GLOB_RE.match(f))


# -- store: framing, recovery, degradation -----------------------------------

def test_store_round_trip_and_recovery(tmp_path):
    d = str(tmp_path)
    st = TimeSeriesStore(d)
    _fill(st, n=12)
    st.append_spans([{"trace_id": "t-1", "name": "tick", "start": 1050.0,
                      "end": 1050.1}], extra={"module": "w0"})
    st.append_decisions([{"trace_id": "t-1", "ts": 1051.0, "service": "s",
                          "channel": 6}], extra={"module": "w0"})
    st.close()

    st2 = TimeSeriesStore(d)
    pts = st2.series_points("apm_x_total", 0, 2000)
    assert len(pts) == 1
    (_key, series), = pts.items()
    assert [v for _, v in series] == [float(i) for i in range(12)]
    spans = st2.spans(0, math.inf)
    assert spans and spans[0]["trace_id"] == "t-1"
    assert spans[0]["module"] == "w0"
    decs = st2.decisions(0, math.inf, match={"module": "w0"})
    assert decs and decs[0]["channel"] == 6
    assert st2.stats()["recovered_rows"] > 0
    st2.close()


def test_store_torn_tail_truncates_not_fails(tmp_path):
    d = str(tmp_path)
    st = TimeSeriesStore(d)
    _fill(st, n=8)
    st.close()
    seg = os.path.join(d, _segs(d)[-1])
    sz = os.path.getsize(seg)
    with open(seg, "r+b") as fh:  # torn final record: chop mid-frame
        fh.truncate(sz - 7)
    st2 = TimeSeriesStore(d)
    (_k, series), = st2.series_points("apm_x_total", 0, 2000).items()
    assert 0 < len(series) < 8  # prefix survives, tail gone
    assert st2.stats()["corrupt_segments_total"] == 1
    # the store stays writable after recovering a torn segment
    st2.append_samples([["apm_x_total", {"queue": "db"}, 99.0]], ts=2000.0)
    st2.close()


def test_store_bit_rot_stops_at_last_valid_segment(tmp_path):
    d = str(tmp_path)
    st = TimeSeriesStore(d, segment_max_bytes=256)  # force several segments
    _fill(st, n=30)
    st.close()
    segs = _segs(d)
    assert len(segs) >= 3
    # flip a payload byte in a MIDDLE segment: CRC must catch it and
    # recovery must stop there — later segments stay unread (prefix
    # semantics, same discipline as the delta chain)
    victim = os.path.join(d, segs[len(segs) // 2])
    with open(victim, "r+b") as fh:
        fh.seek(os.path.getsize(victim) - 3)
        b = fh.read(1)
        fh.seek(-1, os.SEEK_CUR)
        fh.write(bytes([b[0] ^ 0xFF]))
    st2 = TimeSeriesStore(d)
    (_k, series), = st2.series_points("apm_x_total", 0, 5000).items()
    full = TimeSeriesStore(str(tmp_path / "nothing"))
    assert len(series) < 30
    assert st2.stats()["corrupt_segments_total"] >= 1
    # new appends land on a FRESH sequence number (no collision with the
    # unread tail)
    st2.append_samples([["apm_x_total", {"queue": "db"}, 123.0]], ts=9000.0)
    st2.close()
    full.close()
    st3 = TimeSeriesStore(d)
    (_k, series3), = st3.series_points("apm_x_total", 8000, 10000).items()
    assert [v for _, v in series3] == [123.0]
    st3.close()


def test_store_read_only_recovery_is_nondestructive(tmp_path):
    """qstat --store may point at a LIVE recorder directory: a read-only
    open must read the valid prefix without truncating segments in place
    or renaming the tail to *.quarantine under the writer's open handle."""
    d = str(tmp_path)
    st = TimeSeriesStore(d, segment_max_bytes=256)
    _fill(st, n=30)
    st.close()
    segs = _segs(d)
    assert len(segs) >= 3
    victim = os.path.join(d, segs[len(segs) // 2])
    sz = os.path.getsize(victim)
    with open(victim, "r+b") as fh:  # torn mid-frame in a MIDDLE segment
        fh.truncate(sz - 5)

    def _listing():
        return {f: os.path.getsize(os.path.join(d, f)) for f in os.listdir(d)}

    before = _listing()
    ro = TimeSeriesStore(d, read_only=True)
    (_k, series), = ro.series_points("apm_x_total", 0, 5000).items()
    assert 0 < len(series) < 30  # valid prefix only, same stop semantics
    assert ro.stats()["corrupt_segments_total"] >= 1
    # writes are refused wholesale: appends, spans, decisions, compaction
    assert ro.append_samples([["apm_x_total", {}, 1.0]], ts=1.0) == 0
    assert ro.append_spans([{"trace_id": "t", "start": 1.0}]) == 0
    assert ro.append_decisions([{"ts": 1.0}]) == 0
    assert ro.compact(10_000_000.0) == {"dropped": 0, "downsampled": 0}
    ro.close()
    assert _listing() == before
    # the qstat post-mortem paths ride the same read-only recovery
    from apmbackend_tpu.tools import qstat
    assert qstat.main(["--range", "apm_x_total", "--store", d]) == 0
    assert qstat.main(["--slo", "--store", d]) == 0
    assert _listing() == before
    # a subsequent WRITER open still repairs (truncate and/or quarantine)
    TimeSeriesStore(d).close()
    assert _listing() != before


def test_store_enospc_degrades_drop_and_count(tmp_path):
    st = TimeSeriesStore(str(tmp_path), reopen_backoff_s=0.0)
    st.append_samples([["apm_x_total", {}, 1.0]], ts=100.0)
    # after=0,count=1: the NEXT segment write tears (partial bytes hit the
    # file, then ENOSPC) — the deltachain chaos seam, byte-identical plan
    install_fault_plan(StorageFaultPlan("enospc:after=0,count=1"))
    st.append_samples([["apm_x_total", {}, 2.0]], ts=110.0)  # torn + ENOSPC
    install_fault_plan(None)
    stats = st.stats()
    assert stats["write_errors_total"] == 1
    assert stats["dropped_rows_total"] == 1
    # degrade, don't lose the live view: BOTH rows stay queryable
    (_k, series), = st.series_points("apm_x_total", 0, 200).items()
    assert [v for _, v in series] == [1.0, 2.0]
    # and the writer recovers onto a fresh segment afterwards
    st.append_samples([["apm_x_total", {}, 3.0]], ts=120.0)
    assert st.stats()["write_errors_total"] == 1
    st.close()
    st2 = TimeSeriesStore(str(tmp_path))
    (_k, series2), = st2.series_points("apm_x_total", 0, 200).items()
    assert 3.0 in [v for _, v in series2]
    st2.close()


def test_store_retention_and_downsample(tmp_path):
    now = 100000.0
    d = str(tmp_path)

    def _open():
        return TimeSeriesStore(d, retention_s=500.0,
                               downsample_after_s=100.0,
                               downsample_step_s=60.0)

    # segment boundaries via close/reopen (recovered segments are sealed):
    # retention and downsample both operate on whole sealed segments
    st = _open()
    # aged beyond retention: whole segment unlinked
    st.append_samples([["apm_old", {}, 1.0]], ts=now - 1000.0)
    st.close()
    st = _open()
    # old enough to downsample, young enough to keep: 6 points in one
    # 60 s bucket collapse to the LAST value
    for i in range(6):
        st.append_samples([["apm_mid", {}, float(i)]], ts=now - 300.0 + i)
    st.append_spans([{"trace_id": "t", "name": "n", "start": now - 290.0,
                      "end": now - 289.0}])
    st.close()
    st = _open()
    st.append_samples([["apm_new", {}, 7.0]], ts=now - 5.0)
    st.compact(now)
    assert st.series_points("apm_old", 0, now) == {}
    (_k, mid), = st.series_points("apm_mid", 0, now).items()
    assert [v for _, v in mid] == [5.0]  # last value per bucket
    assert st.spans(0, now)  # spans ride through compaction raw
    (_k, new), = st.series_points("apm_new", 0, now).items()
    assert [v for _, v in new] == [7.0]
    stats = st.stats()
    assert stats["retention_drops_total"] >= 1
    assert stats["compactions_total"] >= 1
    st.close()
    # the rewrite is durable: reopen sees the downsampled shape
    st2 = TimeSeriesStore(str(tmp_path))
    (_k, mid2), = st2.series_points("apm_mid", 0, now).items()
    assert [v for _, v in mid2] == [5.0]
    st2.close()


# -- range-query evaluation ---------------------------------------------------

def test_eval_range_instant_rate_and_quantile(tmp_path):
    st = TimeSeriesStore(None)  # volatile store, identical query surface
    for i in range(20):
        t = 1000.0 + i * 5.0
        st.append_samples([["apm_c_total", {"m": "a"}, float(i * 10)]], ts=t)
        # synthetic cumulative histogram: 90% under 0.1s, all under 0.25s
        st.append_samples(
            [["apm_lat_seconds_bucket", {"le": "0.1"}, float(i * 9)],
             ["apm_lat_seconds_bucket", {"le": "0.25"}, float(i * 10)],
             ["apm_lat_seconds_bucket", {"le": "+Inf"}, float(i * 10)]], ts=t)
    doc = eval_range(st, "apm_c_total", 1000.0, 1095.0, 5.0)
    (s,) = doc["series"]
    assert s["labels"] == {"m": "a"}
    assert s["points"][-1][1] == 190.0
    doc = eval_range(st, "rate(apm_c_total[20s])", 1050.0, 1095.0, 5.0)
    vals = {v for _, v in doc["series"][0]["points"] if v is not None}
    assert vals == {2.0}  # +10 every 5s
    doc = eval_range(st, "histogram_quantile(0.95, apm_lat_seconds)",
                     1050.0, 1095.0, 5.0)
    (s,) = doc["series"]
    qv = [v for _, v in s["points"] if v is not None]
    # rank 9.5i lands in the (0.1, 0.25] bucket; prometheus-style linear
    # interpolation puts p95 halfway through it
    assert qv and all(v == pytest.approx(0.175) for v in qv)
    with pytest.raises(ValueError):
        eval_range(st, "not a query(", 0, 1, 1)
    with pytest.raises(ValueError):
        # step-count cap: epoch-wide range at 1 s step must refuse, not spin
        eval_range(st, "apm_c_total", 0, 2_000_000_000, 1.0)
    st.close()


def test_eval_range_histogram_quantile_is_windowed_not_alltime():
    """The quantile at each step must come from the bucket INCREASE over
    the window (histogram_quantile(q, rate(...)) idiom), not the
    cumulative since-process-start counts — after a latency regime change
    the all-time distribution barely moves, the windowed one tracks it."""
    st = TimeSeriesStore(None)
    # phase 1 (t<=1050): every event slow, lands in (0.1, 1.0];
    # phase 2 (t>1050): every NEW event fast, lands in [0, 0.1]
    for i in range(21):
        t = 1000.0 + i * 5.0
        fast = 10.0 * max(0, i - 10)
        total = 10.0 * i
        st.append_samples(
            [["apm_l_seconds_bucket", {"le": "0.1"}, fast],
             ["apm_l_seconds_bucket", {"le": "1.0"}, total],
             ["apm_l_seconds_bucket", {"le": "+Inf"}, total]], ts=t)
    doc = eval_range(st, "histogram_quantile(0.95, apm_l_seconds[20s])",
                     1050.0, 1100.0, 5.0)
    (s,) = doc["series"]
    vals = {t: v for t, v in s["points"]}
    # window fully inside the slow phase: p95 interpolates in (0.1, 1.0]
    assert vals[1050.0] == pytest.approx(0.955)
    # window fully inside the fast phase: p95 lands in the first bucket —
    # the all-time cumulative mix would still report ~0.91 here
    assert vals[1100.0] == pytest.approx(0.095)
    st.close()


def test_query_route_contract(tmp_path):
    """The route handler honours the exporter contract: parse_qs list
    values in, str body out; kind= readers filter on labels."""
    st = TimeSeriesStore(None)
    _fill(st, n=4)
    st.append_spans([{"trace_id": "t-9", "name": "tick", "start": 1000.0,
                      "end": 1000.5}], extra={"module": "shard1"})
    handler = make_query_route(lambda: st)
    code, ctype, body = handler({"series": ["apm_x_total"], "start": ["900"],
                                 "end": ["1200"], "step": ["10"]})
    assert code == 200 and ctype == "application/json"
    assert isinstance(body, str)
    doc = json.loads(body)
    assert doc["series"][0]["labels"] == {"queue": "db"}
    code, _, body = handler({"kind": ["spans"], "start": ["0"],
                             "module": ["shard1"]})
    assert code == 200
    assert json.loads(body)["rows"][0]["trace_id"] == "t-9"
    code, _, body = handler({"kind": ["spans"], "start": ["0"],
                             "module": ["other"]})
    assert json.loads(body)["rows"] == []
    code, _, body = handler({"kind": ["names"]})
    assert "apm_x_total" in json.loads(body)["names"]
    code, _, _body = handler({"series": ["broken("]})
    assert code == 400
    st.close()


# -- fleet recorder -----------------------------------------------------------

def test_recorder_scrapes_live_exporter_and_degrades_on_dead_target():
    from apmbackend_tpu.obs import get_registry
    from apmbackend_tpu.obs.decisions import set_decisions
    from apmbackend_tpu.obs.trace import Tracer, set_tracer

    reg = get_registry()
    reg.gauge("apm_engine_services", "rows").set(42.0)
    old_tracer = set_tracer(Tracer(module="child", sample_rate=1))
    old_ring = set_decisions(DecisionRing())
    from apmbackend_tpu.obs.decisions import get_decisions
    from apmbackend_tpu.obs.trace import get_tracer

    get_tracer().span("t-r1", "tick", 10.0, 10.2)
    get_decisions().record({"trace_id": "t-r1", "ts": 11.0, "service": "s",
                            "channel": 6})
    server = TelemetryServer(reg, port=0, module="child")
    server.start()
    st = TimeSeriesStore(None)
    rec = FleetRecorder(
        st,
        lambda: [("shard0", server.url), ("dead", "http://127.0.0.1:9/")],
        timeout_s=2.0,
    )
    try:
        summary = rec.scrape_once(now=5000.0)
        assert summary["ok"] == 1  # the dead target was skipped, not fatal
        pts = st.series_points("apm_engine_services", 0, 6000,
                               labels={"module": "shard0"})
        (_k, series), = pts.items()
        assert series == [(5000.0, 42.0)]
        assert st.spans(0, math.inf, match={"module": "shard0"})
        assert st.decisions(0, math.inf, match={"module": "shard0"})
        counts = rec.status()["counts"]
        assert counts["scrape_errors_total"] >= 1
        assert counts["span_rows_total"] == 1
        # second pass: ring contents are deduped, counters don't re-count
        rec.scrape_once(now=5001.0)
        assert rec.status()["counts"]["span_rows_total"] == 1
        assert rec.status()["counts"]["decision_rows_total"] == 1
    finally:
        server.stop()
        st.close()
        set_tracer(old_tracer)
        set_decisions(old_ring)


# -- SLO engine ---------------------------------------------------------------

def _lag_breach_store(now, *, breach_from=None):
    """apm_queue_lag for one queue: healthy zeros, then a sustained breach
    (> default 10k threshold) from ``breach_from`` to ``now``."""
    st = TimeSeriesStore(None)
    breach_from = now - 240.0 if breach_from is None else breach_from
    t = now - 3600.0
    while t <= now:
        v = 50000.0 if t >= breach_from else 0.0
        st.append_samples([["apm_queue_lag", {"queue": "db_insert"}, v]], ts=t)
        t += 15.0
    return st


def test_slo_gauge_fast_burn_alert_with_provenance():
    now = 500000.0
    st = _lag_breach_store(now)
    ring = DecisionRing()
    alerts = []
    eng = SLOEngine(st, short_window_s=300.0, long_window_s=3600.0,
                    decisions=ring, on_alert=lambda m, r: alerts.append((m, r)))
    results = eng.evaluate(now)
    lag = [r for r in results if r["objective"] == "queue_lag"]
    assert lag and lag[0]["key"] == "db_insert"
    # short window: 240/300 bad -> burn 80; long: 240/3600 -> burn 6.7;
    # only the SHORT clears 14.4, so severity must NOT be fast...
    assert lag[0]["burn_short"] > 14.4
    # widen the breach to cover the long window too -> fast
    st2 = _lag_breach_store(now, breach_from=now - 3600.0)
    eng2 = SLOEngine(st2, decisions=ring,
                     on_alert=lambda m, r: alerts.append((m, r)))
    res2 = eng2.evaluate(now)
    lag2 = [r for r in res2 if r["objective"] == "queue_lag"][0]
    assert lag2["severity"] == "fast"
    assert alerts, "fast burn must dispatch an alert"
    msg, record = alerts[-1]
    assert "queue_lag" in msg
    # decision provenance: the record resolves every SLO input
    stored = [d for d in ring.recent() if d.get("decision") == "slo_burn_rate"]
    assert stored
    d = stored[-1]
    assert d["series"] == "apm_queue_lag" and d["key"] == "db_insert"
    for w in ("short", "long"):
        win = d["windows"][w]
        assert win["bad_fraction"] == 1.0
        assert win["events"] > 0 and "window_s" in win
    assert d["burn_short"] == pytest.approx(1.0 / 0.01)
    assert d["target"] == 0.99 and d["threshold"] == 10000.0
    # cooldown: immediate re-evaluation must not re-page
    n = len(stored)
    eng2.evaluate(now + 1.0)
    stored2 = [x for x in ring.recent()
               if x.get("decision") == "slo_burn_rate"]
    assert len(stored2) == n
    st.close()
    st2.close()


def test_slo_latency_objective_from_histogram_buckets():
    now = 200000.0
    st = TimeSeriesStore(None)
    # cumulative buckets: of each 100 new events, 90 land <= 0.1s
    for i in range(0, 3600 // 15):
        t = now - 3600.0 + i * 15.0
        st.append_samples(
            [["apm_e2e_ingest_to_emit_seconds_bucket", {"le": "0.1"},
              90.0 * i],
             ["apm_e2e_ingest_to_emit_seconds_bucket", {"le": "+Inf"},
              100.0 * i]], ts=t)
    eng = SLOEngine(st)
    res = eng.evaluate(now)
    det = [r for r in res if r["objective"] == "detection_latency_p95"][0]
    # 10% bad vs 5% budget -> burn 2.0 on both windows; threshold bucket
    # resolved to the smallest le >= 0.1
    assert det["burn_short"] == pytest.approx(2.0, rel=1e-3)
    assert det["burn_long"] == pytest.approx(2.0, rel=1e-3)
    assert det["severity"] is None
    assert det["windows"]["short"]["bucket_le"] == 0.1
    st.close()


def test_slo_latency_bad_fraction_per_labelset_not_interleaved():
    """A manager recorder store holds every shard's cumulative buckets
    under per-shard ``module`` labels. The burn-rate math must delta each
    counter series separately and sum the increases — merging the series
    into one point list reads every shard0→shard1 value transition as a
    counter reset and inflates the event counts by orders of magnitude."""
    now = 400000.0
    st = TimeSeriesStore(None)
    for i in range(0, 3600 // 15):
        t = now - 3600.0 + i * 15.0
        rows = []
        for mod, scale in (("shard0", 100.0), ("shard1", 10.0)):
            rows += [
                ["apm_e2e_ingest_to_emit_seconds_bucket",
                 {"le": "0.1", "module": mod}, 0.9 * scale * i],
                ["apm_e2e_ingest_to_emit_seconds_bucket",
                 {"le": "+Inf", "module": mod}, scale * i],
            ]
        st.append_samples(rows, ts=t)
    eng = SLOEngine(st)
    det = [r for r in eng.evaluate(now)
           if r["objective"] == "detection_latency_p95"][0]
    # both shards run 10% bad against the 5% budget -> burn exactly 2.0
    assert det["burn_short"] == pytest.approx(2.0, rel=1e-3)
    assert det["burn_long"] == pytest.approx(2.0, rel=1e-3)
    assert det["severity"] is None
    # events = the true summed increase across both shards' +Inf counters
    n = 3600 // 15 - 1
    assert det["windows"]["long"]["events"] == pytest.approx(110.0 * n, rel=0.05)
    st.close()


def test_slo_health_degrades_healthz_to_503():
    now = 300000.0
    st = _lag_breach_store(now, breach_from=now - 3600.0)
    eng = SLOEngine(st)
    server = TelemetryServer(MetricsRegistry(), port=0, module="mgr")
    server.add_health("slo", eng.health)
    server.start()
    try:
        status, body = _fetch_any(f"{server.url}/healthz")
        assert status == 200  # no evaluation yet -> no verdict
        eng.evaluate(now)
        assert eng.health()["ok"] is False
        status, body = _fetch_any(f"{server.url}/healthz")
        assert status == 503
        doc = json.loads(body)
        assert doc["slo"]["fast_burning"] == ["queue_lag:db_insert"]
    finally:
        server.stop()
        st.close()


def _fetch_any(url):
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, resp.read().decode("utf-8")
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode("utf-8")


def test_slo_from_config_schema():
    cfg = default_config()
    assert cfg["slo"]["enabled"] is True
    cfg["slo"]["shortWindowSeconds"] = 60.0
    cfg["slo"]["fastBurnThreshold"] = 2.0
    cfg["slo"]["objectives"] = [
        {"name": "only", "kind": "gauge", "series": "apm_queue_lag",
         "threshold": 1.0, "target": 0.5, "per": "queue"}]
    st = TimeSeriesStore(None)
    eng = SLOEngine.from_config(st, cfg)
    assert eng.short_window_s == 60.0
    assert eng.fast_burn == 2.0
    assert [o["name"] for o in eng.objectives] == ["only"]
    st.close()


# -- transport lag gauge ------------------------------------------------------

def test_queue_lag_gauge_memory_and_spool(tmp_path):
    from apmbackend_tpu.obs import get_registry, parse_prom_text
    from apmbackend_tpu.transport.base import QueueManager
    from apmbackend_tpu.transport.memory import MemoryBroker, MemoryChannel
    from apmbackend_tpu.transport.spool import SpoolChannel

    broker = MemoryBroker()
    # producer and consumer live in separate processes in production: two
    # managers over the shared broker (one manager caches by queue name)
    qm_p = QueueManager(lambda d: MemoryChannel(broker), 3600)
    qm_c = QueueManager(lambda d: MemoryChannel(broker), 3600)
    # manual-ack consumer that never acks: both deliveries stay owed
    qm_c.get_queue("q1", "c", lambda line, headers, token: None,
                   manual_ack=True)  # registers the gauge
    prod = qm_p.get_queue("q1", "p")
    prod.write_line("a|b")
    prod.write_line("c|d")
    rendered = {(n, labels.get("queue")): v for n, labels, v in
                parse_prom_text(get_registry().render())
                if n == "apm_queue_lag"}
    assert rendered[("apm_queue_lag", "q1")] == 2.0  # sent, not acked

    ch = SpoolChannel(str(tmp_path))
    ch.send("qs", b"x", None)
    ch.send("qs", b"y", None)
    assert ch.queue_lag("qs") == 2
    # a FRESH channel over the same directory sees the same backlog — the
    # dead-consumer observer path (manager-side lag probe)
    ch2 = SpoolChannel(str(tmp_path))
    assert ch2.queue_lag("qs") == 2
    ch.close()
    ch2.close()


# -- qstat modes --------------------------------------------------------------

def test_qstat_range_and_slo_store_modes(tmp_path, capsys):
    from apmbackend_tpu.tools import qstat

    d = str(tmp_path)
    st = TimeSeriesStore(d)
    now = time.time()
    for i in range(40):
        t = now - 600.0 + i * 15.0
        st.append_samples([["apm_queue_lag", {"queue": "db_insert"},
                            50000.0]], ts=t)
        st.append_samples([["apm_in_total", {}, float(i * 30)]], ts=t)
    st.close()

    assert qstat.main(["--range", "apm_queue_lag", "--store", d]) == 0
    out = capsys.readouterr().out
    assert 'queue="db_insert"' in out and "last=50000" in out

    assert qstat.main(["--range", "rate(apm_in_total[60s])", "--store", d,
                       "--step", "60"]) == 0
    out = capsys.readouterr().out
    assert "last=2" in out  # +30 every 15s

    assert qstat.main(["--slo", "--store", d]) == 0
    out = capsys.readouterr().out
    assert "queue_lag" in out and "fast" in out

    assert qstat.main(["--range", "apm_queue_lag"]) == 2  # no source
    assert qstat.main(["--slo"]) == 2


def test_qstat_range_via_live_query_endpoint():
    from apmbackend_tpu.tools import qstat

    st = TimeSeriesStore(None)
    now = time.time()
    for i in range(10):
        st.append_samples([["apm_live_g", {}, float(i)]], ts=now - 100 + i * 10)
    server = TelemetryServer(MetricsRegistry(), port=0, module="m")
    server.add_route("/query", make_query_route(lambda: st))
    server.start()
    try:
        doc = qstat.range_query_url(server.url, "apm_live_g",
                                    now - 120, now, 10.0)
        assert doc["series"][0]["points"]
        assert qstat.main(["--range", "apm_live_g",
                           "--metrics-url", server.url,
                           "--start", str(now - 120), "--end", str(now)]) == 0
    finally:
        server.stop()
        st.close()


def test_qstat_slo_health_via_url():
    from apmbackend_tpu.tools import qstat

    now = time.time()
    st = _lag_breach_store(now, breach_from=now - 3600.0)
    eng = SLOEngine(st)
    eng.evaluate(now)
    server = TelemetryServer(MetricsRegistry(), port=0, module="mgr")
    server.add_health("slo", eng.health)
    server.start()
    try:
        doc = qstat.slo_health_url(server.url)
        assert doc["status"] == "degraded"
        assert doc["slo"]["fast_burning"] == ["queue_lag:db_insert"]
    finally:
        server.stop()
        st.close()


# -- /query wired into the module runtime ------------------------------------

def test_decision_ring_snapshot_atomic_and_bounded():
    ring = DecisionRing(maxlen=4)
    for i in range(3):
        ring.record({"i": i})
    total, items = ring.snapshot()
    assert total == 3 and [d["i"] for d in items] == [0, 1, 2]
    for i in range(3, 10):  # overflow the ring
        ring.record({"i": i})
    total, items = ring.snapshot()
    assert total == 10 and [d["i"] for d in items] == [6, 7, 8, 9]
    total, items = ring.snapshot(2)
    assert total == 10 and [d["i"] for d in items] == [8, 9]


def test_self_sample_decisions_no_dupes_no_silent_skip():
    """The self-sample pass snapshots (total, items) atomically: repeated
    passes never re-persist a decision, and a between-pass ring overflow
    persists the survivors exactly once while advancing the seen-counter
    past the (already gone) overflow."""
    from apmbackend_tpu.obs.decisions import get_decisions, set_decisions
    from apmbackend_tpu.runtime.module_base import ModuleRuntime

    old_ring = set_decisions(DecisionRing(maxlen=8))
    cfg = default_config()
    cfg["logDir"] = None
    cfg["tpuEngine"]["metricsPort"] = 0
    cfg["observability"]["selfSampleSeconds"] = 3600.0  # manual passes only
    rt = ModuleRuntime("tpuEngine", config=cfg, install_signals=False,
                       console_log=False)
    try:
        ring = get_decisions()
        for i in range(5):
            ring.record({"ts": 100.0 + i, "service": f"s{i}", "channel": 1})
        rt._self_sample()
        rt._self_sample()  # nothing new -> nothing re-appended
        assert len(rt.store.decisions(0.0, 150.0)) == 5
        # 20 > ring size 8: the 12 oldest are gone from the ring either
        # way; the 8 survivors persist once, then the counter is caught up
        for i in range(20):
            ring.record({"ts": 200.0 + i, "service": f"t{i}", "channel": 2})
        rt._self_sample()
        rt._self_sample()
        decs = rt.store.decisions(150.0, math.inf)
        assert [d["service"] for d in decs] == [f"t{i}" for i in range(12, 20)]
    finally:
        rt.stop_timers()
        set_decisions(old_ring)


def test_module_runtime_serves_query_over_self_samples(tmp_path):
    from apmbackend_tpu.runtime.module_base import ModuleRuntime

    cfg = default_config()
    cfg["logDir"] = None
    cfg["tpuEngine"]["metricsPort"] = 0
    cfg["observability"]["selfSampleSeconds"] = 0.1
    cfg["observability"]["storeDir"] = str(tmp_path / "selfstore")
    rt = ModuleRuntime("tpuEngine", config=cfg, install_signals=False,
                       console_log=False)
    try:
        assert rt.store is not None
        assert rt.slo is not None
        rt._self_sample()
        url = f"http://127.0.0.1:{rt.telemetry.port}"
        qs = urlencode({"kind": "stats"})
        status, body = _fetch_any(f"{url}/query?{qs}")
        assert status == 200
        assert json.loads(body)["stats"]["rows_total"] > 0
        status, body = _fetch_any(f"{url}/healthz")
        doc = json.loads(body)
        assert "slo" in doc  # engine health provider mounted
    finally:
        rt.stop_timers()
