"""Transport tests: pause/drain backpressure contract over the memory backend."""

import pytest

from apmbackend_tpu.transport import MemoryBroker, QueueManager, make_queue_manager


def make_qm(capacity=5):
    broker = MemoryBroker(capacity=capacity, low_water_ratio=0.4)
    qm = make_queue_manager({"brokerBackend": "memory", "statLogIntervalInSeconds": 60}, broker=broker)
    return qm, broker


def test_basic_produce_consume():
    qm, broker = make_qm()
    got = []
    prod = qm.get_queue("q1", "p")
    cons = qm.get_queue("q1c", "c", got.append)  # distinct names: one handle per queue
    # point consumer at q1 by registering directly on the same queue name:
    qm2 = make_queue_manager({"brokerBackend": "memory"}, broker=broker)
    cons = qm2.get_queue("q1", "c", got.append)
    cons.start_consume()
    prod.write_line("tx|a|b|c|1|2|3|4|N")
    broker.pump()
    assert got == ["tx|a|b|c|1|2|3|4|N"]


def test_backpressure_pause_and_drain_resume():
    broker = MemoryBroker(capacity=3, low_water_ratio=0.4)
    qm_prod = make_queue_manager({"brokerBackend": "memory"}, broker=broker)
    qm_cons = make_queue_manager({"brokerBackend": "memory"}, broker=broker)

    events = []
    qm_prod.on("pause", lambda: events.append("pause"))
    qm_prod.on("resume", lambda: events.append("resume"))

    prod = qm_prod.get_queue("q", "p")
    for i in range(5):
        prod.write_line(f"line{i}")

    # capacity 3 -> lines 3,4 buffered, pause emitted once
    assert events == ["pause"]
    assert prod.buffer_count() == 2
    assert broker.queue_depth("q") == 3

    got = []
    cons = qm_cons.get_queue("q", "c", got.append)
    cons.start_consume()
    broker.pump()  # drains queue; drain event fires -> retry buffers -> resume
    assert "resume" in events
    assert prod.buffer_count() == 0
    broker.pump()
    assert got == [f"line{i}" for i in range(5)]  # order preserved through buffer


def test_consumer_stop_start():
    qm, broker = make_qm()
    got = []
    prod = qm.get_queue("q", "p")
    qm2 = make_queue_manager({"brokerBackend": "memory"}, broker=broker)
    cons = qm2.get_queue("q", "c", got.append)
    cons.start_consume()
    prod.write_line("a")
    broker.pump()
    cons.stop_consume()
    prod.write_line("b")
    broker.pump()
    assert got == ["a"]
    assert broker.queue_depth("q") == 1  # message waits while cancelled
    cons.start_consume()
    broker.pump()
    assert got == ["a", "b"]


def test_get_queue_validation_and_reuse():
    qm, _ = make_qm()
    with pytest.raises(ValueError):
        qm.get_queue("x", "z")
    with pytest.raises(ValueError):
        qm.get_queue("x", "c")  # consumer without callback
    p1 = qm.get_queue("x", "p")
    p2 = qm.get_queue("x", "p")
    assert p1 is p2  # cached handle (queue.js:109-110)


def test_broker_introspection():
    qm, broker = make_qm()
    prod = qm.get_queue("q", "p")
    prod.write_line("hello")
    assert broker.queue_depth("q") == 1
    assert broker.queue_memory_bytes("q") == 5
    assert "q" in broker.queue_names()


def test_pump_thread_mode():
    import time

    broker = MemoryBroker(capacity=100)
    qm_p = make_queue_manager({"brokerBackend": "memory"}, broker=broker)
    qm_c = make_queue_manager({"brokerBackend": "memory"}, broker=broker)
    got = []
    prod = qm_p.get_queue("q", "p")
    cons = qm_c.get_queue("q", "c", got.append)
    cons.start_consume()
    broker.start_pump_thread()
    for i in range(50):
        prod.write_line(str(i))
    deadline = time.time() + 2.0
    while len(got) < 50 and time.time() < deadline:
        time.sleep(0.01)
    broker.stop()
    assert got == [str(i) for i in range(50)]


def test_pump_max_messages_exact():
    broker = MemoryBroker(capacity=100)
    qms = [make_queue_manager({"brokerBackend": "memory"}, broker=broker) for _ in range(4)]
    got = []
    for i in range(3):
        prod = qms[0].get_queue(f"q{i}", "p")
        cons = qms[i + 1].get_queue(f"q{i}", "c", got.append)
        cons.start_consume()
        prod.write_line(f"m{i}")
    assert broker.pump(max_messages=1) == 1
    assert len(got) == 1
    assert broker.pump() == 2
