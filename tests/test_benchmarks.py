"""Benchmark suite smoke tests: every BASELINE.json config bench runs in
quick mode on the virtual CPU mesh and returns the standard result schema."""

import json
import sys

import pytest

sys.path.insert(0, ".")  # benchmarks/ lives at repo root beside the package

from benchmarks import REGISTRY  # noqa: E402

REQUIRED_KEYS = {"metric", "value", "unit", "vs_baseline", "details"}


@pytest.mark.parametrize("name", sorted(REGISTRY))
def test_bench_quick(name):
    res = REGISTRY[name](quick=True)
    assert REQUIRED_KEYS <= set(res)
    if name == "pallas":
        # off-TPU the kernel bench verifies parity but reports speedup 0
        # (timing needs hardware); the parity check raising IS the test
        assert res["details"]["parity"] == "exact"
    else:
        assert res["value"] > 0
        assert res["vs_baseline"] > 0
    json.dumps(res)  # must be JSON-serializable (the wire contract)


def test_registry_covers_all_five_configs():
    # the five BASELINE.json configs plus the pallas hardware-proof,
    # dispatch-floor, and fleet-spine extras
    assert set(REGISTRY) == {
        "replay", "rolling", "jmx", "podshard", "multiwindow", "pallas",
        "dispatch", "fleet",
    }


def test_runner_cli(capsys):
    from benchmarks.run import main

    rc = main(["--config", "rolling", "--quick"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1
    res = json.loads(out[0])
    assert res["metric"] == "rolling_baseline_throughput"
