"""utils/resume.py failure paths: torn loads fall back cleanly, the Map
wrapper revives, and the atomic tmp+rename write leaves no droppings when the
write itself fails mid-flight (the crash-consistency floor every resume
consumer — alerts, DB buffer, multivariate baseline — stands on)."""

import json
import os

import pytest

from apmbackend_tpu.utils.resume import load_resume_file, save_resume_file


def _tmp_droppings(directory):
    return [n for n in os.listdir(directory) if n.endswith(".tmp")]


def test_missing_file_returns_none(tmp_path):
    assert load_resume_file(str(tmp_path / "nope.json")) is None


def test_torn_json_falls_back_to_none(tmp_path):
    """A crash mid-write of a NON-atomic writer (the reference's
    writeFileSync) leaves a torn prefix; the loader must shrug, not raise."""
    p = tmp_path / "torn.json"
    p.write_text('{"a": [1, 2, {"b": "unclosed')
    assert load_resume_file(str(p)) is None


def test_truncated_to_empty_falls_back(tmp_path):
    p = tmp_path / "empty.json"
    p.write_text("")
    assert load_resume_file(str(p)) is None


def test_binary_garbage_falls_back(tmp_path):
    p = tmp_path / "junk.json"
    p.write_bytes(b"\x00\xff\xfePK\x03\x04 not json")
    assert load_resume_file(str(p)) is None


def test_map_wrapper_revives_nested(tmp_path):
    """The reference's Map replacer shape ({"dataType": "Map", "value":
    [[k, v], ...]}) must revive to plain dicts at ANY nesting depth —
    interchange compatibility with reference-written resume files."""
    p = tmp_path / "map.json"
    wrapper = {
        "dataType": "Map",
        "value": [
            ["svcA", {"dataType": "Map", "value": [["360", {"count": 3}]]}],
            ["svcB", [1, {"dataType": "Map", "value": [["k", "v"]]}]],
        ],
    }
    p.write_text(json.dumps({"alerts": wrapper, "plain": {"x": 1}}))
    out = load_resume_file(str(p))
    assert out == {
        "alerts": {"svcA": {"360": {"count": 3}}, "svcB": [1, {"k": "v"}]},
        "plain": {"x": 1},
    }


def test_save_load_round_trip_with_nan_sanitization(tmp_path):
    p = str(tmp_path / "rt.json")
    save_resume_file(p, {"v": float("nan"), "w": float("inf"), "k": [1.5, None]})
    # NaN/Inf become null, like JSON.stringify — loadable by strict parsers
    assert load_resume_file(p) == {"v": None, "w": None, "k": [1.5, None]}


def test_failed_serialization_leaves_no_droppings_and_keeps_original(tmp_path):
    p = str(tmp_path / "state.json")
    save_resume_file(p, {"good": 1})
    with pytest.raises(TypeError):
        save_resume_file(p, {"bad": {1, 2, 3}})  # sets are not JSON
    assert _tmp_droppings(str(tmp_path)) == []  # tmp cleaned up
    assert load_resume_file(p) == {"good": 1}  # original intact


def test_failed_rename_leaves_no_droppings(tmp_path, monkeypatch):
    import apmbackend_tpu.utils.resume as resume_mod

    p = str(tmp_path / "state.json")
    save_resume_file(p, {"v": 1})

    def boom(src, dst):
        raise OSError("disk gone")

    monkeypatch.setattr(resume_mod.os, "replace", boom)
    with pytest.raises(OSError):
        save_resume_file(p, {"v": 2})
    monkeypatch.undo()
    assert _tmp_droppings(str(tmp_path)) == []
    assert load_resume_file(p) == {"v": 1}  # atomic: old content survives


def test_save_creates_parent_dirs(tmp_path):
    p = str(tmp_path / "a" / "b" / "state.json")
    save_resume_file(p, {"v": 1})
    assert load_resume_file(p) == {"v": 1}
