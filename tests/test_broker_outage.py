"""Broker-outage chaos tier: kill the BROKER (not the worker) mid-stream
under at-least-once delivery and prove bit-identical recovery.

The scenario the backpressure spine exists for: the transport fabric dies
while a live worker holds absorbed-but-unacked messages and a live producer
keeps writing. The contract proved here, per backend:

- zero loss: every line the producer wrote reaches the worker exactly once
  in effect (redeliveries of the delivered-but-unacked window are deduped
  by msg_id, never double-absorbed);
- the final windowed state is bit-identical to a crash-free golden run
  (``assert_snapshots_equal``, the PR 3 chaos-harness comparator);
- producer memory stays bounded: the pause buffer never exceeds
  ``transport.producerBufferMaxLines`` at any observable instant, and the
  ``pause`` event engages synchronously with the first refused write (the
  parser wires this straight to ``TailManager.pause_reads``,
  ingest/parser_main.py:111-112 — one drain interval, no polling gap);
- ``resume`` fires after reconnect+drain and the stream completes.

Backends: fake-redis (server kill/restart severs clients, stream+PEL
survive — AOF semantics), AMQP connection churn (fake_pika
``kill_connections``: unacked requeued at the front, stale acks dropped),
and the durable spool as the control (no broker process exists to die;
an "outage" is a pump gap and must be a perfect no-op).

Run via ``./run_tests.sh --broker``.
"""

import time

import numpy as np
import pytest

from apmbackend_tpu.config import default_config
from apmbackend_tpu.transport.base import QueueManager

from fake_pika import FakeBroker, make_fake_pika
from fake_redis import FakeRedisServer, make_fake_redis
from test_chaos_harness import assert_snapshots_equal, make_stream

CLAIM_IDLE_MS = 500


def wait_for(predicate, timeout=20.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


def _worker_over(factory, resume_path, *, transport=None):
    """A real at-least-once WorkerApp whose QueueManager runs on the given
    channel factory (the test owns the broker seam)."""
    from apmbackend_tpu.runtime.module_base import ModuleRuntime
    from apmbackend_tpu.runtime.worker import WorkerApp

    cfg = default_config()
    eng = cfg["tpuEngine"]
    eng["serviceCapacity"] = 32
    eng["samplesPerBucket"] = 64
    eng["deliveryMode"] = "atLeastOnce"
    eng["resumeFileFullPath"] = resume_path
    cfg["streamCalcZScore"]["defaults"] = [
        {"LAG": 6, "THRESHOLD": 3.0, "INFLUENCE": 0.1}]
    cfg["streamCalcStats"]["resumeFileSaveFrequencyInSeconds"] = 3600
    cfg["streamProcessAlerts"]["alertsResumeFileFullPath"] = None
    cfg["logDir"] = None
    rt = ModuleRuntime("tpuEngine", config=cfg, install_signals=False,
                       console_log=False)
    rt.qm = QueueManager(factory, 3600, logger=rt.logger,
                         transport_config=transport or {})
    worker = WorkerApp(rt)
    return worker, rt


def _absorbed(worker) -> int:
    with worker._driver_lock:
        return int(np.asarray(worker.driver.state.stats.counts).sum())


# -- fake redis: broker process death ------------------------------------------


def _redis_channel(server, **kw):
    from apmbackend_tpu.transport.redis_streams import RedisStreamsChannel

    kw.setdefault("redis_module", make_fake_redis(server))
    kw.setdefault("claim_idle_ms", CLAIM_IDLE_MS)
    kw.setdefault("reconnect_base_backoff_s", 0.0)
    kw.setdefault("reconnect_max_backoff_s", 0.0)
    return RedisStreamsChannel("redis://fake", **kw)


def _drain_redis(worker, cons_ch, server, total, timeout=30.0):
    """Pump delivery + epoch commits until the stream is fully settled:
    backlog empty, PEL empty, nothing left unacked in the worker."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        n = cons_ch.pump_once()
        if n:
            continue
        worker.save_state()  # commit the open epoch -> acks flow
        cons_ch.pump_once()  # ...and let the drain/ack retry settle
        if (cons_ch.queue_lag("transactions") == 0
                and server.pending_count("transactions") == 0):
            return
        server.advance_ms(CLAIM_IDLE_MS + 10)  # age the PEL: claim the rest
    raise TimeoutError(
        f"stream never settled: lag={cons_ch.queue_lag('transactions')} "
        f"pel={server.pending_count('transactions')} absorbed={_absorbed(worker)}")


def _golden_redis(tmp_path, lines):
    server = FakeRedisServer()
    res = str(tmp_path / "golden.npz")
    chans = {}

    def factory(kind):
        chans[kind] = _redis_channel(server)
        return chans[kind]

    worker, rt = _worker_over(factory, res)
    prod_qm = QueueManager(lambda d: _redis_channel(server), 3600)
    prod = prod_qm.get_queue("transactions", "p")
    for line in lines:
        prod.write_line(line)
    _drain_redis(worker, chans["c"], server, len(lines))
    assert _absorbed(worker) == len(lines)
    rt.stop_timers()
    return res


@pytest.mark.slow
def test_redis_broker_killed_midstream_recovery_bit_identical(tmp_path):
    lines = make_stream(n_labels=4, per_label=50)
    golden_res = _golden_redis(tmp_path, lines)

    server = FakeRedisServer()
    chaos_res = str(tmp_path / "chaos.npz")
    chans = {}

    def factory(kind):
        chans[kind] = _redis_channel(server)
        return chans[kind]

    worker, rt = _worker_over(factory, chaos_res)
    # the cap bounds memory; it must be sized ABOVE the expected outage
    # write volume for a loss-free episode (overflow past it is the
    # counted-drop policy, proved in the next test)
    cap = 128
    prod_qm = QueueManager(lambda d: _redis_channel(server, stream_maxlen=100000),
                           3600, transport_config={"producerBufferMaxLines": cap})
    events = []
    prod_qm.on("pause", lambda: events.append("pause"))
    prod_qm.on("resume", lambda: events.append("resume"))
    prod = prod_qm.get_queue("transactions", "p")
    cons = chans["c"]

    half = len(lines) // 2
    for line in lines[:half]:
        prod.write_line(line)
    # deliver ~half in bounded batches, commit ONE epoch mid-way, and leave
    # a delivered-but-unacked window on the PEL for the outage to threaten
    delivered = 0
    while delivered < half // 2:
        delivered += cons.deliver(8)
    worker.save_state()
    while delivered < half:
        delivered += cons.deliver(8)
    unacked_at_kill = server.pending_count("transactions")
    assert unacked_at_kill > 0  # the window the outage puts at risk

    server.kill()  # --- BROKER DEATH ---

    # the producer keeps writing into the outage: sends refuse, the pause
    # engages on the FIRST refused write (no drain-interval lag), and the
    # buffer stays bounded at every instant
    buffer_maxima = []
    for line in lines[half:]:
        prod.write_line(line)
        buffer_maxima.append(prod.buffer_count())
    assert events and events[0] == "pause"
    assert max(buffer_maxima) <= cap
    assert cons.pump_once() == 0  # consumer fails soft while down

    server.restart()  # --- RECOVERY ---

    # producer pump reconnects, sees the drained backlog, fires drain ->
    # retry_buffer -> resume; the buffered tail lands on the stream
    assert wait_for(lambda: (prod_qm.producer_channel.pump_once(), "resume" in events)[1],
                    timeout=10)
    assert prod.buffer_count() == 0

    # age the PEL past claim_idle BEFORE the next epoch commit: the at-risk
    # window must come back through XAUTOCLAIM and be deduped (the
    # alo-reconnect-drops-unacked mutant is the protocol that skips this)
    server.advance_ms(CLAIM_IDLE_MS + 10)
    while cons.pump_once():
        pass
    assert worker._deduped_total >= unacked_at_kill

    _drain_redis(worker, cons, server, len(lines))
    rt.stop_timers()

    # zero loss, zero double-effect: every distinct line absorbed once...
    assert _absorbed(worker) == len(lines)
    # ...the delivered-but-unacked window WAS redelivered (XAUTOCLAIM after
    # the restart) and every copy deduped by msg_id, not re-absorbed
    assert worker._deduped_total >= unacked_at_kill
    # ...and the final windowed state equals the crash-free run exactly
    assert_snapshots_equal(golden_res, chaos_res)


@pytest.mark.slow
def test_redis_outage_producer_overflow_degrades_loudly(tmp_path):
    """Outage outlasting the buffer: eviction is counted, never silent."""
    from apmbackend_tpu.obs.decisions import get_decisions

    server = FakeRedisServer()
    cap = 8
    prod_qm = QueueManager(lambda d: _redis_channel(server), 3600,
                           transport_config={"producerBufferMaxLines": cap})
    overflows = []
    prod_qm.on("overflow", lambda q, n: overflows.append(n))
    prod = prod_qm.get_queue("transactions", "p")
    server.kill()
    for i in range(cap * 3):
        prod.write_line(f"line{i}")
        assert prod.buffer_count() <= cap
    assert sum(overflows) == cap * 2
    assert any(d.get("kind") == "producer_buffer_overflow"
               for d in get_decisions().recent(64))


# -- AMQP: connection churn ----------------------------------------------------


def _amqp_factory(mod, channels, **kw):
    from apmbackend_tpu.transport.amqp import AmqpChannel

    def factory(kind):
        ch = AmqpChannel("amqp://fake", direction=kind, pika_module=mod,
                         poll_interval_s=0.005, **kw)
        channels.append(ch)
        return ch

    return factory


def _drain_amqp(worker, broker, total, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        worker.save_state()  # absorb + commit whatever has arrived
        if (_absorbed(worker) >= total
                and broker.depth("transactions") == 0
                and not worker._epoch_tokens):
            return
        time.sleep(0.02)
    raise TimeoutError(
        f"amqp stream never settled: absorbed={_absorbed(worker)}/{total} "
        f"depth={broker.depth('transactions')}")


def _golden_amqp(tmp_path, lines):
    broker = FakeBroker(block_at=10**9, unblock_at=10)
    mod = make_fake_pika(broker)
    channels = []
    res = str(tmp_path / "golden-amqp.npz")
    worker, rt = _worker_over(
        _amqp_factory(mod, channels, prefetch_count=16), res)
    prod_qm = QueueManager(_amqp_factory(mod, channels), 3600)
    prod = prod_qm.get_queue("transactions", "p")
    for line in lines:
        prod.write_line(line)
    _drain_amqp(worker, broker, len(lines))
    rt.stop_timers()
    for ch in channels:
        ch.close()
    return res


@pytest.mark.slow
def test_amqp_connection_churn_midstream_recovery_bit_identical(tmp_path):
    lines = make_stream(n_labels=3, per_label=40, seed=5)
    golden_res = _golden_amqp(tmp_path, lines)

    broker = FakeBroker(block_at=10**9, unblock_at=10)
    mod = make_fake_pika(broker)
    channels = []
    chaos_res = str(tmp_path / "chaos-amqp.npz")
    # prefetch bounds in-flight unacked at 16: the broker stops delivering
    # until acks flow, so a delivered-but-unacked window deterministically
    # exists when the churn hits
    worker, rt = _worker_over(
        _amqp_factory(mod, channels, prefetch_count=16), chaos_res)
    prod_qm = QueueManager(_amqp_factory(mod, channels), 3600,
                           transport_config={"producerBufferMaxLines": 256})
    prod = prod_qm.get_queue("transactions", "p")

    half = len(lines) // 2
    for line in lines[:half]:
        prod.write_line(line)
    assert wait_for(lambda: len(worker._epoch_tokens) >= 16)  # prefetch full
    worker.save_state()  # one committed epoch: acks flow, delivery resumes
    assert wait_for(lambda: len(worker._epoch_tokens) >= 8)
    assert worker._epoch_tokens  # delivered-but-unacked window at risk

    broker.kill_connections()  # --- CONNECTION CHURN ---
    for line in lines[half:]:
        prod.write_line(line)
        assert prod.buffer_count() <= 256

    # both directions reconnect; the requeued unacked window is redelivered
    # (redelivered flag + original msg_id) and deduped, the tail delivers
    _drain_amqp(worker, broker, len(lines))
    rt.stop_timers()
    for ch in channels:
        ch.close()

    assert _absorbed(worker) == len(lines)
    assert worker._deduped_total >= 1  # churn redelivered the unacked window
    assert_snapshots_equal(golden_res, chaos_res)


# -- spool: the control (no broker process exists to die) ----------------------


@pytest.mark.slow
def test_spool_control_outage_is_a_noop(tmp_path):
    """The durable-spool fabric has no broker process: the same drill is a
    pump gap, and the result must STILL be bit-identical to golden — pinning
    that the harness itself (feed order, epoch timing) adds no noise."""
    from apmbackend_tpu.transport.spool import SpoolChannel

    lines = make_stream(n_labels=3, per_label=40, seed=9)

    def run(spool_dir, res, with_gap):
        spools = []
        worker_chans = {}

        def worker_factory(kind):
            ch = SpoolChannel(spool_dir)
            spools.append(ch)
            worker_chans[kind] = ch
            return ch

        def prod_factory(kind):
            ch = SpoolChannel(spool_dir)
            spools.append(ch)
            return ch

        worker, rt = _worker_over(worker_factory, res)
        prod_qm = QueueManager(prod_factory, 3600,
                               transport_config={"producerBufferMaxLines": 256})
        prod = prod_qm.get_queue("transactions", "p")
        cons = worker_chans["c"]
        half = len(lines) // 2
        for line in lines[:half]:
            prod.write_line(line)
        delivered = 0
        while delivered < half // 2:
            delivered += cons.deliver(16)
        worker.save_state()
        if with_gap:
            time.sleep(0.05)  # the "outage": nothing to kill, just a stall
        for line in lines[half:]:
            prod.write_line(line)
            assert prod.buffer_count() <= 256
        while delivered < len(lines):
            delivered += cons.deliver(64)
        worker.save_state()
        rt.stop_timers()
        for ch in spools:
            ch.close()
        assert _absorbed(worker) == len(lines)
        assert cons.acked_count("transactions") == len(lines)

    gres = str(tmp_path / "golden-spool.npz")
    cres = str(tmp_path / "gap-spool.npz")
    run(str(tmp_path / "sp-golden"), gres, with_gap=False)
    run(str(tmp_path / "sp-gap"), cres, with_gap=True)
    assert_snapshots_equal(gres, cres)
