"""Device stats engine vs the float64 golden oracle (reference semantics)."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apmbackend_tpu.ops import stats as dstats
from apmbackend_tpu.ops.registry import ServiceRegistry

from golden import GoldenStats

BASE_LABEL = 170_000_000  # ~2023 in 10s-bucket units


def make_cfg(capacity=8, cap=64, dtype=jnp.float64):
    return dstats.StatsConfig(capacity=capacity, samples_per_bucket=cap, dtype=dtype)


def drive_both(events, cfg):
    """events: list of (server, service, end_ts_ms, elapsed). Returns
    (golden emissions, device emissions) as lists of dicts keyed identically."""
    golden = GoldenStats()
    reg = ServiceRegistry(cfg.capacity)
    state = dstats.init_state(cfg)
    tick = jax.jit(dstats.tick, static_argnums=1)
    ingest = jax.jit(dstats.ingest, static_argnums=1)

    g_rows, d_rows = [], []
    for server, service, ts, elapsed in events:
        label = int(dstats.bucket_label(ts))
        g_rows.extend(golden.add(server, service, ts, elapsed))
        if label > int(state.latest_bucket):
            res, state = tick(state, cfg, label)
            edge = dstats.edge_ts_ms(label, cfg)
            for row in range(reg.count):
                srv, svc = reg.key_of(row)
                d_rows.append(
                    {
                        "ts": edge, "server": srv, "service": svc,
                        "tpm": float(res.tpm[row]), "average": float(res.average[row]),
                        "per75": float(res.per75[row]), "per95": float(res.per95[row]),
                        "count": int(res.count[row]),
                    }
                )
        row = reg.lookup_or_add(server, service)
        state = ingest(
            state, cfg,
            jnp.array([row], jnp.int32),
            jnp.array([label], jnp.int32),
            jnp.array([elapsed], cfg.dtype),
            jnp.array([True]),
        )
    return g_rows, d_rows


def assert_rows_match(g_rows, d_rows):
    gk = {(r["ts"], r["server"], r["service"]): r for r in g_rows}
    dk = {(r["ts"], r["server"], r["service"]): r for r in d_rows}
    assert set(gk) == set(dk)
    for key, g in gk.items():
        d = dk[key]
        for f in ("tpm", "average", "per75", "per95"):
            gv, dv = g[f], d[f]
            if math.isnan(gv):
                assert math.isnan(dv), (key, f, gv, dv)
            else:
                assert gv == pytest.approx(dv, rel=1e-9), (key, f, gv, dv)
        assert g["count"] == d["count"], key


def test_single_key_basic_window():
    cfg = make_cfg()
    events = []
    # populate 40 consecutive buckets with 3 tx each for one key
    for i in range(40):
        ts = (BASE_LABEL + i) * 10000 + 1234
        for e in (100, 200, 300):
            events.append(("srv1", "svcA", ts, e + i))
    g, d = drive_both(events, cfg)
    assert len(g) > 0
    assert_rows_match(g, d)


def test_multi_key_sparse_traffic():
    rng = np.random.RandomState(42)
    cfg = make_cfg(capacity=8, cap=64)
    keys = [("s1", "a"), ("s1", "b"), ("s2", "a"), ("s2", "c")]
    events = []
    label = BASE_LABEL
    for _ in range(300):
        label += int(rng.rand() < 0.3)  # advance bucket sometimes
        srv, svc = keys[rng.randint(len(keys))]
        ts = label * 10000 + rng.randint(0, 9999)
        events.append((srv, svc, ts, int(rng.randint(1, 5000))))
    g, d = drive_both(events, cfg)
    assert_rows_match(g, d)


def test_bucket_gap_clears_stale_slots():
    cfg = make_cfg()
    events = [("s", "x", BASE_LABEL * 10000, 100)]
    # jump far beyond the ring size: all old data must vanish from stats
    events.append(("s", "x", (BASE_LABEL + 100) * 10000, 500))
    events.append(("s", "x", (BASE_LABEL + 101) * 10000, 700))
    g, d = drive_both(events, cfg)
    assert_rows_match(g, d)


def test_percentile_duplicates_and_singletons():
    cfg = make_cfg()
    events = []
    ts0 = BASE_LABEL * 10000
    for e in (5, 5, 5, 9):  # duplicates kept (binaryConcat duplicate=true)
        events.append(("s", "dup", ts0, e))
    events.append(("s", "single", ts0, 42))
    events.append(("s", "dup", (BASE_LABEL + 1) * 10000, 1))  # trigger tick
    g, d = drive_both(events, cfg)
    assert_rows_match(g, d)


def test_old_label_data_dropped_not_corrupting():
    """A label older than the ring must not alias into a live slot."""
    cfg = make_cfg()
    NB = cfg.num_buckets
    label = BASE_LABEL
    state = dstats.init_state(cfg)
    res, state = dstats.tick(state, cfg, label)
    state = dstats.ingest(
        state, cfg,
        jnp.array([0], jnp.int32),
        jnp.array([label - NB], jnp.int32),  # aliases slot of `label`
        jnp.array([999.0], cfg.dtype),
        jnp.array([True]),
    )
    assert int(jnp.sum(state.counts)) == 0  # dropped entirely


def test_sample_overflow_flags_and_keeps_counts():
    cfg = make_cfg(capacity=2, cap=4)
    label = BASE_LABEL
    state = dstats.init_state(cfg)
    _, state = dstats.tick(state, cfg, label)
    n = 10  # > CAP
    state = dstats.ingest(
        state, cfg,
        jnp.zeros(n, jnp.int32),
        jnp.full(n, label, jnp.int32),
        jnp.arange(1, n + 1, dtype=cfg.dtype),
        jnp.ones(n, bool),
    )
    # advance past the buffer zone so `label` lands inside [latest-36, latest-6]
    res, state = dstats.tick(state, cfg, label + cfg.buffer_sz + 1)
    assert int(res.count[0]) == 10
    assert bool(res.overflowed[0])
    assert float(res.average[0]) == pytest.approx(5.5)  # counts/sums stay exact
    # percentile computed over first CAP samples [1..4]
    assert not math.isnan(float(res.per75[0]))


def test_batched_ingest_equals_sequential():
    """One big scatter with duplicate keys == many single ingests."""
    cfg = make_cfg(capacity=4, cap=32)
    label = BASE_LABEL
    rng = np.random.RandomState(7)
    rows = rng.randint(0, 4, size=50).astype(np.int32)
    elaps = rng.randint(1, 100, size=50).astype(np.float64)

    st_a = dstats.init_state(cfg)
    _, st_a = dstats.tick(st_a, cfg, label)
    st_a = dstats.ingest(st_a, cfg, rows, np.full(50, label, np.int32), elaps, np.ones(50, bool))

    st_b = dstats.init_state(cfg)
    _, st_b = dstats.tick(st_b, cfg, label)
    for i in range(50):
        st_b = dstats.ingest(
            st_b, cfg,
            np.array([rows[i]]), np.array([label], np.int32),
            np.array([elaps[i]]), np.array([True]),
        )
    assert np.array_equal(np.asarray(st_a.counts), np.asarray(st_b.counts))
    assert np.allclose(np.asarray(st_a.sums), np.asarray(st_b.sums))
    # sample multisets per (row, slot) must match (order within bucket may differ)
    sa = np.sort(np.nan_to_num(np.asarray(st_a.samples), nan=-1), axis=-1)
    sb = np.sort(np.nan_to_num(np.asarray(st_b.samples), nan=-1), axis=-1)
    assert np.allclose(sa, sb)


def test_quantize_half_up():
    x = jnp.array([0.25, 0.15, -0.25, 1.05, float("nan")])
    q = dstats.quantize_half_up(x, 1)
    assert float(q[0]) == 0.3
    assert float(q[2]) == -0.2
    assert math.isnan(float(q[4]))


def test_grow_state_preserves():
    cfg = make_cfg(capacity=2)
    state = dstats.init_state(cfg)
    _, state = dstats.tick(state, cfg, BASE_LABEL)
    state = dstats.ingest(
        state, cfg, jnp.array([1], jnp.int32), jnp.array([BASE_LABEL], jnp.int32),
        jnp.array([50.0], cfg.dtype), jnp.array([True]),
    )
    grown, gcfg = dstats.grow_state(state, cfg, 8)
    assert gcfg.capacity == 8
    assert grown.counts.shape[0] == 8
    assert int(jnp.sum(grown.counts)) == 1
    res, _ = dstats.tick(grown, gcfg, BASE_LABEL + gcfg.buffer_sz + 1)
    assert int(res.count[1]) == 1 and math.isnan(float(res.average[2]))


def test_tick_non_increasing_label_is_safe():
    """A stale/equal label must not corrupt the ring (clamped to latest)."""
    cfg = make_cfg(capacity=2)
    state = dstats.init_state(cfg)
    _, state = dstats.tick(state, cfg, BASE_LABEL)
    state = dstats.ingest(
        state, cfg, jnp.array([0], jnp.int32), jnp.array([BASE_LABEL], jnp.int32),
        jnp.array([50.0], cfg.dtype), jnp.array([True]),
    )
    before = np.asarray(state.counts).copy()
    _, state = dstats.tick(state, cfg, BASE_LABEL - 5)  # regressed label
    assert int(state.latest_bucket) == BASE_LABEL
    assert np.array_equal(np.asarray(state.counts), before)


def test_reservoir_estimate_bounded_error_above_cap():
    """>>CAP samples per bucket: the reservoir keeps percentiles an unbiased
    estimate over ALL arrivals (error ~ O(1/sqrt(CAP)) in rank), where
    first-CAP truncation would be arbitrarily biased toward early arrivals."""
    cfg = make_cfg(capacity=1, cap=64, dtype=jnp.float32)
    label = BASE_LABEL
    state = dstats.init_state(cfg)
    _, state = dstats.tick(state, cfg, label)
    rng = np.random.RandomState(3)
    data = rng.uniform(0.0, 1000.0, size=5000).astype(np.float32)
    for i in range(0, len(data), 1024):
        chunk = data[i : i + 1024]
        state = dstats.ingest(
            state, cfg,
            np.zeros(len(chunk), np.int32),
            np.full(len(chunk), label, np.int32),
            chunk,
            np.ones(len(chunk), bool),
        )
    res, state = dstats.tick(state, cfg, label + cfg.buffer_sz + 1)
    assert bool(res.overflowed[0])
    assert int(res.count[0]) == 5000
    assert float(res.average[0]) == pytest.approx(float(data.mean()), rel=1e-3)
    # rank error ~ Normal(0, sqrt(.75*.25/64) ~ 5.4pp): [60th, 90th] is ~±3σ
    est = float(res.per75[0])
    lo, hi = np.percentile(data, 60), np.percentile(data, 90)
    assert lo <= est <= hi, (est, lo, hi)


def test_reservoir_not_biased_to_first_arrivals():
    """Adversarial order: CAP early small values then 10*CAP large ones.
    Truncation would report the small early value; the reservoir must reflect
    that the overwhelming majority of arrivals are large."""
    cap = 16
    cfg = make_cfg(capacity=1, cap=cap, dtype=jnp.float32)
    label = BASE_LABEL
    state = dstats.init_state(cfg)
    _, state = dstats.tick(state, cfg, label)
    data = np.concatenate(
        [np.full(cap, 1.0, np.float32), np.full(10 * cap, 100.0, np.float32)]
    )
    state = dstats.ingest(
        state, cfg,
        np.zeros(len(data), np.int32),
        np.full(len(data), label, np.int32),
        data,
        np.ones(len(data), bool),
    )
    res, _ = dstats.tick(state, cfg, label + cfg.buffer_sz + 1)
    assert bool(res.overflowed[0])
    # ~91% of arrivals are 100.0 => p75 over the reservoir must be 100.0 with
    # overwhelming probability (P[>=25% of 16 slots keep early 1.0s] is tiny);
    # deterministic: the hash makes this one fixed outcome, asserted here
    assert float(res.per75[0]) == pytest.approx(100.0)


def test_reservoir_batched_equals_sequential_above_cap():
    """Replay parity: the deterministic reservoir gives identical state whether
    samples arrive one-by-one or in one big batch (resume/replay fidelity)."""
    cfg = make_cfg(capacity=2, cap=8, dtype=jnp.float32)
    label = BASE_LABEL
    rng = np.random.RandomState(11)
    n = 120  # >> 2 rows * CAP 8
    rows = rng.randint(0, 2, size=n).astype(np.int32)
    elaps = rng.randint(1, 1000, size=n).astype(np.float32)

    st_a = dstats.init_state(cfg)
    _, st_a = dstats.tick(st_a, cfg, label)
    st_a = dstats.ingest(st_a, cfg, rows, np.full(n, label, np.int32), elaps, np.ones(n, bool))

    st_b = dstats.init_state(cfg)
    _, st_b = dstats.tick(st_b, cfg, label)
    for i in range(n):
        st_b = dstats.ingest(
            st_b, cfg,
            np.array([rows[i]]), np.array([label], np.int32),
            np.array([elaps[i]]), np.array([True]),
        )
    assert np.array_equal(np.asarray(st_a.counts), np.asarray(st_b.counts))
    # exact slot-for-slot equality, not just multiset: determinism is the claim
    sa = np.nan_to_num(np.asarray(st_a.samples), nan=-1)
    sb = np.nan_to_num(np.asarray(st_b.samples), nan=-1)
    assert np.array_equal(sa, sb)


def test_topk_percentiles_exact_vs_sort():
    """topk path must be bit-identical to sort + reference index math across
    fill levels, duplicates, singletons, empties, and both dtypes."""
    rng = np.random.RandomState(17)
    for dtype in (np.float32, np.float64):
        S, N = 64, 31 * 8
        window = np.full((S, N), np.nan, dtype)
        counts = rng.randint(0, N + 1, S).astype(np.int32)
        counts[0], counts[1], counts[2], counts[3] = 0, 1, 2, N
        for s in range(S):
            vals = rng.randint(1, 500, counts[s]).astype(dtype)  # many ties
            window[s, : counts[s]] = vals
        w = jnp.asarray(window)
        n = jnp.asarray(counts)
        srt = jnp.sort(w, axis=-1)
        for p in (75, 95):
            want = np.asarray(dstats.reference_percentile_sorted(srt, n, p))
            got = np.asarray(dstats.topk_percentiles(w, n, (p,))[0])
            same = (want == got) | (np.isnan(want) & np.isnan(got))
            assert same.all(), (dtype, p, np.nonzero(~same), want[~same], got[~same])


def test_topk_rejects_low_percentile():
    w = jnp.zeros((2, 8))
    n = jnp.array([4, 4], jnp.int32)
    with pytest.raises(ValueError):
        dstats.topk_percentiles(w, n, (50, 95))


def test_tick_auto_topk_matches_sort_impl():
    """Full tick through auto (=topk) vs explicit sort: identical emissions."""
    cfg_t = make_cfg(capacity=8, cap=16, dtype=jnp.float32)
    cfg_s = cfg_t._replace(percentile_impl="sort")
    rng = np.random.RandomState(29)
    label = BASE_LABEL
    st_t, st_s = dstats.init_state(cfg_t), dstats.init_state(cfg_s)
    _, st_t = dstats.tick(st_t, cfg_t, label)
    _, st_s = dstats.tick(st_s, cfg_s, label)
    for k in range(10):
        n = 40
        rows = rng.randint(0, 8, n).astype(np.int32)
        elaps = rng.randint(1, 900, n).astype(np.float32)
        labs = np.full(n, label + k, np.int32)
        ok = np.ones(n, bool)
        st_t = dstats.ingest(st_t, cfg_t, rows, labs, elaps, ok)
        st_s = dstats.ingest(st_s, cfg_s, rows, labs, elaps, ok)
        res_t, st_t = dstats.tick(st_t, cfg_t, label + k + 1)
        res_s, st_s = dstats.tick(st_s, cfg_s, label + k + 1)
        for f in ("tpm", "average", "per75", "per95"):
            a, b = np.asarray(getattr(res_t, f)), np.asarray(getattr(res_s, f))
            same = (a == b) | (np.isnan(a) & np.isnan(b))
            assert same.all(), (f, k)


@pytest.mark.parametrize("seed", [101, 202, 303])
def test_fuzz_random_stream_vs_oracle(seed):
    """Property fuzz: randomized streams with out-of-order arrivals, label
    jumps, bursts, and within-batch duplicates must match the float64 oracle
    on every emitted window (counts exact; percentiles exact below CAP)."""
    rng = np.random.RandomState(seed)
    cfg = make_cfg(capacity=6, cap=256)  # CAP high: stays in exact mode
    keys = [(f"s{i % 3}", f"svc{i}") for i in range(6)]
    events = []
    label = BASE_LABEL
    for _ in range(500):
        r = rng.rand()
        if r < 0.25:
            label += 1
        elif r < 0.30:
            label += int(rng.randint(2, 9))  # jump (gap clears stale slots)
        srv, svc = keys[rng.randint(len(keys))]
        # out-of-order: sometimes stamp into an older (still-live) bucket
        lbl = label - int(rng.randint(0, 5)) if rng.rand() < 0.2 else label
        ts = lbl * 10000 + int(rng.randint(0, 9999))
        events.append((srv, svc, ts, int(rng.randint(1, 5000))))
    g, d = drive_both(events, cfg)
    assert len(d) > 50
    assert_rows_match(g, d)


def test_weighted_pooling_keeps_burst_mass():
    """Cross-bucket skew: a burst bucket with 100x the arrivals of the quiet
    buckets must dominate the pooled window percentile even though every
    bucket stores at most CAP samples (the importance-weighted pooling)."""
    cap = 16
    cfg = make_cfg(capacity=1, cap=cap, dtype=jnp.float32)
    label = BASE_LABEL
    state = dstats.init_state(cfg)
    _, state = dstats.tick(state, cfg, label)

    def pour(lbl, n, value):
        s = state_box[0]
        for i in range(0, n, 512):
            m = min(512, n - i)
            s = dstats.ingest(
                s, cfg,
                np.zeros(m, np.int32), np.full(m, lbl, np.int32),
                np.full(m, value, np.float32), np.ones(m, bool),
            )
        state_box[0] = s

    state_box = [state]
    # 10 quiet buckets: 64 arrivals each at ~100 ms
    for k in range(10):
        pour(label - k, 64, 100.0)
    # 1 burst bucket: 6400 arrivals at ~1000 ms => ~91% of all arrivals
    pour(label, 6400, 1000.0)
    res, _ = dstats.tick(state_box[0], cfg, label + cfg.buffer_sz + 1)
    assert bool(res.overflowed[0])
    assert int(res.count[0]) == 10 * 64 + 6400
    # p75 and p95 both sit deep inside the burst's arrival mass
    assert float(res.per75[0]) == pytest.approx(1000.0), float(res.per75[0])
    assert float(res.per95[0]) == pytest.approx(1000.0), float(res.per95[0])
    # the pooled average stays exact regardless
    want_avg = (10 * 64 * 100.0 + 6400 * 1000.0) / (10 * 64 + 6400)
    assert float(res.average[0]) == pytest.approx(want_avg, rel=1e-5)


def test_weighted_percentiles_reduce_to_reference_at_unit_weight():
    """With every weight exactly 1 the weighted path must be bit-identical to
    reference_percentile_sorted for all fill levels (the sub-CAP contract)."""
    rng = np.random.RandomState(7)
    S, K = 64, 31 * 8
    window = np.full((S, K), np.nan, np.float32)
    counts = rng.randint(0, K + 1, S).astype(np.int32)
    counts[0], counts[1], counts[2] = 0, 1, K
    for s in range(S):
        window[s, : counts[s]] = rng.randint(1, 500, counts[s]).astype(np.float32)
    w = jnp.asarray(window)
    n = jnp.asarray(counts)
    weights = jnp.where(jnp.isnan(w), 0.0, 1.0).astype(jnp.float32)
    srt = jnp.sort(w, axis=-1)
    for p in (75, 95):
        want = np.asarray(dstats.reference_percentile_sorted(srt, n, p))
        got = np.asarray(
            dstats.weighted_reference_percentiles(w, weights, n, (p,))[0]
        )
        same = (want == got) | (np.isnan(want) & np.isnan(got))
        assert same.all(), (p, np.nonzero(~same), want[~same], got[~same])


def test_advance_one_equals_advance_jump():
    """The staged per-label clear (advance_one, one-slot DUS) composed over a
    label jump must land bit-identically on _advance's whole-buffer select —
    including jumps larger than the ring (only the last NB labels matter)."""
    from apmbackend_tpu.ops import stats as dstats

    cfg = dstats.StatsConfig(capacity=8, window_sz=5, buffer_sz=2,
                             samples_per_bucket=4)
    NB = cfg.num_buckets
    rng = np.random.RandomState(0)

    def seeded_state(label):
        st = dstats.init_state(cfg)
        st = st._replace(latest_bucket=jnp.asarray(label, jnp.int32))
        for lbl in range(label - NB + 1, label + 1):
            rows = rng.randint(0, 8, 16).astype(np.int32)
            st = dstats.ingest(st, cfg, rows, np.full(16, lbl, np.int32),
                               (50 + rng.rand(16)).astype(np.float32),
                               np.ones(16, bool))
        return st

    for jump in (1, 3, NB - 1, NB, NB + 5):
        base = seeded_state(1000)
        target = 1000 + jump
        a = dstats._advance(base, cfg, jnp.asarray(target, jnp.int32))
        b = base
        for lbl in range(max(1001, target - NB + 1), target + 1):
            b = dstats.advance_one(b, cfg, lbl)
        assert int(b.latest_bucket) == int(a.latest_bucket) == target
        np.testing.assert_array_equal(np.asarray(a.counts), np.asarray(b.counts))
        np.testing.assert_array_equal(np.asarray(a.sums), np.asarray(b.sums))
        np.testing.assert_array_equal(np.asarray(a.nsamples), np.asarray(b.nsamples))
        np.testing.assert_array_equal(
            np.nan_to_num(np.asarray(a.samples), nan=-1),
            np.nan_to_num(np.asarray(b.samples), nan=-1),
            err_msg=f"jump {jump}",
        )


def test_staged_step_label_jump_and_stale_label():
    """make_engine_step across a label gap (> buffer) and a stale label must
    match the single-program engine_tick sequence bitwise."""
    import jax

    from apmbackend_tpu.pipeline import (
        engine_init, engine_tick, make_demo_engine, make_engine_step,
    )

    cfg, _, params = make_demo_engine(8, 4, [(4, 3.0, 0.2)])
    sa = engine_init(cfg)
    sb = engine_init(cfg)
    staged = make_engine_step(cfg)
    mono = jax.jit(engine_tick, static_argnums=1)
    labels = [1001, 1002, 1012, 1012, 1013]  # gap of 10, then a stale repeat
    for lbl in labels:
        ea, sa = staged(sa, lbl, params)
        eb, sb = mono(sb, cfg, lbl, params)
        np.testing.assert_array_equal(np.asarray(ea.count), np.asarray(eb.count))
        np.testing.assert_array_equal(
            np.nan_to_num(np.asarray(ea.average)), np.nan_to_num(np.asarray(eb.average))
        )
    np.testing.assert_array_equal(
        np.asarray(sa.stats.latest_bucket), np.asarray(sb.stats.latest_bucket)
    )
    np.testing.assert_array_equal(
        np.nan_to_num(np.asarray(sa.stats.samples), nan=-1),
        np.nan_to_num(np.asarray(sb.stats.samples), nan=-1),
    )


@pytest.mark.skipif(
    not __import__("apmbackend_tpu.native", fromlist=["have_native_percentiles"]).have_native_percentiles(),
    reason="native toolchain unavailable",
)
class TestNativePercentiles:
    """The nth_element kernel (native/percentile.cpp) vs the jitted exact
    paths: same order statistics, same reference index math, same NaN/empty
    semantics — and the staged executor's host-percentile mode end to end."""

    def test_kernel_matches_topk_fuzz(self):
        from apmbackend_tpu.native import window_percentiles_native
        from apmbackend_tpu.ops import stats as dstats

        rng = np.random.RandomState(42)
        for trial in range(6):
            S, NB, CAP = 33, 9, 8
            samples = (rng.rand(S, NB, CAP) * 1000).astype(np.float32)
            samples[rng.rand(S, NB, CAP) < 0.35] = np.nan
            samples[3] = np.nan  # empty row
            if trial % 2:  # exercise tie-heavy data (take_pair neighbors equal)
                samples = np.round(samples / 100) * 100
            mask = np.zeros(NB, bool)
            mask[rng.choice(NB, 5, replace=False)] = True
            native = window_percentiles_native(samples, mask, (75, 95))
            masked = np.where(mask[None, :, None], samples, np.nan).reshape(S, NB * CAP)
            stored = np.sum(~np.isnan(masked), axis=1).astype(np.int32)
            p75, p95 = dstats.topk_percentiles(
                jnp.asarray(masked), jnp.asarray(stored), (75, 95)
            )
            np.testing.assert_array_equal(
                np.nan_to_num(native[:, 0], nan=-1), np.nan_to_num(np.asarray(p75), nan=-1)
            )
            np.testing.assert_array_equal(
                np.nan_to_num(native[:, 1], nan=-1), np.nan_to_num(np.asarray(p95), nan=-1)
            )

    def test_kernel_counts_path_matches_full_scan_fuzz(self):
        """The prefix-bounded gather (counts panel) must select the exact
        same percentiles as the full NaN scan on prefix-shaped reservoirs —
        the layout stats.ingest actually produces (arrivals fill positions
        in order; reservoir replacement stays inside the prefix)."""
        from apmbackend_tpu.native import window_percentiles_native

        rng = np.random.RandomState(7)
        for trial in range(6):
            S, NB, CAP = 41, 9, 8
            samples = np.full((S, NB, CAP), np.nan, np.float32)
            counts = np.zeros((S, NB), np.int32)
            for s in range(S):
                for b in range(NB):
                    n = int(rng.randint(0, CAP + 1))
                    counts[s, b] = n
                    vals = (rng.rand(n) * 1000).astype(np.float32)
                    if trial % 2:
                        vals = np.round(vals / 100) * 100  # tie-heavy
                    samples[s, b, :n] = vals
            mask = np.zeros(NB, bool)
            mask[rng.choice(NB, 5, replace=False)] = True
            full = window_percentiles_native(samples, mask, (75, 95))
            fast = window_percentiles_native(samples, mask, (75, 95), counts)
            np.testing.assert_array_equal(
                np.nan_to_num(full, nan=-1), np.nan_to_num(fast, nan=-1)
            )

    def test_staged_native_matches_topk_engine(self):
        """Full staged engine: the native-percentile mode must emit the same
        wire values as the in-program topk mode tick for tick."""
        from apmbackend_tpu.pipeline import (
            engine_init, engine_ingest, make_demo_engine, make_engine_step,
        )

        cfg, _, params = make_demo_engine(32, 8, [(4, 3.0, 0.2)])
        assert cfg.stats.percentile_impl == "auto"
        ingest = jax.jit(engine_ingest, static_argnums=1)

        def drive(cfg_used):
            rng = np.random.RandomState(7)
            state = engine_init(cfg_used)
            step = make_engine_step(cfg_used)
            label, out = 1000, []
            for _ in range(12):
                label += 1
                e, state = step(state, label, params)
                out.append(jax.device_get(e.average))
                rows = rng.randint(0, 32, 96).astype(np.int32)
                state = ingest(state, cfg_used, rows, np.full(96, label, np.int32),
                               (100 + 100 * rng.rand(96)).astype(np.float32),
                               np.ones(96, bool))
            return out

        a = drive(cfg)  # auto -> native host path on CPU
        b = drive(cfg._replace(stats=cfg.stats._replace(percentile_impl="topk")))
        for t, (x, y) in enumerate(zip(a, b)):
            np.testing.assert_array_equal(
                np.nan_to_num(x), np.nan_to_num(y), err_msg=f"tick {t}"
            )

    def test_staged_native_overflow_falls_back_weighted(self):
        """When a bucket overflows its reservoir the host path must hand the
        tick to the count-weighted jitted fallback (burst mass kept) — same
        emissions as the pure jitted auto mode."""
        from apmbackend_tpu.pipeline import (
            engine_init, engine_ingest, make_demo_engine, make_engine_step,
        )

        cfg, _, params = make_demo_engine(8, 4, [(4, 3.0, 0.2)])  # CAP=4: easy overflow
        ingest = jax.jit(engine_ingest, static_argnums=1)

        def drive(cfg_used):
            rng = np.random.RandomState(11)
            state = engine_init(cfg_used)
            step = make_engine_step(cfg_used)
            label, out = 1000, []
            for _ in range(10):
                label += 1
                e, state = step(state, label, params)
                out.append((jax.device_get(e.average), bool(np.asarray(e.overflowed).any())))
                rows = rng.randint(0, 8, 128).astype(np.int32)  # 16/row >> CAP
                state = ingest(state, cfg_used, rows, np.full(128, label, np.int32),
                               (100 + 100 * rng.rand(128)).astype(np.float32),
                               np.ones(128, bool))
            return out

        a = drive(cfg)
        b = drive(cfg._replace(stats=cfg.stats._replace(percentile_impl="sort")))
        assert any(ov for _, ov in a), "the stream must actually overflow"
        for t, ((x, _), (y, _)) in enumerate(zip(a, b)):
            np.testing.assert_array_equal(
                np.nan_to_num(x), np.nan_to_num(y), err_msg=f"tick {t}"
            )

    def test_kernel_arbitrary_percentiles_vs_reference_math(self):
        """Arbitrary percentile sets (incl. adjacent ranks hitting the
        shrink-the-range boundary with take_pair — the case that once read
        an unpartitioned slot) against the reference index math."""
        from apmbackend_tpu.native import window_percentiles_native

        def ref(vals, p):
            a = np.sort(vals)
            n = len(a)
            pn = p * n
            is_int = pn % 100 == 0
            idx1 = max(pn // 100 - 1, 0) if (is_int or n == 1) else (pn - 1) // 100
            take = (not is_int) and n > 1 and (pn - 1) // 100 != n - 1
            return (a[idx1] + a[idx1 + 1]) / 2 if take else a[idx1]

        rng = np.random.RandomState(0)
        for trial in range(60):
            n_vals = rng.randint(1, 33)
            vals = (rng.rand(n_vals) * 100).astype(np.float32)
            if trial % 3 == 0:
                vals = np.round(vals / 10) * 10  # ties
            CAP = 8
            NB = (n_vals + CAP - 1) // CAP
            samples = np.full((1, NB, CAP), np.nan, np.float32)
            samples.ravel()[:n_vals] = vals
            ps = tuple(sorted(
                rng.choice(range(1, 101), rng.randint(1, 5), replace=False),
                reverse=True))
            out = window_percentiles_native(samples, np.ones(NB, bool), ps)
            for j, p in enumerate(ps):
                assert np.isclose(out[0, j], ref(vals, int(p)), rtol=1e-6), (
                    trial, p, out[0, j], vals)

    def test_staged_native_stale_label_window_anchor(self):
        """A stale re-emission tick (nl < latest) must anchor the native
        percentile mask at the POST-advance latest, exactly like the jitted
        paths — bitwise vs the topk engine through the same stale stream."""
        from apmbackend_tpu.pipeline import (
            engine_init, engine_ingest, make_demo_engine, make_engine_step,
        )

        cfg, _, params = make_demo_engine(16, 8, [(4, 3.0, 0.2)])
        ingest = jax.jit(engine_ingest, static_argnums=1)
        labels = [1001, 1002, 1003, 1004, 1005, 1002, 1006]  # stale mid-stream

        def drive(cfg_used):
            rng = np.random.RandomState(5)
            state = engine_init(cfg_used)
            step = make_engine_step(cfg_used)
            out = []
            for lbl in labels:
                e, state = step(state, lbl, params)
                out.append(jax.device_get(e.average))
                rows = rng.randint(0, 16, 64).astype(np.int32)
                state = ingest(state, cfg_used, rows,
                               np.full(64, max(lbl, 1001), np.int32),
                               (100 + 100 * rng.rand(64)).astype(np.float32),
                               np.ones(64, bool))
            return out

        a = drive(cfg)
        b = drive(cfg._replace(stats=cfg.stats._replace(percentile_impl="topk")))
        for t, (x, y) in enumerate(zip(a, b)):
            np.testing.assert_array_equal(
                np.nan_to_num(x), np.nan_to_num(y), err_msg=f"label {labels[t]}"
            )
