"""Golden-math tests: JS-quirk parity for average/std/percentile/heap/resume."""

import math

from apmbackend_tpu.utils import (
    MinHeap,
    binary_concat,
    js_average,
    js_percentile,
    js_standard_deviation,
    load_resume_file,
    save_resume_file,
)


def test_average_skips_nan():
    assert js_average([1, 2, 3]) == 2.0
    assert js_average([1, float("nan"), 3]) == 2.0
    assert js_average([None, float("nan")]) is None
    assert js_average([]) is None
    assert js_average([0, 0]) == 0.0


def test_std_population_and_zero_variance_quirk():
    vals = [2, 4, 4, 4, 5, 5, 7, 9]
    assert abs(js_standard_deviation(vals) - 2.0) < 1e-12  # population std
    # zero variance -> undefined (None), NOT 0.0 (util_methods.js:44-48)
    assert js_standard_deviation([5, 5, 5]) is None
    assert js_standard_deviation([]) is None
    assert js_standard_deviation([float("nan")]) is None
    # NaN entries skipped
    assert abs(js_standard_deviation([2, 4, float("nan"), 4, 4, 5, 5, 7, 9]) - 2.0) < 1e-12


def test_percentile_reference_index_math():
    # n=4, p=75: index = 2.0 integer -> arr[2]
    assert js_percentile([1, 2, 3, 4], 75) == 3
    # n=5, p=75: index = 2.75 -> ceil 3, not last -> (arr[3]+arr[4])/2
    assert js_percentile([1, 2, 3, 4, 5], 75) == 4.5
    # n=2, p=95: index 0.9 -> ceil 1 == n-1 -> arr[1]
    assert js_percentile([10, 20], 95) == 20
    assert js_percentile([7], 75) == 7
    assert js_percentile([], 75) is None
    assert js_percentile([1, 2, 3], 0) == 1
    assert js_percentile([1, 2, 3], 100) == 3
    # n=20, p=95: index=18 integer -> arr[18]
    arr = list(range(20))
    assert js_percentile(arr, 95) == 18
    # n=21, p=95: index=18.95 -> ceil 19, not last -> (arr[19]+arr[20])/2
    arr = list(range(21))
    assert js_percentile(arr, 95) == 19.5


def test_binary_concat_sorted_with_dups():
    dest = [1, 5, 9]
    binary_concat(dest, [5, 2, 9], duplicate=True)
    assert dest == [1, 2, 5, 5, 9, 9]
    dest2 = [1, 5]
    binary_concat(dest2, [5, 2], duplicate=False)
    assert dest2 == [1, 2, 5]


def test_minheap_pop_all_leq():
    h = MinHeap(lambda x: x["end_ts"])
    for ts in [50, 10, 30, 20, 40]:
        h.push({"end_ts": ts})
    out = h.pop_all_leq(30)
    assert [o["end_ts"] for o in out] == [10, 20, 30]
    assert h.size() == 2
    assert h.peek()["end_ts"] == 40


def test_resume_file_roundtrip(tmp_path):
    p = str(tmp_path / "x.resume")
    save_resume_file(p, {"a": [1, 2], "m": {"k": "v"}})
    assert load_resume_file(p) == {"a": [1, 2], "m": {"k": "v"}}
    # Map-wrapper interop (reference replacer format, util_methods.js:189-208)
    (tmp_path / "m.resume").write_text('{"dataType": "Map", "value": [["tx", [1]], ["fs", []]]}')
    assert load_resume_file(str(tmp_path / "m.resume")) == {"tx": [1], "fs": []}
    assert load_resume_file(str(tmp_path / "missing.resume")) is None


def test_resume_file_nan_becomes_null(tmp_path):
    p = str(tmp_path / "nan.resume")
    save_resume_file(p, {"x": float("nan"), "l": [1.0, float("inf")]})
    raw = open(p).read()
    assert "NaN" not in raw and "Infinity" not in raw
    assert load_resume_file(p) == {"x": None, "l": [1.0, None]}
