"""Fleet chaos tier: kill −9 one shard of a LIVE fleet, and rebalance a
partition under live traffic — multi-process, real worker shards over the
durable spool (``run_tests.sh --fleet``; everything here is ``slow``).

Scenarios (ISSUE 9 chaos satellite):

- **kill −9 one shard mid-stream**: the victim's partition replays from
  its own chain + spool cursor; sibling shards never notice. The fleet
  result is BIT-IDENTICAL to a crash-free golden fleet run, shard for
  shard, array for array — the single-worker crash-equivalence claim
  (PR 3/PR 7) lifted to fleet scope.
- **live-traffic quiesced rebalance**: a partition moves owners while the
  producer keeps streaming into its queue; zero loss / zero double-effect
  by exact accounting, and the merged protocol event logs replay clean
  through BOTH the per-shard conformance mirror and the fleet-level
  checker (owner-locality, quiesce, window transit).
"""

import time

import numpy as np
import pytest

from apmbackend_tpu.analysis.protocol.conformance import (
    check_fleet_trace,
    check_protocol_trace,
)
from apmbackend_tpu.parallel.fleet import FleetHarness, service_partition

from test_chaos_harness import assert_snapshots_equal

pytestmark = pytest.mark.slow

BASE = 170_000_000


def _send_labels(h, t0, t1, per_label=40, services=12, seed=0):
    rng = np.random.RandomState(seed + t0)
    for t in range(t0, t1):
        for seq in range(per_label):
            i = int(rng.randint(0, services))
            e = int(rng.randint(50, 900))
            h.send_line(
                f"tx|jvm{i % 3}|svc{i % services:03d}|c{t}-{seq}|1|"
                f"{(BASE + t) * 10000 - e}|{(BASE + t) * 10000 + seq}|{e}|Y"
            )


def _fleet(workdir, **kw):
    kw.setdefault("shards", 2)
    kw.setdefault("capacity", 64)
    kw.setdefault("save_every_s", 0.3)
    kw.setdefault("lags", "6")
    kw.setdefault("checkpoint_mode", "delta")
    kw.setdefault("event_log", True)
    return FleetHarness(str(workdir), **kw)


def test_kill9_one_shard_fleet_bit_identical_to_golden(tmp_path):
    """SIGKILL one shard of a live 2-shard fleet twice; only its partition
    replays. Every shard's final engine snapshot must equal the crash-free
    golden fleet's, bit for bit."""

    def drive(workdir, kills):
        h = _fleet(workdir)
        try:
            h.start_all()
            _send_labels(h, 0, 3)
            # kill points chosen by the victim's committed cursor so both
            # runs stream identical spools (determinism of the comparison)
            if kills:
                h.wait_acked(1, 10, timeout_s=120)
                h.kill9(1)
                h.start(1)
            _send_labels(h, 3, 6)
            if kills:
                h.wait_acked(1, 40, timeout_s=120)
                h.kill9(1)
                h.start(1)
            _send_labels(h, 6, 9)
            return h, h.finish(timeout_s=300)
        except BaseException:
            h.close()
            raise

    hg, golden = drive(tmp_path / "golden", kills=False)
    hc, chaos = drive(tmp_path / "chaos", kills=True)
    try:
        # identical spool streams per partition: same producer sequence
        assert hg.sent_per_queue == hc.sent_per_queue
        for k in (0, 1):
            assert_snapshots_equal(
                hg.procs[k].resume_path, hc.procs[k].resume_path
            )
        # the sibling shard never restarted and never deduped anything
        assert chaos[0]["deduped_total"] == golden[0]["deduped_total"] == 0
        # conformance: the victim's log replays clean across its crashes
        for k in (0, 1):
            assert check_protocol_trace(hc.shard_events(k)) == []
        assert check_fleet_trace(hc.merged_events()) == []
    finally:
        hg.close()
        hc.close()


def test_live_traffic_rebalance_zero_loss_and_conformant(tmp_path):
    """Move a partition between shards while the producer keeps writing
    into its queue: nothing lost, nothing double-absorbed, ownership
    lands on the adopter, and the protocol event logs replay clean
    through the shardmodel-derived checkers."""
    h = _fleet(tmp_path, shards=2)
    try:
        h.start_all()
        _send_labels(h, 0, 3)
        h.wait_acked(1, 10, timeout_s=120)
        # live traffic DURING the handoff: these lines land on p1's queue
        # while ownership is moving — nobody may consume them until the
        # adopter owns the partition
        _send_labels(h, 3, 4)
        res = h.rebalance(1, 1, 0)
        assert res["released"]["rows"] > 0
        assert len(res["released"]["window"]) > 0
        _send_labels(h, 4, 7)
        stats = h.finish(timeout_s=300)

        # ownership moved; the adopter serves both partitions
        assert stats[0]["owned_partitions"] == [0, 1]
        assert stats[1]["owned_partitions"] == []
        assert stats[1]["services"] == 0
        # zero loss: every produced record acked on its partition queue
        for p in (0, 1):
            q = f"transactions.p{p}"
            assert h.acked(p) == h.sent_per_queue[q], q
        # zero double-effect: every absorb unique fleet-wide
        events = h.merged_events()
        absorbed = [
            e["msg"] for e in events
            if e.get("ev") == "deliver" and not e.get("dedup")
            and not e.get("mismatch") and e.get("tx")
        ]
        assert len(absorbed) == len(set(absorbed))
        assert len(set(absorbed)) == sum(h.sent_per_queue.values())
        # and the logs ARE model paths
        for k in (0, 1):
            assert check_protocol_trace(h.shard_events(k)) == []
        assert check_fleet_trace(events) == []
        # the moved services' rows live exactly once, on the adopter
        with np.load(h.procs[0].resume_path, allow_pickle=True) as z:
            keys0 = [tuple(k.split("\x00", 1)) for k in z["registry"].tolist()]
        moved = [k for k in keys0 if service_partition(k[1], 2) == 1]
        assert moved, "no partition-1 services landed on the adopter"
    finally:
        h.close()


def test_rebalance_then_kill9_adopter_recovers_ownership(tmp_path):
    """Crash the adopter AFTER the handoff: on restart it must re-own
    BOTH partitions (ownership rides the import commit) and drain the
    backlog with zero loss."""
    h = _fleet(tmp_path, shards=2)
    try:
        h.start_all()
        _send_labels(h, 0, 3)
        h.wait_acked(0, 10, timeout_s=120)
        h.rebalance(1, 1, 0)
        _send_labels(h, 3, 5)
        time.sleep(0.4)
        h.kill9(0)
        h.start(0)
        _send_labels(h, 5, 7)
        stats = h.finish(timeout_s=300)
        assert stats[0]["owned_partitions"] == [0, 1]
        for p in (0, 1):
            assert h.acked(p) == h.sent_per_queue[f"transactions.p{p}"]
        for k in (0, 1):
            assert check_protocol_trace(h.shard_events(k)) == []
        assert check_fleet_trace(h.merged_events()) == []
    finally:
        h.close()
