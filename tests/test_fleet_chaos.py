"""Fleet chaos tier: kill −9 one shard of a LIVE fleet, and rebalance a
partition under live traffic — multi-process, real worker shards over the
durable spool (``run_tests.sh --fleet``; everything here is ``slow``).

Scenarios (ISSUE 9 chaos satellite):

- **kill −9 one shard mid-stream**: the victim's partition replays from
  its own chain + spool cursor; sibling shards never notice. The fleet
  result is BIT-IDENTICAL to a crash-free golden fleet run, shard for
  shard, array for array — the single-worker crash-equivalence claim
  (PR 3/PR 7) lifted to fleet scope.
- **live-traffic quiesced rebalance**: a partition moves owners while the
  producer keeps streaming into its queue; zero loss / zero double-effect
  by exact accounting, and the merged protocol event logs replay clean
  through BOTH the per-shard conformance mirror and the fleet-level
  checker (owner-locality, quiesce, window transit).
"""

import time

import numpy as np
import pytest

from apmbackend_tpu.analysis.protocol.conformance import (
    check_fleet_trace,
    check_protocol_trace,
)
from apmbackend_tpu.parallel.fleet import FleetHarness, service_partition

from test_chaos_harness import assert_snapshots_equal

pytestmark = pytest.mark.slow

BASE = 170_000_000


def _send_labels(h, t0, t1, per_label=40, services=12, seed=0):
    rng = np.random.RandomState(seed + t0)
    for t in range(t0, t1):
        for seq in range(per_label):
            i = int(rng.randint(0, services))
            e = int(rng.randint(50, 900))
            h.send_line(
                f"tx|jvm{i % 3}|svc{i % services:03d}|c{t}-{seq}|1|"
                f"{(BASE + t) * 10000 - e}|{(BASE + t) * 10000 + seq}|{e}|Y"
            )


def _fleet(workdir, **kw):
    kw.setdefault("shards", 2)
    # legacy scenarios pin P == N (identity partition map); the ISSUE 18
    # rebalance scenarios below run the fine-grained default (P = 4N)
    kw.setdefault("partitions", kw["shards"])
    kw.setdefault("capacity", 64)
    kw.setdefault("save_every_s", 0.3)
    kw.setdefault("lags", "6")
    kw.setdefault("checkpoint_mode", "delta")
    kw.setdefault("event_log", True)
    return FleetHarness(str(workdir), **kw)


def test_kill9_one_shard_fleet_bit_identical_to_golden(tmp_path):
    """SIGKILL one shard of a live 2-shard fleet twice; only its partition
    replays. Every shard's final engine snapshot must equal the crash-free
    golden fleet's, bit for bit."""

    def drive(workdir, kills):
        h = _fleet(workdir)
        try:
            h.start_all()
            _send_labels(h, 0, 3)
            # kill points chosen by the victim's committed cursor so both
            # runs stream identical spools (determinism of the comparison)
            if kills:
                h.wait_acked(1, 10, timeout_s=120)
                h.kill9(1)
                h.start(1)
            _send_labels(h, 3, 6)
            if kills:
                h.wait_acked(1, 40, timeout_s=120)
                h.kill9(1)
                h.start(1)
            _send_labels(h, 6, 9)
            return h, h.finish(timeout_s=300)
        except BaseException:
            h.close()
            raise

    hg, golden = drive(tmp_path / "golden", kills=False)
    hc, chaos = drive(tmp_path / "chaos", kills=True)
    try:
        # identical spool streams per partition: same producer sequence
        assert hg.sent_per_queue == hc.sent_per_queue
        for k in (0, 1):
            assert_snapshots_equal(
                hg.procs[k].resume_path, hc.procs[k].resume_path
            )
        # the sibling shard never restarted and never deduped anything
        assert chaos[0]["deduped_total"] == golden[0]["deduped_total"] == 0
        # conformance: the victim's log replays clean across its crashes
        for k in (0, 1):
            assert check_protocol_trace(hc.shard_events(k)) == []
        assert check_fleet_trace(hc.merged_events()) == []
    finally:
        hg.close()
        hc.close()


def test_live_traffic_rebalance_zero_loss_and_conformant(tmp_path):
    """Move a partition between shards while the producer keeps writing
    into its queue: nothing lost, nothing double-absorbed, ownership
    lands on the adopter, and the protocol event logs replay clean
    through the shardmodel-derived checkers."""
    h = _fleet(tmp_path, shards=2)
    try:
        h.start_all()
        _send_labels(h, 0, 3)
        h.wait_acked(1, 10, timeout_s=120)
        # live traffic DURING the handoff: these lines land on p1's queue
        # while ownership is moving — nobody may consume them until the
        # adopter owns the partition
        _send_labels(h, 3, 4)
        res = h.rebalance(1, 1, 0)
        assert res["released"]["rows"] > 0
        assert len(res["released"]["window"]) > 0
        _send_labels(h, 4, 7)
        stats = h.finish(timeout_s=300)

        # ownership moved; the adopter serves both partitions
        assert stats[0]["owned_partitions"] == [0, 1]
        assert stats[1]["owned_partitions"] == []
        assert stats[1]["services"] == 0
        # zero loss: every produced record acked on its partition queue
        for p in (0, 1):
            q = f"transactions.p{p}"
            assert h.acked(p) == h.sent_per_queue[q], q
        # zero double-effect: every absorb unique fleet-wide
        events = h.merged_events()
        absorbed = [
            e["msg"] for e in events
            if e.get("ev") == "deliver" and not e.get("dedup")
            and not e.get("mismatch") and e.get("tx")
        ]
        assert len(absorbed) == len(set(absorbed))
        assert len(set(absorbed)) == sum(h.sent_per_queue.values())
        # and the logs ARE model paths
        for k in (0, 1):
            assert check_protocol_trace(h.shard_events(k)) == []
        assert check_fleet_trace(events) == []
        # the moved services' rows live exactly once, on the adopter
        with np.load(h.procs[0].resume_path, allow_pickle=True) as z:
            keys0 = [tuple(k.split("\x00", 1)) for k in z["registry"].tolist()]
        moved = [k for k in keys0 if service_partition(k[1], 2) == 1]
        assert moved, "no partition-1 services landed on the adopter"
    finally:
        h.close()


def test_rebalance_then_kill9_adopter_recovers_ownership(tmp_path):
    """Crash the adopter AFTER the handoff: on restart it must re-own
    BOTH partitions (ownership rides the import commit) and drain the
    backlog with zero loss."""
    h = _fleet(tmp_path, shards=2)
    try:
        h.start_all()
        _send_labels(h, 0, 3)
        h.wait_acked(0, 10, timeout_s=120)
        h.rebalance(1, 1, 0)
        _send_labels(h, 3, 5)
        time.sleep(0.4)
        h.kill9(0)
        h.start(0)
        _send_labels(h, 5, 7)
        stats = h.finish(timeout_s=300)
        assert stats[0]["owned_partitions"] == [0, 1]
        for p in (0, 1):
            assert h.acked(p) == h.sent_per_queue[f"transactions.p{p}"]
        for k in (0, 1):
            assert check_protocol_trace(h.shard_events(k)) == []
        assert check_fleet_trace(h.merged_events()) == []
    finally:
        h.close()


# -- ISSUE 12: the durable telemetry spine under shard loss --------------------


def _fetch(url, timeout=10):
    import json as _json
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, _json.loads(resp.read().decode("utf-8"))
    except urllib.error.HTTPError as e:
        return e.code, _json.loads(e.read().decode("utf-8"))


def test_recorder_survives_shard_kill9_and_slo_burn_alert(tmp_path):
    """ISSUE 12 acceptance, end to end: fleet recorder on, kill -9 one
    shard mid-stream. (a) the DEAD shard's pre-crash metric series, trace
    spans, and alert decisions are still queryable through the manager-side
    ``/query`` endpoint (its telemetry outlives the process); (b) the
    sustained queue-lag breach the dead consumer leaves behind raises a
    multi-window fast-burn SLO alert whose decision record resolves the
    inputs; (c) the manager ``/healthz`` degrades to 503; (d) the recorder
    degrades on the dead target (counts errors, keeps scraping the rest)."""
    import json

    from apmbackend_tpu.config import default_config
    from apmbackend_tpu.obs import (
        FleetRecorder,
        MetricsRegistry,
        SLOEngine,
        TelemetryServer,
        TimeSeriesStore,
        make_query_route,
    )
    from apmbackend_tpu.obs.decisions import DecisionRing
    from apmbackend_tpu.obs.trace import Tracer, set_tracer
    from apmbackend_tpu.transport.spool import SpoolChannel

    # head-sample EVERY produced line: trace ids are stamped producer-side,
    # and the producer queue resolves the process tracer at creation — so
    # install it BEFORE the harness builds its partitioner
    old_tracer = set_tracer(Tracer(module="producer", sample_rate=1))
    h = _fleet(tmp_path, metrics=True, fast_alerts=True)
    store = TimeSeriesStore(str(tmp_path / "recorder-store"))
    ring = DecisionRing()
    paged = []

    # services pinned to the victim shard's partition (p1)
    victims = [f"svc{i:03d}" for i in range(64)
               if service_partition(f"svc{i:03d}", 2) == 1][:3]
    assert len(victims) == 3
    sent_p1 = 0

    def send_victims(t, elapsed):
        nonlocal sent_p1
        for seq, svc in enumerate(victims):
            # jittered baseline: a zero-variance window never emits a z
            # signal, so give the detector a real (small) std to band around
            e = elapsed + (t * 7 + seq * 13) % 30
            h.send_line(
                f"tx|jvm1|{svc}|e{t}-{seq}|1|{(BASE + t) * 10000 - e}|"
                f"{(BASE + t) * 10000 + seq}|{e}|Y"
            )
            sent_p1 += 1

    # dead-consumer lag probe: a FRESH spool view per scrape reads the
    # victim partition's backlog (records minus acked cursor) off disk —
    # it keeps reporting after the consumer is SIGKILLed
    def p1_lag():
        ch = SpoolChannel(str(h.spool_dir))
        try:
            return float(ch.queue_lag("transactions.p1"))
        finally:
            ch.close()

    probe_reg = MetricsRegistry()
    probe_reg.gauge(
        "apm_queue_lag", "victim partition backlog (observer view)",
        labels={"queue": "transactions.p1"},
    ).set_fn(p1_lag)
    probe = TelemetryServer(probe_reg, port=0, module="lagprobe")
    probe.start()

    # tight SLO windows so the breach certifies in seconds, not hours
    cfg = default_config()
    cfg["slo"]["shortWindowSeconds"] = 3.0
    cfg["slo"]["longWindowSeconds"] = 10.0
    cfg["slo"]["alertCooldownSeconds"] = 0.0
    cfg["slo"]["objectives"] = [
        {"name": "queue_lag", "kind": "gauge", "series": "apm_queue_lag",
         "threshold": 10.0, "target": 0.99, "per": "queue"},
    ]
    eng = SLOEngine.from_config(store, cfg, decisions=ring,
                                on_alert=lambda m, r: paged.append(m))
    qsrv = TelemetryServer(MetricsRegistry(), port=0, module="mgr")
    qsrv.add_route("/query", make_query_route(lambda: store))
    qsrv.add_health("slo", eng.health)
    qsrv.start()

    rec = None
    try:
        h.start_all()
        rec = FleetRecorder(
            store,
            lambda: h.metrics_targets(timeout_s=30.0)
            + [("lagprobe", probe.url)],
            interval_s=0.25, self_module="mgr",
        )
        rec.start()

        # baseline ticks, then a sustained spike: with --fast-alerts the
        # victim shard pages on the 2nd bad interval and records the alert
        # decisions the recorder must preserve past the crash
        for t in range(12):
            send_victims(t, 100)
        send_victims(12, 30000)
        send_victims(13, 30000)
        # the stats stream holds bufferSizeInIntervals=6 buckets open behind
        # the watermark: trailing labels flush the spike buckets into ticks
        for t in range(14, 22):
            send_victims(t, 100)
        h.wait_acked(1, sent_p1, timeout_s=120)
        time.sleep(0.6)  # at least one full scrape cadence post-drain
        rec.scrape_once()  # deterministic pre-crash snapshot
        errors_before = rec.status()["counts"]["scrape_errors_total"]

        # -- kill -9 the victim mid-stream; its backlog starts growing ----
        h.kill9(1)
        for t in range(14, 34):
            send_victims(t, 100)  # 60 lines nobody will ack
        time.sleep(4.0)  # breach spans the whole short window + scrapes

        # (d) recorder degrades drop-and-count on the dead target
        counts = rec.status()["counts"]
        assert counts["scrape_errors_total"] > errors_before
        assert counts["scrapes_total"] > 0

        # (a) the dead shard's pre-crash telemetry is queryable via /query
        now = time.time()
        status, doc = _fetch(
            f"{qsrv.url}/query?series=apm_engine_tx_ingested_total"
            f"&start={now - 600:.0f}&end={now:.0f}&step=10&module=shard1")
        assert status == 200
        assert doc["series"], "dead shard's metric series must survive"
        assert any(v is not None and v > 0
                   for s in doc["series"] for _, v in s["points"])
        status, doc = _fetch(
            f"{qsrv.url}/query?kind=spans&start=0&module=shard1&limit=64")
        assert status == 200 and len(doc["rows"]) >= 1
        status, doc = _fetch(
            f"{qsrv.url}/query?kind=decisions&start=0&module=shard1")
        assert status == 200
        assert len(doc["rows"]) >= 1, "pre-crash alert decision must survive"
        assert any(d.get("service") in victims for d in doc["rows"])

        # (b) sustained queue-lag breach -> multi-window fast burn + page
        res = eng.evaluate(time.time())
        lag = [r for r in res if r["objective"] == "queue_lag"
               and r.get("key") == "transactions.p1"]
        assert lag, f"queue_lag objective missing from {res!r}"
        assert lag[0]["severity"] == "fast"
        assert lag[0]["burn_short"] >= 14.4 and lag[0]["burn_long"] >= 14.4
        assert paged, "fast burn must dispatch an alert"
        stored = [d for d in ring.recent()
                  if d.get("decision") == "slo_burn_rate"]
        assert stored
        d = stored[-1]
        assert d["series"] == "apm_queue_lag"
        assert d["key"] == "transactions.p1"
        assert d["threshold"] == 10.0 and d["target"] == 0.99
        for w in ("short", "long"):
            assert d["windows"][w]["events"] > 0
            assert d["windows"][w]["bad_fraction"] >= 0.144

        # (c) the manager healthz degrades to 503 while fast-burning
        status, doc = _fetch(f"{qsrv.url}/healthz")
        assert status == 503
        assert "queue_lag:transactions.p1" in doc["slo"]["fast_burning"]
        assert json.loads(json.dumps(doc))  # body is real JSON end to end
    finally:
        if rec is not None:
            rec.stop()
        probe.stop()
        qsrv.stop()
        store.close()
        h.close()
        set_tracer(old_tracer)


# -- ISSUE 18: the self-managing fleet (automatic rebalance) -------------------


# services pinned per P=8 partition (service_partition(svc, 8), see
# test_fleet.py's pinned-values test): p0<-svc005, p2<-svc003,
# p4<-svc001/svc009, p6<-svc007/svc010 all stripe to shard 0 at boot
_P8_HOT = {0: "svc005", 2: "svc003", 4: "svc001", 6: "svc007"}
_P8_COOL = {1: "svc006", 3: "svc004", 5: "svc002", 7: "svc000"}

# the deterministic skewed-load fixture the policy replays: shard 0's
# partitions carry 20x the backlog of shard 1's
_SKEW_PROFILE = {0: 100.0, 2: 100.0, 4: 100.0, 6: 100.0,
                 1: 5.0, 3: 5.0, 5: 5.0, 7: 5.0}
_CTL_CFG = {"enabled": True, "highWatermark": 150.0, "lowWatermark": 130.0,
            "cooldownSeconds": 1.0, "movesPerPartition": 1,
            "moveTimeoutSeconds": 60.0}


def _send_skewed(h, t0, t1, per=6):
    """Real traffic matching the skew profile's shape: hot services on
    shard 0's partitions, a trickle on shard 1's."""
    for t in range(t0, t1):
        for p, svc in _P8_HOT.items():
            for seq in range(per):
                e = 100 + (t * 7 + seq * 13 + p) % 50
                h.send_line(
                    f"tx|jvm1|{svc}|h{p}-{t}-{seq}|1|{(BASE + t) * 10000 - e}|"
                    f"{(BASE + t) * 10000 + seq}|{e}|Y")
        for p, svc in _P8_COOL.items():
            e = 100 + (t * 11 + p) % 50
            h.send_line(
                f"tx|jvm2|{svc}|c{p}-{t}|1|{(BASE + t) * 10000 - e}|"
                f"{(BASE + t) * 10000 + 900 + p}|{e}|Y")


def _mk_controller(h, *, restart=None, clock=None):
    from apmbackend_tpu.parallel.rebalancer import (
        Observation, RebalanceController)

    owners = {p: p % h.shards for p in range(h.partitions)}

    def observe():
        return Observation(dict(_SKEW_PROFILE), owners)

    observe.owners = owners
    return RebalanceController(
        h.workdir, {k: h.procs[k] for k in range(h.shards)}, observe,
        dict(_CTL_CFG), restart=restart,
        clock=clock or (lambda: 0.0))


def _golden_decisions(h, ticks):
    """Pure-policy replay of the fixture: what the controller SHOULD
    decide, with moves applied to a simulated ownership map only."""
    from apmbackend_tpu.parallel.rebalancer import (
        Observation, PolicyState, apply_move, decide)

    owners = {p: p % h.shards for p in range(h.partitions)}
    st, out, now = PolicyState(), [], 0.0
    for _ in range(ticks):
        now += 2.0
        d = decide(Observation(dict(_SKEW_PROFILE), owners), st,
                   _CTL_CFG, now)
        out.append(d)
        if d["move"]:
            apply_move(st, d, _CTL_CFG, now)
            owners[d["move"][0]] = d["move"][2]
    return out


def test_controller_converges_on_skew_then_quiet(tmp_path):
    """The acceptance drill: replay the deterministic skewed fixture
    against a LIVE 2-shard / 8-partition fleet. The controller makes at
    most K moves then goes quiet (every further tick is an explained
    no-move), the executed decision sequence is BIT-IDENTICAL to the
    pure-policy golden replay, and the moved fleet loses nothing."""
    h = _fleet(tmp_path, shards=2, partitions=8)
    try:
        h.start_all()
        _send_skewed(h, 0, 3)
        h.wait_acked(0, 10, timeout_s=120)
        now = [0.0]
        ctl = _mk_controller(h, clock=lambda: now[0])
        TICKS, K = 8, 4
        decisions = []
        for _ in range(TICKS):
            now[0] += 2.0  # cooldown window passes between ticks
            decisions.append(ctl.tick())
        moves = [d["move"] for d in decisions if d.get("move")]
        assert moves == [[0, 0, 1], [2, 0, 1]]  # hottest first, then next
        assert len(moves) <= K and ctl.moves_total == len(moves)
        assert all(d.get("executed") for d in decisions if d.get("move"))
        # quiet: after convergence EVERY tick explains why it sits still
        tail = decisions[len(moves):]
        assert tail and all(
            d["move"] is None and d["reason"] == "no-qualifying-move"
            for d in tail)
        # bit-identical to the pure-policy golden replay
        stripped = [{k: v for k, v in d.items() if k != "executed"}
                    for d in decisions]
        assert stripped == _golden_decisions(h, TICKS)
        # live ownership followed the moves
        owned = ctl.owned_map()
        assert owned == {0: [4, 6], 1: [0, 1, 2, 3, 5, 7]}
        # traffic after convergence: zero loss through the moved map
        _send_skewed(h, 3, 6)
        stats = h.finish(timeout_s=300)
        assert stats[0]["owned_partitions"] == [4, 6]
        assert stats[1]["owned_partitions"] == [0, 1, 2, 3, 5, 7]
        for p in range(8):
            assert h.acked(p) == h.sent_per_queue[f"transactions.p{p}"], p
        for k in (0, 1):
            assert check_protocol_trace(h.shard_events(k)) == []
        assert check_fleet_trace(h.merged_events(), n_shards=2) == []
    finally:
        h.close()


def test_controller_survives_kill9_of_releaser_mid_move(tmp_path):
    """kill −9 the releaser with the release request pending: the durable
    request outlives the child, the controller restarts it, the restarted
    worker re-executes the SAME seq, and the move completes — zero loss,
    conformant logs, one move counted."""
    h = _fleet(tmp_path, shards=2, partitions=8)
    try:
        h.start_all()
        _send_skewed(h, 0, 3)
        h.wait_acked(0, 10, timeout_s=120)
        h.wait_acked(1, 1, timeout_s=120)
        # the releaser is DEAD when the decision fires: the request file
        # waits in front of a corpse until the controller restarts it
        h.kill9(0)
        restarts = []

        def restart(k):
            restarts.append(k)
            h.start(k)

        now = [0.0]
        ctl = _mk_controller(h, restart=restart, clock=lambda: now[0])
        now[0] += 2.0
        d = ctl.tick()
        assert d["move"] == [0, 0, 1] and d["executed"] is True
        assert restarts == [0]
        assert ctl.moves_total == 1 and ctl.aborts_total == 0
        assert ctl.owned_map() == {0: [2, 4, 6], 1: [0, 1, 3, 5, 7]}
        _send_skewed(h, 3, 5)
        h.finish(timeout_s=300)
        for p in range(8):
            assert h.acked(p) == h.sent_per_queue[f"transactions.p{p}"], p
        for k in (0, 1):
            assert check_protocol_trace(h.shard_events(k)) == []
        assert check_fleet_trace(h.merged_events(), n_shards=2) == []
    finally:
        h.close()


def test_controller_recovers_manager_death_mid_move(tmp_path):
    """The manager dies BETWEEN release-commit and adopt: the handoff
    file on disk holds the rows' only copy. A fresh controller's
    recover() probes live ownership, completes the move on the intended
    recipient, GCs the file, and the fleet loses nothing."""
    import os as _os

    from apmbackend_tpu.parallel.rebalancer import handoff_path

    h = _fleet(tmp_path, shards=2, partitions=8)
    try:
        h.start_all()
        _send_skewed(h, 0, 3)
        h.wait_acked(0, 10, timeout_s=120)
        # the dead manager got exactly this far: release committed
        path = handoff_path(h.workdir, 0, 0, 1)
        released = h.procs[0].control("release", partition=0, path=path)
        assert released["rows"] > 0 and _os.path.exists(path)
        # ...and a NEW controller (manager restart) resolves the wreck
        ctl = _mk_controller(h)
        res = ctl.recover()
        assert res == [{"file": _os.path.basename(path),
                        "resolution": "completed"}]
        assert not _os.path.exists(path)
        assert ctl.moves_total == 1 and ctl.stale_handoffs_gc_total == 1
        assert ctl.owned_map() == {0: [2, 4, 6], 1: [0, 1, 3, 5, 7]}
        _send_skewed(h, 3, 5)
        h.finish(timeout_s=300)
        for p in range(8):
            assert h.acked(p) == h.sent_per_queue[f"transactions.p{p}"], p
        for k in (0, 1):
            assert check_protocol_trace(h.shard_events(k)) == []
        assert check_fleet_trace(h.merged_events(), n_shards=2) == []
    finally:
        h.close()


def test_queryplane_kill9_drill_partial_stale_zero_5xx(tmp_path):
    """ISSUE 20 CI drill: a fleet query plane over a live 2-shard fleet
    with the recorder store as the durable read path; kill -9 one shard
    MID-query-load. (a) the concurrent dashboard load never sees a 5xx —
    the dead shard's slice degrades to the recorder store; (b) a post-kill
    query answers 200 with ``partial``/``stale`` marking and a positive
    per-shard freshness for the victim; (c) pre-kill, a single-service
    query is answered by exactly the owning shard per the owner map."""
    import urllib.parse

    from apmbackend_tpu.obs import (
        FleetRecorder,
        MetricsRegistry,
        QueryPlane,
        TelemetryServer,
        TimeSeriesStore,
    )
    from apmbackend_tpu.testing.chaos import QueryLoad

    h = _fleet(tmp_path, metrics=True)
    store = TimeSeriesStore(str(tmp_path / "rec-store"))
    rec = None
    psrv = None
    try:
        h.start_all()
        rec = FleetRecorder(
            store, lambda: h.metrics_targets(timeout_s=30.0),
            interval_s=0.25)
        rec.start()
        _send_labels(h, 0, 4)
        for p in range(h.partitions):
            h.wait_acked(p, h.sent_per_queue[f"transactions.p{p}"],
                         timeout_s=120)
        time.sleep(0.8)  # a couple of recorder passes + shard self-samples

        reg = MetricsRegistry()
        plane = QueryPlane(
            lambda: h.metrics_targets(timeout_s=0.5),
            owners=h.owner_map.read,
            store=store,
            partitions=h.partitions,
            registry=reg,
            freshness=rec.freshness,
            cache_ttl_s=0.25,
            timeout_s=2.0,
        )
        psrv = TelemetryServer(reg, port=0, module="queryplane")
        for route_path, route_fn in plane.make_routes().items():
            psrv.add_route(route_path, route_fn)
        psrv.start()
        base = psrv.url
        now = time.time()

        # (c) single-service routing: exactly the owning shard answers
        svc = "svc003"
        p = service_partition(svc, h.partitions)
        owner = h.owner_map.read()[1][p]
        qs = urllib.parse.urlencode({
            "series": "apm_engine_tx_ingested_total", "service": svc,
            "start": f"{now - 120:.0f}", "end": f"{now:.0f}", "step": "10"})
        status, doc = _fetch(f"{base}/query?{qs}")
        assert status == 200
        assert doc["shards_queried"] == [owner]
        assert doc["partial"] is False

        urls = [
            f"{base}/query?" + urllib.parse.urlencode(
                {"series": "rate(apm_engine_tx_ingested_total[10s])"}),
            f"{base}/query?" + urllib.parse.urlencode(
                {"series": "apm_queue_lag"}),
            f"{base}/trace?n=64",
            f"{base}/decisions?n=64",
        ]
        load = QueryLoad(urls, threads=3, seed=11).start()
        time.sleep(0.6)
        h.kill9(1)  # -- the drill: victim dies under live dashboard load
        time.sleep(2.5)
        summary = load.stop()
        # (a) degraded serving, never failed serving
        assert summary["five_xx"] == 0, summary
        assert summary["errors"] == 0, summary
        assert summary["requests"] > 0
        assert summary["codes"].get(200, 0) == summary["requests"]

        # (b) explicit post-kill query: partial + stale + freshness
        now = time.time()
        qs = urllib.parse.urlencode({
            "series": "apm_engine_tx_ingested_total", "cache": "0",
            "start": f"{now - 600:.0f}", "end": f"{now:.0f}", "step": "10"})
        status, doc = _fetch(f"{base}/query?{qs}")
        assert status == 200
        assert doc["partial"] is True and doc["stale"] is True
        assert doc["shards"]["shard0"]["status"] == "live"
        assert doc["shards"]["shard1"]["status"] == "stale"
        assert doc["shards"]["shard1"]["freshness_s"] > 0
        # the dead shard's slice really is in the merged answer
        assert any(s["points"] and any(v is not None for _t, v in s["points"])
                   for s in doc["series"])

        h.start(1)  # restore the victim so the fleet drains clean
        h.finish(timeout_s=300)
    finally:
        if rec is not None:
            rec.stop()
        if psrv is not None:
            psrv.stop()
        h.close()
