"""CLI dispatcher: ``python -m apmbackend_tpu <command> [...]``.

Commands map to the reference's process/tool set:

- ``worker``      TPU pipeline worker (stats+zscore+alerts fused)
- ``parser``      transaction parser / log tailer
- ``insertdb``    DB sink
- ``jmx``         JMX poller
- ``standalone``  whole pipeline in one process (memory broker)
- ``manager``     supervisor process (apm_manager.js)
- ``controller``  start|stop|restart the manager (controller.sh)
- ``pidstats``    'MEM_MiB SWAP_MiB' for a PID (pid_stats.py)
- ``dequeue``     destructive queue peek (dequeue.js)
- ``qstat``       queue depth/memory (qstat.sh)
- ``backup``      timestamped source/config backups (backup.sh)
- ``config``      print the full default config as commented JSON
- ``smoke``       manual integration harnesses: db insert, Grafana
                  annotation/render, path resolution (the reference's
                  dbtest/posttest/imagedltest/maptest scratch scripts)
- ``schema``      generate/apply sink DDL + the Grafana alert-inspector
                  dashboard JSON for the configured table names
- ``demo``        sixty-second tour: synthetic log fleet with an injected
                  latency regression through the whole pipeline; exit 0 iff
                  exactly that service alerts
"""

import importlib
import sys

# command -> (dotted module exposing main(), main takes argv?). This is THE
# table: the supervisor's stale-PID matching derives its dispatcher aliases
# from it (manager.cmdline_pattern_for), so both launch forms of a module stay
# recognizable without a second hand-maintained mapping.
COMMANDS = {
    "worker": ("apmbackend_tpu.runtime.worker", False),
    "parser": ("apmbackend_tpu.ingest.parser_main", False),
    "insertdb": ("apmbackend_tpu.sinks.insert_db_main", False),
    "jmx": ("apmbackend_tpu.ingest.jmx_main", False),
    "standalone": ("apmbackend_tpu.standalone", True),
    "manager": ("apmbackend_tpu.manager.manager", False),
    "controller": ("apmbackend_tpu.manager.controller", True),
    "pidstats": ("apmbackend_tpu.manager.pid_stats", True),
    "dequeue": ("apmbackend_tpu.tools.dequeue", True),
    "qstat": ("apmbackend_tpu.tools.qstat", True),
    "backup": ("apmbackend_tpu.tools.backup", True),
    "config": ("apmbackend_tpu.config", True),
    "smoke": ("apmbackend_tpu.tools.smoke", True),
    "schema": ("apmbackend_tpu.tools.schema", True),
    "demo": ("apmbackend_tpu.tools.demo", True),
}


def main() -> int:
    if len(sys.argv) < 2:
        print(__doc__, file=sys.stderr)
        return 2
    cmd, argv = sys.argv[1], sys.argv[2:]
    entry = COMMANDS.get(cmd)
    if entry is None:
        print(f"Unknown command: {cmd}\n{__doc__}", file=sys.stderr)
        return 2
    sys.argv = [f"apmbackend_tpu {cmd}"] + argv
    module_path, takes_argv = entry
    m = importlib.import_module(module_path).main
    result = m(argv) if takes_argv else m()
    return 0 if result is None else int(result)


if __name__ == "__main__":
    sys.exit(main())
