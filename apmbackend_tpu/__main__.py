"""CLI dispatcher: ``python -m apmbackend_tpu <command> [...]``.

Commands map to the reference's process/tool set:

- ``worker``      TPU pipeline worker (stats+zscore+alerts fused)
- ``parser``      transaction parser / log tailer
- ``insertdb``    DB sink
- ``jmx``         JMX poller
- ``standalone``  whole pipeline in one process (memory broker)
- ``manager``     supervisor process (apm_manager.js)
- ``controller``  start|stop|restart the manager (controller.sh)
- ``pidstats``    'MEM_MiB SWAP_MiB' for a PID (pid_stats.py)
- ``dequeue``     destructive queue peek (dequeue.js)
- ``qstat``       queue depth/memory (qstat.sh)
"""

import sys


def main() -> int:
    if len(sys.argv) < 2:
        print(__doc__, file=sys.stderr)
        return 2
    cmd, argv = sys.argv[1], sys.argv[2:]
    sys.argv = [f"apmbackend_tpu {cmd}"] + argv
    if cmd == "worker":
        from .runtime.worker import main as m

        m()
    elif cmd == "parser":
        from .ingest.parser_main import main as m

        m()
    elif cmd == "insertdb":
        from .sinks.insert_db_main import main as m

        m()
    elif cmd == "jmx":
        from .ingest.jmx_main import main as m

        m()
    elif cmd == "standalone":
        from .standalone import main as m

        return m(argv)
    elif cmd == "manager":
        from .manager.manager import main as m

        m()
    elif cmd == "controller":
        from .manager.controller import main as m

        return m(argv)
    elif cmd == "pidstats":
        from .manager.pid_stats import main as m

        return m(argv)
    elif cmd == "dequeue":
        from .tools.dequeue import main as m

        return m(argv)
    elif cmd == "qstat":
        from .tools.qstat import main as m

        return m(argv)
    else:
        print(f"Unknown command: {cmd}\n{__doc__}", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
