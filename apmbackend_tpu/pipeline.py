"""The fused device pipeline + host driver.

This is the TPU-native replacement for three whole reference processes —
stream_calc_stats, stream_calc_z_score, stream_process_alerts — collapsed into
ONE jitted step function over dense state (SURVEY.md §7.2 steps 4-6). Where the
reference hops RabbitMQ between stages per message, here a 10 s tick runs:

    stats.tick  ->  wire-quantize  ->  zscore.step (per lag)  ->  alerts.eval

entirely on device, for every (server, service) row at once. The host driver
around it keeps the string<->row registry, splits incoming micro-batches at
tick boundaries (preserving the reference's stats-before-addData event order,
stream_calc_stats.js:348-370), re-orders raw tx for the DB sink via the
min-heap (stream_calc_stats.js:136-155 role), applies per-service alert
cooldowns, and snapshots/restores the full device state (resume files, §5.4).

Wire parity: ``quantize=True`` rounds avg/p75/p95 to 1 decimal and tpm to 2
before the z-score step — exactly what the reference's CSV hop does
(StatEntry.toCSVString -> parseFloat, entries.js:72) — so device FullStat
output matches a reference pipeline reading the same queues.
"""

from __future__ import annotations

import math
import os
import re
import time
from collections import deque
from typing import Callable, Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .entries import FullStatEntry, StatEntry, TxEntry
from .ops import alerts as dalerts
from .ops import ewma as dewma
from .ops import stats as dstats
from .ops import zscore as dzscore
from .ops.registry import CapacityExceeded, ServiceRegistry
from .utils.heap import MinHeap

# the numeric forms whose numpy float parse == JS parseInt truncation; rows
# outside this shape fall back to js_parse_int in feed_csv_batch
_PLAIN_NUMBER = re.compile(r"^[+-]?\d+(?:\.\d+)?$")


def _pad_tier_repeat(idx: np.ndarray, *, last: bool = False) -> np.ndarray:
    """Pad a gather-index vector to the next power-of-two tier by repeating
    one element (first by default, last with ``last=True``) so the delta
    capture compiles a BOUNDED set of gather shapes instead of one per
    distinct count. Duplicated indices are harmless on both sides: the
    gather reads the same cell twice, the replay scatter writes the same
    post-state value twice."""
    n = len(idx)
    if n == 0:
        return idx
    tier = 1
    while tier < n:
        tier *= 2
    if tier == n:
        return idx
    fill = idx[-1] if last else idx[0]
    return np.concatenate([idx, np.full(tier - n, fill, idx.dtype)])


class LagSpec(NamedTuple):
    lag: int
    suppressed: bool  # lag in suppressedLags
    # median/MAD baseline instead of mean/std (ops/zscore.py ZScoreConfig
    # .robust); per-lag static — it changes the compiled program
    robust: bool = False


class EngineConfig(NamedTuple):
    stats: dstats.StatsConfig
    lags: Tuple[LagSpec, ...]
    alert_rules: Tuple[dalerts.AlertRuleConfig, ...]  # one per lag
    quantize: bool = True
    # multi-window extension (SURVEY.md §7.2 step 10): EWMA/seasonal channels
    ewma: Tuple[dewma.EwmaSpec, ...] = ()
    ewma_rules: Tuple[dalerts.AlertRuleConfig, ...] = ()  # one per channel
    # storage dtype for the z-score rings (None = stats dtype); bfloat16
    # halves the dominant HBM read per tick (ops/zscore.py ring_dtype)
    zscore_ring_dtype: Optional[jnp.dtype] = None
    # one-pass shifted variance (ops/zscore.py onepass_var); f64 parity mode
    # always keeps the exact two-pass regardless
    zscore_onepass: bool = True
    # O(1)-per-tick incremental window aggregates (ops/zscore.py sliding;
    # module docstring there). Takes precedence over onepass; inert for f64
    # parity mode and robust lags. Production default: ON.
    zscore_sliding: bool = True
    zscore_rebuild_every: int = 64
    # per-tick executor: "staged" = the multi-program read-free-writer
    # choreography (make_staged_executor), "fused" = the single/two-dispatch
    # fused tick with the staggered rebuild folded in (make_fused_step),
    # "auto" = fused while the donated-copy-prone state (sample reservoir +
    # z-score rings) fits under the fused byte budget, staged above it —
    # small shapes are dispatch-bound (the ~3-4 ms/tick floor VERDICT r5
    # flagged), huge shapes are copy-bound (XLA:CPU rewrites any big buffer
    # a single program both reads and writes, measured 736 ms/tick at the
    # 8192 x 8640 ring)
    tick_executor: str = "auto"

    @property
    def capacity(self) -> int:
        return self.stats.capacity


class EngineState(NamedTuple):
    stats: dstats.StatsState
    zscores: Tuple[dzscore.ZScoreState, ...]  # one per lag
    alert_counters: Tuple[jnp.ndarray, ...]  # [S] int32 per lag
    ewmas: Tuple[dewma.EwmaState, ...] = ()  # one per EWMA channel
    ewma_counters: Tuple[jnp.ndarray, ...] = ()  # [S] int32 per channel


class EngineParams(NamedTuple):
    """Per-row parameter vectors gathered from config (refreshed on hot reload
    or registry growth)."""

    thresholds: Tuple[jnp.ndarray, ...]  # [S] per lag
    influences: Tuple[jnp.ndarray, ...]  # [S] per lag
    hard_max_ms: jnp.ndarray  # [S]
    suppressed: jnp.ndarray  # [S] bool
    # rows that exist in the registry: gates the z-score warm-up so a
    # service first seen mid-run waits a full lag window (reference per-key
    # list-creation semantics). None = treat every row as active.
    active: Optional[jnp.ndarray] = None  # [S] bool
    # per-row EWMA-channel overrides (registry.ewma_params); empty tuples =
    # every row uses the channel spec's scalar defaults
    ewma_thresholds: Tuple[jnp.ndarray, ...] = ()  # [S] per channel
    ewma_influences: Tuple[jnp.ndarray, ...] = ()  # [S] per channel


class LagEmission(NamedTuple):
    window_avg: jnp.ndarray  # [S, 3]
    lower_bound: jnp.ndarray  # [S, 3]
    upper_bound: jnp.ndarray  # [S, 3]
    signal: jnp.ndarray  # [S, 3] int32
    trigger: jnp.ndarray  # [S] bool
    cause_bits: jnp.ndarray  # [S] int32


class TickEmission(NamedTuple):
    tpm: jnp.ndarray  # [S] (wire-rounded when quantize)
    average: jnp.ndarray  # [S, 3] = (avg, p75, p95), wire-rounded
    count: jnp.ndarray  # [S] int32
    overflowed: jnp.ndarray  # [S] bool
    lags: Tuple[LagEmission, ...]
    ewma: Tuple[LagEmission, ...] = ()  # one per EWMA/seasonal channel


def zscore_cfg(cfg: EngineConfig, spec: LagSpec) -> dzscore.ZScoreConfig:
    """The ONE place an EngineConfig lag becomes a ZScoreConfig (init, tick,
    grow, restore and the sharded spec builders all route through here so the
    variance-mode/state-shape decision cannot drift between them)."""
    return dzscore.ZScoreConfig(
        cfg.capacity, spec.lag, cfg.stats.dtype, spec.robust,
        cfg.zscore_ring_dtype, cfg.zscore_onepass,
        cfg.zscore_sliding, cfg.zscore_rebuild_every,
    )


def engine_init(cfg: EngineConfig) -> EngineState:
    S = cfg.capacity
    return EngineState(
        stats=dstats.init_state(cfg.stats),
        zscores=tuple(
            dzscore.init_state(zscore_cfg(cfg, spec)) for spec in cfg.lags
        ),
        alert_counters=tuple(jnp.zeros((S,), jnp.int32) for _ in cfg.lags),
        ewmas=tuple(dewma.init_state(S, spec, cfg.stats.dtype) for spec in cfg.ewma),
        ewma_counters=tuple(jnp.zeros((S,), jnp.int32) for _ in cfg.ewma),
    )


def _engine_tick_impl(
    state: EngineState, cfg: EngineConfig, new_label, params: EngineParams,
    evicted: Optional[Tuple[jnp.ndarray, ...]],
    stats_res: Optional[dstats.TickResult] = None,
) -> Tuple[TickEmission, EngineState, Tuple[jnp.ndarray, ...]]:
    """Shared fused-tick body. ``evicted`` selects the execution shape:

    - None: single-program mode — the stats ring advances in-program and
      sliding lags compose their ring read and write inside this program
      (dzscore.step). Used by shard_map and the compile-check entry; pays
      whole-buffer copies on XLA:CPU.
    - tuple of [S, 3] slices (one per sliding lag, in lag order; may be
      empty): STAGED mode — the stats ring arrives PRE-advanced (the host
      dispatched dstats.advance_one per new label), this program only READS
      the big buffers (window_stats is read-only; sliding lags run
      ring-free via dzscore.step_core) and returns the ring pushes; the
      caller owes the ring_write dispatches. make_engine_step wires the
      programs together so every big buffer is only ever written by an
      in-place dynamic_update_slice in a read-free program.
    """
    if stats_res is not None:
        # fully-precomputed window stats (native-percentile staging: the
        # host filled per75/per95 outside this program)
        res = stats_res
        stats_state = state.stats
    elif evicted is not None:
        res = dstats.window_stats(state.stats, cfg.stats)
        stats_state = state.stats
    else:
        res, stats_state = dstats.tick(state.stats, cfg.stats, new_label)

    if cfg.quantize:
        tpm = dstats.quantize_half_up(res.tpm, 2)
        avg = dstats.quantize_half_up(res.average, 1)
        p75 = dstats.quantize_half_up(res.per75, 1)
        p95 = dstats.quantize_half_up(res.per95, 1)
    else:
        tpm, avg, p75, p95 = res.tpm, res.average, res.per75, res.per95

    new_values = jnp.stack([avg, p75, p95], axis=1)  # [S, 3]

    lag_emissions = []
    new_zstates = []
    new_counters = []
    pushes = []
    for i, spec in enumerate(cfg.lags):
        zcfg = zscore_cfg(cfg, spec)
        if evicted is not None and zcfg.sliding_active:
            act = params.active
            if act is None:
                act = jnp.ones((cfg.capacity,), bool)
            zres, zstate, push = dzscore.step_core(
                state.zscores[i], zcfg, new_values,
                params.thresholds[i], params.influences[i], act,
                evicted[len(pushes)],
            )
            pushes.append(push)
        else:
            zres, zstate = dzscore.step(
                state.zscores[i], zcfg, new_values,
                params.thresholds[i], params.influences[i], params.active,
            )
        ares = dalerts.eval_rules(
            state.alert_counters[i],
            cfg.alert_rules[i],
            avg, p75, tpm,
            zres.signal[:, 0], zres.signal[:, 1],
            params.hard_max_ms, params.suppressed,
        )
        lag_emissions.append(
            LagEmission(
                zres.window_avg, zres.lower_bound, zres.upper_bound, zres.signal,
                ares.trigger, ares.cause_bits,
            )
        )
        new_zstates.append(zstate)
        new_counters.append(ares.counters)

    # EWMA/seasonal channels: same inputs and alert ladder, O(1) state. The
    # season slot is keyed by the *edge* label — the time the emitted stats
    # actually describe (latest - buffer - 1, stream_calc_stats.js:356) — not
    # the raw tick label.
    edge_label = jnp.asarray(new_label, jnp.int32) - (cfg.stats.buffer_sz + 1)
    ewma_emissions = []
    new_estates = []
    new_ecounters = []
    for i, espec in enumerate(cfg.ewma):
        eres, estate = dewma.step(
            state.ewmas[i], espec, new_values, edge_label,
            params.ewma_thresholds[i] if i < len(params.ewma_thresholds) else None,
            params.ewma_influences[i] if i < len(params.ewma_influences) else None,
        )
        ares = dalerts.eval_rules(
            state.ewma_counters[i],
            cfg.ewma_rules[i],
            avg, p75, tpm,
            eres.signal[:, 0], eres.signal[:, 1],
            params.hard_max_ms, params.suppressed,
        )
        ewma_emissions.append(
            LagEmission(
                eres.window_avg, eres.lower_bound, eres.upper_bound, eres.signal,
                ares.trigger, ares.cause_bits,
            )
        )
        new_estates.append(estate)
        new_ecounters.append(ares.counters)

    emission = TickEmission(
        tpm, new_values, res.count, res.overflowed,
        tuple(lag_emissions), tuple(ewma_emissions),
    )
    new_state = EngineState(
        stats_state, tuple(new_zstates), tuple(new_counters),
        tuple(new_estates), tuple(new_ecounters),
    )
    return emission, new_state, tuple(pushes)


def engine_tick(
    state: EngineState, cfg: EngineConfig, new_label, params: EngineParams
) -> Tuple[TickEmission, EngineState]:
    """The fused per-interval step — the flagship jittable function
    (single-program form; latency-critical hosts use make_engine_step)."""
    emission, new_state, _ = _engine_tick_impl(state, cfg, new_label, params, None)
    return emission, new_state


def engine_core_tick(
    state: EngineState, cfg: EngineConfig, new_label, params: EngineParams,
    evicted: Tuple[jnp.ndarray, ...],
) -> Tuple[TickEmission, EngineState, Tuple[jnp.ndarray, ...]]:
    """Ring-free fused tick (staged mode; see _engine_tick_impl)."""
    return _engine_tick_impl(state, cfg, new_label, params, evicted)


def engine_core_tick_stats(
    state: EngineState, cfg: EngineConfig, new_label, params: EngineParams,
    evicted: Tuple[jnp.ndarray, ...], stats_res: dstats.TickResult,
) -> Tuple[TickEmission, EngineState, Tuple[jnp.ndarray, ...]]:
    """Ring-free fused tick over HOST-completed window stats (the
    native-percentile staging; see _engine_tick_impl)."""
    return _engine_tick_impl(state, cfg, new_label, params, evicted, stats_res)


def fused_copy_bytes(cfg: EngineConfig) -> int:
    """Bytes of big state a FUSED program may rewrite/copy per tick on
    XLA:CPU (the sample reservoir plus every z-score ring — a single program
    that both reads and writes a donated buffer pays a whole-buffer copy
    there). The auto executor gate compares this against the fused budget:
    below it the saved dispatches dwarf the copies, above it the staged
    read-free-writer choreography is mandatory."""
    st = cfg.stats
    dt_bytes = jnp.dtype(st.dtype).itemsize
    total = st.capacity * st.num_buckets * st.samples_per_bucket * dt_bytes
    for spec in cfg.lags:
        zc = zscore_cfg(cfg, spec)
        total += cfg.capacity * 3 * spec.lag * jnp.dtype(zc.storage_dtype).itemsize
    return total


# auto-gate budget: measured on the one-core CPU fallback, the fused
# executor wins up to ~tens of MB of copy-prone state (the rolling/replay
# shapes are ~2 MB; the 8192 x 8640 headline shape is ~850 MB and must stay
# staged). Overridable for experiments via APM_FUSED_MAX_BYTES.
_FUSED_MAX_BYTES_DEFAULT = 32 * 1024 * 1024


def resolve_tick_executor(cfg: EngineConfig) -> str:
    """The ONE executor-choice rule ("fused" | "staged"), shared by the
    single-chip and pod builders so hosts of a pod cannot diverge on it
    (the choice changes the dispatch sequence; divergence would deadlock
    pod collectives — parallel/sharded.py folds this into its pod-global
    agreement alongside the native-percentile capability flag)."""
    mode = os.environ.get("APM_TICK_EXECUTOR") or cfg.tick_executor
    if mode not in ("auto", "fused", "staged"):
        raise ValueError(f"tick executor must be auto|fused|staged, got {mode!r}")
    if mode != "auto":
        return mode
    budget = int(os.environ.get("APM_FUSED_MAX_BYTES", _FUSED_MAX_BYTES_DEFAULT))
    return "fused" if fused_copy_bytes(cfg) <= budget else "staged"


def _use_native_percentiles(cfg: EngineConfig) -> bool:
    """The native-percentile-stage gate shared by the staged and fused
    executors (CPU backend, f32, toolchain present): the host nth_element/
    radix kernel replaces XLA's one-core top_k (~3x, and far more at dense
    windows). On TPU the in-program top_k is the right shape instead."""
    if (
        cfg.stats.percentile_impl in ("auto", "native")
        and cfg.stats.dtype != jnp.float64
        and jax.default_backend() == "cpu"
    ):
        from . import native as _native

        return _native.have_native_percentiles()
    return False


def _rebuild_rotation(cfg: EngineConfig):
    """(chunk, starts) of the staggered-rebuild rotation — the same clamped
    schedule RebuildScheduler walks, for executors that fold the rebuild
    chunk into the tick program."""
    S = cfg.capacity
    chunk = dzscore.rebuild_chunk_rows(S, cfg.zscore_rebuild_every)
    n_chunks = -(-S // chunk)
    return chunk, [min(i * chunk, S - chunk) for i in range(n_chunks)]


def _staged_ring_update(cfg: EngineConfig, state2: EngineState, pushes):
    """Apply this tick's ring pushes to ``state2`` (slot = cursor - 1, the
    pre-advance cursor) — the in-program form of the staged write program,
    shared by the fused executors and make_megatick."""
    sliding_idx = sliding_lag_indices(cfg)
    zs = list(state2.zscores)
    for i, push in zip(sliding_idx, pushes):
        z = zs[i]
        L = z.values.shape[-1]
        zs[i] = z._replace(values=dzscore.ring_write(z.values, push, (z.pos - 1) % L))
    return state2._replace(zscores=tuple(zs))


def make_fused_step(cfg: EngineConfig, *, integrate_rebuild: bool = True):
    """The FUSED per-tick executor: ``step(state, new_label, params) ->
    (emission, new_state)`` in ONE donated dispatch (or two around the host
    percentile kernel) instead of the staged path's five-plus.

    This is the dispatch-floor fix for small shapes (VERDICT r5 weak 2): at
    the reference's real scale (~100 services, ~1,200 metrics/tick) the
    staged executor's per-tick cost is dominated by fixed overhead — five
    program dispatches, the latest-label host sync, and per-stage
    device_puts — worth ~2 ms against ~0.3 ms of actual compute. Here the
    whole tick (label advance -> staggered-rebuild chunk -> window stats ->
    quantize -> z-score -> alerts -> ring writes) is one jitted program over
    the donated EngineState, with the new label a TRACED scalar
    (ops/stats.py advance_span absorbs any label jump in-program, so there
    is no host mirror and no device->host sync).

    Two forms, picked by the same native-percentile gate as the staged
    executor:
      - native (CPU + toolchain): TWO programs — A = advance + z-ring evict
        reads + window panel stats + the staggered-rebuild chunk (the ring
        is only ever READ here, so no copy at any shape); the host fills
        exact percentiles straight from the (zero-copy) sample reservoir
        via the native selection kernel; B = the ring-free core + in-place
        ring writes (the ring's ONLY use in B is the DUS operand). A bucket
        overflow falls back to the count-weighted jitted percentiles for
        that tick, exactly like the staged path.
      - fused-all (TPU / no toolchain / f64): everything including the
        in-program percentiles in ONE program.

    The staggered rebuild rides the tick program on a rotating chunk (same
    schedule as RebuildScheduler; ``step.rebuild_integrated`` tells the host
    loop to skip its separate scheduler). It runs at the START of the tick —
    rebuild-then-tick, where the staged host loop runs tick-then-rebuild —
    because the chunk pass must only ever READ the ring: reading any slice
    of a ring the same program DUS-writes forces a whole-ring copy on
    XLA:CPU (measured 736 ms at [8192, 3, 8640]). Every row is still
    exactly re-aggregated once per ``zscore_rebuild_every`` ticks — the
    drift/blind-spot bound is phase-shifted by one tick, not weakened.

    Unlike the staged executor the rebuild chunk here is XLA, not the
    native streaming kernel — at the small shapes the fused path targets,
    the [chunk, 3, L] slice reduce is microseconds; at shapes where the
    native kernel's ~25x matters, resolve_tick_executor picks staged
    anyway."""
    sliding_idx = sliding_lag_indices(cfg)
    rebuild = integrate_rebuild and engine_needs_rebuild(cfg)
    if rebuild:
        chunk, starts = _rebuild_rotation(cfg)
    else:
        chunk, starts = 0, [0]
    rot = {"i": 0}

    def _next_start():
        s = starts[rot["i"]]
        rot["i"] = (rot["i"] + 1) % len(starts)
        return np.int32(s)

    use_native = _use_native_percentiles(cfg)

    if not use_native:

        def fused_all(state, nl, params, rb_start):
            state = state._replace(stats=dstats.advance_span(state.stats, cfg.stats, nl))
            if rebuild:
                state = engine_rebuild_slice(state, cfg, rb_start, chunk)
            rings = tuple(state.zscores[i].values for i in sliding_idx)
            cursors = tuple(state.zscores[i].pos for i in sliding_idx)
            evicted = tuple(
                dzscore.ring_evict_read(r, g) for r, g in zip(rings, cursors)
            )
            emission, state2, pushes = engine_core_tick(state, cfg, nl, params, evicted)
            return emission, _staged_ring_update(cfg, state2, pushes)

        jfused = jax.jit(fused_all, donate_argnums=(0,))

        def step(state, new_label, params):
            # np scalars: a jnp.int32() here would dispatch a device
            # convert per tick before the program even launches
            return jfused(state, np.int32(new_label), params, _next_start())

        step.rebuild_integrated = rebuild
        step.kind = "fused"
        step.rebuild_rot = rot
        step.rebuild_chunk = chunk
        step.rebuild_starts = starts
        return step

    # ---- native-percentile form: two programs around the host kernel ----
    from .native import window_percentiles_native

    def pre_program(stats_state, aggs, rings, cursors, fills, nl, rb_start):
        st = dstats.advance_span(stats_state, cfg.stats, nl)
        evicted = tuple(
            dzscore.ring_evict_read(r, g) for r, g in zip(rings, cursors)
        )
        res = dstats.window_pre(st, cfg.stats)
        if rebuild:
            new_aggs = []
            for k, i in enumerate(sliding_idx):
                zc = zscore_cfg(cfg, cfg.lags[i])
                zstate = dzscore.ZScoreState(rings[k], fills[k], cursors[k], aggs[k])
                zstate = dzscore.rebuild_agg_slice(zstate, zc, rb_start, chunk)
                new_aggs.append(zstate.agg)
            aggs = tuple(new_aggs)
        # the host needs the overflow decision and the window-slot mask;
        # producing both IN-PROGRAM keeps the host free of blocking scalar
        # reads (int(latest_bucket) costs a per-tick sync on the dispatch
        # queue) — the zero-copy views of these outputs carry the wait
        nbk = cfg.stats.num_buckets
        off = jnp.arange(cfg.stats.buffer_sz, cfg.stats.num_keep + 1, dtype=jnp.int32)
        in_window = jnp.zeros((nbk,), bool).at[(st.latest_bucket - off) % nbk].set(True)
        return st, evicted, res, aggs, jnp.any(res.overflowed), in_window

    # donate the stats state and the [S, 3] aggregates; the rings are READ
    # ONLY here (donating them would free the buffers program B writes)
    jpre = jax.jit(pre_program, donate_argnums=(0, 1))

    def core_pct(state, nl, params, evicted, res, pct):
        # splice the host-selected percentiles in-program: one [S, 2] put
        # instead of two separate device arrays
        res = res._replace(per75=pct[:, 0], per95=pct[:, 1])
        emission, state2, pushes = engine_core_tick_stats(
            state, cfg, nl, params, evicted, res
        )
        return emission, _staged_ring_update(cfg, state2, pushes)

    def core_res(state, nl, params, evicted, res):
        emission, state2, pushes = engine_core_tick_stats(
            state, cfg, nl, params, evicted, res
        )
        return emission, _staged_ring_update(cfg, state2, pushes)

    jcore_pct = jax.jit(core_pct, donate_argnums=(0,))
    jcore_res = jax.jit(core_res, donate_argnums=(0,))
    weighted = jax.jit(dstats.window_stats, static_argnums=1)
    weighted_cfg = cfg.stats._replace(percentile_impl="sort")

    # apm: sync-boundary: the fused executor's single sanctioned readiness wait — dlpack views of program A's outputs feed the host percentile kernel between the two donated programs
    def step(state, new_label, params):
        nl = np.int32(new_label)
        aggs = tuple(state.zscores[i].agg for i in sliding_idx)
        rings = tuple(state.zscores[i].values for i in sliding_idx)
        cursors = tuple(state.zscores[i].pos for i in sliding_idx)
        fills = tuple(state.zscores[i].fill for i in sliding_idx)
        st, evicted, res, new_aggs, overflowed, in_window = jpre(
            state.stats, aggs, rings, cursors, fills, nl, _next_start()
        )
        zs = list(state.zscores)
        for i, agg in zip(sliding_idx, new_aggs):
            zs[i] = zs[i]._replace(agg=agg)
        state = state._replace(stats=st, zscores=tuple(zs))
        # one readiness wait covers everything below: the zero-copy views of
        # A's outputs block until A lands; the overflow flag and the window
        # mask (anchored at the POST-advance latest, stale ticks clamped)
        # ride the same views instead of per-tick jax-scalar fetches
        try:
            overflow_np = np.from_dlpack(overflowed)
            mask = np.from_dlpack(in_window)
            samples = np.from_dlpack(st.samples)  # zero-copy on CPU
            counts = np.from_dlpack(st.nsamples)
        except Exception:  # pragma: no cover - dlpack unavailable
            overflow_np = np.asarray(overflowed)
            mask = np.asarray(in_window)
            samples = np.asarray(st.samples)
            counts = np.asarray(st.nsamples)
        if bool(overflow_np):
            # reservoir overflow: the count-weighted jitted path keeps burst
            # arrival mass exact for this tick (same fallback as staged)
            return jcore_res(state, nl, params, evicted, weighted(st, weighted_cfg))
        pct = window_percentiles_native(samples, mask, (75, 95), counts)
        return jcore_pct(state, nl, params, evicted, res, pct)

    step.rebuild_integrated = rebuild
    step.kind = "fused-native"
    step.rebuild_rot = rot
    step.rebuild_chunk = chunk
    step.rebuild_starts = starts
    return step


def make_megatick(cfg: EngineConfig, n_slots: int, batch_per_slot: int):
    """The MEGATICK executor: K buffered (tick?, ingest) slots in ONE
    donated ``lax.scan`` dispatch — replay/catch-up amortization for shapes
    where per-tick dispatch overhead dominates and a K-tick emission delay
    is acceptable (detection latency trades at K x 10 s of LOG time, which
    replay compresses to milliseconds of wall time).

    ``mega(state, params, new_labels[K], do_ticks[K], rows[K,B], labels[K,B],
    elapsed[K,B], valid[K,B]) -> (stacked TickEmission, new_state)``. Each
    slot optionally ticks FIRST (the stats-before-addData event order:
    entries that crossed a boundary are ingested after the tick they
    triggered), then scatters its micro-batch; slots with ``do_tick`` False
    are ingest-only (their emission slot is NaN/zero filler — mask by do_tick).
    The staggered-rebuild chunk rides every ticking slot, same rotation as
    make_fused_step (the wrapper threads the rotation across calls).

    Percentiles run IN-PROGRAM (the host selection kernel cannot ride a
    scan), so on the one-core CPU fallback this path loses to the fused
    native executor at dense windows — it is the TPU-shape amortizer, kept
    honest by the dispatch-floor microbench measuring both."""
    sliding_idx = sliding_lag_indices(cfg)
    rebuild = engine_needs_rebuild(cfg)
    chunk, starts = _rebuild_rotation(cfg) if rebuild else (0, [0])
    rot = {"i": 0}

    def tick_body(state, nl, rb_start, params):
        state = state._replace(stats=dstats.advance_span(state.stats, cfg.stats, nl))
        if rebuild:
            state = engine_rebuild_slice(state, cfg, rb_start, chunk)
        rings = tuple(state.zscores[i].values for i in sliding_idx)
        cursors = tuple(state.zscores[i].pos for i in sliding_idx)
        evicted = tuple(dzscore.ring_evict_read(r, g) for r, g in zip(rings, cursors))
        emission, state2, pushes = engine_core_tick(state, cfg, nl, params, evicted)
        return emission, _staged_ring_update(cfg, state2, pushes)

    def mega(state, params, nls, do_ticks, rb_starts, rows, labels, elaps, valid):
        # the no-tick branch must match the tick branch's exact leaf dtypes
        # (x64 mode weak-promotes tpm/count); derive them abstractly
        em_struct = jax.eval_shape(
            lambda s: tick_body(s, nls[0], rb_starts[0], params)[0], state
        )
        zero_em = jax.tree.map(
            lambda l: jnp.full(l.shape, jnp.nan, l.dtype)
            if jnp.issubdtype(l.dtype, jnp.floating)
            else jnp.zeros(l.shape, l.dtype),
            em_struct,
        )

        def slot(st, xs):
            nl, do_tick, rb_start, r, l, e, v = xs
            emission, st = jax.lax.cond(
                do_tick,
                lambda s: tick_body(s, nl, rb_start, params),
                lambda s: (zero_em, s),
                st,
            )
            st = engine_ingest(st, cfg, r, l, e, v)
            return st, emission

        state, emissions = jax.lax.scan(
            slot, state, (nls, do_ticks, rb_starts, rows, labels, elaps, valid)
        )
        return emissions, state

    jmega = jax.jit(mega, donate_argnums=(0,))

    def step(state, params, new_labels, do_ticks, rows, labels, elaps, valid):
        K = len(new_labels)
        if K != n_slots or rows.shape != (n_slots, batch_per_slot):
            raise ValueError(
                f"megatick compiled for [{n_slots}, {batch_per_slot}] slots, "
                f"got {K} labels / batch {rows.shape}"
            )
        rb = np.zeros(K, np.int32)
        for j, dt_ in enumerate(np.asarray(do_ticks, bool)):
            if dt_ and rebuild:
                rb[j] = starts[rot["i"]]
                rot["i"] = (rot["i"] + 1) % len(starts)
        return jmega(
            state, params,
            jnp.asarray(new_labels, jnp.int32), jnp.asarray(do_ticks, bool),
            jnp.asarray(rb), jnp.asarray(rows, jnp.int32),
            jnp.asarray(labels, jnp.int32),
            jnp.asarray(elaps, cfg.stats.dtype), jnp.asarray(valid, bool),
        )

    step.rebuild_integrated = rebuild
    step.kind = "megatick"
    step.rebuild_rot = rot
    step.rebuild_chunk = chunk
    step.rebuild_starts = starts
    return step


def make_engine_step(cfg: EngineConfig):
    """The staged per-tick executor: ``step(state, new_label, params) ->
    (emission, new_state)`` with donation throughout.

    Up to four program kinds per tick, each touching the big buffers only
    in the way XLA can keep in place:
      1. stats advance: dstats.advance_one per new label (host-counted from
         the latest-label scalar; normally one call) — the sample-reservoir
         clear is a single dynamic_update_slice, never a whole-buffer
         select,
      2. evict-read: one program slicing every sliding ring's about-to-be-
         overwritten slot (read-only — the rings must NOT be donated here),
      3. core tick: everything else — window_stats and the sliding lags
         only READ the big buffers, which pass through as donated identity,
      4. ring-write: one program of pure dynamic_update_slices (donated —
         the ONLY writer of the z-score rings; any same-program read would
         force a whole-ring copy on XLA:CPU, measured 736 ms vs 0.6 ms at
         [8192, 3, 8640]).

    On the CPU backend (percentileImpl auto/native, f32, toolchain present)
    the percentile stage additionally moves to the HOST: a tiny jitted
    program computes the panel stats, the native nth_element kernel selects
    the exact reference percentiles straight from the (zero-copy) sample
    reservoir, and the core program receives the completed TickResult —
    ~3x cheaper than one-core XLA top_k. Any bucket overflow falls back to
    the jitted count-weighted path for that tick. On TPU the in-program
    top_k is the right shape and this stage stays fused.

    Executor selection (resolve_tick_executor): small shapes route to the
    FUSED executor (make_fused_step — the dispatch-floor fix), big shapes
    keep the staging described above; ``tpuEngine.tickExecutor`` /
    APM_TICK_EXECUTOR pin either explicitly."""
    if resolve_tick_executor(cfg) == "fused":
        return make_fused_step(cfg)
    use_native = _use_native_percentiles(cfg)

    if not use_native:
        core = jax.jit(engine_core_tick, static_argnums=1, donate_argnums=(0,))
        return make_staged_executor(
            cfg,
            core=lambda state, nl, params, evicted: core(state, cfg, nl, params, evicted),
        )

    from .native import window_percentiles_native

    pre = jax.jit(dstats.window_pre, static_argnums=1)
    # overflow tick: the count-weighted sort keeps burst arrival mass exact
    weighted = jax.jit(
        dstats.window_stats, static_argnums=1
    )
    weighted_cfg = cfg.stats._replace(percentile_impl="sort")
    core = jax.jit(engine_core_tick_stats, static_argnums=1, donate_argnums=(0,))
    NB = cfg.stats.num_buckets
    offsets = np.arange(cfg.stats.buffer_sz, cfg.stats.num_keep + 1)

    # apm: sync-boundary: staged executor's host percentile stage — the overflow probe and reservoir readback sit between the pre and core programs by design
    def native_core(state, nl, params, evicted):
        res = pre(state.stats, cfg.stats)
        if bool(np.asarray(res.overflowed).any()):
            res = weighted(state.stats, weighted_cfg)
        else:
            # anchor the window at the POST-advance latest label, exactly
            # like window_pre/window_stats — on a stale re-emission tick
            # (nl < latest: restore/replay out-of-order delivery) the
            # advance loop left latest unchanged and nl would select the
            # wrong slots
            latest = int(state.stats.latest_bucket)
            mask = np.zeros(NB, bool)
            mask[(latest - offsets) % NB] = True
            try:
                samples = np.from_dlpack(state.stats.samples)  # zero-copy on CPU
                counts = np.from_dlpack(state.stats.nsamples)
            except Exception:  # pragma: no cover - dlpack unavailable
                samples = np.asarray(state.stats.samples)
                counts = np.asarray(state.stats.nsamples)
            # counts = the filled-prefix panel: the kernel gathers only live
            # samples instead of NaN-scanning every CAP slot (stats.ingest
            # fills positions in order; reservoir replacement stays inside
            # the prefix, so validity == prefix membership)
            pct = window_percentiles_native(samples, mask, (75, 95), counts)
            res = res._replace(
                per75=jnp.asarray(pct[:, 0], cfg.stats.dtype),
                per95=jnp.asarray(pct[:, 1], cfg.stats.dtype),
            )
        return core(state, cfg, nl, params, evicted, res)

    return make_staged_executor(cfg, core=native_core)


def sliding_lag_indices(cfg: EngineConfig) -> Tuple[int, ...]:
    """Which lags maintain sliding aggregates (ring staging applies)."""
    return tuple(
        i for i, spec in enumerate(cfg.lags) if zscore_cfg(cfg, spec).sliding_active
    )


def staged_ring_programs():
    """The two ring-only jitted programs of the staging contract, shared by
    the single-chip and pod executors: the read-only evict slices and the
    donated pure-DUS writes (write slot = the cursor BEFORE the core
    advanced it = new_pos - 1)."""
    evict = jax.jit(
        lambda rings, cursors: tuple(
            dzscore.ring_evict_read(r, g) for r, g in zip(rings, cursors)
        )
    )
    write = jax.jit(
        lambda rings, pushes, new_cursors: tuple(
            dzscore.ring_write(r, p, (g - 1) % r.shape[-1])
            for r, p, g in zip(rings, pushes, new_cursors)
        ),
        donate_argnums=(0,),
    )
    return evict, write


def make_staged_executor(cfg: EngineConfig, *, core):
    """The ONE staging choreography (single-chip make_engine_step and the
    pod-scale parallel.sharded.make_sharded_step both run on it, so the
    label-advance clamp, evict/write slot math and donation ordering cannot
    drift between them).

    ``core(state, new_label_int, params, evicted) -> (*outs, new_state,
    pushes)`` is the ring-free fused program (possibly shard_mapped, possibly
    emitting extra outputs like the fleet rollup); the returned
    ``step(state, new_label, params) -> (*outs, new_state)`` wraps it with:

      1. stats ring advance, one label at a time (a jump clears at most NB
         slots — the ring only holds NB labels). The latest-label scalar is
         already host-visible from the previous step; reading it keeps the
         host counter self-healing across restores.
      2. the read-only z-ring evict slices,
      3. the core program,
      4. the in-place pure-DUS ring writes.
    """
    sliding_idx = sliding_lag_indices(cfg)
    NB = cfg.stats.num_buckets
    advance = jax.jit(dstats.advance_one, static_argnums=1, donate_argnums=(0,))
    evict, write = staged_ring_programs()
    # APM_STAGE_TIMING=1: accumulate per-stage wall time on step.stage_ms
    # (diagnostic; each stage then pays a block_until_ready sync)
    timing = os.environ.get("APM_STAGE_TIMING") == "1"
    stage_ms = {"advance": 0.0, "evict": 0.0, "core": 0.0, "write": 0.0, "n": 0}

    def _sync(x):
        jax.block_until_ready(x)
        return time.perf_counter()

    def step(state, new_label, params):
        t0 = time.perf_counter() if timing else 0.0
        latest = int(state.stats.latest_bucket)
        nl = int(new_label)
        st = state.stats
        for lbl in range(max(latest + 1, nl - NB + 1), nl + 1):
            st = advance(st, cfg.stats, lbl)
        state = state._replace(stats=st)
        if timing:
            t1 = _sync(state.stats.counts)
            stage_ms["advance"] += (t1 - t0) * 1000

        rings = tuple(state.zscores[i].values for i in sliding_idx)
        cursors = tuple(state.zscores[i].pos for i in sliding_idx)
        evicted = evict(rings, cursors) if sliding_idx else ()
        if timing:
            t2 = _sync(evicted)
            stage_ms["evict"] += (t2 - t1) * 1000
        *outs, state2, pushes = core(state, nl, params, evicted)
        if timing:
            t3 = _sync(pushes)
            stage_ms["core"] += (t3 - t2) * 1000
        if sliding_idx:
            rings2 = tuple(state2.zscores[i].values for i in sliding_idx)
            new_cursors = tuple(state2.zscores[i].pos for i in sliding_idx)
            new_rings = write(rings2, pushes, new_cursors)
            zs = list(state2.zscores)
            for i, ring in zip(sliding_idx, new_rings):
                zs[i] = zs[i]._replace(values=ring)
            state2 = state2._replace(zscores=tuple(zs))
        if timing:
            t4 = _sync(state2.zscores[sliding_idx[0]].values if sliding_idx else 0)
            stage_ms["write"] += (t4 - t3) * 1000
            stage_ms["n"] += 1
        return (*outs, state2)

    step.stage_ms = stage_ms
    step.rebuild_integrated = False
    step.kind = "staged"
    return step


def engine_ingest(state: EngineState, cfg: EngineConfig, rows, labels, elapsed, valid) -> EngineState:
    return state._replace(
        stats=dstats.ingest(state.stats, cfg.stats, rows, labels, elapsed, valid)
    )


def engine_rebuild_aggs(state: EngineState, cfg: EngineConfig) -> EngineState:
    """Amortized exact rebuild of every sliding lag's running aggregates.

    Host loops (PipelineDriver, bench) call this every
    ``cfg.zscore_rebuild_every`` ticks; jittable and donation-friendly. A
    no-op (identity pytree) when no lag runs sliding."""
    zstates = tuple(
        dzscore.rebuild_agg_state(z, zscore_cfg(cfg, spec))
        for z, spec in zip(state.zscores, cfg.lags)
    )
    return state._replace(zscores=zstates)


def engine_needs_rebuild(cfg: EngineConfig) -> bool:
    """True when any lag maintains sliding aggregates (the host loop then
    owes a periodic engine_rebuild_aggs call)."""
    return any(zscore_cfg(cfg, spec).sliding_active for spec in cfg.lags)


def engine_rebuild_slice(state: EngineState, cfg: EngineConfig, row_start, chunk: int) -> EngineState:
    """One STAGGERED-rebuild step: exact re-aggregation of ring rows
    [row_start, row_start+chunk) for every sliding lag (ops/zscore.py
    rebuild_agg_slice). RebuildScheduler calls this every tick on a rotating
    chunk so the whole ring is re-aggregated once per
    ``cfg.zscore_rebuild_every`` ticks with no tick ever paying a full ring
    pass — the production cadence replacing the monolithic
    engine_rebuild_aggs stall. jittable; ``cfg``/``chunk`` static."""
    zstates = tuple(
        dzscore.rebuild_agg_slice(z, zscore_cfg(cfg, spec), row_start, chunk)
        for z, spec in zip(state.zscores, cfg.lags)
    )
    return state._replace(zscores=zstates)


def cpu_zero_copy_view(arr) -> np.ndarray:
    """Zero-copy numpy view of a CPU-backend device array (or one
    addressable shard's block). bfloat16 buffers — which numpy's dlpack
    import rejects — are exposed as their raw uint16 bit pattern straight
    from the device buffer (native/rebuild.cpp's is_bf16 branch decodes
    bits << 16), so no full-size cast ever materializes."""
    try:
        return np.from_dlpack(arr)
    except Exception:
        if arr.dtype.itemsize != 2:
            # the bit-view fallback is ONLY for 2-byte (bf16) buffers; a
            # wider dtype failing dlpack must surface, not decode as garbage
            raise
        if len(arr.addressable_shards) != 1:
            # shard 0's buffer holds only a fraction of a multi-shard
            # array's elements — reshaping it to the full shape would be an
            # out-of-bounds read; callers view per-shard blocks instead
            raise ValueError(
                "cpu_zero_copy_view bit-view fallback requires a "
                f"single-shard array, got {len(arr.addressable_shards)} shards"
            )
        import ctypes

        n = int(np.prod(arr.shape))
        ptr = arr.addressable_shards[0].data.unsafe_buffer_pointer()
        buf = (ctypes.c_uint16 * n).from_address(ptr)
        return np.frombuffer(buf, np.uint16).reshape(arr.shape)


def default_native_rebuild_gate(cfg: EngineConfig) -> bool:
    """ONE definition of "may the staggered rebuild use the native streaming
    kernel" shared by the single-chip and pod schedulers: CPU backend,
    single process, f32 compute, and a ring storage dtype the kernel
    decodes (f32 bits or bf16 bits)."""
    return (
        jax.default_backend() == "cpu"
        and jax.process_count() == 1
        and cfg.stats.dtype != jnp.float64
        and cfg.zscore_ring_dtype in (None, jnp.float32, jnp.bfloat16)
    )


class _StaggeredRebuildBase:
    """Shared shell of the two staggered-rebuild schedulers: the chunk
    rotation, the native-try/permanent-fallback policy, and the benchmark
    sync boundary. Subclasses provide ``_native_step(state, start)`` and
    ``_slice_call(state, start)`` plus all their construction."""

    active: bool = False

    def step_synced(self, state: EngineState) -> EngineState:
        """step() + block until the merged aggregates are materialized — the
        timing boundary benchmarks charge (one definition of "what must be
        waited on", instead of copies reaching into _sliding_idx)."""
        state = self.step(state)
        if self.active:
            jax.block_until_ready([state.zscores[i].agg for i in self._sliding_idx])
        return state

    def step(self, state: EngineState) -> EngineState:
        """Rebuild this tick's due chunk; returns the updated state."""
        if not self.active:
            return state
        start = self.starts[self._i]
        self._i = (self._i + 1) % self.n_chunks
        if self._native:
            try:
                return self._native_step(state, start)
            except Exception:
                # e.g. dlpack view unavailable — fall back permanently, but
                # never silently: the jitted slice path is ~25x slower on CPU
                self._native = False
                import logging

                logging.getLogger(type(self).__module__).warning(
                    "native staggered rebuild failed; falling back to the "
                    "jitted slice path for the rest of the process",
                    exc_info=True,
                )
        return self._slice_call(state, start)


class RebuildScheduler(_StaggeredRebuildBase):
    """Host-side rotation of the staggered sliding-aggregate rebuild.

    ``step(state)`` is called once per engine tick; it rebuilds ONE
    contiguous row chunk (rebuild_chunk_rows sizes it so a full rotation
    spans ``cfg.zscore_rebuild_every`` ticks) and returns the new state.
    Every row's rebuild interval stays <= rebuild_every ticks — the same
    drift/blind-spot bound as the monolithic pass, minus the multi-second
    tick stall at pod shapes (the reference pays its window recompute on
    EVERY entry, stream_calc_z_score.js:66-104; this is the amortized
    replacement being staggered).

    Backend-adaptive like the percentile stage: on the single-process CPU
    backend with the toolchain present, the chunk pass runs in the native
    streaming kernel (native/rebuild.cpp, ~25x the XLA:CPU variadic reduce)
    against zero-copy dlpack ring views, and only the [chunk, 3] partials
    enter the jitted merge (ops/zscore.py merge_agg_slice). Everywhere else
    (TPU, no toolchain) the whole slice rebuild runs in one jitted program
    — on TPU the fused reduce over a [chunk, 3, L] slice is microseconds.
    A native-path failure permanently falls back to the jitted path.
    """

    def __init__(self, cfg: EngineConfig, *, allow_native: Optional[bool] = None):
        self.cfg = cfg
        self.active = engine_needs_rebuild(cfg)
        if not self.active:
            return
        S = cfg.capacity
        self.chunk = dzscore.rebuild_chunk_rows(S, cfg.zscore_rebuild_every)
        self.n_chunks = -(-S // self.chunk)
        # ragged tail chunks clamp (re-rebuilding a few rows is harmless —
        # the rebuild is idempotent) so ONE compiled program serves all
        self.starts = [min(i * self.chunk, S - self.chunk) for i in range(self.n_chunks)]
        self._i = 0
        self._sliding_idx = sliding_lag_indices(cfg)
        self._slice_fn = jax.jit(
            engine_rebuild_slice, static_argnums=(1, 3), donate_argnums=(0,)
        )
        if allow_native is None:
            allow_native = default_native_rebuild_gate(cfg)
        self._native = False
        if allow_native:
            from . import native as _native

            self._native = _native.have_native_rebuild()
        if self._native:

            def _make_merge(zc):
                def m(agg, row_start, cnt, vsum, vsumsq, anchor, vmin, vmax, last_push):
                    return dzscore.merge_agg_slice(
                        agg, zc, row_start, cnt, vsum, vsumsq, anchor, vmin, vmax, last_push
                    )

                # NO donation: the [S, 3] leaf copies are noise, and keeping
                # the old agg buffers alive makes the jitted fallback safe
                # even if a multi-lag native step fails halfway through
                return jax.jit(m)

            self._merge_fns = {
                i: _make_merge(zscore_cfg(cfg, cfg.lags[i])) for i in self._sliding_idx
            }

    def _slice_call(self, state: EngineState, start: int) -> EngineState:
        return self._slice_fn(state, self.cfg, start, self.chunk)

    # apm: sync-boundary: rebuild scheduler's native window-agg pass reads the ring chunk back for the C++ kernel (merge returns to device)
    def _native_step(self, state: EngineState, start: int) -> EngineState:
        from . import native as _native

        zs = list(state.zscores)
        end = start + self.chunk
        for i in self._sliding_idx:
            z = zs[i]
            agg = z.agg
            ring = cpu_zero_copy_view(z.values)  # zero-copy on the CPU backend
            cnt = np.from_dlpack(agg.cnt)[start:end]
            vsum = np.from_dlpack(agg.vsum)[start:end]
            anc = np.from_dlpack(agg.anchor)[start:end]
            # the incremental mean as the variance anchor (rebuild_agg_state);
            # maximum(cnt,1) values are exact in f32, so this matches the
            # jitted producer's f32 arithmetic
            anchor_est = np.where(
                cnt > 0, anc + vsum / np.maximum(cnt, 1).astype(np.float32), anc
            ).astype(np.float32)
            L = ring.shape[-1]
            last_slot = (int(z.pos) - 1) % L
            c, vs, vs2, mn, mx, lastp = _native.window_aggs_native(
                ring[start:end], anchor_est, last_slot
            )
            zs[i] = z._replace(
                agg=self._merge_fns[i](agg, start, c, vs, vs2, anchor_est, mn, mx, lastp)
            )
        return state._replace(zscores=tuple(zs))


def engine_derive_aggs(state: EngineState, cfg: EngineConfig) -> EngineState:
    """Derive the sliding aggregates from freshly-restored rings — the ONE
    restore-time derivation, shared by the npz load_resume and the orbax
    checkpoint restore (the aggregates are never serialized; SlidingAgg
    docstring)."""
    zstates = []
    for z, spec in zip(state.zscores, cfg.lags):
        zc = zscore_cfg(cfg, spec)
        agg = dzscore.build_agg(z.values, zc, z.pos) if zc.sliding_active else None
        zstates.append(z._replace(agg=agg))
    return state._replace(zscores=tuple(zstates))


def build_engine_config(apm_config: dict, capacity: Optional[int] = None) -> EngineConfig:
    """Derive EngineConfig from the APM config tree (apm_config.json shape)."""
    eng = apm_config.get("tpuEngine", {})
    calc = apm_config.get("streamCalcStats", {})
    zcfg = apm_config.get("streamCalcZScore", {})
    acfg = apm_config.get("streamProcessAlerts", {})

    if capacity is None:
        capacity = int(eng.get("serviceCapacity", 1024))
    dtype = jnp.float64 if eng.get("dtype") == "float64" else jnp.float32
    ring_name = eng.get("zscoreRingDtype") or None
    if ring_name is not None:
        ring_dtypes = {"float32": jnp.float32, "float64": jnp.float64,
                       "bfloat16": jnp.bfloat16}
        if ring_name not in ring_dtypes:
            raise ValueError(
                f"tpuEngine.zscoreRingDtype must be one of {sorted(ring_dtypes)}, "
                f"got {ring_name!r}"
            )
        ring_dtype = ring_dtypes[ring_name]
        if ring_dtype == dtype:
            ring_dtype = None  # same as compute dtype: keep configs hashable-equal
    else:
        ring_dtype = None
    stats_cfg = dstats.StatsConfig(
        capacity=capacity,
        window_sz=int(calc.get("windowSizeInIntervals", 30)),
        buffer_sz=int(calc.get("bufferSizeInIntervals", 6)),
        interval_len_s=int(calc.get("intervalLengthInSeconds", 10)),
        samples_per_bucket=int(eng.get("samplesPerBucket", 128)),
        dtype=dtype,
        percentile_impl=str(eng.get("percentileImpl", "auto")),
    )
    suppressed_lags = {int(x) for x in acfg.get("suppressedLags", [])}
    lags = tuple(
        LagSpec(
            int(d["LAG"]),
            int(d["LAG"]) in suppressed_lags,
            bool(d.get("ROBUST", False)),
        )
        for d in zcfg.get("defaults", [])
    )
    def rule_for(suppressed: bool) -> dalerts.AlertRuleConfig:
        return dalerts.AlertRuleConfig(
            hard_min_ms=float(acfg.get("hardMinMsAlertThreshold", 200)),
            hard_min_tpm=float(acfg.get("hardMinTpmAlertThreshold", 1.0)),
            alert_on_both_only=bool(acfg.get("alertOnBothOnly", True)),
            window_sz=int(acfg.get("rollingAlertWindowSizeInIntervals", 60)),
            required_bad=int(acfg.get("requiredNumberBadIntervalsInAlertWindowToTrigger", 45)),
            lag_suppressed=suppressed,
        )

    rules = tuple(rule_for(spec.suppressed) for spec in lags)
    ewma_specs = dewma.specs_from_config(eng)
    ewma_rules = tuple(rule_for(spec.suppressed) for spec in ewma_specs)
    vp = str(eng.get("zscoreVariancePass", "auto"))
    if vp not in ("auto", "sliding", "one", "two"):
        raise ValueError(
            f"tpuEngine.zscoreVariancePass must be auto|sliding|one|two, got {vp!r}"
        )
    # "auto" = sliding O(1) aggregates for f32 production (ops/zscore.py pins
    # f64 parity mode and robust lags to the full-window computation
    # regardless of this flag); "one"/"two" force the ring-pass variants
    sliding = vp in ("auto", "sliding")
    onepass = vp != "two"
    tick_exec = str(eng.get("tickExecutor", "auto"))
    if tick_exec not in ("auto", "fused", "staged"):
        raise ValueError(
            f"tpuEngine.tickExecutor must be auto|fused|staged, got {tick_exec!r}"
        )
    return EngineConfig(
        stats=stats_cfg, lags=lags, alert_rules=rules, quantize=True,
        ewma=ewma_specs, ewma_rules=ewma_rules, zscore_ring_dtype=ring_dtype,
        zscore_onepass=onepass, zscore_sliding=sliding,
        zscore_rebuild_every=int(eng.get("zscoreRebuildEvery", 64)),
        tick_executor=tick_exec,
    )


def make_demo_engine(
    capacity: int,
    samples_per_bucket: int,
    lag_settings: Sequence[Tuple[int, float, float]],
    *,
    hard_max_ms: float = 10000.0,
    ewma_channels: Sequence[dict] = (),
    ring_dtype: Optional[str] = None,
) -> Tuple[EngineConfig, EngineState, EngineParams]:
    """(cfg, fresh state, uniform params) for benches/dryruns/tests.

    ``lag_settings`` is [(lag, threshold, influence), ...]; ``ewma_channels``
    is a list of tpuEngine.ewmaChannels dicts (uppercase keys). Single source
    for the engine-setup boilerplate shared by bench.py, __graft_entry__.py
    and the sharding tests.
    """
    from .config import default_config

    cfg_tree = default_config()
    cfg_tree["streamCalcZScore"]["defaults"] = [
        {"LAG": lag, "THRESHOLD": thr, "INFLUENCE": infl}
        for lag, thr, infl in lag_settings
    ]
    cfg_tree["tpuEngine"]["serviceCapacity"] = capacity
    cfg_tree["tpuEngine"]["samplesPerBucket"] = samples_per_bucket
    if ewma_channels:
        cfg_tree["tpuEngine"]["ewmaChannels"] = list(ewma_channels)
    if ring_dtype is not None:
        cfg_tree["tpuEngine"]["zscoreRingDtype"] = ring_dtype
    cfg = build_engine_config(cfg_tree, capacity)
    state = engine_init(cfg)
    S = cfg.capacity
    params = EngineParams(
        thresholds=tuple(
            jnp.full(S, thr, cfg.stats.dtype) for _lag, thr, _infl in lag_settings
        ),
        influences=tuple(
            jnp.full(S, infl, cfg.stats.dtype) for _lag, _thr, infl in lag_settings
        ),
        hard_max_ms=jnp.full(S, hard_max_ms, cfg.stats.dtype),
        suppressed=jnp.zeros(S, bool),
        active=jnp.ones(S, bool),  # demo fleets are fully populated
        # populated whenever channels exist so the params pytree matches the
        # sharded in_specs (parallel/sharded._params_specs mirrors cfg.ewma)
        ewma_thresholds=tuple(
            jnp.full(S, spec.threshold, cfg.stats.dtype) for spec in cfg.ewma
        ),
        ewma_influences=tuple(
            jnp.full(S, spec.influence, cfg.stats.dtype) for spec in cfg.ewma
        ),
    )
    return cfg, state, params


class PipelineDriver:
    """Host loop around the fused device step.

    Consumes TxEntry objects (or raw CSV lines), micro-batches them, splits at
    tick boundaries, and emits:
    - ordered raw tx lines for the DB sink (min-heap drain up to the window
      edge, stream_calc_stats.js:364 role),
    - StatEntry lines ('stats' queue parity),
    - FullStatEntry lines per lag ('z_score' queue parity),
    - AlertEntry via the provided AlertsManager (cooldown applied).
    """

    def __init__(
        self,
        apm_config: dict,
        *,
        capacity: Optional[int] = None,
        alerts_manager=None,
        on_stat: Optional[Callable[[StatEntry], None]] = None,
        on_fullstat: Optional[Callable[[FullStatEntry], None]] = None,
        on_ordered_tx: Optional[Callable[[TxEntry], None]] = None,
        on_ordered_csv: Optional[Callable[[str], None]] = None,
        on_alert: Optional[Callable] = None,
        on_overflow: Optional[Callable[[int, int], None]] = None,
        on_fullstat_csv: Optional[Callable[[List[str]], None]] = None,
        logger=None,
        micro_batch_size: int = 8192,
        async_emission: Optional[bool] = None,
        metrics_registry=None,
    ):
        self.apm_config = apm_config
        self.cfg = build_engine_config(apm_config, capacity)
        self.state = engine_init(self.cfg)
        self.registry = ServiceRegistry(self.cfg.capacity)
        self.alerts_manager = alerts_manager
        self.on_stat = on_stat
        self.on_fullstat = on_fullstat
        if on_ordered_tx is not None and on_ordered_csv is not None:
            raise ValueError(
                "on_ordered_tx and on_ordered_csv are mutually exclusive "
                "(one ordered-tx drain per driver); pick the object heap or "
                "the raw-line fast path"
            )
        self.on_ordered_tx = on_ordered_tx
        # fast-path variant of on_ordered_tx: receives the RAW tx CSV line at
        # the tick-boundary drain, end_ts-ordered, without TxEntry objects or
        # per-entry heap pushes. Served by feed_csv_batch only (feed() keeps
        # the object heap); producers emit normalized to_csv() lines so the
        # raw line is the same wire bytes the object path would re-serialize.
        self.on_ordered_csv = on_ordered_csv
        self._tx_backlog: List[Tuple[float, str]] = []  # (end_ts, raw line)
        self.on_alert = on_alert
        self.on_overflow = on_overflow
        # bulk wire-format emission: receives the tick's FullStat CSV lines
        # for one channel as a list, built without per-row dataclass objects
        # (byte-identical to [fs.to_csv() for fs in ...]); the fast path for
        # queue-writing consumers at 10k-row fleets
        self.on_fullstat_csv = on_fullstat_csv
        self.logger = logger
        # percentile-reservoir overflow telemetry (ops/stats.py reservoir):
        # rows whose window percentile was estimated from a uniform CAP-sample
        # rather than computed exactly — bounded error, but worth alerting on
        # so operators can raise samplesPerBucket if it is chronic
        self.overflow_rows_total = 0
        self.overflow_ticks = 0
        self._overflow_last_logged_tick = -1000
        self.micro_batch_size = micro_batch_size
        # at-least-once delivery coupling (runtime/worker.py epoch cycle):
        # the per-queue {"epoch": n, "dedup": [msg ids], ...} tree the last
        # save_resume carried / load_resume recovered. None = snapshot
        # predates the feature or the worker runs at-most-once.
        self.delivery_state: Optional[dict] = None
        # -- incremental delta-checkpoint capture (deltachain.py) -----------
        # Enabled by enable_delta_capture() (the worker's checkpointMode:
        # "delta"); at-most-once / full-snapshot drivers pay one bool check
        # per bulk feed. Tracking granularity: stats mutations are dirty
        # (row, bucket-slot) CELLS (feeds scatter into exactly those cells;
        # tick ring-advances are derivable from the tick labels), z rings
        # are one pushed column per tick at the shared cursor, EWMA channels
        # one season-slot column per tick — so a delta's size is
        # proportional to the epoch's ingest + tick count, not state size.
        self._delta_track = False
        self._delta_np_gather = False
        self._dirty_cells: set = set()  # packed row*NB+slot ints since last commit
        self._delta_ticks: List[int] = []  # tick labels since last commit
        self._delta_pos0: List[int] = []  # per-lag ring cursor at last commit
        self._delta_reg_base = 0  # registry count at last commit
        self.heap = MinHeap(lambda tx: tx.end_ts)
        self._pending: List[Tuple[int, int, float]] = []  # (row, label, elapsed)
        self._latest_label = 0  # host mirror of stats.latest_bucket (hot path)
        # native batch decoder (native/decoder.cpp): created lazily on the
        # first feed_csv_batch; None = unavailable or disabled, use the
        # numpy path. _decode2row maps decoder key ids -> registry rows.
        self._use_native_decode = bool(
            apm_config.get("tpuEngine", {}).get("nativeDecode", True)
        )
        self._native_dec = None
        self._native_dec_tried = False
        self._reset_decode_map()
        # -- telemetry plane (obs/): per-stage tick tracing + e2e latency ----
        # Host-side perf_counter boundaries ONLY — no new device syncs (the
        # emit stage's np.asarray readback is the blocking sync point we
        # already pay; DESIGN.md §4). Cost is ~5 histogram observes per TICK
        # (microseconds against the ~0.5 ms tick floor); observability.enabled
        # = false removes even that.
        self._telemetry = bool(apm_config.get("observability", {}).get("enabled", True))
        self._intake_oldest_ts: Optional[float] = None  # oldest undelivered ingest stamp
        self._emitting_intake_ts: Optional[float] = None
        # -- distributed trace plane + decision provenance -------------------
        # Sampled per-transaction traces (obs/trace): the worker registers
        # in-flight sampled transactions via note_trace(); the tick that
        # closes a transaction's bucket records its tick/emit/alert spans.
        # Alert decision records (obs/decisions) capture the z inputs behind
        # every page. Both are alert/trace-path only: an unsampled message
        # costs nothing here, and a tick with no live traces pays one
        # truthiness check.
        self._live_traces: deque = deque(maxlen=256)
        self._emitting_traces: Sequence[dict] = ()
        self._emit_wall_start: Optional[float] = None
        # tick wall-clock windows by label: the "tick" span of a claimed
        # trace must describe the tick that CLOSED its bucket even when
        # async-emission delivers the emission one tick late
        self._tick_walls: Dict[int, Tuple[float, float]] = {}
        # host numpy mirrors of the per-row alert params (threshold/influence
        # by channel id) + channel -> device-state index maps; refreshed with
        # the device params, read only on the alert path (decision records)
        self._host_thresholds: Dict = {}
        self._host_influences: Dict = {}
        self._lag_index: Dict = {}
        self._ewma_index: Dict = {}
        if self._telemetry:
            from .obs import get_registry
            from .obs.decisions import get_decisions
            from .obs.registry import DEFAULT_COUNT_BUCKETS
            from .obs.trace import get_tracer
            from .obs.tracing import TickTracer

            self._trace = get_tracer()
            self._decisions = get_decisions()
            reg = metrics_registry if metrics_registry is not None else get_registry()
            self._tracer = TickTracer(reg)
            self._m_capacity = reg.gauge(
                "apm_engine_capacity", "Device state rows allocated [S]"
            )
            self._m_services = reg.gauge(
                "apm_engine_services", "Registered (server, service) rows"
            )
            self._m_tx = reg.counter(
                "apm_engine_tx_ingested_total", "Transactions scattered into device state"
            )
            self._m_overflow_rows = reg.counter(
                "apm_engine_overflow_row_ticks_total",
                "Row-ticks whose percentile fell back to the reservoir estimate",
            )
            self._m_grows = reg.counter(
                "apm_engine_capacity_grows_total", "Capacity-doubling recompiles"
            )
            self._m_emit_lat = reg.histogram(
                "apm_e2e_ingest_to_emit_seconds",
                "Transport ingest stamp -> tick emission fan-out (oldest record)",
            )
            self._m_alert_lat = reg.histogram(
                "apm_e2e_ingest_to_alert_seconds",
                "Transport ingest stamp -> alert dispatch (oldest record)",
            )
            self._m_alerts = reg.counter(
                "apm_alerts_total", "Alert triggers dispatched by the driver"
            )
            self._m_pending_batch = reg.histogram(
                "apm_engine_flush_batch_size",
                "Records per ingest scatter",
                buckets=DEFAULT_COUNT_BUCKETS,
            )
            # wall-clock attribution (obs.attrib): the tick stage splits the
            # TickTracer already measures double as busy seconds for the
            # bottleneck estimator — same perf_counter boundaries, zero new
            # syncs
            from .obs.attrib import (
                STAGE_TICK_DISPATCH,
                STAGE_TICK_EMIT,
                STAGE_TICK_REBUILD,
                STAGE_TICK_TX_DRAIN,
                get_attrib,
            )

            _att = get_attrib()
            self._att_tick = {
                "dispatch": _att.clock(STAGE_TICK_DISPATCH),
                "rebuild": _att.clock(STAGE_TICK_REBUILD),
                "tx_drain": _att.clock(STAGE_TICK_TX_DRAIN),
                "emit": _att.clock(STAGE_TICK_EMIT),
            }
        else:
            self._tracer = None
            self._trace = None
            self._decisions = None
            self._att_tick = None
        self._refresh_params()
        # emission pipelining (tpuEngine.asyncEmission / the async_emission
        # kwarg; default OFF): hold each tick's TickEmission and fetch it
        # while the NEXT tick's dispatch is in flight, overlapping the
        # device->host readback + host fan-out with device compute (CPU and
        # TPU dispatch are both async). Costs one tick of emission/alert
        # latency — a replay/catch-up throughput mode, never the default
        # (the <100 ms detection budget is per-tick).
        if async_emission is None:
            async_emission = bool(
                apm_config.get("tpuEngine", {}).get("asyncEmission", False)
            )
        self._async_emission = async_emission
        self._pending_emission: Optional[Tuple[int, TickEmission, int]] = None
        # jax.jit memoizes per static EngineConfig, so growth (a new cfg)
        # recompiles automatically through these two callables
        self._step = make_engine_step(self.cfg)
        self._ingest = jax.jit(engine_ingest, static_argnums=1, donate_argnums=(0,))
        # the fused executor folds the staggered-rebuild chunk into the tick
        # program; only the staged executor owes the separate scheduler
        self._rebuild_sched = (
            None if self._step.rebuild_integrated else RebuildScheduler(self.cfg)
        )

    # -- params / growth -----------------------------------------------------
    def _refresh_params(self) -> None:
        zcfg = self.apm_config.get("streamCalcZScore", {})
        acfg = self.apm_config.get("streamProcessAlerts", {})
        lag_values = [spec.lag for spec in self.cfg.lags]
        np_dtype = self._np_dtype()
        zparams = self.registry.zscore_params(zcfg, lag_values, dtype=np_dtype)
        aparams = self.registry.alert_params(acfg, dtype=np_dtype)
        eparams = self.registry.ewma_params(
            self.apm_config.get("tpuEngine", {}), self.cfg.ewma, dtype=np_dtype
        )
        self.params = EngineParams(
            thresholds=tuple(jnp.asarray(zparams[l]["threshold"]) for l in lag_values),
            influences=tuple(jnp.asarray(zparams[l]["influence"]) for l in lag_values),
            hard_max_ms=jnp.asarray(aparams["hard_max_ms"]),
            suppressed=jnp.asarray(aparams["suppressed"]),
            active=jnp.asarray(np.arange(self.cfg.capacity) < self.registry.count),
            ewma_thresholds=tuple(
                jnp.asarray(eparams[spec.channel_id]["threshold"]) for spec in self.cfg.ewma
            ),
            ewma_influences=tuple(
                jnp.asarray(eparams[spec.channel_id]["influence"]) for spec in self.cfg.ewma
            ),
        )
        self._params_registry_count = self.registry.count
        if self._telemetry:
            # decision-record inputs (obs/decisions): the exact host vectors
            # the device params were built from, keyed by channel id (lag
            # value for z-score channels, negative channel_id for EWMA)
            self._host_thresholds = {
                int(l): zparams[l]["threshold"] for l in lag_values
            }
            self._host_influences = {
                int(l): zparams[l]["influence"] for l in lag_values
            }
            for spec in self.cfg.ewma:
                self._host_thresholds[spec.channel_id] = eparams[spec.channel_id]["threshold"]
                self._host_influences[spec.channel_id] = eparams[spec.channel_id]["influence"]
            self._lag_index = {int(spec.lag): i for i, spec in enumerate(self.cfg.lags)}
            self._ewma_index = {spec.channel_id: i for i, spec in enumerate(self.cfg.ewma)}
        if self._tracer is not None:
            self._m_capacity.set(self.cfg.capacity)
            self._m_services.set(self.registry.count)

    def note_intake_time(self, ingest_ts: Optional[float]) -> None:
        """Record a message's transport ingest stamp (header ``ingest_ts``);
        the oldest outstanding stamp anchors the ingest->emit/alert latency
        observed at the next emission. Benign-racy min (GIL-atomic reads):
        called from the broker consumer thread while the device thread
        resets it."""
        if ingest_ts is None or self._tracer is None:
            return
        cur = self._intake_oldest_ts
        if cur is None or ingest_ts < cur:
            self._intake_oldest_ts = ingest_ts

    def note_trace(
        self,
        trace_id: str,
        server: str,
        service: str,
        label: int,
        start: float,
        end: Optional[float] = None,
        **attrs,
    ) -> None:
        """Register one SAMPLED in-flight transaction (the worker's feed
        boundary). Records the ``feed`` span (transport delivery -> device
        absorb) and keeps the trace live until the tick that closes its
        bucket emits — _process_emission then records the tick/emit (and
        alert, when fired) spans under the same trace_id. Called only for
        the 1/rate sampled messages, never on the per-message hot path."""
        if self._trace is None:
            return
        end = time.time() if end is None else end
        self._trace.span(
            trace_id, "feed", start, end,
            server=server, service=service, label=int(label), **attrs,
        )
        self._live_traces.append(
            {
                "trace_id": trace_id,
                "server": server,
                "service": service,
                "label": int(label),
            }
        )

    def apply_config(self, apm_config: dict) -> None:
        """Hot-reload hook: re-derive per-row params (thresholds, overrides,

        suppression) without touching device state — the live-actionable
        subset, like the reference's watcher callbacks (§5.6)."""
        self.apm_config = apm_config
        self._refresh_params()
        if self.alerts_manager is not None:
            self.alerts_manager.set_config(apm_config.get("streamProcessAlerts", {}))

    def _grow(self) -> None:
        self.drain_emission()  # pending emission belongs to the old capacity
        new_capacity = self.cfg.capacity * 2
        if self.logger:
            self.logger.warning(f"Growing service capacity {self.cfg.capacity} -> {new_capacity} (recompile)")
        self.registry = self.registry.grown(new_capacity)
        stats_state, stats_cfg = dstats.grow_state(self.state.stats, self.cfg.stats, new_capacity)
        zstates = []
        for i, spec in enumerate(self.cfg.lags):
            zs, _ = dzscore.grow_state(
                self.state.zscores[i], zscore_cfg(self.cfg, spec), new_capacity
            )
            zstates.append(zs)
        pad_n = new_capacity - self.cfg.capacity
        counters = tuple(jnp.pad(c, (0, pad_n)) for c in self.state.alert_counters)
        estates = tuple(dewma.grow_state(e, new_capacity) for e in self.state.ewmas)
        ecounters = tuple(jnp.pad(c, (0, pad_n)) for c in self.state.ewma_counters)
        self.cfg = self.cfg._replace(stats=stats_cfg)
        self.state = EngineState(stats_state, tuple(zstates), counters, estates, ecounters)
        # the staged step closes over cfg (capacity changed: new programs);
        # the rebuild rotation restarts at chunk 0 — harmless (idempotent)
        self._step = make_engine_step(self.cfg)
        self._rebuild_sched = (
            None if self._step.rebuild_integrated else RebuildScheduler(self.cfg)
        )
        if self._tracer is not None:
            self._m_grows.inc()
        self._refresh_params()

    def _row_for(self, server: str, service: str) -> int:
        try:
            return self.registry.lookup_or_add(server, service)
        except CapacityExceeded:
            self._flush_pending()
            self._grow()
            return self.registry.lookup_or_add(server, service)

    # -- feed ----------------------------------------------------------------
    def feed(self, tx: TxEntry) -> None:
        """One transaction (consumeMsg parity, stream_calc_stats.js:331-371)."""
        if math.isnan(tx.end_ts) or math.isnan(tx.elapsed):
            # malformed numerics are rejected at intake: a stored NaN sample
            # would poison window sums AND make the percentile basis depend
            # on the impl's NaN ordering (sort vs top_k)
            if self.logger:
                self.logger.error(f"NaN end_ts/elapsed in txEntry, dropped: {tx}")
            return
        label = int(tx.end_ts) // 10000
        # host-side label mirror: avoids a device->host sync per message
        if label > self._latest_label:
            self._flush_pending()
            self._run_tick(label)
            self._latest_label = label
        row = self._row_for(tx.server, tx.service)
        self._pending.append((row, label, float(tx.elapsed)))
        if self.on_ordered_tx is not None:
            self.heap.push(tx)
        elif self.on_ordered_csv is not None:  # mixed callers: feed() must
            # serve the CSV drain too, not only feed_csv_batch
            self._tx_backlog.append((float(tx.end_ts), tx.to_csv()))
        if len(self._pending) >= self.micro_batch_size:
            self._flush_pending()

    def feed_batch(self, txs: Sequence[TxEntry]) -> None:
        for tx in txs:
            self.feed(tx)

    def feed_csv_batch(self, lines: Sequence[str]) -> int:
        """Bulk host fast path: decode ``tx|...`` pipe-CSV lines with numpy
        split/astype and ingest them as arrays, skipping TxEntry objects, the
        per-entry heap push, and the per-tuple pending list entirely.

        Emissions are identical to feeding line-by-line: arrival order is
        kept, and ticks fire exactly where feed() would fire them — before
        each entry whose label exceeds every label seen so far (the
        stats-before-addData event order, stream_calc_stats.js:348-370).
        Entries between two ticks are scattered as one array batch. Returns
        the number of transactions ingested. Falls back to the object path
        when an ordered-tx consumer needs the heap.
        """
        if self.on_ordered_tx is not None:
            from .entries import EntryFactory

            fac = EntryFactory()
            n = 0
            for line in lines:
                entry = fac.from_csv(line)
                if entry is not None and entry.type == "tx":
                    self.feed(entry)
                    n += 1
                elif self.logger:
                    self.logger.info(f"Not a transactions entry: {line[:200]}")
            return n

        dec = self._decoder()
        if dec is not None:
            return self.feed_csv_bytes("\n".join(lines).encode("utf-8"))

        good = []
        good_lines: List[str] = []
        n_bad = 0
        for line in lines:
            p = line.split("|")
            if len(p) == 9 and p[0] == "tx":
                good.append(p)
                good_lines.append(line)
            else:
                n_bad += 1
        if n_bad and self.logger:
            self.logger.info(f"Skipped {n_bad} non-tx/malformed lines in batch")
        if not good:
            return 0
        fields = np.array(good, dtype=object)  # [N, 9] strings
        # numpy float parsing accepts forms JS parseInt does not ('1e5',
        # 'inf', '1_0'); the wire is explicitly interoperable, so rows whose
        # numerics are not plain decimals take the js_parse_int slow path to
        # keep this batch path's labels identical to feed()'s
        plain = np.fromiter(
            (
                bool(_PLAIN_NUMBER.match(p[6])) and bool(_PLAIN_NUMBER.match(p[7]))
                for p in good
            ),
            bool,
            len(good),
        )
        from .entries import js_parse_int

        if plain.all():
            end_ts = fields[:, 6].astype(np.float64)
            elaps = fields[:, 7].astype(np.float64)
        else:
            end_ts = np.empty(len(good), np.float64)
            elaps = np.empty(len(good), np.float64)
            pi = np.nonzero(plain)[0]
            if len(pi):
                end_ts[pi] = fields[pi, 6].astype(np.float64)
                elaps[pi] = fields[pi, 7].astype(np.float64)
            for i in np.nonzero(~plain)[0]:
                end_ts[i] = js_parse_int(fields[i, 6])
                elaps[i] = js_parse_int(fields[i, 7])
        end_ts = np.trunc(end_ts)  # TxEntry applies js_parse_int (int truncation)
        elaps = np.trunc(elaps)
        ok = ~np.isnan(end_ts) & ~np.isnan(elaps)  # same intake filter as feed()
        n_nan = int(len(end_ts) - ok.sum())
        if n_nan:
            if self.logger:
                self.logger.error(f"NaN end_ts/elapsed in batch: {n_nan} lines dropped")
            fields, end_ts, elaps = fields[ok], end_ts[ok], elaps[ok]
            good_lines = [gl for gl, o in zip(good_lines, ok) if o]
            if len(fields) == 0:
                return 0
        labels = (end_ts.astype(np.int64) // 10000).astype(np.int32)
        keys = np.array(
            [s + "\x00" + v for s, v in zip(fields[:, 1], fields[:, 2])]
        )

        def resolve_rows(lo: int, hi: int) -> np.ndarray:
            # registry rows for one segment: each unique (server, service)
            # resolved once. Per-SEGMENT (not per-batch) so a tick only ever
            # sees services registered by entries processed before it, and
            # new keys register in FIRST-APPEARANCE order (np.unique sorts,
            # which would permute emission row order vs feed())
            uk, first_idx, inv = np.unique(
                keys[lo:hi], return_index=True, return_inverse=True
            )
            rowmap = np.empty(len(uk), np.int32)
            for j in np.argsort(first_idx, kind="stable"):
                rowmap[j] = self._row_for(*uk[j].split("\x00", 1))
            return rowmap[inv]

        track_ordered = self.on_ordered_csv is not None
        ets_list = end_ts.tolist() if track_ordered else None

        def backlog(lo: int, hi: int) -> None:
            self._tx_backlog.extend(zip(ets_list[lo:hi], good_lines[lo:hi]))

        self._walk_tick_segments(
            labels,
            lambda lo, hi: self._ingest_arrays(
                resolve_rows(lo, hi), labels[lo:hi], elaps[lo:hi]
            ),
            backlog if track_ordered else None,
        )
        return len(labels)

    def _walk_tick_segments(self, labels: np.ndarray, ingest_segment, backlog_segment) -> None:
        """Shared tick-ordering walk for the bulk intake paths.

        Ticks fire exactly where feed() would fire them: before each entry
        whose label exceeds every label seen so far — INCLUDING the pre-batch
        latest. Without the floor, a batch that is internally increasing but
        wholly below the resumed latest (stale backfill after a restart)
        would tick backward and regress the label mirror (caught by the soak
        test's mid-run kill/restore). Entries between two ticks form one
        segment: ``backlog_segment(lo, hi)`` (if given) then
        ``ingest_segment(lo, hi)`` run before the tick that follows them."""
        self._flush_pending()  # interleaved feed() entries must not reorder
        running_max = np.maximum(np.maximum.accumulate(labels), self._latest_label)
        prior = np.concatenate([[self._latest_label], running_max[:-1]])
        tick_points = np.nonzero(running_max > prior)[0]
        idx = 0
        for i in tick_points:
            i = int(i)
            if i > idx:
                if backlog_segment is not None:
                    backlog_segment(idx, i)
                ingest_segment(idx, i)
                idx = i
            label = int(labels[i])
            self._run_tick(label)
            self._latest_label = label
        if backlog_segment is not None:
            backlog_segment(idx, len(labels))
        ingest_segment(idx, len(labels))

    def _reset_decode_map(self) -> None:
        # decoder-id -> registry row; -1 = interned but never registered (the
        # id's records were all NaN-dropped so far — the numpy path would not
        # have registered that key either). _decode_keys caches the decoder's
        # id -> (server, service) strings, fetched incrementally.
        self._decode2row = np.full(256, -1, np.int32)
        self._decode_keys: List[Tuple[str, str]] = []

    def _decoder(self):
        """The native batch decoder, created lazily; None when disabled or
        the toolchain is unavailable (callers fall back to the numpy path)."""
        if not self._use_native_decode:
            return None
        if not self._native_dec_tried:
            self._native_dec_tried = True
            try:
                from .native import TxDecoder

                self._native_dec = TxDecoder()
                self._reset_decode_map()
            except Exception as e:
                self._native_dec = None
                if self.logger:
                    self.logger.info(f"native decoder unavailable, using numpy path: {e}")
        return self._native_dec

    def feed_csv_bytes(self, blob: bytes) -> int:
        """Bulk intake of a newline-separated ``tx|...`` byte blob through the
        native decoder — one C++ pass instead of per-line Python string ops.
        Emission/tick semantics are identical to :meth:`feed_csv_batch`
        (asserted by tests/test_native.py parity tests). Falls back to the
        numpy path when the native decoder is unavailable."""
        dec = self._decoder()
        if dec is None or self.on_ordered_tx is not None:
            return self.feed_csv_batch(blob.decode("utf-8", "replace").splitlines())

        from .entries import js_parse_int

        end_ts, elaps, keyids, offs, lens, flags, n_bad = dec.decode(blob)
        if n_bad and self.logger:
            self.logger.info(f"Skipped {n_bad} non-tx/malformed lines in batch")
        if len(end_ts) == 0:
            return 0
        # exotic numerics (non-ASCII bytes): re-parse with the reference
        # implementation so the native path cannot silently diverge
        for i in np.nonzero(flags & 1)[0]:
            o, l = int(offs[i]), int(lens[i])
            p = blob[o : o + l].decode("utf-8", "replace").split("|")
            end_ts[i] = js_parse_int(p[6])
            elaps[i] = js_parse_int(p[7])
        ok = ~np.isnan(end_ts) & ~np.isnan(elaps)  # same intake filter as feed()
        n_nan = int(len(end_ts) - ok.sum())
        if n_nan:
            if self.logger:
                self.logger.error(f"NaN end_ts/elapsed in batch: {n_nan} lines dropped")
            end_ts, elaps, keyids = end_ts[ok], elaps[ok], keyids[ok]
            offs, lens = offs[ok], lens[ok]
            if len(end_ts) == 0:
                return 0
        labels = (end_ts.astype(np.int64) // 10000).astype(np.int32)

        track_ordered = self.on_ordered_csv is not None
        if track_ordered:
            ets_list = end_ts.tolist()
            # ASCII blob (the wire norm): byte offsets == str offsets, so one
            # whole-blob decode + str slicing replaces per-line bytes.decode
            text = blob.decode("ascii") if blob.isascii() else None
            offs_l = offs.tolist()
            lens_l = lens.tolist()

        def backlog(lo: int, hi: int) -> None:
            if text is not None:
                self._tx_backlog.extend(
                    (ets_list[j], text[offs_l[j] : offs_l[j] + lens_l[j]])
                    for j in range(lo, hi)
                )
            else:
                self._tx_backlog.extend(
                    (ets_list[j], blob[offs_l[j] : offs_l[j] + lens_l[j]].decode("utf-8", "replace"))
                    for j in range(lo, hi)
                )

        self._walk_tick_segments(
            labels,
            lambda lo, hi: self._ingest_arrays(
                self._resolve_decoded_rows(keyids[lo:hi]), labels[lo:hi], elaps[lo:hi]
            ),
            backlog if track_ordered else None,
        )
        return len(labels)

    def feed_frames(self, blob: bytes) -> int:
        """Bulk intake of one packed APF1 frame batch (transport.frameMode).

        The frame's lines region already IS the newline-separated
        ``tx|...`` byte blob the bulk decoder wants, so a frame feed is a
        header check plus one slice into :meth:`feed_csv_bytes` — zero
        per-record objects between the parser's emitter and the columnar
        ingest. Raises ``FrameError`` on a corrupt header (callers treat
        it like any bad batch: count, log, drop)."""
        from .transport import frames as _frames

        region = _frames.lines_region(blob)
        if len(region) == 0:
            return 0
        return self.feed_csv_bytes(bytes(region))

    def _resolve_decoded_rows(self, seg_ids: np.ndarray) -> np.ndarray:
        """Registry rows for one tick segment of decoder key ids.

        A key registers at its first id that actually reaches a segment
        (post NaN-filter) — NOT at interning time, because the decoder
        interns tx-shaped lines whose numerics turn out unparseable, and the
        numpy path never registers those phantom keys. Unregistered ids stay
        -1 in the map until a surviving record arrives. Decoder ids are
        assigned in first-appearance order, so registering a segment's
        unmapped ids in ascending id order IS the numpy path's
        first-appearance registration order."""
        if seg_ids.size == 0:
            return seg_ids.astype(np.int32)
        top = int(seg_ids.max()) + 1
        known = len(self._decode_keys)
        if top > known:
            self._decode_keys.extend(self._native_dec.keys_from(known))
            if len(self._decode_keys) > len(self._decode2row):
                grown = np.full(
                    max(len(self._decode_keys), 2 * len(self._decode2row)), -1, np.int32
                )
                grown[: len(self._decode2row)] = self._decode2row
                self._decode2row = grown
        rows = self._decode2row[seg_ids]
        unmapped = rows == -1
        if unmapped.any():
            # register in FIRST-APPEARANCE order within the segment (not
            # ascending id): a phantom-interned key re-appearing valid after
            # a newer key must register after it, exactly as the numpy path
            # (which never saw the phantom) would
            uk, first_idx = np.unique(seg_ids[unmapped], return_index=True)
            for j in np.argsort(first_idx, kind="stable"):
                i = int(uk[j])
                self._decode2row[i] = self._row_for(*self._decode_keys[i])
            rows = self._decode2row[seg_ids]
        return rows

    def _ingest_arrays(self, rows: np.ndarray, labels: np.ndarray, elaps: np.ndarray) -> None:
        """Scatter pre-decoded arrays in micro_batch_size chunks, with the
        SAME two pad tiers as _flush_pending (small tier for sub-256
        segments, full tier otherwise) so a trickle-sized bulk feed — the
        at-least-once batched intake, tick-boundary segments — doesn't pay
        a micro_batch_size-wide scatter per segment, and both paths share
        the same two compiled ingest variants."""
        B = self.micro_batch_size
        small = min(256, B)
        dtype = self._np_dtype()
        if self._delta_track:
            self._mark_cells(rows, labels)
        for i in range(0, len(rows), B):
            m = min(B, len(rows) - i)
            pad = small if m <= small else B
            r = np.zeros(pad, np.int32)
            l = np.zeros(pad, np.int32)
            e = np.zeros(pad, dtype)
            v = np.zeros(pad, bool)
            r[:m] = rows[i : i + m]
            l[:m] = labels[i : i + m]
            e[:m] = elaps[i : i + m]
            v[:m] = True
            self.state = self._ingest(self.state, self.cfg, r, l, e, v)
            if self._tracer is not None:
                self._m_tx.inc(m)
                self._m_pending_batch.observe(m)

    def flush(self) -> None:
        self._flush_pending()
        self.drain_emission()

    def drain_emission(self) -> None:
        """Deliver the held tick emission (async-emission mode). No-op when
        nothing is pending; callers that need every callback delivered
        (flush, snapshot, shutdown) route through here."""
        if self._pending_emission is not None:
            label, emission, count = self._pending_emission
            self._pending_emission = None
            self._process_emission(label, emission, count)

    def _flush_pending(self) -> None:
        if not self._pending:
            return
        ingest = self._ingest
        # TWO pad tiers: a small one for sparse tick-boundary flushes (~10
        # records must not pay a micro_batch_size-wide scatter — the ingest
        # program's cost scales with the padded width) and the full
        # micro-batch tier. Exactly two compiled variants: each extra tier
        # costs a ~1 s XLA:CPU compile on first use, which a replay-style
        # run pays INSIDE its measured window.
        n = len(self._pending)
        small = min(256, self.micro_batch_size)
        pad = small if n <= small else max(self.micro_batch_size, n)
        rows = np.zeros(pad, np.int32)
        labels = np.zeros(pad, np.int32)
        elaps = np.zeros(pad, self._np_dtype())
        valid = np.zeros(pad, bool)
        r_t, l_t, e_t = zip(*self._pending)  # column fill, no per-tuple loop
        rows[:n] = r_t
        labels[:n] = l_t
        elaps[:n] = e_t
        valid[:n] = True
        if self._delta_track:
            self._mark_cells(rows[:n], labels[:n])
        self._pending.clear()
        self.state = ingest(self.state, self.cfg, rows, labels, elaps, valid)
        if self._tracer is not None:
            self._m_tx.inc(n)
            self._m_pending_batch.observe(n)

    def _np_dtype(self):
        return np.float64 if self.cfg.stats.dtype == jnp.float64 else np.float32

    # -- tick ----------------------------------------------------------------
    def _run_tick(self, new_label: int) -> None:
        if self._delta_track:
            # delta capture derives the stats ring-advance, the z ring push
            # positions and the EWMA season slots from the tick-label
            # sequence alone — no per-tick readback
            self._delta_ticks.append(int(new_label))
        tr = self._tracer
        # trace plane: a tick with live sampled traces notes its wall window
        # so their "tick" span describes the tick that closed their bucket
        # (looked up by label at emission — exact under async-emission too).
        # A tick with no live traces pays one truthiness check.
        trace_tick = self._trace is not None and bool(self._live_traces)
        if trace_tick:
            tick_wall_start = time.time()
        if tr is not None:
            # catch-up depth: labels advanced by this tick (1 = steady state;
            # >1 = replay/backfill jump — the megatick-candidate signal)
            catchup = new_label - self._latest_label if self._latest_label else 1
            t0 = time.perf_counter()
        if self.registry.count != self._params_registry_count:
            # newly registered services activate (z-score warm-up starts) at
            # the next tick boundary — the reference's per-key list creation
            self._refresh_params()
        emission, self.state = self._step(self.state, new_label, self.params)
        t1 = time.perf_counter() if tr is not None else 0.0
        if self._rebuild_sched is not None:
            # staggered exact rebuild of the sliding z-score aggregates: one
            # row chunk per tick on a rotating schedule (RebuildScheduler) —
            # the staged executor's companion; the fused executor folds the
            # chunk into the tick program instead (rebuild_integrated).
            self.state = self._rebuild_sched.step(self.state)
        t2 = time.perf_counter() if tr is not None else 0.0
        edge_ts = dstats.edge_ts_ms(new_label, self.cfg.stats)

        # ordered tx drain to DB (heap pop up to edge timestamp)
        if self.on_ordered_tx is not None:
            for tx in self.heap.pop_all_leq(edge_ts):
                self.on_ordered_tx(tx)
        else:
            self.heap.pop_all_leq(edge_ts)
        # fast-path drain: due raw lines, end_ts-sorted (stable: arrival order
        # within equal timestamps), one C-speed sort per tick instead of
        # per-entry heap pushes
        if self.on_ordered_csv is not None and self._tx_backlog:
            due = [p for p in self._tx_backlog if p[0] <= edge_ts]
            if due:
                self._tx_backlog = [p for p in self._tx_backlog if p[0] > edge_ts]
                due.sort(key=lambda p: p[0])
                for _ts, line in due:
                    self.on_ordered_csv(line)

        t3 = time.perf_counter() if tr is not None else 0.0
        if trace_tick:
            self._tick_walls[new_label] = (tick_wall_start, time.time())
            if len(self._tick_walls) > 8:  # bounded: emission pops its label;
                # a zero-row emission (count==0) leaves one behind — prune
                for stale in sorted(self._tick_walls)[:-8]:
                    self._tick_walls.pop(stale, None)
        if self._async_emission:
            # double-buffered readback: hold this tick's emission; deliver
            # the PREVIOUS one now, while this tick's programs are still in
            # flight on the device. Per-tick callback order (stats ->
            # fullstats -> alerts) is preserved; the ordered-tx drain above
            # stays immediate (host-only bookkeeping, different queue).
            # Registry count snapshots at dispatch: rows registered later
            # did not exist at this tick and must not emit for it.
            prev, self._pending_emission = (
                self._pending_emission,
                (new_label, emission, self.registry.count),
            )
            if prev is not None:
                self._process_emission(*prev)
        else:
            self._process_emission(new_label, emission, self.registry.count)
        if tr is not None:
            stages = {
                "dispatch": t1 - t0,
                "rebuild": t2 - t1,
                "tx_drain": t3 - t2,
                "emit": time.perf_counter() - t3,
            }
            tr.record(new_label, stages, catchup_labels=catchup)
            if self._att_tick is not None:
                for k, clk in self._att_tick.items():
                    clk.add_busy(stages[k])

    # apm: sync-boundary: THE emit readback — the one blocking sync per tick the cost model budgets for (async emission overlaps it with the next dispatch)
    def _process_emission(self, new_label: int, emission: TickEmission, count: int) -> None:
        """Device->host readback + host fan-out of one tick's emission
        (StatEntry/FullStatEntry/alert callbacks). Split from _run_tick so
        async-emission mode can run it one tick late."""
        edge_ts = dstats.edge_ts_ms(new_label, self.cfg.stats)
        if count == 0:
            return
        # claim the oldest outstanding transport stamp for THIS emission
        # (async mode delivers one tick late, so the stamp honestly includes
        # the pipelining delay the operator is paying); claimed only by an
        # emission that actually fans out — a zero-row tick leaves it for
        # the first real one
        self._emitting_intake_ts, self._intake_oldest_ts = self._intake_oldest_ts, None
        # claim the sampled traces whose bucket this tick closed (labels
        # below new_label); later labels stay live for their own tick. The
        # claimed set is matched against alerts during the fan-out below.
        if self._trace is not None and self._live_traces:
            keep: deque = deque(maxlen=self._live_traces.maxlen)
            claimed: List[dict] = []
            for t in self._live_traces:
                (claimed if t["label"] < new_label else keep).append(t)
            self._live_traces = keep
            self._emitting_traces = claimed
        else:
            self._emitting_traces = ()
        self._emit_wall_start = time.time()
        # np.asarray(whole)[:count], never np.asarray(x[:count]): slicing a
        # jax array dispatches a compiled gather per call (~1.2 ms each on
        # CPU), and this path runs 3 + 6*channels of them per tick — the
        # numpy copy of the full row axis is microseconds by comparison
        tpm = np.asarray(emission.tpm)[:count]
        metrics = np.asarray(emission.average)[:count]  # [count, 3]

        emit_landed = time.time()
        if self._tracer is not None and self._emitting_intake_ts is not None:
            # the readback above (np.asarray of the emission) has landed: the
            # tick's results are host-visible — the "emit" moment
            lat = emit_landed - self._emitting_intake_ts
            if self._emitting_traces:
                # OpenMetrics exemplar: the latency bucket points at a trace
                # that actually lived through this emission
                self._m_emit_lat.observe_exemplar(lat, self._emitting_traces[0]["trace_id"])
            else:
                self._m_emit_lat.observe(lat)
        if self._emitting_traces:
            tick_wall = self._tick_walls.pop(new_label, None)
            for t in self._emitting_traces:
                if tick_wall is not None:
                    self._trace.span(
                        t["trace_id"], "tick", tick_wall[0], tick_wall[1],
                        label=new_label, service=t["service"],
                    )
                self._trace.span(
                    t["trace_id"], "emit", self._emit_wall_start, emit_landed,
                    label=new_label, service=t["service"], rows=count,
                )

        n_overflowed = int(np.asarray(emission.overflowed)[:count].sum())
        if n_overflowed:
            self.overflow_rows_total += n_overflowed
            self.overflow_ticks += 1
            if self._tracer is not None:
                self._m_overflow_rows.inc(n_overflowed)
            if self.on_overflow is not None:
                self.on_overflow(new_label, n_overflowed)
            if self.logger and self.overflow_ticks - self._overflow_last_logged_tick >= 30:
                self._overflow_last_logged_tick = self.overflow_ticks
                self.logger.warning(
                    f"Percentile reservoir overflow: {n_overflowed} rows this tick "
                    f"({self.overflow_rows_total} row-ticks total) exceeded "
                    f"samplesPerBucket={self.cfg.stats.samples_per_bucket}; percentiles "
                    f"for those rows are reservoir estimates (bounded error). Raise "
                    f"tpuEngine.samplesPerBucket to restore exactness."
                )

        # .tolist() ONCE per array: row loops below then touch plain Python
        # floats — float(arr[row]) per field costs a numpy scalar box each
        # (measured ~2M boxings per replay run before batching)
        tpm_l = tpm.tolist()
        metrics_l = metrics.tolist()
        if self.on_stat is not None:
            key_of = self.registry.key_of
            for row in range(count):
                server, service = key_of(row)
                mr = metrics_l[row]
                self.on_stat(
                    StatEntry(edge_ts, server, service, tpm_l[row], mr[0], mr[1], mr[2])
                )

        # lag windows + EWMA/seasonal channels share the emission path; EWMA
        # channels ride the FullStatEntry wire with lag = channel_id (<0)
        channels = [(spec.lag, em) for spec, em in zip(self.cfg.lags, emission.lags)]
        channels += [(spec.channel_id, em) for spec, em in zip(self.cfg.ewma, emission.ewma)]
        need_fs = self.on_fullstat is not None
        need_csv = self.on_fullstat_csv is not None
        need_alert = self.on_alert is not None or self.alerts_manager is not None
        for chan_id, lag_em in channels:
            if not (need_fs or need_csv or need_alert):
                continue
            wavg = np.asarray(lag_em.window_avg)[:count]
            lb = np.asarray(lag_em.lower_bound)[:count]
            ub = np.asarray(lag_em.upper_bound)[:count]
            sig = np.asarray(lag_em.signal)[:count]
            trig = np.asarray(lag_em.trigger)[:count]
            bits = np.asarray(lag_em.cause_bits)[:count]
            w_l, lo_l, up_l, sg_l = wavg.tolist(), lb.tolist(), ub.tolist(), sig.tolist()
            key_of = self.registry.key_of

            def make_fs(row: int) -> FullStatEntry:
                server, service = key_of(row)
                mr, wr, lr, ur, sr = (
                    metrics_l[row], w_l[row], lo_l[row], up_l[row], sg_l[row]
                )
                return FullStatEntry(
                    edge_ts, server, service, tpm_l[row], chan_id,
                    mr[0], wr[0], lr[0], ur[0], sr[0],
                    mr[1], wr[1], lr[1], ur[1], sr[1],
                    mr[2], wr[2], lr[2], ur[2], sr[2],
                )

            if need_csv:
                self.on_fullstat_csv(
                    self._format_fullstat_lines(edge_ts, chan_id, count, tpm, metrics, wavg, lb, ub, sig)
                )
            if need_fs:
                for row in range(count):
                    fs = make_fs(row)
                    self.on_fullstat(fs)
                    if need_alert and trig[row]:
                        self._dispatch_alert(fs, int(bits[row]), row=row)
            elif need_alert:
                # alert-only fast path: build objects for triggered rows only
                for row in np.nonzero(trig)[0]:
                    self._dispatch_alert(make_fs(int(row)), int(bits[row]), row=int(row))

    def _trace_for_alert(self, fs: FullStatEntry) -> Optional[str]:
        """trace_id of a claimed (this-emission) sampled trace matching the
        alert's (server, service), or None. Alert-path only."""
        for t in self._emitting_traces:
            if t["service"] == fs.service and t["server"] == fs.server:
                return t["trace_id"]
        return None

    # apm: sync-boundary: alert-path only — one ring-fill scalar read per dispatched alert for decision provenance, never per tick
    def _window_occupancy(self, chan_id, row: int) -> Optional[int]:
        """Ring fill (lag channels) / max slot update count (EWMA channels)
        for one row — a device readback, paid on the ALERT path only."""
        try:
            i = self._lag_index.get(chan_id)
            if i is not None:
                return int(np.asarray(self.state.zscores[i].fill)[row])
            i = self._ewma_index.get(chan_id)
            if i is not None:
                return int(np.asarray(self.state.ewmas[i].count)[row].max())
        except Exception:
            pass
        return None

    def _record_decision(self, fs: FullStatEntry, bits: int, row: Optional[int],
                         trace_id: Optional[str]) -> None:
        """Alert decision provenance (obs/decisions): the z inputs behind
        this page — triggering values, window means, the bands actually
        compared, smoothed signals, configured threshold/influence, window
        occupancy, device cause bits — keyed by trace_id when the bucket
        carried a sampled trace. A failure here must never lose the alert."""
        try:
            chan_id = fs.lag
            thr = infl = None
            if row is not None:
                tv = self._host_thresholds.get(chan_id)
                iv = self._host_influences.get(chan_id)
                if tv is not None and row < len(tv):
                    thr = float(tv[row])
                if iv is not None and row < len(iv):
                    infl = float(iv[row])
            self._decisions.record(
                {
                    "trace_id": trace_id,
                    "ts": time.time(),
                    "edge_ts": int(fs.timestamp),
                    "server": fs.server,
                    "service": fs.service,
                    "channel": chan_id,
                    "row": row,
                    "cause_bits": bits,
                    "cause": dalerts.cause_string(bits),
                    "threshold": thr,
                    "influence": infl,
                    "window_occupancy": self._window_occupancy(chan_id, row)
                    if row is not None else None,
                    "tpm": fs.tpm,
                    "metrics": {
                        "average": {
                            "value": fs.average, "window_mean": fs.average_avg,
                            "lower": fs.average_lb, "upper": fs.average_ub,
                            "signal": fs.average_signal,
                        },
                        "per75": {
                            "value": fs.per75, "window_mean": fs.per75_avg,
                            "lower": fs.per75_lb, "upper": fs.per75_ub,
                            "signal": fs.per75_signal,
                        },
                        "per95": {
                            "value": fs.per95, "window_mean": fs.per95_avg,
                            "lower": fs.per95_lb, "upper": fs.per95_ub,
                            "signal": fs.per95_signal,
                        },
                    },
                }
            )
        except Exception:
            if self.logger:
                self.logger.exception("Decision record failed (alert still dispatched)")

    def _dispatch_alert(self, fs: FullStatEntry, bits: int, row: Optional[int] = None) -> None:
        trace_id = None
        if self._tracer is not None:
            self._m_alerts.inc()
            trace_id = self._trace_for_alert(fs) if self._emitting_traces else None
            if self._emitting_intake_ts is not None:
                lat = time.time() - self._emitting_intake_ts
                if trace_id is not None:
                    self._m_alert_lat.observe_exemplar(lat, trace_id)
                else:
                    self._m_alert_lat.observe(lat)
            if trace_id is not None:
                # the alert hop of the sampled transaction's trace: emission
                # readback -> this dispatch
                self._trace.span(
                    trace_id, "alert",
                    self._emit_wall_start or time.time(), time.time(),
                    service=fs.service, channel=fs.lag,
                    cause=dalerts.cause_string(bits),
                )
        if self._decisions is not None:
            self._record_decision(fs, bits, row, trace_id)
        if self.alerts_manager is not None:
            alert = self.alerts_manager.process_trigger(fs, bits)
            if alert is not None:
                self.alerts_manager.add_to_buffer(alert)
                if self.on_alert is not None:
                    self.on_alert(alert)
        elif self.on_alert is not None:
            self.on_alert((fs, bits))

    def _format_fullstat_lines(
        self, edge_ts: int, chan_id, count: int, tpm, metrics, wavg, lb, ub, sig
    ) -> List[str]:
        """The tick's FullStat wire lines for one channel, in bulk.

        Byte-identical to ``FullStatEntry(...).to_csv()`` (entries.py wire
        quirks: nf() 1-decimal toFixed, tpm 2-decimal, bare average signal —
        entries.js:117) without constructing 20-field dataclasses per row;
        asserted by tests/test_pipeline.py parity."""
        from .entries import nf

        ts_s = str(int(edge_ts))
        t = tpm.tolist()
        m = metrics.tolist()
        w = wavg.tolist()
        lo = lb.tolist()
        up = ub.tolist()
        sg = sig.tolist()
        key_of = self.registry.key_of
        lines = []
        for row in range(count):
            server, service = key_of(row)
            mr, wr, lr, ur, sr = m[row], w[row], lo[row], up[row], sg[row]
            lines.append(
                f"fs|{ts_s}|{server}|{service}|{chan_id}|{nf(t[row], 2)}|"
                f"{nf(mr[0])}:{nf(wr[0])}:{nf(lr[0])}:{nf(ur[0])}:{sr[0]}|"
                f"{nf(mr[1])}:{nf(wr[1])}:{nf(lr[1])}:{nf(ur[1])}:{nf(sr[1])}|"
                f"{nf(mr[2])}:{nf(wr[2])}:{nf(lr[2])}:{nf(ur[2])}:{nf(sr[2])}"
            )
        return lines

    # -- checkpoint / resume (§5.4) ------------------------------------------
    # apm: sync-boundary: checkpoint serialization reads the full engine state back by contract (epoch cadence, not tick cadence)
    def _capture_resume_arrays(self, delivery: Optional[dict] = None) -> dict:
        """The full-snapshot array dict (save_resume's npz schema, registry
        and pending included) — shared by the atomic npz writer and the
        delta chain's compaction path (deltachain.DeltaChain.compact), which
        writes the same capture as a chain base off the hot path."""
        arrays = {
            "latest_bucket": np.asarray(self.state.stats.latest_bucket),
            "counts": np.asarray(self.state.stats.counts),
            "sums": np.asarray(self.state.stats.sums),
            "samples": np.asarray(self.state.stats.samples),
            "nsamples": np.asarray(self.state.stats.nsamples),
        }
        for i, spec in enumerate(self.cfg.lags):
            z = self.state.zscores[i]
            zvals = np.asarray(z.values)
            if zvals.dtype not in (np.float32, np.float64):
                # bf16 rings: .npz has no portable bfloat16 — store f32
                # (exact upcast; load downcasts back to identical bits)
                zvals = zvals.astype(np.float32)
            arrays[f"z{spec.lag}_values"] = zvals
            arrays[f"z{spec.lag}_fill"] = np.asarray(z.fill)
            arrays[f"z{spec.lag}_pos"] = np.asarray(z.pos)
            arrays[f"z{spec.lag}_counters"] = np.asarray(self.state.alert_counters[i])
        for i, espec in enumerate(self.cfg.ewma):
            e = self.state.ewmas[i]
            # key includes the slot count AND slot width so a SEASON_SLOTS or
            # SLOT_INTERVALS config change invalidates the snapshot (like lag
            # in the z{lag}_* keys) instead of resuming baselines under a
            # wrong-shaped or wrong-meaning slot mapping
            ek = f"e{espec.channel_id}x{espec.season_slots}x{espec.slot_intervals}"
            arrays[f"{ek}_mean"] = np.asarray(e.mean)
            arrays[f"{ek}_var"] = np.asarray(e.var)
            arrays[f"{ek}_count"] = np.asarray(e.count)
            arrays[f"{ek}_counters"] = np.asarray(self.state.ewma_counters[i])
            arrays[f"{ek}_trend"] = np.asarray(e.trend)
        arrays["registry"] = np.array(
            ["\x00".join(k) for k in self.registry.rows()], dtype=object
        )
        # pending ordered-tx records (not yet past the window edge) must
        # survive a restart — the reference keeps its heap in the resume file
        # (stream_calc_stats resume semantics). Stored as wire lines.
        arrays["pending_tx"] = np.array(self._pending_tx_lines(), dtype=object)
        if delivery is None:
            delivery = self.delivery_state
        if delivery is not None:
            # JSON in a 0-d object array: schema-stable regardless of the
            # dedup window's shape, absent entirely for at-most-once workers
            import json as _json

            arrays["delivery_state"] = np.array(_json.dumps(delivery), dtype=object)
            self.delivery_state = delivery
        return arrays

    def _pending_tx_lines(self) -> List[str]:
        pending = [tx.to_csv() for tx in self.heap.items()]
        pending += [line for _ts, line in self._tx_backlog]
        return pending

    def save_resume(self, path: str, *, delivery: Optional[dict] = None) -> None:
        """Atomic snapshot (tmp + rename); `path` is used verbatim — no .npz
        suffix magic — so load_resume(path) always finds what was saved.

        ``delivery`` couples the snapshot to queue position (the at-least-once
        epoch contract): a per-queue dict of {"epoch": watermark, "dedup":
        [recently absorbed msg ids], ...} saved ATOMICALLY WITH the engine
        state that absorbed those messages — the invariant the worker's
        ack-after-checkpoint cycle rests on (a message id is in the saved
        window iff its effect is in the saved tensors)."""
        # a held emission describes a tick already IN the snapshot state; it
        # must reach its consumers now or a restore would silently drop it
        self.drain_emission()
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        arrays = self._capture_resume_arrays(delivery)
        import tempfile

        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(os.path.abspath(path)), suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as fh:
                np.savez_compressed(fh, **arrays)
            os.replace(tmp, path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    # -- incremental delta checkpoints (deltachain.py) -----------------------
    def enable_delta_capture(self) -> None:
        """Arm dirty-state tracking for delta commits (checkpointMode:
        "delta"). Call after construction or after a resume install — the
        capture baseline is the CURRENT state, which must equal the chain
        tail the next delta will append to."""
        self._delta_track = True
        # CPU backend: gather the epoch's cells/columns on a zero-copy
        # numpy view — no dispatch, no per-shape compile. Other backends
        # pay a device gather with tier-padded (bounded-compile) indices.
        self._delta_np_gather = jax.default_backend() == "cpu"
        self._delta_reset_capture()

    def _delta_gather(self, arr, index) -> np.ndarray:
        """One capture gather (``arr[index]``), returned as an owning numpy
        array in the npz schema's dtype (bf16 ring bits decode to exact
        f32). Advanced indexing copies on both paths, so the result never
        aliases a device buffer a later donated dispatch could invalidate."""
        if self._delta_np_gather:
            view = None
            try:
                view = cpu_zero_copy_view(arr)
            except Exception:
                pass  # exotic layout: fall through to the device gather
            if view is not None:
                out = view[index]
                if view.dtype == np.uint16:  # bf16 bit pattern -> exact f32
                    out = (out.astype(np.uint32) << 16).view(np.float32)
                return out
        out = arr[index]
        if out.dtype not in (jnp.float32, jnp.float64, jnp.int32):
            out = out.astype(jnp.float32)  # npz schema: no bf16
        return np.asarray(out)

    def _mark_cells(self, rows: np.ndarray, labels: np.ndarray) -> None:
        """Record the (row, bucket-slot) cells one ingest scatter touches."""
        nb = self.cfg.stats.num_buckets
        packed = rows.astype(np.int64) * nb + labels.astype(np.int64) % nb
        self._dirty_cells.update(np.unique(packed).tolist())

    # apm: sync-boundary: delta-capture baseline reads the ring cursors back once per epoch commit
    def _delta_reset_capture(self) -> None:
        self._dirty_cells.clear()
        self._delta_ticks = []
        self._delta_pos0 = [int(np.asarray(z.pos)) for z in self.state.zscores]
        self._delta_reg_base = self.registry.count

    # apm: sync-boundary: delta capture gathers the epoch's touched cells/columns back by contract (epoch cadence, not tick cadence)
    def _capture_delta(self, delivery_delta: Optional[dict] = None):
        """(arrays, meta) for one delta segment: everything the state changed
        since the last commit, at dirty-cell / pushed-column granularity.
        Does NOT reset tracking — the caller resets only after the segment
        is durably on disk, so a failed write retries with a superset."""
        cfg = self.cfg
        nb = cfg.stats.num_buckets
        ticks = list(self._delta_ticks)
        T = len(ticks)
        arrays: dict = {"latest_bucket": np.asarray(self.state.stats.latest_bucket)}
        meta: dict = {
            "capacity": int(cfg.capacity),
            "nb": int(nb),
            "ticks": ticks,
            "zchannels": [],
            "echannels": [],
        }
        if self._dirty_cells:
            packed = np.fromiter(self._dirty_cells, np.int64, len(self._dirty_cells))
            packed.sort()
            rows = (packed // nb).astype(np.int32)
            slots = (packed % nb).astype(np.int32)
            # pad the index vectors to power-of-two tiers: a shape-varying
            # gather would recompile per distinct cell count (the XLA trap
            # _ingest_arrays' pad tiers exist for). Padding REPEATS the
            # first cell — the duplicate scatters the same post-state value
            # twice at replay, which is idempotent by construction.
            rows = _pad_tier_repeat(rows)
            slots = _pad_tier_repeat(slots)
            arrays["cell_rows"] = rows
            arrays["cell_slots"] = slots
            st = self.state.stats
            # O(cells) gathers, not O(state) (zero-copy numpy view on CPU,
            # device gather elsewhere — _delta_gather)
            arrays["cell_counts"] = self._delta_gather(st.counts, (rows, slots))
            arrays["cell_sums"] = self._delta_gather(st.sums, (rows, slots))
            arrays["cell_nsamples"] = self._delta_gather(st.nsamples, (rows, slots))
            arrays["cell_samples"] = self._delta_gather(st.samples, (rows, slots))
        if T:
            for i, spec in enumerate(cfg.lags):
                z = self.state.zscores[i]
                L = spec.lag
                key = f"z{spec.lag}"
                pos0 = self._delta_pos0[i]
                meta["zchannels"].append({"key": key, "lag": L, "pos0": pos0})
                if T >= L:
                    # every ring slot was rewritten this epoch: store the
                    # whole ring (the full snapshot's representation)
                    zvals = np.asarray(
                        z.values.astype(jnp.float32)
                        if z.values.dtype not in (jnp.float32, jnp.float64)
                        else z.values
                    )
                    arrays[f"{key}_values"] = zvals
                else:
                    # tier-padded with the last position repeated (same
                    # column gathered twice == same column written twice at
                    # replay); apply_delta slices back to len(ticks)
                    positions = _pad_tier_repeat(
                        np.asarray([(pos0 + t) % L for t in range(T)], np.int32),
                        last=True,
                    )
                    arrays[f"{key}_push"] = self._delta_gather(
                        z.values, (slice(None), slice(None), positions)
                    )
                arrays[f"{key}_fill"] = np.asarray(z.fill)
                arrays[f"{key}_pos"] = np.asarray(z.pos)
                arrays[f"{key}_counters"] = np.asarray(self.state.alert_counters[i])
            buf1 = cfg.stats.buffer_sz + 1
            for i, espec in enumerate(cfg.ewma):
                e = self.state.ewmas[i]
                K = espec.season_slots
                ek = f"e{espec.channel_id}x{K}x{espec.slot_intervals}"
                slots_e = sorted(
                    {((nl - buf1) // espec.slot_intervals) % K for nl in ticks}
                )
                meta["echannels"].append({"key": ek, "slots": slots_e})
                if len(slots_e) >= K:
                    arrays[f"{ek}_mean"] = np.asarray(e.mean)
                    arrays[f"{ek}_var"] = np.asarray(e.var)
                    arrays[f"{ek}_trend"] = np.asarray(e.trend)
                    arrays[f"{ek}_count"] = np.asarray(e.count)
                else:
                    sl = _pad_tier_repeat(np.asarray(slots_e, np.int32), last=True)
                    ix3 = (slice(None), slice(None), sl)
                    arrays[f"{ek}_mean_cols"] = self._delta_gather(e.mean, ix3)
                    arrays[f"{ek}_var_cols"] = self._delta_gather(e.var, ix3)
                    arrays[f"{ek}_trend_cols"] = self._delta_gather(e.trend, ix3)
                    arrays[f"{ek}_count_cols"] = self._delta_gather(
                        e.count, (slice(None), sl)
                    )
                arrays[f"{ek}_counters"] = np.asarray(self.state.ewma_counters[i])
        new_keys = self.registry.rows()[self._delta_reg_base :]
        if new_keys:
            meta["registry_new"] = ["\x00".join(k) for k in new_keys]
        if T or self._dirty_cells:
            # any feed/tick may have moved the ordered-tx heap/backlog;
            # bounded by the window buffer (drained past the edge every tick)
            meta["pending"] = self._pending_tx_lines()
        if delivery_delta is not None:
            meta["delivery_delta"] = delivery_delta
        return arrays, meta

    @property
    def has_uncheckpointed_changes(self) -> bool:
        """True when delta-capture tracking has recorded engine changes
        since the last committed epoch (dirty cells, executed ticks,
        registry growth, or pending ordered-tx). False only under active
        tracking — with tracking off, idleness cannot be proven and the
        caller must not skip its checkpoint. Lets an idle worker's save
        cadence skip no-op commits instead of appending empty deltas
        (chains otherwise grow one segment per interval — and one per
        boot — while serving nothing)."""
        if not self._delta_track:
            return True
        # NOTE: heap/backlog content is deliberately NOT consulted — a
        # restored engine re-seeds its pending-tx buffer from the last
        # commit, and every path that grows it also dirties cells or
        # executes ticks, so the buffer alone never constitutes a change
        return bool(
            self._dirty_cells or self._delta_ticks
            or self.registry.count != self._delta_reg_base
        )

    def save_resume_delta(self, chain, *, delivery_delta: Optional[dict] = None) -> int:
        """Commit one epoch as a delta segment appended to ``chain``
        (deltachain.DeltaChain). The delta + the worker's incremental dedup
        record form the SAME atomic commit unit the full snapshot provides:
        a msg id is in the chain's recovered window iff its effect is in the
        chain's recovered tensors. Raises deltachain.CheckpointWriteError on
        storage failure — tracking is NOT reset, so the retry captures a
        superset and the chain still ends at a committed boundary."""
        if not self._delta_track:
            raise RuntimeError("delta capture not enabled (enable_delta_capture)")
        self.flush()  # pending scatters + held emission belong to this epoch
        arrays, meta = self._capture_delta(delivery_delta)
        epoch = chain.append(arrays, meta)
        self._delta_reset_capture()
        return epoch

    def load_resume_chain(self, chain) -> bool:
        """Restore from a delta chain (deltachain.DeltaChain or directory
        path): base + ordered deltas replayed to the last committed epoch,
        then installed through the exact same path as a full-snapshot
        restore. Returns False (start fresh) when no readable chain exists."""
        from .deltachain import DeltaChain

        if isinstance(chain, str):
            chain = DeltaChain(chain, logger=self.logger)
        rec = chain.load()
        if rec is None or rec.data is None:
            return False
        self.drain_emission()  # pre-restore emissions belong to the old stream
        if not self._install_resume_data(rec.data, f"chain {chain.directory}"):
            return False
        if self._delta_track:
            self._delta_reset_capture()
        return True

    def load_resume(self, path: str) -> bool:
        if not os.path.exists(path):
            return False
        self.drain_emission()  # pre-restore emissions belong to the old stream
        # Fully materialize the snapshot before touching any state: np.load
        # succeeds on any readable zip, and member reads (KeyError, zlib
        # errors on truncation) raise lazily — a corrupt file must mean
        # "start fresh", never a crash or a half-mutated driver.
        try:
            with np.load(path, allow_pickle=True) as npz:
                data = {name: npz[name] for name in npz.files}
        except Exception:
            if self.logger:
                self.logger.error(f"Could not load resume snapshot (starting fresh): {path}")
            return False
        return self._install_resume_data(data, path)

    # apm: sync-boundary: resume install materializes host arrays onto the device once at boot
    def _install_resume_data(self, data: dict, source: str) -> bool:
        """Install a full-snapshot ``data`` dict (npz schema) into the live
        driver — shared by the npz path and the delta-chain replay, so a
        chain restore is bit-identical to restoring a full snapshot of the
        same state. Validation failure means "start fresh", never a crash."""
        try:
            keys = [tuple(k.split("\x00", 1)) for k in data["registry"].tolist()]
            required = ["latest_bucket", "counts", "sums", "samples", "nsamples"]
            for spec in self.cfg.lags:
                required += [f"z{spec.lag}_{f}" for f in ("values", "fill", "pos", "counters")]
            for espec in self.cfg.ewma:
                ek = f"e{espec.channel_id}x{espec.season_slots}x{espec.slot_intervals}"
                required += [f"{ek}_{f}" for f in ("mean", "var", "count", "counters")]
            missing = [name for name in required if name not in data]
            if missing:
                raise KeyError(missing[0])
        except Exception:
            if self.logger:
                self.logger.error(f"Could not load resume snapshot (starting fresh): {source}")
            return False
        needed = len(keys)
        while needed > self.cfg.capacity:
            self._grow()
        self.registry = ServiceRegistry(self.cfg.capacity)
        for server, service in keys:
            self.registry.lookup_or_add(server, service)
        # the registry was rebuilt: decoder-id -> row mappings are stale, and
        # re-resolving old interned keys eagerly would register absent
        # services early. Start a fresh decoder lazily instead.
        if self._native_dec is not None:
            self._native_dec.close()
        self._native_dec = None
        self._native_dec_tried = False
        self._reset_decode_map()

        def pad_rows(a: np.ndarray) -> np.ndarray:
            if a.shape and a.shape[0] < self.cfg.capacity:
                pad_width = [(0, self.cfg.capacity - a.shape[0])] + [(0, 0)] * (a.ndim - 1)
                fill = np.nan if np.issubdtype(a.dtype, np.floating) else 0
                return np.pad(a, pad_width, constant_values=fill)
            return a[: self.cfg.capacity]

        stats_state = dstats.StatsState(
            latest_bucket=jnp.asarray(data["latest_bucket"]),
            counts=jnp.asarray(pad_rows(data["counts"])),
            sums=jnp.asarray(pad_rows(data["sums"])),
            samples=jnp.asarray(pad_rows(data["samples"])),
            nsamples=jnp.asarray(pad_rows(data["nsamples"])),
        )
        zstates, counters = [], []
        ring_dtype = self.cfg.zscore_ring_dtype or self.cfg.stats.dtype
        for spec in self.cfg.lags:
            values_np = pad_rows(data[f"z{spec.lag}_values"])
            fill_np = pad_rows(data[f"z{spec.lag}_fill"])
            pos_np = np.asarray(data[f"z{spec.lag}_pos"])
            if pos_np.ndim == 0:
                pos = jnp.asarray(pos_np, jnp.int32)
            else:
                # legacy snapshot with PER-ROW cursors: rotate each row so
                # its next-write slot lands on the shared cursor 0 (window
                # content and eviction order are rotation-invariant, so the
                # restored engine is bit-equivalent to the legacy layout)
                values_np = dzscore.normalize_legacy_ring(
                    values_np, fill_np, pad_rows(pos_np), spec.lag
                )
                pos = jnp.zeros((), jnp.int32)
            values = jnp.asarray(values_np).astype(ring_dtype)
            zstates.append(
                dzscore.ZScoreState(
                    values=values,
                    fill=jnp.asarray(fill_np),
                    pos=pos,
                )
            )
            counters.append(jnp.asarray(pad_rows(data[f"z{spec.lag}_counters"])))
        estates, ecounters = [], []
        for espec in self.cfg.ewma:
            ek = f"e{espec.channel_id}x{espec.season_slots}x{espec.slot_intervals}"
            mean = pad_rows(data[f"{ek}_mean"])
            # trend is absent in pre-Holt snapshots: zero-fill == the exact
            # plain-EWMA state those snapshots were saved under
            trend = (
                pad_rows(data[f"{ek}_trend"])
                if f"{ek}_trend" in data
                else np.zeros_like(mean)
            )
            estates.append(
                dewma.EwmaState(
                    mean=jnp.asarray(mean),
                    var=jnp.asarray(pad_rows(data[f"{ek}_var"])),
                    count=jnp.asarray(pad_rows(data[f"{ek}_count"])),
                    trend=jnp.asarray(trend),
                )
            )
            ecounters.append(jnp.asarray(pad_rows(data[f"{ek}_counters"])))
        # the sliding aggregates are DERIVED state: rebuilt exactly from the
        # restored rings, so snapshot schemas are unchanged and pre-sliding
        # snapshots restore 1:1 (shared derivation: engine_derive_aggs)
        self.state = engine_derive_aggs(
            EngineState(
                stats_state, tuple(zstates), tuple(counters), tuple(estates), tuple(ecounters)
            ),
            self.cfg,
        )
        self._latest_label = int(data["latest_bucket"])
        self.delivery_state = None
        if "delivery_state" in data:  # optional: absent for at-most-once
            import json as _json

            try:
                self.delivery_state = _json.loads(data["delivery_state"].item())
            except Exception:
                # a mangled delivery record must not reject the engine
                # snapshot: worst case the dedup window starts empty and a
                # redelivery double-counts — the at-most-once baseline
                if self.logger:
                    self.logger.error(
                        f"Resume snapshot delivery state unreadable (ignored): {source}"
                    )
        self.heap = MinHeap(lambda tx: tx.end_ts)
        self._tx_backlog = []
        if "pending_tx" in data:  # optional: absent in older snapshots
            from .entries import EntryFactory

            fac = EntryFactory()
            for line in data["pending_tx"].tolist():
                if self.on_ordered_tx is not None:
                    entry = fac.from_csv(line)
                    if entry is not None and entry.type == "tx":
                        self.heap.push(entry)
                elif self.on_ordered_csv is not None:
                    p = line.split("|")
                    if len(p) == 9 and p[0] == "tx":
                        try:
                            self._tx_backlog.append((float(p[6]), line))
                        except ValueError:
                            pass
        self._refresh_params()
        return True

    # -- partition row handoff (parallel/fleet.py, DESIGN.md §10) ------------
    # The quiesced-rebalance primitives: a partition's service rows leave one
    # engine and join another as npz-schema dicts, through the SAME install
    # path checkpoints restore through — so a handed-off row is bit-identical
    # to one that was checkpointed and restored. All three are epoch-cadence
    # operations (full capture + reinstall): rebalances are rare control-plane
    # events, and reusing the battle-tested snapshot path beats a bespoke
    # incremental row mover that would need its own bit-identity proofs.

    def _row_array_names(self, data: dict) -> List[str]:
        """Capture keys indexed by service row (first axis == capacity):
        stats planes, z rings/fill/counters, EWMA planes — everything except
        the 0-d cursors (latest_bucket, z pos) and object arrays (registry,
        pending_tx, delivery_state)."""
        return [
            k for k, a in data.items()
            if isinstance(a, np.ndarray) and a.dtype != np.dtype(object)
            and a.ndim >= 1 and a.shape[0] == self.cfg.capacity
        ]

    def export_service_rows(self, pred) -> dict:
        """Snapshot the rows whose ``(server, service)`` key satisfies
        ``pred`` as a self-contained npz-schema dict (cursors included, so
        the importer can re-align ring rotation), WITHOUT mutating this
        engine. Pending ordered-tx lines of those services ride along."""
        self.flush()
        self.drain_emission()
        data = self._capture_resume_arrays(None)
        keys = self.registry.rows()
        take = [i for i, (srv, svc) in enumerate(keys) if pred(srv, svc)]
        idx = np.asarray(take, np.intp)
        out = {k: np.array(data[k][idx]) for k in self._row_array_names(data)}
        out["latest_bucket"] = np.asarray(data["latest_bucket"])
        for spec in self.cfg.lags:
            out[f"z{spec.lag}_pos"] = np.asarray(data[f"z{spec.lag}_pos"])
        out["registry"] = np.array(
            ["\x00".join(keys[i]) for i in take], dtype=object
        )
        out["pending_tx"] = np.array(
            [ln for ln in data["pending_tx"].tolist()
             if self._pending_line_matches(ln, pred)],
            dtype=object,
        )
        return out

    @staticmethod
    def _pending_line_matches(line: str, pred) -> bool:
        p = line.split("|", 3)
        return len(p) >= 3 and pred(p[1], p[2])

    def remove_service_rows(self, pred) -> int:
        """Drop the rows whose key satisfies ``pred`` (the release half of a
        handoff): the remaining rows are re-installed through the resume
        path, so row indices compact and derived aggregates rebuild exactly
        as a restore would. Returns the number of rows removed."""
        self.flush()
        self.drain_emission()
        data = self._capture_resume_arrays(None)
        keys = self.registry.rows()
        keep = [i for i, (srv, svc) in enumerate(keys) if not pred(srv, svc)]
        removed = len(keys) - len(keep)
        if removed == 0:
            return 0
        idx = np.asarray(keep, np.intp)
        for k in self._row_array_names(data):
            data[k] = np.array(data[k][idx])
        data["registry"] = np.array(
            ["\x00".join(keys[i]) for i in keep], dtype=object
        )
        data["pending_tx"] = np.array(
            [ln for ln in data["pending_tx"].tolist()
             if not self._pending_line_matches(ln, pred)],
            dtype=object,
        )
        if not self._install_resume_data(data, "partition-release"):
            raise RuntimeError("row removal re-install failed")
        if self._delta_track:
            self._delta_reset_capture()
        return removed

    def import_service_rows(self, incoming: dict) -> int:
        """Merge an :meth:`export_service_rows` dict into this engine (the
        adopt half of a handoff). Incoming z-ring columns are rotated from
        the exporter's shared cursor/label onto this engine's, so a row's
        window reads the same label sequence it would have on its old owner;
        stats/EWMA planes are label-slot indexed and merge as-is, with cells
        older than the merged bucket window cleared. Duplicate service keys
        are a routing-discipline violation and raise (one partition, one
        owner — shardmodel owner-locality)."""
        self.flush()
        self.drain_emission()
        in_keys = [tuple(k.split("\x00", 1))
                   for k in incoming["registry"].tolist()]
        if not in_keys:
            return 0
        cur = self._capture_resume_arrays(None)
        cur_keys = self.registry.rows()
        dup = set(cur_keys) & set(in_keys)
        if dup:
            raise ValueError(
                f"import_service_rows: {len(dup)} keys already live here "
                f"(first: {sorted(dup)[0]}) — a partition can only have one "
                f"owner"
            )
        cur_label = int(cur["latest_bucket"])
        in_label = int(incoming["latest_bucket"])
        new_label = max(cur_label, in_label)
        nb = self.cfg.stats.num_buckets
        n_cur = len(cur_keys)
        merged: dict = {}
        for k in self._row_array_names(cur):
            inc = np.array(incoming[k])
            merged[k] = np.concatenate([np.array(cur[k][:n_cur]), inc], axis=0)
        # ring rotation: column of label t sits at (pos - 1 - (label - t))
        # mod L, so aligning the two histories shifts incoming columns by
        # (cur_pos - in_pos - (cur_label - in_label)) mod L
        for spec in self.cfg.lags:
            L = spec.lag
            cur_pos = int(np.asarray(cur[f"z{L}_pos"]))
            in_pos = int(np.asarray(incoming[f"z{L}_pos"]))
            d = (cur_pos - in_pos - (cur_label - in_label)) % L
            if d:
                vk = f"z{L}_values"
                merged[vk][n_cur:] = np.roll(
                    np.array(incoming[vk]), d, axis=-1
                )
        # bucket-slot hygiene across a label skew: slot s last held label
        # latest - ((latest - s) % nb); anything at or below new_label - nb
        # is outside the merged window and must read empty (the live engine
        # clears those slots as it advances — a handoff must not resurrect
        # them)
        if in_label != cur_label:
            slots = np.arange(nb)
            for label0, rows in ((in_label, slice(n_cur, None)),
                                 (cur_label, slice(0, n_cur))):
                dead = (label0 - ((label0 - slots) % nb)) <= new_label - nb
                if not dead.any():
                    continue
                for k in ("counts", "sums", "nsamples", "samples"):
                    merged[k][rows, dead] = 0
        # keep the cursor dtype of the capture (int32): a bare python int
        # would become int64 and poison every label-indexed dynamic slice
        # under x64
        merged["latest_bucket"] = np.asarray(
            new_label, dtype=np.asarray(cur["latest_bucket"]).dtype
        )
        for spec in self.cfg.lags:
            merged[f"z{spec.lag}_pos"] = np.asarray(cur[f"z{spec.lag}_pos"])
        merged["registry"] = np.array(
            ["\x00".join(k) for k in list(cur_keys) + in_keys], dtype=object
        )
        merged["pending_tx"] = np.array(
            cur["pending_tx"].tolist() + incoming["pending_tx"].tolist(),
            dtype=object,
        )
        if "delivery_state" in cur:
            merged["delivery_state"] = cur["delivery_state"]
        if not self._install_resume_data(merged, "partition-adopt"):
            raise RuntimeError("row import re-install failed")
        if self._delta_track:
            self._delta_reset_capture()
        return len(in_keys)
