"""Device multivariate anomaly detector for JMX / machine-health vectors
(BASELINE.json configs[2]: "JMX + datasource + VM-CPU multivariate batch").

The reference only *persists* JMX samples (pull_jvm_stats.js -> stream_insert_db
-> Grafana eyeballs); it has no detector over them. This module closes that gap
the TPU way: every poll the fleet's per-host metric vectors form one ``[H, M]``
batch, and a single jitted step updates an exponentially weighted mean vector
and covariance matrix per host and scores the new sample by normalized
Mahalanobis distance — the multivariate generalization of the per-metric
smoothed z-score (stream_calc_z_score.js:66-104):

- state: ``mean [H, M]``, ``cov [H, M, M]``, ``count [H]``. EW recursion
  (incremental West 1979, matching ops/ewma.py): ``delta = x - mean``,
  ``mean += alpha*delta``, ``cov = (1-alpha)*(cov + alpha*outer(delta, delta))``.
- score: ``sqrt(d' (C + ridge*diag(C) + eps*I)^-1 d / m)`` over the ``m``
  observed dims, where ``C`` is the covariance bias-corrected by
  ``1/(1-(1-alpha)^n)`` (the EW estimate converges from below; uncorrected it
  over-signals right after warmup). The *relative* ridge keeps the score
  invariant to per-metric units (heap bytes vs sysload), and dividing by ``m``
  makes one threshold work across hosts reporting different metric subsets.
  Under normality ``m*score^2 ~ chi2(m)``, so ``threshold=3`` is roughly a
  per-dim 3-sigma gate.
- quirk parity with the z-score channel: warm-up gating on update count (the
  lag-length analog, stream_calc_z_score.js:75), NaN dims are masked (a down
  collector must not poison the baseline), and signalling samples enter the
  recursion influence-damped (stream_calc_z_score.js:96-97) so an anomaly
  cannot inflate its own covariance and mask successors.

Host-side, :class:`MvDriver` keeps the server->row registry and turns
:class:`~apmbackend_tpu.entries.JmxEntry` batches into device calls.
"""

from __future__ import annotations

import math
from typing import List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..entries import JmxEntry


class MvSpec(NamedTuple):
    """Static detector settings (hashable, part of the jitted closure)."""

    n_features: int
    alpha: float = 0.05  # EW smoothing factor
    threshold: float = 3.0  # signal at normalized Mahalanobis > threshold
    # min updates before signalling. A covariance over M dims needs well over
    # M samples to be full-rank and stable — keep warmup >= ~2*n_features (the
    # reference's analog waits for the FULL lag window before signalling,
    # stream_calc_z_score.js:75)
    warmup: int = 24
    ridge: float = 0.05  # relative diagonal regularization
    eps: float = 1e-9  # absolute regularization floor
    influence: float = 0.25  # damping for signalling samples (1 = none)
    # a dim only *scores* while its EW std exceeds std_floor_frac*(|mean|+1):
    # a long-constant metric's variance decays to ~0, and without this gate the
    # next +-1 blip would divide by eps and signal unconditionally. Collapsed
    # dims still *update* (so the baseline tracks and variance can recover) —
    # the univariate channels have the same semantics (zero variance -> std
    # undefined -> no signal, ops/ewma.py; stream_calc_z_score.js:66-104).
    std_floor_frac: float = 1e-4


class MvState(NamedTuple):
    mean: jnp.ndarray  # [H, M] (NaN = dim not yet seeded)
    cov: jnp.ndarray  # [H, M, M]
    count: jnp.ndarray  # [H] int32


class MvResult(NamedTuple):
    score: jnp.ndarray  # [H] normalized Mahalanobis distance (NaN = cold)
    signal: jnp.ndarray  # [H] int32 {0, 1}
    observed: jnp.ndarray  # [H] int32: dims observed this step


def init_state(capacity: int, spec: MvSpec, dtype=jnp.float32) -> MvState:
    H, M = capacity, spec.n_features
    return MvState(
        mean=jnp.full((H, M), jnp.nan, dtype),
        cov=jnp.zeros((H, M, M), dtype),
        count=jnp.zeros((H,), jnp.int32),
    )


def step(
    state: MvState, spec: MvSpec, x: jnp.ndarray, valid: jnp.ndarray
) -> Tuple[MvResult, MvState]:
    """One poll for the whole fleet: x [H, M] float (NaN = missing),
    valid [H] bool (False = host not polled this round; state untouched)."""
    M = spec.n_features
    dtype = state.mean.dtype
    x = jnp.asarray(x, dtype)
    valid = jnp.asarray(valid, bool)

    seeded = ~jnp.isnan(state.mean)  # [H, M] per-dim
    obs = valid[:, None] & ~jnp.isnan(x)  # [H, M]
    live = obs & seeded  # dims that update the baseline this step
    # EW covariance starts at 0 and converges from below (var after n updates
    # ~ (1-(1-alpha)^n)*sigma^2), which inflates early Mahalanobis scores and
    # over-signals right after warmup. Score against the bias-corrected
    # covariance (Adam-style 1/(1-(1-alpha)^n)); state keeps the raw EW form.
    bias = 1.0 - (1.0 - spec.alpha) ** jnp.maximum(state.count, 1).astype(dtype)  # [H]
    cov_c = state.cov / bias[:, None, None]
    diag = jnp.diagonal(cov_c, axis1=1, axis2=2)  # [H, M]
    var_floor = jnp.square(spec.std_floor_frac * (jnp.abs(jnp.where(seeded, state.mean, 0.0)) + 1.0))
    scorable = live & (diag > var_floor)  # dims that enter the score
    m_obs = jnp.sum(scorable, axis=1)  # [H]

    d = jnp.where(scorable, x - state.mean, 0.0)  # [H, M]
    reg = spec.ridge * diag + spec.eps
    # unobserved/unseeded dims get an identity row/col so the solve stays
    # well-posed without influencing observed dims (their d is already 0)
    eye = jnp.eye(M, dtype=dtype)
    mask2d = scorable[:, :, None] & scorable[:, None, :]
    C = jnp.where(mask2d, cov_c, 0.0) + eye[None] * jnp.where(scorable, reg, 1.0)[:, :, None]
    y = jnp.linalg.solve(C, d[:, :, None])[:, :, 0]  # [H, M]
    maha2 = jnp.sum(d * y, axis=1)  # [H]

    warm = (state.count >= spec.warmup) & (m_obs > 0)
    score = jnp.where(warm, jnp.sqrt(jnp.maximum(maha2, 0.0) / jnp.maximum(m_obs, 1)), jnp.nan)
    signal = jnp.where(warm & (score > spec.threshold), 1, 0).astype(jnp.int32)

    # EW update. Signalling samples are influence-damped; dims seen for the
    # first time seed mean=x (cov row/col stays 0 until a second sample).
    damped = jnp.where(
        (signal == 1)[:, None] & live,
        spec.influence * x + (1.0 - spec.influence) * state.mean,
        x,
    )
    delta = jnp.where(live, damped - state.mean, 0.0)  # [H, M]
    new_mean = jnp.where(live, state.mean + spec.alpha * delta, state.mean)
    new_mean = jnp.where(obs & ~seeded, x, new_mean)  # seed fresh dims
    outer = delta[:, :, None] * delta[:, None, :]
    upd = (1.0 - spec.alpha) * (state.cov + spec.alpha * outer)
    # only covariance entries whose BOTH dims were observed update — a missing
    # collector must not decay unrelated baselines (EWMA NaN-skip parity)
    live2d = live[:, :, None] & live[:, None, :]
    new_cov = jnp.where(live2d, upd, state.cov)
    new_count = state.count + jnp.any(obs, axis=1).astype(jnp.int32)

    return (
        MvResult(score.astype(dtype), signal, m_obs.astype(jnp.int32)),
        MvState(new_mean.astype(dtype), new_cov.astype(dtype), new_count),
    )


def grow_state(state: MvState, new_capacity: int) -> MvState:
    H_old = state.count.shape[0]
    if new_capacity < H_old:
        raise ValueError("cannot shrink")
    pad = new_capacity - H_old
    return MvState(
        mean=jnp.pad(state.mean, ((0, pad), (0, 0)), constant_values=jnp.nan),
        cov=jnp.pad(state.cov, ((0, pad), (0, 0), (0, 0))),
        count=jnp.pad(state.count, (0, pad)),
    )


# -- JMX feature map ---------------------------------------------------------

def _frac(used: float, cap: float) -> float:
    if math.isnan(used) or math.isnan(cap) or cap <= 0:
        return float("nan")
    return used / cap


def jmx_features(e: JmxEntry) -> np.ndarray:
    """JmxEntry -> stationary-ish feature vector (ratios where a capacity
    exists, raw where not). Order is the wire contract for resume snapshots."""
    return np.array(
        [
            e.ds_in_use_nodes,
            e.ds_active_nodes,
            _frac(e.ds_in_use_nodes, e.ds_available_nodes),
            _frac(e.heap_used, e.heap_max),
            _frac(e.heap_committed, e.heap_max),
            _frac(e.meta_used, e.meta_max if not math.isnan(e.meta_max) and e.meta_max > 0 else e.meta_committed),
            e.sys_load,
            e.class_cnt,
            e.thread_cnt,
            e.daemon_thread_cnt,
            _frac(
                e.bean_pool_current_size - e.bean_pool_available_count
                if not math.isnan(e.bean_pool_current_size)
                else float("nan"),
                e.bean_pool_max_size,
            ),
        ],
        dtype=np.float64,
    )


JMX_FEATURE_COUNT = 11


class MvDriver:
    """Host loop: JmxEntry batches -> device step; server->row registry with
    growth-by-recompile (same pattern as pipeline.PipelineDriver)."""

    def __init__(
        self,
        spec: Optional[MvSpec] = None,
        *,
        capacity: int = 8,
        dtype=jnp.float32,
        logger=None,
    ):
        self.spec = spec or MvSpec(n_features=JMX_FEATURE_COUNT)
        self.capacity = capacity
        self.dtype = dtype
        self.logger = logger
        self.rows: dict = {}
        self.state = init_state(capacity, self.spec, dtype)
        self._step = jax.jit(step, static_argnums=1)

    def _row_for(self, server: str) -> int:
        row = self.rows.get(server)
        if row is None:
            if len(self.rows) >= self.capacity:
                self._grow()
            row = len(self.rows)
            self.rows[server] = row
        return row

    def _grow(self) -> None:
        new_capacity = self.capacity * 2
        if self.logger:
            self.logger.warning(
                f"Growing JMX host capacity {self.capacity} -> {new_capacity} (recompile)"
            )
        self.state = grow_state(self.state, new_capacity)
        self.capacity = new_capacity

    # apm: sync-boundary: JMX poll-path readback — one device round-trip per polling interval (seconds), not per tick
    def feed(self, entries: Sequence[JmxEntry]) -> List[dict]:
        """One poll round. Returns [{server, score, signal, observed}] for
        hosts present in this batch (NaN score while warming up)."""
        if not entries:
            return []
        for e in entries:  # resolve rows first: growth must precede the step
            self._row_for(e.server)
        H, M = self.capacity, self.spec.n_features
        x = np.full((H, M), np.nan, np.float64)
        valid = np.zeros((H,), bool)
        for e in entries:
            row = self.rows[e.server]
            x[row] = jmx_features(e)
            valid[row] = True
        res, self.state = self._step(self.state, self.spec, x.astype(self._np_dtype()), valid)
        score = np.asarray(res.score)
        signal = np.asarray(res.signal)
        observed = np.asarray(res.observed)
        out = []
        for e in entries:
            row = self.rows[e.server]
            out.append(
                {
                    "server": e.server,
                    "score": float(score[row]),
                    "signal": int(signal[row]),
                    "observed": int(observed[row]),
                }
            )
        return out

    def _np_dtype(self):
        return np.float64 if self.dtype == jnp.float64 else np.float32

    # -- checkpoint / resume (§5.4 parity with the engine's resume files) ----
    def save_resume(self, path: str) -> None:
        """Atomic snapshot of baselines + host registry (tmp + rename)."""
        import os
        import tempfile

        arrays = {
            "mean": np.asarray(self.state.mean),
            "cov": np.asarray(self.state.cov),
            "count": np.asarray(self.state.count),
            # spec fields that change the meaning/shape of the state: a
            # mismatch on load invalidates the snapshot
            "spec": np.array([self.spec.n_features, self.spec.alpha], np.float64),
            "servers": np.array(
                sorted(self.rows, key=self.rows.get), dtype=object
            ),
        }
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as fh:
                np.savez_compressed(fh, **arrays)
            os.replace(tmp, path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    def load_resume(self, path: str) -> bool:
        """Restore baselines; a corrupt/mismatched snapshot means start
        fresh (False), never a crash or half-mutated driver."""
        import os

        if not os.path.exists(path):
            return False
        try:
            with np.load(path, allow_pickle=True) as npz:
                data = {name: npz[name] for name in npz.files}
            n_features, alpha = data["spec"]
            if int(n_features) != self.spec.n_features or float(alpha) != self.spec.alpha:
                raise ValueError("spec mismatch")
            servers = [str(s) for s in data["servers"].tolist()]
            mean, cov, count = data["mean"], data["cov"], data["count"]
            if mean.shape[1] != self.spec.n_features or len(servers) > mean.shape[0]:
                raise ValueError("shape mismatch")
        except Exception:
            if self.logger:
                self.logger.error(f"Could not load JMX detector snapshot (starting fresh): {path}")
            return False
        while len(servers) > self.capacity:
            self._grow()
        H = self.capacity

        def pad(a):
            if a.shape[0] < H:
                width = [(0, H - a.shape[0])] + [(0, 0)] * (a.ndim - 1)
                fill = np.nan if np.issubdtype(a.dtype, np.floating) else 0
                return np.pad(a, width, constant_values=fill)
            return a[:H]

        self.rows = {s: i for i, s in enumerate(servers)}
        dt = self._np_dtype()
        self.state = MvState(
            mean=jnp.asarray(pad(mean).astype(dt)),
            cov=jnp.asarray(pad(cov).astype(dt)),
            count=jnp.asarray(pad(count).astype(np.int32)),
        )
        return True
