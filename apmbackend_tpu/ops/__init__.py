from .registry import CapacityExceeded, ServiceRegistry  # noqa: F401
from . import alerts, stats, zscore  # noqa: F401
