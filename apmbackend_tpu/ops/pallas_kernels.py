"""Pallas TPU kernels for the hot ops (SURVEY.md §7.3).

Multi-rank selection: the stats tick needs exactly two order statistics per
row (p75/p95 with the reference's neighbor-interpolation,
util_methods.js:112-142) out of a ``[S, W*CAP]`` window — but the XLA
baseline pays for a full per-row sort (O(N log^2 N) bitonic passes, each
moving the whole row through VMEM). This kernel computes EXACT order
statistics with no sort:

1. bitcast each f32 to its order-preserving uint32 key (sign-magnitude to
   biased-int transform; NaN keys sort past +inf, matching jnp.sort's
   NaN-to-end behavior),
2. binary-search the k-th smallest KEY VALUE bit by bit — 32 fixed
   iterations, each a masked compare+popcount over the row (pure VPU work on
   VMEM-resident data),
3. fetch the (k+1)-th value with one extra pass (count<=p, then min of keys
   strictly greater) for the interpolation midpoint,
4. invert the key transform back to f32.

Per-row ranks differ (each row has its own valid-sample count), so ranks ride
in as a ``[S, 2]`` operand. Rows are blocked over a 1-D grid; each block's
window slab lives in VMEM for all 64+2 passes — one HBM read of the data
total, vs. the sort's repeated round trips.

Exactness: identical results to ``sort + reference_percentile_sorted`` for
every float input (the bit search recovers the exact stored element bits, not
an approximation) — property-tested against the sort path in
tests/test_pallas_kernels.py. The kernel is f32-only; f64 parity mode and
non-TPU backends use the sort path (ops/stats.py chooses per dtype/backend).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .stats import percentile_rank  # single source of the reference index math

try:  # pltpu memory spaces exist only on TPU-enabled builds
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    _VMEM = None

import numpy as np

# numpy scalars: inlined as literals when traced inside the kernel (a closed-
# over jnp array would be a captured constant, which pallas_call rejects)
_SIGN = np.uint32(0x80000000)
_LOW31 = np.uint32(0x7FFFFFFF)
_UMAX = np.uint32(0xFFFFFFFF)


def _f32_to_ukey(x: jnp.ndarray) -> jnp.ndarray:
    """Order-preserving f32 -> uint32 (NaN > +inf, -0.0 < +0.0)."""
    u = jax.lax.bitcast_convert_type(x, jnp.uint32)
    neg = (u & _SIGN) != 0
    return jnp.where(neg, ~u, u | _SIGN)


def _ukey_to_f32(u: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`_f32_to_ukey`."""
    neg = (u & _SIGN) == 0  # encoded negatives lost their sign bit
    raw = jnp.where(neg, ~u, u & _LOW31)
    return jax.lax.bitcast_convert_type(raw, jnp.float32)


def _select_kernel(window_ref, ranks_ref, v1_ref, v2_ref, *, n_ranks: int):
    """One row-block: exact values at rank k and k+1 for each requested rank.

    window_ref [BR, N] f32 (NaN = empty slot), ranks_ref [BR, n_ranks] int32
    (1-indexed; any value is safe — rows gate on count outside), outputs
    [BR, n_ranks] f32.
    """
    ukey = _f32_to_ukey(window_ref[...])  # [BR, N]
    for j in range(n_ranks):
        k = ranks_ref[:, j : j + 1]  # [BR, 1]
        p = jnp.zeros_like(k, dtype=jnp.uint32)
        for b in range(31, -1, -1):
            cand = p | np.uint32(1 << b)
            cnt = jnp.sum((ukey < cand).astype(jnp.int32), axis=1, keepdims=True)
            p = jnp.where(cnt < k, cand, p)
        # p is now the exact ukey of the k-th smallest element
        le = jnp.sum((ukey <= p).astype(jnp.int32), axis=1, keepdims=True)
        nxt = jnp.min(jnp.where(ukey > p, ukey, _UMAX), axis=1, keepdims=True)
        p2 = jnp.where(le >= k + 1, p, nxt)  # duplicates: rank k+1 == rank k
        v1_ref[:, j : j + 1] = _ukey_to_f32(p)
        v2_ref[:, j : j + 1] = _ukey_to_f32(p2)


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def select_ranks(
    window: jnp.ndarray,  # [S, N] f32, NaN = empty
    ranks: jnp.ndarray,  # [S, R] int32, 1-indexed
    *,
    block_rows: int = 256,
    interpret: bool = False,
):
    """Exact (value at rank k, value at rank k+1) per row for each rank column.

    Rows are processed in ``block_rows`` slabs; a non-divisible row count is
    padded internally (pad-row outputs are sliced off). N should be
    lane-aligned (pad with NaN) for TPU efficiency.
    """
    S, N = window.shape
    R = ranks.shape[1]
    block_rows = min(block_rows, ((S + 7) // 8) * 8)
    s_pad = (-S) % block_rows
    if s_pad:
        window = jnp.pad(window, ((0, s_pad), (0, 0)), constant_values=jnp.nan)
        ranks = jnp.pad(ranks, ((0, s_pad), (0, 0)), constant_values=1)
    grid = ((S + s_pad) // block_rows,)
    if _VMEM is not None and not interpret:
        mem = {"memory_space": _VMEM}
    else:
        mem = {}
    out_shape = [
        jax.ShapeDtypeStruct((S + s_pad, R), jnp.float32),
        jax.ShapeDtypeStruct((S + s_pad, R), jnp.float32),
    ]
    v1, v2 = pl.pallas_call(
        functools.partial(_select_kernel, n_ranks=R),
        out_shape=out_shape,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, N), lambda i: (i, 0), **mem),
            pl.BlockSpec((block_rows, R), lambda i: (i, 0), **mem),
        ],
        out_specs=[
            pl.BlockSpec((block_rows, R), lambda i: (i, 0), **mem),
            pl.BlockSpec((block_rows, R), lambda i: (i, 0), **mem),
        ],
        interpret=interpret,
    )(window, ranks)
    return v1[:S], v2[:S]




def window_percentiles(
    window: jnp.ndarray,  # [S, N] float (any), NaN = empty
    counts: jnp.ndarray,  # [S] int32 valid samples per row
    ps=(75, 95),
    *,
    block_rows: int = 256,
    interpret: bool = False,
) -> tuple:
    """Exact reference percentiles for each p in ``ps`` via the selection
    kernel. Returns a tuple of [S] arrays (NaN where count == 0). Pads rows
    and lanes internally; caller passes raw shapes."""
    S, N = window.shape
    orig_dtype = window.dtype
    w = window.astype(jnp.float32)
    n_pad = (-N) % 128
    if n_pad:
        w = jnp.pad(w, ((0, 0), (0, n_pad)), constant_values=jnp.nan)

    ranks = []
    pairs = []
    for p in ps:
        r, tp = percentile_rank(counts, p)
        ranks.append(r)
        pairs.append(tp)
    ranks_arr = jnp.stack(ranks, axis=1)  # [S, R]
    v1, v2 = select_ranks(w, ranks_arr, block_rows=block_rows, interpret=interpret)
    out = []
    for i, p in enumerate(ps):
        val = jnp.where(pairs[i], (v1[:, i] + v2[:, i]) / 2.0, v1[:, i])
        out.append(jnp.where(counts > 0, val, jnp.nan).astype(orig_dtype))
    return tuple(out)
